"""The dctpu flywheel: train -> distill -> quant gates -> export.

One command that turns training data into a servable artifact, with
the quantization acceptance gates from tests/test_quantized_inference
enforced AT RUNTIME between distillation and export:

  * int8 gate — held-out alignment identity within
    config.INT8_IDENTITY_GATE of the f32 baseline
    (models/evaluate.run_evaluation on both variants);
  * bf16 gate — per-base quality values within config.BF16_QV_GATE of
    f32 on positions where both precisions call the same base (the
    FASTQ delta gate, computed from softmax probabilities via the host
    epilogue oracle ops/output_plane.host_quality_reference).

A failed gate raises faults.FlywheelGateError BEFORE export_model runs
— an artifact that would serve degraded consensus is never written.

Durability (the orchestration layer): every stage is a `Stage` entry
in `<out_dir>/flywheel_journal.json` — committed atomically
(tmp + rename + fsync) with the stage's exact inputs, its outputs
inventory, and a status in {running, done, failed, interrupted}. A
crashed or SIGKILLed cycle restarts with `--resume`: completed stages
whose recorded inputs match and whose outputs still validate are
skipped, the in-flight stage is re-entered idempotently, and changed
parameters raise a typed faults.FlywheelResumeError naming the
mismatched field instead of silently mixing configurations. Transient
stage failures retry with the run_training_with_retry backoff and
crash-loop breaker semantics; SIGTERM mid-cycle checkpoints the
running stage (train/distill support it), marks the journal
`interrupted`, and exits cleanly. The export publishes atomically:
the artifact is built in `artifact.tmp/` and renamed into `export/`
only when complete, so a half-written artifact is never servable.

Every stage and gate lands in flywheel_manifest.json next to the
artifact (same atomic writer as the journal), so `dctpu serve`'s
baked-lever mismatch checks have a provenance record to point at. On
resume, gates are re-verified from the journal — enforced on every
run, measured exactly once.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import ml_collections
import numpy as np

from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu import obs as obs_lib
from deepconsensus_tpu.calibration import lib as calibration_lib
from deepconsensus_tpu.models import checkpoints as checkpoints_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.models import distill as distill_lib
from deepconsensus_tpu.models import evaluate as evaluate_lib
from deepconsensus_tpu.models import export as export_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.models import quantize as quantize_lib
from deepconsensus_tpu.models import train as train_lib
from deepconsensus_tpu.ops import output_plane

log = logging.getLogger(__name__)

MANIFEST_NAME = 'flywheel_manifest.json'
JOURNAL_NAME = 'flywheel_journal.json'
# Bumped whenever a stage's journal entry shape changes incompatibly;
# a resume across versions raises FlywheelResumeError instead of
# misreading old entries.
JOURNAL_SCHEMA_VERSION = 1
MANIFEST_SCHEMA_VERSION = 2
STAGE_ORDER = ('train', 'distill', 'gates', 'export')
# Export staging directory: export_model writes here, and the complete
# tree is renamed to export/ in one atomic publish step.
EXPORT_STAGING = 'artifact.tmp'

# Gate thresholds live in models/config.py — the ONE shared home the
# acceptance tests import too, so runtime gate and test can never
# drift. Re-exported here for compatibility.
INT8_IDENTITY_GATE = config_lib.INT8_IDENTITY_GATE
BF16_QV_GATE = config_lib.BF16_QV_GATE

_UNSET = object()


# ----------------------------------------------------------------------
# Atomic JSON commits: the journal and the manifest share one writer.


def atomic_write_json(path: str, obj: Dict) -> str:
  """tmp + fsync + rename: readers see the old file or the new file,
  never a torn write — a SIGKILL mid-commit leaves at worst a stale
  .tmp next to an intact previous version. The tmp name is per-process
  so elastic hosts sharing one out_dir can't rename each other's
  half-written tmp out from under them."""
  tmp = f'{path}.tmp.{os.getpid()}'
  with open(tmp, 'w') as f:
    json.dump(obj, f, indent=2, sort_keys=True)
    f.write('\n')
    f.flush()
    os.fsync(f.fileno())
  os.replace(tmp, path)
  return path


def _write_manifest(out_dir: str, manifest: Dict) -> str:
  manifest.setdefault('schema_version', MANIFEST_SCHEMA_VERSION)
  return atomic_write_json(os.path.join(out_dir, MANIFEST_NAME), manifest)


def _inputs_digest(inputs: Dict) -> str:
  blob = json.dumps(inputs, sort_keys=True).encode()
  return hashlib.sha256(blob).hexdigest()[:16]


# ----------------------------------------------------------------------
# The stage journal.


class FlywheelJournal:
  """Per-stage durable record under <out_dir>/flywheel_journal.json.

  Mutations happen in memory; commit() writes the whole journal
  atomically. The orchestrator commits at every status transition, so
  the on-disk journal always describes a consistent resume point."""

  def __init__(self, out_dir: str):
    self.out_dir = out_dir
    self.path = os.path.join(out_dir, JOURNAL_NAME)
    self.data: Dict = {
        'schema_version': JOURNAL_SCHEMA_VERSION,
        'stages': {},
    }

  def load(self) -> bool:
    """Adopts an existing journal (resume). False when none exists —
    --resume on a fresh out_dir is just a fresh run."""
    if not os.path.exists(self.path):
      return False
    with open(self.path) as f:
      data = json.load(f)
    version = data.get('schema_version')
    if version != JOURNAL_SCHEMA_VERSION:
      raise faults_lib.FlywheelResumeError(
          'schema_version', version, JOURNAL_SCHEMA_VERSION)
    data.setdefault('stages', {})
    self.data = data
    return True

  def commit(self) -> str:
    return atomic_write_json(self.path, self.data)

  def entry(self, stage: str) -> Optional[Dict]:
    return self.data['stages'].get(stage)

  def begin(self, stage: str, inputs: Dict, status: str = 'running',
            n_resumes: int = 0) -> Dict:
    prev = self.data['stages'].get(stage) or {}
    entry = {
        'status': status,
        'inputs': inputs,
        'inputs_digest': _inputs_digest(inputs),
        'outputs': {},
        'n_retries': int(prev.get('n_retries', 0) or 0),
        'n_resumes': n_resumes,
        'started': time.time(),
        'finished': None,
    }
    self.data['stages'][stage] = entry
    return entry

  def finish(self, stage: str, outputs: Dict) -> None:
    entry = self.data['stages'][stage]
    entry['status'] = 'done'
    entry['outputs'] = outputs
    entry['finished'] = time.time()

  def fail(self, stage: str, error: str) -> None:
    entry = self.data['stages'].setdefault(stage, {'inputs': {}})
    entry['status'] = 'failed'
    entry['error'] = error
    entry['finished'] = time.time()

  def interrupt(self, stage: str, outputs: Optional[Dict] = None) -> None:
    entry = self.data['stages'].setdefault(stage, {'inputs': {}})
    entry['status'] = 'interrupted'
    if outputs is not None:
      entry['outputs'] = outputs
    entry['finished'] = time.time()

  def note_retry(self, stage: str) -> None:
    entry = self.data['stages'].setdefault(stage, {'inputs': {}})
    entry['n_retries'] = int(entry.get('n_retries', 0) or 0) + 1

  def counters(self) -> Dict[str, int]:
    retries = resumes = 0
    for entry in self.data['stages'].values():
      retries += int(entry.get('n_retries', 0) or 0)
      resumes += int(entry.get('n_resumes', 0) or 0)
    return {'n_stage_retries': retries, 'n_stage_resumes': resumes}


# ----------------------------------------------------------------------
# The Stage abstraction + the durable orchestrator core.


class Stage:
  """One durable flywheel stage.

  inputs is the exact JSON-serializable record of everything the
  stage's outputs depend on: matching inputs are what make a journaled
  `done` entry skippable on resume, and a mismatch is what makes the
  journal stale (FlywheelResumeError). run() does the work and returns
  the outputs inventory; a truthy outputs['preempted'] tells the
  orchestrator the stage checkpointed and stopped at a preemption
  signal. outputs_valid re-validates a journaled outputs inventory
  against disk (checkpoints may have been quarantined since).
  progress, when given, is the stage's resume marker — the crash-loop
  breaker only counts retries that fail to advance it. on_transient
  runs before each retry (the elastic degrade hook)."""

  def __init__(self, name: str, inputs: Dict,
               run: Callable[[], Dict],
               outputs_valid: Optional[Callable[[Dict], bool]] = None,
               progress: Optional[Callable[[], Any]] = None,
               on_transient: Optional[Callable[[Exception], None]] = None,
               retryable: bool = True):
    self.name = name
    self.inputs = inputs
    self.run = run
    self.outputs_valid = outputs_valid or (lambda outputs: True)
    self.progress = progress
    self.on_transient = on_transient
    self.retryable = retryable


def _check_inputs(stage: Stage, entry: Dict) -> None:
  """Stale-journal guard: a resumed invocation must present the same
  inputs the journal recorded, field by field."""
  recorded = entry.get('inputs') or {}
  for key in sorted(set(recorded) | set(stage.inputs)):
    if recorded.get(key) != stage.inputs.get(key):
      raise faults_lib.FlywheelResumeError(
          key, recorded.get(key), stage.inputs.get(key), stage=stage.name)


def _retry_stage(stage: Stage, journal: FlywheelJournal,
                 obs: obs_lib.MetricsRegistry, *,
                 max_retries: int = 1_000_000,
                 backoff_base: float = 0.5,
                 backoff_max: float = 60.0,
                 max_stalled_restarts: int = 3,
                 sleep: Callable[[float], None] = time.sleep) -> Dict:
  """run_training_with_retry semantics at stage granularity: only
  TRANSIENT errors retry, exponential backoff between attempts, and a
  crash-loop breaker when the stage's progress marker stops advancing
  across max_stalled_restarts consecutive restarts."""
  attempts = 0
  stalled = 0
  last = _UNSET
  while True:
    try:
      return stage.run()
    except Exception as e:  # pylint: disable=broad-except
      message = f'{type(e).__name__}: {e}'
      if (not stage.retryable
          or faults_lib.classify_error(message)
          != faults_lib.FaultKind.TRANSIENT):
        raise
      attempts += 1
      if attempts > max_retries:
        raise
      progress = stage.progress() if stage.progress is not None else None
      if last is not _UNSET and progress == last:
        stalled += 1
        if stalled >= max_stalled_restarts:
          raise faults_lib.CrashLoopError(
              f'flywheel stage {stage.name!r} failed {stalled + 1} '
              f'consecutive time(s) without its progress marker '
              f'advancing past {progress!r}; aborting instead of '
              f'crash-looping (last error: {message.splitlines()[0]})'
          ) from e
      else:
        stalled = 0
      last = progress
      if stage.on_transient is not None:
        stage.on_transient(e)
      obs.inc('n_stage_retries')
      journal.note_retry(stage.name)
      journal.commit()
      delay = min(backoff_max, backoff_base * (2 ** (attempts - 1)))
      log.warning(
          'flywheel stage %r: transient failure (%s); retrying in '
          '%.1fs (attempt %d)', stage.name,
          message.splitlines()[0], delay, attempts,
      )
      sleep(delay)


def _run_stages(stage_factories: Sequence[Callable[[Dict], Stage]],
                journal: FlywheelJournal,
                guard,
                obs: obs_lib.MetricsRegistry,
                *,
                resume: bool = False,
                results: Optional[Dict[str, Dict]] = None,
                retry_opts: Optional[Dict] = None,
                ) -> Tuple[Dict[str, Dict], Optional[str]]:
  """Runs stages in order against the journal. Returns (results,
  interrupted_stage). Each factory receives the results of every
  earlier stage (later stages derive their inputs — e.g. checkpoint
  paths — from them)."""
  results = dict(results or {})
  opts = dict(retry_opts or {})
  for factory in stage_factories:
    stage = factory(results)
    entry = journal.entry(stage.name)
    if resume and entry is not None and entry.get('inputs'):
      _check_inputs(stage, entry)
    if (resume and entry is not None and entry.get('status') == 'done'
        and stage.outputs_valid(entry.get('outputs') or {})):
      results[stage.name] = entry.get('outputs') or {}
      obs.inc('n_stage_skips')
      log.info('flywheel: stage %r already done (journal); skipping',
               stage.name)
      continue
    if guard.local():
      # Preempted between stages: record where the cycle stops so
      # --resume re-enters exactly here.
      journal.begin(stage.name, stage.inputs, status='interrupted')
      journal.commit()
      return results, stage.name
    n_resumes = 0
    if entry is not None:
      n_resumes = int(entry.get('n_resumes', 0) or 0) + 1
      obs.inc('n_stage_resumes')
      log.warning('flywheel: re-entering stage %r (journal status %r)',
                  stage.name, entry.get('status'))
    journal.begin(stage.name, stage.inputs, n_resumes=n_resumes)
    journal.commit()
    # The stage-boundary drill hook: the `running` entry above is
    # already durable, so a SIGKILL here is the worst-timed crash.
    faults_lib.maybe_kill_flywheel_at_stage(stage.name)
    t0 = time.time()
    try:
      outputs = _retry_stage(stage, journal, obs, **opts)
    except BaseException as e:
      journal.fail(stage.name, f'{type(e).__name__}: {e}')
      journal.commit()
      obs_lib.trace.complete_event(
          'flywheel_stage', 'flywheel', t0, time.time(),
          {'stage': stage.name, 'status': 'failed'})
      if isinstance(e, (ValueError, KeyboardInterrupt,
                        faults_lib.FlywheelGateError,
                        faults_lib.FlywheelStageError,
                        faults_lib.CrashLoopError)):
        raise
      if isinstance(e, Exception):
        raise faults_lib.FlywheelStageError(
            stage.name, f'{type(e).__name__}: {e}') from e
      raise
    t1 = time.time()
    if outputs.get('preempted'):
      journal.interrupt(stage.name, outputs)
      journal.commit()
      obs_lib.trace.complete_event(
          'flywheel_stage', 'flywheel', t0, t1,
          {'stage': stage.name, 'status': 'interrupted'})
      results[stage.name] = outputs
      return results, stage.name
    journal.finish(stage.name, outputs)
    journal.commit()
    obs_lib.trace.complete_event(
        'flywheel_stage', 'flywheel', t0, t1,
        {'stage': stage.name, 'status': 'done',
         'n_retries': int(journal.entry(stage.name).get('n_retries', 0))})
    results[stage.name] = outputs
  return results, None


# ----------------------------------------------------------------------
# Quantization gates (unchanged semantics; thresholds from config).


def _with_levers(params: ml_collections.ConfigDict,
                 inference_dtype: Optional[str] = None,
                 quantize_matmuls: Optional[str] = None):
  """Copy of params with the quantization levers folded in (the
  config-side half of runner._apply_quant_levers)."""
  p = ml_collections.ConfigDict(params.to_dict())
  with p.unlocked():
    if inference_dtype:
      p.inference_dtype = inference_dtype
      p.dtype = inference_dtype
    if quantize_matmuls and quantize_matmuls != 'none':
      p.quantize_matmuls = quantize_matmuls
  return p


def _eval_identity(params, variables, eval_patterns, out_dir) -> float:
  metrics = evaluate_lib.run_evaluation(
      params=params, checkpoint_path=None, eval_patterns=eval_patterns,
      out_dir=out_dir, variables=variables)
  return float(metrics['alignment_identity'])


def long_insert_identity_record(student_params, student_variables,
                                baseline_checkpoint, eval_patterns,
                                out_dir) -> Dict:
  """Informational manifest record (passed is always True — it never
  vetoes export): alignment_identity of the student vs a reference
  checkpoint (e.g. the L=100 production model) on the same eval
  shards. This is the acceptance readout for the L=500 long-insert
  flywheel — the manifest shows the long-window student's identity
  side by side with the short-window baseline's. A baseline that
  cannot consume the eval shards (its window_buckets don't cover the
  long windows) records the typed error instead of aborting the
  cycle."""
  student = _eval_identity(
      student_params, student_variables, eval_patterns,
      os.path.join(out_dir, 'gate_student_identity'))
  detail: Dict = {'student_identity': round(student, 6),
                  'baseline_checkpoint': baseline_checkpoint}
  measured = None
  try:
    base_params = config_lib.read_params_from_json(baseline_checkpoint)
    config_lib.finalize_params(base_params, is_training=False)
    base_vars = {
        'params': checkpoints_lib.load_params(baseline_checkpoint)}
    baseline = _eval_identity(
        base_params, base_vars, eval_patterns,
        os.path.join(out_dir, 'gate_baseline_identity'))
    detail['baseline_identity'] = round(baseline, 6)
    measured = round(student - baseline, 6)
  except Exception as e:  # informational: record, never abort
    detail['baseline_error'] = f'{type(e).__name__}: {e}'
  return {
      'name': 'long_insert_identity_vs_baseline',
      'threshold': None,
      'measured': measured,
      'passed': True,
      'detail': detail,
  }


def int8_identity_gate(params, variables, eval_patterns, out_dir,
                       threshold: float = INT8_IDENTITY_GATE) -> Dict:
  """|alignment_identity(int8) - alignment_identity(f32)| <= threshold."""
  base = _eval_identity(params, variables, eval_patterns,
                        os.path.join(out_dir, 'gate_f32'))
  params_q = _with_levers(params, quantize_matmuls='int8')
  variables_q, n_quantized = quantize_lib.prepare_inference_variables(
      variables, params_q)
  quant = _eval_identity(params_q, variables_q, eval_patterns,
                         os.path.join(out_dir, 'gate_int8'))
  measured = abs(quant - base)
  return {
      'name': 'int8_alignment_identity_delta',
      'threshold': threshold,
      'measured': round(measured, 6),
      'passed': measured <= threshold,
      'detail': {'f32_identity': round(base, 6),
                 'int8_identity': round(quant, 6),
                 'n_quantized_matmuls': int(n_quantized)},
  }


def bf16_qv_gate(params, variables, eval_patterns,
                 threshold: int = BF16_QV_GATE,
                 max_batches: int = 4,
                 max_base_quality: int = 93) -> Dict:
  """Max per-base QV delta between f32 and bf16 forwards <= threshold.

  QVs come from the host epilogue oracle on each precision's softmax
  max-probability; only positions where both precisions argmax to the
  SAME base are compared (near-tie argmax flips change the base, not
  the confidence — the FASTQ gate excludes them the same way).
  """
  cal = calibration_lib.parse_calibration_string('skip')
  model_f32 = model_lib.get_model(params)
  params_16 = _with_levers(params, inference_dtype='bfloat16')
  model_16 = model_lib.get_model(params_16)
  variables_16, _ = quantize_lib.prepare_inference_variables(
      variables, params_16)
  ds = data_lib.DatasetIterator(
      patterns=list(eval_patterns), params=params,
      batch_size=params.batch_size, shuffle=False)
  fwd32 = jax.jit(lambda v, rows: model_f32.apply(v, rows))
  fwd16 = jax.jit(lambda v, rows: model_16.apply(v, rows))
  max_delta = 0
  n_compared = 0
  for i, batch in enumerate(ds.epoch()):
    if i >= max_batches:
      break
    rows = batch['rows']
    preds32 = np.asarray(fwd32(variables, rows), np.float32)
    preds16 = np.asarray(fwd16(variables_16, rows), np.float32)
    agree = preds32.argmax(-1) == preds16.argmax(-1)
    q32 = output_plane.host_quality_reference(
        preds32.max(-1), cal, max_base_quality)
    q16 = output_plane.host_quality_reference(
        preds16.max(-1), cal, max_base_quality)
    if agree.any():
      delta = np.abs(q32.astype(int) - q16.astype(int))[agree]
      max_delta = max(max_delta, int(delta.max()))
      n_compared += int(agree.sum())
  return {
      'name': 'bf16_max_qv_delta',
      'threshold': threshold,
      'measured': max_delta,
      'passed': max_delta <= threshold,
      'detail': {'n_positions_compared': n_compared},
  }


def _enforce(gates: Sequence[Dict]) -> None:
  for gate in gates:
    if not gate['passed']:
      raise faults_lib.FlywheelGateError(
          gate['name'], gate['measured'], gate['threshold'],
          detail=json.dumps(gate.get('detail', {})))


# ----------------------------------------------------------------------
# The flywheel driver.


def _build_manifest(results: Dict[str, Dict], journal: FlywheelJournal,
                    interrupted: Optional[str] = None) -> Dict:
  manifest: Dict = {
      'schema_version': MANIFEST_SCHEMA_VERSION,
      'stages': {},
      'gates': [],
      'ok': False,
      'counters': journal.counters(),
  }
  for name in ('train', 'distill', 'export'):
    if name in results:
      manifest['stages'][name] = results[name]
  gates = (results.get('gates') or {}).get('gates') or []
  manifest['gates'] = gates
  manifest['ok'] = bool(
      gates and all(g['passed'] for g in gates) and 'export' in results)
  if interrupted is not None:
    manifest['interrupted'] = interrupted
  return manifest


def run_flywheel(
    out_dir: str,
    train_patterns: Sequence[str],
    eval_patterns: Sequence[str],
    teacher_config: str = 'transformer_learn_values+test',
    student_config: str = 'transformer_learn_values_distill+test',
    teacher_checkpoint: Optional[str] = None,
    teacher_overrides: Sequence[str] = (),
    student_overrides: Sequence[str] = (),
    num_epochs: Optional[int] = None,
    batch_size: Optional[int] = None,
    export_batch_size: int = 1024,
    inference_dtype: Optional[str] = None,
    quantize_matmuls: Optional[str] = None,
    int8_gate_threshold: float = INT8_IDENTITY_GATE,
    bf16_gate_threshold: int = BF16_QV_GATE,
    mesh=None,
    resume: bool = False,
    elastic_config: Optional[Dict] = None,
    window_buckets: Optional[Sequence[int]] = None,
    baseline_checkpoint: Optional[str] = None,
) -> Dict:
  """Train -> distill -> gates -> export; returns the manifest dict.

  With teacher_checkpoint the training stage is skipped and the
  flywheel spins from an existing teacher (the common retrain-student
  loop). inference_dtype / quantize_matmuls choose the levers BAKED
  into the exported artifact; both gates run and are enforced
  regardless, so the manifest always records the full quantization
  safety envelope of the released weights.

  resume=True adopts <out_dir>/flywheel_journal.json: completed stages
  are skipped (after validating their recorded inputs against this
  invocation — FlywheelResumeError on drift), the in-flight stage is
  re-entered. elastic_config (host_id, n_hosts, barrier_timeout,
  on_host_error, readmit — the `dctpu train --elastic` shape) runs the
  train and distill stages under the PR-18 pod protocol; a
  HostLostError that escapes the pod's own rebuild degrades the pod by
  one host at the stage retry instead of killing the cycle.

  A preemption signal (SIGTERM/SIGINT) mid-cycle checkpoints the
  running stage where supported, marks the journal `interrupted`, and
  returns a manifest with manifest['interrupted'] = <stage> — the
  caller exits cleanly and `--resume` picks the cycle back up.
  """
  from deepconsensus_tpu import cli as cli_lib

  out_dir = os.path.abspath(out_dir)
  os.makedirs(out_dir, exist_ok=True)
  obs_lib.trace.configure_from_env(tier='flywheel')
  obs = obs_lib.MetricsRegistry(tier='flywheel')
  journal = FlywheelJournal(out_dir)
  if resume:
    journal.load()
  journal.commit()
  elastic = dict(elastic_config) if elastic_config else None
  barrier_timeout = float(
      (elastic or {}).get('barrier_timeout', 30.0) or 30.0)
  guard = train_lib.PreemptionGuard(
      barrier_timeout=barrier_timeout).install()

  teacher_dir = os.path.join(out_dir, 'teacher')
  student_dir = os.path.join(out_dir, 'student')
  gates_dir = os.path.join(out_dir, 'gates')

  def _teacher_params():
    p = config_lib.get_config(teacher_config)
    cli_lib._apply_overrides(p, list(teacher_overrides))
    config_lib.finalize_params(p)
    with p.unlocked():
      if batch_size:
        p.batch_size = batch_size
      if window_buckets:
        p.window_buckets = tuple(window_buckets)
    return p

  def _student_params():
    p = config_lib.get_config(student_config)
    cli_lib._apply_overrides(p, list(student_overrides))
    config_lib.finalize_params(p)
    with p.unlocked():
      if batch_size:
        p.batch_size = batch_size
      if window_buckets:
        p.window_buckets = tuple(window_buckets)
    return p

  def _degrade_pod(err: Exception) -> None:
    """Stage-retry hook: a HostLostError that escaped the pod's own
    rebuild means the lost host is not coming back inside the retry
    window — shrink the expected membership so the retried stage forms
    a smaller pod instead of waiting on the dead host forever."""
    if not isinstance(err, faults_lib.HostLostError):
      return
    if elastic and int(elastic.get('n_hosts', 1) or 1) > 1:
      elastic['n_hosts'] = int(elastic['n_hosts']) - 1
      log.warning(
          'flywheel: degrading pod to %d host(s) after %s',
          elastic['n_hosts'], str(err).splitlines()[0])

  def _metrics_of(metrics: Dict) -> Dict[str, float]:
    return {k: float(v) for k, v in metrics.items()}

  # ---- stage factories -------------------------------------------------

  def _train_stage(results: Dict[str, Dict]) -> Stage:
    del results
    inputs = {
        'teacher_config': teacher_config,
        'teacher_overrides': list(teacher_overrides),
        'teacher_checkpoint': teacher_checkpoint or '',
        'batch_size': int(batch_size or 0),
        'num_epochs': int(num_epochs or 0),
        'train_patterns': list(train_patterns),
        'eval_patterns': list(eval_patterns),
        'window_buckets': list(window_buckets or ()),
    }

    def run() -> Dict:
      if teacher_checkpoint:
        if not os.path.exists(teacher_checkpoint):
          raise FileNotFoundError(
              f'--teacher_checkpoint {teacher_checkpoint!r} does not '
              'exist')
        return {'checkpoint': teacher_checkpoint, 'skipped': True}
      metrics = train_lib.run_training(
          params=_teacher_params(),
          out_dir=teacher_dir,
          train_patterns=list(train_patterns),
          eval_patterns=list(eval_patterns),
          num_epochs=num_epochs,
          mesh=mesh,
          elastic_config=elastic,
          preemption_guard=guard,
      )
      ckpt = checkpoints_lib.latest_valid_checkpoint(
          os.path.join(teacher_dir, 'checkpoints'))
      if metrics.get('preempted'):
        return {'preempted': True,
                'stop_step': float(metrics.get('stop_step', 0.0)),
                'checkpoint': ckpt or ''}
      if ckpt is None:
        raise faults_lib.FlywheelStageError(
            'train',
            f'training under {teacher_dir} left no valid checkpoint')
      return {'checkpoint': ckpt, 'metrics': _metrics_of(metrics)}

    def outputs_valid(outputs: Dict) -> bool:
      ckpt = outputs.get('checkpoint')
      if not ckpt:
        return False
      if outputs.get('skipped'):
        return os.path.exists(ckpt)
      return checkpoints_lib.validate_checkpoint(ckpt)[0]

    return Stage(
        'train', inputs, run, outputs_valid=outputs_valid,
        progress=lambda: checkpoints_lib.latest_valid_step(
            os.path.join(teacher_dir, 'checkpoints')),
        on_transient=_degrade_pod)

  def _distill_stage(results: Dict[str, Dict]) -> Stage:
    teacher_ckpt = results['train']['checkpoint']
    inputs = {
        'student_config': student_config,
        'student_overrides': list(student_overrides),
        'batch_size': int(batch_size or 0),
        'num_epochs': int(num_epochs or 0),
        'train_patterns': list(train_patterns),
        'eval_patterns': list(eval_patterns),
        'teacher_checkpoint': teacher_ckpt,
        'window_buckets': list(window_buckets or ()),
    }

    def run() -> Dict:
      teacher_params = config_lib.read_params_from_json(teacher_ckpt)
      config_lib.finalize_params(teacher_params)
      teacher_weights = checkpoints_lib.load_params(teacher_ckpt)
      metrics = distill_lib.run_distillation(
          params=_student_params(),
          teacher_params_cfg=teacher_params,
          teacher_variables={'params': teacher_weights},
          out_dir=student_dir,
          train_patterns=list(train_patterns),
          eval_patterns=list(eval_patterns),
          num_epochs=num_epochs,
          mesh=mesh,
          elastic_config=elastic,
          preemption_guard=guard,
      )
      ckpt = checkpoints_lib.latest_valid_checkpoint(
          os.path.join(student_dir, 'checkpoints'))
      if metrics.get('preempted'):
        return {'preempted': True,
                'stop_step': float(metrics.get('stop_step', 0.0)),
                'checkpoint': ckpt or ''}
      if ckpt is None:
        raise faults_lib.FlywheelStageError(
            'distill',
            f'distillation under {student_dir} left no valid checkpoint')
      return {'checkpoint': ckpt, 'metrics': _metrics_of(metrics)}

    def outputs_valid(outputs: Dict) -> bool:
      ckpt = outputs.get('checkpoint')
      return bool(ckpt) and checkpoints_lib.validate_checkpoint(ckpt)[0]

    return Stage(
        'distill', inputs, run, outputs_valid=outputs_valid,
        progress=lambda: checkpoints_lib.latest_valid_step(
            os.path.join(student_dir, 'checkpoints')),
        on_transient=_degrade_pod)

  def _gates_stage(results: Dict[str, Dict]) -> Stage:
    student_ckpt = results['distill']['checkpoint']
    inputs = {
        'student_config': student_config,
        'student_overrides': list(student_overrides),
        'batch_size': int(batch_size or 0),
        'int8_gate_threshold': float(int8_gate_threshold),
        'bf16_gate_threshold': int(bf16_gate_threshold),
        'eval_patterns': list(eval_patterns),
        'checkpoint': student_ckpt,
        'baseline_checkpoint': baseline_checkpoint or '',
    }

    def run() -> Dict:
      student_params = _student_params()
      variables = {'params': checkpoints_lib.load_params(student_ckpt)}
      gates: List[Dict] = [
          int8_identity_gate(student_params, variables,
                             list(eval_patterns), gates_dir,
                             threshold=int8_gate_threshold),
          bf16_qv_gate(student_params, variables,
                       list(eval_patterns),
                       threshold=bf16_gate_threshold),
      ]
      if baseline_checkpoint:
        gates.append(long_insert_identity_record(
            student_params, variables, baseline_checkpoint,
            list(eval_patterns), gates_dir))
      return {'gates': gates}

    return Stage('gates', inputs, run)

  def _export_stage(results: Dict[str, Dict]) -> Stage:
    student_ckpt = results['distill']['checkpoint']
    inputs = {
        'export_batch_size': int(export_batch_size),
        'inference_dtype': inference_dtype or '',
        'quantize_matmuls': quantize_matmuls or '',
        'checkpoint': student_ckpt,
    }

    def run() -> Dict:
      student_params = _student_params()
      variables = {'params': checkpoints_lib.load_params(student_ckpt)}
      staging = os.path.join(out_dir, EXPORT_STAGING)
      final = os.path.join(out_dir, 'export')
      if os.path.isdir(staging):
        # Idempotent re-entry: a half-finished staging tree from a
        # killed export is rebuilt from scratch, never patched.
        shutil.rmtree(staging)
      artifact = export_lib.export_model(
          checkpoint_path=student_ckpt,
          out_dir=staging,
          batch_size=export_batch_size,
          variables=variables,
          params=student_params,
          inference_dtype=inference_dtype,
          quantize_matmuls=quantize_matmuls,
      )
      if os.path.isdir(final):
        # The journal does not say `done` (we are running), so
        # anything at the final path is wreckage from an interrupted
        # publish — replace it.
        shutil.rmtree(final)
      os.replace(staging, final)
      return {
          'artifact': os.path.join(final, os.path.basename(artifact)),
          'baked_levers': {
              'inference_dtype': inference_dtype or 'float32',
              'quantize_matmuls': quantize_matmuls or 'none',
          },
      }

    def outputs_valid(outputs: Dict) -> bool:
      artifact = outputs.get('artifact')
      return bool(artifact) and os.path.exists(artifact)

    return Stage('export', inputs, run, outputs_valid=outputs_valid)

  # ---- orchestration ---------------------------------------------------

  try:
    results, interrupted = _run_stages(
        [_train_stage, _distill_stage, _gates_stage],
        journal, guard, obs, resume=resume)
    if interrupted is not None:
      manifest = _build_manifest(results, journal, interrupted=interrupted)
      _write_manifest(out_dir, manifest)
      return manifest
    # Manifest lands even on a failed gate: the failure itself is the
    # record the next flywheel turn starts from. On resume the gates
    # come straight from the journal — measured once, enforced always.
    manifest = _build_manifest(results, journal)
    _write_manifest(out_dir, manifest)
    _enforce(results['gates']['gates'])
    results, interrupted = _run_stages(
        [_export_stage], journal, guard, obs,
        resume=resume, results=results)
    manifest = _build_manifest(results, journal, interrupted=interrupted)
    _write_manifest(out_dir, manifest)
    return manifest
  finally:
    guard.restore()
