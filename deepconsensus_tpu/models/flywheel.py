"""The dctpu flywheel: train -> distill -> quant gates -> export.

One command that turns training data into a servable artifact, with
the quantization acceptance gates from tests/test_quantized_inference
enforced AT RUNTIME between distillation and export:

  * int8 gate — held-out alignment identity within 0.002 of the f32
    baseline (models/evaluate.run_evaluation on both variants);
  * bf16 gate — per-base quality values within 3 QV of f32 on
    positions where both precisions call the same base (the FASTQ
    delta gate, computed from softmax probabilities via the host
    epilogue oracle ops/output_plane.host_quality_reference).

A failed gate raises faults.FlywheelGateError BEFORE export_model runs
— an artifact that would serve degraded consensus is never written.
Every stage and gate lands in flywheel_manifest.json next to the
artifact, so `dctpu serve`'s baked-lever mismatch checks have a
provenance record to point at.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import jax
import ml_collections
import numpy as np

from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu.calibration import lib as calibration_lib
from deepconsensus_tpu.models import checkpoints as checkpoints_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.models import distill as distill_lib
from deepconsensus_tpu.models import evaluate as evaluate_lib
from deepconsensus_tpu.models import export as export_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.models import quantize as quantize_lib
from deepconsensus_tpu.models import train as train_lib
from deepconsensus_tpu.ops import output_plane

MANIFEST_NAME = 'flywheel_manifest.json'

# Gate thresholds mirror the acceptance tests; keep in sync with
# tests/test_quantized_inference.py (0.002 identity, MAX_QV_DELTA=3).
INT8_IDENTITY_GATE = 0.002
BF16_QV_GATE = 3


def _with_levers(params: ml_collections.ConfigDict,
                 inference_dtype: Optional[str] = None,
                 quantize_matmuls: Optional[str] = None):
  """Copy of params with the quantization levers folded in (the
  config-side half of runner._apply_quant_levers)."""
  p = ml_collections.ConfigDict(params.to_dict())
  with p.unlocked():
    if inference_dtype:
      p.inference_dtype = inference_dtype
      p.dtype = inference_dtype
    if quantize_matmuls and quantize_matmuls != 'none':
      p.quantize_matmuls = quantize_matmuls
  return p


def _eval_identity(params, variables, eval_patterns, out_dir) -> float:
  metrics = evaluate_lib.run_evaluation(
      params=params, checkpoint_path=None, eval_patterns=eval_patterns,
      out_dir=out_dir, variables=variables)
  return float(metrics['alignment_identity'])


def int8_identity_gate(params, variables, eval_patterns, out_dir,
                       threshold: float = INT8_IDENTITY_GATE) -> Dict:
  """|alignment_identity(int8) - alignment_identity(f32)| <= threshold."""
  base = _eval_identity(params, variables, eval_patterns,
                        os.path.join(out_dir, 'gate_f32'))
  params_q = _with_levers(params, quantize_matmuls='int8')
  variables_q, n_quantized = quantize_lib.prepare_inference_variables(
      variables, params_q)
  quant = _eval_identity(params_q, variables_q, eval_patterns,
                         os.path.join(out_dir, 'gate_int8'))
  measured = abs(quant - base)
  return {
      'name': 'int8_alignment_identity_delta',
      'threshold': threshold,
      'measured': round(measured, 6),
      'passed': measured <= threshold,
      'detail': {'f32_identity': round(base, 6),
                 'int8_identity': round(quant, 6),
                 'n_quantized_matmuls': int(n_quantized)},
  }


def bf16_qv_gate(params, variables, eval_patterns,
                 threshold: int = BF16_QV_GATE,
                 max_batches: int = 4,
                 max_base_quality: int = 93) -> Dict:
  """Max per-base QV delta between f32 and bf16 forwards <= threshold.

  QVs come from the host epilogue oracle on each precision's softmax
  max-probability; only positions where both precisions argmax to the
  SAME base are compared (near-tie argmax flips change the base, not
  the confidence — the FASTQ gate excludes them the same way).
  """
  cal = calibration_lib.parse_calibration_string('skip')
  model_f32 = model_lib.get_model(params)
  params_16 = _with_levers(params, inference_dtype='bfloat16')
  model_16 = model_lib.get_model(params_16)
  variables_16, _ = quantize_lib.prepare_inference_variables(
      variables, params_16)
  ds = data_lib.DatasetIterator(
      patterns=list(eval_patterns), params=params,
      batch_size=params.batch_size, shuffle=False)
  fwd32 = jax.jit(lambda v, rows: model_f32.apply(v, rows))
  fwd16 = jax.jit(lambda v, rows: model_16.apply(v, rows))
  max_delta = 0
  n_compared = 0
  for i, batch in enumerate(ds.epoch()):
    if i >= max_batches:
      break
    rows = batch['rows']
    preds32 = np.asarray(fwd32(variables, rows), np.float32)
    preds16 = np.asarray(fwd16(variables_16, rows), np.float32)
    agree = preds32.argmax(-1) == preds16.argmax(-1)
    q32 = output_plane.host_quality_reference(
        preds32.max(-1), cal, max_base_quality)
    q16 = output_plane.host_quality_reference(
        preds16.max(-1), cal, max_base_quality)
    if agree.any():
      delta = np.abs(q32.astype(int) - q16.astype(int))[agree]
      max_delta = max(max_delta, int(delta.max()))
      n_compared += int(agree.sum())
  return {
      'name': 'bf16_max_qv_delta',
      'threshold': threshold,
      'measured': max_delta,
      'passed': max_delta <= threshold,
      'detail': {'n_positions_compared': n_compared},
  }


def _enforce(gates: Sequence[Dict]) -> None:
  for gate in gates:
    if not gate['passed']:
      raise faults_lib.FlywheelGateError(
          gate['name'], gate['measured'], gate['threshold'],
          detail=json.dumps(gate.get('detail', {})))


def run_flywheel(
    out_dir: str,
    train_patterns: Sequence[str],
    eval_patterns: Sequence[str],
    teacher_config: str = 'transformer_learn_values+test',
    student_config: str = 'transformer_learn_values_distill+test',
    teacher_checkpoint: Optional[str] = None,
    teacher_overrides: Sequence[str] = (),
    student_overrides: Sequence[str] = (),
    num_epochs: Optional[int] = None,
    batch_size: Optional[int] = None,
    export_batch_size: int = 1024,
    inference_dtype: Optional[str] = None,
    quantize_matmuls: Optional[str] = None,
    int8_gate_threshold: float = INT8_IDENTITY_GATE,
    bf16_gate_threshold: int = BF16_QV_GATE,
    mesh=None,
) -> Dict:
  """Train -> distill -> gates -> export; returns the manifest dict.

  With teacher_checkpoint the training stage is skipped and the
  flywheel spins from an existing teacher (the common retrain-student
  loop). inference_dtype / quantize_matmuls choose the levers BAKED
  into the exported artifact; both gates run and are enforced
  regardless, so the manifest always records the full quantization
  safety envelope of the released weights.
  """
  from deepconsensus_tpu import cli as cli_lib

  os.makedirs(out_dir, exist_ok=True)
  manifest: Dict = {'stages': {}, 'gates': [], 'ok': False}

  # ---- stage 1: teacher ----------------------------------------------
  if teacher_checkpoint is None:
    teacher_params = config_lib.get_config(teacher_config)
    cli_lib._apply_overrides(teacher_params, list(teacher_overrides))
    config_lib.finalize_params(teacher_params)
    with teacher_params.unlocked():
      if batch_size:
        teacher_params.batch_size = batch_size
    teacher_dir = os.path.join(out_dir, 'teacher')
    train_metrics = train_lib.run_training_with_retry(
        params=teacher_params,
        out_dir=teacher_dir,
        train_patterns=list(train_patterns),
        eval_patterns=list(eval_patterns),
        num_epochs=num_epochs,
        mesh=mesh,
    )
    teacher_checkpoint = checkpoints_lib.latest_valid_checkpoint(
        os.path.join(teacher_dir, 'checkpoints'))
    if teacher_checkpoint is None:
      raise faults_lib.FlywheelGateError(
          'teacher_training', 'no valid checkpoint', 'one checkpoint',
          detail=f'training under {teacher_dir} left no valid checkpoint')
    manifest['stages']['train'] = {
        'checkpoint': teacher_checkpoint,
        'metrics': {k: float(v) for k, v in train_metrics.items()},
    }
  else:
    teacher_params = config_lib.read_params_from_json(teacher_checkpoint)
    config_lib.finalize_params(teacher_params)
    manifest['stages']['train'] = {
        'checkpoint': teacher_checkpoint, 'skipped': True,
    }
  teacher_weights = checkpoints_lib.load_params(teacher_checkpoint)

  # ---- stage 2: distill ----------------------------------------------
  student_params = config_lib.get_config(student_config)
  cli_lib._apply_overrides(student_params, list(student_overrides))
  config_lib.finalize_params(student_params)
  with student_params.unlocked():
    if batch_size:
      student_params.batch_size = batch_size
  student_dir = os.path.join(out_dir, 'student')
  distill_metrics = distill_lib.run_distillation(
      params=student_params,
      teacher_params_cfg=teacher_params,
      teacher_variables={'params': teacher_weights},
      out_dir=student_dir,
      train_patterns=list(train_patterns),
      eval_patterns=list(eval_patterns),
      num_epochs=num_epochs,
      mesh=mesh,
  )
  student_checkpoint = checkpoints_lib.latest_valid_checkpoint(
      os.path.join(student_dir, 'checkpoints'))
  if student_checkpoint is None:
    raise faults_lib.FlywheelGateError(
        'distillation', 'no valid checkpoint', 'one checkpoint',
        detail=f'distillation under {student_dir} left no valid checkpoint')
  manifest['stages']['distill'] = {
      'checkpoint': student_checkpoint,
      'metrics': {k: float(v) for k, v in distill_metrics.items()},
  }
  student_variables = {'params': checkpoints_lib.load_params(
      student_checkpoint)}

  # ---- stage 3: quantization gates -----------------------------------
  gates_dir = os.path.join(out_dir, 'gates')
  gates: List[Dict] = [
      int8_identity_gate(student_params, student_variables,
                         list(eval_patterns), gates_dir,
                         threshold=int8_gate_threshold),
      bf16_qv_gate(student_params, student_variables,
                   list(eval_patterns), threshold=bf16_gate_threshold),
  ]
  manifest['gates'] = gates
  # Manifest lands even on a failed gate: the failure itself is the
  # record the next flywheel turn starts from.
  _write_manifest(out_dir, manifest)
  _enforce(gates)

  # ---- stage 4: export -----------------------------------------------
  export_dir = os.path.join(out_dir, 'export')
  artifact = export_lib.export_model(
      checkpoint_path=student_checkpoint,
      out_dir=export_dir,
      batch_size=export_batch_size,
      variables=student_variables,
      params=student_params,
      inference_dtype=inference_dtype,
      quantize_matmuls=quantize_matmuls,
  )
  manifest['stages']['export'] = {
      'artifact': artifact,
      'baked_levers': {
          'inference_dtype': inference_dtype or 'float32',
          'quantize_matmuls': quantize_matmuls or 'none',
      },
  }
  manifest['ok'] = all(g['passed'] for g in gates)
  _write_manifest(out_dir, manifest)
  return manifest


def _write_manifest(out_dir: str, manifest: Dict) -> str:
  path = os.path.join(out_dir, MANIFEST_NAME)
  tmp = path + '.tmp'
  with open(tmp, 'w') as f:
    json.dump(manifest, f, indent=2, sort_keys=True)
    f.write('\n')
  os.replace(tmp, path)
  return path
