"""Checkpoint loading helpers shared by training, inference, eval,
export, and distillation."""
from __future__ import annotations

import logging
import os
from typing import Any, Dict

log = logging.getLogger(__name__)


def load_params(checkpoint_path: str, params_template=None):
  """Restores the params tree from a checkpoint, tolerating any extra
  saved collections (step, opt_state, model_state).

  Checkpoints written by Trainer.save_checkpoint always carry extra
  keys, so the whole tree restores untyped and the params subtree is
  selected; this trades peak host memory (optimizer moments load too)
  for format independence.
  """
  import orbax.checkpoint as ocp

  checkpointer = ocp.StandardCheckpointer()
  restored = checkpointer.restore(os.path.abspath(checkpoint_path))
  if 'params' not in restored:
    raise KeyError(
        f'checkpoint {checkpoint_path!r} has no params tree; '
        f'keys: {list(restored)}'
    )
  return restored['params']


def load_full_state(checkpoint_path: str) -> Dict[str, Any]:
  """Restores the complete saved dict (params/opt_state/model_state/
  step where present)."""
  import orbax.checkpoint as ocp

  return ocp.StandardCheckpointer().restore(
      os.path.abspath(checkpoint_path)
  )
