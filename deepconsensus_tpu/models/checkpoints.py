"""Checkpoint loading helpers shared by training, inference, eval,
export, and distillation."""
from __future__ import annotations

import logging
import os
from typing import Any, Dict

log = logging.getLogger(__name__)


def load_params(checkpoint_path: str, params_template=None):
  """Restores the params tree from a checkpoint, tolerating any extra
  saved collections (step, opt_state, model_state).

  Checkpoints written by Trainer.save_checkpoint always carry extra
  keys, so the whole tree restores untyped and the params subtree is
  selected; this trades peak host memory (optimizer moments load too)
  for format independence.

  With params_template, the restored tree is validated against the
  template's structure and leaf shapes (clear restore-time error
  instead of a delayed flax scope failure) and each leaf is cast to
  the template's dtype (a bf16-saved checkpoint warm-starting an f32
  run must not silently flip the training dtype).
  """
  import orbax.checkpoint as ocp

  checkpointer = ocp.StandardCheckpointer()
  restored = checkpointer.restore(os.path.abspath(checkpoint_path))
  if 'params' not in restored:
    raise KeyError(
        f'checkpoint {checkpoint_path!r} has no params tree; '
        f'keys: {list(restored)}'
    )
  params = restored['params']
  if params_template is not None:
    import jax

    t_struct = jax.tree.structure(params_template)
    r_struct = jax.tree.structure(params)
    if t_struct != r_struct:
      raise ValueError(
          f'checkpoint {checkpoint_path!r} params tree does not match '
          f'the model: saved {r_struct}, expected {t_struct}'
      )

    def _adopt(t, r):
      if hasattr(t, 'shape') and tuple(t.shape) != tuple(r.shape):
        raise ValueError(
            f'checkpoint {checkpoint_path!r} leaf shape {tuple(r.shape)} '
            f'does not match the model\'s {tuple(t.shape)}'
        )
      return r.astype(t.dtype) if hasattr(t, 'dtype') else r

    params = jax.tree.map(_adopt, params_template, params)
  return params


def load_full_state(checkpoint_path: str) -> Dict[str, Any]:
  """Restores the complete saved dict (params/opt_state/model_state/
  step where present)."""
  import orbax.checkpoint as ocp

  return ocp.StandardCheckpointer().restore(
      os.path.abspath(checkpoint_path)
  )
