"""Checkpoint loading helpers shared by training, inference, eval,
export, and distillation — plus the checkpoint-integrity layer
(per-checkpoint manifests, validation, quarantine).

Integrity model: Trainer.save_checkpoint commits a small JSON manifest
*after* orbax's wait_until_finished, into
<ckpt_dir>/.manifests/checkpoint-N.json (atomic write + rename). A
checkpoint directory without a committed manifest is, by construction,
one whose save never finished; a directory whose on-disk file sizes
disagree with the manifest inventory was truncated or tampered with.
latest_valid_checkpoint() therefore never hands training a half-written
resume source: invalid candidates are moved to <ckpt_dir>/.quarantine/
and the newest valid one wins.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

_CKPT_NAME_RE = re.compile(r'^checkpoint-(\d+)$')
MANIFEST_DIRNAME = '.manifests'
QUARANTINE_DIRNAME = '.quarantine'
MANIFEST_VERSION = 1


def load_params(checkpoint_path: str, params_template=None):
  """Restores the params tree from a checkpoint, tolerating any extra
  saved collections (step, opt_state, model_state).

  Checkpoints written by Trainer.save_checkpoint always carry extra
  keys, so the whole tree restores untyped and the params subtree is
  selected; this trades peak host memory (optimizer moments load too)
  for format independence.

  With params_template, the restored tree is validated against the
  template's structure and leaf shapes (clear restore-time error
  instead of a delayed flax scope failure) and each leaf is cast to
  the template's dtype (a bf16-saved checkpoint warm-starting an f32
  run must not silently flip the training dtype).
  """
  if not os.path.exists(checkpoint_path):
    raise FileNotFoundError(
        f'checkpoint path {checkpoint_path!r} does not exist'
    )
  import orbax.checkpoint as ocp

  checkpointer = ocp.StandardCheckpointer()
  restored = checkpointer.restore(os.path.abspath(checkpoint_path))
  if 'params' not in restored:
    raise KeyError(
        f'checkpoint {checkpoint_path!r} has no params tree; '
        f'keys: {list(restored)}'
    )
  params = restored['params']
  if params_template is not None:
    import jax

    t_struct = jax.tree.structure(params_template)
    r_struct = jax.tree.structure(params)
    if t_struct != r_struct:
      raise ValueError(
          f'checkpoint {checkpoint_path!r} params tree does not match '
          f'the model: saved {r_struct}, expected {t_struct}'
      )

    def _adopt(t, r):
      if hasattr(t, 'shape') and tuple(t.shape) != tuple(r.shape):
        raise ValueError(
            f'checkpoint {checkpoint_path!r} leaf shape {tuple(r.shape)} '
            f'does not match the model\'s {tuple(t.shape)}'
        )
      return r.astype(t.dtype) if hasattr(t, 'dtype') else r

    params = jax.tree.map(_adopt, params_template, params)
  return params


def load_full_state(checkpoint_path: str) -> Dict[str, Any]:
  """Restores the complete saved dict (params/opt_state/model_state/
  step where present)."""
  if not os.path.exists(checkpoint_path):
    raise FileNotFoundError(
        f'checkpoint path {checkpoint_path!r} does not exist'
    )
  import orbax.checkpoint as ocp

  return ocp.StandardCheckpointer().restore(
      os.path.abspath(checkpoint_path)
  )


# ----------------------------------------------------------------------
# Checkpoint integrity: manifests, validation, quarantine


def tree_digest(tree: Any) -> str:
  """Deterministic sha256 over a checkpoint pytree's leaf CONTENTS
  (dtype + shape + raw bytes per leaf, combined order-independently).
  Deliberately structure-agnostic: the save side hashes live optax
  namedtuples while verify_digest hashes orbax's untyped restore
  (plain dicts), so leaf paths and flatten order differ between the
  two even for identical data. Save-time identity for deep
  verification; validation proper never needs to load arrays."""
  import jax
  import numpy as np

  leaf_digests = []
  for leaf in jax.tree_util.tree_leaves(tree):
    arr = np.asarray(leaf)
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(arr.tobytes())
    leaf_digests.append(h.hexdigest())
  return hashlib.sha256(
      ''.join(sorted(leaf_digests)).encode()
  ).hexdigest()


def checkpoint_step(ckpt_path: str) -> Optional[int]:
  """Step number encoded in a checkpoint-N directory name, else None."""
  m = _CKPT_NAME_RE.match(os.path.basename(ckpt_path))
  return int(m.group(1)) if m else None


def manifest_path(ckpt_path: str) -> str:
  ckpt_path = ckpt_path.rstrip(os.sep)
  return os.path.join(
      os.path.dirname(ckpt_path), MANIFEST_DIRNAME,
      os.path.basename(ckpt_path) + '.json',
  )


def _file_inventory(ckpt_path: str) -> Dict[str, int]:
  """{relative path: size} for every regular file under ckpt_path."""
  inventory: Dict[str, int] = {}
  for root, _, files in os.walk(ckpt_path):
    for name in files:
      full = os.path.join(root, name)
      inventory[os.path.relpath(full, ckpt_path)] = os.path.getsize(full)
  return inventory


def write_manifest(ckpt_path: str, step: int,
                   digest: Optional[str] = None,
                   extra: Optional[Dict[str, Any]] = None) -> str:
  """Commits the manifest for a fully-written checkpoint (atomic write
  + rename). Call only after the checkpointer's wait_until_finished:
  the manifest's existence IS the commit record. `extra` merges
  additional provenance keys (elastic runs record pod_epoch and
  pod_members so a checkpoint names the member set that wrote it);
  reserved keys cannot be overridden."""
  path = manifest_path(ckpt_path)
  os.makedirs(os.path.dirname(path), exist_ok=True)
  manifest = {
      'version': MANIFEST_VERSION,
      'step': int(step),
      'digest': digest,
      'time': time.time(),
      'files': _file_inventory(ckpt_path),
  }
  if extra:
    for key, value in extra.items():
      manifest.setdefault(key, value)
  tmp = path + '.tmp'
  with open(tmp, 'w') as f:
    json.dump(manifest, f)
    f.flush()
    os.fsync(f.fileno())
  os.replace(tmp, path)
  return path


def read_manifest(ckpt_path: str) -> Optional[Dict[str, Any]]:
  try:
    with open(manifest_path(ckpt_path)) as f:
      return json.load(f)
  except (FileNotFoundError, json.JSONDecodeError):
    return None


def validate_checkpoint(ckpt_path: str) -> Tuple[bool, str]:
  """(ok, reason). Cheap structural validation: a committed manifest
  whose step matches the directory name and whose recorded file
  inventory matches what is on disk (existence + exact sizes — catches
  truncation without loading any arrays)."""
  if not os.path.isdir(ckpt_path):
    return False, 'not a directory'
  step = checkpoint_step(ckpt_path)
  if step is None:
    return False, 'name does not match checkpoint-<step>'
  manifest = read_manifest(ckpt_path)
  if manifest is None:
    return False, 'no committed manifest (save did not finish?)'
  if manifest.get('version') != MANIFEST_VERSION:
    return False, f'unknown manifest version {manifest.get("version")!r}'
  if manifest.get('step') != step:
    return False, (
        f'manifest step {manifest.get("step")} != directory step {step}'
    )
  recorded = manifest.get('files') or {}
  if not recorded:
    return False, 'manifest records no files'
  for rel, size in recorded.items():
    full = os.path.join(ckpt_path, rel)
    if not os.path.exists(full):
      return False, f'missing file {rel}'
    actual = os.path.getsize(full)
    if actual != size:
      return False, f'size mismatch for {rel}: {actual} != {size}'
  return True, 'ok'


def verify_digest(ckpt_path: str) -> bool:
  """Deep verification: reload the checkpoint and compare its leaf-tree
  digest against the manifest's. Expensive (full restore) — forensic
  use, not the resume path."""
  manifest = read_manifest(ckpt_path)
  if manifest is None or not manifest.get('digest'):
    return False
  return tree_digest(load_full_state(ckpt_path)) == manifest['digest']


def quarantine_checkpoint(ckpt_path: str, reason: str) -> str:
  """Moves a corrupt/uncommitted checkpoint (and its manifest, if any)
  into <ckpt_dir>/.quarantine/ so the resume scan never considers it
  again, preserving the bytes for forensics. Returns the new path."""
  ckpt_path = ckpt_path.rstrip(os.sep)
  qdir = os.path.join(os.path.dirname(ckpt_path), QUARANTINE_DIRNAME)
  os.makedirs(qdir, exist_ok=True)
  dest = os.path.join(qdir, os.path.basename(ckpt_path))
  suffix = 0
  while os.path.exists(dest):
    suffix += 1
    dest = os.path.join(qdir, f'{os.path.basename(ckpt_path)}.{suffix}')
  os.rename(ckpt_path, dest)
  src_manifest = manifest_path(ckpt_path)
  if os.path.exists(src_manifest):
    os.rename(src_manifest, dest + '.manifest.json')
  with open(dest + '.reason.txt', 'w') as f:
    f.write(reason + '\n')
  log.warning('quarantined checkpoint %s -> %s (%s)',
              ckpt_path, dest, reason)
  return dest


def _candidate_steps(ckpt_dir: str) -> List[Tuple[int, str]]:
  """(step, path) for checkpoint-N subdirectories, newest first."""
  if not os.path.isdir(ckpt_dir):
    return []
  out = []
  for name in os.listdir(ckpt_dir):
    m = _CKPT_NAME_RE.match(name)
    path = os.path.join(ckpt_dir, name)
    if m and os.path.isdir(path):
      out.append((int(m.group(1)), path))
  return sorted(out, reverse=True)


def latest_valid_checkpoint(ckpt_dir: str,
                            quarantine: bool = True) -> Optional[str]:
  """Newest checkpoint that passes validation; invalid newer ones are
  quarantined (or just skipped with quarantine=False — e.g. on
  non-primary hosts, where process 0 owns the shared filesystem
  mutation) so training falls back instead of crash-looping on a
  half-written resume source.

  Legacy compatibility: a checkpoint directory predating the manifest
  format (no .manifests/ entry for ANY candidate) is handled with the
  old newest-step-wins rule rather than quarantining a whole run's
  history."""
  candidates = _candidate_steps(ckpt_dir)
  if not candidates:
    return None
  if not any(read_manifest(path) is not None for _, path in candidates):
    newest = candidates[0][1]
    log.warning(
        'checkpoint dir %s has no manifests (written by an older '
        'version?); falling back to newest-step resume: %s',
        ckpt_dir, newest,
    )
    return newest
  for _, path in candidates:
    ok, reason = validate_checkpoint(path)
    if ok:
      return path
    if quarantine:
      try:
        quarantine_checkpoint(path, reason)
      except OSError as e:  # racing host already moved it
        log.warning('could not quarantine %s: %s', path, e)
    else:
      log.warning('skipping invalid checkpoint %s (%s)', path, reason)
  return None


def latest_valid_step(ckpt_dir: str) -> Optional[int]:
  """Step of the newest valid checkpoint, without quarantining
  (read-only — used by the crash-loop breaker to detect stalled
  restarts)."""
  candidates = _candidate_steps(ckpt_dir)
  if candidates and not any(
      read_manifest(path) is not None for _, path in candidates):
    return candidates[0][0]
  for step, path in candidates:
    if validate_checkpoint(path)[0]:
      return step
  return None
