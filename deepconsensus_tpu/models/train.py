"""Training loop: optax LAMB + SPMD data/tensor parallelism + orbax.

TPU-native re-design of the reference's custom tf.distribute loop
(reference: deepconsensus/models/model_train_custom_loop.py:93-358,
model_utils.py:478-669): one jitted train_step with sharded inputs over
a jax.sharding.Mesh, LAMB with warmup+polynomial decay, periodic eval
with checkpointing, best-checkpoint tracking by eval accuracy, a
checkpoint_metrics.tsv sidecar, and crash-resumable state.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import ml_collections
import numpy as np
import optax
from flax import struct
from flax.training import train_state as ts_lib
import orbax.checkpoint as ocp

from deepconsensus_tpu import constants
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.models import losses as losses_lib
from deepconsensus_tpu.models import metrics as metrics_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.parallel import mesh as mesh_lib
from deepconsensus_tpu.preprocess.pileup import row_indices


def enable_compilation_cache(cache_dir: Optional[str] = None) -> None:
  """Persistent XLA compilation cache: the differentiated wavefront
  scans compile slowly on TPU, so amortize across processes.

  Directory resolution: explicit arg > DC_TPU_COMPILE_CACHE env var >
  per-user default. Set DC_TPU_COMPILE_CACHE=off to disable.
  """
  cache_dir = cache_dir or os.environ.get('DC_TPU_COMPILE_CACHE')
  if cache_dir == 'off':
    return
  if cache_dir is None:
    cache_dir = os.path.join(
        os.path.expanduser('~'), '.cache', 'dctpu_jax_cache'
    )
  try:
    jax.config.update('jax_compilation_cache_dir', cache_dir)
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 10)
  except AttributeError:  # pragma: no cover - older jax
    pass


class TrainState(ts_lib.TrainState):
  dropout_rng: jax.Array = struct.field(pytree_node=True, default=None)
  # Non-trainable variable collections (e.g. BatchNorm batch_stats for
  # the conv family); empty dict for purely-functional models.
  model_state: Any = struct.field(pytree_node=True, default_factory=dict)


def create_learning_rate_fn(
    params: ml_collections.ConfigDict, decay_steps: int
):
  """Linear warmup into polynomial (power 1) decay, matching tf-models'
  LinearWarmup(PolynomialDecay) (reference model_utils.py:621-669)."""
  decay_steps = max(int(decay_steps), 1)
  poly = optax.polynomial_schedule(
      init_value=params.initial_learning_rate,
      end_value=params.end_learning_rate,
      power=1.0,
      transition_steps=decay_steps,
  )
  warmup_steps = int(params.warmup_steps)
  if warmup_steps <= 0:
    return poly

  def schedule(step):
    warm = poly(warmup_steps) * (step + 1) / warmup_steps
    return jnp.where(step < warmup_steps, warm, poly(step))

  return schedule


def _weight_decay_mask(params):
  """Excludes biases and layer-norm/rezero parameters from decay
  (reference exclude list: model_utils.py:641-648)."""

  def keep(path, leaf):
    del leaf
    parts = [getattr(k, 'key', getattr(k, 'name', str(k))) for k in path]
    path_str = '/'.join(parts).lower()
    if parts and parts[-1] in ('bias', 'alpha'):
      return False
    if 'layer_norm' in path_str or 'norm' in path_str:
      return False
    return True

  return jax.tree_util.tree_map_with_path(keep, params)


def create_optimizer(
    params: ml_collections.ConfigDict, decay_steps: int
) -> optax.GradientTransformation:
  lr_fn = create_learning_rate_fn(params, decay_steps)
  return optax.lamb(
      learning_rate=lr_fn,
      b1=params.beta_1,
      b2=params.beta_2,
      eps=params.epsilon,
      weight_decay=params.weight_decay_rate,
      mask=_weight_decay_mask,
  )


def resolve_pallas_wavefront(params: ml_collections.ConfigDict) -> bool:
  """None = auto: the Pallas DP wins on a real TPU backend (measured
  1.24x the scan DP on v5e); everywhere else the scan DP is faster
  than the interpreted kernel."""
  flag = params.get('use_pallas_wavefront', None)
  if flag is None:
    return jax.default_backend() == 'tpu'
  return bool(flag)


def make_loss(params: ml_collections.ConfigDict) -> losses_lib.AlignmentLoss:
  width = params.get('band_width', None)
  return losses_lib.AlignmentLoss(
      del_cost=params.del_cost,
      loss_reg=params.loss_reg,
      width=width,
      use_pallas=resolve_pallas_wavefront(params),
  )


def ccs_row_from_batch(rows: jnp.ndarray, params) -> jnp.ndarray:
  """Extracts the CCS base row from the stacked input tensor."""
  ccs_range = row_indices(params.max_passes, params.use_ccs_bq)[4]
  return rows[:, ccs_range[0], :, 0]


@dataclasses.dataclass
class Trainer:
  """Owns jitted steps, checkpointing, and the metrics sidecars."""

  params: ml_collections.ConfigDict
  out_dir: str
  mesh: Optional[Any] = None

  def __post_init__(self):
    os.makedirs(self.out_dir, exist_ok=True)
    enable_compilation_cache()
    self.model = model_lib.get_model(self.params)
    self.loss_fn = make_loss(self.params)
    self.alignment_metric = metrics_lib.AlignmentMetric()
    if self.mesh is None:
      self.mesh = mesh_lib.make_mesh()
    self._ckpt_dir = os.path.join(os.path.abspath(self.out_dir), 'checkpoints')
    self._checkpointer = ocp.StandardCheckpointer()
    self._metrics_tsv = os.path.join(self.out_dir, 'checkpoint_metrics.tsv')
    self._best_file = os.path.join(self.out_dir, 'best_checkpoint.txt')
    self._metrics_jsonl = os.path.join(self.out_dir, 'metrics.jsonl')
    # Which eval metric selects best_checkpoint.txt. The reference pins
    # per_example_accuracy (whole-window exact match); on small or
    # held-out eval sets that metric can tie at 0.0 for every
    # checkpoint (observed on the bundled eval split), so it is
    # configurable — eval/identity_pred is the right selector
    # there.
    self._best_metric_name = self.params.get(
        'best_checkpoint_metric', constants.MAIN_EVAL_METRIC_NAME
    ) or constants.MAIN_EVAL_METRIC_NAME
    self._best_metric = -1.0
    self._tsv_columns = None
    # Recover best-metric and header state across restarts.
    if os.path.exists(self._metrics_tsv):
      with open(self._metrics_tsv) as f:
        header = f.readline().strip().split('\t')
        self._tsv_columns = header[1:]
        if self._best_metric_name in self._tsv_columns:
          idx = 1 + self._tsv_columns.index(self._best_metric_name)
          for line in f:
            parts = line.strip().split('\t')
            try:
              self._best_metric = max(self._best_metric, float(parts[idx]))
            except (IndexError, ValueError):
              continue

  # ---- state ---------------------------------------------------------
  def init_state(self, steps_total: int, seed: Optional[int] = None
                 ) -> TrainState:
    seed = self.params.seed if seed is None else seed
    rng = jax.random.PRNGKey(seed)
    rows = jnp.zeros(
        (1, self.params.total_rows, self.params.max_length, 1), jnp.float32
    )
    variables = self.model.init(rng, rows)
    tx = create_optimizer(self.params, steps_total)
    model_state = {k: v for k, v in variables.items() if k != 'params'}
    state = TrainState.create(
        apply_fn=self.model.apply,
        params=variables['params'],
        tx=tx,
        dropout_rng=jax.random.fold_in(rng, 1),
        model_state=model_state,
    )
    with open(os.path.join(self.out_dir, 'model_summary.txt'), 'w') as f:
      f.write(model_lib.summarize_params(variables['params']))
    # Place parameters according to the mesh sharding rules; optimizer
    # state follows the parameter shardings on first update.
    shardings = mesh_lib.param_shardings(self.mesh, state.params)
    params_sharded = jax.device_put(state.params, shardings)
    return state.replace(params=params_sharded)

  # ---- steps ---------------------------------------------------------
  def train_step_fn(self):
    loss_obj = self.loss_fn

    def step(state: TrainState, batch: Dict[str, jnp.ndarray]):
      rng = jax.random.fold_in(state.dropout_rng, state.step)
      mutable = list(state.model_state.keys())

      def loss_of(p):
        if mutable:
          preds, new_model_state = state.apply_fn(
              {'params': p, **state.model_state},
              batch['rows'], train=True, rngs={'dropout': rng},
              mutable=mutable,
          )
        else:
          preds = state.apply_fn(
              {'params': p}, batch['rows'], train=True,
              rngs={'dropout': rng},
          )
          new_model_state = {}
        return loss_obj(batch['label'], preds), (preds, new_model_state)

      (loss, (preds, new_model_state)), grads = jax.value_and_grad(
          loss_of, has_aux=True
      )(state.params)
      new_state = state.apply_gradients(
          grads=grads, model_state=new_model_state
      ) if mutable else state.apply_gradients(grads=grads)
      correct, total = metrics_lib.per_example_accuracy_counts(
          batch['label'], preds
      )
      metrics = {
          'loss': loss,
          'accuracy_correct': correct,
          'accuracy_total': total,
      }
      return new_state, metrics

    batch_sh = self._batch_sharding()
    return jax.jit(
        step,
        in_shardings=(None, {'rows': batch_sh, 'label': batch_sh}),
        donate_argnums=(0,),
    )

  def _batch_sharding(self):
    """Shard the batch over the data axis when divisible, else
    replicate (tiny test batches)."""
    dp = self.mesh.shape[mesh_lib.DATA_AXIS]
    if self.params.batch_size % dp == 0:
      return mesh_lib.batch_sharding(self.mesh)
    return mesh_lib.replicated(self.mesh)

  def globalize_batch(self, batch):
    """Multi-host batch assembly: every host loads the SAME global
    batch (same files, same seed), takes its `local_batch_slice`, and
    the slices are stitched into one globally-sharded array
    (reference reaches pods via TPUStrategy's per-replica dataset:
    model_train_custom_loop.py:333-343). No-op single-process."""
    if jax.process_count() == 1:
      return batch
    from deepconsensus_tpu.parallel import distributed

    spec = self._batch_sharding().spec
    if not len(spec):  # replicated: all hosts feed identical arrays
      return {
          k: distributed.host_local_to_global(self.mesh, spec, v)
          for k, v in batch.items()
      }
    n = next(iter(batch.values())).shape[0]
    sl = distributed.local_batch_slice(n)
    return {
        k: distributed.host_local_to_global(self.mesh, spec, v[sl])
        for k, v in batch.items()
    }

  def eval_step_fn(self):
    loss_obj = self.loss_fn
    params_cfg = self.params
    metric = self.alignment_metric

    def step(state: TrainState, batch: Dict[str, jnp.ndarray]):
      preds = state.apply_fn(
          {'params': state.params, **state.model_state}, batch['rows']
      )
      loss = loss_obj(batch['label'], preds)
      correct, total = metrics_lib.per_example_accuracy_counts(
          batch['label'], preds
      )
      ccs = ccs_row_from_batch(batch['rows'], params_cfg)
      id_ccs, id_pred = metrics_lib.batch_identity_ccs_pred(
          ccs, preds, batch['label'], metric
      )
      out = {
          'loss': loss,
          'accuracy_correct': correct,
          'accuracy_total': total,
          'identity_ccs': id_ccs,
          'identity_pred': id_pred,
      }
      for cls in range(constants.SEQ_VOCAB_SIZE):
        c, t = metrics_lib.per_class_accuracy_counts(
            batch['label'], preds, cls
        )
        out[f'class{cls}_correct'] = c
        out[f'class{cls}_total'] = t
      return out

    batch_sh = self._batch_sharding()
    return jax.jit(
        step, in_shardings=(None, {'rows': batch_sh, 'label': batch_sh})
    )

  def run_eval(self, state, eval_ds) -> Dict[str, float]:
    """One full eval epoch aggregated to the eval/* metric dict.

    The single aggregation used by BOTH run_training and distill, so
    their TSVs carry the same metric key set and
    params.best_checkpoint_metric means the same thing everywhere."""
    if getattr(self, '_cached_eval_step', None) is None:
      self._cached_eval_step = self.eval_step_fn()
    eval_step = self._cached_eval_step
    sums: Dict[str, float] = {}
    batches = 0
    yield_metric = metrics_lib.YieldOverCCS()
    for batch in eval_ds.epoch():
      batch = self.globalize_batch(batch)
      out = {k: float(v) for k, v in eval_step(state, batch).items()}
      yield_metric.update(out['identity_ccs'], out['identity_pred'])
      for k, v in out.items():
        sums[k] = sums.get(k, 0.0) + v
      batches += 1
    if not batches:
      return {}
    acc = sums['accuracy_correct'] / max(sums['accuracy_total'], 1)
    result = {
        'eval/loss': sums['loss'] / batches,
        constants.MAIN_EVAL_METRIC_NAME: acc,
        'eval/identity_ccs': sums['identity_ccs'] / batches,
        'eval/identity_pred': sums['identity_pred'] / batches,
        'eval/yield_over_ccs': yield_metric.result(),
    }
    # Emit every class key unconditionally so the metric key set (and
    # the TSV header) stays stable across evals.
    for cls in range(constants.SEQ_VOCAB_SIZE):
      total = sums.get(f'class{cls}_total', 0.0)
      result[f'eval/class{cls}_accuracy'] = (
          sums[f'class{cls}_correct'] / total if total else 0.0
      )
    return result

  # ---- checkpoints ---------------------------------------------------
  def save_checkpoint(self, state: TrainState, step: int,
                      eval_metrics: Dict[str, float]) -> str:
    path = os.path.join(self._ckpt_dir, f'checkpoint-{step}')
    # Multi-host: EVERY process calls save — orbax's multihost protocol
    # barriers across processes and writes from the primary only.
    self._checkpointer.save(
        path,
        {
            'params': jax.device_get(state.params),
            'opt_state': jax.device_get(state.opt_state),
            'model_state': jax.device_get(state.model_state),
            'step': step,
        },
        force=True,
    )
    # Block until the async write finalizes so a crash right after this
    # point never leaves a half-written latest checkpoint.
    wait = getattr(self._checkpointer, 'wait_until_finished', None)
    if wait is not None:
      wait()
    if jax.process_index() != 0:
      # Metric sidecars (TSV, best-checkpoint) have one writer.
      return path
    header_needed = not os.path.exists(self._metrics_tsv)
    if header_needed:
      self._tsv_columns = sorted(eval_metrics)
      with open(self._metrics_tsv, 'a') as f:
        f.write('checkpoint\t' + '\t'.join(self._tsv_columns) + '\n')
    with open(self._metrics_tsv, 'a') as f:
      # Align values to the header captured at first write; metric key
      # sets are stable by construction (all keys always emitted).
      f.write(
          f'checkpoint-{step}\t'
          + '\t'.join(
              str(eval_metrics.get(k, 'nan')) for k in self._tsv_columns
          )
          + '\n'
      )
    if self._best_metric_name not in eval_metrics:
      # A typo'd metric name would otherwise silently never update
      # best_checkpoint.txt (get() returning -1.0 forever).
      logging.getLogger(__name__).warning(
          'best_checkpoint_metric %r not among eval metrics %s; '
          'best_checkpoint.txt will not update',
          self._best_metric_name, sorted(eval_metrics))
    main = eval_metrics.get(self._best_metric_name, -1.0)
    if main > self._best_metric:
      self._best_metric = main
      with open(self._best_file, 'w') as f:
        f.write(f'checkpoint-{step}\n')
    return path

  def restore_checkpoint(self, state: TrainState, path: str,
                         params_only: bool = False) -> TrainState:
    """Restores training state; full resume includes optimizer state
    and LR-schedule position (the reference restores the whole
    tf.train.Checkpoint: model_utils.py:511-540)."""
    if params_only:
      # Warm-start source checkpoints are usually full TrainStates
      # (params + opt_state + step); a params-only typed target makes
      # orbax raise a structure mismatch, so select the subtree from
      # an untyped restore (same approach as checkpoints.load_params,
      # which inference/export use). The template keeps restore-time
      # structure/shape validation and casts to the model's dtype.
      from deepconsensus_tpu.models.checkpoints import load_params

      return state.replace(params=load_params(
          path, params_template=jax.device_get(state.params)))
    restored = self._checkpointer.restore(
        path,
        target={
            'params': jax.device_get(state.params),
            'opt_state': jax.device_get(state.opt_state),
            'model_state': jax.device_get(state.model_state),
            'step': 0,
        },
    )
    return state.replace(
        params=restored['params'],
        opt_state=restored['opt_state'],
        model_state=restored['model_state'],
        step=jnp.asarray(restored['step']),
    )

  def latest_checkpoint(self) -> Optional[str]:
    if not os.path.isdir(self._ckpt_dir):
      return None
    steps = []
    for name in os.listdir(self._ckpt_dir):
      if name.startswith('checkpoint-'):
        try:
          steps.append(int(name.split('-')[1]))
        except ValueError:
          continue
    if not steps:
      return None
    return os.path.join(self._ckpt_dir, f'checkpoint-{max(steps)}')

  def log_metrics(self, step: int, split: str, metrics: Dict[str, float]):
    if jax.process_index() != 0:
      return
    entry = {'step': step, 'split': split, 'time': time.time(), **metrics}
    with open(self._metrics_jsonl, 'a') as f:
      f.write(json.dumps(entry) + '\n')
    self._write_tensorboard(step, split, metrics)

  def _write_tensorboard(self, step: int, split: str,
                         metrics: Dict[str, float]):
    """Optional TensorBoard scalars (reference writes TB summaries:
    model_train_custom_loop.py:164-166). No-op without tensorflow."""
    if not hasattr(self, '_tb_writers'):
      self._tb_writers = {}
    if split not in self._tb_writers:
      try:
        import tensorflow as tf  # noqa: F401

        self._tb_writers[split] = tf.summary.create_file_writer(
            os.path.join(self.out_dir, 'tensorboard', split)
        )
      except ImportError:
        self._tb_writers[split] = None
    writer = self._tb_writers[split]
    if writer is None:
      return
    import tensorflow as tf

    with writer.as_default():
      for name, value in metrics.items():
        try:
          tf.summary.scalar(name, float(value), step=step)
        except (TypeError, ValueError):
          continue
      writer.flush()


def run_training(
    params: ml_collections.ConfigDict,
    out_dir: str,
    train_patterns=None,
    eval_patterns=None,
    num_epochs: Optional[int] = None,
    mesh=None,
    eval_every: Optional[int] = None,
    warm_start: Optional[str] = None,
    distributed_config: Optional[Dict[str, Any]] = None,
) -> Dict[str, float]:
  """End-to-end training driver. Returns final eval metrics.

  Multi-host: pass distributed_config (coordinator_address,
  num_processes, process_id — or {} for pod auto-detection) to
  initialize jax.distributed before the mesh is built; every host then
  feeds its local slice of the global batch (globalize_batch) and only
  process 0 writes checkpoints/metrics. out_dir must be shared (or at
  least readable) across hosts for crash-resume.
  """
  if distributed_config is not None:
    from deepconsensus_tpu.parallel import distributed

    distributed.initialize(**distributed_config)
  train_patterns = train_patterns or list(params.train_path)
  eval_patterns = eval_patterns or list(params.eval_path)
  num_epochs = num_epochs or params.num_epochs

  streaming = bool(params.get('streaming', False))
  train_ds = None
  if streaming:
    # Shard-interleaved streaming with a shuffle buffer; "epochs"
    # become fixed step counts (n_examples_train / batch). The dataset
    # itself is constructed after checkpoint restore so the stream can
    # be reseeded by resume position.
    n_train = int(params.get('n_examples_train', 0) or 0)
    if n_train < params.batch_size:
      raise ValueError(
          'streaming training requires params.n_examples_train (>= one '
          'batch) to size the step budget'
      )
    steps_per_epoch = n_train // params.batch_size
  else:
    train_ds = data_lib.DatasetIterator(
        patterns=train_patterns,
        params=params,
        batch_size=params.batch_size,
        seed=params.seed,
    )
    steps_per_epoch = train_ds.steps_per_epoch
  eval_ds = data_lib.DatasetIterator(
      patterns=eval_patterns,
      params=params,
      batch_size=params.batch_size,
      shuffle=False,
  )
  decay_steps = steps_per_epoch * params.get('num_epochs_for_decay',
                                             num_epochs)
  trainer = Trainer(params=params, out_dir=out_dir, mesh=mesh)
  config_lib.save_params_as_json(out_dir, params)
  state = trainer.init_state(steps_total=decay_steps)
  if warm_start and trainer.latest_checkpoint() is not None:
    logging.getLogger(__name__).warning(
        'warm_start=%s ignored: %s already has checkpoints; resuming '
        'from the latest instead', warm_start, out_dir,
    )
  if warm_start and trainer.latest_checkpoint() is None:
    # Warm start adopts weights only; optimizer starts fresh
    # (reference --checkpoint warm start: model_train_custom_loop.py:119-124).
    # Applies only to the very first start: once this run has its own
    # checkpoints, crash-resume below must win or a preempted
    # warm-started run would restart from step 0.
    state = trainer.restore_checkpoint(state, warm_start, params_only=True)
  train_step = trainer.train_step_fn()
  eval_every = eval_every or params.get('eval_every_n_steps', 3000)

  def run_eval(state) -> Dict[str, float]:
    return trainer.run_eval(state, eval_ds)

  # Crash-resume: pick up from the newest checkpoint in out_dir
  # (reference resumable training: model_utils.py:511-540).
  # The out_dir's own latest checkpoint always wins over warm_start:
  # warm_start seeds only the very first start, so a preempted
  # warm-started run resumes its own progress instead of resetting.
  step = 0
  latest = trainer.latest_checkpoint()
  if latest:
    state = trainer.restore_checkpoint(state, latest)
    step = int(state.step)

  profile_dir = params.get('profile_dir', None)
  if profile_dir:
    jax.profiler.start_trace(profile_dir)

  def train_batches():
    if streaming:
      # Fold the resume step into the stream seed so a restarted run
      # draws fresh (differently-shuffled) data instead of replaying
      # the head of the corpus.
      ds = data_lib.StreamingDataset(
          patterns=train_patterns,
          params=params,
          batch_size=params.batch_size,
          **({'buffer_size': params.buffer_size}
             if 'buffer_size' in params else {}),
          workers=params.get('loader_workers', 0),
          seed=params.seed + step,
      )
      it = iter(ds)
      try:
        for _ in range(max(steps_per_epoch * num_epochs - step, 0)):
          yield next(it)
      finally:
        it.close()
    else:
      steps_to_skip = step
      for _ in range(num_epochs):
        for batch in train_ds.epoch():
          if steps_to_skip > 0:
            # Skip batches already covered by the restored checkpoint.
            steps_to_skip -= 1
            continue
          yield batch

  def maybe_augmented():
    # Training-time window augmentation (params.augment; applied to
    # training batches only — eval batches go through run_eval
    # untouched). Seeded off params.seed + resume step so a resumed
    # run draws a fresh augmentation stream instead of replaying one.
    if not params.get('augment', False):
      return train_batches()
    aug_rng = np.random.default_rng(params.seed + 7919 * (step + 1))
    return (
        data_lib.augment_batch(b, params, aug_rng)
        for b in train_batches()
    )

  final_metrics: Dict[str, float] = {}
  try:
    # Background prefetch: host-side decode/shuffle/stacking for batch
    # i+1 overlaps the device's step i (the async dispatch returns
    # before compute finishes). Reference counterpart: tf.data
    # prefetch(AUTOTUNE) in data_providers.py.
    for batch in data_lib.prefetch_iterator(maybe_augmented()):
      batch = trainer.globalize_batch(batch)
      with jax.profiler.StepTraceAnnotation('train', step_num=step):
        state, m = train_step(state, batch)
      step += 1
      if step % params.get('log_every_n_steps', 100) == 0:
        m_host = {k: float(v) for k, v in m.items()}
        m_host['train/accuracy'] = m_host['accuracy_correct'] / max(
            m_host['accuracy_total'], 1
        )
        trainer.log_metrics(step, 'train', m_host)
      if step % eval_every == 0:
        final_metrics = run_eval(state)
        trainer.log_metrics(step, 'eval', final_metrics)
        trainer.save_checkpoint(state, step, final_metrics)
    final_metrics = run_eval(state)
    trainer.log_metrics(step, 'eval', final_metrics)
    trainer.save_checkpoint(state, step, final_metrics)
  finally:
    if profile_dir:
      jax.profiler.stop_trace()
  if jax.process_count() > 1:
    # Writes happen on process 0 only; without this sync the other
    # hosts exit first and the distributed shutdown barrier times out
    # while process 0 is still checkpointing.
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices('dc_tpu_end_of_training')
  return final_metrics


def run_training_with_retry(*args, max_retries: int = 1_000_000, **kwargs):
  """Retries training on device-unavailable errors (TPU preemption),
  resuming from the latest checkpoint (reference retry-forever loop:
  model_train_custom_loop.py:333-347)."""
  attempts = 0
  while True:
    try:
      return run_training(*args, **kwargs)
    except Exception as e:  # pylint: disable=broad-except
      message = str(e)
      transient = any(
          key in message.upper()
          for key in ('UNAVAILABLE', 'DEADLINE_EXCEEDED', 'PREEMPT')
      )
      attempts += 1
      if not transient or attempts > max_retries:
        raise
      logging.getLogger(__name__).warning(
          'transient device failure (%s); restarting from latest '
          'checkpoint (attempt %d)', message.splitlines()[0], attempts,
      )
