"""Training loop: optax LAMB + SPMD data/tensor parallelism + orbax.

TPU-native re-design of the reference's custom tf.distribute loop
(reference: deepconsensus/models/model_train_custom_loop.py:93-358,
model_utils.py:478-669): one jitted train_step with sharded inputs over
a jax.sharding.Mesh, LAMB with warmup+polynomial decay, periodic eval
with checkpointing, best-checkpoint tracking by eval accuracy, a
checkpoint_metrics.tsv sidecar, and crash-resumable state.
"""
from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import logging
import os
import queue as queue_lib
import threading
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import ml_collections
import numpy as np
import optax
from flax import struct
from flax.training import train_state as ts_lib
import orbax.checkpoint as ocp

from deepconsensus_tpu import constants
from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu import obs as obs_lib
from deepconsensus_tpu.models import checkpoints as checkpoints_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.models import losses as losses_lib
from deepconsensus_tpu.models import metrics as metrics_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.parallel import mesh as mesh_lib
from deepconsensus_tpu.parallel import partition_rules
from deepconsensus_tpu.parallel import ring_attention as ring_lib
from deepconsensus_tpu.preprocess.pileup import row_indices


def enable_compilation_cache(cache_dir: Optional[str] = None) -> None:
  """Persistent XLA compilation cache: the differentiated wavefront
  scans compile slowly on TPU, so amortize across processes.

  Directory resolution: explicit arg > DC_TPU_COMPILE_CACHE env var >
  per-user default. Set DC_TPU_COMPILE_CACHE=off to disable.
  """
  cache_dir = cache_dir or os.environ.get('DC_TPU_COMPILE_CACHE')
  if cache_dir == 'off':
    return
  if cache_dir is None:
    cache_dir = os.path.join(
        os.path.expanduser('~'), '.cache', 'dctpu_jax_cache'
    )
  try:
    jax.config.update('jax_compilation_cache_dir', cache_dir)
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 10)
  except AttributeError:  # pragma: no cover - older jax
    pass


class TrainState(ts_lib.TrainState):
  dropout_rng: jax.Array = struct.field(pytree_node=True, default=None)
  # Non-trainable variable collections (e.g. BatchNorm batch_stats for
  # the conv family); empty dict for purely-functional models.
  model_state: Any = struct.field(pytree_node=True, default_factory=dict)


def create_learning_rate_fn(
    params: ml_collections.ConfigDict, decay_steps: int
):
  """Linear warmup into polynomial (power 1) decay, matching tf-models'
  LinearWarmup(PolynomialDecay) (reference model_utils.py:621-669)."""
  decay_steps = max(int(decay_steps), 1)
  poly = optax.polynomial_schedule(
      init_value=params.initial_learning_rate,
      end_value=params.end_learning_rate,
      power=1.0,
      transition_steps=decay_steps,
  )
  warmup_steps = int(params.warmup_steps)
  if warmup_steps <= 0:
    return poly

  def schedule(step):
    warm = poly(warmup_steps) * (step + 1) / warmup_steps
    return jnp.where(step < warmup_steps, warm, poly(step))

  return schedule


def _weight_decay_mask(params):
  """Excludes biases and layer-norm/rezero parameters from decay
  (reference exclude list: model_utils.py:641-648)."""

  def keep(path, leaf):
    del leaf
    parts = [getattr(k, 'key', getattr(k, 'name', str(k))) for k in path]
    path_str = '/'.join(parts).lower()
    if parts and parts[-1] in ('bias', 'alpha'):
      return False
    if 'layer_norm' in path_str or 'norm' in path_str:
      return False
    return True

  return jax.tree_util.tree_map_with_path(keep, params)


def create_optimizer(
    params: ml_collections.ConfigDict, decay_steps: int
) -> optax.GradientTransformation:
  lr_fn = create_learning_rate_fn(params, decay_steps)
  return optax.lamb(
      learning_rate=lr_fn,
      b1=params.beta_1,
      b2=params.beta_2,
      eps=params.epsilon,
      weight_decay=params.weight_decay_rate,
      mask=_weight_decay_mask,
  )


def resolve_pallas_wavefront(params: ml_collections.ConfigDict) -> bool:
  """None = auto: the Pallas DP wins on a real TPU backend (measured
  1.24x the scan DP on v5e); everywhere else the scan DP is faster
  than the interpreted kernel."""
  flag = params.get('use_pallas_wavefront', None)
  if flag is None:
    return jax.default_backend() == 'tpu'
  return bool(flag)


def make_loss(params: ml_collections.ConfigDict) -> losses_lib.AlignmentLoss:
  width = params.get('band_width', None)
  return losses_lib.AlignmentLoss(
      del_cost=params.del_cost,
      loss_reg=params.loss_reg,
      width=width,
      use_pallas=resolve_pallas_wavefront(params),
  )


def ccs_row_from_batch(rows: jnp.ndarray, params) -> jnp.ndarray:
  """Extracts the CCS base row from the stacked input tensor."""
  ccs_range = row_indices(params.max_passes, params.use_ccs_bq)[4]
  return rows[:, ccs_range[0], :, 0]


@dataclasses.dataclass
class Trainer:
  """Owns jitted steps, checkpointing, and the metrics sidecars."""

  params: ml_collections.ConfigDict
  out_dir: str
  mesh: Optional[Any] = None
  # Elastic pod membership endpoint (parallel/elastic.py). When set,
  # the mesh is host-local, cross-host reduction runs through the pod's
  # bounded step_sync, and "the one writer" means the pod LEADER (lowest
  # live host id — survives leader loss) rather than jax process 0.
  pod: Optional[Any] = None
  # False when each pod member streams its OWN shard subset
  # (elastic_config['shard_streams']): batches are then host-local data,
  # not slices of a replicated global batch, so localize_batch must not
  # re-slice them.
  pod_slices_batches: bool = True

  def __post_init__(self):
    # Bucketed training compiles one pjit step per bucket width over a
    # single param tree, so the bucket SET must be valid at
    # construction (strictly ascending, smallest == max_length — the
    # normalizer's contract) and the model family must be
    # length-agnostic: the FC head sizes its output Dense by
    # max_length, so one param tree cannot serve two widths there.
    try:
      buckets = config_lib.resolve_window_buckets(self.params)
    except ValueError as e:
      raise faults_lib.WindowBucketError(str(e)) from e
    if (len(buckets) > 1
        and not str(self.params.model_name).startswith('transformer')):
      raise faults_lib.WindowBucketError(
          f'window_buckets={tuple(buckets)} needs a length-agnostic '
          f'model, but model_name={self.params.model_name!r} has '
          'window-width-dependent parameter shapes (the FC head is '
          'sized by max_length); use a transformer config for bucketed '
          'training'
      )
    self.window_buckets = buckets
    # Distinct train-step traces (== compiled batch geometries). One
    # per bucket width on a clean bucketed run; mesh degradation
    # legitimately re-traces.
    self.n_train_forward_shapes = 0
    os.makedirs(self.out_dir, exist_ok=True)
    enable_compilation_cache()
    self.model = model_lib.get_model(self.params)
    self.loss_fn = make_loss(self.params)
    self.alignment_metric = metrics_lib.AlignmentMetric()
    if self.mesh is None:
      self.mesh = mesh_lib.make_mesh()
    self._ckpt_dir = os.path.join(os.path.abspath(self.out_dir), 'checkpoints')
    self._checkpointer = ocp.StandardCheckpointer()
    self._metrics_tsv = os.path.join(self.out_dir, 'checkpoint_metrics.tsv')
    self._best_file = os.path.join(self.out_dir, 'best_checkpoint.txt')
    self._metrics_jsonl = os.path.join(self.out_dir, 'metrics.jsonl')
    # Central metrics registry (obs/): the metrics sidecar mirrors every
    # logged scalar into typed gauges and the training loop feeds the
    # step-time histogram, so `obs.metrics` sees train the same way it
    # sees serve/router/featurize tiers.
    self.obs = obs_lib.MetricsRegistry(tier='train')
    self.step_time_hist = self.obs.histogram(
        'train_step_s', help='wall time per training step')
    # Which eval metric selects best_checkpoint.txt. The reference pins
    # per_example_accuracy (whole-window exact match); on small or
    # held-out eval sets that metric can tie at 0.0 for every
    # checkpoint (observed on the bundled eval split), so it is
    # configurable — eval/identity_pred is the right selector
    # there.
    self._best_metric_name = self.params.get(
        'best_checkpoint_metric', constants.MAIN_EVAL_METRIC_NAME
    ) or constants.MAIN_EVAL_METRIC_NAME
    self._best_metric = -1.0
    self._tsv_columns = None
    # Recover best-metric and header state across restarts.
    if os.path.exists(self._metrics_tsv):
      with open(self._metrics_tsv) as f:
        header = f.readline().strip().split('\t')
        self._tsv_columns = header[1:]
        if self._best_metric_name in self._tsv_columns:
          idx = 1 + self._tsv_columns.index(self._best_metric_name)
          for line in f:
            parts = line.strip().split('\t')
            try:
              self._best_metric = max(self._best_metric, float(parts[idx]))
            except (IndexError, ValueError):
              continue

  # ---- state ---------------------------------------------------------
  def init_state(self, steps_total: int, seed: Optional[int] = None
                 ) -> TrainState:
    seed = self.params.seed if seed is None else seed
    rng = jax.random.PRNGKey(seed)
    rows = jnp.zeros(
        (1, self.params.total_rows, self.params.max_length, 1), jnp.float32
    )
    variables = self.model.init(rng, rows)
    tx = create_optimizer(self.params, steps_total)
    model_state = {k: v for k, v in variables.items() if k != 'params'}
    state = TrainState.create(
        apply_fn=self.model.apply,
        params=variables['params'],
        tx=tx,
        dropout_rng=jax.random.fold_in(rng, 1),
        model_state=model_state,
    )
    with open(os.path.join(self.out_dir, 'model_summary.txt'), 'w') as f:
      f.write(model_lib.summarize_params(variables['params']))
    # Place the WHOLE state by the declarative rule table: the LAMB
    # moments mirror the param tree, so one re.search pass shards them
    # exactly like their parameters (partition_rules.py), and scalars
    # (step counts, schedule state) replicate.
    return jax.device_put(state, self.state_shardings(state))

  def state_shardings(self, state):
    """Rule-table NamedShardings for a full TrainState (params,
    optimizer moments, model_state, rng, scalars) on this mesh — the
    single source train/eval/distill pjit steps compile against."""
    return partition_rules.tree_shardings(self.mesh, state)

  def _is_writer(self) -> bool:
    """Whether THIS host owns the shared-filesystem mutations
    (checkpoint manifests, TSV/best sidecars, metrics.jsonl,
    quarantine). Elastic pods elect the leader; legacy multi-host keeps
    the fixed process-0 convention."""
    if self.pod is not None:
      return self.pod.is_leader
    return jax.process_index() == 0

  def _manifest_extra(self) -> Optional[Dict[str, Any]]:
    """Elastic provenance for the checkpoint manifest: which member-set
    epoch wrote it (so a post-mortem can tell a degraded-pod checkpoint
    from a full-strength one)."""
    if self.pod is None:
      return None
    return {'pod_epoch': int(self.pod.epoch),
            'pod_members': [int(m) for m in self.pod.members]}

  # ---- steps ---------------------------------------------------------
  def train_step_fn(self, state: Optional[TrainState] = None):
    loss_obj = self.loss_fn

    def step(state: TrainState, batch: Dict[str, jnp.ndarray]):
      # Python body == one pjit trace. jit caches one executable per
      # batch geometry, so over a bucketed stream this counts exactly
      # n_buckets traces (surfaced as n_train_forward_shapes; the
      # compile-once tests pin it — a value above the bucket count
      # means mid-run recompiles).
      self.n_train_forward_shapes += 1
      rng = jax.random.fold_in(state.dropout_rng, state.step)
      mutable = list(state.model_state.keys())

      def loss_of(p):
        if mutable:
          preds, new_model_state = state.apply_fn(
              {'params': p, **state.model_state},
              batch['rows'], train=True, rngs={'dropout': rng},
              mutable=mutable,
          )
        else:
          preds = state.apply_fn(
              {'params': p}, batch['rows'], train=True,
              rngs={'dropout': rng},
          )
          new_model_state = {}
        return loss_obj(batch['label'], preds), (preds, new_model_state)

      (loss, (preds, new_model_state)), grads = jax.value_and_grad(
          loss_of, has_aux=True
      )(state.params)
      new_state = state.apply_gradients(
          grads=grads, model_state=new_model_state
      ) if mutable else state.apply_gradients(grads=grads)
      correct, total = metrics_lib.per_example_accuracy_counts(
          batch['label'], preds
      )
      metrics = {
          'loss': loss,
          # Exposed for the NaN/Inf sentinel: a non-finite gradient can
          # poison the params even when this step's loss still computes
          # finite, so divergence is judged on both.
          'grad_norm': optax.global_norm(grads),
          'accuracy_correct': correct,
          'accuracy_total': total,
      }
      return new_state, metrics

    batch_sh = self._batch_sharding()
    # With a concrete state the step is an explicit-sharding pjit: the
    # donated input state and the returned state both carry the rule-
    # table shardings, so XLA keeps every optimizer update in place
    # (no gather/scatter around the step). Without one (legacy/bench
    # callers) the state sharding is inferred from the arguments.
    state_sh = None if state is None else self.state_shardings(state)
    return partition_rules.compile_parallel(
        step,
        in_shardings=(state_sh, {'rows': batch_sh, 'label': batch_sh}),
        out_shardings=(state_sh, None),
        donate_argnums=(0,),
    )

  def grad_step_fn(self, state: Optional[TrainState] = None):
    """First half of the elastic-pod data plane: forward+backward on
    this host's batch slice only, returning (grads, new_model_state,
    metrics) WITHOUT applying, so the pod's bounded weighted-mean
    allreduce (ElasticPod.step_sync) runs between compute and update.
    No donation and no pinned batch sharding: the same state re-enters
    apply_step_fn (and re-enters here when a lost-host rebuild replays
    the step), and the batch's leading dim changes with membership, so
    shapes/shardings are inferred per call."""
    del state  # shardings inferred from the concrete (placed) arguments
    loss_obj = self.loss_fn

    def step(state: TrainState, batch: Dict[str, jnp.ndarray]):
      rng = jax.random.fold_in(state.dropout_rng, state.step)
      mutable = list(state.model_state.keys())

      def loss_of(p):
        if mutable:
          preds, new_model_state = state.apply_fn(
              {'params': p, **state.model_state},
              batch['rows'], train=True, rngs={'dropout': rng},
              mutable=mutable,
          )
        else:
          preds = state.apply_fn(
              {'params': p}, batch['rows'], train=True,
              rngs={'dropout': rng},
          )
          new_model_state = {}
        return loss_obj(batch['label'], preds), (preds, new_model_state)

      (loss, (preds, new_model_state)), grads = jax.value_and_grad(
          loss_of, has_aux=True
      )(state.params)
      correct, total = metrics_lib.per_example_accuracy_counts(
          batch['label'], preds
      )
      metrics = {
          'loss': loss,
          'accuracy_correct': correct,
          'accuracy_total': total,
      }
      return grads, new_model_state, metrics

    return partition_rules.compile_parallel(step)

  def apply_step_fn(self, state: Optional[TrainState] = None):
    """Second half: applies the pod-averaged gradients (and merged
    model_state) to the local state replica. Every member applies the
    SAME averaged arrays to the SAME state, so replicas stay in sync
    without any cross-host state transfer. grad_norm is computed on the
    averaged gradients — the same quantity the fused single-mesh step
    reports for the whole global batch."""
    del state
    def step(state: TrainState, grads, new_model_state):
      if new_model_state:
        new_state = state.apply_gradients(
            grads=grads, model_state=new_model_state
        )
      else:
        new_state = state.apply_gradients(grads=grads)
      return new_state, optax.global_norm(grads)

    return partition_rules.compile_parallel(step, donate_argnums=(0,))

  def _batch_sharding(self, n: Optional[int] = None):
    """Shard the batch over the data axis when divisible, else
    replicate (tiny test batches, uneven elastic member slices). `n`
    overrides the configured global batch size — elastic pod members
    feed membership-dependent slices whose length params.batch_size no
    longer describes."""
    dp = self.mesh.shape[mesh_lib.DATA_AXIS]
    n = int(self.params.batch_size) if n is None else int(n)
    if n % dp == 0:
      return mesh_lib.batch_sharding(self.mesh)
    return mesh_lib.replicated(self.mesh)

  def globalize_batch(self, batch):
    """Multi-host batch assembly: every host loads the SAME global
    batch (same files, same seed), takes its `local_batch_slice`, and
    the slices are stitched into one globally-sharded array
    (reference reaches pods via TPUStrategy's per-replica dataset:
    model_train_custom_loop.py:333-343). No-op single-process."""
    if jax.process_count() == 1:
      return batch
    from deepconsensus_tpu.parallel import distributed

    spec = self._batch_sharding().spec
    if not len(spec):  # replicated: all hosts feed identical arrays
      return {
          k: distributed.host_local_to_global(self.mesh, spec, v)
          for k, v in batch.items()
      }
    n = next(iter(batch.values())).shape[0]
    sl = distributed.local_batch_slice(n)
    return {
        k: distributed.host_local_to_global(self.mesh, spec, v[sl])
        for k, v in batch.items()
    }

  def localize_batch(self, batch):
    """The training-input view of one loaded batch on THIS host.

    Elastic pod: every member loads the SAME global batch (same files,
    same seed) and trains on its member_batch_slice — the union covers
    every row exactly once at ANY member count, so a pod of one
    degrades to the full batch and survivor training matches the
    undisturbed run. With shard_streams the batch is already host-local
    data and passes through. Legacy multi-host delegates to
    globalize_batch; single everything is a no-op.
    """
    if self.pod is None:
      return self.globalize_batch(batch)
    if not self.pod_slices_batches:
      return batch
    members = self.pod.members
    if len(members) <= 1:
      return batch
    from deepconsensus_tpu.parallel import distributed

    n = next(iter(batch.values())).shape[0]
    sl = distributed.member_batch_slice(
        n, len(members), sorted(members).index(self.pod.host_id))
    return {k: v[sl] for k, v in batch.items()}

  def eval_step_fn(self, state: Optional[TrainState] = None):
    loss_obj = self.loss_fn
    params_cfg = self.params
    metric = self.alignment_metric

    def step(state: TrainState, batch: Dict[str, jnp.ndarray]):
      preds = state.apply_fn(
          {'params': state.params, **state.model_state}, batch['rows']
      )
      loss = loss_obj(batch['label'], preds)
      correct, total = metrics_lib.per_example_accuracy_counts(
          batch['label'], preds
      )
      ccs = ccs_row_from_batch(batch['rows'], params_cfg)
      id_ccs, id_pred = metrics_lib.batch_identity_ccs_pred(
          ccs, preds, batch['label'], metric
      )
      out = {
          'loss': loss,
          'accuracy_correct': correct,
          'accuracy_total': total,
          'identity_ccs': id_ccs,
          'identity_pred': id_pred,
      }
      for cls in range(constants.SEQ_VOCAB_SIZE):
        c, t = metrics_lib.per_class_accuracy_counts(
            batch['label'], preds, cls
        )
        out[f'class{cls}_correct'] = c
        out[f'class{cls}_total'] = t
      return out

    batch_sh = self._batch_sharding()
    state_sh = None if state is None else self.state_shardings(state)
    return partition_rules.compile_parallel(
        step,
        in_shardings=(state_sh, {'rows': batch_sh, 'label': batch_sh}),
    )

  def run_eval(self, state, eval_ds) -> Dict[str, float]:
    """One full eval epoch aggregated to the eval/* metric dict.

    The single aggregation used by BOTH run_training and distill, so
    their TSVs carry the same metric key set and
    params.best_checkpoint_metric means the same thing everywhere."""
    if getattr(self, '_cached_eval_step', None) is None:
      self._cached_eval_step = self.eval_step_fn(state)
    eval_step = self._cached_eval_step
    sums: Dict[str, float] = {}
    batches = 0
    yield_metric = metrics_lib.YieldOverCCS()
    for batch in eval_ds.epoch():
      # Window ids (params.track_window_ids) are training-loop
      # forensics; the jitted eval step shards (rows, label) only.
      batch = {k: v for k, v in batch.items() if k != 'name'}
      batch = self.globalize_batch(batch)
      out = {k: float(v) for k, v in eval_step(state, batch).items()}
      yield_metric.update(out['identity_ccs'], out['identity_pred'])
      for k, v in out.items():
        sums[k] = sums.get(k, 0.0) + v
      batches += 1
    if not batches:
      return {}
    acc = sums['accuracy_correct'] / max(sums['accuracy_total'], 1)
    result = {
        'eval/loss': sums['loss'] / batches,
        constants.MAIN_EVAL_METRIC_NAME: acc,
        'eval/identity_ccs': sums['identity_ccs'] / batches,
        'eval/identity_pred': sums['identity_pred'] / batches,
        'eval/yield_over_ccs': yield_metric.result(),
    }
    # Emit every class key unconditionally so the metric key set (and
    # the TSV header) stays stable across evals.
    for cls in range(constants.SEQ_VOCAB_SIZE):
      total = sums.get(f'class{cls}_total', 0.0)
      result[f'eval/class{cls}_accuracy'] = (
          sums[f'class{cls}_correct'] / total if total else 0.0
      )
    return result

  # ---- checkpoints ---------------------------------------------------
  def save_checkpoint(self, state: TrainState, step: int,
                      eval_metrics: Dict[str, float]) -> str:
    path = os.path.join(self._ckpt_dir, f'checkpoint-{step}')
    saved = {
        'params': jax.device_get(state.params),
        'opt_state': jax.device_get(state.opt_state),
        'model_state': jax.device_get(state.model_state),
        'step': step,
    }
    def do_save():
      self._checkpointer.save(path, saved, force=True)
      # Block until the async write finalizes so a crash right after
      # this point never leaves a half-written latest checkpoint.
      wait = getattr(self._checkpointer, 'wait_until_finished', None)
      if wait is not None:
        wait()

    if self.pod is not None:
      # Elastic pod: each member is its own single-process jax runtime
      # sharing out_dir, so orbax's multihost protocol does not apply —
      # the leader writes alone and a bounded pod barrier aligns the
      # rest (deadline scaled well above the step barrier: checkpoint
      # IO legitimately takes longer than a gradient sync).
      if self._is_writer():
        do_save()
      if len(self.pod.members) > 1:
        self.pod.barrier(
            f'ckpt-{step}',
            timeout_s=max(60.0, 4.0 * self.pod.barrier_timeout))
    elif jax.process_count() > 1:
      # Legacy multi-host: EVERY process calls save — orbax's multihost
      # protocol barriers across processes and writes from the primary
      # only. Bounded (the PR-18 rule: no collective waits forever): a
      # peer dying inside the save barrier surfaces as HostLostError
      # for the retry wrapper instead of hanging every survivor.
      from deepconsensus_tpu.parallel import elastic as elastic_lib

      elastic_lib.bounded_call(
          do_save, self._save_timeout(), f'orbax-save-{step}')
    else:
      do_save()
    if not self._is_writer():
      # Metric sidecars (TSV, best-checkpoint) and manifests have one
      # writer.
      return path
    # Commit the integrity manifest only now that the checkpoint is
    # fully on disk: its presence marks the directory as complete, and
    # its file inventory lets latest_valid_checkpoint detect truncation
    # without loading arrays.
    checkpoints_lib.write_manifest(
        path, step, digest=checkpoints_lib.tree_digest(saved),
        extra=self._manifest_extra(),
    )
    if not eval_metrics:
      # Emergency (preemption) saves carry no eval pass; skip the
      # metric sidecars rather than writing an empty TSV header.
      return path
    header_needed = not os.path.exists(self._metrics_tsv)
    if header_needed:
      self._tsv_columns = sorted(eval_metrics)
      with open(self._metrics_tsv, 'a') as f:
        f.write('checkpoint\t' + '\t'.join(self._tsv_columns) + '\n')
    with open(self._metrics_tsv, 'a') as f:
      # Align values to the header captured at first write; metric key
      # sets are stable by construction (all keys always emitted).
      f.write(
          f'checkpoint-{step}\t'
          + '\t'.join(
              str(eval_metrics.get(k, 'nan')) for k in self._tsv_columns
          )
          + '\n'
      )
    if self._best_metric_name not in eval_metrics:
      # A typo'd metric name would otherwise silently never update
      # best_checkpoint.txt (get() returning -1.0 forever).
      logging.getLogger(__name__).warning(
          'best_checkpoint_metric %r not among eval metrics %s; '
          'best_checkpoint.txt will not update',
          self._best_metric_name, sorted(eval_metrics))
    main = eval_metrics.get(self._best_metric_name, -1.0)
    if main > self._best_metric:
      self._best_metric = main
      with open(self._best_file, 'w') as f:
        f.write(f'checkpoint-{step}\n')
    return path

  def _save_timeout(self) -> float:
    """Deadline for the legacy multi-host orbax save barrier: generous
    (checkpoint IO is slow) but finite."""
    base = float(
        self.params.get('elastic_barrier_timeout', 30.0) or 30.0)
    return max(300.0, 10.0 * base)

  def restore_checkpoint(self, state: TrainState, path: str,
                         params_only: bool = False) -> TrainState:
    """Restores training state; full resume includes optimizer state
    and LR-schedule position (the reference restores the whole
    tf.train.Checkpoint: model_utils.py:511-540)."""
    if params_only:
      # Warm-start source checkpoints are usually full TrainStates
      # (params + opt_state + step); a params-only typed target makes
      # orbax raise a structure mismatch, so select the subtree from
      # an untyped restore (same approach as checkpoints.load_params,
      # which inference/export use). The template keeps restore-time
      # structure/shape validation and casts to the model's dtype.
      from deepconsensus_tpu.models.checkpoints import load_params

      return state.replace(params=load_params(
          path, params_template=jax.device_get(state.params)))
    restored = self._checkpointer.restore(
        path,
        target={
            'params': jax.device_get(state.params),
            'opt_state': jax.device_get(state.opt_state),
            'model_state': jax.device_get(state.model_state),
            'step': 0,
        },
    )
    return state.replace(
        params=restored['params'],
        opt_state=restored['opt_state'],
        model_state=restored['model_state'],
        step=jnp.asarray(restored['step']),
    )

  def latest_valid_checkpoint(self) -> Optional[str]:
    """Newest checkpoint that passes integrity validation; corrupt or
    uncommitted (manifest-less) directories are quarantined to
    checkpoints/.quarantine/ and the scan falls back to the next
    valid one. Replaces the old latest_checkpoint(), which compared
    step numbers only and would happily resume onto a half-written
    directory."""
    return checkpoints_lib.latest_valid_checkpoint(
        self._ckpt_dir, quarantine=self._is_writer()
    )

  # Backward-compatible name; validation semantics included.
  latest_checkpoint = latest_valid_checkpoint

  def log_metrics(self, step: int, split: str, metrics: Dict[str, float]):
    if not self._is_writer():
      return
    for name, value in metrics.items():
      try:
        self.obs.set_gauge(f'{split}/{name}', float(value))
      except (TypeError, ValueError):
        continue
    entry = {'step': step, 'split': split, 'time': time.time(), **metrics}
    with open(self._metrics_jsonl, 'a') as f:
      f.write(json.dumps(entry) + '\n')
    self._write_tensorboard(step, split, metrics)

  def _write_tensorboard(self, step: int, split: str,
                         metrics: Dict[str, float]):
    """Optional TensorBoard scalars (reference writes TB summaries:
    model_train_custom_loop.py:164-166). No-op without tensorflow."""
    if not hasattr(self, '_tb_writers'):
      self._tb_writers = {}
    if split not in self._tb_writers:
      try:
        import tensorflow as tf  # noqa: F401

        self._tb_writers[split] = tf.summary.create_file_writer(
            os.path.join(self.out_dir, 'tensorboard', split)
        )
      except ImportError:
        self._tb_writers[split] = None
    writer = self._tb_writers[split]
    if writer is None:
      return
    import tensorflow as tf

    with writer.as_default():
      for name, value in metrics.items():
        try:
          tf.summary.scalar(name, float(value), step=step)
        except (TypeError, ValueError):
          continue
      writer.flush()


class _PrefetchedBatch:
  """One in-flight training batch: host arrays (kept for the NaN
  sentinel and for re-placement after a mesh degrade), the async
  device transfer, and the mesh generation the transfer targeted."""

  __slots__ = ('names', 'host', 'device', 'generation', 'error')

  def __init__(self):
    self.names = None
    self.host = None
    self.device = None
    self.generation = 0
    self.error: Optional[BaseException] = None


class TrainBatchPrefetcher:
  """Double-buffered training-batch transfer: the PR-8 dispatch
  pattern applied to input.

  A producer thread pulls host batches (already host-prefetched by
  data.prefetch_iterator), applies the batch fault-injection hooks,
  and issues batch N+1's ASYNC sharded jax.device_put while the device
  runs step N — jax.device_put returns before the copy completes, so
  the H2D transfer rides under compute instead of serializing in the
  jitted call's argument placement. The queue holds one ready handle
  and the consumer holds another: depth-2 double buffering, same as
  the inference dispatch pipeline.

  Counters (surfaced in the metrics sidecar's `faults` split):
  `n_batches_prefetched` counts launches issued while an earlier
  batch's step was in flight (every launch after the first — the
  depth-1 queue guarantees launch k happens only after the consumer
  took batch k-1, i.e. during step k-1's async window);
  `train_transfer_overlap_fraction` is that count over all launches,
  so a clean run reports (steps-1)/steps.

  Mesh degrades retarget the prefetcher: `retarget()` bumps the mesh
  generation, and a handle whose transfer targeted a retired mesh is
  re-placed from its host copy at consumption time.
  """

  def __init__(self, batches, trainer: Trainer, poison_base_step: int = 0):
    self._trainer = trainer
    self._batches = batches
    self._poison_base = poison_base_step
    self._lock = threading.Lock()
    self._generation = 0  # guarded by: self._lock
    self._n_launched = 0  # guarded by: self._lock
    self._n_overlapped = 0  # guarded by: self._lock
    self._n_replaced = 0  # guarded by: self._lock
    self._stop = threading.Event()
    self._queue: queue_lib.Queue = queue_lib.Queue(maxsize=1)
    self._thread = threading.Thread(
        target=self._produce, daemon=True, name='train-batch-prefetch'
    )
    self._thread.start()

  # ---- producer thread ----------------------------------------------
  def _produce(self):
    ordinal = self._poison_base
    try:
      for batch in self._batches:
        if self._stop.is_set():
          break
        item = _PrefetchedBatch()
        item.names = batch.pop('name', None)
        ordinal += 1
        # Injection ordinal = the step this batch is consumed at on the
        # no-rollback path (rollbacks replay step numbers but never
        # batches; _fire_once keeps hooks consume-once either way).
        faults_lib.maybe_poison_batch(ordinal, batch)
        item.host = dict(batch)
        item.generation, item.device = self._launch(item.host)
        if not self._put(item):
          break
    # dclint-style routing: the error crosses threads via the handle
    # and re-raises at the consumer, like data.prefetch_iterator.
    except BaseException as e:  # pylint: disable=broad-except
      item = _PrefetchedBatch()
      item.error = e
      self._put(item)
    else:
      self._put(None)
    finally:
      close = getattr(self._batches, 'close', None)
      if close is not None:
        try:
          close()
        except Exception:  # pragma: no cover - best-effort shutdown
          pass

  def _launch(self, host: Dict[str, np.ndarray]):
    """Issues the async sharded H2D transfer for one host batch and
    returns (mesh generation, device arrays)."""
    gbatch = self._trainer.localize_batch(dict(host))
    sh = self._trainer._batch_sharding(
        n=next(iter(gbatch.values())).shape[0])
    with self._lock:
      gen = self._generation
      self._n_launched += 1
      if self._n_launched > 1:
        self._n_overlapped += 1
    return gen, jax.device_put(gbatch, {k: sh for k in gbatch})

  def _put(self, item) -> bool:
    while not self._stop.is_set():
      try:
        self._queue.put(item, timeout=0.1)
        return True
      except queue_lib.Full:
        continue
    return False

  # ---- consumer (training loop) -------------------------------------
  def __iter__(self):
    return self

  def __next__(self):
    item = self._queue.get()
    if item is None:
      raise StopIteration
    if item.error is not None:
      raise item.error
    with self._lock:
      gen = self._generation
    if item.generation != gen:
      # The transfer targeted a mesh that has since been degraded;
      # re-place from the host copy onto the current mesh.
      item.device = self.place(item.host)
      item.generation = gen
    return item.names, item.host, item.device

  def place(self, host: Dict[str, np.ndarray]):
    """Direct (non-overlapped) placement of a host batch on the
    CURRENT mesh (and, for elastic pods, the CURRENT membership —
    re-placing after a rebuild re-slices the same host batch for the
    surviving member set) — used to re-dispatch the failed batch after
    a degrade/rebuild and to refresh stale prefetched transfers."""
    gbatch = self._trainer.localize_batch(dict(host))
    sh = self._trainer._batch_sharding(
        n=next(iter(gbatch.values())).shape[0])
    with self._lock:
      self._n_replaced += 1
    return jax.device_put(gbatch, {k: sh for k in gbatch})

  def retarget(self) -> None:
    """Invalidates in-flight transfers after a mesh rebuild: bumps the
    generation so stale handles re-place at consumption."""
    with self._lock:
      self._generation += 1

  def stats(self) -> Dict[str, float]:
    with self._lock:
      launched = self._n_launched
      overlapped = self._n_overlapped
      replaced = self._n_replaced
    return {
        'n_batch_launches': float(launched),
        'n_batches_prefetched': float(overlapped),
        'n_batches_replaced': float(replaced),
        'train_transfer_overlap_fraction': (
            round(overlapped / launched, 4) if launched else 0.0
        ),
    }

  def close(self) -> None:
    self._stop.set()
    # Drain so a producer blocked in _put can observe the stop flag.
    try:
      while True:
        self._queue.get_nowait()
    except queue_lib.Empty:
      pass
    self._thread.join(timeout=5.0)


class PreemptionGuard:
  """SIGTERM/SIGINT -> emergency checkpoint at the next step boundary.

  TPU-VM preemption delivers SIGTERM with a short grace period; a
  Ctrl-C during a long local run deserves the same treatment. The
  handler only sets a flag — the training loop polls requested() once
  per step and performs the (collective) checkpoint save itself, so the
  save never runs inside a signal handler or mid-step. A second signal
  aborts immediately (raises KeyboardInterrupt) for operators who
  really mean it.

  Multi-host: the decision to stop must be unanimous — the orbax save
  is collective, so one host checkpointing alone would deadlock the
  rest. requested() allgathers the local flags and trips when ANY host
  saw a signal. The vote is BOUNDED (PR 18): a peer that died before
  voting surfaces as HostLostError after barrier_timeout instead of
  wedging every survivor inside process_allgather forever. Elastic
  pods skip the collective entirely — they piggyback `local()` on the
  per-step sync, which is already bounded.
  """

  def __init__(self, barrier_timeout: float = 30.0):
    self._event = threading.Event()
    self._prev: Dict[int, Any] = {}
    self.signum: Optional[int] = None
    self.barrier_timeout = float(barrier_timeout)

  def install(self) -> 'PreemptionGuard':
    import signal

    for sig in (signal.SIGTERM, signal.SIGINT):
      try:
        self._prev[sig] = signal.signal(sig, self._handle)
      except ValueError:
        # Not the main thread (e.g. training driven from a worker
        # thread in tests): preemption safety degrades to the default
        # handlers rather than breaking training.
        pass
    return self

  def _handle(self, signum, frame):
    del frame
    if self._event.is_set():
      raise KeyboardInterrupt(
          f'second signal {signum} during checkpoint-and-exit'
      )
    self.signum = signum
    self._event.set()
    logging.getLogger(__name__).warning(
        'signal %s received; will checkpoint and exit at the next step '
        'boundary (send again to abort immediately)', signum,
    )

  def local(self) -> bool:
    """This host's own stop flag, no collective — what the elastic pod
    piggybacks as its stop vote on step_sync."""
    return self._event.is_set()

  def requested(self) -> bool:
    local = self._event.is_set()
    if jax.process_count() == 1:
      return local
    from jax.experimental import multihost_utils

    from deepconsensus_tpu.parallel import elastic as elastic_lib

    def vote():
      return multihost_utils.process_allgather(
          np.asarray([local], dtype=np.int32)
      )

    flags = elastic_lib.bounded_call(
        vote, self.barrier_timeout, 'preemption-stop-vote')
    return bool(np.any(flags))

  def restore(self) -> None:
    import signal

    for sig, prev in self._prev.items():
      try:
        signal.signal(sig, prev)
      except ValueError:
        pass
    self._prev = {}


class NanSentinel:
  """Watches per-step loss/grad-norm finiteness; after `limit`
  consecutive non-finite steps, rolls training back to the last valid
  checkpoint (the train step donates and overwrites its input state, so
  a NaN update poisons the live params irreversibly — rollback is the
  only recovery). Every non-finite step is dead-lettered with the
  offending batch's window ids (params.track_window_ids) or a content
  fingerprint, in the PR 1 sidecar format, to <out_dir>/training.failed.jsonl.

  Verdicts are read one step late: float(metrics) blocks on the device,
  so checking step k while step k+1 is dispatching preserves the
  async-dispatch pipeline. The one extra contaminated step costs
  nothing — rollback discards it either way. The exception is a save
  boundary (eval checkpoint, emergency preemption save, final save):
  there the loop force-resolves the pending verdict and refuses to
  checkpoint while `consecutive > 0`, so a poisoned state can never
  become the "last valid checkpoint" the rollback restores.
  """

  def __init__(self, params: ml_collections.ConfigDict, out_dir: str,
               writer: Optional[bool] = None):
    self.limit = int(params.get('nan_sentinel_steps', 3) or 0)
    self.max_rollbacks = int(params.get('nan_max_rollbacks', 2) or 0)
    self.enabled = self.limit > 0
    self.consecutive = 0
    self.rollbacks = 0
    self.counters: collections.Counter = collections.Counter()
    self._dead_letter = None
    if writer is None:
      # Legacy convention; elastic runs pass the leader verdict so the
      # shared dead-letter file keeps one writer across pod epochs.
      writer = jax.process_index() == 0
    if self.enabled and writer:
      self._dead_letter = faults_lib.DeadLetterWriter(
          os.path.join(out_dir, 'training.failed.jsonl'), append=True
      )

  def observe(self, step: int, metrics: Dict[str, Any],
              names, batch: Optional[Dict[str, np.ndarray]]) -> bool:
    """Returns True (and records a dead letter) when this step's loss
    or grad norm is non-finite."""
    loss = float(metrics['loss'])
    grad_norm = float(metrics.get('grad_norm', 0.0))
    if np.isfinite(loss) and np.isfinite(grad_norm):
      self.consecutive = 0
      return False
    self.consecutive += 1
    self.counters['n_nonfinite_steps'] += 1
    extra: Dict[str, Any] = {
        'step': step, 'loss': loss, 'grad_norm': grad_norm,
    }
    if names is not None:
      extra['window_ids'] = [
          n.decode('utf-8', 'replace') if isinstance(n, bytes) else str(n)
          for n in names
      ]
    elif batch is not None and 'rows' in batch:
      extra['batch_sha1'] = hashlib.sha1(
          np.ascontiguousarray(batch['rows']).tobytes()
      ).hexdigest()[:16]
    will_roll = self.consecutive >= self.limit
    if self._dead_letter is not None:
      self._dead_letter.record(
          None, 'train', faults_lib.FaultKind.TRANSIENT,
          f'non-finite training step: loss={loss} grad_norm={grad_norm}',
          'rollback' if will_roll else 'recorded', extra=extra,
      )
    logging.getLogger(__name__).warning(
        'non-finite training step %d (loss=%s grad_norm=%s; %d/%d '
        'consecutive)', step, loss, grad_norm, self.consecutive,
        self.limit,
    )
    return True

  def should_rollback(self) -> bool:
    return self.enabled and self.consecutive >= self.limit

  def rolled_back(self, checkpoint: str) -> None:
    self.rollbacks += 1
    self.consecutive = 0
    self.counters['n_nan_rollbacks'] += 1
    logging.getLogger(__name__).warning(
        'NaN sentinel: rolled back to %s (rollback %d/%d)',
        checkpoint, self.rollbacks, self.max_rollbacks,
    )

  def close(self) -> None:
    if self._dead_letter is not None:
      self._dead_letter.close()


def run_training(
    params: ml_collections.ConfigDict,
    out_dir: str,
    train_patterns=None,
    eval_patterns=None,
    num_epochs: Optional[int] = None,
    mesh=None,
    eval_every: Optional[int] = None,
    warm_start: Optional[str] = None,
    distributed_config: Optional[Dict[str, Any]] = None,
    elastic_config: Optional[Dict[str, Any]] = None,
    preemption_guard: Optional['PreemptionGuard'] = None,
) -> Dict[str, float]:
  """End-to-end training driver. Returns final eval metrics.

  Multi-host: pass distributed_config (coordinator_address,
  num_processes, process_id — or {} for pod auto-detection) to
  initialize jax.distributed before the mesh is built; every host then
  feeds its local slice of the global batch (globalize_batch) and only
  process 0 writes checkpoints/metrics. out_dir must be shared (or at
  least readable) across hosts for crash-resume.

  Elastic multi-host: pass elastic_config (host_id, n_hosts, plus
  optional barrier_timeout / on_host_error / readmit /
  heartbeat_interval / shard_streams / defer_join_until_step) instead.
  Each host runs its own single-process jax over a LOCAL mesh; the
  membership layer (parallel/elastic.py) forms the pod in
  <out_dir>/.pod/, gradients cross hosts through the bounded per-step
  weighted-mean sync, and a lost host triggers the coordinated rebuild
  (agreement round, epoch bump, batch re-slice, step replay) instead
  of a hang. docs/training.md "Elastic multi-host training".
  """
  if distributed_config is not None:
    from deepconsensus_tpu.parallel import distributed

    distributed.initialize(**distributed_config)
  pod = None
  pod_start = None
  shard_streams = False
  on_host_error = 'degrade'
  if elastic_config:
    from deepconsensus_tpu.parallel import elastic as elastic_lib

    shard_streams = bool(elastic_config.get('shard_streams', False))
    on_host_error = str(
        elastic_config.get('on_host_error')
        or params.get('on_host_error', 'degrade') or 'degrade')
    defer = int(elastic_config.get('defer_join_until_step', 0) or 0)
    if not defer:
      # Subprocess fault drills arm the rejoin hook via the restarted
      # process's environment (scripts/inject_faults.py host).
      defer = faults_lib.host_rejoin_step()
    pod = elastic_lib.ElasticPod(
        os.path.join(os.path.abspath(out_dir), '.pod'),
        host_id=int(elastic_config['host_id']),
        n_hosts=int(elastic_config['n_hosts']),
        barrier_timeout=float(
            elastic_config.get('barrier_timeout')
            or params.get('elastic_barrier_timeout', 30.0) or 30.0),
        heartbeat_interval=float(
            elastic_config.get('heartbeat_interval', 0.25) or 0.25),
        readmit=bool(elastic_config.get('readmit', True)),
        defer_join_until_step=defer,
    )
  train_patterns = train_patterns or list(params.train_path)
  eval_patterns = eval_patterns or list(params.eval_path)
  num_epochs = num_epochs or params.num_epochs

  streaming = bool(params.get('streaming', False))
  train_ds = None
  if streaming:
    # Shard-interleaved streaming with a shuffle buffer; "epochs"
    # become fixed step counts (n_examples_train / batch). The dataset
    # itself is constructed after checkpoint restore so the stream can
    # be reseeded by resume position.
    n_train = int(params.get('n_examples_train', 0) or 0)
    if n_train < params.batch_size:
      raise ValueError(
          'streaming training requires params.n_examples_train (>= one '
          'batch) to size the step budget'
      )
    steps_per_epoch = n_train // params.batch_size
  else:
    train_ds = data_lib.DatasetIterator(
        patterns=train_patterns,
        params=params,
        batch_size=params.batch_size,
        seed=params.seed,
    )
    steps_per_epoch = train_ds.steps_per_epoch
  eval_ds = data_lib.DatasetIterator(
      patterns=eval_patterns,
      params=params,
      batch_size=params.batch_size,
      shuffle=False,
  )
  decay_steps = steps_per_epoch * params.get('num_epochs_for_decay',
                                             num_epochs)
  if pod is not None and mesh is None:
    # The jit-visible mesh of an elastic member never spans processes;
    # cross-host reduction happens at host level through step_sync.
    mesh = mesh_lib.local_mesh(tp=int(params.get('tp', 1) or 1))
  trainer = Trainer(params=params, out_dir=out_dir, mesh=mesh, pod=pod,
                    pod_slices_batches=not shard_streams)
  if pod is not None:
    # Form (or join) the pod BEFORE any shared-filesystem writes so
    # writer gating (_is_writer == pod leader) is meaningful.
    pod_start = pod.start()
  if trainer._is_writer():
    config_lib.save_params_as_json(out_dir, params)
  state = trainer.init_state(steps_total=decay_steps)
  resume_from = trainer.latest_valid_checkpoint()
  if warm_start and resume_from is not None:
    logging.getLogger(__name__).warning(
        'warm_start=%s ignored: %s already has checkpoints; resuming '
        'from the latest instead', warm_start, out_dir,
    )
  if warm_start and resume_from is None:
    # Warm start adopts weights only; optimizer starts fresh
    # (reference --checkpoint warm start: model_train_custom_loop.py:119-124).
    # Applies only to the very first start: once this run has its own
    # checkpoints, crash-resume below must win or a preempted
    # warm-started run would restart from step 0.
    state = trainer.restore_checkpoint(state, warm_start, params_only=True)
  eval_every = eval_every or params.get('eval_every_n_steps', 3000)

  def run_eval(state) -> Dict[str, float]:
    return trainer.run_eval(state, eval_ds)

  # Crash-resume: pick up from the newest VALID checkpoint in out_dir
  # (reference resumable training: model_utils.py:511-540) — a
  # half-written or truncated latest checkpoint is quarantined by
  # latest_valid_checkpoint and the previous one wins.
  # The out_dir's own latest checkpoint always wins over warm_start:
  # warm_start seeds only the very first start, so a preempted
  # warm-started run resumes its own progress instead of resetting.
  step = 0
  if pod_start is not None and pod_start.joined:
    # Re-admission: adopt the leader's LIVE snapshot (state re-placed
    # outward at the admission boundary), which supersedes any local
    # checkpoint — the pod has advanced past what disk remembers.
    if pod_start.state is None:
      raise faults_lib.ElasticRebuildError(
          f'host {pod.host_id} was admitted at epoch {pod_start.epoch} '
          'but no state snapshot exists for that epoch in the pod dir')
    host_state = jax.device_get(state)
    leaves, treedef = jax.tree_util.tree_flatten(host_state)
    if len(pod_start.state) != len(leaves):
      raise faults_lib.ElasticRebuildError(
          f'pod snapshot carries {len(pod_start.state)} leaves but the '
          f'local state template has {len(leaves)}; the rejoining host '
          'is running a different model/optimizer config than the pod')
    state = jax.tree_util.tree_unflatten(
        treedef, [np.asarray(snap_leaf, dtype=np.asarray(tmpl).dtype)
                  for snap_leaf, tmpl in zip(pod_start.state, leaves)])
    step = int(pod_start.step)
    state = jax.device_put(state, trainer.state_shardings(state))
  elif resume_from:
    state = trainer.restore_checkpoint(state, resume_from)
    step = int(state.step)
    # Restore materializes host arrays; re-place under the rule table
    # so the donated pjit step below sees committed sharded inputs.
    state = jax.device_put(state, trainer.state_shardings(state))
  # Compiled against the concrete (placed) state: explicit rule-table
  # in/out shardings plus donation keep the optimizer update in place.
  # Elastic pods split the step instead (grad compute / bounded
  # host-level allreduce / apply), so the compiled graph never contains
  # a cross-host collective a dead peer could wedge.
  train_step = grad_step = apply_step = None
  if pod is None:
    train_step = trainer.train_step_fn(state)
  else:
    grad_step = trainer.grad_step_fn(state)
    apply_step = trainer.apply_step_fn(state)

  # Fleet tracing + on-demand profiler: spans and dead letters from
  # this run carry one minted trace id; SIGUSR2 triggers a short
  # jax.profiler capture into <out_dir>/profile — the batch-side
  # counterpart of serve's /debugz/profile endpoint.
  obs_lib.trace.configure_from_env(tier='train')
  obs_lib.trace.set_trace_id(obs_lib.trace.mint_trace_id())
  obs_lib.profiler.install_sigusr2(os.path.join(out_dir, 'profile'))
  # Snapshot the module-global blockwise-attention trace count so the
  # end-of-run delta attributes ring routing to THIS run (tests train
  # several models per process).
  ring_traces_start = ring_lib.n_blockwise_traces

  profile_dir = params.get('profile_dir', None)
  if profile_dir:
    jax.profiler.start_trace(profile_dir)

  stream_ds = None
  if streaming:
    # Constructed here (after checkpoint restore) so the stream can be
    # reseeded by resume position: a restarted run draws fresh
    # (differently-shuffled) data instead of replaying the head of the
    # corpus. Held in a variable so its fault counters (skipped shards
    # etc.) survive the iterator for the end-of-run summary.
    if pod is not None and not shard_streams and pod.readmit:
      logging.getLogger(__name__).warning(
          'elastic + streaming without shard_streams: a re-admitted '
          'host reseeds its stream by resume position and so draws '
          'approximately (not exactly) the batches its peers hold; '
          'pass shard_streams for per-host shard ownership, or use '
          'the non-streaming loader for exact replicated batches')
    stream_ds = data_lib.StreamingDataset(
        patterns=train_patterns,
        params=params,
        batch_size=params.batch_size,
        **({'buffer_size': params.buffer_size}
           if 'buffer_size' in params else {}),
        **({'host_rank': sorted(pod.members).index(pod.host_id),
            'host_count': len(pod.members)}
           if (pod is not None and shard_streams) else {}),
        workers=params.get('loader_workers', 0),
        seed=params.seed + step,
        on_shard_error=params.get('on_shard_error', 'fail'),
    )

  def train_batches():
    if streaming:
      it = iter(stream_ds)
      try:
        for _ in range(max(steps_per_epoch * num_epochs - step, 0)):
          yield next(it)
      finally:
        it.close()
    else:
      steps_to_skip = step
      for _ in range(num_epochs):
        for batch in train_ds.epoch():
          if steps_to_skip > 0:
            # Skip batches already covered by the restored checkpoint.
            steps_to_skip -= 1
            continue
          yield batch

  def maybe_augmented():
    # Training-time window augmentation (params.augment; applied to
    # training batches only — eval batches go through run_eval
    # untouched). Seeded off params.seed + resume step so a resumed
    # run draws a fresh augmentation stream instead of replaying one.
    if not params.get('augment', False):
      return train_batches()
    aug_rng = np.random.default_rng(params.seed + 7919 * (step + 1))
    return (
        data_lib.augment_batch(b, params, aug_rng)
        for b in train_batches()
    )

  # An orchestrator (models/flywheel.py) that owns the process-wide
  # signal handlers passes its guard in; we only install (and later
  # restore) our own when running standalone.
  owns_guard = preemption_guard is None
  guard = preemption_guard or PreemptionGuard(
      barrier_timeout=float(
          params.get('elastic_barrier_timeout', 30.0) or 30.0)
  ).install()
  sentinel = NanSentinel(
      params, out_dir,
      writer=trainer._is_writer() if pod is not None else None)
  # The sentinel reads verdicts one step late (see NanSentinel);
  # pending holds (step, metrics, window ids, host batch) for the step
  # whose device result is not yet known.
  pending = None

  def rollback():
    nonlocal state, step, pending
    if sentinel.rollbacks >= sentinel.max_rollbacks:
      raise faults_lib.NonFiniteTrainingError(
          f'training diverged: non-finite steps persisted through '
          f'{sentinel.rollbacks} rollback(s); refusing to roll back '
          f'again (params.nan_max_rollbacks={sentinel.max_rollbacks})'
      )
    latest = trainer.latest_valid_checkpoint()
    if latest is None:
      raise faults_lib.NonFiniteTrainingError(
          f'training diverged after {sentinel.consecutive} consecutive '
          f'non-finite step(s) at step {step} and no valid checkpoint '
          f'exists to roll back to'
      )
    # The contaminated state is still a valid restore template (same
    # tree/shapes); its values are fully overwritten.
    state = trainer.restore_checkpoint(state, latest)
    step = int(state.step)
    state = jax.device_put(state, trainer.state_shardings(state))
    pending = None
    if pod is not None:
      # Every member judges the same merged metrics, so all roll back
      # at the same step; bumping the barrier round in lockstep keeps
      # the replayed step numbers out of their first pass's stale
      # payload files.
      pod.advance_round()
    sentinel.rolled_back(latest)

  # Training degradation ladder (--on_device_error=degrade): the
  # inference-side dp ladder (runner.degrade_mesh) applied to training.
  # A permanent DeviceLostError mid-step rebuilds the mesh one dp step
  # down over the surviving devices, re-places the live state from
  # memory (checkpoint rollback only when the state itself is
  # unreadable, i.e. died with the device), recompiles the pjit step,
  # retargets in-flight prefetched transfers, and re-runs the failed
  # batch — the run completes instead of crash-looping at fixed dp.
  on_device_error = params.get('on_device_error', 'fail')
  n_train_degraded = 0
  prefetcher: Optional[TrainBatchPrefetcher] = None

  def degrade_mesh() -> bool:
    nonlocal state, step, pending, train_step, n_train_degraded
    dp = int(trainer.mesh.shape[mesh_lib.DATA_AXIS])
    tp = int(trainer.mesh.shape.get(mesh_lib.MODEL_AXIS, 1))
    new_dp = dp // 2
    # The global batch must still split evenly over the data axis.
    while new_dp >= 1 and params.batch_size % new_dp:
      new_dp //= 2
    if new_dp < 1 or new_dp >= dp or jax.process_count() > 1:
      # Single device (nothing smaller) or multi-host (the mesh spans
      # processes; shrinking it here would desync the others).
      return False
    # Pull the live state to host BEFORE abandoning the old mesh: when
    # the read succeeds the run continues from the exact last step (no
    # rollback); when the state died with the device, rebuild and fall
    # back to the last valid checkpoint.
    contaminated = False
    host_state = None
    try:
      host_state = jax.device_get(state)
    except Exception:  # pylint: disable=broad-except
      contaminated = True
    devices = np.asarray(trainer.mesh.devices).reshape(-1)[:new_dp * tp]
    trainer.mesh = mesh_lib.make_mesh(dp=new_dp, tp=tp,
                                      devices=list(devices))
    trainer._cached_eval_step = None  # eval recompiles on the new mesh
    if contaminated:
      latest = trainer.latest_valid_checkpoint()
      if latest is None:
        return False
      state = trainer.init_state(steps_total=decay_steps)
      state = trainer.restore_checkpoint(state, latest)
      step = int(state.step)
      pending = None
    else:
      state = host_state
    state = jax.device_put(state, trainer.state_shardings(state))
    train_step = trainer.train_step_fn(state)
    if prefetcher is not None:
      prefetcher.retarget()
    n_train_degraded += 1
    logging.getLogger(__name__).warning(
        'training mesh degraded to dp=%d after a device loss (step %d '
        'of the ladder)%s', new_dp, n_train_degraded,
        '; rolled back to the last valid checkpoint' if contaminated
        else '; state carried over in memory',
    )
    return True

  # Elastic host-loss handling (--on_host_error=degrade): the pod-scale
  # sibling of degrade_mesh. A HostLostError from any bounded barrier
  # triggers the survivor-side agreement round; the member set shrinks,
  # the epoch bumps, batches re-slice over the survivors, and the
  # failed step replays under the new epoch's barrier namespace.
  def rebuild_after_host_loss(err: Exception) -> bool:
    """Returns True when this host adopted a peer's AHEAD state: the
    lost host died inside a step barrier some members had already
    collected, so the pod split across a step boundary; the
    most-advanced member snapshots its live state and the rest adopt
    it — forward reconciliation, never a checkpoint rollback (that is
    reserved for state that died with a host, mirroring the PR-14
    degrade rule)."""
    nonlocal state, step, pending
    t0 = time.time()
    old_members = pod.members
    members = ()
    got = None
    for _ in range(max(pod.rebuild_attempts, 1)):
      members = pod.rebuild()
      try:
        got = pod.allgather('resume', {'step': int(step)})
        break
      except faults_lib.HostLostError as resume_err:
        # Another member died between the agreement round and the
        # resume exchange; rebuild again without it.
        err = resume_err
    if got is None:
      raise faults_lib.ElasticRebuildError(
          f'pod resume exchange never converged after '
          f'{pod.rebuild_attempts} rebuild(s); last error: {err}')
    steps = {int(h): int(meta['step']) for h, (meta, _) in got.items()}
    max_step = max(steps.values())
    adopted = False
    if len(set(steps.values())) > 1:
      max_host = min(h for h, s in steps.items() if s == max_step)
      if pod.host_id == max_host:
        pod.write_state_snapshot(
            pod.epoch, max_step,
            [np.asarray(x) for x in
             jax.tree_util.tree_flatten(jax.device_get(state))[0]])
      pod.barrier('resume-adopt')
      if steps[pod.host_id] < max_step:
        snap = pod.read_state_snapshot(pod.epoch)
        if snap is None:
          raise faults_lib.ElasticRebuildError(
              f'resume snapshot for epoch {pod.epoch} missing after '
              'the adopt barrier; pod dir inconsistent')
        leaves, treedef = jax.tree_util.tree_flatten(
            jax.device_get(state))
        state = jax.tree_util.tree_unflatten(
            treedef,
            [np.asarray(s_leaf, dtype=np.asarray(t).dtype)
             for s_leaf, t in zip(snap, leaves)])
        step = max_step
        pending = None
        adopted = True
    # Re-place the live TrainState by the rule table. The mesh is
    # host-local and unchanged, so this is cheap — placement is only
    # actually rebuilt for host-materialized (adopted) leaves.
    state = jax.device_put(state, trainer.state_shardings(state))
    if jax.process_count() > 1:
      # Real multi-controller pod: re-enter initialize_distributed
      # semantics at the agreed process count.
      from deepconsensus_tpu.parallel import distributed

      distributed.reinitialize(
          num_processes=len(members),
          process_id=sorted(members).index(pod.host_id))
    if prefetcher is not None:
      prefetcher.retarget()
    if stream_ds is not None and shard_streams:
      stream_ds.reassign_hosts(
          sorted(members).index(pod.host_id), len(members))
    obs_lib.trace.complete_event('host_rebuild', 'train', t0, time.time(), {
        'epoch': pod.epoch,
        'missing': [int(h) for h in getattr(err, 'missing', ()) or ()],
        'members_before': len(old_members),
        'members_after': len(members),
        'adopted_peer_state': adopted,
    })
    logging.getLogger(__name__).warning(
        'pod rebuilt after host loss (%s): members %s -> %s, epoch %d%s',
        err, sorted(old_members), sorted(members), pod.epoch,
        '; adopted the most-advanced survivor state' if adopted else '')
    return adopted

  def admit_joiners(joiners, at_step: int) -> None:
    """Survivor side of re-admission, at a step boundary: snapshot the
    live state outward, agree on the expanded member set, retarget the
    input pipeline to the new membership."""
    t0 = time.time()
    members = pod.admit(
        joiners,
        [np.asarray(x) for x in
         jax.tree_util.tree_flatten(jax.device_get(state))[0]],
        at_step)
    if jax.process_count() > 1:
      from deepconsensus_tpu.parallel import distributed

      distributed.reinitialize(
          num_processes=len(members),
          process_id=sorted(members).index(pod.host_id))
    if prefetcher is not None:
      prefetcher.retarget()
    if stream_ds is not None and shard_streams:
      stream_ds.reassign_hosts(
          sorted(members).index(pod.host_id), len(members))
    obs_lib.trace.complete_event(
        'host_readmit', 'train', t0, time.time(),
        {'epoch': pod.epoch, 'joiners': [int(j) for j in joiners],
         'members': len(members), 'step': int(at_step)})
    logging.getLogger(__name__).warning(
        'pod re-admitted %s at the step %d boundary: members now %s '
        '(epoch %d)', sorted(joiners), at_step, sorted(members),
        pod.epoch)

  def elastic_step(batch):
    """One pod-synchronized training step: local grads on this host's
    batch slice, bounded weighted-mean allreduce across members,
    identical apply everywhere. Returns (merged metrics, StepSync)."""
    nonlocal state
    grads, new_mstate, m_local = grad_step(state, batch)
    g_leaves, g_treedef = jax.tree_util.tree_flatten(
        jax.device_get((grads, new_mstate)))
    sync = pod.step_sync(
        step + 1,
        [np.asarray(leaf, np.float32) for leaf in g_leaves],
        weight=float(next(iter(batch.values())).shape[0]),
        meta={
            'loss': float(m_local['loss']),
            'acc_correct': float(m_local['accuracy_correct']),
            'acc_total': float(m_local['accuracy_total']),
        },
        stop_vote=guard.local(),
    )
    avg_grads, avg_mstate = jax.tree_util.tree_unflatten(
        g_treedef, sync.arrays)
    state, grad_norm = apply_step(state, avg_grads, avg_mstate)
    total = sync.weight_total
    merged = {
        # Per-host losses are slice means; their weighted mean is the
        # exact global-batch mean. Accuracy counts just sum.
        'loss': sum(meta['loss'] * meta['weight']
                    for meta in sync.metas.values()) / total,
        'grad_norm': grad_norm,
        'accuracy_correct': sum(
            meta['acc_correct'] for meta in sync.metas.values()),
        'accuracy_total': sum(
            meta['acc_total'] for meta in sync.metas.values()),
    }
    return merged, sync

  def pod_safe_save(at_step: int, metrics: Dict[str, float]) -> None:
    """save_checkpoint, with a peer death inside the checkpoint barrier
    handled like any other host loss (the leader's write is already
    intact or will be redone at the next boundary)."""
    try:
      trainer.save_checkpoint(state, at_step, metrics)
    except faults_lib.HostLostError as host_err:
      if pod is None or on_host_error != 'degrade':
        raise
      rebuild_after_host_loss(host_err)

  preempted = False
  final_metrics: Dict[str, float] = {}
  try:
    # Two prefetch layers: data.prefetch_iterator overlaps host-side
    # decode/shuffle/stacking with the device step (reference
    # counterpart: tf.data prefetch(AUTOTUNE) in data_providers.py),
    # and TrainBatchPrefetcher overlaps the sharded H2D transfer of
    # batch i+1 with the device's step i.
    prefetcher = TrainBatchPrefetcher(
        data_lib.prefetch_iterator(maybe_augmented()),
        trainer,
        poison_base_step=step,
    )
    t_step = time.time()
    for names, host_batch, batch in prefetcher:
      sync = None
      if pod is not None:
        # The host-loss drill hook fires BEFORE the step so the death
        # lands mid-barrier for the survivors, like a real SIGKILL.
        faults_lib.maybe_host_lost(step + 1, pod.host_id, pod.abandon)
        m = None
        attempts = 0
        while True:
          try:
            with jax.profiler.StepTraceAnnotation('train', step_num=step):
              m, sync = elastic_step(batch)
            break
          except faults_lib.HostLostError as host_err:
            attempts += 1
            if (on_host_error != 'degrade'
                or attempts > pod.rebuild_attempts):
              raise
            if rebuild_after_host_loss(host_err):
              # This host adopted a peer state AHEAD of its own, so
              # the batch in hand was already applied pod-wide; drop
              # it (adoption advanced `step`) and realign on the next.
              break
            # The failed step never committed (apply only runs after a
            # full collect): re-slice this same host batch for the
            # surviving member set and replay it under the new epoch.
            batch = prefetcher.place(host_batch)
        if m is None:
          continue
      else:
        try:
          faults_lib.injected_train_device_fault(step + 1)
          with jax.profiler.StepTraceAnnotation('train', step_num=step):
            state, m = train_step(state, batch)
        except Exception as e:  # pylint: disable=broad-except
          err = faults_lib.classify_device_error(e)
          if (on_device_error != 'degrade'
              or not isinstance(err, faults_lib.DeviceLostError)):
            raise
          if not degrade_mesh():
            raise err
          # The failed batch was consumed from the pipeline but never
          # applied: re-place it on the rebuilt mesh and re-run.
          batch = prefetcher.place(host_batch)
          with jax.profiler.StepTraceAnnotation('train', step_num=step):
            state, m = train_step(state, batch)
      step += 1
      # Per-iteration wall time (dispatch-to-dispatch, which converges
      # to device step time once the pipeline fills) feeds the registry
      # histogram and — when DCTPU_TRACE is set — a train_step span.
      t_now = time.time()
      trainer.step_time_hist.observe(t_now - t_step)
      obs_lib.trace.complete_event('train_step', 'train', t_step, t_now,
                                   {'step': step})
      t_step = t_now
      faults_lib.maybe_kill_train_at_step(step)
      faults_lib.maybe_sigterm_at_step(step)
      if pod is not None and sync is not None and sync.join_requests:
        # Re-admission lands exactly at a step boundary: every member
        # saw the same join requests piggybacked on this step's sync.
        admit_joiners(sync.join_requests, step)
      if sentinel.enabled:
        if pending is not None and sentinel.observe(*pending):
          if sentinel.should_rollback():
            rollback()
            continue
        pending = (step, m, names, host_batch)
      if step % params.get('log_every_n_steps', 100) == 0:
        m_host = {k: float(v) for k, v in m.items()}
        m_host['train/accuracy'] = m_host['accuracy_correct'] / max(
            m_host['accuracy_total'], 1
        )
        trainer.log_metrics(step, 'train', m_host)
      if step % eval_every == 0:
        # Force-resolve the delayed verdict before checkpointing: a
        # save boundary crossed while the state is contaminated would
        # persist NaN params, and the rollback path would then "heal"
        # onto the poisoned checkpoint. The extra device sync is free
        # here — eval blocks on the device anyway.
        if sentinel.enabled and pending is not None:
          sentinel.observe(*pending)
          pending = None
        if sentinel.should_rollback():
          rollback()
          continue
        if sentinel.consecutive:
          logging.getLogger(__name__).warning(
              'skipping eval/checkpoint at step %d: state contaminated '
              'by a non-finite update (%d/%d consecutive)',
              step, sentinel.consecutive, sentinel.limit,
          )
        else:
          final_metrics = run_eval(state)
          trainer.log_metrics(step, 'eval', final_metrics)
          pod_safe_save(step, final_metrics)
      # Elastic pods read the stop decision off the step sync (bounded,
      # unanimous-by-construction: every member merged the same votes);
      # legacy runs take the allgather vote, now also bounded.
      stop_requested = (bool(sync is not None and sync.stop)
                        if pod is not None else guard.requested())
      if stop_requested:
        # Emergency checkpoint at the step boundary, then a clean
        # return: the retry wrapper / scheduler restarts from it.
        # Same contamination guard as above: resuming from a NaN
        # emergency save would be worse than losing a few steps.
        if sentinel.enabled and pending is not None:
          sentinel.observe(*pending)
          pending = None
        if sentinel.consecutive:
          logging.getLogger(__name__).warning(
              'skipping emergency checkpoint at step %d: state '
              'contaminated by a non-finite update; resume will fall '
              'back to the last valid checkpoint', step,
          )
        else:
          pod_safe_save(step, {})
        final_metrics = {'preempted': 1.0, 'stop_step': float(step)}
        preempted = True
        logging.getLogger(__name__).warning(
            'preemption checkpoint saved at step %d; exiting cleanly',
            step,
        )
        break
    if not preempted:
      if sentinel.enabled and pending is not None:
        sentinel.observe(*pending)
        pending = None
      if sentinel.enabled and sentinel.consecutive:
        # Out of data with contaminated params: roll back even below
        # the threshold rather than finish (and save) a NaN state.
        rollback()
      final_metrics = run_eval(state)
      trainer.log_metrics(step, 'eval', final_metrics)
      pod_safe_save(step, final_metrics)
  finally:
    if prefetcher is not None:
      prefetcher.close()
    if owns_guard:
      guard.restore()
    sentinel.close()
    fault_counters: Dict[str, float] = dict(sentinel.counters)
    if pod is not None:
      # pod_epoch / n_host_rebuilds / n_host_readmissions /
      # n_barrier_timeouts land in the same `faults` split the other
      # resilience counters use.
      fault_counters.update(pod.counters())
      pod.close()
    if stream_ds is not None:
      fault_counters.update(stream_ds.counters)
    if train_ds is not None:
      fault_counters.update(train_ds.counters)
    # Bucketed-training observability: distinct compiled step
    # geometries (clean run: == n buckets), ring-attention routing for
    # long-insert widths, and the padding waste of bucket triage.
    fault_counters['n_train_forward_shapes'] = float(
        trainer.n_train_forward_shapes)
    ring_traces = ring_lib.n_blockwise_traces - ring_traces_start
    if ring_traces:
      fault_counters['n_ring_attention_traces'] = float(ring_traces)
    total_pos = float(fault_counters.get('n_train_window_positions', 0))
    if total_pos:
      fault_counters['train_padding_fraction'] = (
          float(fault_counters.get('n_train_padded_positions', 0))
          / total_pos)
    if prefetcher is not None:
      # Transfer-overlap observability: a clean N-step run reports
      # train_transfer_overlap_fraction == (N-1)/N (every launch after
      # the first rides under the previous step's compute).
      fault_counters.update(prefetcher.stats())
    if n_train_degraded:
      fault_counters['n_train_degraded'] = float(n_train_degraded)
    step_times = trainer.step_time_hist.percentiles()
    if step_times['count']:
      fault_counters['train_step_p50_s'] = step_times['p50']
      fault_counters['train_step_p99_s'] = step_times['p99']
    if fault_counters:
      trainer.log_metrics(step, 'faults', fault_counters)
    if profile_dir:
      jax.profiler.stop_trace()
  if jax.process_count() > 1:
    # Writes happen on process 0 only; without this sync the other
    # hosts exit first and the distributed shutdown barrier times out
    # while process 0 is still checkpointing.
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices('dc_tpu_end_of_training')
  return final_metrics


_UNSET = object()


def run_training_with_retry(
    *args,
    max_retries: int = 1_000_000,
    backoff_base: float = 0.5,
    backoff_max: float = 60.0,
    max_stalled_restarts: int = 3,
    **kwargs,
):
  """Retries training on transient failures (TPU preemption,
  device-unavailable), resuming from the latest valid checkpoint
  (reference retry-forever loop: model_train_custom_loop.py:333-347) —
  with three brakes the reference lacks:

  * only TRANSIENT errors retry (shared taxonomy,
    deepconsensus_tpu/faults.classify_error); a permanent error (bad
    config, bad data, diverged model) raises on the first attempt
    instead of looping forever;
  * exponential backoff between attempts (backoff_base * 2^k, capped
    at backoff_max) so a flapping device isn't hammered;
  * a crash-loop breaker: when the resume step fails to advance across
    max_stalled_restarts consecutive restarts, retrying cannot help
    (the failure precedes the first new checkpoint every time) and
    CrashLoopError aborts the loop.
  """
  log = logging.getLogger(__name__)
  out_dir = kwargs.get('out_dir')
  if out_dir is None and len(args) >= 2 and isinstance(args[1], str):
    out_dir = args[1]
  attempts = 0
  last_step = _UNSET
  stalled = 0
  while True:
    try:
      return run_training(*args, **kwargs)
    except Exception as e:  # pylint: disable=broad-except
      message = f'{type(e).__name__}: {e}'
      attempts += 1
      if faults_lib.classify_error(message) != faults_lib.FaultKind.TRANSIENT:
        raise
      if attempts > max_retries:
        raise
      if out_dir is not None:
        # Crash-loop detection needs the resume position; read it
        # without quarantining (run_training owns that mutation).
        resume_step = checkpoints_lib.latest_valid_step(
            os.path.join(os.path.abspath(out_dir), 'checkpoints')
        )
        if last_step is not _UNSET and resume_step == last_step:
          stalled += 1
          if stalled >= max_stalled_restarts:
            raise faults_lib.CrashLoopError(
                f'training failed {stalled + 1} consecutive time(s) '
                f'without the resume step advancing past '
                f'{resume_step}; aborting instead of crash-looping '
                f'(last error: {message.splitlines()[0]})'
            ) from e
        else:
          stalled = 0
        last_step = resume_step
      delay = min(backoff_max, backoff_base * (2 ** (attempts - 1)))
      log.warning(
          'transient failure (%s); restarting from latest valid '
          'checkpoint in %.1fs (attempt %d)',
          message.splitlines()[0], delay, attempts,
      )
      time.sleep(delay)
