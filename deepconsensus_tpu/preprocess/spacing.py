"""Vectorized multi-read spacing ("gap-aware" pileup alignment).

Inserts gap columns so that every insertion in any subread gets its own
column, keeping all reads aligned to the draft CCS. Semantics are
bit-identical to the reference's per-base state machine
(reference: deepconsensus/preprocess/pre_lib.py:176-276,1242-1276) but
re-derived as a closed-form column model that runs in O(columns) numpy
instead of a Python loop over every base of every read:

* For non-label reads, all reads share a "boundary" space: boundary b
  sits before the b-th non-insertion position (non-insertion positions
  of every read align 1:1 with CCS coordinate space because expansion
  indents all reads to coordinate 0). At boundary b the pileup allocates
  max-over-reads(insertion-run length at b) insertion columns; each
  read's insertions are left-aligned into that block, and everything
  else gets gaps there.

* Label reads (truth aligned to CCS) follow the reference's special
  rule: a label consumes its pending insertions eagerly whenever polled
  and never creates columns of its own. Their column assignment has the
  closed form col(p) = iteration_consumed(p) + #insertions-before-p,
  including the reference's trailing "zombie gap" behavior where an
  exhausted label keeps acquiring gaps through insertion columns until
  the next non-insertion iteration.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.preprocess.alignment import AlignedRead

Cigar = constants.Cigar


def _ins_col_mask(
    maxins: np.ndarray, block_start: np.ndarray, total_cols: int
) -> np.ndarray:
  """Boolean mask of insertion columns from the per-boundary widths."""
  is_ins_col = np.zeros(total_cols, dtype=bool)
  nz = np.flatnonzero(maxins)
  if nz.size:
    starts = block_start[nz]
    widths = maxins[nz]
    offsets = np.arange(int(widths.sum()))
    group_starts = np.repeat(np.cumsum(widths) - widths, widths)
    ins_cols = np.repeat(starts, widths) + (offsets - group_starts)
    is_ins_col[ins_cols[ins_cols < total_cols]] = True
  return is_ins_col


def _column_layout_batched(
    nonlabel: List[AlignedRead],
) -> Tuple[List[np.ndarray], np.ndarray, int]:
  """_column_layout with every per-read loop flattened into segment
  ops over the reads' concatenated positions (per-read cumsums become
  global cumsums minus per-read offsets; per-read insertion-run
  bincounts become run-length detection on (read, boundary) change
  points + np.maximum.at). Same return contract, ~5x fewer numpy
  dispatches on typical 10-subread ZMWs."""
  n_reads = len(nonlabel)
  lens = np.array([len(r) for r in nonlabel], dtype=np.int64)
  total = int(lens.sum())
  if total == 0:
    return [np.empty(0, np.int64) for _ in nonlabel], np.zeros(0, bool), 0
  ends = np.cumsum(lens)
  read_idx = np.repeat(np.arange(n_reads), lens)

  cigar = np.concatenate([r.cigar for r in nonlabel])
  is_ins = cigar == Cigar.INS
  nonins = ~is_ins

  # boundary of each position = #non-insertions before it IN ITS READ.
  cs = np.cumsum(nonins)
  # Exclusive-prefix indexing so zero-length reads (ends[i] == start[i])
  # don't wrap: cs[ends - 1] would read cs[-1] for a leading empty read.
  cs_pad = np.concatenate([[0], cs])
  cs_end = cs_pad[ends]
  cs_before = np.concatenate([[0], cs_end[:-1]])
  boundary = cs - cs_before[read_idx] - nonins
  nonins_per_read = cs_end - cs_before
  b_max = int(nonins_per_read.max())

  # maxins[b]: widest insertion run at boundary b across reads.
  # Insertion runs are maximal stretches of ins positions sharing one
  # (read, boundary); positions are ordered, so change points find them.
  maxins = np.zeros(b_max + 1, dtype=np.int64)
  ins_pos = np.flatnonzero(is_ins)
  if ins_pos.size:
    key = read_idx[ins_pos] * np.int64(b_max + 2) + boundary[ins_pos]
    change = np.empty(len(ins_pos), dtype=bool)
    change[0] = True
    change[1:] = key[1:] != key[:-1]
    run_start_idx = np.flatnonzero(change)
    run_len = np.diff(np.append(run_start_idx, len(ins_pos)))
    run_boundary = boundary[ins_pos[run_start_idx]]
    np.maximum.at(maxins, run_boundary, run_len)
    # rank of each insertion within its run (left-aligned placement).
    run_starts_bcast = np.maximum.accumulate(
        np.where(change, np.arange(len(ins_pos)), 0)
    )
    rank = np.arange(len(ins_pos)) - run_starts_bcast

  cum = np.cumsum(maxins)  # inclusive prefix sum
  # Non-insertion position b sits at column b + cum[b]; the insertion
  # block of boundary b starts at C(b) = b + cum[b] - maxins[b].
  block_start = np.arange(b_max + 1) + cum - maxins

  cols = np.empty(total, dtype=np.int64)
  b_idx = boundary[nonins]
  cols[nonins] = b_idx + cum[b_idx]
  if ins_pos.size:
    cols[ins_pos] = block_start[boundary[ins_pos]] + rank

  nonempty = lens > 0
  last_cols = np.zeros(n_reads, dtype=np.int64)
  last_cols[nonempty] = cols[ends[nonempty] - 1] + 1
  total_cols = int(last_cols.max())

  cols_per_read = [
      cols[ends[i] - lens[i] : ends[i]] for i in range(n_reads)
  ]
  return cols_per_read, _ins_col_mask(maxins, block_start,
                                      total_cols), total_cols


def _column_layout(
    nonlabel: List[AlignedRead],
) -> Tuple[List[np.ndarray], np.ndarray, int]:
  """Computes column indices for each non-label read.

  Returns (cols_per_read, is_ins_col, total_cols).
  """
  n_reads = len(nonlabel)
  per_read = []
  b_max = 0
  for r in nonlabel:
    is_ins = r.cigar == Cigar.INS
    nonins_count = int((~is_ins).sum())
    per_read.append((is_ins, nonins_count))
    b_max = max(b_max, nonins_count)

  # maxins[b]: widest insertion run at boundary b across reads.
  maxins = np.zeros(b_max + 1, dtype=np.int64)
  boundaries_per_read = []
  for (is_ins, nonins_count), r in zip(per_read, nonlabel):
    # boundary of each position = number of non-insertions before it.
    cum_nonins = np.cumsum(~is_ins)
    boundary = cum_nonins - (~is_ins)
    ins_boundaries = boundary[is_ins]
    boundaries_per_read.append((is_ins, boundary, ins_boundaries))
    if ins_boundaries.size:
      counts = np.bincount(ins_boundaries, minlength=b_max + 1)
      np.maximum(maxins, counts, out=maxins)

  cum = np.cumsum(maxins)  # inclusive prefix sum
  # Non-insertion position b sits at column b + cum[b]; the insertion
  # block of boundary b starts at C(b) = b + cum[b] - maxins[b].
  block_start = np.arange(b_max + 1) + cum - maxins

  cols_per_read: List[np.ndarray] = []
  total_cols = 0
  for (is_ins, boundary, ins_boundaries), r in zip(
      boundaries_per_read, nonlabel
  ):
    n = len(r)
    cols = np.empty(n, dtype=np.int64)
    nonins_mask = ~is_ins
    b_idx = boundary[nonins_mask]
    cols[nonins_mask] = b_idx + cum[b_idx]
    if ins_boundaries.size:
      # rank of each insertion within its boundary's run (left-aligned).
      change = np.empty(len(ins_boundaries), dtype=bool)
      change[0] = True
      change[1:] = ins_boundaries[1:] != ins_boundaries[:-1]
      run_starts = np.maximum.accumulate(
          np.where(change, np.arange(len(ins_boundaries)), 0)
      )
      rank = np.arange(len(ins_boundaries)) - run_starts
      cols[is_ins] = block_start[ins_boundaries] + rank
    cols_per_read.append(cols)
    if n:
      total_cols = max(total_cols, int(cols[-1]) + 1)

  return cols_per_read, _ins_col_mask(maxins, block_start,
                                      total_cols), total_cols


def _label_layout(
    label: AlignedRead, is_ins_col: np.ndarray, total_cols: int
) -> Tuple[np.ndarray, int]:
  """Column assignment + final width for a label read (closed form)."""
  is_ins = label.cigar == Cigar.INS
  n = len(label)
  n_ins_total = int(is_ins.sum())
  n_nonins = n - n_ins_total

  # Iterations at which non-insertion moves happen: non-insertion
  # columns of the pileup, extended past total_cols (all-quiet tail).
  ni = np.flatnonzero(~is_ins_col)
  if len(ni) < n_nonins:
    deficit = n_nonins - len(ni)
    ni = np.concatenate([ni, np.arange(total_cols, total_cols + deficit)])

  cols = np.empty(n, dtype=np.int64)
  ins_before = np.cumsum(is_ins) - is_ins  # exclusive prefix count
  nonins_rank = np.cumsum(~is_ins) - (~is_ins)  # j(p) for every position

  nonins_mask = ~is_ins
  cols[nonins_mask] = ni[nonins_rank[nonins_mask]] + ins_before[nonins_mask]
  if n_ins_total:
    j = nonins_rank[is_ins]
    # Run preceding non-ins rank j is consumed at iteration NI[j-1]+1
    # (iteration 0 for the leading run).
    prev_iter = np.where(j > 0, ni[np.maximum(j - 1, 0)] + 1, 0)
    cols[is_ins] = prev_iter + ins_before[is_ins]

  # Final spaced width, including the reference's zombie-gap behavior.
  if n == 0:
    t_star = 0
  elif not is_ins[-1]:
    return cols, int(ni[n_nonins - 1]) + n_ins_total + 1
  else:
    t_star = int(ni[n_nonins - 1]) + 1 if n_nonins > 0 else 0
  # Count consecutive insertion iterations starting at t_star.
  zombie = 0
  t = t_star
  while t < total_cols and is_ins_col[t]:
    zombie += 1
    t += 1
  return cols, t_star + n_ins_total + zombie


def _apply_spacing(
    read: AlignedRead, cols: np.ndarray, width: int
) -> AlignedRead:
  """Scatters a read's per-position data into spaced column arrays
  (reference put_spacing: pre_lib.py:218-250)."""
  bases = np.zeros(width, dtype=np.uint8)
  pw = np.zeros(width, dtype=np.int32)
  ip = np.zeros(width, dtype=np.int32)
  ccs_idx = np.full(width, -1, dtype=np.int64)
  bases[cols] = read.bases
  pw[cols] = read.pw
  ip[cols] = read.ip
  ccs_idx[cols] = read.ccs_idx

  cigar = read.cigar
  truth_idx = read.truth_idx
  if read.is_label:
    spaced_cigar = np.full(width, int(Cigar.HARD_CLIP), dtype=np.uint8)
    spaced_cigar[cols] = read.cigar
    cigar = spaced_cigar
    truth_pos = np.full(width, -1, dtype=np.int64)
    rng = np.arange(
        read.truth_range['begin'], read.truth_range['end'], dtype=np.int64
    )
    aln_base = np.isin(cigar, constants.READ_ADVANCING_OPS_ARR)
    if int(aln_base.sum()) != len(rng):
      raise ValueError(
          f'label truth range mismatch for {read.name}: '
          f'{int(aln_base.sum())} aligned bases vs {len(rng)} truth positions'
      )
    truth_pos[aln_base] = rng
    truth_idx = truth_pos

  bq = read.base_quality_scores
  if bq.size and bq.any():
    spaced_bq = np.full(width, -1, dtype=np.int64)
    spaced_bq[cols] = bq
    bq = spaced_bq

  return AlignedRead(
      name=read.name,
      bases=bases,
      cigar=cigar,
      pw=pw,
      ip=ip,
      sn=read.sn,
      strand=read.strand,
      ec=read.ec,
      np_num_passes=read.np_num_passes,
      rq=read.rq,
      rg=read.rg,
      ccs_idx=ccs_idx,
      base_quality_scores=bq,
      truth_idx=truth_idx,
      truth_range=read.truth_range,
  )


def _apply_spacing_batched(
    reads: List[AlignedRead],
    cols_per_read: List[np.ndarray],
    width: int,
) -> List[AlignedRead]:
  """_apply_spacing for a batch of non-label reads: one [n_reads,
  width] allocation and one fancy-index scatter per field instead of
  per-read buffers (base qualities, present only on the CCS read,
  keep the per-read path)."""
  n_reads = len(reads)
  lens = np.array([len(c) for c in cols_per_read], dtype=np.int64)
  row_idx = np.repeat(np.arange(n_reads), lens)
  flat_cols = (
      np.concatenate(cols_per_read) if n_reads else np.empty(0, np.int64)
  )
  bases2d = np.zeros((n_reads, width), dtype=np.uint8)
  pw2d = np.zeros((n_reads, width), dtype=np.int32)
  ip2d = np.zeros((n_reads, width), dtype=np.int32)
  ccs_idx2d = np.full((n_reads, width), -1, dtype=np.int64)
  if flat_cols.size:
    bases2d[row_idx, flat_cols] = np.concatenate(
        [r.bases for r in reads]
    )
    pw2d[row_idx, flat_cols] = np.concatenate([r.pw for r in reads])
    ip2d[row_idx, flat_cols] = np.concatenate([r.ip for r in reads])
    ccs_idx2d[row_idx, flat_cols] = np.concatenate(
        [r.ccs_idx for r in reads]
    )
  out = []
  for i, (read, cols) in enumerate(zip(reads, cols_per_read)):
    bq = read.base_quality_scores
    if bq.size and bq.any():
      spaced_bq = np.full(width, -1, dtype=np.int64)
      spaced_bq[cols] = bq
      bq = spaced_bq
    out.append(
        AlignedRead(
            name=read.name,
            bases=bases2d[i],
            cigar=read.cigar,
            pw=pw2d[i],
            ip=ip2d[i],
            sn=read.sn,
            strand=read.strand,
            ec=read.ec,
            np_num_passes=read.np_num_passes,
            rq=read.rq,
            rg=read.rg,
            ccs_idx=ccs_idx2d[i],
            base_quality_scores=bq,
            truth_idx=read.truth_idx,
            truth_range=read.truth_range,
        )
    )
  return out


def space_out_reads(reads: List[AlignedRead]) -> List[AlignedRead]:
  """Spaces out a ZMW's reads (subreads + ccs [+ label]) into a pileup.

  Returns new AlignedReads, all of equal spaced width.
  """
  has_label = bool(reads) and reads[-1].is_label
  nonlabel = reads[:-1] if has_label else reads
  label: Optional[AlignedRead] = reads[-1] if has_label else None

  cols_per_read, is_ins_col, total_cols = _column_layout_batched(nonlabel)
  widths = [
      int(c[-1]) + 1 if len(c) else 0 for c in cols_per_read
  ]
  label_cols = None
  if label is not None:
    label_cols, label_width = _label_layout(label, is_ins_col, total_cols)
    widths.append(label_width)
  max_len = max(widths) if widths else 0

  spaced = _apply_spacing_batched(nonlabel, cols_per_read, max_len)
  if label is not None:
    spaced.append(_apply_spacing(label, label_cols, max_len))
  return spaced
