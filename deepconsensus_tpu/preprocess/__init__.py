from deepconsensus_tpu.preprocess.alignment import (  # noqa: F401
    AlignedRead,
    construct_ccs_read,
    expand_aligned_record,
)
from deepconsensus_tpu.preprocess.spacing import space_out_reads  # noqa: F401
from deepconsensus_tpu.preprocess.pileup import (  # noqa: F401
    FeatureLayout,
    Pileup,
    layout_from_shape,
)
from deepconsensus_tpu.preprocess.feeder import (  # noqa: F401
    create_proc_feeder,
    reads_to_pileup,
)
