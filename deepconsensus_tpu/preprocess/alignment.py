"""Aligned-read container and alignment expansion.

Converts BAM alignment records into gap-expanded, CCS-indexed read
arrays. Behavior mirrors the reference's Read dataclass and
expand_clip_indent/trim_insertions (reference:
deepconsensus/preprocess/pre_lib.py:110-421,1061-1239) but everything is
vectorized numpy over the expanded-cigar column space, and bases are
kept vocab-encoded (uint8, gap=0) end to end instead of char arrays.

One deliberate divergence: bases outside the vocab (e.g. 'N') encode to
gap (0); the reference leaves uninitialized memory for them
(pre_lib.py:253-260 writes only vocab matches into an np.ndarray).
"""
from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Any, Dict, Optional

import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.io.bam import BamRecord
from deepconsensus_tpu.utils import phred

Cigar = constants.Cigar

_EMPTY_I32 = np.empty(0, dtype=np.int32)
_EMPTY_U8 = np.empty(0, dtype=np.uint8)


@dataclasses.dataclass
class AlignedRead:
  """A gap-expanded sequence aligned to CCS coordinates.

  bases are vocab-encoded uint8 (0=gap). ccs_idx maps each column to a
  CCS coordinate or -1. For labels, truth_range/truth_idx track the
  genome interval the truth sequence came from.
  """

  name: str
  bases: np.ndarray          # uint8 vocab codes
  cigar: np.ndarray          # uint8 op codes
  pw: np.ndarray             # int32
  ip: np.ndarray             # int32
  sn: np.ndarray             # float32[4] (empty for labels)
  strand: constants.Strand
  ec: Optional[float] = None
  np_num_passes: Optional[int] = None
  rq: Optional[float] = None
  rg: Optional[str] = None
  ccs_idx: np.ndarray = dataclasses.field(
      default_factory=lambda: np.empty(0, dtype=np.int64))
  base_quality_scores: np.ndarray = dataclasses.field(
      default_factory=lambda: _EMPTY_I32.copy())
  truth_idx: np.ndarray = dataclasses.field(
      default_factory=lambda: np.empty(0, dtype=np.int64))
  truth_range: Optional[Dict[str, Any]] = None

  # ------------------------------------------------------------------
  @property
  def is_label(self) -> bool:
    return self.truth_range is not None

  @property
  def zmw(self) -> int:
    return int(self.name.split('/')[1])

  @property
  def avg_base_quality_score(self) -> float:
    return phred.avg_phred(self.base_quality_scores)

  def __len__(self) -> int:
    return len(self.bases)

  def __str__(self) -> str:
    return phred.encoded_sequence_to_string(self.bases)

  @property
  def ccs_bounds(self) -> slice:
    """Min/max covered CCS coordinate (inclusive max), or empty slice."""
    covered = self.ccs_idx[self.ccs_idx != -1]
    if covered.size == 0:
      return slice(0, 0)
    return slice(int(covered.min()), int(covered.max()))

  @property
  def label_bounds(self) -> slice:
    covered = self.truth_idx[self.truth_idx != -1]
    if covered.size == 0:
      return slice(0, 0)
    return slice(int(covered.min()), int(covered.max()))

  @property
  def label_coords(self) -> str:
    if self.is_label:
      bounds = self.label_bounds
      return f'{self.truth_range["contig"]}:{bounds.start}-{bounds.stop}'
    return ''

  # ------------------------------------------------------------------
  def slice_columns(self, r_slice: slice) -> 'AlignedRead':
    """Slice all per-column attributes (reference: pre_lib.py:392-409)."""
    return AlignedRead(
        name=self.name,
        bases=self.bases[r_slice],
        cigar=self.cigar[r_slice],
        pw=self.pw[r_slice],
        ip=self.ip[r_slice],
        sn=self.sn,
        strand=self.strand,
        ec=self.ec,
        np_num_passes=self.np_num_passes,
        rq=self.rq,
        rg=self.rg,
        ccs_idx=self.ccs_idx[r_slice],
        base_quality_scores=self.base_quality_scores[r_slice]
        if self.base_quality_scores.size
        else self.base_quality_scores,
        truth_idx=self.truth_idx[r_slice]
        if self.truth_idx.size
        else self.truth_idx,
        truth_range=self.truth_range,
    )

  def ccs_slice(self, start: int, end: int) -> 'AlignedRead':
    """Slice by CCS coordinates; bounds inclusive (pre_lib.py:308-334)."""
    locs = np.where((self.ccs_idx >= start) & (self.ccs_idx <= end))[0]
    if locs.size:
      sl = slice(int(locs.min()), int(locs.max()) + 1)
    else:
      sl = slice(0, 0)
    out = self.slice_columns(sl)
    return out

  def pad(self, pad_width: int) -> 'AlignedRead':
    """Right-pad all per-column attributes to pad_width."""
    n = len(self)
    if n >= pad_width:
      return self
    extra = pad_width - n

    def _pad(arr, value, dtype=None):
      if dtype is None:
        dtype = arr.dtype
      fill = np.full(extra, value, dtype=dtype)
      return np.concatenate([arr.astype(dtype), fill])

    return AlignedRead(
        name=self.name,
        bases=_pad(self.bases, constants.GAP_INT),
        cigar=_pad(self.cigar, int(Cigar.HARD_CLIP)),
        pw=_pad(self.pw, 0),
        ip=_pad(self.ip, 0),
        sn=self.sn,
        strand=self.strand,
        ec=self.ec,
        np_num_passes=self.np_num_passes,
        rq=self.rq,
        rg=self.rg,
        ccs_idx=_pad(self.ccs_idx, -1),
        base_quality_scores=_pad(self.base_quality_scores, -1, np.int64),
        truth_idx=_pad(self.truth_idx, -1, np.int64),
        truth_range=self.truth_range,
    )

  def remove_gaps_and_pad(self, pad_width: int) -> Optional['AlignedRead']:
    """Drop gap columns; None if still longer than pad_width.

    Used to fit long labels into the window (pre_lib.py:358-384).
    """
    keep = self.bases != constants.GAP_INT
    if int(keep.sum()) > pad_width:
      return None
    kept = AlignedRead(
        name=self.name,
        bases=self.bases[keep],
        cigar=self.cigar[keep],
        pw=self.pw[keep],
        ip=self.ip[keep],
        sn=self.sn,
        strand=self.strand,
        ec=self.ec,
        np_num_passes=self.np_num_passes,
        rq=self.rq,
        rg=self.rg,
        ccs_idx=self.ccs_idx[keep],
        base_quality_scores=self.base_quality_scores[keep]
        if self.base_quality_scores.size
        else self.base_quality_scores,
        truth_idx=self.truth_idx[keep]
        if self.truth_idx.size
        else self.truth_idx,
        truth_range=self.truth_range,
    )
    return kept.pad(pad_width)


# ---------------------------------------------------------------------------
# Expansion from BAM records
# ---------------------------------------------------------------------------


def _trim_insertions(
    record: BamRecord,
    ins_trim: int,
    counter: Optional[Counter],
):
  """Removes insertions longer than ins_trim.

  Returns (cigar_ops, cigar_lens, seq_codes, keep_mask_query) where
  keep_mask_query marks surviving query bases in *aligned* orientation
  (reference: pre_lib.py:1061-1125).
  """
  ops = record.cigar_ops
  lens = record.cigar_lens
  seq_codes = np.frombuffer(record.seq.encode('ascii'), dtype=np.uint8)
  if counter is not None:
    counter['zmw_total_bp'] += int(lens.sum())
  if ins_trim <= 0:
    return ops, lens, seq_codes, None

  big_ins = (ops == Cigar.INS) & (lens > ins_trim)
  if not big_ins.any():
    return ops, lens, seq_codes, None

  # Query-consuming ops (per SAM spec) give seq offsets per cigar op.
  q_consume = np.array(
      [op in (0, 1, 4, 7, 8) for op in range(10)], dtype=bool
  )[ops]
  q_starts = np.concatenate([[0], np.cumsum(np.where(q_consume, lens, 0))])[:-1]
  keep_mask = np.ones(len(seq_codes), dtype=bool)
  for i in np.flatnonzero(big_ins):
    keep_mask[q_starts[i] : q_starts[i] + lens[i]] = False
    if counter is not None:
      counter['zmw_trimmed_insertions'] += 1
      counter['zmw_trimmed_insertions_bp'] += int(lens[i])
  new_ops = ops[~big_ins]
  new_lens = lens[~big_ins]
  return new_ops, new_lens, seq_codes[keep_mask], keep_mask


def expand_aligned_record(
    record: BamRecord,
    truth_range: Optional[Dict[str, Any]] = None,
    ins_trim: int = 0,
    counter: Optional[Counter] = None,
) -> AlignedRead:
  """Expands a BAM alignment into CCS-column space.

  Deletions become gap columns, soft clips are removed, the read is
  indented to reference coordinate 0, and PW/IP tag values (stored in
  instrument orientation) are reversed onto reverse-strand alignments
  (reference: pre_lib.py:1128-1239).
  """
  ops, lens, seq_codes, keep_mask = _trim_insertions(record, ins_trim, counter)
  if truth_range is not None:
    truth_range = dict(truth_range)

  # Expanded per-column arrays over the (hard-clip-free) alignment.
  hard = ops == Cigar.HARD_CLIP
  exp_ops = np.repeat(ops[~hard], lens[~hard]).astype(np.uint8)
  q_mask = np.array([op in (0, 1, 4, 7, 8) for op in range(10)], bool)[exp_ops]
  r_mask = np.array([op in (0, 2, 3, 7, 8) for op in range(10)], bool)[exp_ops]
  read_idx = np.where(q_mask, np.cumsum(q_mask) - 1, -1)
  ccs_idx = np.where(r_mask, record.pos + np.cumsum(r_mask) - 1, -1).astype(
      np.int64
  )

  aln_len = len(exp_ops)
  new_bases = np.zeros(aln_len, dtype=np.uint8)
  new_bases[q_mask] = constants.VOCAB_LUT[seq_codes]
  new_pw = np.zeros(aln_len, dtype=np.int32)
  new_ip = np.zeros(aln_len, dtype=np.int32)

  strand = (
      constants.Strand.REVERSE if record.is_reverse
      else constants.Strand.FORWARD
  )

  if truth_range is None:
    pw_vals = np.asarray(record.get_tag('pw'), dtype=np.int32)
    ip_vals = np.asarray(record.get_tag('ip'), dtype=np.int32)
    if keep_mask is not None:
      if record.is_reverse:
        pw_vals = pw_vals[keep_mask[::-1]]
        ip_vals = ip_vals[keep_mask[::-1]]
      else:
        pw_vals = pw_vals[keep_mask]
        ip_vals = ip_vals[keep_mask]
    if strand == constants.Strand.REVERSE:
      pw_vals = pw_vals[::-1]
      ip_vals = ip_vals[::-1]
    new_pw[q_mask] = pw_vals
    new_ip[q_mask] = ip_vals
    sn = np.asarray(record.get_tag('sn'), dtype=np.float32)
  else:
    sn = np.empty(0, dtype=np.float32)

  # Remove soft-clipped ends (bases nulled, columns dropped). Bounds
  # must come from the *trimmed* cigar, like the reference which trims
  # the record in place before expanding (pre_lib.py:1153-1155).
  soft = exp_ops == Cigar.SOFT_CLIP
  if soft.any():
    new_bases[soft] = constants.GAP_INT
    q_start = 0
    for op, ln in zip(ops, lens):
      if op == Cigar.SOFT_CLIP:
        q_start += int(ln)
      elif op != Cigar.HARD_CLIP:
        break
    q_end = len(seq_codes)
    for op, ln in zip(ops[::-1], lens[::-1]):
      if op == Cigar.SOFT_CLIP:
        q_end -= int(ln)
      elif op != Cigar.HARD_CLIP:
        break
    col_start = int(np.flatnonzero(read_idx == q_start)[0])
    col_end = int(np.flatnonzero(read_idx == q_end - 1)[0]) + 1
    if truth_range is not None:
      if ops[0] == Cigar.SOFT_CLIP:
        truth_range['begin'] += int(lens[0])
      if ops[-1] == Cigar.SOFT_CLIP:
        truth_range['end'] -= int(lens[-1])
    sl = slice(col_start, col_end)
    new_bases = new_bases[sl]
    new_pw = new_pw[sl]
    new_ip = new_ip[sl]
    exp_ops = exp_ops[sl]
    ccs_idx = ccs_idx[sl]

  # Indent to reference coordinate zero with REF_SKIP columns.
  if record.pos:
    indent = record.pos
    new_bases = np.concatenate(
        [np.zeros(indent, dtype=np.uint8), new_bases]
    )
    exp_ops = np.concatenate(
        [np.full(indent, int(Cigar.REF_SKIP), dtype=np.uint8), exp_ops]
    )
    new_pw = np.concatenate([np.zeros(indent, np.int32), new_pw])
    new_ip = np.concatenate([np.zeros(indent, np.int32), new_ip])
    ccs_idx = np.concatenate([np.full(indent, -1, np.int64), ccs_idx])

  return AlignedRead(
      name=record.qname,
      bases=new_bases,
      cigar=exp_ops,
      pw=new_pw,
      ip=new_ip,
      sn=sn,
      strand=strand,
      ccs_idx=ccs_idx,
      truth_range=truth_range,
  )


def construct_ccs_read(record: BamRecord) -> AlignedRead:
  """Builds the CCS draft read with base qualities and aux tags
  (reference: pre_lib.py:966-998)."""
  seq_codes = np.frombuffer(record.seq.encode('ascii'), dtype=np.uint8)
  n = len(seq_codes)
  tags = record.tags
  return AlignedRead(
      name=record.qname,
      bases=constants.VOCAB_LUT[seq_codes].copy(),
      cigar=np.zeros(n, dtype=np.uint8),  # all MATCH
      pw=np.zeros(n, dtype=np.int32),
      ip=np.zeros(n, dtype=np.int32),
      sn=np.zeros(4, dtype=np.float32),
      strand=constants.Strand.UNKNOWN,
      ec=tags.get('ec'),
      np_num_passes=tags.get('np'),
      rq=tags.get('rq'),
      rg=tags.get('RG'),
      ccs_idx=np.arange(n, dtype=np.int64),
      base_quality_scores=(
          record.quals.astype(np.int64)
          if record.quals is not None
          else np.zeros(n, dtype=np.int64)
      ),
  )
