"""`preprocess` driver: BAMs -> per-split gzip TFRecord shards + summary.

Equivalent of the reference's preprocess binary (reference:
deepconsensus/preprocess/preprocess.py:63-361): optional worker-pool
featurization with a single writer, @split filename templating, and a
JSON summary combining counters, layout, and flags.
"""
from __future__ import annotations

import collections
import json
import multiprocessing
import os
from typing import Dict, List, Optional, Tuple

from deepconsensus_tpu import constants
from deepconsensus_tpu.io.tfrecord import TFRecordWriter
from deepconsensus_tpu.models.config import DEFAULT_MAX_LENGTH
from deepconsensus_tpu.preprocess.feeder import create_proc_feeder
from deepconsensus_tpu.preprocess.pileup import FeatureLayout
from deepconsensus_tpu.preprocess.feeder import reads_to_pileup


def _process_zmw(args) -> Tuple[List[bytes], str, Dict[str, int]]:
  """Featurizes one ZMW into serialized examples (worker side)."""
  subreads, name, layout, split, window_widths = args
  pileup = reads_to_pileup(subreads, name, layout, window_widths)
  serialized = [w.to_example().serialize() for w in pileup.iter_windows()]
  return serialized, split, dict(pileup.counter)


def run_preprocess(
    subreads_to_ccs: str,
    ccs_bam: str,
    output: str,
    max_passes: int = 20,
    example_width: int = DEFAULT_MAX_LENGTH,
    use_ccs_bq: bool = False,
    ins_trim: int = 5,
    use_ccs_smart_windows: bool = False,
    truth_bed: Optional[str] = None,
    truth_to_ccs: Optional[str] = None,
    truth_split: Optional[str] = None,
    limit: int = 0,
    cpus: int = 0,
    shard: Optional[tuple] = None,
    compression: str = 'BGZF',
) -> Dict[str, int]:
  """Writes examples to `output` ('@split' expands per split).

  Returns the combined counter. With cpus>0 featurization fans out to a
  process pool while the main process remains the single writer
  (reference: preprocess.py:297-332).

  compression: 'BGZF' (default) writes .gz shards as BGZF blocks —
  still valid gzip for any TFRecord reader, and the training loader's
  native decode path can inflate the blocks in parallel. 'GZIP' writes
  a single-member stream like the reference's TF writer.
  """
  is_training = bool(truth_bed and truth_to_ccs and truth_split)
  splits = ('train', 'eval', 'test') if is_training else ('inference',)
  if '@split' not in output and is_training:
    raise ValueError('training output path must contain @split')

  layout = FeatureLayout(max_passes, example_width, use_ccs_bq)
  feeder, counter = create_proc_feeder(
      subreads_to_ccs=subreads_to_ccs,
      ccs_bam=ccs_bam,
      layout=layout,
      ins_trim=ins_trim,
      use_ccs_smart_windows=use_ccs_smart_windows,
      truth_bed=truth_bed,
      truth_to_ccs=truth_to_ccs,
      truth_split=truth_split,
      limit=limit,
      shard=shard,
  )

  writers = {}
  for split in splits:
    path = output.replace('@split', split)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    writers[split] = TFRecordWriter(
        path, compression=compression if path.endswith('.gz') else None)

  agg: collections.Counter = collections.Counter()

  def consume(result):
    serialized, split, zmw_counter = result
    agg.update(zmw_counter)
    for record in serialized:
      writers[split].write(record)
      agg[f'n_examples_{split}'] += 1
      agg['n_examples'] += 1

  if cpus and cpus > 1:
    with multiprocessing.Pool(cpus) as pool:
      for result in pool.imap(_process_zmw, feeder(), chunksize=4):
        consume(result)
  else:
    for item in feeder():
      consume(_process_zmw(item))

  for w in writers.values():
    w.close()

  summary = dict(counter)
  summary.update(agg)
  summary.update(layout.to_dict())
  summary.update({
      'subreads_to_ccs': subreads_to_ccs,
      'ccs_bam': ccs_bam,
      'truth_to_ccs': truth_to_ccs or '',
      'truth_bed': truth_bed or '',
      'truth_split': truth_split or '',
      'ins_trim': str(ins_trim),
      'version': constants.__version__,
  })
  mode = 'training' if is_training else 'inference'
  summary_path = (
      output.replace('@split', 'summary').rsplit('.tfrecord', 1)[0]
      + f'.summary.{mode}.json'
  )
  os.makedirs(os.path.dirname(os.path.abspath(summary_path)), exist_ok=True)
  with open(summary_path, 'w') as f:
    json.dump(summary, f, indent=1)
  return summary
