"""Feature layout and windowed pileup examples.

FeatureLayout mirrors the reference's DcConfig row bookkeeping
(reference: deepconsensus/preprocess/pre_lib.py:424-528); Pileup mirrors
DcExample windowing/feature assembly (pre_lib.py:531-819). The stacked
2-D tensor layout is identical: [bases x max_passes, pw x max_passes,
ip x max_passes, strand x max_passes, ccs, (ccs_bq), sn x 4] rows by
max_length columns.
"""
from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.io.example_proto import Example
from deepconsensus_tpu.preprocess.alignment import AlignedRead
from deepconsensus_tpu.utils import phred


class FeatureLayout:
  """Row layout of the stacked example tensor."""

  N_SUBREAD_FEATURES = ('bases', 'pw', 'ip', 'strand')

  def __init__(self, max_passes: int, max_length: int,
               use_ccs_bq: bool = False,
               window_buckets: Optional[Tuple[int, ...]] = None):
    self.max_passes = max_passes
    self.max_length = max_length
    self.use_ccs_bq = use_ccs_bq
    # Window length buckets for the variable-width (smart windows)
    # path: a spaced window pads to the smallest bucket that fits
    # instead of pad-to-max_length, and only windows wider than the
    # largest bucket overflow. None/empty keeps the single-shape rule.
    # Rides on the layout so bucketing reaches featurize workers
    # without widening the feeder plumbing.
    self.window_buckets = tuple(window_buckets) if window_buckets else (
        (max_length,))
    self.feature_rows = {
        'bases': max_passes,
        'pw': max_passes,
        'ip': max_passes,
        'strand': max_passes,
        'ccs': 1,
        'ccs_bq': 1 if use_ccs_bq else 0,
        'sn': 4,
    }
    self.feature_start: Dict[str, int] = {}
    i = 0
    for name, rows in self.feature_rows.items():
      self.feature_start[name] = i
      i += rows

  def indices(self, feature: str, n_subreads: int = 0) -> slice:
    start = self.feature_start[feature]
    if n_subreads:
      assert feature in self.N_SUBREAD_FEATURES
      return slice(start, start + min(n_subreads, self.max_passes))
    assert feature not in self.N_SUBREAD_FEATURES
    return slice(start, start + self.feature_rows[feature])

  @property
  def tensor_height(self) -> int:
    return sum(self.feature_rows.values())

  def to_dict(self) -> Dict[str, str]:
    return {
        'max_passes': str(self.max_passes),
        'max_length': str(self.max_length),
        'tensor_height': str(self.tensor_height),
        'tensor_width': str(self.max_length),
    }


def layout_from_shape(shape: Tuple[int, int, int],
                      use_ccs_bq: bool = False) -> FeatureLayout:
  """Recovers a FeatureLayout from a subreads tensor shape."""
  height, width, _ = shape
  fixed = 6 if use_ccs_bq else 5
  max_passes, rem = divmod(height - fixed, len(FeatureLayout.N_SUBREAD_FEATURES))
  if rem != 0:
    raise ValueError(f'invalid subreads shape {shape!r}')
  return FeatureLayout(max_passes, width, use_ccs_bq)


def bucket_window_width(window_width: int,
                        layout: FeatureLayout) -> Tuple[int, bool]:
  """(padded_width, overflow) for a spaced window under the layout's
  bucket set: the smallest bucket that fits, or (window_width, True)
  past the largest bucket — overflow windows keep their natural width
  and are triaged to the CCS-fallback path downstream, exactly as the
  single-shape rule did for window_width > max_length."""
  for b in layout.window_buckets:
    if window_width <= b:
      return int(b), False
  return int(window_width), True


def total_rows(max_passes: int, use_ccs_bq: bool) -> int:
  """Number of rows in the stacked tensor
  (reference: models/data_providers.py:61-78)."""
  return max_passes * 4 + (6 if use_ccs_bq else 5)


def row_indices(
    max_passes: int, use_ccs_bq: bool
) -> Tuple[Tuple[int, int], ...]:
  """(start, end) row ranges: bases, pw, ip, strand, ccs, ccs_bq, sn
  (reference: models/data_providers.py:81-113)."""
  base = (0, max_passes)
  pw = (max_passes, max_passes * 2)
  ip = (max_passes * 2, max_passes * 3)
  strand = (max_passes * 3, max_passes * 4)
  ccs = (max_passes * 4, max_passes * 4 + 1)
  if use_ccs_bq:
    ccs_bq = (max_passes * 4 + 1, max_passes * 4 + 2)
    sn = (max_passes * 4 + 2, max_passes * 4 + 6)
  else:
    ccs_bq = (0, 0)
    sn = (max_passes * 4 + 1, max_passes * 4 + 5)
  return base, pw, ip, strand, ccs, ccs_bq, sn


@dataclasses.dataclass
class Pileup:
  """A ZMW's spaced reads plus windowing and feature assembly."""

  name: str
  reads: List[AlignedRead]
  layout: FeatureLayout
  window_widths: Optional[np.ndarray] = None
  counter: Counter = dataclasses.field(default_factory=Counter)
  overflow: bool = False

  _width: Optional[int] = None
  _ccs_width: Optional[int] = None
  # Window pileups yielded by iter_windows carry their feature tensor
  # pre-sliced from the parent ZMW matrix (label rows are not part of
  # the matrix, so training label adjustments don't invalidate it).
  _cached_features: Optional[np.ndarray] = None

  @property
  def is_training(self) -> bool:
    return self.reads[-1].is_label

  @property
  def ccs(self) -> AlignedRead:
    return self.reads[-2] if self.is_training else self.reads[-1]

  @property
  def label(self) -> Optional[AlignedRead]:
    return self.reads[-1] if self.is_training else None

  @property
  def label_coords(self) -> str:
    return self.label.label_coords if self.is_training else ''

  @property
  def contig(self) -> Optional[str]:
    return self.label.truth_range['contig'] if self.is_training else None

  @property
  def subreads(self) -> List[AlignedRead]:
    return self.reads[:-2] if self.is_training else self.reads[:-1]

  @property
  def n_subreads(self) -> int:
    return len(self.subreads)

  @property
  def keep_subreads(self) -> int:
    return min(self.layout.max_passes, self.n_subreads)

  @property
  def width(self) -> int:
    if self._width is None:
      self._width = len(self.ccs.bases)
    return self._width

  @property
  def ccs_width(self) -> int:
    """Spaced width excluding trailing gap columns."""
    if self._ccs_width is None:
      nz = np.flatnonzero(self.ccs.bases != constants.GAP_INT)
      self._ccs_width = int(nz[-1]) + 1 if nz.size else 0
    return self._ccs_width

  @property
  def is_empty(self) -> bool:
    return not (self.ccs.ccs_idx >= 0).any()

  @property
  def ccs_matches_label(self) -> bool:
    ccs = phred.left_shift_seq(self.ccs.bases)
    label = phred.left_shift_seq(self.label.bases)
    n = max(len(ccs), len(label))
    ccs = np.pad(ccs, (0, n - len(ccs)))
    label = np.pad(label, (0, n - len(label)))
    return bool(np.array_equal(ccs, label))

  # ------------------------------------------------------------------
  def window_slice(self, r_slice: slice) -> 'Pileup':
    """Column-slices subreads+ccs; ccs-coordinate-slices the label
    (reference: pre_lib.py:789-798)."""
    reads = [x.slice_columns(r_slice) for x in self.subreads + [self.ccs]]
    if self.is_training:
      bounds = reads[-1].ccs_bounds
      reads.append(self.label.ccs_slice(bounds.start, bounds.stop))
    return Pileup(self.name, reads, self.layout)

  def calculate_windows(self, example_width: int) -> List[int]:
    """Window widths in spaced-column units (pre_lib.py:625-650)."""
    if self.window_widths is not None:
      # "Smart windows": the wl tag gives widths in unspaced CCS bases;
      # translate to spaced columns by walking non-gap positions.
      ccs_bases = self.ccs.bases
      nongap_positions = np.flatnonzero(ccs_bases != constants.GAP_INT)
      widths = []
      last_pos = 0
      consumed = 0
      for w in self.window_widths:
        consumed += int(w)
        # Column just past the consumed-th non-gap base.
        end_col = int(nongap_positions[consumed - 1]) + 1
        widths.append(end_col - last_pos)
        last_pos = end_col
      if sum(widths) != self.ccs_width:
        raise ValueError(
            f'smart windows cover {sum(widths)} columns, '
            f'expected {self.ccs_width}'
        )
      return widths
    n_windows = self.ccs_width // example_width
    if self.ccs_width % example_width > 0:
      n_windows += 1
    return [example_width] * n_windows

  def iter_windows(self) -> Iterator['Pileup']:
    """Yields fixed-width window Pileups (reference iter_examples:
    pre_lib.py:652-697). Each yielded window carries its feature
    tensor pre-sliced from the ZMW matrix (built once), so
    to_example/extract_features skip the per-window re-stacking."""
    self.counter = Counter()
    layout = self.layout
    max_length = layout.max_length
    matrix = self.full_matrix()
    keep = self.subreads[: layout.max_passes]
    strand_rows = layout.indices('strand', self.n_subreads)
    sn_rows = layout.indices('sn')
    strand_col = np.array(
        [float(int(r.strand)) for r in keep], dtype=constants.NP_DATA_TYPE
    )
    sn_col = (
        np.asarray(self.subreads[0].sn, dtype=constants.NP_DATA_TYPE)
        if self.subreads else np.zeros(4, dtype=constants.NP_DATA_TYPE)
    )

    start = 0
    for window_width in self.calculate_windows(max_length):
      self.counter[f'example_width_bucket_{window_width}'] += 1
      window = self.window_slice(slice(start, start + window_width))
      if start > self.ccs_width:
        break
      win_start, start = start, start + window_width
      if window.is_empty:
        self.counter['n_examples_no_ccs_idx'] += 1
        continue

      if self.is_training and len(window.label.bases) > max_length:
        adjusted = window.label.remove_gaps_and_pad(max_length)
        if adjusted is None:
          self.counter['n_examples_label_overflow'] += 1
          continue
        self.counter['n_examples_adjusted_label'] += 1
        window.reads[-1] = adjusted

      if self.is_training:
        # Training keeps the reference single-shape rule; buckets are
        # an inference-side geometry.
        width = max(window_width, max_length)
        overflow = window_width > max_length
      else:
        width, overflow = bucket_window_width(window_width, layout)
      if overflow:
        self.counter['n_examples_overflow'] += 1
        if self.is_training:
          continue
      else:
        self.counter['n_examples_skip_large_windows_keep'] += 1

      reads = [x.pad(width) for x in window.reads]
      out = Pileup(self.name, reads, self.layout, overflow=overflow)
      # Same tail padding rules as AlignedRead.pad: strand/sn repeat,
      # ccs_bq pads with -1, everything else pads with zeros.
      chunk = matrix[:, win_start : win_start + window_width]
      if chunk.shape[1] < width:
        data = np.zeros(
            (layout.tensor_height, width), dtype=constants.NP_DATA_TYPE
        )
        data[:, : chunk.shape[1]] = chunk
        data[strand_rows, chunk.shape[1]:] = strand_col[:, None]
        data[sn_rows, chunk.shape[1]:] = sn_col[:, None]
        if layout.use_ccs_bq:
          data[layout.indices('ccs_bq'), chunk.shape[1]:] = -1
      else:
        data = chunk
      out._cached_features = data[:, :, None]
      yield out

  # ------------------------------------------------------------------
  def extract_features(self, min_width: int = 0) -> np.ndarray:
    """Stacks the window into the [rows, width, 1] tensor
    (reference: pre_lib.py:704-744). min_width over-allocates columns
    (zero-filled past the pileup) so the batched window path can
    reshape in place instead of re-copying into a padded buffer."""
    if self._cached_features is not None and not min_width:
      return self._cached_features
    layout = self.layout
    n_subreads = self.n_subreads
    data = np.zeros(
        (layout.tensor_height, max(self.width, min_width)),
        dtype=constants.NP_DATA_TYPE,
    )
    body = data[:, : self.width]
    keep = self.subreads[: layout.max_passes]
    if keep:
      body[layout.indices('bases', n_subreads)] = np.stack(
          [r.bases for r in keep]
      )
      body[layout.indices('pw', n_subreads)] = np.stack([r.pw for r in keep])
      body[layout.indices('ip', n_subreads)] = np.stack([r.ip for r in keep])
      strand_col = np.array([float(int(r.strand)) for r in keep],
                            dtype=constants.NP_DATA_TYPE)
      body[layout.indices('strand', n_subreads)] = strand_col[:, None]
    body[layout.indices('ccs')] = self.ccs.bases
    if layout.use_ccs_bq:
      body[layout.indices('ccs_bq')] = self.ccs.base_quality_scores
    if self.subreads:
      body[layout.indices('sn')] = np.asarray(
          self.subreads[0].sn, dtype=constants.NP_DATA_TYPE
      )[:, None]
    return data[:, :, None]

  def full_matrix(self, min_width: int = 0) -> np.ndarray:
    """Whole-ZMW stacked feature matrix [tensor_height, width].

    Windows are column slices of this matrix (plus padding rules), so
    building it once replaces per-window re-stacking.
    """
    return self.extract_features(min_width)[:, :, 0]

  def iter_window_features(self) -> Iterator[Dict[str, Any]]:
    """Fast inference path: window feature dicts via slices of the
    whole-ZMW matrix. Produces dicts identical to
    iter_windows()+to_features_dict() for inference pileups.
    """
    assert not self.is_training, 'fast path is inference-only'
    self.counter = Counter()
    layout = self.layout
    max_length = layout.max_length
    if self.window_widths is None:
      # Over-allocate to the padded window total up front so the
      # batched branch below reshapes the matrix in place.
      n_batched = (self.ccs_width + max_length - 1) // max_length
      matrix = self.full_matrix(min_width=n_batched * max_length)
    else:
      matrix = self.full_matrix()
    ccs = self.ccs
    ccs_idx = ccs.ccs_idx
    bq = ccs.base_quality_scores
    has_bq = bq.size == len(ccs.bases)  # spaced alongside the pileup

    n_subreads = self.n_subreads
    keep = self.subreads[: layout.max_passes]
    strand_rows = layout.indices('strand', n_subreads)
    sn_rows = layout.indices('sn')
    strand_col = np.array(
        [float(int(r.strand)) for r in keep], dtype=constants.NP_DATA_TYPE
    )
    sn_col = (
        np.asarray(self.subreads[0].sn, dtype=constants.NP_DATA_TYPE)
        if self.subreads else np.zeros(4, dtype=constants.NP_DATA_TYPE)
    )

    if self.window_widths is None:
      # Regular windows are contiguous stride-max_length column slices
      # of the whole-ZMW matrix: build every window with ONE
      # pad+reshape and vectorized per-window metadata instead of
      # ~(ccs_width/100) small-array slice/copy/min calls (the
      # measured host featurization hot spot). Yielded tensors are
      # views into the batched array.
      w = max_length
      n = n_batched
      if n == 0:
        return
      total = n * w
      cols = min(self.width, total)
      # matrix was over-allocated to >= total columns (zero-filled
      # past the pileup); apply the padding rules to the tail in
      # place: strand/sn rows repeat, ccs_bq pads with -1 (see
      # extract_features + AlignedRead.pad).
      padded = matrix[:, :total]
      if cols < total:
        padded[strand_rows, cols:] = strand_col[:, None]
        padded[sn_rows, cols:] = sn_col[:, None]
        if layout.use_ccs_bq:
          padded[layout.indices('ccs_bq'), cols:] = -1
      windows3d = padded.reshape(layout.tensor_height, n, w)

      idx_pad = np.full(total, -1, dtype=np.int64)
      m = min(len(ccs_idx), total)
      idx_pad[:m] = ccs_idx[:m]
      idx_w = idx_pad.reshape(n, w)
      big = np.iinfo(np.int64).max
      window_pos = np.where(idx_w >= 0, idx_w, big).min(axis=1)
      has_cov = window_pos != big

      bq_pad = np.full(total, -1, dtype=np.int64)
      if has_bq:
        m = min(len(bq), total)
        bq_pad[:m] = bq[:m]
      bq_w = bq_pad.reshape(n, w)

      self.counter[f'example_width_bucket_{w}'] += n
      n_cov = int(has_cov.sum())
      if n - n_cov:  # += 0 would still materialize the Counter key
        self.counter['n_examples_no_ccs_idx'] += n - n_cov
      if n_cov:
        self.counter['n_examples_skip_large_windows_keep'] += n_cov
      invariant = {
          'subreads/num_passes': self.keep_subreads,
          'name': self.name,
          'overflow': False,
          'ec': ccs.ec,
          'np_num_passes': ccs.np_num_passes,
          'rq': ccs.rq,
          'rg': ccs.rg,
      }
      for i in range(n):
        if not has_cov[i]:
          continue
        fd = dict(invariant)
        fd['subreads'] = windows3d[:, i, :, None]
        fd['window_pos'] = int(window_pos[i])
        fd['ccs_base_quality_scores'] = bq_w[i]
        yield fd
      return

    start = 0
    for window_width in self.calculate_windows(max_length):
      self.counter[f'example_width_bucket_{window_width}'] += 1
      if start > self.ccs_width:
        break
      sl = slice(start, start + window_width)
      start += window_width
      idx_slice = ccs_idx[sl]
      covered = idx_slice[idx_slice >= 0]
      if covered.size == 0:
        self.counter['n_examples_no_ccs_idx'] += 1
        continue
      width, overflow = bucket_window_width(window_width, layout)
      if overflow:
        self.counter['n_examples_overflow'] += 1
      else:
        self.counter['n_examples_skip_large_windows_keep'] += 1

      chunk = matrix[:, sl]
      if chunk.shape[1] < width:
        data = np.zeros(
            (layout.tensor_height, width), dtype=constants.NP_DATA_TYPE
        )
        data[:, : chunk.shape[1]] = chunk
        # Padding rules: strand/sn rows repeat across the pad; ccs_bq
        # pads with -1 (see extract_features + AlignedRead.pad).
        data[strand_rows, chunk.shape[1] :] = strand_col[:, None]
        data[sn_rows, chunk.shape[1] :] = sn_col[:, None]
        if layout.use_ccs_bq:
          data[layout.indices('ccs_bq'), chunk.shape[1] :] = -1
      else:
        data = np.ascontiguousarray(chunk)

      window_bq = np.full(width, -1, dtype=np.int64)
      if has_bq:
        window_bq[: min(len(bq[sl]), width)] = bq[sl][:width]
      yield {
          'subreads': data[:, :, None],
          'subreads/num_passes': self.keep_subreads,
          'name': self.name,
          'window_pos': int(covered.min()),
          'ccs_base_quality_scores': window_bq,
          'overflow': overflow,
          'ec': ccs.ec,
          'np_num_passes': ccs.np_num_passes,
          'rq': ccs.rq,
          'rg': ccs.rg,
      }

  def to_features_dict(self) -> Dict[str, Any]:
    """Feature dict for the in-memory inference path
    (reference: pre_lib.py:746-762)."""
    return {
        'subreads': self.extract_features(),
        'subreads/num_passes': self.keep_subreads,
        'name': self.name,
        'window_pos': self.ccs.ccs_bounds.start,
        'ccs_base_quality_scores': self.ccs.base_quality_scores,
        'overflow': self.overflow,
        'ec': self.ccs.ec,
        'np_num_passes': self.ccs.np_num_passes,
        'rq': self.ccs.rq,
        'rg': self.ccs.rg,
    }

  def to_example(self) -> Example:
    """Serializable example, wire-compatible with the reference's
    tf.Example schema (reference: pre_lib.py:764-787)."""
    data = self.extract_features()
    ex = Example()
    ex.add_bytes('subreads/encoded', [data.tobytes()])
    ex.add_int64('subreads/shape', list(data.shape))
    ex.add_int64('subreads/num_passes', [self.keep_subreads])
    ex.add_bytes('name', [self.name.encode()])
    ex.add_int64('window_pos', [self.ccs.ccs_bounds.start])
    ex.add_int64(
        'ccs_base_quality_scores', self.ccs.base_quality_scores.tolist()
    )
    if self.is_training:
      label = self.label.bases.astype(constants.NP_DATA_TYPE)
      ex.add_bytes('label/encoded', [label.tobytes()])
      ex.add_int64('label/shape', [label.shape[0]])
    return ex
