"""ZMW stream assembly: subread groups + CCS draft + optional labels.

Equivalent of the reference's create_proc_feeder/subreads_to_dc_example
(reference: deepconsensus/preprocess/pre_lib.py:1279-1384) on top of the
dependency-free BAM reader.
"""
from __future__ import annotations

import logging
from collections import Counter
from typing import Any, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.io import bam
from deepconsensus_tpu.preprocess.alignment import (
    AlignedRead,
    construct_ccs_read,
    expand_aligned_record,
)
from deepconsensus_tpu.preprocess.pileup import FeatureLayout, Pileup
from deepconsensus_tpu.preprocess.spacing import space_out_reads

Issue = constants.Issue

log = logging.getLogger(__name__)


def read_truth_bedfile(truth_bed: str) -> Dict[str, Dict[str, Any]]:
  """ccs_seqname -> {contig, begin, end} (reference: pre_lib.py:1017-1025)."""
  bed_coords = {}
  with open(truth_bed) as bedfile:
    for line in bedfile:
      contig, begin, end, ccs_seqname = line.strip().split('\t')[:4]
      bed_coords[ccs_seqname] = {
          'contig': contig,
          'begin': int(begin),
          'end': int(end),
      }
  return bed_coords


def read_truth_split(split_fname: str) -> Dict[str, str]:
  """contig -> train/eval/test via genome inferred from the filename
  (reference: pre_lib.py:1028-1058)."""
  lower = split_fname.lower()
  if any(x in lower for x in ('chm13', 'hg00', 'human')):
    genome = 'HUMAN'
  elif 'maize' in lower:
    genome = 'MAIZE'
  else:
    raise ValueError(
        f'{split_fname} does not correspond to a known genome; expected the '
        'filename to contain one of chm13/hg00/human/maize'
    )
  split_regions = {}
  for chrom in constants.TRAIN_REGIONS[genome]:
    split_regions[chrom] = 'train'
  for chrom in constants.EVAL_REGIONS[genome]:
    split_regions[chrom] = 'eval'
  for chrom in constants.TEST_REGIONS[genome]:
    split_regions[chrom] = 'test'
  contig_split = {}
  with open(split_fname) as f:
    for line in f:
      contig, chrom = line.split()
      if chrom in split_regions:
        contig_split[contig] = split_regions[chrom]
  return contig_split


def fetch_label_alignment(
    ccs_seqname: str,
    truth_by_ref: Dict[str, List[bam.BamRecord]],
    truth_range: Dict[str, Any],
) -> Union[constants.Issue, AlignedRead]:
  """Expands the truth alignment for one CCS (pre_lib.py:1001-1014)."""
  records = truth_by_ref.get(ccs_seqname)
  if not records:
    return Issue.TRUTH_ALIGNMENT_NOT_FOUND
  truth_alignment = records[0]
  if truth_alignment.is_supplementary:
    return Issue.SUPP_TRUTH_ALIGNMENT
  return expand_aligned_record(truth_alignment, truth_range=truth_range)


ZmwInput = Tuple[List[AlignedRead], str, FeatureLayout, str,
                 Optional[np.ndarray]]


def _fasta_ccs_iter(path: str):
  """Yields pseudo CCS records from a FASTA (no quals/tags), supporting
  the reference's --ccs_fasta input mode."""
  import numpy as np

  from deepconsensus_tpu.io import fastx

  for name, seq in fastx.read_fasta(path).items():
    yield bam.BamRecord(
        qname=name,
        flag=4,
        ref_id=-1,
        pos=0,
        mapq=255,
        cigar_ops=np.empty(0, dtype=np.uint8),
        cigar_lens=np.empty(0, dtype=np.int32),
        seq=seq,
        quals=None,
        tags={},
    )


def create_proc_feeder(
    subreads_to_ccs: str,
    ccs_bam: Optional[str] = None,
    layout: FeatureLayout = None,
    ins_trim: int = 0,
    use_ccs_smart_windows: bool = False,
    truth_bed: Optional[str] = None,
    truth_to_ccs: Optional[str] = None,
    truth_split: Optional[str] = None,
    limit: int = 0,
    ccs_fasta: Optional[str] = None,
    shard: Optional[Tuple[int, int]] = None,
    quarantine=None,
    resume_skip_groups: int = 0,
    max_record_bytes: int = bam.DEFAULT_MAX_RECORD_BYTES,
):
  """Returns (generator_fn, counter) yielding per-ZMW work items.

  shard=(i, n) keeps only ZMWs with zm % n == i — built-in fleet
  scaling over one shared BAM, replacing the reference's external
  500-way BAM-splitting step (docs/quick_start.md:82-99 upstream).

  quarantine (inference.faults.Quarantine, optional) applies the
  --on-zmw-error policy: per-ZMW decode/expansion failures are
  dead-lettered and either skipped or replaced by a CcsFallback item
  (yielded in-stream; callers must dispatch on type). Without it the
  feeder keeps its historical fail-fast behavior.

  resume_skip_groups fast-skips the first N subread groups (no
  expansion work; the lockstep ccs_iter scan self-heals) — the
  --resume path replaying the feeder past already-committed ZMWs.
  """
  main_counter: Counter = Counter()
  # Under a quarantine policy the grouper turns recoverable corrupt
  # records into in-stream CorruptInputError events (handled below)
  # instead of raising; fail-fast runs keep the historical raise.
  grouper = bam.SubreadGrouper(subreads_to_ccs,
                               max_record_bytes=max_record_bytes,
                               skip_corrupt_records=quarantine is not None)
  if ccs_bam:
    ccs_iter = iter(bam.BamReader(ccs_bam,
                                  max_record_bytes=max_record_bytes))
  elif ccs_fasta:
    ccs_iter = _fasta_ccs_iter(ccs_fasta)
  else:
    raise ValueError('need ccs_bam or ccs_fasta')

  is_training = bool(truth_bed and truth_to_ccs and truth_split)
  if is_training:
    truth_by_ref = bam.read_bam_by_name(truth_to_ccs)
    truth_ref_coords = read_truth_bedfile(truth_bed)
    truth_split_dict = read_truth_split(truth_split)

  def proc_feeder() -> Iterator[ZmwInput]:
    groups = iter(grouper)
    last_name: Optional[str] = None
    while True:
      try:
        read_set = next(groups)
      except StopIteration:
        break
      except Exception as e:
        # Stream-level decode failure (truncated/corrupt BGZF or BAM
        # framing): the stream cannot be advanced past it, so record
        # one decode fault and end the feed. Everything already
        # yielded stays valid.
        main_counter['n_zmw_decode_failed'] += 1
        if quarantine is None:
          raise
        quarantine.handle(
            f'<stream after {last_name}>' if last_name else '<stream>',
            'decode', e, fallback=None,
        )
        break
      if isinstance(read_set, bam.CorruptInputError):
        # Recoverable corrupt record: the grouper dropped the affected
        # molecule and kept streaming. Quarantine it (degrades to skip:
        # ccs-fallback would need a trustworthy name to scan the ccs
        # stream for, which a corrupt record cannot provide).
        main_counter['n_corrupt_records'] += 1
        quarantine.handle(
            read_set.zmw or (f'<record after {last_name}>'
                             if last_name else '<record>'),
            'decode', read_set, fallback=None,
        )
        continue
      main_counter['n_zmw_processed'] += 1
      if main_counter['n_zmw_processed'] <= resume_skip_groups:
        main_counter['n_zmw_resume_skipped'] += 1
        continue
      ccs_seqname = read_set[0].reference_name
      last_name = ccs_seqname
      if shard is not None:
        # The lockstep ccs_iter scan below skips over filtered ZMWs'
        # records on its own (both BAMs share the same order), so a
        # sharded-out ZMW costs no expansion work at all.
        try:
          zm = int(ccs_seqname.split('/')[1])
        except (IndexError, ValueError):
          raise ValueError(
              f'shard={shard} requires PacBio movie/zm/ccs read names '
              f'to extract the zm hole number; got {ccs_seqname!r}'
          )
        if zm % shard[1] != shard[0]:
          main_counter['n_zmw_sharded_out'] += 1
          continue
      # Scan for the draft CCS before expanding subreads so a
      # per-ZMW expansion failure still has the draft available for
      # the ccs-fallback policy. The ccs bam is ordered like the
      # subread bam; skip CCS reads with no mapped subreads
      # (reference: pre_lib.py:1320-1326).
      ccs_record = None
      try:
        for candidate in ccs_iter:
          if candidate.qname == ccs_seqname:
            ccs_record = candidate
            break
        else:
          raise ValueError(f'ccs bam does not contain {ccs_seqname}')
        subreads = [
            expand_aligned_record(
                rec, ins_trim=ins_trim, counter=main_counter)
            for rec in read_set
        ]
        ccs_read = construct_ccs_read(ccs_record)
        window_widths = None
        if use_ccs_smart_windows:
          window_widths = np.asarray(ccs_record.get_tag('wl'))
        subreads.append(ccs_read)
      except Exception as e:
        if quarantine is None:
          raise
        record = ccs_record
        fallback = None
        if record is not None:
          def fallback(rec=record):
            from deepconsensus_tpu.inference import faults

            return faults.fallback_from_record(rec)
        item = quarantine.handle(ccs_seqname, 'featurize', e,
                                 fallback=fallback)
        if item is not None:
          yield item
        continue

      if is_training:
        truth_range = truth_ref_coords.get(ccs_seqname)
        if not truth_range:
          log.info('No truth_range defined for %s.', ccs_seqname)
          main_counter['n_zmw_missing_truth_range'] += 1
          continue
        label = fetch_label_alignment(ccs_seqname, truth_by_ref, truth_range)
        if label is Issue.TRUTH_ALIGNMENT_NOT_FOUND:
          log.info('Unable to fetch label alignment for %s.', ccs_seqname)
          main_counter['n_zmw_no_label_alignment'] += 1
          continue
        if label is Issue.SUPP_TRUTH_ALIGNMENT:
          main_counter['n_zmw_truth_label_supp_alignment'] += 1
          continue
        subreads.append(label)
        split = truth_split_dict.get(truth_range['contig'])
        if not split:
          log.info('No split defined for %s.', ccs_seqname)
          main_counter['n_zmw_missing_contig_split'] += 1
          continue
      else:
        split = 'inference'
      main_counter[f'n_zmw_{split}'] += 1
      main_counter['n_zmw_pass'] += 1
      yield (subreads, ccs_seqname, layout, split, window_widths)
      if limit and main_counter['n_zmw_pass'] >= limit:
        break

  return proc_feeder, main_counter


def reads_to_pileup(
    subreads: List[AlignedRead],
    ccs_seqname: str,
    layout: FeatureLayout,
    window_widths: Optional[np.ndarray] = None,
) -> Pileup:
  """Spaces a ZMW's reads into a Pileup (pre_lib.py:1370-1384)."""
  spaced = space_out_reads(subreads)
  return Pileup(
      name=ccs_seqname,
      reads=spaced,
      layout=layout,
      window_widths=window_widths,
  )
