"""A/B the banded-attention implementations across window lengths.

Times, per window length L (constant total tokens B*L):
  * xla      — reference_banded_attention (XLA fuses the dense band)
  * fused    — whole-L VMEM kernel (ops/banded_attention.py)
  * flash    — block-banded flash kernel (ops/flash_band_attention.py)

The flagship pileup window is L=100 where XLA wins (measured 0.82x for
the fused kernel); the flash kernel is the long-window path, where the
dense [L, L] band becomes O(L^2) waste. Prints one JSON line per L so
partial runs (tunnel hangs) keep completed rows.
"""
import argparse
import json
import time


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--tokens', type=int, default=1 << 17,
                  help='total tokens per call: batch = tokens // L')
  ap.add_argument('--heads', type=int, default=2)
  ap.add_argument('--dim', type=int, default=140,
                  help='per-head width (flagship: hidden 280 / 2 heads)')
  ap.add_argument('--win', type=int, default=12)
  ap.add_argument('--lengths', type=int, nargs='+',
                  default=[100, 256, 512, 1024, 2048, 4096])
  ap.add_argument('--iters', type=int, default=20)
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  import jax

  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import numpy as np
  from deepconsensus_tpu.ops import banded_attention as ba
  from deepconsensus_tpu.ops import flash_band_attention as fba

  def timed(fn, q, k, v):
    out = fn(q, k, v)
    np.asarray(out)
    t0 = time.perf_counter()
    for i in range(args.iters):
      out = fn(q.at[0, 0, 0, 0].set(float(i)), k, v)
    np.asarray(out)
    return (time.perf_counter() - t0) / args.iters

  for l in args.lengths:
    b = max(1, args.tokens // l)
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(
        rng.normal(size=(b, l, args.heads, args.dim)).astype(np.float32)
    ).astype(jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    row = {'L': l, 'batch': b, 'tokens': b * l}
    impls = {
        'xla': jax.jit(
            lambda q, k, v: ba.reference_banded_attention(q, k, v, args.win)
        ),
        'flash': jax.jit(
            lambda q, k, v: fba.flash_band_attention(q, k, v, args.win)
        ),
    }
    if l <= 512:  # whole-L kernel: [G, L, L] must fit VMEM
      impls['fused'] = jax.jit(
          lambda q, k, v: ba.banded_attention(q, k, v, args.win)
      )
    for name, fn in impls.items():
      try:
        dt = timed(fn, q, k, v)
        row[f'{name}_us'] = round(dt * 1e6, 1)
        row[f'{name}_tokens_per_s'] = round(b * l / dt)
      except Exception as e:
        row[f'{name}_error'] = repr(e)[:120]
    if 'xla_us' in row and 'flash_us' in row:
      row['flash_speedup_vs_xla'] = round(row['xla_us'] / row['flash_us'], 3)
    print(json.dumps(row), flush=True)


if __name__ == '__main__':
  main()
