"""Streaming-loader throughput: native BGZF decode vs pure Python,
serial vs workers (VERDICT r3 #6).

Measures StreamingDataset examples/s over real shards for each
(native, workers) combination, back-to-back in one process so numbers
are comparable. The dp=8 feeding target on a many-core host is
~12k ex/s (8 chips x ~1.5k ex/s at b1024); on this 1-core build host
the interesting numbers are the serial per-core ceiling and the
native-vs-Python decode ratio. Prints one JSON line per combination.

Shards written by `dctpu preprocess` are BGZF-framed by default, which
is what the native path parallelizes; point --pattern at gzip shards
to see the serial-native fallback.
"""
import argparse
import itertools
import json
import os
import sys
import time


def measure(pattern, params, batch_size, workers, n_batches, native):
  env_before = os.environ.get('DC_TPU_NO_NATIVE')
  os.environ['DC_TPU_NO_NATIVE'] = '' if native else '1'
  it = None
  try:
    from deepconsensus_tpu.models.data import StreamingDataset

    ds = StreamingDataset(
        pattern, params, batch_size=batch_size,
        buffer_size=4 * batch_size, workers=workers, seed=0)
    it = iter(ds)
    # Warmup: first batches pay buffer fill + (native) first-shard
    # decode + (workers) process spawn.
    for _ in itertools.islice(it, 3):
      pass
    t0 = time.perf_counter()
    n = sum(1 for _ in itertools.islice(it, n_batches))
    dt = time.perf_counter() - t0
    # Per-worker decode counters (n_parsed_worker_N): the split across
    # workers is the evidence for any linear-scaling extrapolation.
    per_worker = {
        k: v for k, v in sorted(ds.counters.items())
        if k.startswith('n_parsed_worker_')
    }
    return n * batch_size / dt, per_worker
  finally:
    if it is not None:
      # Deterministic worker teardown: on this 1-core host a previous
      # leg's lingering workers would skew the next leg's numbers.
      it.close()
    if env_before is None:
      os.environ.pop('DC_TPU_NO_NATIVE', None)
    else:
      os.environ['DC_TPU_NO_NATIVE'] = env_before


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--pattern', default='/root/data_r4/examples/train/*')
  ap.add_argument('--batch_size', type=int, default=256)
  ap.add_argument('--n_batches', type=int, default=40)
  ap.add_argument('--workers', type=int, nargs='+', default=[0, 2, 3])
  ap.add_argument('--synth_dir', default='/tmp/dctpu_loader_synth',
                  help='where the synthetic-shard fallback lands when '
                  '--pattern matches nothing')
  ap.add_argument('--synth_shards', type=int, default=6)
  ap.add_argument('--synth_examples', type=int, default=2000,
                  help='examples per synthetic shard')
  args = ap.parse_args()

  import jax

  jax.config.update('jax_platforms', 'cpu')  # loader is host-only
  from deepconsensus_tpu.models import config as config_lib

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)

  from deepconsensus_tpu import native as native_lib
  from deepconsensus_tpu.io.tfrecord import glob_paths

  if not glob_paths(args.pattern):
    # Hosts without real preprocessed shards fall back to synthetic
    # production-shape shards (rows (85, 100, 1)) — decode cost per
    # record is representative; the content is noise. Reused across
    # runs when the directory already holds the requested shard count.
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from scripts.inject_faults import write_synthetic_tfrecords

    existing = glob_paths(os.path.join(args.synth_dir, '*'))
    if len(existing) != args.synth_shards:
      os.makedirs(args.synth_dir, exist_ok=True)
      for old in existing:
        os.remove(old)
      write_synthetic_tfrecords(
          args.synth_dir, n_shards=args.synth_shards,
          n_examples=args.synth_examples,
          max_passes=params.max_passes, max_length=params.max_length)
    args.pattern = os.path.join(args.synth_dir, '*')
    print(json.dumps({'synthetic_shards': args.pattern,
                      'n_shards': args.synth_shards,
                      'examples_per_shard': args.synth_examples}),
          flush=True)

  n_shards = len(glob_paths(args.pattern))
  native_available = native_lib.get_lib() is not None

  seen = set()
  for workers in args.workers:
    # StreamingDataset clamps workers to the shard count; dedupe so the
    # sweep never prints the same effective configuration under two
    # labels (a fake scaling plateau).
    effective_workers = min(workers, n_shards) if workers else 0
    for native in (False, True):
      if native and not native_available:
        print(json.dumps({
            'workers': effective_workers, 'native_decode': True,
            'error': 'native library unavailable; leg skipped '
                     '(A/B would silently measure Python twice)',
        }), flush=True)
        continue
      if (effective_workers, native) in seen:
        continue
      seen.add((effective_workers, native))
      try:
        ex_s, per_worker = measure(args.pattern, params, args.batch_size,
                                   effective_workers, args.n_batches,
                                   native)
        line = {
            'workers': effective_workers,
            'requested_workers': workers,
            'n_shards': n_shards,
            'native_decode': native,
            'examples_per_sec': round(ex_s, 1),
            'cores': os.cpu_count(),
            'batch_size': args.batch_size,
        }
        if per_worker:
          line['per_worker_parsed'] = per_worker
          counts = list(per_worker.values())
          # min/max balance of the decode split: ~1.0 means the load
          # divides evenly and worker-count extrapolation is sound.
          line['worker_balance'] = round(min(counts) / max(counts), 3)
        print(json.dumps(line), flush=True)
      except Exception as e:  # pragma: no cover
        print(json.dumps({
            'workers': effective_workers, 'native_decode': native,
            'error': repr(e)[:200],
        }), flush=True)
  return 0


if __name__ == '__main__':
  raise SystemExit(main())
