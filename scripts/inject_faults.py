#!/usr/bin/env python3
"""Fault-injection harness for the fault-tolerance layers.

Inference-side tools:

* synth    — write a synthetic (subreads_to_ccs.bam, ccs.bam) pair with
             deterministic sequences, one BGZF block per ZMW so a
             truncation lands mid-file rather than killing block 0.
* corrupt  — re-encode a subreads BAM dropping aux tags (default: pw)
             from one target ZMW, which makes expand_aligned_record
             raise for exactly that molecule (a featurize-stage fault).
* truncate — chop a file to a fraction/byte count, producing a
             mid-stream BGZF decode fault (decode-stage).
* fuzz     — deterministic mutational fuzzer: bit flips, truncations,
             length-field inflation, CRC/zero-run corruption over a
             seed file, one mutant file per (seed, index). Drives
             tests/test_io_fuzz.py's decode-layer invariant.
* corrupt_record — surgically corrupt ONE record of a BAM at the
             uncompressed layer (l_read_name, cigar count, block_size)
             and re-BGZF it: record-body modes leave the framing
             intact, so the hardened reader quarantines exactly that
             molecule and keeps going.

Training-side tools:

* synth_tfrecords — write synthetic training TFRecord shards (the
             pileup-tensor + label examples models/data.py consumes),
             so resilience tests need no reference testdata.
* corrupt_ckpt — truncate a checkpoint's largest array file (size
             mismatch vs the integrity manifest) or delete its
             manifest (simulates a save that never committed).

Serve-side tools (`dctpu serve` robustness drills):

* serve_client — adversarial clients against a running daemon:
             disconnect (claim full length, send half, RST),
             garbage (well-framed HTTP, non-npz body), oversized
             (absurd Content-Length, no body), slowloris (drip one
             byte per interval). The daemon must shed each with a
             typed rejection while concurrent well-formed clients
             keep completing.
* preempt  — cloud-preemption drill against a running replica pid:
             deliver the preemption notice (SIGUSR1 — the replica
             flips to draining and finishes admitted work), then
             SIGKILL after the provider's grace deadline if it is
             still alive. A drain-clean replica exits 0 before the
             kill lands; with `dctpu autoscale` watching the fleet,
             capacity is replaced while the victim drains.

Worker SIGKILL, NaN-batch, preemption-signal, consumer-crash, poison
window, and client self-sabotage injection are driven by env vars read
by deepconsensus_tpu/faults.py; this script documents them in --help.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepconsensus_tpu.io import bam as bam_lib  # noqa: E402
from deepconsensus_tpu.io.bam_writer import BamWriter  # noqa: E402

_BASES = np.frombuffer(b'ACGT', dtype=np.uint8)


def write_synthetic_zmw_bams(
    out_dir: str,
    n_zmws: int = 6,
    n_subreads: int = 3,
    seq_len: int = 120,
    movie: str = 'm00001_000000_000000',
    seed: int = 7,
    base_qual: int = 30,
    plain_names: bool = False,
) -> Tuple[str, str]:
  """Writes (subreads_to_ccs.bam, ccs.bam) for n_zmws molecules.

  Subreads are exact copies of the draft CCS (all-match cigar) with
  deterministic pw/ip/sn tags, grouped per ZMW and flushed into their
  own BGZF block so truncate() faults mid-file. The ccs BAM carries
  quals=base_qual and ec/np/rq/RG tags. plain_names drops the PacBio
  movie/zmw/ccs structure (exercises the defensive zm-tag parse).
  """
  rng = np.random.RandomState(seed)
  os.makedirs(out_dir, exist_ok=True)
  subreads_path = os.path.join(out_dir, 'subreads_to_ccs.bam')
  ccs_path = os.path.join(out_dir, 'ccs.bam')

  zmw_ids = [100 + i for i in range(n_zmws)]
  if plain_names:
    ccs_names = [f'read{z}' for z in zmw_ids]
  else:
    ccs_names = [f'{movie}/{z}/ccs' for z in zmw_ids]
  seqs = [
      bytes(_BASES[rng.randint(0, 4, seq_len)]).decode('ascii')
      for _ in zmw_ids
  ]

  sub_writer = BamWriter(
      subreads_path,
      header_text='@HD\tVN:1.5\tSO:unknown\n',
      references=[(name, seq_len) for name in ccs_names],
  )
  for i, (zmw, seq) in enumerate(zip(zmw_ids, seqs)):
    for k in range(n_subreads):
      if plain_names:
        qname = f'sub{zmw}_{k}'
      else:
        qname = f'{movie}/{zmw}/{k * 1000}_{k * 1000 + seq_len}'
      tags = {
          'zm': zmw,
          'pw': rng.randint(1, 6, seq_len).astype(np.int32),
          'ip': rng.randint(1, 9, seq_len).astype(np.int32),
          'sn': rng.uniform(4.0, 12.0, 4).astype(np.float32),
      }
      sub_writer.write(
          qname, seq, None, tags=tags, flag=0, ref_id=i, pos=0,
          cigar=[(0, seq_len)],
      )
    # One BGZF block per ZMW: a later truncate() then faults mid-file
    # instead of corrupting the first group.
    sub_writer.flush()
  sub_writer.close()

  ccs_writer = BamWriter(
      ccs_path,
      header_text='@HD\tVN:1.5\tSO:unknown\n'
      '@RG\tID:rg1\tPL:PACBIO\tSM:synthetic\n',
  )
  for name, seq in zip(ccs_names, seqs):
    ccs_writer.write(
        name, seq, np.full(seq_len, base_qual, dtype=np.uint8),
        tags={
            'ec': float(n_subreads),
            'np': int(n_subreads),
            'rq': 0.99,
            'RG': 'rg1',
        },
        flag=4,
    )
    ccs_writer.flush()
  ccs_writer.close()
  return subreads_path, ccs_path


def corrupt_zmw(
    in_bam: str,
    out_bam: str,
    zmw: int,
    drop_tags: Sequence[str] = ('pw',),
) -> int:
  """Re-encodes in_bam with drop_tags removed from records of one ZMW.

  Dropping 'pw' makes expand_aligned_record raise KeyError('pw') for
  exactly that molecule — the canonical per-ZMW featurize fault.
  Returns the number of corrupted records.
  """
  reader = bam_lib.BamReader(in_bam)
  # Our reader ignores declared reference lengths; 0 keeps the header
  # faithful enough for round-tripping.
  writer = BamWriter(
      out_bam,
      header_text=reader.header_text,
      references=[(name, 0) for name in reader.references],
  )
  n_corrupted = 0
  for rec in reader:
    tags = dict(rec.tags)
    if int(tags.get('zm', -1)) == zmw:
      for tag in drop_tags:
        tags.pop(tag, None)
      n_corrupted += 1
    writer.write(
        rec.qname, rec.seq, rec.quals, tags=tags, flag=rec.flag,
        ref_id=rec.ref_id, pos=rec.pos,
        cigar=list(zip(rec.cigar_ops.tolist(), rec.cigar_lens.tolist())),
    )
  writer.close()
  return n_corrupted


def truncate_file(path: str, fraction: float = 0.5,
                  keep_bytes: Optional[int] = None) -> int:
  """Truncates path mid-stream; returns the new size."""
  size = os.path.getsize(path)
  keep = keep_bytes if keep_bytes is not None else max(1, int(size * fraction))
  with open(path, 'r+b') as f:
    f.truncate(keep)
  return keep


def write_synthetic_tfrecords(
    out_dir: str,
    n_shards: int = 2,
    n_examples: int = 64,
    max_passes: int = 5,
    max_length: int = 20,
    seed: int = 3,
    compression: str = 'BGZF',
) -> List[str]:
  """Writes synthetic training shards shard-NNNNN.tfrecord.gz.

  Examples carry the fields models/data.py parses (subreads tensor of
  shape (4*max_passes+5, max_length, 1) for use_ccs_bq=False, label of
  shape (max_length,), plus name/num_passes/window_pos/quality for the
  full parse path). Content is drawn so training is well-posed: bases,
  ccs, and label agree per column, so a tiny model reaches a finite,
  decreasing loss. Examples are spread round-robin over n_shards.
  Returns the shard paths.
  """
  from deepconsensus_tpu.io.example_proto import Example
  from deepconsensus_tpu.io.tfrecord import TFRecordWriter

  rng = np.random.RandomState(seed)
  os.makedirs(out_dir, exist_ok=True)
  total_rows = 4 * max_passes + 5
  paths = [
      os.path.join(out_dir, f'shard-{i:05d}.tfrecord.gz')
      for i in range(n_shards)
  ]
  writers = [TFRecordWriter(p, compression=compression) for p in paths]
  for i in range(n_examples):
    seq = rng.randint(1, 5, size=max_length)  # vocab ' ATCG' -> 1..4
    subreads = np.zeros((total_rows, max_length, 1), dtype=np.float32)
    for p in range(max_passes):
      subreads[p, :, 0] = seq                      # bases
      subreads[max_passes + p, :, 0] = rng.randint(1, 5, max_length)  # pw
      subreads[2 * max_passes + p, :, 0] = rng.randint(1, 9, max_length)
      subreads[3 * max_passes + p, :, 0] = 1 + (p % 2)  # strand
    subreads[4 * max_passes, :, 0] = seq             # ccs row
    subreads[4 * max_passes + 1:, :, 0] = rng.uniform(
        4.0, 12.0, size=(4, 1)
    )                                                # sn rows
    label = seq.astype(np.float32)
    ex = Example()
    ex.add_bytes('subreads/encoded',
                 [subreads.astype(np.float32).tobytes()])
    ex.add_int64('subreads/shape', list(subreads.shape))
    ex.add_bytes('label/encoded', [label.tobytes()])
    ex.add_int64('label/shape', [max_length])
    ex.add_bytes('name', [f'syn/{100 + i}/ccs-{i}'.encode('ascii')])
    ex.add_int64('subreads/num_passes', [max_passes])
    ex.add_int64('window_pos', [i * max_length])
    ex.add_int64('ccs_base_quality_scores', [30] * max_length)
    writers[i % n_shards].write(ex.serialize())
  for w in writers:
    w.close()
  return paths


# ----------------------------------------------------------------------
# Mutational fuzzing (tests/test_io_fuzz.py)

FUZZ_MODES = ('bitflip', 'truncate', 'length_inflate', 'crc_corrupt',
              'zero_run')


def fuzz_mutants(src: bytes, n_mutants: int, seed: int = 0,
                 protect_prefix: int = 0,
                 modes: Sequence[str] = FUZZ_MODES):
  """Yields (index, mode, mutated_bytes) — deterministic in (seed, src).

  Mutation classes mirror how real inputs rot: random bit flips
  (storage/transfer), tail truncation (interrupted upload), inflated
  little-endian length fields (the classic resource-exhaustion vector),
  footer-area byte smashes (CRC corruption), and zero runs (sparse-file
  holes). protect_prefix shields the first N bytes so corpora can keep
  e.g. a magic number intact and exercise deeper parse layers.
  """
  rng = np.random.RandomState(seed)
  n = len(src)
  if n < 2 or protect_prefix >= n - 1:
    raise ValueError('source corpus too small to fuzz')
  lo = protect_prefix
  for i in range(n_mutants):
    mode = modes[rng.randint(len(modes))]
    buf = bytearray(src)
    if mode == 'bitflip':
      for _ in range(rng.randint(1, 9)):
        buf[rng.randint(lo, n)] ^= 1 << rng.randint(8)
    elif mode == 'truncate':
      buf = buf[:rng.randint(lo + 1, n)]
    elif mode == 'length_inflate':
      pos = rng.randint(lo, max(lo + 1, n - 4))
      huge = int(rng.choice([1 << 24, 1 << 30, 0x7FFFFFFF, 0xFFFFFFFF]))
      buf[pos:pos + 4] = huge.to_bytes(4, 'little')
    elif mode == 'crc_corrupt':
      # CRCs live near frame/file tails; smash a byte in the last 64.
      pos = rng.randint(max(lo, n - 64), n)
      buf[pos] ^= 0xFF
    elif mode == 'zero_run':
      pos = rng.randint(lo, n)
      run = rng.randint(1, min(256, n - pos) + 1)
      buf[pos:pos + run] = b'\x00' * run
    else:
      raise ValueError(f'unknown fuzz mode {mode!r}')
    yield i, mode, bytes(buf)


def write_fuzz_corpus(src_path: str, out_dir: str, n_mutants: int,
                      seed: int = 0, protect_prefix: int = 0) -> List[str]:
  """Materializes fuzz_mutants() of one file as mutant-NNNNN-<mode>."""
  with open(src_path, 'rb') as f:
    src = f.read()
  os.makedirs(out_dir, exist_ok=True)
  paths = []
  for i, mode, data in fuzz_mutants(src, n_mutants, seed=seed,
                                    protect_prefix=protect_prefix):
    path = os.path.join(out_dir, f'mutant-{i:05d}-{mode}')
    with open(path, 'wb') as f:
      f.write(data)
    paths.append(path)
  return paths


BAM_RECORD_MODES = ('read_name_zero', 'read_name_overrun', 'cigar_overrun',
                    'block_size_inflate')


def corrupt_bam_record(in_bam: str, out_bam: str, record_index: int,
                       mode: str = 'read_name_zero') -> int:
  """Corrupts exactly one record of a BAM at the uncompressed layer.

  Decompresses the BGZF stream, walks the header + record frames to the
  record_index'th record, damages it, and re-BGZFs the stream (valid
  blocks + EOF marker — the compressed container stays pristine, so the
  damage tests the RECORD decoder, not the gzip layer). Record-body
  modes (read_name_zero/read_name_overrun/cigar_overrun) keep the
  block_size framing intact: the hardened reader raises a recoverable
  CorruptInputError and can keep streaming. block_size_inflate breaks
  the framing itself (stream-level fault). Returns the decompressed
  byte offset of the corrupted record.
  """
  from deepconsensus_tpu.io.bam_writer import BgzfWriter

  raw = bytearray(bam_lib.bgzf_decompress_file_py(in_bam))
  if raw[:4] != b'BAM\x01':
    raise ValueError(f'{in_bam}: not a BAM file')
  (l_text,) = np.frombuffer(raw[4:8], dtype='<i4')
  pos = 8 + int(l_text)
  (n_ref,) = np.frombuffer(raw[pos:pos + 4], dtype='<i4')
  pos += 4
  for _ in range(int(n_ref)):
    (l_name,) = np.frombuffer(raw[pos:pos + 4], dtype='<i4')
    pos += 4 + int(l_name) + 4
  index = 0
  while pos < len(raw):
    (block_size,) = np.frombuffer(raw[pos:pos + 4], dtype='<i4')
    if index == record_index:
      body = pos + 4
      if mode == 'read_name_zero':
        raw[body + 8] = 0
      elif mode == 'read_name_overrun':
        raw[body + 8] = 0xFF
      elif mode == 'cigar_overrun':
        raw[body + 12:body + 14] = (0xFFFF).to_bytes(2, 'little')
      elif mode == 'block_size_inflate':
        raw[pos:pos + 4] = (1 << 30).to_bytes(4, 'little')
      else:
        raise ValueError(f'unknown corrupt_bam_record mode {mode!r}')
      writer = BgzfWriter(out_bam)
      writer.write(bytes(raw))
      writer.close()
      return pos
    pos += 4 + int(block_size)
    index += 1
  raise IndexError(
      f'{in_bam}: record_index {record_index} out of range ({index} records)')


def corrupt_checkpoint(ckpt_path: str, mode: str = 'truncate',
                       fraction: float = 0.5) -> str:
  """Corrupts one orbax checkpoint directory. Returns the path acted on.

  * truncate: chops the largest file under the directory — the
    integrity manifest's size inventory then disagrees, so
    latest_valid_checkpoint quarantines the directory.
  * delete-manifest: removes the committed manifest — indistinguishable
    from a save that never finished.
  """
  from deepconsensus_tpu.models import checkpoints as ckpt_lib

  if mode == 'delete-manifest':
    manifest = ckpt_lib.manifest_path(ckpt_path)
    os.unlink(manifest)
    return manifest
  if mode != 'truncate':
    raise ValueError(f'unknown corrupt_checkpoint mode {mode!r}')
  largest, largest_size = None, -1
  for root, _, files in os.walk(ckpt_path):
    for name in files:
      full = os.path.join(root, name)
      size = os.path.getsize(full)
      if size > largest_size:
        largest, largest_size = full, size
  if largest is None:
    raise FileNotFoundError(f'no files under {ckpt_path!r}')
  truncate_file(largest, fraction=fraction)
  return largest


def preempt_replica(pid: int, grace_s: float = 30.0,
                    poll_interval_s: float = 0.2,
                    is_alive=None) -> Dict[str, Any]:
  """Cloud-preemption drill: SIGUSR1 notice now, SIGKILL after the
  grace deadline if the process is still alive. A well-behaved replica
  (serve/server.py _PreemptionWatch) drains and exits inside the
  grace window, so the kill never fires. `is_alive` defaults to an
  os.kill(pid, 0) liveness probe; a caller that owns the Popen should
  pass `lambda: proc.poll() is None` so zombies count as exited."""
  if is_alive is None:
    def is_alive():
      try:
        os.kill(pid, 0)
        return True
      except ProcessLookupError:
        return False
  t0 = time.monotonic()
  os.kill(pid, signal.SIGUSR1)
  while time.monotonic() - t0 < grace_s:
    if not is_alive():
      return {'pid': pid, 'noticed': True, 'killed': False,
              'waited_s': round(time.monotonic() - t0, 3)}
    time.sleep(poll_interval_s)
  killed = True
  try:
    os.kill(pid, signal.SIGKILL)
  except ProcessLookupError:
    killed = False  # exited right at the deadline
  return {'pid': pid, 'noticed': True, 'killed': killed,
          'waited_s': round(time.monotonic() - t0, 3)}


def main(argv: Optional[List[str]] = None) -> int:
  parser = argparse.ArgumentParser(
      description=__doc__,
      formatter_class=argparse.RawDescriptionHelpFormatter,
      epilog=(
          'Env-var hooks (read by deepconsensus_tpu/faults.py):\n'
          '  DCTPU_FAULT_KILL_ZMW=<ccs name>   SIGKILL the pool worker '
          'featurizing that ZMW\n'
          '  DCTPU_FAULT_KILL_TOKEN=<path>     kill only once (token '
          'file created on first kill)\n'
          '  DCTPU_FAULT_CRASH_AFTER_BATCHES=N crash the consumer loop '
          'after N batches\n'
          '  DCTPU_FAULT_NAN_AT_STEP=N         poison the training batch '
          'consumed at step N with NaNs (fires once per process)\n'
          '  DCTPU_FAULT_SIGTERM_AT_STEP=N     deliver SIGTERM to the '
          'trainer after step N (preemption drill, fires once)\n'
          '  DCTPU_FAULT_KILL_TRAIN_AT_STEP=N  SIGKILL the trainer after '
          'step N (token-gated: fires once across restarts)\n'
          '  DCTPU_FAULT_KILL_SHARD_READER=<substr>  SIGKILL the shard '
          'reader that opens a shard path containing substr '
          '(token-gated)\n'
          '  DCTPU_FAULT_POISON_WINDOW=<substr>  `dctpu serve`: a '
          'request whose ZMW name contains substr carries a poison '
          'window that fails its model pack (and its isolation retry) '
          '-> quarantine with request attribution\n'
          '  DCTPU_FAULT_SERVE_CLIENT=<mode>   ServeClient.polish() '
          'misbehaves on the wire instead of sending (modes: '
          'disconnect, garbage, oversized, slowloris)\n'
          '  DCTPU_FAULT_SERVE_CLIENT_ZMW=<substr>  scope the client '
          'sabotage to molecules whose name contains substr\n'
          '  DCTPU_FAULT_DEVICE_OOM_AT_PACK=N  raise RESOURCE_EXHAUSTED '
          'inside the launch of the Nth dispatched pack (1-based; '
          'fires once) — --on_device_error=degrade bisects it\n'
          '  DCTPU_FAULT_DEVICE_LOST_AT_PACK=N raise a halted-device '
          'error at the Nth pack — degrade rebuilds the mesh one dp '
          'step down and resubmits\n'
          '  DCTPU_FAULT_DEVICE_LOST_AT_STEP=N raise a halted-device '
          'error at the Nth TRAINING step (1-based; fires once) — '
          '`dctpu train --on_device_error=degrade` rebuilds the mesh '
          'one dp step down, re-places the live state, and re-runs '
          'the failed batch\n'
          '  DCTPU_FAULT_DEVICE_HANG_AT_PACK=N hang the Nth pack\'s '
          'finalize so the --dispatch_timeout watchdog must fire\n'
          '  DCTPU_FAULT_DEVICE_HANG_S=<secs>  hang duration for '
          'HANG_AT_PACK (default 30)\n'
          '  DCTPU_FAULT_PREEMPT_AT_S=<secs>   `dctpu serve`: the '
          'replica delivers itself a preemption notice N seconds '
          'after start — /readyz flips to 503 draining, admitted work '
          'finishes, clean exit with preempted=true (same path as an '
          'external SIGUSR1 / `preempt` below)\n'
          '  DCTPU_FAULT_HOST_LOST_AT_STEP=N   elastic training: this '
          'host dies at the Nth step (1-based, fires once) — '
          'survivors hit a bounded barrier timeout, name the missing '
          'host in HostLostError, and (with --on_host_error=degrade) '
          'rebuild the pod and keep training\n'
          '  DCTPU_FAULT_HOST_LOST_HOST=<id>   scope HOST_LOST to one '
          'pod host id (default: every host)\n'
          '  DCTPU_FAULT_HOST_LOST_MODE=<m>    kill (default): '
          'SIGKILL the process, the hard drill; drop: leave the '
          'heartbeat thread running but abandon the barriers, the '
          'zombie-host drill\n'
          '  DCTPU_FAULT_HOST_REJOIN_AT_STEP=N a restarted host '
          'defers its join request until the pod reaches step N '
          '(1-based) — paces re-admission drills\n'
          '  DCTPU_FAULT_FLYWHEEL_KILL_AT_STAGE=<train|distill|gates|'
          'export>  SIGKILL `dctpu flywheel` right after the named '
          'stage commits its `running` journal entry — the '
          'worst-timed stage-boundary crash (consume-once per '
          'process; honors DCTPU_FAULT_KILL_TOKEN so a --resume '
          'rerun under the same env completes)\n'
      ),
  )
  sub = parser.add_subparsers(dest='command', required=True)

  p = sub.add_parser('synth', help='Write synthetic subreads/ccs BAMs.')
  p.add_argument('--out_dir', required=True)
  p.add_argument('--n_zmws', type=int, default=6)
  p.add_argument('--n_subreads', type=int, default=3)
  p.add_argument('--seq_len', type=int, default=120)
  p.add_argument('--seed', type=int, default=7)
  p.add_argument('--base_qual', type=int, default=30)
  p.add_argument('--plain_names', action='store_true')

  p = sub.add_parser('corrupt', help='Drop aux tags from one ZMW.')
  p.add_argument('--in_bam', required=True)
  p.add_argument('--out_bam', required=True)
  p.add_argument('--zmw', type=int, required=True)
  p.add_argument('--drop_tag', action='append', default=None,
                 help='Tag to drop (repeatable; default pw).')

  p = sub.add_parser('truncate', help='Truncate a file mid-stream.')
  p.add_argument('--path', required=True)
  p.add_argument('--fraction', type=float, default=0.5)
  p.add_argument('--bytes', type=int, default=None, dest='keep_bytes')

  p = sub.add_parser('fuzz', help='Write a deterministic mutant corpus.')
  p.add_argument('--src', required=True, help='Seed file to mutate.')
  p.add_argument('--out_dir', required=True)
  p.add_argument('--n', type=int, default=100)
  p.add_argument('--seed', type=int, default=0)
  p.add_argument('--protect_prefix', type=int, default=0,
                 help='Shield the first N bytes from mutation.')

  p = sub.add_parser('corrupt_record',
                     help='Corrupt one BAM record at the uncompressed '
                     'layer (framing-intact or framing-breaking).')
  p.add_argument('--in_bam', required=True)
  p.add_argument('--out_bam', required=True)
  p.add_argument('--record', type=int, required=True)
  p.add_argument('--mode', choices=BAM_RECORD_MODES,
                 default='read_name_zero')

  p = sub.add_parser('synth_tfrecords',
                     help='Write synthetic training TFRecord shards.')
  p.add_argument('--out_dir', required=True)
  p.add_argument('--n_shards', type=int, default=2)
  p.add_argument('--n_examples', type=int, default=64)
  p.add_argument('--max_passes', type=int, default=5)
  p.add_argument('--max_length', type=int, default=20)
  p.add_argument('--seed', type=int, default=3)

  p = sub.add_parser('corrupt_ckpt',
                     help='Truncate or un-commit a checkpoint directory.')
  p.add_argument('--ckpt', required=True,
                 help='Path to one checkpoint-N directory.')
  p.add_argument('--mode', choices=('truncate', 'delete-manifest'),
                 default='truncate')
  p.add_argument('--fraction', type=float, default=0.5)

  p = sub.add_parser('device',
                     help='Arm a device-fault hook (OOM / lost / hang '
                     'at a pack ordinal) and optionally exec a command '
                     'under it.')
  p.add_argument('--fault', required=True, choices=('oom', 'lost', 'hang'))
  p.add_argument('--pack', type=int, default=1,
                 help='1-based dispatch ordinal of the targeted pack.')
  p.add_argument('--step', type=int, default=None,
                 help='lost only: arm the TRAINING hook instead — the '
                 'device is lost at this 1-based train step ('
                 '`dctpu train --on_device_error=degrade` steps the '
                 'mesh one dp down and keeps training).')
  p.add_argument('--hang_s', type=float, default=30.0,
                 help='hang: seconds the finalize sleeps (pair with '
                 '--dispatch_timeout below it).')
  p.add_argument('cmd', nargs=argparse.REMAINDER,
                 help='Command to exec with the hook armed; without '
                 'one, print the env assignments to eval.')

  p = sub.add_parser('host',
                     help='Arm an elastic host-fault hook (die at a '
                     'train step, optionally scoped to one host / '
                     'deferred rejoin) and optionally exec a command '
                     'under it.')
  p.add_argument('--lost_at_step', type=int, default=None,
                 help='1-based train step at which the host dies '
                 '(fires once per process).')
  p.add_argument('--host', type=int, default=None,
                 help='Pod host id to kill (default: every host that '
                 'reaches the step).')
  p.add_argument('--mode', choices=('kill', 'drop'), default='kill',
                 help='kill: SIGKILL the process (hard drill). '
                 'drop: abandon the pod barriers but keep the '
                 'process alive (zombie-host drill).')
  p.add_argument('--rejoin_at_step', type=int, default=None,
                 help='Defer a restarted host\'s join request until '
                 'the pod reaches this 1-based step.')
  p.add_argument('cmd', nargs=argparse.REMAINDER,
                 help='Command to exec with the hook armed; without '
                 'one, print the env assignments to eval.')

  p = sub.add_parser('flywheel',
                     help='Arm the flywheel stage-boundary kill hook '
                     '(SIGKILL right after the named stage commits '
                     'its `running` journal entry) and optionally '
                     'exec a command under it.')
  p.add_argument('--kill_at_stage', required=True,
                 choices=('train', 'distill', 'gates', 'export'))
  p.add_argument('--kill_token', default=None,
                 help='Token file path: the kill fires only once '
                 'across restarts, so a --resume rerun under the '
                 'same env completes.')
  p.add_argument('cmd', nargs=argparse.REMAINDER,
                 help='Command to exec with the hook armed; without '
                 'one, print the env assignments to eval.')

  p = sub.add_parser('preempt',
                     help='Preemption notice (SIGUSR1) to a replica '
                     'pid, then SIGKILL after the grace deadline if '
                     'it is still alive.')
  p.add_argument('--pid', type=int, required=True)
  p.add_argument('--grace_s', type=float, default=30.0,
                 help='Provider grace window between notice and hard '
                 'kill.')

  p = sub.add_parser('serve_client',
                     help='Adversarial client against a running '
                     '`dctpu serve` daemon.')
  p.add_argument('--host', default='127.0.0.1')
  p.add_argument('--port', type=int, default=8764)
  p.add_argument('--mode', required=True,
                 choices=('disconnect', 'garbage', 'oversized',
                          'slowloris'))
  p.add_argument('--n', type=int, default=1, help='Repeat count.')
  p.add_argument('--duration_s', type=float, default=30.0,
                 help='slowloris: how long to keep dripping.')
  p.add_argument('--interval_s', type=float, default=0.5,
                 help='slowloris: seconds between dripped bytes.')

  args = parser.parse_args(argv)
  if args.command == 'synth':
    subreads, ccs = write_synthetic_zmw_bams(
        args.out_dir, n_zmws=args.n_zmws, n_subreads=args.n_subreads,
        seq_len=args.seq_len, seed=args.seed, base_qual=args.base_qual,
        plain_names=args.plain_names,
    )
    print(subreads)
    print(ccs)
    return 0
  if args.command == 'corrupt':
    n = corrupt_zmw(args.in_bam, args.out_bam, args.zmw,
                    drop_tags=tuple(args.drop_tag or ('pw',)))
    print(f'corrupted {n} record(s)')
    return 0 if n else 1
  if args.command == 'truncate':
    print(truncate_file(args.path, fraction=args.fraction,
                        keep_bytes=args.keep_bytes))
    return 0
  if args.command == 'fuzz':
    for path in write_fuzz_corpus(args.src, args.out_dir, args.n,
                                  seed=args.seed,
                                  protect_prefix=args.protect_prefix):
      print(path)
    return 0
  if args.command == 'corrupt_record':
    print(corrupt_bam_record(args.in_bam, args.out_bam, args.record,
                             mode=args.mode))
    return 0
  if args.command == 'synth_tfrecords':
    for path in write_synthetic_tfrecords(
        args.out_dir, n_shards=args.n_shards, n_examples=args.n_examples,
        max_passes=args.max_passes, max_length=args.max_length,
        seed=args.seed):
      print(path)
    return 0
  if args.command == 'corrupt_ckpt':
    print(corrupt_checkpoint(args.ckpt, mode=args.mode,
                             fraction=args.fraction))
    return 0
  if args.command == 'device':
    from deepconsensus_tpu import faults as faults_lib

    if args.step is not None and args.fault != 'lost':
      parser.error('--step arms the training device-lost hook; it '
                   'only combines with --fault lost')
    env = {
        'oom': {faults_lib.ENV_DEVICE_OOM_AT_PACK: str(args.pack)},
        'lost': {faults_lib.ENV_DEVICE_LOST_AT_PACK: str(args.pack)},
        'hang': {
            faults_lib.ENV_DEVICE_HANG_AT_PACK: str(args.pack),
            faults_lib.ENV_DEVICE_HANG_S: str(args.hang_s),
        },
    }[args.fault]
    if args.step is not None:
      env = {faults_lib.ENV_DEVICE_LOST_AT_STEP: str(args.step)}
    cmd = [c for c in args.cmd if c != '--']
    if not cmd:
      for key, value in env.items():
        print(f'export {key}={value}')
      return 0
    os.environ.update(env)
    os.execvp(cmd[0], cmd)

  if args.command == 'host':
    from deepconsensus_tpu import faults as faults_lib

    if args.lost_at_step is None and args.rejoin_at_step is None:
      parser.error('nothing to arm: pass --lost_at_step and/or '
                   '--rejoin_at_step')
    env = {}
    if args.lost_at_step is not None:
      env[faults_lib.ENV_HOST_LOST_AT_STEP] = str(args.lost_at_step)
      if args.host is not None:
        env[faults_lib.ENV_HOST_LOST_HOST] = str(args.host)
      if args.mode != 'kill':
        env[faults_lib.ENV_HOST_LOST_MODE] = args.mode
    if args.rejoin_at_step is not None:
      env[faults_lib.ENV_HOST_REJOIN_AT_STEP] = str(args.rejoin_at_step)
    cmd = [c for c in args.cmd if c != '--']
    if not cmd:
      for key, value in env.items():
        print(f'export {key}={value}')
      return 0
    os.environ.update(env)
    os.execvp(cmd[0], cmd)

  if args.command == 'flywheel':
    from deepconsensus_tpu import faults as faults_lib

    env = {faults_lib.ENV_FLYWHEEL_KILL_AT_STAGE: args.kill_at_stage}
    if args.kill_token:
      env[faults_lib.ENV_KILL_TOKEN] = args.kill_token
    cmd = [c for c in args.cmd if c != '--']
    if not cmd:
      for key, value in env.items():
        print(f'export {key}={value}')
      return 0
    os.environ.update(env)
    os.execvp(cmd[0], cmd)

  if args.command == 'preempt':
    import json

    result = preempt_replica(args.pid, grace_s=args.grace_s)
    print(json.dumps(result))
    return 0 if not result['killed'] else 1

  if args.command == 'serve_client':
    from deepconsensus_tpu.serve import client as client_lib
    from deepconsensus_tpu.serve import protocol

    # A small but well-formed request body for the half-send; the
    # server never decodes it, so the shapes are arbitrary.
    body = protocol.encode_request(
        'inject/0/ccs',
        np.zeros((1, 9, 8, 1), dtype=np.float32),
        np.zeros(1, dtype=np.int64),
        np.zeros((1, 8), dtype=np.int32),
        np.zeros(1, dtype=np.uint8))
    for i in range(args.n):
      if args.mode == 'disconnect':
        sent = client_lib.send_disconnect(args.host, args.port, body)
        print(f'[{i}] disconnect: sent {sent}/{len(body)} claimed bytes')
      elif args.mode == 'garbage':
        status = client_lib.send_garbage(args.host, args.port, seed=i)
        print(f'[{i}] garbage: HTTP {status}')
      elif args.mode == 'oversized':
        status = client_lib.send_oversized(args.host, args.port)
        print(f'[{i}] oversized: HTTP {status}')
      elif args.mode == 'slowloris':
        survived = client_lib.send_slowloris(
            args.host, args.port, duration_s=args.duration_s,
            interval_s=args.interval_s)
        print(f'[{i}] slowloris: connection survived {survived:.1f}s')
    return 0
  return 2


if __name__ == '__main__':
  sys.exit(main())
