#!/usr/bin/env python3
"""Fault-injection harness for the inference fault-tolerance layer.

Three tools, usable from the CLI or imported by tests:

* synth    — write a synthetic (subreads_to_ccs.bam, ccs.bam) pair with
             deterministic sequences, one BGZF block per ZMW so a
             truncation lands mid-file rather than killing block 0.
* corrupt  — re-encode a subreads BAM dropping aux tags (default: pw)
             from one target ZMW, which makes expand_aligned_record
             raise for exactly that molecule (a featurize-stage fault).
* truncate — chop a file to a fraction/byte count, producing a
             mid-stream BGZF decode fault (decode-stage).

Worker SIGKILL and consumer-crash injection are driven by env vars read
by deepconsensus_tpu/inference/faults.py (ENV_KILL_ZMW, ENV_KILL_TOKEN,
ENV_CRASH_AFTER_BATCHES); this script documents them in --help.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence, Tuple

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepconsensus_tpu.io import bam as bam_lib  # noqa: E402
from deepconsensus_tpu.io.bam_writer import BamWriter  # noqa: E402

_BASES = np.frombuffer(b'ACGT', dtype=np.uint8)


def write_synthetic_zmw_bams(
    out_dir: str,
    n_zmws: int = 6,
    n_subreads: int = 3,
    seq_len: int = 120,
    movie: str = 'm00001_000000_000000',
    seed: int = 7,
    base_qual: int = 30,
    plain_names: bool = False,
) -> Tuple[str, str]:
  """Writes (subreads_to_ccs.bam, ccs.bam) for n_zmws molecules.

  Subreads are exact copies of the draft CCS (all-match cigar) with
  deterministic pw/ip/sn tags, grouped per ZMW and flushed into their
  own BGZF block so truncate() faults mid-file. The ccs BAM carries
  quals=base_qual and ec/np/rq/RG tags. plain_names drops the PacBio
  movie/zmw/ccs structure (exercises the defensive zm-tag parse).
  """
  rng = np.random.RandomState(seed)
  os.makedirs(out_dir, exist_ok=True)
  subreads_path = os.path.join(out_dir, 'subreads_to_ccs.bam')
  ccs_path = os.path.join(out_dir, 'ccs.bam')

  zmw_ids = [100 + i for i in range(n_zmws)]
  if plain_names:
    ccs_names = [f'read{z}' for z in zmw_ids]
  else:
    ccs_names = [f'{movie}/{z}/ccs' for z in zmw_ids]
  seqs = [
      bytes(_BASES[rng.randint(0, 4, seq_len)]).decode('ascii')
      for _ in zmw_ids
  ]

  sub_writer = BamWriter(
      subreads_path,
      header_text='@HD\tVN:1.5\tSO:unknown\n',
      references=[(name, seq_len) for name in ccs_names],
  )
  for i, (zmw, seq) in enumerate(zip(zmw_ids, seqs)):
    for k in range(n_subreads):
      if plain_names:
        qname = f'sub{zmw}_{k}'
      else:
        qname = f'{movie}/{zmw}/{k * 1000}_{k * 1000 + seq_len}'
      tags = {
          'zm': zmw,
          'pw': rng.randint(1, 6, seq_len).astype(np.int32),
          'ip': rng.randint(1, 9, seq_len).astype(np.int32),
          'sn': rng.uniform(4.0, 12.0, 4).astype(np.float32),
      }
      sub_writer.write(
          qname, seq, None, tags=tags, flag=0, ref_id=i, pos=0,
          cigar=[(0, seq_len)],
      )
    # One BGZF block per ZMW: a later truncate() then faults mid-file
    # instead of corrupting the first group.
    sub_writer.flush()
  sub_writer.close()

  ccs_writer = BamWriter(
      ccs_path,
      header_text='@HD\tVN:1.5\tSO:unknown\n'
      '@RG\tID:rg1\tPL:PACBIO\tSM:synthetic\n',
  )
  for name, seq in zip(ccs_names, seqs):
    ccs_writer.write(
        name, seq, np.full(seq_len, base_qual, dtype=np.uint8),
        tags={
            'ec': float(n_subreads),
            'np': int(n_subreads),
            'rq': 0.99,
            'RG': 'rg1',
        },
        flag=4,
    )
    ccs_writer.flush()
  ccs_writer.close()
  return subreads_path, ccs_path


def corrupt_zmw(
    in_bam: str,
    out_bam: str,
    zmw: int,
    drop_tags: Sequence[str] = ('pw',),
) -> int:
  """Re-encodes in_bam with drop_tags removed from records of one ZMW.

  Dropping 'pw' makes expand_aligned_record raise KeyError('pw') for
  exactly that molecule — the canonical per-ZMW featurize fault.
  Returns the number of corrupted records.
  """
  reader = bam_lib.BamReader(in_bam)
  # Our reader ignores declared reference lengths; 0 keeps the header
  # faithful enough for round-tripping.
  writer = BamWriter(
      out_bam,
      header_text=reader.header_text,
      references=[(name, 0) for name in reader.references],
  )
  n_corrupted = 0
  for rec in reader:
    tags = dict(rec.tags)
    if int(tags.get('zm', -1)) == zmw:
      for tag in drop_tags:
        tags.pop(tag, None)
      n_corrupted += 1
    writer.write(
        rec.qname, rec.seq, rec.quals, tags=tags, flag=rec.flag,
        ref_id=rec.ref_id, pos=rec.pos,
        cigar=list(zip(rec.cigar_ops.tolist(), rec.cigar_lens.tolist())),
    )
  writer.close()
  return n_corrupted


def truncate_file(path: str, fraction: float = 0.5,
                  keep_bytes: Optional[int] = None) -> int:
  """Truncates path mid-stream; returns the new size."""
  size = os.path.getsize(path)
  keep = keep_bytes if keep_bytes is not None else max(1, int(size * fraction))
  with open(path, 'r+b') as f:
    f.truncate(keep)
  return keep


def main(argv: Optional[List[str]] = None) -> int:
  parser = argparse.ArgumentParser(
      description=__doc__,
      formatter_class=argparse.RawDescriptionHelpFormatter,
      epilog=(
          'Env-var hooks (read by inference/faults.py):\n'
          '  DCTPU_FAULT_KILL_ZMW=<ccs name>   SIGKILL the pool worker '
          'featurizing that ZMW\n'
          '  DCTPU_FAULT_KILL_TOKEN=<path>     kill only once (token '
          'file created on first kill)\n'
          '  DCTPU_FAULT_CRASH_AFTER_BATCHES=N crash the consumer loop '
          'after N batches\n'
      ),
  )
  sub = parser.add_subparsers(dest='command', required=True)

  p = sub.add_parser('synth', help='Write synthetic subreads/ccs BAMs.')
  p.add_argument('--out_dir', required=True)
  p.add_argument('--n_zmws', type=int, default=6)
  p.add_argument('--n_subreads', type=int, default=3)
  p.add_argument('--seq_len', type=int, default=120)
  p.add_argument('--seed', type=int, default=7)
  p.add_argument('--base_qual', type=int, default=30)
  p.add_argument('--plain_names', action='store_true')

  p = sub.add_parser('corrupt', help='Drop aux tags from one ZMW.')
  p.add_argument('--in_bam', required=True)
  p.add_argument('--out_bam', required=True)
  p.add_argument('--zmw', type=int, required=True)
  p.add_argument('--drop_tag', action='append', default=None,
                 help='Tag to drop (repeatable; default pw).')

  p = sub.add_parser('truncate', help='Truncate a file mid-stream.')
  p.add_argument('--path', required=True)
  p.add_argument('--fraction', type=float, default=0.5)
  p.add_argument('--bytes', type=int, default=None, dest='keep_bytes')

  args = parser.parse_args(argv)
  if args.command == 'synth':
    subreads, ccs = write_synthetic_zmw_bams(
        args.out_dir, n_zmws=args.n_zmws, n_subreads=args.n_subreads,
        seq_len=args.seq_len, seed=args.seed, base_qual=args.base_qual,
        plain_names=args.plain_names,
    )
    print(subreads)
    print(ccs)
    return 0
  if args.command == 'corrupt':
    n = corrupt_zmw(args.in_bam, args.out_bam, args.zmw,
                    drop_tags=tuple(args.drop_tag or ('pw',)))
    print(f'corrupted {n} record(s)')
    return 0 if n else 1
  if args.command == 'truncate':
    print(truncate_file(args.path, fraction=args.fraction,
                        keep_bytes=args.keep_bytes))
    return 0
  return 2


if __name__ == '__main__':
  sys.exit(main())
