"""Shared setup for the train-step bench scripts.

One copy of the Trainer construction, synthetic-batch featurization
(mirroring the stacked-row layout models/data.py produces), and the
transfer-free scalar train step, so scripts/bench_train_scaling.py and
scripts/bench_train_stages.py cannot drift apart.
"""


def make_rows(params, batch, seed=2, rng=None):
  """Synthetic [B, R, L, 1] pileup rows with per-feature-valid ranges
  (the stacked layout models/data.py produces). Pass `rng` to draw
  from a caller-owned stream (keeps downstream draws — e.g. labels —
  on the same stream across refactors, so bench loss values stay
  comparable between rounds)."""
  import numpy as np

  if rng is None:
    rng = np.random.default_rng(seed)
  rows = np.zeros(
      (batch, params.total_rows, params.max_length, 1), np.float32)
  mp = params.max_passes
  rows[:, :mp] = rng.integers(0, 5, size=rows[:, :mp].shape)  # bases
  rows[:, mp:3 * mp] = rng.integers(  # pw, ip
      0, 256, size=rows[:, mp:3 * mp].shape)
  rows[:, 3 * mp:4 * mp] = rng.integers(  # strand
      0, 3, size=rows[:, 3 * mp:4 * mp].shape)
  rows[:, 4 * mp] = rng.integers(0, 5, size=rows[:, 4 * mp].shape)  # ccs
  rows[:, 4 * mp + 1:] = rng.integers(  # sn
      0, 501, size=rows[:, 4 * mp + 1:].shape)
  return rows


def make_trainer_and_batch(batch, use_scan_dp=False,
                           out_dir='/tmp/dc_bench_train'):
  """Returns (trainer, state, rows_t, label) for the test config at
  the given batch size; use_scan_dp pins the lax.scan DP instead of
  the TPU-default Pallas wavefront."""
  import jax.numpy as jnp
  import numpy as np
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import train as train_lib

  tp = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(tp)
  with tp.unlocked():
    tp.batch_size = batch
    tp.use_pallas_wavefront = False if use_scan_dp else None
  trainer = train_lib.Trainer(params=tp, out_dir=out_dir, mesh=None)
  state = trainer.init_state(steps_total=100)

  # One stream for rows THEN label, matching the pre-refactor draw
  # order bit-for-bit (round-2/3 measured loss values diff cleanly).
  rng = np.random.default_rng(2)
  rows_t = jnp.asarray(make_rows(tp, batch, rng=rng))
  label = jnp.asarray(
      rng.integers(0, 5, size=(batch, tp.max_length)), jnp.int32)
  return trainer, state, rows_t, label


def make_scalar_step(state, loss_fn):
  """Jitted train step returning only scalars (loss + a parameter
  fingerprint that keeps the LAMB update live against DCE), so timing
  excludes device->host tensor transfers."""
  import jax
  import jax.numpy as jnp

  def step(state, rows, label):
    rng_step = jax.random.fold_in(state.dropout_rng, state.step)

    def loss_of(p):
      preds = state.apply_fn(
          {'params': p}, rows, train=True, rngs={'dropout': rng_step}
      )
      return loss_fn(label, preds)

    loss, grads = jax.value_and_grad(loss_of)(state.params)
    new_state = state.apply_gradients(grads=grads)
    fp = sum(jnp.sum(x) for x in jax.tree.leaves(new_state.params))
    return loss, fp

  del state
  return jax.jit(step)
