"""Forward-pass attribution: where the non-MXU 79% goes (VERDICT r3 #5).

The measured forward MFU is 0.21 at b1024; this script attributes
wall-clock across the forward's stages without parsing profiler traces
over a tunnel that can hang (same strategy as bench_train_stages.py):
cumulative ablations of the real model — embed gathers alone, +
condenser, + encoder, + logits/softmax — timed back-to-back in one
process, plus standalone same-shape modules (one attention block, one
FFN block) for the within-encoder split, plus compiled-flops MFU for
every piece. --batches 1024 2048 also answers the r2-#8 b2048
regression with the same numbers. --trace DIR additionally dumps a
jax.profiler trace of the full forward for offline inspection.

Prints one JSON line per batch size.
"""
import argparse
import json
import time

REFERENCE_WINDOWS_PER_SEC = 114.0
PEAK_BF16_FLOPS = 197e12


def _timed(fn, args_, steps=10):
  import jax

  out = fn(*args_)
  jax.block_until_ready(out)
  t0 = time.perf_counter()
  for _ in range(steps):
    out = fn(*args_)
  jax.block_until_ready(out)
  return (time.perf_counter() - t0) / steps


def _flops(jitted, *args):
  try:
    cost = jitted.lower(*args).compile().cost_analysis()
    entry = cost[0] if isinstance(cost, (list, tuple)) else cost
    return float(entry.get('flops', 0.0)) or None
  except Exception:
    return None


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--batches', type=int, nargs='+', default=[1024, 2048])
  ap.add_argument('--steps', type=int, default=10)
  ap.add_argument('--cpu', action='store_true')
  ap.add_argument('--trace', default=None,
                  help='directory for a jax.profiler trace of the full '
                  'forward (inspect offline with tensorboard/xprof)')
  ap.add_argument('--set', action='append', default=[], dest='overrides',
                  metavar='KEY=VALUE',
                  help='config override (e.g. embed_onehot=true, '
                  'attn_softmax_dtype=bfloat16) for lever A/Bs')
  ap.add_argument('--config', default='transformer_learn_values+test',
                  help='config preset; use '
                  'transformer_learn_values_distill+test for the '
                  'quantized-student sweeps')
  args = ap.parse_args()

  import jax

  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import numpy as np
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib
  from scripts._bench_common import make_rows

  params = config_lib.get_config(args.config)
  if args.overrides:
    from deepconsensus_tpu.cli import _apply_overrides

    _apply_overrides(params, args.overrides)
  if params.get('inference_dtype', None):
    # Mirror runner._apply_quant_levers: the inference dtype is also
    # the compute dtype, so activations follow the weights end-to-end.
    with params.unlocked():
      params.dtype = params.inference_dtype
  config_lib.finalize_params(params, is_training=False)
  model = model_lib.get_model(params)
  quant_levers = bool(
      params.get('inference_dtype', None)
      or (params.get('quantize_matmuls', None) or 'none') != 'none')

  for batch in args.batches:
    rows_np = make_rows(params, batch)
    rows = jnp.asarray(rows_np)
    variables = model.init(jax.random.PRNGKey(0), rows[:1])
    n_quantized = 0
    if quant_levers:
      # Same transform the runner applies at load: int8-quantize the
      # matmul weights (dequantized params + a 'quant' collection for
      # the fused kernels), then cast float leaves to inference_dtype.
      # Stage ablations below run the XLA methods on the transformed
      # tree, so their numbers attribute the levered model.
      from deepconsensus_tpu.models import quantize as quantize_lib

      variables, n_quantized = quantize_lib.prepare_inference_variables(
          variables, params)
    rows3 = jnp.squeeze(rows, -1)

    # -- cumulative ablations of the real model ------------------------
    full = jax.jit(lambda v, r: model.apply(v, r))
    embed = jax.jit(lambda v, r: model.apply(
        v, r, method=lambda m, rr: m._embed_rows(rr)))
    embed_condense = jax.jit(lambda v, r: model.apply(
        v, r, method=lambda m, rr: m.condenser(m._embed_rows(rr))))
    encoder_in = embed_condense(variables, rows3)
    encoder_only = jax.jit(lambda v, x: model.apply(
        v, x, method=lambda m, xx: m.encoder(xx, deterministic=True)))
    encoded = encoder_only(variables, encoder_in)
    logits_only = jax.jit(lambda v, x: model.apply(
        v, x, method=lambda m, xx: jax.nn.softmax(
            m.logits_layer(xx.astype(jnp.float32)), axis=-1)))

    stages = {}
    t_full = _timed(full, (variables, rows), args.steps)
    stages['full'] = t_full
    stages['embed'] = _timed(embed, (variables, rows3), args.steps)
    stages['embed_condense'] = _timed(
        embed_condense, (variables, rows3), args.steps)
    stages['encoder'] = _timed(
        encoder_only, (variables, encoder_in), args.steps)
    stages['logits_softmax'] = _timed(
        logits_only, (variables, encoded), args.steps)

    # -- standalone same-shape blocks for the within-encoder split -----
    dt = jnp.dtype(params.get('dtype', 'float32'))
    x_enc = encoder_in.astype(dt)
    attn = model_lib.BandedSelfAttention(
        hidden_size=params.hidden_size, num_heads=params.num_heads,
        dropout_rate=0.0, attn_win_size=params.attn_win_size, dtype=dt,
        use_pallas=params.get('use_pallas_attention', False),
        softmax_dtype=jnp.dtype(
            params.get('attn_softmax_dtype', None) or 'float32'))
    attn_vars = attn.init(jax.random.PRNGKey(1), x_enc, True)
    attn_fn = jax.jit(
        lambda v, x: attn.apply(v, x, True))
    stages['one_attention_block'] = _timed(
        attn_fn, (attn_vars, x_enc), args.steps)
    ffn = model_lib.FeedForward(
        hidden_size=params.hidden_size, filter_size=params.filter_size,
        dropout_rate=0.0, dtype=dt)
    ffn_vars = ffn.init(jax.random.PRNGKey(2), x_enc, True)
    ffn_fn = jax.jit(lambda v, x: ffn.apply(v, x, True))
    stages['one_ffn_block'] = _timed(ffn_fn, (ffn_vars, x_enc), args.steps)

    flops_full = _flops(full, variables, rows)
    result = {
        'batch': batch,
        'backend': jax.default_backend(),
        'windows_per_sec': round(batch / t_full, 1),
        'vs_baseline': round(batch / t_full / REFERENCE_WINDOWS_PER_SEC, 2),
        'stage_ms': {k: round(v * 1e3, 3) for k, v in stages.items()},
        'stage_share_of_full': {
            k: round(v / t_full, 3) for k, v in stages.items()
        },
        'n_layers': params.num_hidden_layers,
    }
    if quant_levers:
      result['inference_dtype'] = str(
          params.get('inference_dtype', None) or 'float32')
      result['quantize_matmuls'] = str(
          params.get('quantize_matmuls', None) or 'none')
      result['n_quantized_matmuls'] = n_quantized
    if flops_full:
      result['mfu'] = round(
          flops_full / t_full / PEAK_BF16_FLOPS, 4)
      result['flops_per_batch'] = flops_full
    for name, fn, fargs in (
        ('embed', embed, (variables, rows3)),
        ('encoder', encoder_only, (variables, encoder_in)),
        ('one_ffn_block', ffn_fn, (ffn_vars, x_enc)),
        ('one_attention_block', attn_fn, (attn_vars, x_enc)),
    ):
      f = _flops(fn, *fargs)
      if f and stages[name] > 0:
        result.setdefault('stage_mfu', {})[name] = round(
            f / stages[name] / PEAK_BF16_FLOPS, 4)
    print(json.dumps(result), flush=True)

    if args.trace:
      with jax.profiler.trace(args.trace):
        for _ in range(3):
          out = full(variables, rows)
        jax.block_until_ready(out)
      print(json.dumps({'trace_dir': args.trace, 'batch': batch}),
            flush=True)
  return 0


if __name__ == '__main__':
  raise SystemExit(main())
