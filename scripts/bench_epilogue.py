"""Device-epilogue A/B: D2H bytes/pack + windows/s, on vs off.

Drives the same depth-2 dispatch/finalize pipeline the ConsensusEngine
uses, once with the device-resident output plane (uint8 ids + quals
drained, 2 bytes/position) and once with the host quality path (int32
ids + f32 max_prob, 8 bytes/position), and prints one JSON line per
variant plus a summary line with the measured reduction and a
byte-identity verdict. The bytes ratio is backend-independent; the
windows/s delta is the number the measure_r4.sh forward_epilogue stage
exists to capture on live chips (on CPU it mostly measures the host
log10/round work the epilogue removes).
"""
import argparse
import json
import time
from collections import deque


def _run_variant(runner_lib, params, variables, args, pool, device_epilogue,
                 mesh=None):
  options = runner_lib.InferenceOptions(
      batch_size=args.batch, device_epilogue=device_epilogue)
  runner = runner_lib.ModelRunner(params, dict(variables), options,
                                  mesh=mesh)
  for i in range(args.warmup):
    runner.finalize(runner.dispatch(pool[i % len(pool)]))
  pending = deque()
  last = None
  t0 = time.perf_counter()
  for i in range(args.packs):
    pending.append(runner.dispatch(pool[i % len(pool)]))
    if len(pending) >= 2:  # engine dispatch_depth pattern
      last = runner.finalize(pending.popleft())
  while pending:
    last = runner.finalize(pending.popleft())
  dt = time.perf_counter() - t0
  stats = runner.dispatch_stats()
  return {
      'device_epilogue': bool(device_epilogue),
      'windows_per_sec': round(args.batch * args.packs / dt, 1),
      'd2h_bytes_per_pack': stats['d2h_bytes_per_pack'],
      'd2h_bytes_per_position': round(
          stats['d2h_bytes_per_pack'] / (args.batch * params.max_length),
          2),
      'n_epilogue_packs': stats['n_epilogue_packs'],
  }, last


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--batch', type=int, default=1024)
  ap.add_argument('--packs', type=int, default=8)
  ap.add_argument('--warmup', type=int, default=2)
  ap.add_argument('--config', default='transformer_learn_values_distill+test')
  ap.add_argument('--fused', action='store_true',
                  help='route through the fused encoder blocks (the '
                       'Pallas epilogue rides the fused hot path)')
  args = ap.parse_args()

  import jax
  import jax.numpy as jnp
  import numpy as np

  from deepconsensus_tpu.inference import runner as runner_lib
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib
  from scripts._bench_common import make_rows

  params = config_lib.get_config(args.config)
  if args.fused:
    with params.unlocked():
      params.use_fused_hotpath = True
  config_lib.finalize_params(params, is_training=False)
  model = model_lib.get_model(params)
  variables = model.init(
      jax.random.PRNGKey(0),
      jnp.zeros((1, params.total_rows, params.max_length, 1)))

  rng = np.random.default_rng(0)
  pool = [make_rows(params, args.batch, rng=rng)
          for _ in range(min(4, args.packs))]

  results = {}
  outputs = {}
  for device_epilogue in (True, False):
    line, last = _run_variant(runner_lib, params, variables, args, pool,
                              device_epilogue)
    line.update({'backend': jax.devices()[0].platform,
                 'batch': args.batch, 'packs': args.packs,
                 'config': args.config, 'fused': args.fused})
    results[device_epilogue] = line
    outputs[device_epilogue] = last
    print(json.dumps(line), flush=True)

  on, off = results[True], results[False]
  identical = bool(
      np.array_equal(np.asarray(outputs[True][0], np.int64),
                     np.asarray(outputs[False][0], np.int64))
      and np.array_equal(np.asarray(outputs[True][1], np.int64),
                         np.asarray(outputs[False][1], np.int64)))
  print(json.dumps({
      'summary': 'd2h_epilogue_ab',
      'd2h_reduction': round(
          off['d2h_bytes_per_pack'] / on['d2h_bytes_per_pack'], 2),
      'speedup_epilogue': round(
          on['windows_per_sec'] / off['windows_per_sec'], 3),
      'byte_identical': identical,
  }), flush=True)
  return 0 if identical else 1


if __name__ == '__main__':
  raise SystemExit(main())
