"""Compare our preprocess output against the reference's bundled TFRecords."""
import collections
import sys

import numpy as np

sys.path.insert(0, '/root/repo')

from deepconsensus_tpu.io import tfrecord
from deepconsensus_tpu.io.example_proto import Example
from deepconsensus_tpu.preprocess import FeatureLayout, create_proc_feeder, reads_to_pileup

TD = '/root/reference/deepconsensus/testdata/human_1m'


def load_reference_examples():
  ref = {}
  for split in ('train', 'eval', 'test'):
    for raw in tfrecord.read_tfrecords(f'{TD}/tf_examples/{split}/{split}.tfrecord.gz'):
      ex = Example.parse(raw)
      name = ex['name'][0].decode()
      pos = ex['window_pos'][0]
      ref[(name, pos)] = (split, ex)
  return ref


def main():
  layout = FeatureLayout(max_passes=20, max_length=100)
  feeder, counter = create_proc_feeder(
      subreads_to_ccs=f'{TD}/subreads_to_ccs.bam',
      ccs_bam=f'{TD}/ccs.bam',
      layout=layout,
      ins_trim=5,
      truth_bed=f'{TD}/truth.bed',
      truth_to_ccs=f'{TD}/truth_to_ccs.bam',
      truth_split=f'{TD}/truth_split.tsv',
  )
  ours = {}
  split_counts = collections.Counter()
  agg = collections.Counter()
  for subreads, name, lay, split, ww in feeder():
    pileup = reads_to_pileup(subreads, name, lay, ww)
    for window in pileup.iter_windows():
      ex = window.to_example()
      pos = window.ccs.ccs_bounds.start
      ours[(window.name, pos)] = (split, ex)
      split_counts[split] += 1
    agg.update(pileup.counter)
  print('counters:', dict(counter))
  print('agg window counters:', dict(agg))
  print('ours per split:', dict(split_counts))

  ref = load_reference_examples()
  print(f'ref examples: {len(ref)}, ours: {len(ours)}')
  missing = set(ref) - set(ours)
  extra = set(ours) - set(ref)
  print(f'missing: {len(missing)} extra: {len(extra)}')
  for k in list(missing)[:5]:
    print('  missing:', k, ref[k][0])
  for k in list(extra)[:5]:
    print('  extra:', k, ours[k][0])

  n_exact = n_rows_diff = n_label_diff = n_meta_diff = 0
  first_diff = None
  for key in sorted(set(ref) & set(ours)):
    rsplit, rex = ref[key]
    osplit, oex = ours[key]
    ok = True
    if rsplit != osplit:
      n_meta_diff += 1
      ok = False
    r_rows = np.frombuffer(rex['subreads/encoded'][0], np.float32)
    o_rows = np.frombuffer(oex['subreads/encoded'][0], np.float32)
    if not np.array_equal(r_rows, o_rows):
      n_rows_diff += 1
      ok = False
      if first_diff is None:
        first_diff = (key, r_rows, o_rows, rex, oex)
    if ('label/encoded' in rex) != ('label/encoded' in oex):
      n_label_diff += 1
      ok = False
    elif 'label/encoded' in rex:
      if rex['label/encoded'][0] != oex['label/encoded'][0]:
        n_label_diff += 1
        ok = False
    if rex['subreads/num_passes'] != oex['subreads/num_passes']:
      n_meta_diff += 1
      ok = False
    if rex['ccs_base_quality_scores'] != oex['ccs_base_quality_scores']:
      n_meta_diff += 1
      ok = False
    if ok:
      n_exact += 1
  print(f'exact: {n_exact} rows_diff: {n_rows_diff} label_diff: {n_label_diff} meta_diff: {n_meta_diff}')
  if first_diff is not None:
    key, r_rows, o_rows, rex, oex = first_diff
    r = r_rows.reshape(85, 100)
    o = o_rows.reshape(85, 100)
    bad_rows = np.unique(np.nonzero(r != o)[0])
    print('first diff:', key, 'rows differing:', bad_rows[:20])
    i = bad_rows[0]
    print('ref row :', r[i][:50])
    print('ours row:', o[i][:50])


if __name__ == '__main__':
  main()
