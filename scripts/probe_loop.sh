#!/bin/bash
# Round-5 opportunistic TPU probe loop (VERDICT r4 "What's weak" #1 /
# "Next round" #1): ping the tunneled chip every DC_PROBE_INTERVAL
# seconds for the whole round, log every attempt to PROBE_LOG_r5.jsonl
# (proof of round-long coverage if the chip never answers), and fire
# the staged measurement sweep scripts/measure_r4.sh exactly once on
# the first successful probe.
#
# Run detached:  nohup bash scripts/probe_loop.sh &
# State files:
#   .tpu_alive          — present while the last probe succeeded
#   .measure_r4_fired   — sweep has been launched (guard against refire)
set -u
REPO=/root/repo
LOG=$REPO/PROBE_LOG_r5.jsonl
MEASURE_LOG=$REPO/measure_r5_run.log
INTERVAL=${DC_PROBE_INTERVAL:-150}
mkdir -p "$REPO/MEASURED_TPU_r4.d"

probe() {
  timeout 90 env PYTHONPATH=$REPO:/root/.axon_site JAX_PLATFORMS='' \
    python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu', d" \
    >/dev/null 2>&1
}

while true; do
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  if probe; then
    echo "{\"ts\": \"$ts\", \"alive\": true}" >> "$LOG"
    touch "$REPO/.tpu_alive"
    if [ ! -e "$REPO/.measure_r4_fired" ]; then
      touch "$REPO/.measure_r4_fired"
      echo "{\"ts\": \"$ts\", \"event\": \"firing measure_r4.sh\"}" >> "$LOG"
      bash "$REPO/scripts/measure_r4.sh" > "$MEASURE_LOG" 2>&1
      rc=$?
      echo "{\"ts\": \"$(date -u +%Y-%m-%dT%H:%M:%SZ)\", \"event\": \"measure_r4.sh done\", \"rc\": $rc}" >> "$LOG"
    fi
  else
    echo "{\"ts\": \"$ts\", \"alive\": false}" >> "$LOG"
    rm -f "$REPO/.tpu_alive"
  fi
  sleep "$INTERVAL"
done
