"""End-to-end inference benchmark: BAM -> FASTQ ZMW/s on real hardware.

Drives the full `run_inference` pipeline (BAM decode, featurization,
skip triage, jit'd model forward, stitch, FASTQ write) over the bundled
human_1m testdata, repeated --repeats times so the jit compile and BAM
open amortize out of the steady-state number. Prints one JSON line with
ZMW/s, windows/s, and the per-stage runtime split from the runtime CSV.

The reference's end-to-end anchor is 178 ZMWs in 234.95 s (~0.76
ZMW/s) on an n1-standard-16 (reference docs/quick_start.md:315-320);
vs_baseline is against that. The full-size model runs on whatever
backend jax selects (TPU via the tunnel when alive); featurization
runs on the host, so on a 1-core host this measures the host-bound
configuration — rerun on a many-core host with --cpus for the
chip-bound one.
"""
import argparse
import csv
import json
import os
import tempfile
import time

REFERENCE_ZMW_PER_SEC = 178 / 234.95


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--testdata',
                  default='/root/reference/deepconsensus/testdata/human_1m')
  ap.add_argument('--repeats', type=int, default=8)
  ap.add_argument('--cpus', type=int, default=0)
  ap.add_argument('--batch_size', type=int, default=1024)
  ap.add_argument('--depth', type=int, default=8,
                  help='dispatch pipeline depth (batches in flight; '
                  'r2 measured 4.78 s/batch of tunnel round-trip at '
                  'depth 1 — sweep this on hardware)')
  ap.add_argument('--batch_zmws', type=int, default=100)
  ap.add_argument('--cpu', action='store_true', help='force CPU backend')
  args = ap.parse_args()
  if args.repeats < 1:
    ap.error('--repeats must be >= 1 (repeat 0 is the compile warmup)')

  import jax

  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  from deepconsensus_tpu.inference import runner as runner_lib
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params, is_training=False)
  model = model_lib.get_model(params)
  rows = jnp.zeros((1, params.total_rows, params.max_length, 1))
  variables = model.init(jax.random.PRNGKey(0), rows)
  options = runner_lib.InferenceOptions(
      batch_size=args.batch_size, batch_zmws=args.batch_zmws,
      cpus=args.cpus, dispatch_depth=args.depth,
      min_quality=0,  # untrained weights: keep the writer path honest
  )
  runner = runner_lib.ModelRunner(params, variables, options)

  td = args.testdata
  out_dir = tempfile.mkdtemp(prefix='dc_e2e_')
  totals = {}
  n_zmws = n_windows = 0
  warm_plus_timed = args.repeats + 1
  t_steady = None
  for rep in range(warm_plus_timed):
    if rep == 1:  # repeat 0 pays jit compile; steady state starts here
      t_steady = time.perf_counter()
    out = os.path.join(out_dir, f'out_{rep}.fastq')
    counters = runner_lib.run_inference(
        subreads_to_ccs=f'{td}/subreads_to_ccs.bam',
        ccs_bam=f'{td}/ccs.bam',
        checkpoint=None,
        output=out,
        options=options,
        runner=runner,
    )
    if rep == 0:
      continue
    n_zmws += counters['n_zmw_pass']
    with open(out + '.runtime.csv') as f:
      for row in csv.DictReader(f):
        totals[row['stage']] = (
            totals.get(row['stage'], 0.0) + float(row['runtime'])
        )
        if row['stage'] == 'run_model':
          n_windows += int(row.get('n_examples', 0) or 0)
  elapsed = time.perf_counter() - t_steady
  result = {
      'metric': 'e2e_inference_zmw_per_sec',
      'value': round(n_zmws / elapsed, 2),
      'unit': (f'ZMW/s e2e (backend={jax.default_backend()}, '
               f'cpus={args.cpus}, depth={args.depth}, '
               f'{os.cpu_count()} host cores)'),
      'dispatch_depth': args.depth,
      'batch_zmws': args.batch_zmws,
      'vs_baseline': round(n_zmws / elapsed / REFERENCE_ZMW_PER_SEC, 1),
      'windows_per_sec': round(n_windows / elapsed, 1),
      'stage_seconds': {k: round(v, 2) for k, v in sorted(totals.items())},
      'n_zmws': n_zmws,
  }
  print(json.dumps(result), flush=True)


if __name__ == '__main__':
  main()
