"""Banded alignment-DP A/B: lax.scan vs the Pallas band kernel
(VERDICT r4 #4).

Times forward and forward+grad at a production-ish shape on whatever
backend is live (TPU via the tunnel, else CPU — Pallas kernels run in
interpret mode on CPU, so CPU numbers measure correctness plumbing,
not kernel speed; the decision number is the TPU run). Prints one JSON
line per leg.
"""
import argparse
import json
import time


def bench(fn, args, steps):
  import jax

  out = fn(*args)
  jax.block_until_ready(out)
  t0 = time.perf_counter()
  for _ in range(steps):
    out = fn(*args)
  jax.block_until_ready(out)
  return (time.perf_counter() - t0) / steps


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--batch', type=int, default=256)
  ap.add_argument('--m', type=int, default=120)
  ap.add_argument('--widths', type=int, nargs='+', default=[2, 4, 8])
  ap.add_argument('--loss_reg', type=float, default=0.1)
  ap.add_argument('--steps', type=int, default=5)
  ap.add_argument('--cpu', action='store_true',
                  help='force the CPU backend (the axon TPU plugin '
                       'ignores JAX_PLATFORMS=cpu, so a dead tunnel '
                       'hangs device init without this)')
  args = ap.parse_args()

  import jax

  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import numpy as np

  from deepconsensus_tpu.ops import wavefront, wavefront_pallas as wp

  backend = jax.devices()[0].platform
  rng = np.random.default_rng(0)
  b, m = args.batch, args.m
  subs = jnp.asarray(rng.uniform(0, 5, size=(b, m, m)).astype(np.float32))
  ins = jnp.asarray(rng.uniform(0, 5, size=(b, m)).astype(np.float32))
  lens = jnp.asarray(rng.integers(m // 2, m + 1, size=b).astype(np.int32))
  reg = args.loss_reg
  minop = lambda t: -reg * jax.nn.logsumexp(-t / reg, axis=0)

  for width in args.widths:
    legs = {
        'scan_fwd': jax.jit(lambda s, i, w=width: wavefront.
                            banded_alignment_scan(
                                s, i, jnp.float32(3.0), lens, w, minop)),
        'pallas_fwd': jax.jit(lambda s, i, w=width: wp.
                              banded_alignment_scores(
                                  s, i, 3.0, lens, w, loss_reg=reg,
                                  interpret=backend != 'tpu')),
        'scan_grad': jax.jit(jax.grad(
            lambda s, i, w=width: jnp.sum(wavefront.banded_alignment_scan(
                s, i, jnp.float32(3.0), lens, w, minop)), argnums=(0, 1))),
        'pallas_grad': jax.jit(jax.grad(
            lambda s, i, w=width: jnp.sum(wp.banded_alignment_scores_vjp(
                s, i, lens, 3.0, reg, w)), argnums=(0, 1))),
    }
    times = {}
    for name, fn in legs.items():
      try:
        times[name] = bench(fn, (subs, ins), args.steps)
      except Exception as e:  # pragma: no cover
        times[name] = None
        print(json.dumps({'leg': name, 'width': width,
                          'error': repr(e)[:200]}), flush=True)
    row = {
        'backend': backend, 'batch': b, 'm': m, 'width': width,
        'loss_reg': reg, 'steps': args.steps,
        'interpret_mode': backend != 'tpu',
    }
    for name, t in times.items():
      if t is not None:
        row[f'{name}_ms'] = round(t * 1e3, 2)
    if times.get('scan_grad') and times.get('pallas_grad'):
      row['pallas_grad_speedup'] = round(
          times['scan_grad'] / times['pallas_grad'], 3)
    if times.get('scan_fwd') and times.get('pallas_fwd'):
      row['pallas_fwd_speedup'] = round(
          times['scan_fwd'] / times['pallas_fwd'], 3)
    print(json.dumps(row), flush=True)
  return 0


if __name__ == '__main__':
  raise SystemExit(main())
