"""Train-step stage shares: model fwd/bwd + optimizer vs alignment DP.

VERDICT r2 #4 asked how the train step splits between the model and
the AlignmentLoss wavefront DP. Rather than parsing jax.profiler
traces over a tunnel that can hang, this times jitted step variants
back-to-back in one process:

  step_dp   - the real train step (model fwd/bwd + AlignmentLoss DP +
              LAMB), the same construction as scripts/bench_train_scaling.py
  step_xent - identical step with the DP loss swapped for a cheap
              masked per-position cross-entropy, so model fwd/bwd +
              optimizer cost is intact and (step_dp - step_xent)
              estimates the DP's share (forward + backward + cost
              construction)
  dp_grad   - jit(value_and_grad(AlignmentLoss)) alone on a fixed
              prediction tensor: the DP share measured directly. Its
              forward is the emit_rows=True kernel (streams DP rows
              to HBM as VJP residuals), so dp_grad covers the
              residual-streaming forward + the reverse adjoint sweep.
  dp_fwd    - jit(AlignmentLoss) forward only — the emit_rows=False
              scorer. dp_grad_over_fwd therefore compares the whole
              differentiated DP (row-streaming forward + backward)
              against the lean forward, not backward-vs-forward alone.

Prints one JSON line per (batch, dp-impl) with seconds per step and
derived shares. --scan-too also measures the lax.scan DP for the
kernel-vs-scan A/B at the same shapes.
"""
import argparse
import json
import time


def _timed(fn, args_, steps):
  import jax

  out = fn(*args_)
  jax.block_until_ready(out)
  t0 = time.perf_counter()
  for _ in range(steps):
    out = fn(*args_)
  jax.block_until_ready(out)
  return (time.perf_counter() - t0) / steps


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--batches', type=int, nargs='+', default=[256, 1024])
  ap.add_argument('--steps', type=int, default=6)
  ap.add_argument('--scan-too', action='store_true')
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  import jax

  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import numpy as np

  from scripts import _bench_common

  dp_impls = ['pallas'] + (['scan'] if args.scan_too else [])
  for batch in args.batches:
    for dp_impl in dp_impls:
      trainer, state, rows_t, label = _bench_common.make_trainer_and_batch(
          batch, use_scan_dp=(dp_impl == 'scan'),
          out_dir='/tmp/dc_bench_train_stages',
      )
      loss_obj = trainer.loss_fn

      def masked_xent(y_true, y_pred):
        length = min(y_true.shape[1], y_pred.shape[1])
        yp = jnp.clip(y_pred[:, :length], 1e-7, 1.0)
        onehot = jax.nn.one_hot(y_true[:, :length], yp.shape[-1])
        return -jnp.mean(jnp.sum(onehot * jnp.log(yp), axis=-1))

      rng = np.random.default_rng(3)
      preds_fixed = jax.nn.softmax(jnp.asarray(
          rng.normal(
              size=(batch, trainer.params.max_length, 5)
          ).astype(np.float32)))
      dp_grad = jax.jit(jax.value_and_grad(
          lambda yp: loss_obj(label, yp)))
      dp_fwd = jax.jit(lambda yp: loss_obj(label, yp))

      row = {'batch': batch, 'dp': dp_impl}
      try:
        t_dp = _timed(
            _bench_common.make_scalar_step(state, loss_obj),
            (state, rows_t, label), args.steps)
        t_xent = _timed(
            _bench_common.make_scalar_step(state, masked_xent),
            (state, rows_t, label), args.steps)
        t_dpg = _timed(dp_grad, (preds_fixed,), args.steps)
        t_dpf = _timed(dp_fwd, (preds_fixed,), args.steps)
        row.update({
            'step_dp_s': round(t_dp, 4),
            'step_xent_s': round(t_xent, 4),
            'dp_grad_s': round(t_dpg, 4),
            'dp_fwd_s': round(t_dpf, 4),
            'examples_per_sec': round(batch / t_dp, 1),
            'dp_share_of_step': round(max(0.0, t_dp - t_xent) / t_dp, 3),
            'model_opt_share': round(t_xent / t_dp, 3),
            'dp_grad_over_fwd': round(t_dpg / max(t_dpf, 1e-9), 2),
        })
      except Exception as e:  # keep earlier rows on tunnel failures
        row['error'] = repr(e)[:200]
      print(json.dumps(row), flush=True)


if __name__ == '__main__':
  main()
