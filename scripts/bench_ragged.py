"""Ragged-dispatch A/B: per-bucket packer fleet vs one ragged stream.

Drives one mixed-length window stream (default 70% L=100, 30% L=200)
through the ConsensusEngine twice on the same weights: once with the
per-bucket packers (the round-12 policy — one compiled forward per
bucket) and once with use_ragged_kernel (ONE pack stream, every width
packed back-to-back into fixed [n_slots, R, slot_len] slots, a single
compiled forward for the whole run). Prints one JSON line per variant
(windows/s, padded-position fraction, per-bucket pack counts,
n_forward_shapes, host-gap-per-pack from trace spans) plus a summary
line with the measured speedup, the padding delta, and a delivery
byte-identity verdict: every window's (ids, quals) from the ragged run
must be identical to the bucketed run's. Exit 1 = identity violation
or the ragged run compiled more than one forward shape — investigate
before reading the perf numbers.

The padded-position fraction and n_forward_shapes are stream
arithmetic (backend-independent); the windows/s delta is what the
measure_r4.sh forward_ragged stage exists to capture on live chips,
and the host-gap-per-pack number (device_compute gaps minus the
h2d-transfer-covered portion, per pack) is the residency signal the
forward_ragged_resident stage watches: a device-resident pack loop
leaves transfer-only gaps.
"""
import argparse
import json
import time


def _fake_rows(params, np, width, batch, seed):
  """Featurized rows at an arbitrary width with the SN rows constant
  per window across positions, as the real featurizer emits them (the
  ragged dispatch ships one SN scalar per window)."""
  rng = np.random.default_rng(seed)
  rows = np.zeros((batch, params.total_rows, width, 1), dtype=np.float32)
  mp = params.max_passes
  rows[:, :mp] = rng.integers(0, 5, size=rows[:, :mp].shape)
  rows[:, mp:2 * mp] = rng.integers(0, 256, size=rows[:, :mp].shape)
  rows[:, 2 * mp:3 * mp] = rng.integers(0, 256, size=rows[:, :mp].shape)
  rows[:, 3 * mp:4 * mp] = rng.integers(0, 3, size=rows[:, :mp].shape)
  rows[:, 4 * mp] = rng.integers(0, 5, size=rows[:, 4 * mp].shape)
  if params.use_ccs_bq:
    rows[:, 4 * mp + 1] = rng.integers(
        -1, params.CCS_BQ_MAX - 1, size=rows[:, 4 * mp + 1].shape)
    sn_lo = 4 * mp + 2
  else:
    sn_lo = 4 * mp + 1
  sn = rng.integers(0, 501, size=(batch, rows.shape[1] - sn_lo, 1, 1))
  rows[:, sn_lo:] = np.broadcast_to(sn, rows[:, sn_lo:].shape)
  return rows


def _mixed_stream(params, np, buckets, n_windows, long_frac, seed=12):
  """n_windows featurized rows with widths drawn from buckets
  (long_frac at the largest), interleaved pseudo-randomly."""
  rng = np.random.default_rng(seed)
  probs = np.full(len(buckets),
                  (1 - long_frac) / max(1, len(buckets) - 1))
  probs[-1] = long_frac
  widths = rng.choice(buckets, size=n_windows, p=probs)
  pools = {int(b): list(_fake_rows(params, np, int(b),
                                   int((widths == b).sum()), 100 + i))
           for i, b in enumerate(buckets) if (widths == b).any()}
  stream = [pools[int(w)].pop() for w in widths]
  return stream, widths


def _host_gap_per_pack(summarize_lib, trace_path, n_packs):
  """device_compute gap accounting from the run's trace spans: the
  residency number is host time per pack NOT covered by an H2D
  transfer."""
  events = summarize_lib.load_trace(trace_path)
  gaps = summarize_lib.device_gaps(events)
  return {
      'n_gaps': gaps['n_gaps'],
      'host_gap_per_pack_s': round(
          gaps['host_gap_s'] / max(1, n_packs), 6),
      'transfer_only_fraction': gaps['transfer_only_fraction'],
  }


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--batch', type=int, default=1024)
  ap.add_argument('--windows', type=int, default=4096)
  ap.add_argument('--long_frac', type=float, default=0.3,
                  help='fraction of windows at the largest bucket')
  ap.add_argument('--buckets', default='',
                  help='comma-separated lengths; default from config')
  ap.add_argument('--config', default='transformer_learn_values+test')
  ap.add_argument('--depth', type=int, default=2,
                  help='dispatch_depth (packs in flight)')
  ap.add_argument('--out', default='',
                  help='also write the summary dict to this JSON path')
  args = ap.parse_args()

  import tempfile

  import jax
  import jax.numpy as jnp
  import numpy as np

  from deepconsensus_tpu.inference import engine as engine_lib
  from deepconsensus_tpu.inference import runner as runner_lib
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib
  from deepconsensus_tpu.obs import summarize as summarize_lib
  from deepconsensus_tpu.obs import trace as trace_lib

  params = config_lib.get_config(args.config)
  config_lib.finalize_params(params, is_training=False)
  buckets = (tuple(int(b) for b in args.buckets.split(','))
             if args.buckets else config_lib.DEFAULT_WINDOW_BUCKETS)
  buckets = config_lib.normalize_window_buckets(buckets, params.max_length)
  variables = model_lib.get_model(params).init(
      jax.random.PRNGKey(0),
      jnp.zeros((1, params.total_rows, params.max_length, 1)))

  stream, widths = _mixed_stream(params, np, buckets, args.windows,
                                 args.long_frac)
  useful = int(widths.sum())
  tmpdir = tempfile.mkdtemp(prefix='bench_ragged_')

  results = {}
  deliveries = {}
  for name, use_ragged in (('bucketed', False), ('ragged', True)):
    options = runner_lib.InferenceOptions(
        batch_size=args.batch, max_passes=params.max_passes,
        max_length=params.max_length, use_ccs_bq=params.use_ccs_bq,
        dispatch_depth=args.depth, window_buckets=buckets,
        use_ragged_kernel=use_ragged)
    runner = runner_lib.ModelRunner(params, dict(variables), options,
                                    mesh=None)
    delivered = {}
    engine = engine_lib.ConsensusEngine(
        runner, options,
        deliver=lambda t, ids, quals, d=delivered: d.__setitem__(
            t, (ids.copy(), quals.copy())))
    # Warm every executable BEFORE the trace starts so compile time
    # lands in neither the windows/s number nor the gap spans. The
    # ragged warmup must dispatch at the packer's exact slot geometry
    # or it would add a second entry to n_forward_shapes.
    if use_ragged:
      packer = engine._packer_for(buckets[0])
      wps = packer.slot_len // buckets[0]
      warm_rows = np.zeros(
          (packer.n_slots, params.total_rows, packer.slot_len, 1),
          np.float32)
      warm_lengths = np.full((packer.n_slots, wps), buckets[0], np.int32)
      runner.finalize(runner.dispatch_ragged(warm_rows, warm_lengths))
    else:
      for b in buckets:
        runner.predict(
            np.zeros((args.batch, params.total_rows, b, 1), np.float32))
    trace_path = f'{tmpdir}/{name}_trace.jsonl'
    trace_lib.configure(trace_path, tier='run')
    try:
      t0 = time.perf_counter()
      engine.submit_formatted(stream, list(range(args.windows)))
      engine.flush()
      dt = time.perf_counter() - t0
    finally:
      trace_lib.configure(None)
    stats = engine.stats()
    if use_ragged:
      rp = engine._ragged_packer
      dispatched = stats['n_packs_by_bucket'][rp.slot_len] * (
          rp.n_slots * rp.slot_len)
    else:
      dispatched = sum(stats['n_packs_by_bucket'][b] * args.batch * b
                       for b in stats['n_packs_by_bucket'])
    line = {
        'variant': name,
        'backend': jax.devices()[0].platform,
        'batch': args.batch,
        'windows': args.windows,
        'windows_per_sec': round(args.windows / dt, 1),
        'padded_position_fraction': round(1 - useful / dispatched, 4),
        'n_packs_by_bucket': {int(b): int(n) for b, n
                              in stats['n_packs_by_bucket'].items()},
        'n_forward_shapes': stats.get('n_forward_shapes', 0),
        'n_starvation_flushes': stats.get('n_starvation_flushes', 0),
        'host_gaps': _host_gap_per_pack(summarize_lib, trace_path,
                                        engine.n_packs),
        'config': args.config,
    }
    results[name] = line
    deliveries[name] = dict(delivered)
    print(json.dumps(line), flush=True)

  # Delivery byte identity: the ragged stream must hand back exactly
  # the bucketed fleet's (ids, quals) for every window.
  identical = len(deliveries['bucketed']) == len(deliveries['ragged'])
  if identical:
    for t, (ids, quals) in deliveries['bucketed'].items():
      got = deliveries['ragged'].get(t)
      if got is None or not (np.array_equal(ids, got[0])
                             and np.array_equal(quals, got[1])):
        identical = False
        break

  buck, rag = results['bucketed'], results['ragged']
  one_shape = rag['n_forward_shapes'] == 1
  summary = {
      'summary': 'ragged_ab',
      'speedup_ragged': round(
          rag['windows_per_sec'] / buck['windows_per_sec'], 3),
      'padding_reduction': round(
          buck['padded_position_fraction']
          - rag['padded_position_fraction'], 4),
      'forward_shapes_collapsed': f'{buck["n_forward_shapes"]} -> '
                                  f'{rag["n_forward_shapes"]}',
      'byte_identical': identical,
      'ragged_single_shape': one_shape,
  }
  print(json.dumps(summary), flush=True)
  if args.out:
    with open(args.out, 'w') as f:
      json.dump({'variants': results, **summary}, f, indent=2)
  return 0 if identical and one_shape else 1


if __name__ == '__main__':
  raise SystemExit(main())
