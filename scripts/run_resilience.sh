#!/usr/bin/env bash
# Runs the fault-injection (resilience) test suite on CPU.
#
# These tests exercise both fault-tolerance layers — inference (per-ZMW
# quarantine, CCS fallback, the pool watchdog's real SIGKILLs,
# crash/resume) and training (checkpoint integrity manifests +
# quarantine, preemption-safe SIGTERM saves, the NaN sentinel's
# rollback, corrupt-shard skip, the crash-loop breaker, and a real
# SIGKILL + truncated-checkpoint restart) — plus the untrusted-input
# data plane (bounded BAM/BGZF/TFRecord decoders, `dctpu validate`
# preflight, and the corruption-fuzz harness) — against synthetic BAMs
# and TFRecord shards, so they need no reference testdata and no
# accelerator. The timeout keeps the suite inside the tier-1 budget;
# the whole run takes a couple of minutes on a laptop.
#
#   scripts/run_resilience.sh             # full resilience suite
#   scripts/run_resilience.sh --io-fuzz   # corruption-fuzz stage only,
#                                         # at 2000 mutants per format
#   scripts/run_resilience.sh --serve     # `dctpu serve` stage only:
#                                         # engine boundary + service
#                                         # fault drills + the real
#                                         # SIGTERM-under-load drain
#   scripts/run_resilience.sh --device    # device fault domain only:
#                                         # typed XLA faults, dispatch
#                                         # watchdog, OOM bisection,
#                                         # mesh degradation (dp 8->4)
#                                         # incl. byte-identity drills
#   scripts/run_resilience.sh --elastic   # elastic multi-host domain
#                                         # only: bounded pod barriers
#                                         # (timeout sweep), the
#                                         # kill-one-host rebuild drill
#                                         # and the re-admission drill
#                                         # (in-process threaded pods),
#                                         # plus the real subprocess
#                                         # SIGKILL drill through the
#                                         # CLI (slow, included here)
#   scripts/run_resilience.sh --flywheel  # flywheel durability only:
#                                         # journal round-trip, resume
#                                         # skip/re-entry, stale-journal
#                                         # rejection, stage retries +
#                                         # breaker, plus the slow
#                                         # subprocess SIGKILL-at-every-
#                                         # stage-boundary drill through
#                                         # the CLI (--resume completes
#                                         # each killed cycle)
#   scripts/run_resilience.sh --fleet     # fleet tier only: `dctpu
#                                         # route` balancing + retry
#                                         # semantics, featurize
#                                         # workers, protocol version
#                                         # negotiation, probe
#                                         # hysteresis, weighted-fair
#                                         # QoS + quota sheds, the
#                                         # preemption notice drain,
#                                         # autoscaler scale-out/in/
#                                         # replace drills (the real-
#                                         # subprocess autoscale +
#                                         # forced-preemption demo is
#                                         # scripts/soak_e2e.py
#                                         # --fleet 2)
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--io-fuzz" ]]; then
  shift
  # A deeper sweep of just the decoder fuzz + native-parity tests.
  # DCTPU_FUZZ_MUTANTS scales every fuzz loop (default 500 in-suite).
  exec timeout -k 10 1200 env JAX_PLATFORMS=cpu \
    DCTPU_FUZZ_MUTANTS="${DCTPU_FUZZ_MUTANTS:-2000}" \
    python -m pytest tests/test_io_fuzz.py tests/test_native.py \
    -q -m resilience --continue-on-collection-errors "$@"
fi

if [[ "${1:-}" == "--serve" ]]; then
  shift
  # The serving stage in isolation, slow tests included (the
  # subprocess SIGTERM drain is the acceptance demo).
  exec timeout -k 10 900 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_engine.py tests/test_serve.py \
    tests/test_window_packer.py \
    -q --continue-on-collection-errors "$@"
fi

if [[ "${1:-}" == "--device" ]]; then
  shift
  # The device fault domain in isolation: fault classification, the
  # dispatch watchdog, OOM bisection, and dp 8->4 mesh degradation —
  # inference (test_device_faults) AND training (test_train_parallel:
  # partition rules, prefetch overlap, the mid-training device-lost
  # degradation ladder). Multichip drills run on the 8 faked CPU
  # devices conftest.py forces via
  # --xla_force_host_platform_device_count.
  exec timeout -k 10 900 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_device_faults.py \
    tests/test_train_parallel.py \
    -q --continue-on-collection-errors "$@"
fi

if [[ "${1:-}" == "--elastic" ]]; then
  shift
  # The elastic multi-host domain in isolation, slow tests included
  # (the subprocess SIGKILL drill through the CLI is the acceptance
  # demo): bounded barriers, coordinated pod rebuild, host
  # re-admission, and the bounded legacy collectives (stop vote,
  # orbax save).
  exec timeout -k 10 1200 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_elastic.py \
    -q --continue-on-collection-errors "$@"
fi

if [[ "${1:-}" == "--flywheel" ]]; then
  shift
  # The flywheel durability domain in isolation, slow drills included
  # (the subprocess SIGKILL-per-stage drill is the ROADMAP item 3
  # acceptance demo; each killed cycle is a real `dctpu flywheel`
  # train->distill->gates->export on synthetic shards).
  # DCTPU_FLYWHEEL_DRILL=1 unlocks the ~20-minute drill tests that the
  # default resilience run (600 s budget) skips.
  exec timeout -k 10 2400 env JAX_PLATFORMS=cpu \
    DCTPU_FLYWHEEL_DRILL=1 \
    python -m pytest tests/test_flywheel_resilience.py \
    -q --continue-on-collection-errors "$@"
fi

if [[ "${1:-}" == "--fleet" ]]; then
  shift
  # The fleet tier in isolation: router + registry (incl. probe
  # hysteresis) + balancer (weighted-fair admission, quotas) +
  # featurize-worker + autoscaler + preemption semantics, all
  # in-process (fast).
  exec timeout -k 10 900 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_fleet.py \
    -q --continue-on-collection-errors "$@"
fi

timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python -m pytest tests/ -q -m resilience \
  --continue-on-collection-errors "$@"
