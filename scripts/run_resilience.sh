#!/usr/bin/env bash
# Runs the fault-injection (resilience) test suite on CPU.
#
# These tests exercise the inference fault-tolerance layer — per-ZMW
# quarantine, CCS fallback, the pool watchdog (real SIGKILLs), and
# crash/resume — against synthetic BAMs, so they need no reference
# testdata and no accelerator. The timeout keeps the suite inside the
# tier-1 budget; the whole run takes well under a minute on a laptop.
set -euo pipefail
cd "$(dirname "$0")/.."

timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python -m pytest tests/ -q -m resilience \
  --continue-on-collection-errors "$@"
