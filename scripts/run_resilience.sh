#!/usr/bin/env bash
# Runs the fault-injection (resilience) test suite on CPU.
#
# These tests exercise both fault-tolerance layers — inference (per-ZMW
# quarantine, CCS fallback, the pool watchdog's real SIGKILLs,
# crash/resume) and training (checkpoint integrity manifests +
# quarantine, preemption-safe SIGTERM saves, the NaN sentinel's
# rollback, corrupt-shard skip, the crash-loop breaker, and a real
# SIGKILL + truncated-checkpoint restart) — against synthetic BAMs and
# TFRecord shards, so they need no reference testdata and no
# accelerator. The timeout keeps the suite inside the tier-1 budget;
# the whole run takes a couple of minutes on a laptop.
set -euo pipefail
cd "$(dirname "$0")/.."

timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python -m pytest tests/ -q -m resilience \
  --continue-on-collection-errors "$@"
