"""Error-analysis walkthrough over labeled examples (notebook-style).

Counterpart of the reference's notebook workflow (reference:
notebooks/ + utils/colab_utils.py:28-159): run a model over labeled
eval windows, then break errors down per window — identity, edit
distance, homopolymer content — print base-level diff views for the
worst windows, and aggregate the most error-prone k-mer contexts.

Usage (bundled testdata, random weights unless --checkpoint):

  python scripts/error_analysis.py \
      --examples '/root/reference/deepconsensus/testdata/human_1m/tf_examples/eval/*' \
      [--checkpoint model_out/checkpoints/checkpoint-38] \
      [--limit 50] [--worst 3] [--json report.json]
"""
import argparse
import json
import sys


def main(argv=None):
  ap = argparse.ArgumentParser(
      description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
  ap.add_argument('--examples', required=True,
                  help='labeled TFRecord pattern (eval/test split)')
  ap.add_argument('--checkpoint', default=None,
                  help='orbax checkpoint dir; random init when absent')
  ap.add_argument('--config', default='transformer_learn_values+test')
  ap.add_argument('--limit', type=int, default=100,
                  help='max examples to analyze')
  ap.add_argument('--worst', type=int, default=3,
                  help='print diff views for this many worst windows')
  ap.add_argument('--kmer', type=int, default=5)
  ap.add_argument('--json', default=None,
                  help='also write the summary as JSON here')
  ap.add_argument('--cpu', action='store_true', help='force CPU backend')
  args = ap.parse_args(argv)

  import jax

  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import numpy as np

  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import data as data_lib
  from deepconsensus_tpu.models import model as model_lib
  from deepconsensus_tpu.utils import analysis, phred

  if args.checkpoint:
    params = config_lib.read_params_from_json(args.checkpoint)
    config_lib.finalize_params(params, is_training=False)
  else:
    params = config_lib.get_config(args.config)
    config_lib.finalize_params(params, is_training=False)
  model = model_lib.get_model(params)
  if args.checkpoint:
    from deepconsensus_tpu.models.checkpoints import load_params

    variables = {'params': load_params(args.checkpoint)}
  else:
    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, params.total_rows, params.max_length, 1)))

  batch = 32
  ds = data_lib.DatasetIterator(
      patterns=args.examples, params=params, batch_size=batch,
      shuffle=False, drop_remainder=False, limit=args.limit,
  )
  apply_fn = jax.jit(model.apply)

  per_window = []
  pairs = []
  for start in range(0, len(ds.rows), batch):
    rows = ds.rows[start:start + batch]
    labels = ds.labels[start:start + batch]
    preds = np.asarray(apply_fn(variables, jnp.asarray(rows)))
    pred_ids = preds.argmax(-1)
    for i in range(len(rows)):
      truth = phred.encoded_sequence_to_string(
          labels[i].astype(np.int32)).replace(' ', '')
      pred = phred.encoded_sequence_to_string(pred_ids[i]).replace(' ', '')
      dist = analysis.edit_distance(truth, pred)
      # Normalize by the longer sequence so identity stays in [0, 1]
      # even when the prediction is longer than the truth.
      denom = max(len(truth), len(pred), 1)
      per_window.append({
          'index': start + i,
          'edit_distance': dist,
          'identity': round(1.0 - dist / denom, 4),
          'truth_len': len(truth),
          'pred_len': len(pred),
          'homopolymer_content': analysis.homopolymer_content(truth),
      })
      pairs.append((truth, pred))

  n = len(per_window)
  idents = np.array([w['identity'] for w in per_window])
  dists = np.array([w['edit_distance'] for w in per_window])
  hp = np.array([w['homopolymer_content'] for w in per_window])
  err_mask = dists > 0
  summary = {
      'n_windows': n,
      'mean_identity': round(float(idents.mean()), 4),
      'median_identity': round(float(np.median(idents)), 4),
      'perfect_windows': int((dists == 0).sum()),
      'mean_edit_distance': round(float(dists.mean()), 2),
      'mean_homopolymer_content': round(float(hp.mean()), 3),
      'mean_homopolymer_content_error_windows': (
          round(float(hp[err_mask].mean()), 3) if err_mask.any() else None),
      'top_error_kmers': analysis.summarize_errors(
          pairs, k=args.kmer, top=10),
  }

  print(f'# Error analysis: {n} windows '
        f'({"checkpoint " + args.checkpoint if args.checkpoint else "random weights"})')
  for key, value in summary.items():
    if key != 'top_error_kmers':
      print(f'{key}: {value}')
  print('top error k-mer contexts (truth-centered):')
  for kmer, count in summary['top_error_kmers']:
    print(f'  {kmer}: {count}')

  worst = sorted(per_window, key=lambda w: w['identity'])[:args.worst]
  for w in worst:
    truth, pred = pairs[w['index']]
    print(f"\n## window {w['index']}: identity {w['identity']}, "
          f"edit distance {w['edit_distance']}, "
          f"homopolymer {w['homopolymer_content']}")
    print(analysis.format_diff(truth, pred))

  if args.json:
    with open(args.json, 'w') as f:
      json.dump({'summary': summary, 'per_window': per_window}, f, indent=1)
    print(f'\nwrote {args.json}')
  return 0


if __name__ == '__main__':
  sys.exit(main())
