#!/bin/bash
# One-shot round-4 TPU measurement sweep. Run when the tunnel is alive:
#   bash scripts/measure_r4.sh
# Each stage has its own timeout so a tunnel hang mid-sweep keeps the
# completed stages; results accumulate in /root/repo/MEASURED_TPU_r4.d/
# and merge into MEASURED_TPU_r4.json at the end (safe to re-run:
# stages overwrite their own files only on success).
#
# IMPORTANT (1-core host): stop background CPU jobs (trainers, pytest,
# probe loops) first, or host-side stages are poisoned.
#
# Coverage (VERDICT r3): #1 headline numbers, #2 e2e dispatch-depth
# sweep toward >=40 ZMW/s, #4 train stage shares + unroll A/B, #5
# forward MFU attribution + the b2048 regression, #6 loader native A/B.
set -u
REPO=/root/repo
OUT=$REPO/MEASURED_TPU_r4.d
mkdir -p "$OUT"
export PYTHONPATH=$REPO:/root/.axon_site
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/root/.dc_jax_cache}

run_stage() {  # name timeout_s cmd...
  local name=$1 t=$2; shift 2
  echo "=== stage $name (timeout ${t}s) ==="
  if timeout "$t" "$@" > "$OUT/$name.tmp" 2> "$OUT/$name.err"; then
    grep -E '^\{' "$OUT/$name.tmp" > "$OUT/$name.jsonl" || true
    tail -3 "$OUT/$name.jsonl"
  else
    echo "stage $name FAILED rc=$? (see $OUT/$name.err)"
    # Keep any JSON lines the stage finished before hanging — losing
    # b1024 because b2048 hit a tunnel hang defeats the sweep's point.
    # Never clobber a PREVIOUS run's complete results with an empty or
    # shorter partial (re-run safety: overwrite only when better).
    grep -E '^\{' "$OUT/$name.tmp" > "$OUT/$name.partial" 2>/dev/null || true
    old_n=$(wc -l < "$OUT/$name.jsonl" 2>/dev/null || echo 0)
    new_n=$(wc -l < "$OUT/$name.partial")
    if [ "$new_n" -gt "$old_n" ]; then
      mv "$OUT/$name.partial" "$OUT/$name.jsonl"
      echo "  (kept $new_n partial result lines)"
    else
      rm -f "$OUT/$name.partial"
    fi
  fi
}

# Cheapest/most-informative first so a fragile tunnel still yields the
# headline numbers.
run_stage forward_profile 900 \
  python "$REPO/scripts/profile_forward.py" --batches 1024 2048 --steps 10
# MFU lever A/Bs (values must match the default: tests lock equivalence).
run_stage forward_onehot 600 \
  python "$REPO/scripts/profile_forward.py" --batches 1024 --steps 10 \
  --set embed_onehot=true
run_stage forward_bf16_softmax 600 \
  python "$REPO/scripts/profile_forward.py" --batches 1024 --steps 10 \
  --set attn_softmax_dtype=bfloat16
# Fused hot-path A/B (round-6 beat-or-retire, VERDICT #3): batch-major
# Pallas embed->condense->attention vs the XLA default at the
# production L=100. Compare 'full' windows/s against forward_profile's
# b1024 line; the fused kernel also folds in the onehot + softmax-dtype
# levers, so read it against those stages too.
run_stage forward_fused 600 \
  python "$REPO/scripts/profile_forward.py" --batches 1024 --steps 10 \
  --set use_fused_hotpath=true
run_stage forward_fused_tile16 600 \
  env DC_TPU_FUSED_TILE=16 \
  python "$REPO/scripts/profile_forward.py" --batches 1024 --steps 10 \
  --set use_fused_hotpath=true
# Quantized-inference levers on the distilled student (round-10
# beat-or-retire): f32/bf16/int8 through the full-encoder fused blocks
# at the production L=100 and b1024. forward_student_f32 is the anchor
# every lever stage reads against (same weights-shape model, same fused
# routing — the lever is the only change); forward_fullfused is the
# shipping configuration (bf16 activations + int8 matmuls). Decision
# rule (docs/performance.md): a lever that does not beat the f32 fused
# anchor on windows/s at equal accuracy gates is retired, not tuned.
run_stage forward_student_f32 600 \
  python "$REPO/scripts/profile_forward.py" --batches 1024 --steps 10 \
  --config transformer_learn_values_distill+test \
  --set use_fused_hotpath=true
run_stage forward_bf16 600 \
  python "$REPO/scripts/profile_forward.py" --batches 1024 --steps 10 \
  --config transformer_learn_values_distill+test \
  --set use_fused_hotpath=true --set inference_dtype=bfloat16
run_stage forward_int8 600 \
  python "$REPO/scripts/profile_forward.py" --batches 1024 --steps 10 \
  --config transformer_learn_values_distill+test \
  --set use_fused_hotpath=true --set quantize_matmuls=int8
run_stage forward_fullfused 600 \
  python "$REPO/scripts/profile_forward.py" --batches 1024 --steps 10 \
  --config transformer_learn_values_distill+test \
  --set use_fused_hotpath=true --set inference_dtype=bfloat16 \
  --set quantize_matmuls=int8
# Device-resident output plane (round-11 beat-or-retire): uint8
# (ids, quals) drained instead of int32 ids + f32 max_prob, quality
# computed on device via the threshold table. The bytes/pack 4x is
# already proven on CPU (bench.py d2h_bytes stage); THIS stage decides
# windows/s over a real tunnel, where the 4x smaller drain shortens
# the serialized tail of each transfer/compute overlap window.
# Decision rule in docs/performance.md (exit 1 = identity violation —
# investigate before reading the perf numbers).
run_stage forward_epilogue 600 \
  python "$REPO/scripts/bench_epilogue.py" --batch 1024 --packs 8 \
  --config transformer_learn_values_distill+test --fused
# Bucketed variable-length windows (round-12 beat-or-retire): one
# mixed L={100,200} stream through the engine, pad-to-max vs
# per-bucket packs. Reads: speedup_bucketed vs the padding_reduction
# (the win should track the padded-position fraction removed), and
# n_forward_shapes (=2: bucketing pays exactly one extra trace).
# Exit 1 = per-bucket byte-identity violation — investigate first.
run_stage forward_bucketed 900 \
  python "$REPO/scripts/bench_bucketed.py" --batch 1024 --windows 4096 \
  --fused
# Single ragged pack stream (round-13 beat-or-retire): the same mixed
# L={100,200} stream, per-bucket packer fleet vs use_ragged_kernel
# (one compiled forward for the whole run). Reads: speedup_ragged
# (decision rule in docs/performance.md: >= 1.15x windows/s on the
# mixed stream keeps ragged as the mixed-width default, else it
# retires to opt-in), padding_reduction (slot packing should beat
# per-bucket pad rows), and forward_shapes_collapsed (must end at 1).
# Exit 1 = delivery byte-identity violation or a second compiled
# shape — investigate before reading the perf numbers.
run_stage forward_ragged 900 \
  python "$REPO/scripts/bench_ragged.py" --batch 1024 --windows 4096
# Residency read of the same A/B at depth 4: with more packs in
# flight the host-gap-per-pack number from the trace spans is the
# signal — a device-resident pack loop leaves compute gaps that are
# transfer-covered (transfer_only_fraction -> 1.0), so host time per
# pack should shrink vs the depth-2 forward_ragged stage, not grow.
run_stage forward_ragged_resident 900 \
  python "$REPO/scripts/bench_ragged.py" --batch 1024 --windows 4096 \
  --depth 4
# dp-sharded double-buffered dispatch (round-6 tentpole): real-chip dp
# scaling of windows/s + transfer-overlap fraction. Staged to fire on
# first live tunnel; until then the host-platform parity sweep lives
# in MULTICHIP_r06.json (bench.py dp_scaling stage). Read against
# forward_profile's b1024 line: dp>1 only earns its keep if windows/s
# scales while the overlap fraction stays near (packs-1)/packs.
run_stage forward_dp2 600 \
  python "$REPO/scripts/bench_dp_scaling.py" --dp 2 --batch 1024 --packs 8
run_stage forward_dp4 600 \
  python "$REPO/scripts/bench_dp_scaling.py" --dp 4 --batch 1024 --packs 8
run_stage e2e_depth8 1200 \
  python "$REPO/scripts/bench_e2e.py" --repeats 6 --depth 8
run_stage e2e_depth1 600 \
  python "$REPO/scripts/bench_e2e.py" --repeats 4 --depth 1
run_stage e2e_depth16_zmws400 900 \
  python "$REPO/scripts/bench_e2e.py" --repeats 6 --depth 16 --batch_zmws 400
run_stage train_stages_b256 900 \
  python "$REPO/scripts/bench_train_stages.py" --batches 256 --steps 6 --scan-too
run_stage train_scaling 1200 \
  python "$REPO/scripts/bench_train_scaling.py" --batches 256 1024 --steps 6
# Pod-scale training (round-7 tentpole): real-chip dp scaling of the
# partition-rule pjit train step + prefetch-overlapped batches.
# Staged to fire on first live tunnel; until then the host-platform
# plumbing sweep lives in MULTICHIP_r07.json (bench.py
# train_dp_scaling stage). Read against train_scaling's b1024 line:
# dp>1 earns its keep if examples/s scales while
# train_transfer_overlap_fraction stays at (steps-1)/steps and the
# loss-curve digest matches dp=1 at equal global batch.
run_stage train_dp2 900 \
  python "$REPO/scripts/bench_train_scaling.py" --dp 2 --global_batch 1024 \
  --train_steps 6
run_stage train_dp4 900 \
  python "$REPO/scripts/bench_train_scaling.py" --dp 4 --global_batch 1024 \
  --train_steps 6
# Bucketed multi-width training (round-20 beat-or-retire): a mixed
# L={100,200} stream, per-bucket width-pure batches with one compiled
# step per bucket. Reads: n_train_forward_shapes (must equal 2 — zero
# mid-run retraces), train_padding_fraction vs padding_fraction_padmax
# (the same stream under the old pad-to-widest policy; the examples/s
# win should track the padded positions removed), and examples/s
# against the train_scaling b1024 anchor. Decision rule in
# docs/performance.md: bucketing stays the mixed-width training
# default only if examples/s beats pad-to-max on this stage.
run_stage train_bucketed 900 \
  python "$REPO/scripts/bench_train_scaling.py" --dp 4 --global_batch 1024 \
  --train_steps 6 --window_buckets 100,200
# Long-insert training (round-20): L=500 windows route the attention
# forward+backward through the blockwise ring scan
# (parallel/ring_attention.py; fused Pallas is L<=128-only, plain XLA
# attention materializes the full 500x500 score matrix per head).
# Reads: examples/s and peak HBM headroom at batch 256; parity vs the
# XLA path is locked at atol<=1e-4 in tests/test_longwin_training.py.
run_stage train_L500 1200 \
  python "$REPO/scripts/bench_train_scaling.py" --dp 4 --global_batch 256 \
  --train_steps 6 --window_buckets 500
run_stage train_stages_b1024 900 \
  python "$REPO/scripts/bench_train_stages.py" --batches 1024 --steps 6
# Pallas wavefront unroll A/B under the persistent compile cache
# (r2 backlog): module default 8 vs 1 vs 16.
for u in 1 16; do
  run_stage "train_unroll_$u" 900 env DC_TPU_PALLAS_UNROLL=$u \
    python "$REPO/scripts/bench_train_stages.py" --batches 1024 --steps 6
done
run_stage flash_band 900 \
  python "$REPO/scripts/bench_flash_band.py"
# Banded alignment-DP scan-vs-Pallas A/B (round-5 kernel).
run_stage banded_dp 900 \
  python "$REPO/scripts/bench_banded_dp.py" --batch 256 --steps 5
# Host-only (loader never touches the chip, but run it inside the sweep
# so the core is otherwise idle).
run_stage loader 900 \
  python "$REPO/scripts/bench_loader.py" --workers 0 2 3

python - <<'EOF'
import json, os, glob
out = {}
d = '/root/repo/MEASURED_TPU_r4.d'
for f in sorted(glob.glob(os.path.join(d, '*.jsonl'))):
    rows = [json.loads(l) for l in open(f) if l.strip()]
    out[os.path.basename(f)[:-6]] = rows
with open('/root/repo/MEASURED_TPU_r4.json', 'w') as fh:
    json.dump(out, fh, indent=1)
print('merged ->', '/root/repo/MEASURED_TPU_r4.json')
EOF
