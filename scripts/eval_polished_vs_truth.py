"""Read-level polished-vs-truth assessment with in-repo tools only.

The reference's yield@Q workflow maps polished reads back to the truth
assembly with an external aligner before `yield_metrics` (reference:
docs/yield_metrics.md); the aligner stays out-of-repo (L0 external
tools). For the bundled 10-ZMW testdata the truth sequence *per ZMW*
is already available from truth_to_ccs.bam, so this script scores each
polished read directly: Levenshtein identity and empirical QV of the
polished sequence and of the raw CCS sequence against that ZMW's
truth, plus the read's mean predicted quality. That is the read-level
counterpart of the window eval metrics (eval/identity_pred vs
eval/identity_ccs) and closes the train -> run -> truth loop for the
training-accuracy artifact.

Usage:
  python scripts/eval_polished_vs_truth.py \
      --polished polished.fastq \
      --ccs_bam testdata/human_1m/ccs.bam \
      --truth_to_ccs testdata/human_1m/truth_to_ccs.bam \
      [--json report.json]
"""
import argparse
import json
import math
import sys


def _empirical_qv(dist, length):
  if length == 0:
    return 0.0
  err = max(dist, 0) / length
  if err <= 0:
    # Error-free at this length; cap like QV tools do.
    return round(10.0 * math.log10(length), 1)
  return round(-10.0 * math.log10(err), 1)


def main(argv=None):
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument('--polished', required=True, help='polished FASTQ')
  ap.add_argument('--ccs_bam', required=True)
  ap.add_argument('--truth_to_ccs', required=True)
  ap.add_argument('--json', default=None)
  ap.add_argument('--yield_csv', default=None,
                  help='also write the reference-style yield@emQ table '
                  '(calibration.yield_metrics.yield_at_thresholds: per '
                  'predicted-Q threshold, reads kept and bases in reads '
                  'with empirical identity >= 0.999) for the polished '
                  'reads AND the raw CCS baseline, to this CSV')
  args = ap.parse_args(argv)

  from deepconsensus_tpu.io import bam as bam_lib
  from deepconsensus_tpu.io import fastx
  from deepconsensus_tpu.utils import analysis, phred

  truth_by_ccs_name = {}
  for rec in bam_lib.BamReader(args.truth_to_ccs):
    # Primary alignments only: a supplementary/secondary record carries
    # a hard-clipped fragment that must not replace the full truth seq
    # (same guard as preprocess/feeder.py and calibration/measure.py).
    if rec.is_supplementary or rec.is_secondary:
      continue
    if rec.reference_name is not None and rec.seq:
      truth_by_ccs_name[rec.reference_name] = rec.seq
  ccs_by_name = {}
  ccs_quals_by_name = {}
  for rec in bam_lib.BamReader(args.ccs_bam):
    if rec.is_supplementary or rec.is_secondary:
      continue
    ccs_by_name[rec.qname] = rec.seq
    ccs_quals_by_name[rec.qname] = rec.quals
  polished = {
      name: (seq, qual) for name, seq, qual in fastx.read_fastq(
          args.polished)
  }

  rows = []
  dist_cache = {}  # (kind, name) -> edit distance, reused by --yield_csv
  for name, (seq, qual) in sorted(polished.items()):
    truth = truth_by_ccs_name.get(name)
    ccs_seq = ccs_by_name.get(name)
    if truth is None or ccs_seq is None:
      print(f'# {name}: no bundled truth/ccs record, skipped',
            file=sys.stderr)
      continue
    d_pred = analysis.edit_distance(seq, truth)
    d_ccs = analysis.edit_distance(ccs_seq, truth)
    dist_cache[('polished', name)] = d_pred
    dist_cache[('ccs', name)] = d_ccs
    tl = len(truth)
    rows.append({
        'read': name,
        'len_polished': len(seq),
        'len_truth': tl,
        'identity_polished': round(1.0 - d_pred / max(tl, 1), 5),
        'identity_ccs': round(1.0 - d_ccs / max(tl, 1), 5),
        'qv_polished': _empirical_qv(d_pred, tl),
        'qv_ccs': _empirical_qv(d_ccs, tl),
        'mean_pred_q': round(
            phred.avg_phred(phred.quality_string_to_array(qual)), 1),
    })

  if not rows:
    print('no scorable reads', file=sys.stderr)
    return 1
  n = len(rows)
  summary = {
      'n_reads': n,
      'mean_identity_polished': round(
          sum(r['identity_polished'] for r in rows) / n, 5),
      'mean_identity_ccs': round(
          sum(r['identity_ccs'] for r in rows) / n, 5),
      'mean_qv_polished': round(
          sum(r['qv_polished'] for r in rows) / n, 1),
      'mean_qv_ccs': round(sum(r['qv_ccs'] for r in rows) / n, 1),
      'reads_polished_better_or_equal': sum(
          1 for r in rows if r['qv_polished'] >= r['qv_ccs']),
  }
  print(json.dumps(summary))
  for r in rows:
    print(json.dumps(r))
  if args.json:
    with open(args.json, 'w') as f:
      json.dump({'summary': summary, 'per_read': rows}, f, indent=1)

  if args.yield_csv:
    # The reference's yield@emQ statistic on the bundled truth set,
    # via the same yield_at_thresholds the aligned-BAM tool uses
    # (reference docs/yield_metrics.md:80-98: Q-filter on PREDICTED
    # avg quality, then bases in reads with empirical identity >=
    # 0.999). Identity here is 1 - d/max(|read|, |truth|) from the
    # Levenshtein distance — the denominator is a lower bound on the
    # alignment length, so the identity (and the yield) is
    # conservative; at the <=0.001 error scale the bar tests, the
    # difference from an aligner's matches/alignment_length is
    # negligible. The whole edit budget is recorded under
    # `mismatches` (no backtrack; only identity feeds the yield bar).
    import csv as csv_lib

    import numpy as np

    from deepconsensus_tpu.calibration import yield_metrics as ym

    from deepconsensus_tpu import constants

    def assessment(kind, name, seq, avg_q, truth):
      # Strip the codebase gap token the same way edit_distance does,
      # so numerator and denominator see identical sequences. The
      # O(len^2) distance dominates this script's cost, so reuse the
      # main loop's result where available.
      seq_nogap = seq.replace(constants.GAP, '')
      truth_nogap = truth.replace(constants.GAP, '')
      d = dist_cache.get((kind, name))
      if d is None:
        d = analysis.edit_distance(seq_nogap, truth_nogap)
      aligned = max(len(seq_nogap), len(truth_nogap))
      return ym.ReadAssessment(
          name=name, length=len(seq_nogap), avg_quality=avg_q,
          matches=aligned - d, mismatches=d, insertions=0, deletions=0)

    tables = {}
    for label, reads in (
        ('polished', [
            assessment(
                'polished', name, seq,
                phred.avg_phred(phred.quality_string_to_array(qual)),
                truth_by_ccs_name[name])
            for name, (seq, qual) in sorted(polished.items())
            if name in truth_by_ccs_name
        ]),
        ('ccs', [
            assessment(
                'ccs', name, ccs_by_name[name],
                # quals is None for the BAM 0xFF no-quality sentinel
                # (same guard as yield_metrics.assess_read).
                phred.avg_phred(
                    ccs_quals_by_name[name]
                    if ccs_quals_by_name[name] is not None
                    else np.empty(0)),
                truth)
            for name, truth in sorted(truth_by_ccs_name.items())
            if name in ccs_by_name
        ]),
    ):
      tables[label] = ym.yield_at_thresholds(reads)
    with open(args.yield_csv, 'w', newline='') as f:
      writer = csv_lib.DictWriter(
          f, fieldnames=['reads'] + list(tables['polished'][0].keys()))
      writer.writeheader()
      for label, table in tables.items():
        for row in table:
          writer.writerow({'reads': label, **row})
    print(json.dumps({'yield_csv': args.yield_csv, **{
        f'{label}_yield_at_q{row["quality_threshold"]}': row['yield_bases']
        for label, table in tables.items() for row in table}}))
  return 0


if __name__ == '__main__':
  sys.exit(main())
