"""Bucketed-dispatch A/B: pad-to-max vs per-bucket packing.

Drives one mixed-length window stream (default 70% L=100, 30% L=200)
through the ConsensusEngine twice on the same weights: once with a
single max-width bucket (every window padded to the largest length —
the pre-round-12 policy) and once with the configured buckets. Prints
one JSON line per variant (windows/s, padded-position fraction,
per-bucket pack counts, compile count) plus a summary line with the
measured speedup, the padding reduction, and a per-bucket
byte-identity verdict: each bucket's windows must come back identical
to the same windows run through a dedicated single-bucket engine.
Exit 1 = identity violation — investigate before reading the perf
numbers. The padded-position fraction is stream arithmetic
(backend-independent); the windows/s delta is what the measure_r4.sh
forward_bucketed stage exists to capture on live chips.
"""
import argparse
import json
import time


def _make_engine(engine_lib, runner_lib, params, variables, batch, buckets):
  options = runner_lib.InferenceOptions(
      batch_size=batch, max_passes=params.max_passes,
      max_length=params.max_length, use_ccs_bq=params.use_ccs_bq)
  options.window_buckets = buckets
  runner = runner_lib.ModelRunner(params, dict(variables), options,
                                  mesh=None)
  delivered = {}
  engine = engine_lib.ConsensusEngine(
      runner, options,
      deliver=lambda t, ids, quals: delivered.__setitem__(t, (ids, quals)))
  return engine, delivered


def _run_stream(engine, delivered, stream, warmup_shapes, params, np):
  import numpy as _np

  del np
  for b, batch in warmup_shapes:
    engine.runner.predict(
        _np.zeros((batch, params.total_rows, b, 1), _np.float32))
  delivered.clear()
  t0 = time.perf_counter()
  engine.submit(stream, list(range(len(stream))))
  engine.flush()
  return time.perf_counter() - t0


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--batch', type=int, default=1024)
  ap.add_argument('--windows', type=int, default=4096)
  ap.add_argument('--long_frac', type=float, default=0.3,
                  help='fraction of windows at the largest bucket')
  ap.add_argument('--buckets', default='',
                  help='comma-separated lengths; default from config')
  ap.add_argument('--config', default='transformer_learn_values+test')
  ap.add_argument('--fused', action='store_true',
                  help='enable the fused hot path (per-bucket eligible: '
                       'only traces at L <= the VMEM limit use it)')
  args = ap.parse_args()

  import jax
  import jax.numpy as jnp
  import numpy as np

  from deepconsensus_tpu.inference import engine as engine_lib
  from deepconsensus_tpu.inference import runner as runner_lib
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib

  params = config_lib.get_config(args.config)
  if args.fused:
    with params.unlocked():
      params.use_fused_hotpath = True
  config_lib.finalize_params(params, is_training=False)
  buckets = (tuple(int(b) for b in args.buckets.split(','))
             if args.buckets else config_lib.DEFAULT_WINDOW_BUCKETS)
  buckets = config_lib.normalize_window_buckets(buckets, params.max_length)
  max_b = max(buckets)
  variables = model_lib.get_model(params).init(
      jax.random.PRNGKey(0),
      jnp.zeros((1, params.total_rows, params.max_length, 1)))

  rng = np.random.default_rng(12)
  probs = np.full(len(buckets), (1 - args.long_frac) / max(1, len(buckets) - 1))
  probs[-1] = args.long_frac
  widths = rng.choice(buckets, size=args.windows, p=probs)
  wins = [rng.integers(0, 5, size=(params.total_rows, int(w), 1))
          .astype(np.float32) for w in widths]
  padded = [np.pad(w, ((0, 0), (0, max_b - w.shape[1]), (0, 0)))
            for w in wins]
  useful = int(widths.sum())

  results = {}
  deliveries = {}
  for name, variant_buckets, stream in (
      ('pad_to_max', (max_b,), padded),
      ('bucketed', buckets, wins)):
    engine, delivered = _make_engine(
        engine_lib, runner_lib, params, variables, args.batch,
        variant_buckets)
    dt = _run_stream(engine, delivered,
                     stream, [(b, args.batch) for b in variant_buckets],
                     params, np)
    stats = engine.stats()
    dispatched = sum(stats['n_packs_by_bucket'][b] * args.batch * b
                     for b in stats['n_packs_by_bucket'])
    line = {
        'variant': name,
        'backend': jax.devices()[0].platform,
        'batch': args.batch,
        'windows': args.windows,
        'windows_per_sec': round(args.windows / dt, 1),
        'padded_position_fraction': round(1 - useful / dispatched, 4),
        'n_packs_by_bucket': {int(b): int(n) for b, n
                              in stats['n_packs_by_bucket'].items()},
        'n_forward_shapes': stats.get('n_forward_shapes', 0),
        'config': args.config,
        'fused': args.fused,
    }
    results[name] = line
    deliveries[name] = dict(delivered)
    print(json.dumps(line), flush=True)

  # Per-bucket byte identity: each width's windows through a dedicated
  # single-bucket engine must match the bucketed run's deliveries.
  identical = True
  for b in buckets:
    idx = [i for i, w in enumerate(widths) if w == b]
    if not idx:
      continue
    solo_engine, solo_delivered = _make_engine(
        engine_lib, runner_lib, params, variables, args.batch, (int(b),))
    _run_stream(solo_engine, solo_delivered, [wins[i] for i in idx],
                [(int(b), args.batch)], params, np)
    for k, i in enumerate(idx):
      got = deliveries['bucketed'][i]
      want = solo_delivered[k]
      if not (np.array_equal(got[0], want[0])
              and np.array_equal(got[1], want[1])):
        identical = False
        break

  pad, buck = results['pad_to_max'], results['bucketed']
  print(json.dumps({
      'summary': 'bucketed_ab',
      'speedup_bucketed': round(
          buck['windows_per_sec'] / pad['windows_per_sec'], 3),
      'padding_reduction': round(
          pad['padded_position_fraction']
          - buck['padded_position_fraction'], 4),
      'byte_identical_per_bucket': identical,
  }), flush=True)
  return 0 if identical else 1


if __name__ == '__main__':
  raise SystemExit(main())
