"""Train-step throughput: batch-size sweep and dp-scaling mode.

Default mode times the full train step (forward + AlignmentLoss DP +
LAMB update) at several batch sizes with the Pallas wavefront loss (the
TPU default), transfer-free timing: the step returns only scalars, with
a parameter fingerprint keeping the update live against DCE.

--dp N switches to the pod-scaling mode: a short REAL run_training
(synthetic shards, pjit step, prefetch-overlapped transfers) on a
dp=N mesh at a FIXED global batch, reporting wall time, the prefetch
overlap counters from the metrics sidecar, and a loss-curve digest —
the digest is the cross-dp identity observable (equal global batch =>
equal curve). jax pins the device count at backend init, so a dp sweep
runs this script once per dp in fresh subprocesses (bench.py's
train_dp_scaling stage does exactly that with --force_host_devices 8).

--window_buckets W1,W2,... (dp mode only) makes the run bucketed: the
synthetic stream mixes windows at every bucket width, the model is the
transformer (the fc head is width-locked), and the row additionally
reports n_train_forward_shapes (the compile-once-per-bucket gate:
must equal the bucket count), per-bucket batch counters, the measured
train_padding_fraction, and padding_fraction_padmax — the waste the
same stream would pay under the old single-shape pad-to-max policy.
The padding delta is stream arithmetic (backend-independent); the
windows/s A/B against pad-to-max defers to live chips
(scripts/measure_r4.sh train_bucketed).

Prints one JSON line per run so a tunnel hang keeps completed rows.
"""
import argparse
import hashlib
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
  sys.path.insert(0, _REPO)


def _run_dp_mode(args):
  """One dp point: tiny real training run, counters from the sidecar."""
  import shutil
  import tempfile

  import jax

  from scripts import inject_faults
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import train as train_lib
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  buckets = tuple(args.window_buckets or ())
  work = tempfile.mkdtemp(prefix=f'dc_bench_train_dp{args.dp}_')
  row = {'dp': args.dp, 'global_batch': args.global_batch,
         'steps': args.train_steps,
         'n_devices_visible': jax.device_count()}
  if buckets:
    row['window_buckets'] = list(buckets)
  try:
    train_patterns = []
    if buckets:
      # One shard set per bucket width so the stream genuinely mixes
      # widths; steps split evenly across buckets.
      n_per_width = args.global_batch * max(
          1, args.train_steps // len(buckets))
      for width in buckets:
        shard_dir = os.path.join(work, f'shards_w{width}')
        inject_faults.write_synthetic_tfrecords(
            shard_dir, n_shards=1, n_examples=n_per_width,
            max_passes=5, max_length=width)
        train_patterns.append(shard_dir + '/*')
      n_examples = n_per_width * len(buckets)
      # The fc head is width-locked; bucketed runs need the
      # length-agnostic transformer family.
      params = config_lib.get_config('transformer_learn_values+test')
    else:
      shard_dir = os.path.join(work, 'shards')
      n_examples = args.global_batch * args.train_steps
      inject_faults.write_synthetic_tfrecords(
          shard_dir, n_shards=2, n_examples=n_examples,
          max_passes=5, max_length=20)
      train_patterns.append(shard_dir + '/*')
      params = config_lib.get_config('fc+test')
    with params.unlocked():
      params.max_passes = 5
      params.max_length = buckets[0] if buckets else 20
    config_lib.finalize_params(params)
    with params.unlocked():
      params.dtype = 'float32'
      params.batch_size = args.global_batch
      params.log_every_n_steps = 1
      params.seed = 7
      if buckets:
        params.window_buckets = buckets
        params.num_hidden_layers = 1
        params.filter_size = 32
    out_dir = os.path.join(work, 'out')
    mesh = mesh_lib.make_mesh(
        dp=args.dp, tp=1, devices=jax.devices()[:args.dp])
    t0 = time.perf_counter()
    train_lib.run_training(
        params=params, out_dir=out_dir,
        train_patterns=train_patterns,
        eval_patterns=train_patterns[:1],
        num_epochs=1, mesh=mesh, eval_every=1_000_000)
    row['wall_s'] = round(time.perf_counter() - t0, 2)
    with open(os.path.join(out_dir, 'metrics.jsonl')) as f:
      entries = [json.loads(line) for line in f]
    losses = [e['loss'] for e in entries if e['split'] == 'train']
    faults = [e for e in entries if e['split'] == 'faults'][-1]
    row['examples_per_sec'] = round(n_examples / row['wall_s'], 1)
    row['loss_first'] = round(losses[0], 6) if losses else None
    row['loss_last'] = round(losses[-1], 6) if losses else None
    # The cross-dp identity observable: same global batch + same seed
    # reproduces this digest at every dp. Quantized at 1e-4 because
    # the cross-shard loss all-reduce changes summation order — curves
    # agree to ~1e-6 relative, not bitwise (the exact first/last
    # values above carry the raw comparison).
    row['loss_curve_digest_1e4'] = hashlib.sha256(
        json.dumps([round(l, 4) for l in losses]).encode()
    ).hexdigest()[:16]
    row['n_batches_prefetched'] = faults.get('n_batches_prefetched')
    row['train_transfer_overlap_fraction'] = faults.get(
        'train_transfer_overlap_fraction')
    if buckets:
      # Compile-once gate + the padding-waste A/B: measured fraction
      # under bucketing vs the arithmetic waste of padding the same
      # stream to the widest bucket (the old single-shape policy).
      row['n_train_forward_shapes'] = faults.get('n_train_forward_shapes')
      for width in buckets:
        row[f'n_train_batches_by_bucket_{width}'] = faults.get(
            f'n_train_batches_by_bucket_{width}')
      row['train_padding_fraction'] = faults.get('train_padding_fraction')
      wmax = max(buckets)
      padmax_pos = sum(
          (faults.get(f'n_train_batches_by_bucket_{w}', 0) or 0)
          * args.global_batch * wmax for w in buckets)
      real_pos = faults.get('n_train_window_positions', 0.0)
      padded = faults.get('n_train_padded_positions', 0.0)
      if padmax_pos:
        row['padding_fraction_padmax'] = round(
            1.0 - (real_pos - padded) / padmax_pos, 4)
  except Exception as e:  # keep the row; a failed point is a result
    row['error'] = repr(e)[:200]
  finally:
    shutil.rmtree(work, ignore_errors=True)
  print(json.dumps(row), flush=True)


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--batches', type=int, nargs='+',
                  default=[256, 512, 1024])
  ap.add_argument('--steps', type=int, default=6)
  ap.add_argument('--scan', action='store_true',
                  help='pin the lax.scan DP instead of Pallas')
  ap.add_argument('--cpu', action='store_true')
  ap.add_argument('--dp', type=int, default=None,
                  help='dp-scaling mode: short real training run on a '
                  'dp=N mesh (one dp per process; sweep via fresh '
                  'subprocesses).')
  ap.add_argument('--global_batch', type=int, default=16,
                  help='dp mode: FIXED global batch across the sweep.')
  ap.add_argument('--train_steps', type=int, default=8,
                  help='dp mode: training steps per point.')
  ap.add_argument('--window_buckets', type=lambda s: tuple(
      int(w) for w in s.split(',')), default=None,
                  help='dp mode: comma-separated ascending bucket '
                  'widths (e.g. 100,200). Mixes one synthetic shard '
                  'set per width and reports the per-bucket compile '
                  'and padding counters.')
  ap.add_argument('--force_host_devices', type=int, default=None,
                  help='Fake N CPU devices (sets XLA_FLAGS; must be '
                  'set before jax initializes, i.e. via this flag, '
                  'not after).')
  args = ap.parse_args()

  if args.force_host_devices:
    os.environ['XLA_FLAGS'] = (
        os.environ.get('XLA_FLAGS', '')
        + f' --xla_force_host_platform_device_count='
        f'{args.force_host_devices}')
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')

  import jax

  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')

  if args.dp:
    _run_dp_mode(args)
    return

  import numpy as np

  from scripts import _bench_common

  for batch in args.batches:
    trainer, state, rows_t, label = _bench_common.make_trainer_and_batch(
        batch, use_scan_dp=args.scan,
        out_dir='/tmp/dc_bench_train_scaling',
    )
    step_fn = _bench_common.make_scalar_step(state, trainer.loss_fn)
    row = {'batch': batch,
           'dp': 'scan' if args.scan else 'pallas(auto)'}
    try:
      t0 = time.perf_counter()
      out = step_fn(state, rows_t, label)
      [np.asarray(o) for o in out]
      row['compile_plus_first_step_s'] = round(time.perf_counter() - t0, 1)
      t0 = time.perf_counter()
      for i in range(args.steps):
        out = step_fn(state, rows_t.at[0, 0, 0, 0].set(float(i)), label)
        vals = [np.asarray(o) for o in out]
      dt = time.perf_counter() - t0
      row['examples_per_sec'] = round(batch * args.steps / dt, 1)
      row['loss'] = round(float(vals[0]), 3)
    except Exception as e:
      row['error'] = repr(e)[:200]
    print(json.dumps(row), flush=True)


if __name__ == '__main__':
  main()
