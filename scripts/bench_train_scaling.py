"""Train-step throughput vs batch size on the available chip.

Times the full train step (forward + AlignmentLoss DP + LAMB update)
at several batch sizes with the Pallas wavefront loss (the TPU
default), transfer-free timing: the step returns only scalars, with a
parameter fingerprint keeping the update live against DCE. Prints one
JSON line per batch so a tunnel hang keeps completed rows.
"""
import argparse
import json
import time


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--batches', type=int, nargs='+',
                  default=[256, 512, 1024])
  ap.add_argument('--steps', type=int, default=6)
  ap.add_argument('--scan', action='store_true',
                  help='pin the lax.scan DP instead of Pallas')
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  import jax

  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import numpy as np
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import train as train_lib

  for batch in args.batches:
    tp = config_lib.get_config('transformer_learn_values+test')
    config_lib.finalize_params(tp)
    with tp.unlocked():
      tp.batch_size = batch
      tp.use_pallas_wavefront = False if args.scan else None
    trainer = train_lib.Trainer(
        params=tp, out_dir='/tmp/dc_bench_train_scaling', mesh=None
    )
    state = trainer.init_state(steps_total=100)
    loss_obj = trainer.loss_fn
    rng = np.random.default_rng(2)
    rows = np.zeros((batch, tp.total_rows, tp.max_length, 1), np.float32)
    mp = tp.max_passes
    rows[:, :mp] = rng.integers(0, 5, size=rows[:, :mp].shape)
    rows[:, mp:3 * mp] = rng.integers(0, 256, size=rows[:, mp:3 * mp].shape)
    rows[:, 3 * mp:4 * mp] = rng.integers(0, 3, size=rows[:, :mp].shape)
    rows[:, 4 * mp] = rng.integers(0, 5, size=rows[:, 4 * mp].shape)
    rows[:, 4 * mp + 1:] = rng.integers(0, 501,
                                        size=rows[:, 4 * mp + 1:].shape)
    rows_t = jnp.asarray(rows)
    label = jnp.asarray(
        rng.integers(0, 5, size=(batch, tp.max_length)), jnp.int32)

    def step_scalar(state, rows, label):
      rng_step = jax.random.fold_in(state.dropout_rng, state.step)

      def loss_of(p):
        preds = state.apply_fn(
            {'params': p}, rows, train=True, rngs={'dropout': rng_step}
        )
        return loss_obj(label, preds)

      loss, grads = jax.value_and_grad(loss_of)(state.params)
      new_state = state.apply_gradients(grads=grads)
      fp = sum(jnp.sum(x) for x in jax.tree.leaves(new_state.params))
      return loss, fp

    step_fn = jax.jit(step_scalar)
    row = {'batch': batch,
           'dp': 'scan' if args.scan else 'pallas(auto)'}
    try:
      t0 = time.perf_counter()
      out = step_fn(state, rows_t, label)
      [np.asarray(o) for o in out]
      row['compile_plus_first_step_s'] = round(time.perf_counter() - t0, 1)
      t0 = time.perf_counter()
      for i in range(args.steps):
        out = step_fn(state, rows_t.at[0, 0, 0, 0].set(float(i)), label)
        vals = [np.asarray(o) for o in out]
      dt = time.perf_counter() - t0
      row['examples_per_sec'] = round(batch * args.steps / dt, 1)
      row['loss'] = round(float(vals[0]), 3)
    except Exception as e:
      row['error'] = repr(e)[:200]
    print(json.dumps(row), flush=True)


if __name__ == '__main__':
  main()
