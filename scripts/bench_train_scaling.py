"""Train-step throughput vs batch size on the available chip.

Times the full train step (forward + AlignmentLoss DP + LAMB update)
at several batch sizes with the Pallas wavefront loss (the TPU
default), transfer-free timing: the step returns only scalars, with a
parameter fingerprint keeping the update live against DCE. Prints one
JSON line per batch so a tunnel hang keeps completed rows.
"""
import argparse
import json
import time


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--batches', type=int, nargs='+',
                  default=[256, 512, 1024])
  ap.add_argument('--steps', type=int, default=6)
  ap.add_argument('--scan', action='store_true',
                  help='pin the lax.scan DP instead of Pallas')
  ap.add_argument('--cpu', action='store_true')
  args = ap.parse_args()

  import jax

  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  import numpy as np

  from scripts import _bench_common

  for batch in args.batches:
    trainer, state, rows_t, label = _bench_common.make_trainer_and_batch(
        batch, use_scan_dp=args.scan,
        out_dir='/tmp/dc_bench_train_scaling',
    )
    step_fn = _bench_common.make_scalar_step(state, trainer.loss_fn)
    row = {'batch': batch,
           'dp': 'scan' if args.scan else 'pallas(auto)'}
    try:
      t0 = time.perf_counter()
      out = step_fn(state, rows_t, label)
      [np.asarray(o) for o in out]
      row['compile_plus_first_step_s'] = round(time.perf_counter() - t0, 1)
      t0 = time.perf_counter()
      for i in range(args.steps):
        out = step_fn(state, rows_t.at[0, 0, 0, 0].set(float(i)), label)
        vals = [np.asarray(o) for o in out]
      dt = time.perf_counter() - t0
      row['examples_per_sec'] = round(batch * args.steps / dt, 1)
      row['loss'] = round(float(vals[0]), 3)
    except Exception as e:
      row['error'] = repr(e)[:200]
    print(json.dumps(row), flush=True)


if __name__ == '__main__':
  main()
