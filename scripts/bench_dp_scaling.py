"""dp-scaling bench: windows/s + transfer-overlap fraction per dp.

Drives the runner's double-buffered dispatch path (dp-sharded
`jax.device_put` of the compact uint8 pack, forward launched by the
NEXT pack's dispatch) through a depth-2 pipeline — the same pattern
the ConsensusEngine uses — and prints one JSON line.

Run ONE dp per process: jax pins the device count at backend init, so
bench.py fans this script out as fresh subprocesses rather than
looping in-process. With --force_host_devices the dp axis spans
virtual CPU devices sharing one host core — windows/s is then an
overhead/parity number, NOT a speedup claim. The real sweep is the
measure_r4.sh forward_dp2/forward_dp4 stages on live chips, where the
overlap fraction measures genuine host->device transfer hiding.
"""
import argparse
import json
import time
from collections import deque


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--dp', type=int, default=1)
  ap.add_argument('--batch', type=int, default=256)
  ap.add_argument('--packs', type=int, default=12)
  ap.add_argument('--warmup', type=int, default=2)
  ap.add_argument('--force_host_devices', type=int, default=0,
                  help='force N virtual CPU devices before backend '
                       'init (the axon TPU plugin ignores '
                       'JAX_PLATFORMS=cpu; the config knob is the '
                       'reliable switch)')
  args = ap.parse_args()

  if args.force_host_devices:
    # XLA reads this at backend init — set it before jax imports.
    import os

    flag = ('--xla_force_host_platform_device_count='
            f'{args.force_host_devices}')
    os.environ['XLA_FLAGS'] = (
        f"{os.environ.get('XLA_FLAGS', '')} {flag}".strip())
  import jax

  if args.force_host_devices:
    try:
      jax.config.update('jax_platforms', 'cpu')
    except RuntimeError:
      pass  # backend already initialized; device check below decides
  import jax.numpy as jnp
  import numpy as np

  from deepconsensus_tpu.inference import runner as runner_lib
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib
  from deepconsensus_tpu.parallel import mesh as mesh_lib
  from scripts._bench_common import make_rows

  devices = jax.devices()
  if len(devices) < args.dp:
    print(json.dumps({
        'dp': args.dp, 'error': f'only {len(devices)} devices; need '
        f'{args.dp} (fresh process or --force_host_devices)'}))
    return 1
  if args.batch % args.dp:
    print(json.dumps({
        'dp': args.dp,
        'error': f'batch {args.batch} not divisible by dp={args.dp}'}))
    return 1
  mesh = None
  if args.dp > 1:
    mesh = mesh_lib.make_mesh(dp=args.dp, tp=1,
                              devices=devices[:args.dp])

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params, is_training=False)
  model = model_lib.get_model(params)
  variables = model.init(
      jax.random.PRNGKey(0),
      jnp.zeros((1, params.total_rows, params.max_length, 1)))
  options = runner_lib.InferenceOptions(batch_size=args.batch)
  runner = runner_lib.ModelRunner(params, variables, options, mesh=mesh)

  # A small rotating pool of distinct packs: varying inputs defeat any
  # result caching in tunneled-device backends without holding
  # args.packs full batches on the host.
  rng = np.random.default_rng(0)
  pool = [make_rows(params, args.batch, rng=rng)
          for _ in range(min(4, args.packs))]

  for i in range(args.warmup):  # compile + steady-state transfers
    runner.finalize(runner.dispatch(pool[i % len(pool)]))

  before = runner.dispatch_stats()
  pending = deque()
  t0 = time.perf_counter()
  for i in range(args.packs):
    pending.append(runner.dispatch(pool[i % len(pool)]))
    if len(pending) >= 2:  # engine dispatch_depth pattern
      runner.finalize(pending.popleft())
  while pending:
    runner.finalize(pending.popleft())
  dt = time.perf_counter() - t0

  after = runner.dispatch_stats()
  overlapped = (after['n_transfer_overlapped']
                - before['n_transfer_overlapped'])
  direct = after['n_transfer_direct'] - before['n_transfer_direct']
  launches = overlapped + direct
  print(json.dumps({
      'dp': args.dp,
      'n_devices': len(devices),
      'backend': devices[0].platform,
      'batch': args.batch,
      'packs': args.packs,
      'sharded': mesh is not None,
      'windows_per_sec': round(args.batch * args.packs / dt, 1),
      'transfer_overlap_fraction': (
          round(overlapped / launches, 4) if launches else 0.0),
      'n_transfer_overlapped': overlapped,
      'n_transfer_direct': direct,
  }), flush=True)
  return 0


if __name__ == '__main__':
  raise SystemExit(main())
