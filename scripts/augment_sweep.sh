#!/bin/bash
# Round-5 data-augmentation sweep (VERDICT r4 #3): can augmentation
# push held-out eval/identity_pred past the 0.828 distillation ceiling
# (teacher peak 0.808 @ step 666; CCS baseline 0.922)?
#
# Protocol matches artifacts/heldout_r4 exactly (same data, seed,
# schedule: transformer_learn_values+test, b32, warmup 100) except for
# the augmentation flags; best checkpoint tracked by held-out
# eval/identity_pred at a finer eval cadence (114 = 3 evals/epoch-ish).
#
#   bash scripts/augment_sweep.sh [sweep_names...]   (default: a b c)
set -u
REPO=/root/repo
DATA=${DC_AUG_DATA:-/root/data_r4/examples}
EPOCHS=${DC_AUG_EPOCHS:-60}
OUTROOT=${DC_AUG_OUT:-/root}
export PYTHONPATH=$REPO:/root/.axon_site

train_one() {  # name extra --set flags...
  local name=$1; shift
  local out="$OUTROOT/aug_r5_$name"
  echo "=== sweep $name -> $out ==="
  python - train --config transformer_learn_values+test \
    --out_dir "$out" \
    --train_path "$DATA/train/*" --eval_path "$DATA/eval/*" \
    --batch_size 32 --num_epochs "$EPOCHS" \
    --set eval_every_n_steps=114 --set warmup_steps=100 \
    --set num_epochs_for_decay="$EPOCHS" \
    --set best_checkpoint_metric=eval/identity_pred \
    --set augment=true "$@" <<'EOF'
import jax, sys
jax.config.update('jax_platforms', 'cpu')
from deepconsensus_tpu.cli import main
sys.exit(main(sys.argv[1:]))
EOF
  rc=$?
  if [ $rc -ne 0 ]; then
    echo "sweep $name FAILED rc=$rc"
    return $rc
  fi
  echo "--- $name trajectory (eval/identity_pred) ---"
  cut -f1,8 "$out/checkpoint_metrics.tsv" 2>/dev/null | tail -25
  cat "$out/best_checkpoint.txt" 2>/dev/null
}

[ $# -eq 0 ] && set -- a b c
for sweep in "$@"; do
  case $sweep in
    a)  # orientation + order only: the two exactly-label-preserving
        # transforms at default strength.
      train_one a --set augment_drop_prob=0.0 --set augment_jitter_prob=0.0
      ;;
    b)  # all four transforms at default strength.
      train_one b
      ;;
    c)  # aggressive: always reorder, heavier downsample/jitter.
      train_one c --set augment_perm_prob=1.0 --set augment_drop_prob=0.5 \
        --set augment_jitter_prob=0.5
      ;;
    *) echo "unknown sweep $sweep"; exit 2;;
  esac
done
