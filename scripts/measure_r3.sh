#!/bin/bash
# One-shot round-3 TPU measurement sweep. Run when the tunnel is alive:
#   bash scripts/measure_r3.sh
# Each stage has its own timeout so a tunnel hang mid-sweep keeps the
# completed stages; results accumulate in /root/repo/MEASURED_TPU_r3.d/
# and are merged into MEASURED_TPU_r3.json at the end (also safe to
# re-run: stages overwrite their own output files only on success).
#
# IMPORTANT (1-core host): stop background CPU jobs (the overfit
# trainer, pytest) before running, or host-side stages are poisoned.
set -u
REPO=/root/repo
OUT=$REPO/MEASURED_TPU_r3.d
mkdir -p "$OUT"
export PYTHONPATH=$REPO:/root/.axon_site
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/root/.dc_jax_cache}

run_stage() {  # name timeout_s cmd...
  local name=$1 t=$2; shift 2
  echo "=== stage $name (timeout ${t}s) ==="
  if timeout "$t" "$@" > "$OUT/$name.tmp" 2> "$OUT/$name.err"; then
    grep -E '^\{' "$OUT/$name.tmp" > "$OUT/$name.jsonl" || true
    tail -3 "$OUT/$name.jsonl"
  else
    echo "stage $name FAILED rc=$? (see $OUT/$name.err)"
  fi
}

# Cheapest first so a fragile tunnel still yields the headline numbers.
run_stage train_stages_b256 900 \
  python "$REPO/scripts/bench_train_stages.py" --batches 256 --steps 6 --scan-too
run_stage e2e 1200 \
  python "$REPO/scripts/bench_e2e.py" --repeats 6
run_stage train_scaling 1200 \
  python "$REPO/scripts/bench_train_scaling.py" --batches 256 1024 --steps 6
run_stage train_stages_b1024 900 \
  python "$REPO/scripts/bench_train_stages.py" --batches 1024 --steps 6
run_stage flash_band 900 \
  python "$REPO/scripts/bench_flash_band.py"

python - <<'EOF'
import json, os, glob
out = {}
d = '/root/repo/MEASURED_TPU_r3.d'
for f in sorted(glob.glob(os.path.join(d, '*.jsonl'))):
    rows = [json.loads(l) for l in open(f) if l.strip()]
    out[os.path.basename(f)[:-6]] = rows
with open('/root/repo/MEASURED_TPU_r3.json', 'w') as fh:
    json.dump(out, fh, indent=1)
print('merged ->', '/root/repo/MEASURED_TPU_r3.json')
EOF
