"""Sustained-scale end-to-end soak (VERDICT r4 #7).

Replicates the bundled 10-ZMW human_1m BAMs to thousands of distinct
ZMWs (byte-level record patching: qname + zm tag get a per-copy offset,
cigars/quals/kinetics preserved exactly — mirrors the reference's
full-SMRT-cell production pattern, quick_start.md:82-99), then runs
`dctpu run` over them as a subprocess while sampling throughput (FASTQ
growth), RSS, and /dev/shm segment count. Emits one JSON line with the
soak verdict: sustained ZMW/s, first-vs-last-quartile throughput ratio
(flatness), peak RSS, peak shm segments.

  python scripts/soak_e2e.py --copies 500 --out_dir /root/soak_r5

Serve mode (--serve N): one `dctpu serve` daemon, N concurrent clients
hammering /v1/polish with featurized synthetic molecules. Verifies
every concurrent result byte-identical to a solo (single-client)
baseline — zero cross-request leaks under continuous batching — then
SIGTERMs the daemon under residual load and checks the graceful drain.
Verdict line reports client-observed p50/p99 latency and the daemon's
own /metricz counters.

  python scripts/soak_e2e.py --serve 8 --serve_rounds 20

Fleet mode (--fleet N): N `dctpu serve` replicas behind one `dctpu
route` front tier, all real subprocesses sharing one persistent
compilation cache dir. Concurrent clients hammer the router; halfway
through, one replica is rolling-restarted (SIGTERM -> drain -> respawn
-> POST /v1/register) while traffic continues. A disaggregated leg
ships per-molecule raw mini BAMs (bam/1) through a featurize worker.
Gates: zero accepted-then-lost requests, every routed result
byte-identical to a solo single-replica baseline, clean drains
everywhere.

  python scripts/soak_e2e.py --fleet 3 --serve_rounds 6

Chaos mode (--chaos): same batch soak, but one device OOM and one
device hang are injected mid-stream via the DCTPU_FAULT_DEVICE_* env
hooks. The child runs with --on_device_error=degrade and a dispatch
watchdog, so the OOM pack must recover through batch bisection and the
hung pack must be cut off by the watchdog (its ZMWs fall back to CCS).
The verdict gains a 'chaos' block read from the run's .inference.json
sidecar; exit is nonzero unless both recovery counters fired and
throughput stayed flat.

  python scripts/soak_e2e.py --chaos --min_minutes 2
"""
import argparse
import gzip
import json
import os
import signal
import struct
import subprocess
import sys
import threading
import time

TESTDATA = '/root/reference/deepconsensus/testdata/human_1m'
ZMW_STRIDE = 1_000_000  # copy c adds c * stride to every ZMW id


def _patch_record(block: bytes, zmw_offset: int) -> bytes:
  """Returns the record with qname's ZMW and the zm:i tag offset."""
  (ref_id, pos, l_read_name, mapq, bin_, n_cigar, flag, l_seq, next_ref,
   next_pos, tlen) = struct.unpack('<iiBBHHHiiii', block[:32])
  name = block[32 : 32 + l_read_name - 1].decode('ascii')
  rest = block[32 + l_read_name :]
  movie, zmw, tail = name.split('/', 2)
  new_name = f'{movie}/{int(zmw) + zmw_offset}/{tail}'.encode('ascii')
  new_lrn = len(new_name) + 1

  # Walk the tag region (after cigar+seq+qual) to rewrite zm:i.
  cigar_seq_qual = n_cigar * 4 + (l_seq + 1) // 2 + l_seq
  tags = bytearray(rest[cigar_seq_qual:])
  p = 0
  sizes = {ord('A'): 1, ord('c'): 1, ord('C'): 1, ord('s'): 2,
           ord('S'): 2, ord('i'): 4, ord('I'): 4, ord('f'): 4}
  while p + 3 <= len(tags):
    tag = bytes(tags[p : p + 2])
    vt = tags[p + 2]
    q = p + 3
    if vt in sizes:
      if tag == b'zm' and vt in (ord('i'), ord('I')):
        (zm_val,) = struct.unpack_from('<i', tags, q)
        struct.pack_into('<i', tags, q, zm_val + zmw_offset)
      q += sizes[vt]
    elif vt in (ord('Z'), ord('H')):
      while tags[q] != 0:
        q += 1
      q += 1
    elif vt == ord('B'):
      sub = tags[q]
      (n,) = struct.unpack_from('<I', tags, q + 1)
      q += 5 + n * sizes[sub]
    else:
      raise ValueError(f'unknown tag type {chr(vt)}')
    p = q

  head = struct.pack('<iiBBHHHiiii', ref_id, pos, new_lrn, mapq, bin_,
                     n_cigar, flag, l_seq, next_ref, next_pos, tlen)
  body = head + new_name + b'\x00' + rest[: cigar_seq_qual] + bytes(tags)
  return struct.pack('<i', len(body)) + body


def replicate_bam(src: str, dst: str, copies: int) -> int:
  """Writes `copies` ZMW-offset replicas of src's records; returns the
  record count written."""
  from deepconsensus_tpu.io.bam_writer import BgzfWriter

  raw = gzip.open(src, 'rb').read()
  assert raw[:4] == b'BAM\x01', src
  (l_text,) = struct.unpack_from('<i', raw, 4)
  p = 8 + l_text
  (n_ref,) = struct.unpack_from('<i', raw, p)
  p += 4
  for _ in range(n_ref):
    (l_name,) = struct.unpack_from('<i', raw, p)
    p += 4 + l_name + 4
  header_end = p

  records = []
  while p < len(raw):
    (size,) = struct.unpack_from('<i', raw, p)
    records.append(raw[p + 4 : p + 4 + size])
    p += 4 + size

  n = 0
  with BgzfWriter(dst) as out:
    out.write(raw[:header_end])
    for c in range(copies):
      off = c * ZMW_STRIDE
      for block in records:
        out.write(_patch_record(block, off) if off else
                  struct.pack('<i', len(block)) + block)
        n += 1
  return n


def count_fastq_records(path: str) -> int:
  # The runner streams into <output>.tmp and renames into place only on
  # success (atomic, resumable output) — mid-run progress lives in the
  # tmp file, the final path only exists after completion.
  if not os.path.exists(path):
    path += '.tmp'
    if not os.path.exists(path):
      return 0
  n = 0
  with open(path, 'rb') as f:
    for _ in f:
      n += 1
  return n // 4


def _featurize_synth(args, n_zmws):
  """Synthesizes molecules and featurizes them once in the parent.
  Returns (molecules, synth_dir)."""
  from deepconsensus_tpu.inference import runner as runner_lib
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.preprocess import (FeatureLayout,
                                            create_proc_feeder)
  from scripts.inject_faults import write_synthetic_zmw_bams

  os.makedirs(args.out_dir, exist_ok=True)
  synth_dir = os.path.join(args.out_dir, f'serve_synth_{n_zmws}')
  if not os.path.isdir(synth_dir):
    write_synthetic_zmw_bams(synth_dir, n_zmws=n_zmws,
                             n_subreads=5, seq_len=600)
  sub_bam = os.path.join(synth_dir, 'subreads_to_ccs.bam')
  ccs_bam = os.path.join(synth_dir, 'ccs.bam')
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params, is_training=False)
  options = runner_lib.InferenceOptions(min_quality=0)
  options.max_passes = params.max_passes
  options.max_length = params.max_length
  options.use_ccs_bq = params.use_ccs_bq
  layout = FeatureLayout(
      max_passes=options.max_passes, max_length=options.max_length,
      use_ccs_bq=options.use_ccs_bq)
  feeder, _ = create_proc_feeder(
      subreads_to_ccs=sub_bam, ccs_bam=ccs_bam, layout=layout,
      ins_trim=options.ins_trim)
  molecules = []
  for zmw_input in feeder():
    features, _ = runner_lib.preprocess_zmw(zmw_input, options)
    if features:
      molecules.append(features)
  return molecules, synth_dir


def _spawn(cmd_tail, env):
  """Starts a dctpu subcommand subprocess and returns (proc, ready)
  once its ready JSON line arrives."""
  proc = subprocess.Popen(
      [sys.executable, '-m', 'deepconsensus_tpu.cli'] + cmd_tail,
      env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
      text=True)
  for line in proc.stdout:
    if line.startswith('{'):
      info = json.loads(line)
      if info.get('event') == 'ready':
        return proc, info
  raise RuntimeError(f'subprocess exited before ready: {cmd_tail}')


def _drained_line(proc):
  out = {}
  for line in proc.stdout.read().splitlines():
    if line.startswith('{'):
      d = json.loads(line)
      if d.get('event') == 'drained':
        out = d
  return out


def fleet_soak(args) -> int:
  """N serve replicas behind `dctpu route` with a `dctpu autoscale`
  controller holding the interactive-class SLO: the load ramp forces a
  scale-out, a forced preemption (SIGUSR1 notice + kill deadline) of
  an operator replica is absorbed by a drain + autoscaler replacement,
  and a disaggregated bam/1 leg rides the featurize tier. Workers are
  class-labeled (one interactive, the rest bulk) so the router's
  per-class latency histograms carry the SLO evidence."""
  sys.path.insert(0, os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))))
  from deepconsensus_tpu.serve.client import ServeClient, ServeClientError
  from scripts.inject_faults import preempt_replica
  from scripts.inject_faults import write_synthetic_zmw_bams

  if args.fleet < 2:
    print('fleet soak needs --fleet >= 2 (one replica is preempted '
          'mid-run)', flush=True)
    return 1
  t0 = time.time()
  molecules, _synth_dir = _featurize_synth(args, args.serve_zmws)
  print(f'featurized {len(molecules)} molecules', flush=True)

  env = dict(os.environ)
  env['PYTHONPATH'] = '/root/repo:' + env.get('PYTHONPATH', '')
  env['JAX_PLATFORMS'] = env.get('JAX_PLATFORMS', 'cpu')
  cache_dir = os.path.join(args.out_dir, 'jit_cache')
  os.makedirs(cache_dir, exist_ok=True)
  # One shared Chrome-trace file for the whole fleet: every tier
  # (replicas, featurize worker, router) appends spans to it, and the
  # post-soak connectivity check joins them by trace id.
  trace_path = os.path.join(args.out_dir, 'fleet_trace.jsonl')
  if os.path.exists(trace_path):
    os.unlink(trace_path)
  env['DCTPU_TRACE'] = trace_path

  def spawn_replica():
    return _spawn(
        ['serve', '--random_init',
         '--config', 'transformer_learn_values+test',
         '--port', '0', '--min_quality', '0',
         '--batch_size', str(args.serve_batch_size),
         '--compilation_cache_dir', cache_dir], env)

  replicas = []  # [proc, port] — mutated by the rolling restart
  t_first = time.time()
  for i in range(args.fleet):
    proc, ready = spawn_replica()
    replicas.append([proc, ready['port']])
    print(json.dumps({'replica': i, **ready,
                      'spawn_s': round(time.time() - t_first, 1)}),
          flush=True)
    t_first = time.time()

  worker_proc, worker_ready = _spawn(
      ['featurize-worker', '--config', 'transformer_learn_values+test',
       '--port', '0'], env)
  print(json.dumps(worker_ready), flush=True)

  router_cmd = ['route', '--port', '0', '--probe_interval_s', '0.2',
                '--queue_wait_s', '0.3',
                '--featurize_worker',
                f'127.0.0.1:{worker_ready["port"]}']
  for _, port in replicas:
    router_cmd += ['--replica', f'127.0.0.1:{port}']
  router_proc, router_ready = _spawn(router_cmd, env)
  print(json.dumps(router_ready), flush=True)
  router_port = router_ready['port']
  router_client = ServeClient(port=router_port, timeout=300)
  if not router_client.wait_ready(120):
    print('router never became ready', flush=True)
    return 1

  # The SLO autoscaler: min = the operator fleet, max allows exactly
  # one scale-out. The p99 target is deliberately tight so the load
  # ramp provably crosses it; the scale-in cooldown is effectively
  # infinite so the replica count only moves for reasons this soak
  # asserts on (scale-out, preemption replacement). Spawned replicas
  # carry the same flags as the operator ones (deterministic
  # random-init weights + the shared compile cache), so byte identity
  # holds no matter who serves a request.
  scaler_cmd = ['autoscale', '--router', f'127.0.0.1:{router_port}',
                '--tier', 'model',
                '--min_replicas', str(args.fleet),
                '--max_replicas', str(args.fleet + 1),
                '--target_p99_s', str(args.autoscale_p99_s),
                '--target_queue_depth', '1e9',
                '--slo_class', 'interactive',
                '--poll_interval_s', '0.5',
                '--scale_out_cooldown_s', '2',
                '--scale_in_cooldown_s', '100000',
                '--serve_arg=--random_init',
                '--serve_arg=--config',
                '--serve_arg=transformer_learn_values+test',
                '--serve_arg=--min_quality',
                '--serve_arg=0',
                '--serve_arg=--batch_size',
                f'--serve_arg={args.serve_batch_size}',
                '--serve_arg=--compilation_cache_dir',
                f'--serve_arg={cache_dir}']
  scaler_proc, scaler_ready = _spawn(scaler_cmd, env)
  print(json.dumps(scaler_ready), flush=True)

  # Solo baseline: one pass straight at replica 0 — the bytes every
  # routed result must reproduce exactly.
  solo_client = ServeClient(port=replicas[0][1], timeout=300)
  solo = {}
  for features in molecules:
    resp = solo_client.polish_features(features)
    name = features[0]['name']
    name = name if isinstance(name, str) else name.decode()
    solo[name] = (resp['status'], resp['seq'],
                  None if resp['quals'] is None
                  else resp['quals'].tobytes())

  lock = threading.Lock()
  latencies = []
  mismatches = []
  accepted_then_lost = []
  errors = []
  n_ok = [0]
  n_shed_retries = [0]
  stop_workers = threading.Event()

  def worker(wid):
    # Multi-tenant attribution: worker 0 is the interactive tenant the
    # SLO is asserted for; the rest are bulk backfill.
    client = ServeClient(
        port=router_port, timeout=300,
        klass='interactive' if wid == 0 else 'bulk',
        client=f'worker-{wid}')
    start = wid % max(1, len(molecules))
    rotated = molecules[start:] + molecules[:start]
    for _ in range(args.serve_rounds):
      for features in rotated:
        if stop_workers.is_set():
          return
        name = features[0]['name']
        name = name if isinstance(name, str) else name.decode()
        t_req = time.monotonic()
        resp = None
        for _attempt in range(40):
          try:
            resp = client.polish_features(
                features, compact=wid % 2 == 0)
            break
          except ServeClientError as e:
            msg = str(e.payload.get('error', ''))
            if 'accepting' in msg:
              # The one error a correct client must NOT retry.
              with lock:
                accepted_then_lost.append(f'{name}: {msg}')
              break
            if e.status in (429, 503):
              with lock:
                n_shed_retries[0] += 1
              time.sleep(0.25)  # fleet busy/rolling; try again
              continue
            with lock:
              errors.append(f'{name}: HTTP {e.status} {msg}')
            break
          except OSError as e:
            with lock:
              errors.append(f'{name}: {type(e).__name__}')
            break
        if resp is None:
          continue
        dt = time.monotonic() - t_req
        got = (resp['status'], resp['seq'],
               None if resp['quals'] is None
               else resp['quals'].tobytes())
        with lock:
          latencies.append(dt)
          if got != solo[name]:
            mismatches.append(name)
          else:
            n_ok[0] += 1

  threads = [threading.Thread(target=worker, args=(w,))
             for w in range(args.fleet_clients)]
  for t in threads:
    t.start()

  def model_tier_counts():
    try:
      m = router_client.metricz()
    except (OSError, ValueError):
      return 0, 0
    reps = [r for r in m.get('replicas', []) if r.get('tier') == 'model']
    ready = sum(1 for r in reps if r.get('state') == 'ready')
    live = sum(1 for r in reps
               if r.get('state') in ('ready', 'joining'))
    return ready, live

  # Phase 1 — SLO scale-out: under the client ramp the cumulative
  # interactive p99 crosses the (deliberately tight) autoscale target
  # and the controller grows the model tier by one replica.
  time.sleep(2.0)
  max_ready = args.fleet
  scaled_out = False
  scale_deadline = time.monotonic() + 300
  while time.monotonic() < scale_deadline:
    ready_n, _live_n = model_tier_counts()
    max_ready = max(max_ready, ready_n)
    if ready_n >= args.fleet + 1:
      scaled_out = True
      break
    time.sleep(0.5)

  # Phase 2 — forced preemption of an operator replica: the SIGUSR1
  # notice flips it to draining (the router routes nothing new to it),
  # it finishes admitted work and exits 0 with preempted=true well
  # inside the grace window (the hard kill never fires), and the
  # autoscaler restores the lost capacity without any manual respawn
  # or re-register.
  old_proc, old_port = replicas.pop(0)
  drill = preempt_replica(
      old_proc.pid, grace_s=300,
      is_alive=lambda: old_proc.poll() is None)
  old_rc = old_proc.wait(timeout=300)
  old_info = _drained_line(old_proc)
  want_live = args.fleet + 1 if scaled_out else args.fleet
  replaced = False
  replace_deadline = time.monotonic() + 300
  while time.monotonic() < replace_deadline:
    _ready_n, live_n = model_tier_counts()
    if live_n >= want_live:
      replaced = True
      break
    time.sleep(0.5)
  preempted = {
      'old_port': old_port, 'old_rc': old_rc,
      'old_drained': bool(old_info.get('drained')),
      'old_preempted': bool(old_info.get('preempted')),
      'kill_fired': bool(drill['killed']),
      'notice_to_exit_s': drill['waited_s'],
      'scaled_out': scaled_out,
      'max_ready_observed': max_ready,
      'replaced': replaced,
  }
  print(json.dumps({'event': 'preempted', **preempted}), flush=True)

  for t in threads:
    t.join()

  # Disaggregated leg: per-molecule raw mini BAMs through the router's
  # featurize tier; solo-replica polish of the monolithic featurize of
  # the same BAMs is the identity reference.
  bam_ok, bam_mismatch = 0, 0
  bam_trace_ids = []
  for i in range(3):
    d = os.path.join(args.out_dir, f'fleet_bam_{i}')
    sub_path, ccs_path = write_synthetic_zmw_bams(
        d, n_zmws=1, n_subreads=5, seq_len=600, seed=100 + i)
    with open(sub_path, 'rb') as f:
      sub_bytes = f.read()
    with open(ccs_path, 'rb') as f:
      ccs_bytes = f.read()
    bam_trace_ids.append(f'bamleg{i:010d}')
    got = router_client.polish_bam(sub_bytes, ccs_bytes, name=f'bam/{i}',
                                   trace_id=bam_trace_ids[-1])
    # Monolithic reference: featurize the exact BAM pair we shipped,
    # polish on a replica directly.
    from deepconsensus_tpu.inference import runner as runner_lib
    from deepconsensus_tpu.models import config as config_lib
    from deepconsensus_tpu.preprocess import (FeatureLayout,
                                              create_proc_feeder)
    params = config_lib.get_config('transformer_learn_values+test')
    config_lib.finalize_params(params, is_training=False)
    layout = FeatureLayout(params.max_passes, params.max_length,
                           params.use_ccs_bq)
    feeder, _ = create_proc_feeder(
        subreads_to_ccs=sub_path, ccs_bam=ccs_path, layout=layout)
    options = runner_lib.InferenceOptions(min_quality=0)
    options.max_passes = params.max_passes
    options.max_length = params.max_length
    options.use_ccs_bq = params.use_ccs_bq
    want = None
    for zmw_input in feeder():
      features, _ = runner_lib.preprocess_zmw(zmw_input, options)
      if features:
        want = ServeClient(
            port=replicas[1][1] if len(replicas) > 1
            else replicas[0][1],
            timeout=300).polish_features(features)
    same = (want is not None and got['status'] == want['status']
            and got['seq'] == want['seq'])
    bam_ok += bool(same)
    bam_mismatch += not same

  metricz = router_client.metricz()

  # Drain the fleet: the autoscaler first (it SIGTERM-drains every
  # replica it spawned), then the router (stops admissions), then the
  # remaining operator tiers.
  scaler_proc.send_signal(signal.SIGTERM)
  scaler_rc = scaler_proc.wait(timeout=600)
  scaler_info = _drained_line(scaler_proc)
  router_proc.send_signal(signal.SIGTERM)
  router_rc = router_proc.wait(timeout=300)
  router_drained = bool(_drained_line(router_proc).get('drained'))
  tier_rcs = []
  for proc, _port in replicas + [[worker_proc, None]]:
    proc.send_signal(signal.SIGTERM)
    tier_rcs.append(proc.wait(timeout=300))

  # Trace connectivity (all tiers have exited, the shared file is
  # complete): every bam-leg request must form ONE connected trace
  # whose spans came from at least three distinct processes (router,
  # featurize worker, model replica), and every verified features-leg
  # delivery must join its router-minted id across router + replica.
  from deepconsensus_tpu.obs import summarize as summarize_lib
  trace_events = summarize_lib.load_trace(trace_path)
  groups = summarize_lib.trace_groups(trace_events)
  bam_connected = [len(groups.get(tid, {}).get('pids', ())) >= 3
                   for tid in bam_trace_ids]
  n_routed_traces = sum(
      1 for g in groups.values() if len(g.get('pids', ())) >= 2)
  # Any dead letter written during the soak must be joinable to its
  # request's trace.
  dead_letters_missing_trace = 0
  for root, _dirs, files in os.walk(args.out_dir):
    for fn in files:
      if fn.endswith('.failed.jsonl'):
        with open(os.path.join(root, fn)) as fh:
          for line in fh:
            if line.strip() and 'trace_id' not in json.loads(line):
              dead_letters_missing_trace += 1
  trace_connected = (all(bam_connected)
                     and len(bam_connected) == len(bam_trace_ids)
                     and n_routed_traces >= n_ok[0]
                     and dead_letters_missing_trace == 0)

  lat = sorted(latencies)
  verdict = {
      'soak': 'fleet',
      'n_replicas': args.fleet,
      'n_clients': args.fleet_clients,
      'n_molecules': len(molecules),
      'n_requests_verified': n_ok[0],
      'n_mismatches': len(mismatches),
      'n_accepted_then_lost': len(accepted_then_lost),
      'n_shed_retries': n_shed_retries[0],
      'n_client_errors': len(errors),
      'bam_leg': {'ok': bam_ok, 'mismatched': bam_mismatch},
      'preempted': preempted,
      'autoscale': {
          'rc': scaler_rc,
          'counters': scaler_info.get('counters', {}),
          'managed': scaler_info.get('managed', []),
      },
      'p50_s': round(lat[len(lat) // 2], 4) if lat else None,
      'p99_s': round(lat[min(len(lat) - 1, int(len(lat) * 0.99))], 4)
               if lat else None,
      'router_counters': metricz.get('counters', {}),
      'router_latency': metricz.get('latency', {}),
      'class_latency': metricz.get('class_latency', {}),
      'qos': metricz.get('qos', {}),
      'router_rc': router_rc,
      'router_drained': router_drained,
      'tier_rcs': tier_rcs,
      'trace': {
          'path': trace_path,
          'n_events': len(trace_events),
          'n_traces': len(groups),
          'n_routed_traces': n_routed_traces,
          'bam_connected': bam_connected,
          'dead_letters_missing_trace': dead_letters_missing_trace,
      },
      'trace_connected': trace_connected,
      'wall_s': round(time.time() - t0, 1),
  }
  print(json.dumps(verdict), flush=True)
  if mismatches:
    print(f'MISMATCHED vs solo: {sorted(set(mismatches))[:10]}',
          flush=True)
  if accepted_then_lost:
    print(f'ACCEPTED-THEN-LOST: {accepted_then_lost[:10]}', flush=True)
  scaler_counters = scaler_info.get('counters', {})
  interactive_p99 = metricz.get('class_latency', {}).get(
      'interactive', {}).get('p99')
  ok = (not mismatches and not accepted_then_lost and not errors
        and n_ok[0] > 0
        # Preemption drill: clean notice-driven drain, kill never
        # fired, the autoscaler replaced the capacity.
        and preempted['old_rc'] == 0 and preempted['old_drained']
        and preempted['old_preempted'] and not preempted['kill_fired']
        and preempted['replaced']
        # Replica count provably moved: the ramp forced a scale-out
        # and the controller both scaled out and replaced at least
        # once by its own accounting.
        and preempted['scaled_out']
        and preempted['max_ready_observed'] >= args.fleet + 1
        and scaler_rc == 0
        and scaler_counters.get('n_scale_out', 0) >= 1
        and scaler_counters.get('n_replaced', 0) >= 1
        # The interactive-class SLO held, as reported by the router's
        # unified /metricz per-class histogram.
        and interactive_p99 is not None
        and interactive_p99 <= args.slo_p99_s
        and router_rc == 0 and router_drained
        and all(rc == 0 for rc in tier_rcs)
        and bam_mismatch == 0 and bam_ok > 0
        and trace_connected)
  return 0 if ok else 1


def serve_soak(args) -> int:
  """Multi-client soak of a resident `dctpu serve` daemon."""
  sys.path.insert(0, os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))))
  from deepconsensus_tpu.serve.client import ServeClient, ServeClientError

  # Featurize every molecule once in the parent; clients re-send the
  # same feature payloads all soak long (the daemon does triage + model
  # + stitch per request).
  config = 'transformer_learn_values+test'
  molecules, synth_dir = _featurize_synth(args, args.serve_zmws)
  print(f'featurized {len(molecules)} molecules from {synth_dir}',
        flush=True)

  env = dict(os.environ)
  env['PYTHONPATH'] = '/root/repo:' + env.get('PYTHONPATH', '')
  env['JAX_PLATFORMS'] = env.get('JAX_PLATFORMS', 'cpu')
  proc = subprocess.Popen(
      [sys.executable, '-m', 'deepconsensus_tpu.cli', 'serve',
       '--random_init', '--config', config, '--port', '0',
       '--min_quality', '0',
       '--batch_size', str(args.serve_batch_size)],
      env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
      text=True)
  t0 = time.time()
  ready = json.loads(proc.stdout.readline())
  port = ready['port']
  print(json.dumps(ready), flush=True)

  # Solo baseline: one client, one pass, no concurrency.
  solo_client = ServeClient(port=port, timeout=180)
  solo = {}
  for features in molecules:
    resp = solo_client.polish_features(features)
    name = features[0]['name']
    name = name if isinstance(name, str) else name.decode()
    solo[name] = (resp['status'], resp['seq'],
                  None if resp['quals'] is None
                  else resp['quals'].tobytes())

  lock = threading.Lock()
  latencies = []
  mismatches = []
  errors = []
  n_ok = [0]

  def worker(wid):
    client = ServeClient(port=port, timeout=180)
    start = wid % max(1, len(molecules))
    rotated = molecules[start:] + molecules[:start]
    for r in range(args.serve_rounds):
      for features in rotated:
        name = features[0]['name']
        name = name if isinstance(name, str) else name.decode()
        t_req = time.monotonic()
        try:
          resp = client.polish_features(features)
        except ServeClientError as e:
          with lock:
            errors.append(f'{name}: HTTP {e.status}')
          continue
        except OSError:
          return  # daemon gone (post-drain) — expected for the tail burst
        dt = time.monotonic() - t_req
        got = (resp['status'], resp['seq'],
               None if resp['quals'] is None
               else resp['quals'].tobytes())
        with lock:
          latencies.append(dt)
          if got != solo[name]:
            mismatches.append(name)
          else:
            n_ok[0] += 1

  threads = [threading.Thread(target=worker, args=(w,))
             for w in range(args.serve)]
  for t in threads:
    t.start()
  for t in threads:
    t.join()

  metricz = solo_client.metricz()
  # Drain under residual load: a last burst of clients is mid-flight
  # when SIGTERM lands; everything admitted must still complete.
  tail = [threading.Thread(target=worker, args=(w,))
          for w in range(min(2, args.serve))]
  for t in tail:
    t.start()
  time.sleep(0.2)
  proc.send_signal(signal.SIGTERM)
  rc = proc.wait(timeout=300)
  for t in tail:
    t.join(60)
  drained_line = {}
  for line in proc.stdout.read().splitlines():
    if line.startswith('{'):
      d = json.loads(line)
      if d.get('event') == 'drained':
        drained_line = d

  lat = sorted(latencies)
  verdict = {
      'soak': 'serve',
      'rc': rc,
      'n_clients': args.serve,
      'n_molecules': len(molecules),
      'n_requests_verified': n_ok[0],
      'n_mismatches': len(mismatches),
      'n_client_errors': len(errors),
      'p50_s': round(lat[len(lat) // 2], 4) if lat else None,
      'p99_s': round(lat[min(len(lat) - 1, int(len(lat) * 0.99))], 4)
               if lat else None,
      'daemon_counters': metricz.get('counters', {}),
      'drained': bool(drained_line.get('drained')),
      'wall_s': round(time.time() - t0, 1),
  }
  print(json.dumps(verdict), flush=True)
  if mismatches:
    print(f'MISMATCHED vs solo: {sorted(set(mismatches))[:10]}',
          flush=True)
  ok = (rc == 0 and not mismatches and verdict['drained']
        and n_ok[0] > 0)
  return 0 if ok else 1


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--copies', type=int, default=500)
  ap.add_argument('--out_dir', default='/root/soak_r5')
  ap.add_argument('--checkpoint',
                  default='/root/distill_r4_ep4/checkpoints/checkpoint-152')
  ap.add_argument('--batch_zmws', type=int, default=100)
  ap.add_argument('--sample_every', type=float, default=10.0)
  ap.add_argument('--min_minutes', type=float, default=10.0)
  ap.add_argument('--synthetic_zmws', type=int, default=4000,
                  help='ZMW count for the synthetic fallback when the '
                  'reference testdata is absent (~5.8 ZMW/s on the '
                  '1-core CPU host -> 4000 gives a >10 min soak)')
  ap.add_argument('--fleet', type=int, default=0, metavar='N',
                  help='Fleet mode: N serve replicas behind `dctpu '
                  'route` with a `dctpu autoscale` controller (real '
                  'subprocesses, shared compile cache), forced '
                  'preemption + replacement mid-soak, disaggregated '
                  'bam/1 leg. Needs N >= 2.')
  ap.add_argument('--fleet_clients', type=int, default=4,
                  help='Fleet mode: concurrent clients through the '
                  'router (client 0 is the interactive tenant, the '
                  'rest are bulk).')
  ap.add_argument('--autoscale_p99_s', type=float, default=0.05,
                  help='Fleet mode: the autoscaler\'s interactive-p99 '
                  'scale-out target — deliberately tight so the load '
                  'ramp provably crosses it.')
  ap.add_argument('--slo_p99_s', type=float, default=120.0,
                  help='Fleet mode: the verdict gate on the '
                  'interactive-class p99 reported by the router '
                  '(generous: CPU hosts serve slowly; the gate is '
                  'that the class histogram exists and stays sane '
                  'while the replica count moves).')
  ap.add_argument('--serve', type=int, default=0, metavar='N',
                  help='Serve mode: soak one `dctpu serve` daemon with '
                  'N concurrent clients instead of the batch pipeline.')
  ap.add_argument('--serve_rounds', type=int, default=10,
                  help='Serve mode: polish passes over the molecule '
                  'set per client.')
  ap.add_argument('--serve_zmws', type=int, default=24,
                  help='Serve mode: synthetic molecule count.')
  ap.add_argument('--serve_batch_size', type=int, default=64,
                  help='Serve mode: daemon pack size (every pack pads '
                  'to this compiled shape; keep small on CPU hosts).')
  ap.add_argument('--batch_size', type=int, default=0,
                  help='Batch mode: child pack size (0 = library '
                  'default of 1024). Chaos mode forces 64 when unset '
                  'so the soak spans many packs and per-pack compute '
                  'stays well under --dispatch_timeout.')
  ap.add_argument('--chaos', action='store_true',
                  help='Inject one device OOM and one device hang '
                  'mid-soak; the run must complete via bisection + '
                  'watchdog with recovery counters in the verdict.')
  ap.add_argument('--chaos_oom_pack', type=int, default=3,
                  help='Chaos mode: 1-based dispatch ordinal of the '
                  'pack that fakes RESOURCE_EXHAUSTED.')
  ap.add_argument('--chaos_hang_pack', type=int, default=6,
                  help='Chaos mode: 1-based dispatch ordinal of the '
                  'pack whose finalize hangs.')
  ap.add_argument('--chaos_hang_s', type=float, default=6.0,
                  help='Chaos mode: how long the hung pack sleeps '
                  '(must exceed --dispatch_timeout).')
  ap.add_argument('--dispatch_timeout', type=float, default=2.0,
                  help='Chaos mode: watchdog bound on the blocking '
                  'device sync in the child.')
  args = ap.parse_args()

  if args.fleet > 0:
    return fleet_soak(args)

  if args.serve > 0:
    return serve_soak(args)

  if args.chaos and not args.batch_size:
    args.batch_size = 64

  os.makedirs(args.out_dir, exist_ok=True)
  # Hosts without the reference testdata fall back to deterministic
  # synthetic BAMs (the fault-injection helper) — QC numbers are
  # meaningless there, but the soak verdict is about pipeline-level
  # properties (throughput flatness, RSS growth, shm leaks), which the
  # synthetic stream exercises identically. Same fallback bench.py's
  # e2e stage uses.
  synthetic = not os.path.isdir(TESTDATA)
  if synthetic:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from scripts.inject_faults import write_synthetic_zmw_bams

    synth_dir = os.path.join(args.out_dir, f'synth_{args.synthetic_zmws}')
    if not os.path.isdir(synth_dir):
      t0 = time.time()
      os.makedirs(synth_dir, exist_ok=True)
      write_synthetic_zmw_bams(
          synth_dir, n_zmws=args.synthetic_zmws, n_subreads=5,
          seq_len=600)
      print(f'synthesized {args.synthetic_zmws} ZMWs -> {synth_dir} '
            f'({time.time() - t0:.1f}s)', flush=True)
    sub_bam = os.path.join(synth_dir, 'subreads_to_ccs.bam')
    ccs_bam = os.path.join(synth_dir, 'ccs.bam')
  else:
    sub_bam = os.path.join(args.out_dir, f'subreads_x{args.copies}.bam')
    ccs_bam = os.path.join(args.out_dir, f'ccs_x{args.copies}.bam')
    for src, dst in ((f'{TESTDATA}/subreads_to_ccs.bam', sub_bam),
                     (f'{TESTDATA}/ccs.bam', ccs_bam)):
      if not os.path.exists(dst):
        t0 = time.time()
        n = replicate_bam(src, dst, args.copies)
        print(f'replicated {src} -> {dst}: {n} records '
              f'({time.time() - t0:.1f}s)', flush=True)

  out_fastq = os.path.join(args.out_dir, 'soak.fastq')
  for stale in (out_fastq, out_fastq + '.tmp', out_fastq + '.progress.json',
                out_fastq + '.runtime.csv', out_fastq + '.inference.json'):
    if os.path.exists(stale):
      os.remove(stale)
  random_init = not os.path.exists(args.checkpoint)
  if random_init:
    # No servable checkpoint on this host: run the pipeline with
    # randomly initialized weights (bench.py's e2e stage does the
    # same). Output qualities are garbage; pipeline dynamics are real.
    child_code = (
        'import jax, sys\n'
        "jax.config.update('jax_platforms', 'cpu')\n"
        'import jax.numpy as jnp\n'
        'from deepconsensus_tpu.inference import runner as runner_lib\n'
        'from deepconsensus_tpu.models import config as config_lib\n'
        'from deepconsensus_tpu.models import model as model_lib\n'
        "params = config_lib.get_config('transformer_learn_values+test')\n"
        'config_lib.finalize_params(params, is_training=False)\n'
        'model = model_lib.get_model(params)\n'
        'variables = model.init(jax.random.PRNGKey(0), jnp.zeros(\n'
        '    (1, params.total_rows, params.max_length, 1)))\n'
        'sub, ccs, out, bz, bs, ode, dt, oze = sys.argv[1:9]\n'
        'options = runner_lib.InferenceOptions(\n'
        '    batch_zmws=int(bz), cpus=0, min_quality=0,\n'
        '    on_device_error=ode, dispatch_timeout=float(dt),\n'
        '    on_zmw_error=oze)\n'
        'if int(bs):\n'
        '  options.batch_size = int(bs)\n'
        'runner = runner_lib.ModelRunner(params, variables, options)\n'
        'runner_lib.run_inference(subreads_to_ccs=sub, ccs_bam=ccs,\n'
        '    checkpoint=None, output=out, options=options,\n'
        '    runner=runner)\n'
    )
    cmd = [
        sys.executable, '-c', child_code,
        sub_bam, ccs_bam, out_fastq, str(args.batch_zmws),
        str(args.batch_size),
        'degrade' if args.chaos else 'fail',
        str(args.dispatch_timeout if args.chaos else 0.0),
        # A watchdogged hang is never retried — its ZMWs must fall back
        # to CCS instead of aborting the whole soak.
        'ccs-fallback' if args.chaos else 'fail',
    ]
  else:
    child_code = (
        'import jax, sys\n'
        "jax.config.update('jax_platforms', 'cpu')\n"
        'from deepconsensus_tpu.cli import main\n'
        'sys.exit(main(sys.argv[1:]))\n'
    )
    cmd = [
        sys.executable, '-c', child_code, 'run',
        '--subreads_to_ccs', sub_bam, '--ccs_bam', ccs_bam,
        '--checkpoint', args.checkpoint, '--output', out_fastq,
        '--batch_zmws', str(args.batch_zmws),
        '--skip_windows_above', '0', '--min_quality', '0',
    ]
    if args.batch_size:
      cmd += ['--batch_size', str(args.batch_size)]
    if args.chaos:
      cmd += ['--on_device_error', 'degrade',
              '--dispatch_timeout', str(args.dispatch_timeout),
              '--on_zmw_error', 'ccs-fallback']
  env = dict(os.environ)
  env['PYTHONPATH'] = '/root/repo:' + env.get('PYTHONPATH', '')
  if args.chaos:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from deepconsensus_tpu import faults as shared_faults

    env[shared_faults.ENV_DEVICE_OOM_AT_PACK] = str(args.chaos_oom_pack)
    env[shared_faults.ENV_DEVICE_HANG_AT_PACK] = str(args.chaos_hang_pack)
    env[shared_faults.ENV_DEVICE_HANG_S] = str(args.chaos_hang_s)
    print(json.dumps({
        'chaos': 'armed',
        'oom_at_pack': args.chaos_oom_pack,
        'hang_at_pack': args.chaos_hang_pack,
        'hang_s': args.chaos_hang_s,
        'dispatch_timeout': args.dispatch_timeout,
    }), flush=True)
  proc = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                          stderr=subprocess.STDOUT)

  samples = []
  t0 = time.time()
  while proc.poll() is None:
    time.sleep(args.sample_every)
    try:
      with open(f'/proc/{proc.pid}/status') as f:
        rss_kb = next(
            (int(l.split()[1]) for l in f if l.startswith('VmRSS')), 0
        )
    except OSError:
      rss_kb = 0
    n_shm = len(os.listdir('/dev/shm')) if os.path.isdir('/dev/shm') else 0
    sample = {
        't': round(time.time() - t0, 1),
        'zmws_done': count_fastq_records(out_fastq),
        'rss_mb': round(rss_kb / 1024, 1),
        'shm_segments': n_shm,
    }
    samples.append(sample)
    print(json.dumps(sample), flush=True)
  rc = proc.returncode
  wall = time.time() - t0

  with open(os.path.join(args.out_dir, 'soak_samples.jsonl'), 'w') as f:
    for s in samples:
      f.write(json.dumps(s) + '\n')

  total = count_fastq_records(out_fastq)
  # Interval throughputs -> first/last quartile flatness ratio.
  # Leading zero-progress samples are JIT compile + BAM indexing, not
  # throughput; folding them into the first quartile would flunk the
  # flatness check on warmup alone.
  first_live = next(
      (i for i, s in enumerate(samples) if s['zmws_done'] > 0), 0)
  warmup_s = samples[first_live]['t'] if samples else 0.0
  live = samples[max(0, first_live - 1):]
  rates = []
  for a, b in zip(live, live[1:]):
    dt = b['t'] - a['t']
    if dt > 0:
      rates.append((b['zmws_done'] - a['zmws_done']) / dt)
  q = max(1, len(rates) // 4)
  first_q = sum(rates[:q]) / q if rates else 0.0
  last_q = sum(rates[-q:]) / q if rates else 0.0
  verdict = {
      'soak': 'e2e',
      'rc': rc,
      'synthetic_data': synthetic,
      'random_init_weights': random_init,
      'zmws_total': total,
      'wall_s': round(wall, 1),
      'warmup_s': round(warmup_s, 1),
      'zmw_per_s': round(total / wall, 2) if wall else 0.0,
      'first_quartile_zmw_per_s': round(first_q, 2),
      'last_quartile_zmw_per_s': round(last_q, 2),
      'throughput_flat': bool(
          first_q > 0 and 0.7 <= last_q / first_q <= 1.4
      ),
      'rss_mb_max': max((s['rss_mb'] for s in samples), default=0),
      'rss_mb_final': samples[-1]['rss_mb'] if samples else 0,
      'shm_segments_max': max(
          (s['shm_segments'] for s in samples), default=0
      ),
      'ran_minutes': round(wall / 60, 1),
      'long_enough': wall >= args.min_minutes * 60,
  }
  if args.chaos:
    counters = {}
    sidecar = out_fastq + '.inference.json'
    if os.path.exists(sidecar):
      with open(sidecar) as f:
        counters = json.load(f)
    chaos = {
        'n_device_faults': counters.get('n_device_faults', 0),
        'n_oom_bisections': counters.get('n_oom_bisections', 0),
        'n_dispatch_timeouts': counters.get('n_dispatch_timeouts', 0),
        'n_mesh_degradations': counters.get('n_mesh_degradations', 0),
        'n_zmw_quarantined': counters.get('n_zmw_quarantined', 0),
    }
    chaos['recovered'] = bool(
        rc == 0 and chaos['n_oom_bisections'] >= 1
        and chaos['n_dispatch_timeouts'] >= 1)
    verdict['chaos'] = chaos
  print(json.dumps(verdict), flush=True)
  if args.chaos:
    # Recovery counters are the point; flatness only judges runs long
    # enough to have quartiles that mean something.
    flat_ok = verdict['throughput_flat'] or len(rates) < 4
    return 0 if verdict['chaos']['recovered'] and flat_ok else 1
  return 0 if rc == 0 else rc


if __name__ == '__main__':
  raise SystemExit(main())
