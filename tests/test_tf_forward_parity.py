"""Value-level forward parity vs the reference TF model.

Builds the reference EncoderOnlyLearnedValuesTransformer from
/root/reference source (with minimal stubs for its two uninstalled
dependencies), saves a random-weight tf.train.Checkpoint, ports it with
port_tf_checkpoint, and asserts window-for-window forward agreement.
This is the test VERDICT r1 #5 asked for: it fails if any kernel
layout/transpose in the port map is wrong — and, beyond the port, it
proves the flax forward pass is numerically the reference model.
"""
import os
import sys
import types

import numpy as np
import pytest

REFERENCE_ROOT = '/root/reference'


def _install_stubs(tf):
  """Registers stand-ins for `official.nlp.modeling.layers` (tf-models)
  and `pysam`, which the reference imports but are not installed.

  OnDeviceEmbedding and RelativePositionEmbedding reimplement the
  tf-models semantics (embedding gather * scale_factor; [sin|cos]
  timing signal); pysam only supplies BAM-spec cigar ints (0..9).
  """
  if 'official' in sys.modules:
    return

  class OnDeviceEmbedding(tf.keras.layers.Layer):

    def __init__(self, vocab_size, embedding_width, initializer=None,
                 scale_factor=None, **kwargs):
      super().__init__(**kwargs)
      self._vocab_size = vocab_size
      self._embedding_width = embedding_width
      self._initializer = initializer or 'glorot_uniform'
      self._scale_factor = scale_factor

    def build(self, input_shape):
      self.embeddings = self.add_weight(
          'embeddings',
          shape=[self._vocab_size, self._embedding_width],
          initializer=self._initializer,
          dtype=tf.float32,
      )
      super().build(input_shape)

    def call(self, inputs):
      flat = tf.reshape(inputs, [-1])
      emb = tf.gather(self.embeddings, tf.cast(flat, tf.int32))
      emb = tf.reshape(
          emb, tf.concat([tf.shape(inputs), [self._embedding_width]], 0)
      )
      if self._scale_factor:
        emb *= self._scale_factor
      return emb

  class RelativePositionEmbedding(tf.keras.layers.Layer):

    def __init__(self, hidden_size, min_timescale=1.0,
                 max_timescale=1.0e4, **kwargs):
      super().__init__(**kwargs)
      self._hidden_size = hidden_size
      self._min_timescale = min_timescale
      self._max_timescale = max_timescale

    def call(self, inputs, length=None):
      if inputs is not None:
        length = tf.shape(inputs)[1]
      position = tf.cast(tf.range(length), tf.float32)
      num_timescales = self._hidden_size // 2
      log_increment = np.log(
          self._max_timescale / self._min_timescale
      ) / max(num_timescales - 1, 1)
      inv_timescales = self._min_timescale * tf.exp(
          tf.cast(tf.range(num_timescales), tf.float32) * -log_increment
      )
      scaled = tf.expand_dims(position, 1) * tf.expand_dims(
          inv_timescales, 0
      )
      return tf.concat([tf.sin(scaled), tf.cos(scaled)], axis=1)

  official = types.ModuleType('official')
  nlp = types.ModuleType('official.nlp')
  modeling = types.ModuleType('official.nlp.modeling')
  layers_mod = types.ModuleType('official.nlp.modeling.layers')
  layers_mod.OnDeviceEmbedding = OnDeviceEmbedding
  layers_mod.RelativePositionEmbedding = RelativePositionEmbedding
  official.nlp = nlp
  nlp.modeling = modeling
  modeling.layers = layers_mod
  sys.modules.update({
      'official': official,
      'official.nlp': nlp,
      'official.nlp.modeling': modeling,
      'official.nlp.modeling.layers': layers_mod,
  })

  if 'pysam' not in sys.modules:
    pysam = types.ModuleType('pysam')
    for i, name in enumerate(
        ['CMATCH', 'CINS', 'CDEL', 'CREF_SKIP', 'CSOFT_CLIP',
         'CHARD_CLIP', 'CPAD', 'CEQUAL', 'CDIFF', 'CBACK']
    ):
      setattr(pysam, name, i)
    sys.modules['pysam'] = pysam


def _finalize_ref_params(ref_params):
  """Reference modify_params' derivations (model_utils.py:237-355),
  replicated here because model_utils itself imports more uninstalled
  tf-models modules than the networks need."""
  from deepconsensus.models import data_providers
  from deepconsensus.models import transformer_basic_params

  with ref_params.unlocked():
    ref_params.batch_size = 4
    ref_params.total_rows = data_providers.get_total_rows(
        ref_params.max_passes, ref_params.use_ccs_bq
    )
    dim = (
        ref_params.use_bases * ref_params.per_base_hidden_size
        + ref_params.use_pw * ref_params.pw_hidden_size
        + ref_params.use_ip * ref_params.ip_hidden_size
        + ref_params.use_strand * ref_params.strand_hidden_size
        + ref_params.use_ccs_bq * ref_params.ccs_bq_hidden_size
    )
    ref_params.hidden_size = (
        ref_params.max_passes * dim
        + ref_params.use_ccs * ref_params.per_base_hidden_size
        + ref_params.use_ccs_bq * ref_params.ccs_bq_hidden_size
        + ref_params.use_sn * ref_params.sn_hidden_size * 4
    )
    if ref_params.hidden_size % 2 != 0:
      ref_params.hidden_size += 1
    ref_params.default_batch_size = ref_params.batch_size
    if ref_params.condense_transformer_input:
      ref_params.hidden_size = ref_params.transformer_input_size
    preset = {
        'tiny': transformer_basic_params.TINY_PARAMS,
        'base': transformer_basic_params.BASE_PARAMS,
        'big': transformer_basic_params.BIG_PARAMS,
    }[ref_params.transformer_model_size]
    for name, value in preset.items():
      if name not in ref_params:
        ref_params[name] = value


@pytest.fixture(scope='module')
def reference_model_and_checkpoint(tmp_path_factory):
  tf = pytest.importorskip('tensorflow')
  _install_stubs(tf)
  if REFERENCE_ROOT not in sys.path:
    sys.path.insert(0, REFERENCE_ROOT)
  pytest.importorskip(
      'deepconsensus',
      reason='reference deepconsensus checkout not present under '
      f'{REFERENCE_ROOT}')
  from deepconsensus.models import model_configs as ref_configs
  from deepconsensus.models import networks as ref_networks

  ref_params = ref_configs.get_config('transformer_learn_values+test')
  _finalize_ref_params(ref_params)
  model = ref_networks.EncoderOnlyLearnedValuesTransformer(ref_params)

  rng = np.random.default_rng(0)
  rows = np.zeros((4, ref_params.total_rows, ref_params.max_length, 1),
                  np.float32)
  mp = ref_params.max_passes
  rows[:, :mp] = rng.integers(0, 5, size=rows[:, :mp].shape)
  rows[:, mp:2 * mp] = rng.integers(0, 256, size=rows[:, :mp].shape)
  rows[:, 2 * mp:3 * mp] = rng.integers(0, 256, size=rows[:, :mp].shape)
  rows[:, 3 * mp:4 * mp] = rng.integers(0, 3, size=rows[:, :mp].shape)
  rows[:, 4 * mp] = rng.integers(0, 5, size=rows[:, 4 * mp].shape)
  rows[:, 4 * mp + 1:] = rng.integers(
      0, 15, size=rows[:, 4 * mp + 1:].shape)

  preds_tf = model(tf.constant(rows), training=False).numpy()

  prefix = str(tmp_path_factory.mktemp('tf_ckpt') / 'checkpoint-1')
  tf.train.Checkpoint(model=model).write(prefix)
  return ref_params, rows, preds_tf, prefix


def test_forward_parity_after_port(reference_model_and_checkpoint):
  import jax
  import jax.numpy as jnp

  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib
  from deepconsensus_tpu.models import port_tf_checkpoint as port

  ref_params, rows, preds_tf, prefix = reference_model_and_checkpoint

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
  # The two configs must describe the same architecture.
  for key in ('hidden_size', 'max_length', 'max_passes', 'num_heads',
              'num_hidden_layers', 'filter_size', 'attn_win_size',
              'transformer_input_size', 'per_base_hidden_size'):
    assert params[key] == ref_params[key], key

  model = model_lib.get_model(params)
  variables = model.init(
      jax.random.PRNGKey(0), jnp.asarray(rows[:1])
  )
  flax_params = jax.tree.map(np.asarray, variables['params'])
  ported = port.port_checkpoint(prefix, flax_params)

  preds_flax = np.asarray(
      model.apply({'params': ported}, jnp.asarray(rows))
  )
  np.testing.assert_allclose(preds_flax, preds_tf, atol=1e-4, rtol=1e-3)


def test_port_rejects_shape_mismatch(reference_model_and_checkpoint):
  import jax
  import jax.numpy as jnp

  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib
  from deepconsensus_tpu.models import port_tf_checkpoint as port

  _, _, _, prefix = reference_model_and_checkpoint
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_heads = 4  # wrong head split -> kernel shape mismatch
  model = model_lib.get_model(params)
  rows = jnp.zeros((1, params.total_rows, params.max_length, 1))
  flax_params = jax.tree.map(
      np.asarray, model.init(jax.random.PRNGKey(0), rows)['params']
  )
  with pytest.raises(ValueError, match='shape mismatch'):
    port.port_checkpoint(prefix, flax_params)


def test_port_to_orbax_cli_roundtrip(reference_model_and_checkpoint,
                                     tmp_path):
  """The port tool's CLI path: TF checkpoint -> orbax checkpoint that
  loads through the standard inference loader with identical outputs."""
  import jax
  import jax.numpy as jnp

  from deepconsensus_tpu import cli
  from deepconsensus_tpu.models import checkpoints as ckpt_lib
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib

  _, rows, preds_tf, prefix = reference_model_and_checkpoint
  out_dir = str(tmp_path / 'ported')
  # params.json: reuse this framework's config (same architecture).
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
  config_lib.save_params_as_json(out_dir, params)

  rc = cli.main([
      'port',
      '--tf_checkpoint', prefix,
      '--params', out_dir,
      '--out_dir', out_dir,
  ])
  assert rc == 0
  ported_ckpt = os.path.join(out_dir, 'checkpoints', 'checkpoint-0')
  loaded = ckpt_lib.load_params(ported_ckpt)
  model = model_lib.get_model(params)
  preds = np.asarray(
      model.apply({'params': loaded}, jnp.asarray(rows))
  )
  np.testing.assert_allclose(preds, preds_tf, atol=1e-4, rtol=1e-3)


def test_port_rejects_uncovered_flax_params(
    reference_model_and_checkpoint):
  """A flax module the TF checkpoint lacks must fail loudly instead of
  silently shipping init-valued weights."""
  import jax
  import jax.numpy as jnp

  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib
  from deepconsensus_tpu.models import port_tf_checkpoint as port

  _, _, _, prefix = reference_model_and_checkpoint
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
  model = model_lib.get_model(params)
  rows = jnp.zeros((1, params.total_rows, params.max_length, 1))
  flax_params = jax.tree.map(
      np.asarray, model.init(jax.random.PRNGKey(0), rows)['params']
  )
  flax_params['phantom_module'] = {
      'kernel': np.zeros((3, 3), np.float32)
  }
  with pytest.raises(ValueError, match='not covered'):
    port.port_checkpoint(prefix, flax_params)
