"""Shared test fixtures/builders (counterpart of the reference's
utils/test_utils.py:49-161)."""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from deepconsensus_tpu import constants


def seq_to_array(seq: str) -> np.ndarray:
  """ASCII sequence -> float vocab ids ('A T' -> [1, 0, 2])."""
  return np.array(
      [constants.SEQ_VOCAB.index(c) for c in seq], dtype=np.float32
  )


def seq_to_one_hot(seq: str) -> np.ndarray:
  """ASCII sequence -> one-hot [len, vocab] distribution."""
  eye = np.eye(constants.SEQ_VOCAB_SIZE, dtype=np.float32)
  return np.stack([eye[constants.SEQ_VOCAB.index(c)] for c in seq])


def get_one_hot(index: int) -> np.ndarray:
  return np.eye(constants.SEQ_VOCAB_SIZE, dtype=np.float32)[index]


def multiseq_to_array(seqs: Sequence[str]) -> np.ndarray:
  """List of equal-length sequences -> [n, len] vocab-id matrix."""
  return np.stack([seq_to_array(s) for s in seqs])


def convert_seqs(
    sequences: Tuple[Sequence[str], Sequence[str]]
) -> Tuple[np.ndarray, np.ndarray]:
  """(labels, predictions) string lists -> (y_true ids, y_pred one-hot)."""
  y_true = multiseq_to_array(sequences[0])
  y_pred = np.stack([seq_to_one_hot(s) for s in sequences[1]])
  return y_true, y_pred


def load_dataset_examples(pattern: str) -> List[bytes]:
  """All serialized examples matching a TFRecord glob."""
  from deepconsensus_tpu.io.tfrecord import read_tfrecords

  return list(read_tfrecords(pattern))
