"""Golden-window parity for the batch-major fused hot path
(ops/fused_window_attention.py) vs the XLA model path.

All tests run the kernel in Pallas interpret mode on CPU
(pallas_util.resolve_interpret), so the fused path's correctness is
provable without TPU hardware. The full-model goldens use the
production window shape (L=100, condensed input, ReZero) with the
float32 dtype override that every CPU numerics test in this repo uses.
ReZero alphas init to zero — which would let a broken attention fusion
pass trivially — so parity tests overwrite every alpha with a nonzero
value first.
"""
import flax
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.ops import fused_window_attention as fwa


def make_params(name='transformer_learn_values+test', pre=None, **overrides):
  params = config_lib.get_config(name)
  if pre:
    with params.unlocked():
      for k, v in pre.items():
        params[k] = v
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    for k, v in overrides.items():
      params[k] = v
  return params


def fake_rows(params, batch=2, seed=0):
  rng = np.random.default_rng(seed)
  rows = np.zeros(
      (batch, params.total_rows, params.max_length, 1), dtype=np.float32
  )
  mp = params.max_passes
  rows[:, :mp] = rng.integers(0, 5, size=rows[:, :mp].shape)
  rows[:, mp:2 * mp] = rng.integers(0, 256, size=rows[:, :mp].shape)
  rows[:, 2 * mp:3 * mp] = rng.integers(0, 256, size=rows[:, :mp].shape)
  rows[:, 3 * mp:4 * mp] = rng.integers(0, 3, size=rows[:, :mp].shape)
  rows[:, 4 * mp] = rng.integers(0, 5, size=rows[:, 4 * mp].shape)
  if params.use_ccs_bq:
    # ccs_bq stores gap as -1 (embedded with shift +1).
    rows[:, 4 * mp + 1] = rng.integers(
        -1, params.CCS_BQ_MAX - 1, size=rows[:, 4 * mp + 1].shape)
    sn_lo = 4 * mp + 2
  else:
    sn_lo = 4 * mp + 1
  rows[:, sn_lo:] = rng.integers(0, 501, size=rows[:, sn_lo:].shape)
  return jnp.asarray(rows)


def nonzero_alphas(variables, seed=3):
  """ReZero alphas init to 0, which zeroes every residual branch; give
  each a distinct nonzero value so parity actually exercises them."""
  flat = flax.traverse_util.flatten_dict(flax.core.unfreeze(variables))
  rng = np.random.default_rng(seed)
  for key in flat:
    if key[-1] == 'alpha':
      flat[key] = jnp.asarray(rng.uniform(0.3, 1.0), jnp.float32)
  return flax.traverse_util.unflatten_dict(flat)


def init_pair(params, batch=3, seed=0):
  rows = fake_rows(params, batch=batch, seed=seed)
  model = model_lib.get_model(params)
  variables = model.init(jax.random.PRNGKey(0), rows)
  return model, nonzero_alphas(variables), rows


def kernel_args(params, variables, rows):
  specs, keys, _ = fwa.build_family_specs(params)
  p = variables['params']
  tables = {k: p[f'{k}_embedding']['embedding'] for k in keys}
  h = params.hidden_size
  a0 = p['encoder']['self_attention_0']
  args = (
      jnp.squeeze(rows, -1), tables, p['condenser']['kernel'],
      a0['query']['kernel'].reshape(h, h),
      a0['key']['kernel'].reshape(h, h),
      a0['value']['kernel'].reshape(h, h),
      a0['output_transform']['kernel'].reshape(h, h),
      jnp.asarray(model_lib.sinusoidal_position_encoding(rows.shape[2], h)),
  )
  kwargs = dict(specs=specs, table_keys=keys, num_heads=params.num_heads,
                attn_win_size=params.attn_win_size or None)
  return args, kwargs


# ---------------------------------------------------------------------------
# Full-model goldens: production window shape, fused vs XLA.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize('embed_onehot', [False, True])
def test_fused_matches_xla_on_golden_production_windows(embed_onehot):
  """L=100, condensed, ReZero: the acceptance-criteria golden. Batch 11
  with the default tile of 8 also exercises the batch-padding path."""
  params = make_params(embed_onehot=embed_onehot)
  assert params.max_length == 100 and params.condense_transformer_input
  model, variables, rows = init_pair(params, batch=11, seed=7)
  ref = model.apply(variables, rows, False,
                    method='apply_with_intermediates')

  params_f = make_params(embed_onehot=embed_onehot, use_fused_hotpath=True)
  model_f = model_lib.get_model(params_f)
  got = model_f.apply(variables, rows, False,
                      method='apply_with_intermediates')
  # Acceptance bar: atol 1e-5 on the model output (preds). Logits get
  # a small rtol on top — six f32 encoder layers amplify the kernel's
  # different-but-valid summation order to ~2e-5 on O(10) logits.
  np.testing.assert_allclose(
      np.asarray(got['logits']), np.asarray(ref['logits']),
      rtol=2e-3, atol=1e-5)
  np.testing.assert_allclose(
      np.asarray(got['preds']), np.asarray(ref['preds']), atol=1e-5)


def test_fused_matches_xla_with_ccs_bq():
  """The ccs_bq family has a +1 id shift and its own vocab; make sure
  the family-spec table covers it."""
  params = make_params(pre={'use_ccs_bq': True})
  model, variables, rows = init_pair(params, batch=4, seed=11)
  ref = model.apply(variables, rows)
  params_f = make_params(pre={'use_ccs_bq': True}, use_fused_hotpath=True)
  got = model_lib.get_model(params_f).apply(variables, rows)
  np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_fused_path_is_actually_taken(monkeypatch):
  """Guard against eligibility silently routing to XLA (which would
  make every parity test vacuous)."""
  calls = []
  real = fwa.fused_embed_condense_attention

  def spy(*args, **kwargs):
    calls.append(1)
    return real(*args, **kwargs)

  monkeypatch.setattr(fwa, 'fused_embed_condense_attention', spy)
  params = make_params(use_fused_hotpath=True)
  model, variables, rows = init_pair(params, batch=2)
  assert not calls  # init must create params via the XLA path
  model.apply(variables, rows)
  assert calls


def test_fused_softmax_dtype_lever():
  """attn_softmax_dtype=bfloat16 mirrors the XLA cast chain; bf16
  accumulation legitimately perturbs weights at ~1e-2, so the check is
  loose tolerance + argmax agreement (same bar as the XLA lever test)."""
  params = make_params(attn_softmax_dtype='bfloat16')
  model, variables, rows = init_pair(params, batch=3, seed=5)
  ref = model.apply(variables, rows)
  params_f = make_params(attn_softmax_dtype='bfloat16',
                         use_fused_hotpath=True)
  got = model_lib.get_model(params_f).apply(variables, rows)
  np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=5e-2)
  # bf16 rounding order differs between the two paths, so near-tie
  # positions can legitimately flip; require near-total agreement.
  agree = np.mean(
      np.asarray(got.argmax(-1)) == np.asarray(ref.argmax(-1)))
  assert agree >= 0.98, f'argmax agreement {agree:.3f}'


# ---------------------------------------------------------------------------
# Fallback routing: configs the kernel doesn't serve must be bitwise
# identical to the flag-off run (both land on the XLA path).
# ---------------------------------------------------------------------------


def test_training_falls_back_to_xla():
  params = make_params()
  model, variables, rows = init_pair(params, batch=2)
  rngs = {'dropout': jax.random.PRNGKey(42)}
  ref = model.apply(variables, rows, train=True, rngs=rngs)
  params_f = make_params(use_fused_hotpath=True)
  got = model_lib.get_model(params_f).apply(
      variables, rows, train=True, rngs=rngs)
  np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_long_window_falls_back_to_xla():
  pre = {'max_length': fwa.MAX_WINDOW_LEN + 32}
  params = make_params(pre=pre)
  model, variables, rows = init_pair(params, batch=2)
  ref = model.apply(variables, rows)
  params_f = make_params(pre=pre, use_fused_hotpath=True)
  got = model_lib.get_model(params_f).apply(variables, rows)
  np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_init_param_tree_identical():
  params = make_params()
  params_f = make_params(use_fused_hotpath=True)
  rows = fake_rows(params, batch=2)
  v0 = model_lib.get_model(params).init(jax.random.PRNGKey(0), rows)
  v1 = model_lib.get_model(params_f).init(jax.random.PRNGKey(0), rows)
  assert jax.tree_util.tree_structure(v0) == jax.tree_util.tree_structure(v1)
  for a, b in zip(jax.tree_util.tree_leaves(v0),
                  jax.tree_util.tree_leaves(v1)):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Kernel-level unit tests vs the pure-jnp reference.
# ---------------------------------------------------------------------------


def test_family_specs_cover_condenser_input():
  for pre in (None, {'use_ccs_bq': True}):
    params = make_params(pre=pre)
    specs, keys, width = fwa.build_family_specs(params)
    variables = model_lib.get_model(params).init(
        jax.random.PRNGKey(0), fake_rows(params, batch=1))
    assert width == variables['params']['condenser']['kernel'].shape[0]
    assert sorted({s.name for s in specs}) == sorted(
        ['bases', 'pw', 'ip', 'strand', 'ccs', 'sn']
        + (['ccs_bq'] if params.use_ccs_bq else []))
    # ccs rows must share the bases table.
    ccs = next(s for s in specs if s.name == 'ccs')
    bases = next(s for s in specs if s.name == 'bases')
    assert ccs.table_idx == bases.table_idx


@pytest.mark.parametrize('attn_win_size', [None, 12])
@pytest.mark.parametrize('batch,tile', [(3, 4), (11, 4)])
def test_kernel_matches_jnp_reference(attn_win_size, batch, tile):
  """Direct kernel-vs-reference parity, including batch==tile-remainder
  padding (11 % 4 != 0) and unbanded attention."""
  params = make_params()
  with params.unlocked():
    params.attn_win_size = attn_win_size or 0
  model, variables, rows = init_pair(params, batch=batch, seed=batch)
  args, kwargs = kernel_args(params, variables, rows)
  xb_k, at_k = fwa.fused_embed_condense_attention(
      *args, tile_windows=tile, **kwargs)
  xb_r, at_r = fwa.reference_fused_forward(*args, **kwargs)
  assert xb_k.shape == (batch, params.max_length, params.hidden_size)
  # When batch != tile the reference chunks differently than the
  # kernel, so f32 summation order differs at the ~1e-6 level.
  np.testing.assert_allclose(np.asarray(xb_k), np.asarray(xb_r), atol=1e-5)
  np.testing.assert_allclose(np.asarray(at_k), np.asarray(at_r), atol=1e-5)


def test_kernel_rejects_mismatched_condenser():
  params = make_params()
  model, variables, rows = init_pair(params, batch=2)
  args, kwargs = kernel_args(params, variables, rows)
  bad = list(args)
  bad[2] = jnp.zeros((args[2].shape[0] + 8, args[2].shape[1]))
  with pytest.raises(ValueError, match='condenser'):
    fwa.fused_embed_condense_attention(*bad, **kwargs)
