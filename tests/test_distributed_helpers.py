import jax
import numpy as np

from deepconsensus_tpu.parallel import distributed, mesh as mesh_lib


def test_initialize_single_process_noop():
  distributed.initialize()  # must not raise in single-process mode


def test_local_batch_slice_single_host():
  sl = distributed.local_batch_slice(64)
  assert sl == slice(0, 64)


def test_param_shardings_tp_divisibility_guard():
  # Odd dims replicate instead of sharding on the model axis.
  m = mesh_lib.make_mesh(dp=4, tp=2)
  params = {
      'encoder': {
          'ffn_0': {
              'filter_layer': {
                  'kernel': np.zeros((280, 2048), np.float32),
                  'bias': np.zeros((2048,), np.float32),
              },
          },
          'ffn_1': {
              'filter_layer': {
                  # Odd filter size: cannot shard over tp=2.
                  'kernel': np.zeros((280, 2047), np.float32),
              },
          },
      },
  }
  shardings = mesh_lib.param_shardings(m, params)
  even = shardings['encoder']['ffn_0']['filter_layer']['kernel']
  odd = shardings['encoder']['ffn_1']['filter_layer']['kernel']
  assert even.spec == jax.sharding.PartitionSpec(None, 'model')
  assert odd.spec == jax.sharding.PartitionSpec()


def test_cli_yield_metrics(testdata_dir, tmp_path):
  from deepconsensus_tpu import cli

  out = str(tmp_path / 'yield.csv')
  rc = cli.main([
      'yield_metrics',
      '--bam', str(testdata_dir
                   / 'prediction_assessment'
                   / 'CHM13_chr20_0_200000_dc.to_truth.bam'),
      '--ref', str(testdata_dir
                   / 'prediction_assessment/CHM13_chr20_0_200000.fa'),
      '--output', out,
  ])
  assert rc == 0
  with open(out) as f:
    assert 'yield_bases' in f.readline()
