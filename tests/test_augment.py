"""Training-time window augmentation (models/data.py:augment_batch)."""
import numpy as np
import pytest

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib


@pytest.fixture(scope='module')
def batch_and_params(testdata_dir):
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  ds = data_lib.DatasetIterator(
      patterns=str(
          testdata_dir / 'human_1m/tf_examples/train/train.tfrecord.gz'
      ),
      params=params,
      batch_size=48,
      seed=0,
      shuffle=False,
      limit=48,
  )
  return next(iter(ds)), params


def with_probs(params, **probs):
  p = config_lib.ml_collections.ConfigDict(params.to_dict())
  for k in ('augment_perm_prob', 'augment_drop_prob', 'augment_rc_prob',
            'augment_jitter_prob'):
    p[k] = 0.0
  for k, v in probs.items():
    p[k] = v
  return p


def subread_blocks(rows, p):
  return rows[:, : 4 * p, :, 0].reshape(rows.shape[0], 4, p,
                                        rows.shape[2])


def test_augment_noop_when_all_probs_zero(batch_and_params):
  batch, params = batch_and_params
  out = data_lib.augment_batch(batch, with_probs(params),
                               np.random.default_rng(0))
  np.testing.assert_array_equal(out['rows'], batch['rows'])
  np.testing.assert_array_equal(out['label'], batch['label'])
  assert out['rows'] is not batch['rows']  # never aliases the input


def test_augment_preserves_shapes_and_input(batch_and_params):
  batch, params = batch_and_params
  rows_before = batch['rows'].copy()
  label_before = batch['label'].copy()
  p = with_probs(params, augment_perm_prob=1.0, augment_drop_prob=1.0,
                 augment_rc_prob=1.0, augment_jitter_prob=1.0)
  out = data_lib.augment_batch(batch, p, np.random.default_rng(1))
  assert out['rows'].shape == batch['rows'].shape
  assert out['rows'].dtype == batch['rows'].dtype
  assert out['label'].shape == batch['label'].shape
  # The input batch is untouched.
  np.testing.assert_array_equal(batch['rows'], rows_before)
  np.testing.assert_array_equal(batch['label'], label_before)
  # And the augmented batch actually differs.
  assert not np.array_equal(out['rows'], batch['rows'])


def test_permutation_preserves_subread_multiset(batch_and_params):
  batch, params = batch_and_params
  p = with_probs(params, augment_perm_prob=1.0)
  out = data_lib.augment_batch(batch, p, np.random.default_rng(2))
  mp = params.max_passes
  before = subread_blocks(batch['rows'], mp)
  after = subread_blocks(out['rows'], mp)
  changed = 0
  for b in range(before.shape[0]):
    # Each subread is the 4-feature tuple (bases, pw, ip, strand);
    # permutation must preserve the multiset of tuples.
    tb = {tuple(before[b, :, i].ravel()) for i in range(mp)}
    ta = {tuple(after[b, :, i].ravel()) for i in range(mp)}
    assert tb == ta
    changed += int(
        not np.array_equal(before[b], after[b])
    )
  assert changed > before.shape[0] // 2  # prob 1.0: most examples move
  # ccs/sn rows and the label are untouched by permutation.
  np.testing.assert_array_equal(
      out['rows'][:, 4 * mp:], batch['rows'][:, 4 * mp:]
  )
  np.testing.assert_array_equal(out['label'], batch['label'])


def test_downsample_keeps_at_least_half(batch_and_params):
  batch, params = batch_and_params
  p = with_probs(params, augment_drop_prob=1.0)
  out = data_lib.augment_batch(batch, p, np.random.default_rng(3))
  mp = params.max_passes
  before = subread_blocks(batch['rows'], mp)
  after = subread_blocks(out['rows'], mp)
  n_before = (before[:, 3].max(axis=2) > 0).sum(axis=1)
  n_after = (after[:, 3].max(axis=2) > 0).sum(axis=1)
  assert (n_after <= n_before).all()
  assert (n_after >= -(-n_before // 2)).all()  # keep >= ceil(n/2)
  assert (n_after >= 1).all()
  # Kept subreads are a subset of the originals, compacted to front.
  for b in range(before.shape[0]):
    tb = {tuple(before[b, :, i].ravel()) for i in range(mp)}
    for i in range(int(n_after[b])):
      assert tuple(after[b, :, i].ravel()) in tb
    # Tail is zero.
    assert not after[b, :, int(n_after[b]):].any()


def test_reverse_complement_is_involutive(batch_and_params):
  batch, params = batch_and_params
  p = with_probs(params, augment_rc_prob=1.0)
  once = data_lib.augment_batch(batch, p, np.random.default_rng(4))
  assert not np.array_equal(once['rows'], batch['rows'])
  assert not np.array_equal(once['label'], batch['label'])
  twice = data_lib.augment_batch(once, p, np.random.default_rng(5))
  np.testing.assert_array_equal(twice['rows'], batch['rows'])
  # Label: RC twice reverses the full row twice -> identity.
  np.testing.assert_array_equal(twice['label'], batch['label'])


def test_reverse_complement_flips_strand_and_sn(batch_and_params):
  batch, params = batch_and_params
  p = with_probs(params, augment_rc_prob=1.0)
  out = data_lib.augment_batch(batch, p, np.random.default_rng(6))
  mp = params.max_passes
  strand_b = batch['rows'][:, 3 * mp : 4 * mp, :, 0]
  strand_a = out['rows'][:, 3 * mp : 4 * mp, :, 0]
  # 1 <-> 2 swap: the multiset per example flips.
  assert ((strand_b == 1).sum() == (strand_a == 2).sum())
  assert ((strand_b == 2).sum() == (strand_a == 1).sum())
  sn_start = 4 * mp + 1 + (1 if params.use_ccs_bq else 0)
  sn_b = batch['rows'][:, sn_start : sn_start + 4, :, 0]
  sn_a = out['rows'][:, sn_start : sn_start + 4, :, 0]
  np.testing.assert_array_equal(sn_a, sn_b[:, [3, 2, 1, 0]])


def test_jitter_bounded_and_sparse(batch_and_params):
  batch, params = batch_and_params
  p = with_probs(params, augment_jitter_prob=1.0)
  out = data_lib.augment_batch(batch, p, np.random.default_rng(7))
  mp = params.max_passes
  for lo, hi, cap in ((mp, 2 * mp, params.PW_MAX),
                      (2 * mp, 3 * mp, params.IP_MAX)):
    before = batch['rows'][:, lo:hi, :, 0]
    after = out['rows'][:, lo:hi, :, 0]
    # Zero (absent/gap) entries never become nonzero.
    assert not after[before == 0].any()
    nz = before > 0
    assert (after[nz] >= 1).all() and (after[nz] <= cap).all()
    assert np.abs(after[nz] - before[nz]).max() <= 1
  # Bases/strand/ccs rows untouched.
  np.testing.assert_array_equal(out['rows'][:, :mp], batch['rows'][:, :mp])
  np.testing.assert_array_equal(
      out['rows'][:, 3 * mp:], batch['rows'][:, 3 * mp:]
  )


def test_augmented_loss_stays_in_family(batch_and_params):
  """The alignment loss of a fixed prediction against augmented labels
  stays finite, and RC'd labels score identically to RC'd predictions
  (sequence-level consistency of the label transform)."""
  import jax
  import jax.numpy as jnp

  from deepconsensus_tpu.models import losses as losses_lib

  batch, params = batch_and_params
  p = with_probs(params, augment_rc_prob=1.0)
  out = data_lib.augment_batch(batch, p, np.random.default_rng(8))
  y_true = jnp.asarray(batch['label'][:8])
  y_true_rc = jnp.asarray(out['label'][:8])
  rng = np.random.default_rng(0)
  logits = jnp.asarray(
      rng.normal(size=(8, params.max_length, 5)).astype(np.float32)
  )
  y_pred = jax.nn.softmax(logits)
  loss = losses_lib.AlignmentLoss(del_cost=10.0, loss_reg=0.1)
  base = float(loss(y_true, y_pred))
  aug = float(loss(y_true_rc, y_pred))
  assert np.isfinite(base) and np.isfinite(aug)
  # RC both sides: reverse the prediction along the window and swap
  # complement channels (vocab ' ATCG' -> [0, 2, 1, 4, 3]).
  y_pred_rc = y_pred[:, ::-1, :][:, :, jnp.asarray([0, 2, 1, 4, 3])]
  aug_both = float(loss(y_true_rc, y_pred_rc))
  np.testing.assert_allclose(aug_both, base, rtol=1e-5)


def test_rc_partial_batch_leaves_unflipped_examples_untouched(
    batch_and_params):
  """At rc_prob=0.5 the non-flipped examples' rows AND label must be
  byte-identical to the input (review regression: the ccs row of
  non-flipped examples was being complemented in place)."""
  batch, params = batch_and_params
  p = with_probs(params, augment_rc_prob=0.5)
  out = data_lib.augment_batch(batch, p, np.random.default_rng(9))
  mp = params.max_passes
  # RC is the only enabled transform, so an example is flipped iff its
  # bases block changed; every other example must be untouched in FULL
  # (the regression: their ccs row came back complemented).
  rc_on = np.array([
      not np.array_equal(out['rows'][b, :mp], batch['rows'][b, :mp])
      for b in range(batch['rows'].shape[0])
  ])
  assert rc_on.any() and not rc_on.all()  # both kinds in the batch
  np.testing.assert_array_equal(
      out['rows'][~rc_on], batch['rows'][~rc_on]
  )
  np.testing.assert_array_equal(
      out['label'][~rc_on], batch['label'][~rc_on]
  )


def test_downsample_subset_is_random_without_permutation(
    batch_and_params):
  """Drop-only augmentation (perm off) must remove a RANDOM subset, not
  always the trailing subreads (review regression), while preserving
  the original relative order of the kept ones."""
  batch, params = batch_and_params
  p = with_probs(params, augment_drop_prob=1.0)
  out = data_lib.augment_batch(batch, p, np.random.default_rng(10))
  mp = params.max_passes
  before = subread_blocks(batch['rows'], mp)
  after = subread_blocks(out['rows'], mp)
  n_after = (after[:, 3].max(axis=2) > 0).sum(axis=1)
  non_tail_drop = 0
  for b in range(before.shape[0]):
    k = int(n_after[b])
    sig = lambda blk, i: tuple(blk[b, :, i].ravel())
    kept = [sig(after, i) for i in range(k)]
    orig = [sig(before, i) for i in range(mp)]
    # Kept rows appear in their original relative order.
    pos = [orig.index(s) for s in kept]
    assert pos == sorted(pos), (b, pos)
    # Not simply the first k originals?
    if kept != orig[:k]:
      non_tail_drop += 1
  assert non_tail_drop > before.shape[0] // 4


@pytest.fixture(scope='module')
def bq_batch_and_params(batch_and_params):
  """Synthesizes a use_ccs_bq=True batch by inserting a ccs_bq row
  (the bundled shard predates bq; the transform logic is what is under
  test — review finding: the bq branch had zero coverage)."""
  batch, params = batch_and_params
  rows = batch['rows']
  mp = params.max_passes
  ccs_row = 4 * mp
  b, _, length, _ = rows.shape
  rng = np.random.default_rng(42)
  bq = rng.integers(0, 93, size=(b, 1, length, 1)).astype(rows.dtype)
  # -1 padding beyond the ccs content extent (pileup's bq pad rule).
  ccs_content = rows[:, ccs_row : ccs_row + 1, :, :] > 0
  bq = np.where(ccs_content, bq, -1.0)
  rows_bq = np.concatenate(
      [rows[:, : ccs_row + 1], bq, rows[:, ccs_row + 1 :]], axis=1
  )
  p = config_lib.ml_collections.ConfigDict(params.to_dict())
  p.use_ccs_bq = True
  p.total_rows = params.total_rows + 1
  return {'rows': rows_bq, 'label': batch['label'].copy()}, p


def test_rc_with_ccs_bq_row(bq_batch_and_params):
  """RC with use_ccs_bq: the bq row reverses with the window (staying
  aligned to the RC'd ccs row), the SN swap applies to the SN rows at
  their shifted offset, and RC remains involutive."""
  batch, params = bq_batch_and_params
  p = with_probs(params, augment_rc_prob=1.0)
  out = data_lib.augment_batch(batch, p, np.random.default_rng(11))
  mp = params.max_passes
  ccs_row = 4 * mp
  sn_start = ccs_row + 2  # ccs, ccs_bq, then 4 SN rows
  # SN swap hit the actual SN rows, not the bq row.
  np.testing.assert_array_equal(
      out['rows'][:, sn_start : sn_start + 4],
      batch['rows'][:, sn_start : sn_start + 4][:, [3, 2, 1, 0]],
  )
  # bq stays aligned with ccs: wherever the RC'd ccs has a base, the
  # RC'd bq carries the value that base had before the flip.
  ccs_b = batch['rows'][:, ccs_row, :, 0]
  bq_b = batch['rows'][:, ccs_row + 1, :, 0]
  ccs_a = out['rows'][:, ccs_row, :, 0]
  bq_a = out['rows'][:, ccs_row + 1, :, 0]
  comp = np.array([0, 2, 1, 4, 3], dtype=ccs_b.dtype)
  for b_i in range(ccs_b.shape[0]):
    nz_b = np.flatnonzero(ccs_b[b_i] > 0)
    nz_a = np.flatnonzero(ccs_a[b_i] > 0)
    assert len(nz_b) == len(nz_a)
    # Reversed base-by-base: k-th base of RC'd ccs == complement of
    # the k-th-from-last original base, and its bq follows it.
    np.testing.assert_array_equal(
        ccs_a[b_i, nz_a], comp[ccs_b[b_i, nz_b[::-1]].astype(int)]
    )
    np.testing.assert_array_equal(bq_a[b_i, nz_a], bq_b[b_i, nz_b[::-1]])
  # Involution.
  twice = data_lib.augment_batch(out, p, np.random.default_rng(12))
  np.testing.assert_array_equal(twice['rows'], batch['rows'])
  np.testing.assert_array_equal(twice['label'], batch['label'])


def test_unfired_example_with_interior_absent_subread_untouched():
  """The combined perm/drop gather is only the identity for an
  example where neither transform fired if its present subreads are
  front-compacted; the write must be gated per-example so an example
  with an interior all-zero subread row passes through byte-identical
  (review regression, ADVICE round-5)."""
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  mp, length = params.max_passes, params.max_length
  b = 4
  rows = np.zeros((b, params.total_rows, length, 1), np.float32)

  def set_subread(example, slot, base):
    rows[example, slot, :, 0] = base  # bases
    rows[example, 3 * mp + slot, :, 0] = 1.0  # strand FORWARD
  # Example 0: subreads 0 and 2 present, slot 1 an interior hole.
  set_subread(0, 0, 1.0)
  set_subread(0, 2, 3.0)
  # Remaining examples: two front-compacted subreads.
  for ex in range(1, b):
    set_subread(ex, 0, 2.0)
    set_subread(ex, 1, 4.0)
  batch = {'rows': rows,
           'label': np.zeros((b, length), np.int64)}
  p = with_probs(params, augment_perm_prob=0.5)
  # Find a seed whose FIRST rng draw (perm_on) skips example 0 but
  # fires for at least one other example, mirroring augment_batch's
  # draw order.
  seed = next(
      s for s in range(1000)
      if (lambda m: not m[0] and m[1:].any())(
          np.random.default_rng(s).random(b) < 0.5))
  out = data_lib.augment_batch(batch, p, np.random.default_rng(seed))
  assert not np.array_equal(out['rows'], batch['rows'])  # someone fired
  np.testing.assert_array_equal(out['rows'][0], batch['rows'][0])
