"""Property/fuzz tests for the alignment DPs.

* A band at least as wide as the sequence equals the unbanded loss.
* Soft-min loss approaches the hard-min loss as reg -> 0 (from below).
* AlignmentMetric's optimal score matches a naive O(mn) affine-gap NW
  implemented directly in test code.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.models import losses, metrics


def random_case(rng, m=12):
  y_true = rng.integers(0, 5, size=(1, m)).astype(np.float32)
  logits = rng.normal(size=(1, m, 5)).astype(np.float32)
  y_pred = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
  return jnp.asarray(y_true), jnp.asarray(y_pred)


@pytest.mark.parametrize('seed', range(10))
def test_wide_band_equals_unbanded(seed):
  rng = np.random.default_rng(seed)
  y_true, y_pred = random_case(rng)
  m = y_true.shape[1]
  full = losses.AlignmentLoss(del_cost=3.0, loss_reg=None)
  banded = losses.AlignmentLoss(del_cost=3.0, loss_reg=None, width=m)
  a = float(full(y_true, y_pred))
  b = float(banded(y_true, y_pred))
  assert a == pytest.approx(b, rel=1e-5), seed


@pytest.mark.parametrize('seed', range(5))
def test_soft_min_bounds_hard_min(seed):
  rng = np.random.default_rng(100 + seed)
  y_true, y_pred = random_case(rng)
  hard = float(losses.AlignmentLoss(del_cost=3.0, loss_reg=None)(
      y_true, y_pred))
  for reg in (1.0, 0.1, 0.01):
    soft = float(losses.AlignmentLoss(del_cost=3.0, loss_reg=reg)(
        y_true, y_pred))
    assert soft <= hard + 1e-4
  tight = float(losses.AlignmentLoss(del_cost=3.0, loss_reg=0.01)(
      y_true, y_pred))
  assert tight == pytest.approx(hard, abs=0.2)


def naive_affine_nw(a, b, match=2.0, mismatch=5.0, gap_open=9.0,
                    gap_extend=4.0):
  """Gotoh affine-gap NW score maximization (open includes first
  extend, matching AlignmentMetric's folded gap_open)."""
  m, n = len(a), len(b)
  NEG = -1e9
  Mm = np.full((m + 1, n + 1), NEG)
  Ix = np.full((m + 1, n + 1), NEG)  # consume b (insertion)
  Iy = np.full((m + 1, n + 1), NEG)  # consume a (deletion)
  Mm[0, 0] = 0.0
  for j in range(1, n + 1):
    Ix[0, j] = -(gap_open + (j - 1) * gap_extend)
  for i in range(1, m + 1):
    Iy[i, 0] = -(gap_open + (i - 1) * gap_extend)
  for i in range(1, m + 1):
    for j in range(1, n + 1):
      s = match if a[i - 1] == b[j - 1] else -mismatch
      Mm[i, j] = max(Mm[i - 1, j - 1], Ix[i - 1, j - 1],
                     Iy[i - 1, j - 1]) + s
      Ix[i, j] = max(Mm[i, j - 1] - gap_open, Ix[i, j - 1] - gap_extend)
      Iy[i, j] = max(Mm[i - 1, j] - gap_open, Ix[i - 1, j] - gap_open,
                     Iy[i - 1, j] - gap_extend)
  return max(Mm[m, n], Ix[m, n], Iy[m, n])


@pytest.mark.parametrize('seed', range(15))
def test_alignment_metric_score_matches_naive_nw(seed):
  rng = np.random.default_rng(200 + seed)
  m = 10
  true_len = int(rng.integers(1, m + 1))
  pred_len = int(rng.integers(1, m + 1))
  true_seq = rng.integers(1, 5, size=true_len)
  pred_seq = rng.integers(1, 5, size=pred_len)
  y_true = np.zeros((1, m), np.float32)
  y_true[0, :true_len] = true_seq
  y_pred = np.zeros((1, m, 5), np.float32)
  for j in range(m):
    y_pred[0, j, pred_seq[j] if j < pred_len else 0] = 1.0

  metric = metrics.AlignmentMetric()
  v_opt, _, mv = metric.alignment(
      jnp.asarray(y_true), jnp.asarray(y_pred)
  )
  want = naive_affine_nw(list(true_seq), list(pred_seq))
  assert float(v_opt[0]) == pytest.approx(want, abs=1e-4), (
      seed, true_seq, pred_seq
  )
  # Path-derived counts are consistent.
  assert int(mv['alignment_length'][0]) >= max(true_len, pred_len)
