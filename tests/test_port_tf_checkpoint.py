"""TF->flax checkpoint port: full name/shape mapping validated against
the bundled reference checkpoint index (data blobs are stripped
upstream, so value transfer is validated structurally)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.models import port_tf_checkpoint as port


@pytest.fixture(scope='module')
def flax_params():
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
  model = model_lib.get_model(params)
  rows = jnp.zeros((1, params.total_rows, params.max_length, 1))
  return model.init(jax.random.PRNGKey(0), rows)['params']


def test_every_reference_variable_maps(testdata_dir, flax_params):
  tf = pytest.importorskip('tensorflow')
  prefix = str(testdata_dir / 'model/checkpoint-1')
  mapping, unmapped = port.map_checkpoint_names(prefix)
  assert not unmapped, unmapped
  # All six embeddings + condenser + logits + 6*(attention 4 + alpha) +
  # 6*(ffn 4 + alpha) + final LN(2).
  assert len(mapping) >= 5 + 1 + 2 + 6 * 5 + 6 * 5 + 2

  flat = {
      '/'.join(str(getattr(k, 'key', k)) for k in path): v
      for path, v in jax.tree_util.tree_flatten_with_path(flax_params)[0]
  }
  for tf_name, path in mapping.items():
    key = '/'.join(path)
    assert key in flat, f'{tf_name} -> {key} missing in flax params'

  # Shapes agree variable-for-variable with the reference index.
  for (tf_name, shape) in tf.train.list_variables(prefix):
    path = port.tf_name_to_flax_path(tf_name)
    if path is None:
      continue
    key = '/'.join(path)
    flax_shape = tuple(flat[key].shape)
    assert tuple(shape) == flax_shape, (tf_name, shape, flax_shape)


def test_non_model_variables_ignored():
  assert port.tf_name_to_flax_path(
      'save_counter/.ATTRIBUTES/VARIABLE_VALUE') is None
  assert port.tf_name_to_flax_path(
      'model/fc1/kernel/.OPTIMIZER_SLOT/optimizer/m/'
      '.ATTRIBUTES/VARIABLE_VALUE') is None
  assert port.tf_name_to_flax_path('_CHECKPOINTABLE_OBJECT_GRAPH') is None
