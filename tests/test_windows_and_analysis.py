"""Smart-window width translation and error-analysis utilities."""
import numpy as np
import pytest

from deepconsensus_tpu import constants
from deepconsensus_tpu.preprocess.alignment import AlignedRead
from deepconsensus_tpu.preprocess.pileup import FeatureLayout, Pileup
from deepconsensus_tpu.utils import analysis

C = constants.Cigar
M, I = int(C.MATCH), int(C.INS)


def make_pileup(sub_seq, sub_cigar, ccs_seq, window_widths=None):
  from deepconsensus_tpu.preprocess.spacing import space_out_reads

  def read(seq, cig, name):
    bases = np.array([constants.SEQ_VOCAB.index(c) for c in seq], np.uint8)
    cigar = np.array(cig, np.uint8)
    is_ref = np.array([op != I for op in cig])
    ccs_idx = np.where(is_ref, np.cumsum(is_ref) - 1, -1).astype(np.int64)
    return AlignedRead(
        name=name, bases=bases, cigar=cigar,
        pw=np.ones(len(seq), np.int32), ip=np.ones(len(seq), np.int32),
        sn=np.ones(4, np.float32), strand=constants.Strand.FORWARD,
        ccs_idx=ccs_idx,
        base_quality_scores=np.full(len(seq), 30, np.int64)
        if name.endswith('ccs') else np.empty(0, np.int64),
    )

  reads = [
      read(sub_seq, sub_cigar, 'm/1/0_10'),
      read(ccs_seq, [M] * len(ccs_seq), 'm/1/ccs'),
  ]
  spaced = space_out_reads(reads)
  return Pileup(
      name='m/1/ccs', reads=spaced, layout=FeatureLayout(2, 4),
      window_widths=window_widths,
  )


def test_standard_windows():
  p = make_pileup('ACGTACGT', [M] * 8, 'ACGTACGT')
  assert p.calculate_windows(4) == [4, 4]
  p2 = make_pileup('ACGTAC', [M] * 6, 'ACGTAC')
  assert p2.calculate_windows(4) == [4, 4]


def test_smart_windows_translate_spacing():
  # Subread insertion after base 1 creates a gap column in the CCS, so
  # a 2-base smart window spans 3 columns.
  p = make_pileup(
      'ATCGT', [M, I, M, M, M], 'ACGT',
      window_widths=np.array([2, 2]),
  )
  assert str(p.ccs) == 'A CGT'
  assert p.calculate_windows(100) == [3, 2]


def test_smart_windows_width_mismatch_raises():
  p = make_pileup('ACGT', [M] * 4, 'ACGT', window_widths=np.array([2, 1]))
  with pytest.raises(ValueError):
    p.calculate_windows(100)


def test_diff_and_kmers():
  truth = 'ACGTACGT'
  pred = 'ACCTACGA'
  diffs = analysis.diff_strings(truth, pred)
  assert diffs == [(2, 'G', 'C'), (7, 'T', 'A')]
  view = analysis.format_diff(truth, pred)
  assert '^' in view and 'truth' in view
  kmers = analysis.error_kmers(truth, pred, k=3)
  assert kmers['CGT'] == 1  # context around position 2
  top = analysis.summarize_errors([(truth, pred)], k=3, top=5)
  assert len(top) >= 1


def test_get_prediction_shapes():
  import jax
  import jax.numpy as jnp

  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = 1
    params.filter_size = 32
  model = model_lib.get_model(params)
  rows = np.zeros((params.total_rows, 100, 1), np.float32)
  variables = model.init(jax.random.PRNGKey(0), jnp.asarray(rows[None]))
  out = analysis.get_prediction(model.apply, variables, rows)
  assert len(out['sequence']) == 100
  assert out['quality_scores'].shape == (100,)
  assert out['probabilities'].shape == (100, 5)


def test_edit_distance_matches_naive():
  """Vectorized Levenshtein vs a naive DP, incl. the reference doc
  examples and gap stripping (model_inference_transforms.py:35-69)."""
  import numpy as np

  from deepconsensus_tpu.utils import analysis

  def naive(s1, s2):
    s1 = s1.replace(' ', '')
    s2 = s2.replace(' ', '')
    dp = list(range(len(s2) + 1))
    for i, c1 in enumerate(s1):
      ndp = [i + 1]
      for j, c2 in enumerate(s2):
        ndp.append(min(dp[j] + (c1 != c2), dp[j + 1] + 1, ndp[-1] + 1))
      dp = ndp
    return dp[-1]

  assert analysis.edit_distance('CAT', 'BAT') == 1
  assert analysis.edit_distance('CAT', 'BATS') == 2
  assert analysis.edit_distance('C AT', 'BA TS') == 2  # gaps stripped
  assert analysis.edit_distance('', 'ACGT') == 4

  rng = np.random.default_rng(0)
  bases = 'ACGT '
  for _ in range(50):
    s1 = ''.join(rng.choice(list(bases), size=rng.integers(0, 12)))
    s2 = ''.join(rng.choice(list(bases), size=rng.integers(0, 12)))
    assert analysis.edit_distance(s1, s2) == naive(s1, s2), (s1, s2)


def test_homopolymer_content():
  from deepconsensus_tpu.utils import analysis

  assert analysis.homopolymer_content('') == 0.0
  assert analysis.homopolymer_content('ACGT') == 0.0
  assert analysis.homopolymer_content('AAAT') == 0.75
  assert analysis.homopolymer_content('AAATTT') == 1.0
  assert analysis.homopolymer_content('AA TTT') == 0.6  # gaps stripped


def test_error_analysis_walkthrough(tmp_path, testdata_dir,
                                    scripts_importable):
  """The notebook-style driver runs end to end on bundled eval data
  and emits a well-formed JSON report."""
  import json

  from scripts import error_analysis

  report = str(tmp_path / 'report.json')
  rc = error_analysis.main([
      '--examples', str(testdata_dir / 'human_1m/tf_examples/eval/*'),
      '--limit', '8', '--worst', '1', '--json', report, '--cpu',
  ])
  assert rc == 0
  with open(report) as f:
    saved = json.load(f)
  assert saved['summary']['n_windows'] == 8
  assert len(saved['per_window']) == 8
  for w in saved['per_window']:
    assert 0.0 <= w['identity'] <= 1.0
    assert w['edit_distance'] >= 0


def test_eval_polished_vs_truth_scoring(tmp_path, testdata_dir,
                                        scripts_importable):
  """The read-level truth scorer: a FASTQ that echoes each ZMW's truth
  sequence must score identity 1.0 and beat (or tie) the CCS read.
  (The bundled truth BAM has primaries only, so the script's
  supplementary-record guard is not exercised here.)"""
  import json

  from scripts import eval_polished_vs_truth

  from deepconsensus_tpu.io import bam as bam_lib

  truth_bam = str(testdata_dir / 'human_1m/truth_to_ccs.bam')
  ccs_bam = str(testdata_dir / 'human_1m/ccs.bam')
  truths = {}
  for rec in bam_lib.BamReader(truth_bam):
    if rec.is_supplementary or rec.is_secondary:
      continue
    if rec.reference_name and rec.seq and rec.reference_name not in truths:
      truths[rec.reference_name] = rec.seq
  names = sorted(truths)[:2]
  fastq = tmp_path / 'perfect.fastq'
  with open(fastq, 'w') as f:
    for name in names:
      seq = truths[name]
      f.write(f'@{name}\n{seq}\n+\n{"I" * len(seq)}\n')

  report = str(tmp_path / 'report.json')
  yield_csv = str(tmp_path / 'yield.csv')
  rc = eval_polished_vs_truth.main([
      '--polished', str(fastq), '--ccs_bam', ccs_bam,
      '--truth_to_ccs', truth_bam, '--json', report,
      '--yield_csv', yield_csv,
  ])
  assert rc == 0
  with open(report) as f:
    saved = json.load(f)
  assert saved['summary']['n_reads'] == len(names)
  for row in saved['per_read']:
    assert row['identity_polished'] == 1.0
    assert row['qv_polished'] >= row['qv_ccs']
    assert row['mean_pred_q'] == 40.0  # 'I' = Q40

  # yield@emQ table (the reference's Q-filter + identity>=0.999 bar):
  # echo-the-truth reads at Q40 pass every threshold with full bases;
  # the CCS baseline rows exist for the at-equal-yield comparison.
  import csv

  with open(yield_csv) as f:
    yrows = list(csv.DictReader(f))
  total = sum(len(truths[n]) for n in names)
  pol = {int(r['quality_threshold']): r for r in yrows
         if r['reads'] == 'polished'}
  assert set(pol) == {20, 30, 40}
  for q, row in pol.items():
    assert int(row['num_reads']) == len(names)
    assert int(row['yield_bases']) == total
    assert float(row['mean_identity']) == 1.0
  assert any(r['reads'] == 'ccs' for r in yrows)
