"""End-to-end inference pipeline tests on the bundled human_1m BAMs."""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.calibration import lib as calibration_lib
from deepconsensus_tpu.inference import runner as runner_lib
from deepconsensus_tpu.io import fastx
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib


def tiny_model():
  """Shared small model recipe for the e2e inference tests."""
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params, is_training=False)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = 1
    params.filter_size = 64
  model = model_lib.get_model(params)
  rows = jnp.zeros((1, params.total_rows, params.max_length, 1))
  variables = model.init(jax.random.PRNGKey(0), rows)
  return params, variables



@pytest.fixture(scope='module')
def small_runner():
  params, variables = tiny_model()
  options = runner_lib.InferenceOptions(batch_size=32, batch_zmws=4, limit=3)
  return runner_lib.ModelRunner(params, variables, options), options


def test_run_inference_end_to_end(testdata_dir, tmp_path, small_runner):
  runner, options = small_runner
  out = str(tmp_path / 'out.fastq')
  counters = runner_lib.run_inference(
      subreads_to_ccs=str(testdata_dir / 'human_1m/subreads_to_ccs.bam'),
      ccs_bam=str(testdata_dir / 'human_1m/ccs.bam'),
      checkpoint=None,
      output=out,
      options=options,
      runner=runner,
  )
  assert counters['n_zmw_pass'] == 3
  # With an untrained model most reads fail the q20 filter, but the
  # pipeline must produce its sidecar outputs and consistent counts.
  assert os.path.exists(out + '.runtime.csv')
  assert os.path.exists(out + '.inference.json')
  with open(out + '.inference.json') as f:
    saved = json.load(f)
  assert saved['n_zmw_pass'] == 3
  total_outcomes = (
      saved['success'] + saved['empty_sequence'] + saved['only_gaps']
      + saved['failed_quality_filter'] + saved['failed_length_filter']
  )
  assert total_outcomes == 3


def test_skip_windows_adopt_ccs(testdata_dir, tmp_path, small_runner):
  """With skip_windows_above=1 every window adopts the CCS sequence, so
  outputs equal the draft CCS reads (quality-filtered)."""
  runner, _ = small_runner
  options = runner_lib.InferenceOptions(
      batch_size=32, batch_zmws=4, limit=2, skip_windows_above=1,
      min_quality=0,
  )
  out = str(tmp_path / 'ccs_passthrough.fastq')
  counters = runner_lib.run_inference(
      subreads_to_ccs=str(testdata_dir / 'human_1m/subreads_to_ccs.bam'),
      ccs_bam=str(testdata_dir / 'human_1m/ccs.bam'),
      checkpoint=None,
      output=out,
      options=options,
      runner=runner,
  )
  assert counters.get('n_windows_to_model', 0) == 0
  assert counters['n_windows_quality_skipped'] > 0
  reads = list(fastx.read_fastq(out))
  assert len(reads) == counters['success'] > 0

  # Compare against the raw CCS bases for those molecules.
  from deepconsensus_tpu.io import bam as bam_lib

  ccs_by_name = {}
  for rec in bam_lib.BamReader(str(testdata_dir / 'human_1m/ccs.bam')):
    ccs_by_name[rec.qname] = rec.seq
  for name, seq, qual in reads:
    assert name in ccs_by_name
    # Windows only cover CCS coordinates present in subread alignments,
    # so the stitched read is a prefix-slice of the CCS draft.
    assert seq in ccs_by_name[name]
    assert len(seq) == len(qual)


def test_compact_dispatch_lossless_with_ccs_bq():
  """Compact uint8 transport must preserve ccs_bq -1 sentinels (gap
  columns / padded tails) instead of wrapping them to 255 (ADVICE r2)."""
  params = config_lib.get_config('transformer_learn_values+test_bq')
  config_lib.finalize_params(params, is_training=False)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = 1
    params.filter_size = 64
  model = model_lib.get_model(params)
  mp, n_rows, length = params.max_passes, params.total_rows, params.max_length
  rng = np.random.default_rng(0)
  batch = 8
  rows = np.zeros((batch, n_rows, length, 1), np.float32)
  rows[:, :mp] = rng.integers(0, 5, (batch, mp, length, 1))
  rows[:, mp:2 * mp] = rng.integers(0, 256, (batch, mp, length, 1))
  rows[:, 2 * mp:3 * mp] = rng.integers(0, 256, (batch, mp, length, 1))
  rows[:, 3 * mp:4 * mp] = rng.integers(0, 3, (batch, mp, length, 1))
  rows[:, 4 * mp] = rng.integers(0, 5, (batch, length, 1))
  bq = rng.integers(-1, 94, (batch, length, 1)).astype(np.float32)
  bq[:, length // 2:] = -1.0  # padded-tail sentinels
  rows[:, 4 * mp + 1] = bq
  rows[:, -4:] = rng.uniform(0, 20, (batch, 4, 1, 1)).astype(np.float32)
  variables = model.init(
      jax.random.PRNGKey(0), jnp.zeros((1, n_rows, length, 1)))
  # Host output plane: raw max_prob is the observable that makes a
  # transport bit-flip visible at full float precision (the device
  # epilogue's uint8 planes are covered by test_device_epilogue.py).
  options = runner_lib.InferenceOptions(batch_size=batch,
                                        device_epilogue=False)
  runner = runner_lib.ModelRunner(params, variables, options)

  pred_ids, max_prob, n = runner.raw_outputs(runner.dispatch(rows))
  direct = model.apply(variables, jnp.asarray(rows))
  np.testing.assert_array_equal(
      np.asarray(pred_ids[:n]), np.asarray(jnp.argmax(direct, axis=-1)))
  np.testing.assert_allclose(
      np.asarray(max_prob[:n]), np.asarray(jnp.max(direct, axis=-1)),
      rtol=1e-5)


def test_preprocess_driver_matches_feeder(testdata_dir, tmp_path):
  from deepconsensus_tpu.preprocess.driver import run_preprocess
  from deepconsensus_tpu.io import tfrecord
  from deepconsensus_tpu.io.example_proto import Example

  td = str(testdata_dir / 'human_1m')
  out = str(tmp_path / 'examples' / '@split' / '@split.tfrecord.gz')
  summary = run_preprocess(
      subreads_to_ccs=f'{td}/subreads_to_ccs.bam',
      ccs_bam=f'{td}/ccs.bam',
      output=out,
      ins_trim=5,
      truth_bed=f'{td}/truth.bed',
      truth_to_ccs=f'{td}/truth_to_ccs.bam',
      truth_split=f'{td}/truth_split.tsv',
      limit=3,
  )
  assert summary['n_zmw_pass'] == 3
  n = 0
  for split in ('train', 'eval', 'test'):
    path = out.replace('@split', split)
    for raw in tfrecord.read_tfrecords(path):
      ex = Example.parse(raw)
      assert ex['subreads/shape'] == [85, 100, 1]
      n += 1
  assert n == summary['n_examples']


def test_preprocess_driver_multiprocess_equivalence(testdata_dir, tmp_path):
  from deepconsensus_tpu.preprocess.driver import run_preprocess
  from deepconsensus_tpu.io import tfrecord

  td = str(testdata_dir / 'human_1m')
  out_serial = str(tmp_path / 'serial' / '@split.tfrecord.gz')
  out_mp = str(tmp_path / 'mp' / '@split.tfrecord.gz')
  kwargs = dict(
      subreads_to_ccs=f'{td}/subreads_to_ccs.bam',
      ccs_bam=f'{td}/ccs.bam',
      ins_trim=5,
      truth_bed=f'{td}/truth.bed',
      truth_to_ccs=f'{td}/truth_to_ccs.bam',
      truth_split=f'{td}/truth_split.tsv',
      limit=4,
  )
  s1 = run_preprocess(output=out_serial, cpus=0, **kwargs)
  s2 = run_preprocess(output=out_mp, cpus=2, **kwargs)
  assert s1['n_examples'] == s2['n_examples']
  for split in ('train', 'eval', 'test'):
    a = list(tfrecord.read_tfrecords(out_serial.replace('@split', split)))
    b = list(tfrecord.read_tfrecords(out_mp.replace('@split', split)))
    assert a == b  # imap preserves order -> byte-identical shards


def _run_single_vs_mesh(testdata_dir, tmp_path, make_run_kwargs):
  """Runs the full pipeline single-device and on the 8-device DP mesh
  and asserts byte-identical FASTQ. make_run_kwargs(options, mesh) ->
  dict supplying the model source (runner= or checkpoint=[+mesh=])."""
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  outputs = {}
  for name, mesh in (
      ('single', None),
      ('mesh', mesh_lib.make_mesh(dp=8, tp=1)),
  ):
    options = runner_lib.InferenceOptions(
        batch_size=32, batch_zmws=4, limit=3, min_quality=0
    )
    out = str(tmp_path / f'{name}.fastq')
    counters = runner_lib.run_inference(
        subreads_to_ccs=str(testdata_dir / 'human_1m/subreads_to_ccs.bam'),
        ccs_bam=str(testdata_dir / 'human_1m/ccs.bam'),
        output=out,
        options=options,
        **make_run_kwargs(options, mesh),
    )
    assert counters['n_zmw_pass'] == 3
    with open(out, 'rb') as f:
      outputs[name] = f.read()
  assert outputs['single'], 'empty FASTQ output'
  assert outputs['single'] == outputs['mesh']


def test_mesh_inference_matches_single_device(testdata_dir, tmp_path):
  """DP-mesh inference produces byte-identical FASTQ to single-device
  (VERDICT r1 #4: window batch sharded over the mesh data axis)."""
  params, variables = tiny_model()
  _run_single_vs_mesh(
      testdata_dir, tmp_path,
      lambda options, mesh: {
          'checkpoint': None,
          'runner': runner_lib.ModelRunner(
              params, variables, options, mesh=mesh),
      })


def test_exported_artifact_mesh_inference_e2e(testdata_dir, tmp_path):
  """The full run_inference pipeline (BAM -> featurize -> model ->
  stitch -> FASTQ) serving an exported StableHLO artifact over a DP
  mesh — the from_checkpoint auto-detect + shard_map serving path the
  CLI's `--checkpoint <export_dir> --dp N` takes — byte-matches the
  single-device artifact run."""
  from deepconsensus_tpu.models import export as export_lib

  params, variables = tiny_model()
  export_dir = str(tmp_path / 'export')
  # checkpoint_path is unused when variables= and params= are given.
  export_lib.export_model(
      checkpoint_path=export_dir, out_dir=export_dir, batch_size=32,
      variables=variables, params=params)
  _run_single_vs_mesh(
      testdata_dir, tmp_path,
      lambda options, mesh: {'checkpoint': export_dir, 'mesh': mesh})


def test_mesh_batch_divisibility_guard():
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params, is_training=False)
  options = runner_lib.InferenceOptions(batch_size=30)
  mesh = mesh_lib.make_mesh(dp=8, tp=1)
  with pytest.raises(ValueError, match='not divisible'):
    runner_lib.ModelRunner(params, {}, options, mesh=mesh)


def test_tp_mesh_inference_matches_single_device(testdata_dir, tmp_path):
  """dp x tp inference: weights shard on the model axis, outputs stay
  byte-identical to single-device."""
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  params, variables = tiny_model()

  mesh = mesh_lib.make_mesh(dp=4, tp=2)
  shardings = mesh_lib.param_shardings(mesh, variables['params'])
  assert mesh_lib.count_model_sharded(shardings) > 0

  outputs = {}
  for name, m in (('single', None), ('tp', mesh)):
    options = runner_lib.InferenceOptions(
        batch_size=32, batch_zmws=4, limit=2, min_quality=0
    )
    runner = runner_lib.ModelRunner(params, variables, options, mesh=m)
    out = str(tmp_path / f'{name}.fastq')
    runner_lib.run_inference(
        subreads_to_ccs=str(testdata_dir / 'human_1m/subreads_to_ccs.bam'),
        ccs_bam=str(testdata_dir / 'human_1m/ccs.bam'),
        checkpoint=None,
        output=out,
        options=options,
        runner=runner,
    )
    with open(out, 'rb') as f:
      outputs[name] = f.read()
  assert outputs['single'] and outputs['single'] == outputs['tp']


def test_sharded_inference_partitions_zmws(testdata_dir, tmp_path):
  """shard=(i,n) runs partition the ZMW set exactly: the union of all
  shards' FASTQ reads equals the unsharded run's reads."""
  params, variables = tiny_model()

  def reads_of(path):
    return {name: seq for name, seq, _ in fastx.read_fastq(path)}

  def run(name, shard):
    options = runner_lib.InferenceOptions(
        batch_size=32, batch_zmws=4, min_quality=0,
        skip_windows_above=1, shard=shard,
    )
    runner = runner_lib.ModelRunner(params, variables, options)
    out = str(tmp_path / f'{name}.fastq')
    counters = runner_lib.run_inference(
        subreads_to_ccs=str(testdata_dir / 'human_1m/subreads_to_ccs.bam'),
        ccs_bam=str(testdata_dir / 'human_1m/ccs.bam'),
        checkpoint=None,
        output=out,
        options=options,
        runner=runner,
    )
    return reads_of(out), counters

  full, _ = run('full', None)
  shard0, c0 = run('s0', (0, 2))
  shard1, c1 = run('s1', (1, 2))
  assert c0['n_zmw_sharded_out'] > 0 and c1['n_zmw_sharded_out'] > 0
  assert not set(shard0) & set(shard1)
  merged = {**shard0, **shard1}
  assert merged == full


def test_preprocess_shard_partitions_examples(testdata_dir, tmp_path):
  """Preprocess shards partition the example set exactly."""
  from deepconsensus_tpu.io import tfrecord
  from deepconsensus_tpu.preprocess.driver import run_preprocess

  td = str(testdata_dir / 'human_1m')

  def run(name, shard):
    out = str(tmp_path / name / 'inference.tfrecord.gz')
    summary = run_preprocess(
        subreads_to_ccs=f'{td}/subreads_to_ccs.bam',
        ccs_bam=f'{td}/ccs.bam',
        output=out,
        ins_trim=5,
        shard=shard,
    )
    records = set()
    for raw in tfrecord.read_tfrecords(out):
      records.add(raw)
    return records, summary

  full, _ = run('full', None)
  s0, sum0 = run('s0', (0, 2))
  s1, sum1 = run('s1', (1, 2))
  assert sum0['n_zmw_sharded_out'] > 0 and sum1['n_zmw_sharded_out'] > 0
  assert not s0 & s1
  assert (s0 | s1) == full
