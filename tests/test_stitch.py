from deepconsensus_tpu.postprocess import stitch


def make_output(pos, seq, qual_char='I'):
  return stitch.DCModelOutput(
      molecule_name='m/1/ccs',
      window_pos=pos,
      sequence=seq,
      quality_string=qual_char * len(seq),
  )


def test_stitch_simple():
  outs = [make_output(0, 'ACGT'), make_output(4, 'TTGG')]
  counter = stitch.OutcomeCounter()
  fastq = stitch.stitch_to_fastq('m/1/ccs', outs, 4, 0, 0, counter)
  assert fastq == '@m/1/ccs\nACGTTTGG\n+\nIIIIIIII\n'
  assert counter.success == 1


def test_stitch_removes_gaps():
  outs = [make_output(0, 'AC T')]
  counter = stitch.OutcomeCounter()
  fastq = stitch.stitch_to_fastq('m/1/ccs', outs, 4, 0, 0, counter)
  assert fastq.splitlines()[1] == 'ACT'
  assert len(fastq.splitlines()[3]) == 3


def test_stitch_missing_window_fails():
  outs = [make_output(4, 'TTGG')]  # window 0 missing
  counter = stitch.OutcomeCounter()
  fastq = stitch.stitch_to_fastq('m/1/ccs', outs, 4, 0, 0, counter)
  assert fastq is None
  assert counter.empty_sequence == 1


def test_quality_filter():
  outs = [make_output(0, 'ACGT', qual_char='+')]  # q10
  counter = stitch.OutcomeCounter()
  assert stitch.stitch_to_fastq('m/1/ccs', outs, 4, 20, 0, counter) is None
  assert counter.failed_quality_filter == 1
  # Threshold exactly at the read quality passes (rounding guard).
  counter = stitch.OutcomeCounter()
  assert stitch.stitch_to_fastq(
      'm/1/ccs', [make_output(0, 'ACGT', qual_char='+')], 4, 10, 0, counter
  ) is not None


def test_length_filter():
  outs = [make_output(0, 'AC  ')]
  counter = stitch.OutcomeCounter()
  assert stitch.stitch_to_fastq('m/1/ccs', outs, 4, 0, 5, counter) is None
  assert counter.failed_length_filter == 1


def test_only_gaps():
  outs = [make_output(0, '    ')]
  counter = stitch.OutcomeCounter()
  assert stitch.stitch_to_fastq('m/1/ccs', outs, 4, 0, 0, counter) is None
  assert counter.only_gaps == 1


def test_calibration_lib():
  import numpy as np
  from deepconsensus_tpu.calibration import lib

  cv = lib.parse_calibration_string('skip')
  assert not cv.enabled
  cv = lib.parse_calibration_string('10,0.9,1.5')
  assert cv.enabled and cv.threshold == 10 and cv.w == 0.9 and cv.b == 1.5
  scores = np.array([5.0, 20.0])
  out = lib.calibrate_quality_scores(scores, cv)
  np.testing.assert_allclose(out, [5.0, 20 * 0.9 + 1.5])
  cv0 = lib.parse_calibration_string('0,2.0,1.0')
  np.testing.assert_allclose(
      lib.calibrate_quality_scores(scores, cv0), scores * 2 + 1
  )


def test_stitch_fill_n_pads_missing_window():
  """fill_n=True replaces a knocked-out window with Ns at EMPTY_QUAL
  (reference stitch_utils_test: test_get_partial_sequences)."""
  from deepconsensus_tpu import constants
  from deepconsensus_tpu.utils import phred

  outs = [make_output(0, 'ACGT'), make_output(8, 'TTGG')]  # window 4-8 gone
  seq, qual = stitch.get_full_sequence(outs, max_length=4, fill_n=True)
  assert seq == 'ACGT' + 'NNNN' + 'TTGG'
  empty = phred.quality_scores_to_string([constants.EMPTY_QUAL] * 4)
  assert qual == 'IIII' + empty + 'IIII'


def test_stitch_fill_n_false_fails():
  outs = [make_output(0, 'ACGT'), make_output(8, 'TTGG')]
  seq, qual = stitch.get_full_sequence(outs, max_length=4, fill_n=False)
  assert seq is None
