from deepconsensus_tpu.postprocess import stitch


def make_output(pos, seq, qual_char='I'):
  return stitch.DCModelOutput(
      molecule_name='m/1/ccs',
      window_pos=pos,
      sequence=seq,
      quality_string=qual_char * len(seq),
  )


def test_stitch_simple():
  outs = [make_output(0, 'ACGT'), make_output(4, 'TTGG')]
  counter = stitch.OutcomeCounter()
  fastq = stitch.stitch_to_fastq('m/1/ccs', outs, 4, 0, 0, counter)
  assert fastq == '@m/1/ccs\nACGTTTGG\n+\nIIIIIIII\n'
  assert counter.success == 1


def test_stitch_removes_gaps():
  outs = [make_output(0, 'AC T')]
  counter = stitch.OutcomeCounter()
  fastq = stitch.stitch_to_fastq('m/1/ccs', outs, 4, 0, 0, counter)
  assert fastq.splitlines()[1] == 'ACT'
  assert len(fastq.splitlines()[3]) == 3


def test_stitch_missing_window_fails():
  outs = [make_output(4, 'TTGG')]  # window 0 missing
  counter = stitch.OutcomeCounter()
  fastq = stitch.stitch_to_fastq('m/1/ccs', outs, 4, 0, 0, counter)
  assert fastq is None
  assert counter.empty_sequence == 1


def test_quality_filter():
  outs = [make_output(0, 'ACGT', qual_char='+')]  # q10
  counter = stitch.OutcomeCounter()
  assert stitch.stitch_to_fastq('m/1/ccs', outs, 4, 20, 0, counter) is None
  assert counter.failed_quality_filter == 1
  # Threshold exactly at the read quality passes (rounding guard).
  counter = stitch.OutcomeCounter()
  assert stitch.stitch_to_fastq(
      'm/1/ccs', [make_output(0, 'ACGT', qual_char='+')], 4, 10, 0, counter
  ) is not None


def test_length_filter():
  outs = [make_output(0, 'AC  ')]
  counter = stitch.OutcomeCounter()
  assert stitch.stitch_to_fastq('m/1/ccs', outs, 4, 0, 5, counter) is None
  assert counter.failed_length_filter == 1


def test_only_gaps():
  outs = [make_output(0, '    ')]
  counter = stitch.OutcomeCounter()
  assert stitch.stitch_to_fastq('m/1/ccs', outs, 4, 0, 0, counter) is None
  assert counter.only_gaps == 1


def test_calibration_lib():
  import numpy as np
  from deepconsensus_tpu.calibration import lib

  cv = lib.parse_calibration_string('skip')
  assert not cv.enabled
  cv = lib.parse_calibration_string('10,0.9,1.5')
  assert cv.enabled and cv.threshold == 10 and cv.w == 0.9 and cv.b == 1.5
  scores = np.array([5.0, 20.0])
  out = lib.calibrate_quality_scores(scores, cv)
  np.testing.assert_allclose(out, [5.0, 20 * 0.9 + 1.5])
  cv0 = lib.parse_calibration_string('0,2.0,1.0')
  np.testing.assert_allclose(
      lib.calibrate_quality_scores(scores, cv0), scores * 2 + 1
  )


def test_stitch_fill_n_pads_missing_window():
  """fill_n=True replaces a knocked-out window with Ns at EMPTY_QUAL
  (reference stitch_utils_test: test_get_partial_sequences)."""
  from deepconsensus_tpu import constants
  from deepconsensus_tpu.utils import phred

  outs = [make_output(0, 'ACGT'), make_output(8, 'TTGG')]  # window 4-8 gone
  seq, qual = stitch.get_full_sequence(outs, max_length=4, fill_n=True)
  assert seq == 'ACGT' + 'NNNN' + 'TTGG'
  empty = phred.quality_scores_to_string([constants.EMPTY_QUAL] * 4)
  assert qual == 'IIII' + empty + 'IIII'


def test_stitch_fill_n_false_fails():
  outs = [make_output(0, 'ACGT'), make_output(8, 'TTGG')]
  seq, qual = stitch.get_full_sequence(outs, max_length=4, fill_n=False)
  assert seq is None


# ----------------------------------------------------------------------
# stitch_arrays ragged rows (bucketed variable-length windows)


def _arr_windows(widths, base=1):
  """Per-window (pos, ids, quals) with distinct id values per window."""
  import numpy as np

  pos, ids, quals = [], [], []
  start = 0
  for k, w in enumerate(widths):
    pos.append(start)
    ids.append(np.full(w, base + (k % 4), dtype=np.uint8))
    quals.append(np.full(w, 30 + k, dtype=np.uint8))
    start += w
  return np.asarray(pos, dtype=np.int64), ids, quals


def test_stitch_arrays_ragged_matches_uniform():
  """A list of equal-length 1-D windows must produce byte-identical
  output to the stacked 2-D path (the fixed-shape byte-identity
  contract the ragged generalization preserves)."""
  import numpy as np

  pos, ids, quals = _arr_windows([4, 4, 4])
  c1, c2 = stitch.OutcomeCounter(), stitch.OutcomeCounter()
  uniform = stitch.stitch_arrays(
      'm/1/ccs', pos, np.stack(ids), np.stack(quals),
      max_length=4, min_quality=0, min_length=0, outcome_counter=c1)
  ragged = stitch.stitch_arrays(
      'm/1/ccs', pos, ids, quals,
      max_length=4, min_quality=0, min_length=0, outcome_counter=c2)
  assert uniform[0] == ragged[0]
  np.testing.assert_array_equal(uniform[1], ragged[1])
  assert c1.success == c2.success == 1


def test_stitch_arrays_mixed_widths():
  """Windows of different bucket widths concatenate in position order;
  output length is the sum of the per-window lengths."""
  import numpy as np

  pos, ids, quals = _arr_windows([4, 8, 4])
  counter = stitch.OutcomeCounter()
  seq, q = stitch.stitch_arrays(
      'm/1/ccs', pos, ids, quals,
      max_length=4, min_quality=0, min_length=0, outcome_counter=counter)
  assert len(seq) == 16 and len(q) == 16
  # Position order survives even when windows arrive shuffled.
  shuffle = [2, 0, 1]
  counter2 = stitch.OutcomeCounter()
  seq2, q2 = stitch.stitch_arrays(
      'm/1/ccs', pos[shuffle], [ids[i] for i in shuffle],
      [quals[i] for i in shuffle],
      max_length=4, min_quality=0, min_length=0, outcome_counter=counter2)
  assert seq2 == seq
  np.testing.assert_array_equal(q2, q)


def test_stitch_arrays_ragged_missing_window_fails():
  """The missing-window rule generalizes to cumulative capacity: a
  window starting past the sum of the lengths before it fails the
  molecule (uniform rows degrade to the legacy k*max_length bound)."""
  import numpy as np

  pos, ids, quals = _arr_windows([4, 8, 4])
  # Drop the middle (8-wide) window: window at pos 12 > capacity 4.
  counter = stitch.OutcomeCounter()
  assert stitch.stitch_arrays(
      'm/1/ccs', pos[[0, 2]], [ids[0], ids[2]], [quals[0], quals[2]],
      max_length=4, min_quality=0, min_length=0,
      outcome_counter=counter) is None
  assert counter.empty_sequence == 1
  # An all-200-style uniform wide molecule is NOT falsely flagged: two
  # 8-wide windows at 0 and 8 pass even though max_length is 4.
  counter = stitch.OutcomeCounter()
  pos2, ids2, quals2 = _arr_windows([8, 8])
  seq, _ = stitch.stitch_arrays(
      'm/1/ccs', pos2, np.stack(ids2), np.stack(quals2),
      max_length=4, min_quality=0, min_length=0, outcome_counter=counter)
  assert len(seq) == 16
  assert counter.success == 1
