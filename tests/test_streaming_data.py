import itertools

import numpy as np

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib


def test_streaming_dataset(testdata_dir):
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  ds = data_lib.StreamingDataset(
      patterns=str(testdata_dir / 'human_1m/tf_examples/train/*'),
      params=params,
      batch_size=16,
      buffer_size=64,
  )
  batches = list(itertools.islice(iter(ds), 5))
  assert len(batches) == 5
  for batch in batches:
    assert batch['rows'].shape == (16, 85, 100, 1)
    assert batch['label'].shape == (16, 100)
  # Stream repeats past one epoch without exhausting (1239 examples).
  more = list(itertools.islice(iter(ds), 100))
  assert len(more) == 100


def test_streaming_dataset_workers_yield_real_examples(testdata_dir):
  """workers>0 moves shard reading + decode into processes; every
  streamed (rows, label) pair must still be a genuine dataset example
  (checked against the eagerly-loaded iterator's example set)."""
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  pattern = str(testdata_dir / 'human_1m/tf_examples/train/*')
  eager = data_lib.DatasetIterator(
      patterns=pattern, params=params, batch_size=4, shuffle=False,
  )
  known = {
      (r.tobytes(), l.tobytes())
      for r, l in zip(eager.rows, eager.labels)
  }
  ds = data_lib.StreamingDataset(
      patterns=pattern, params=params, batch_size=16, buffer_size=64,
      workers=2,
  )
  it = iter(ds)
  try:
    for batch in itertools.islice(it, 4):
      assert batch['rows'].shape == (16, 85, 100, 1)
      for row, label in zip(batch['rows'], batch['label']):
        assert (row.tobytes(), label.tobytes()) in known
  finally:
    it.close()


def test_left_shift_batched_matches_per_row():
  from deepconsensus_tpu.utils import phred

  rng = np.random.default_rng(3)
  batch = rng.integers(0, 5, size=(64, 100)).astype(np.float32)
  want = np.stack([phred.left_shift_seq(row) for row in batch])
  got = phred.left_shift(batch)
  np.testing.assert_array_equal(got, want)


def test_prefetch_iterator_matches_plain():
  from deepconsensus_tpu.models import data as data_lib

  items = [{'a': np.full((2, 2), i)} for i in range(7)]
  got = list(data_lib.prefetch_iterator(iter(items), depth=2))
  assert len(got) == 7
  for want, g in zip(items, got):
    np.testing.assert_array_equal(g['a'], want['a'])


def test_prefetch_iterator_propagates_errors():
  from deepconsensus_tpu.models import data as data_lib

  def bad():
    yield {'a': np.zeros(1)}
    raise RuntimeError('boom in producer')

  it = data_lib.prefetch_iterator(bad())
  next(it)
  import pytest as _pytest
  with _pytest.raises(RuntimeError, match='boom in producer'):
    next(it)


def test_prefetch_iterator_early_close_stops_producer():
  import threading

  from deepconsensus_tpu.models import data as data_lib

  produced = []

  def source():
    for i in range(10_000):
      produced.append(i)
      yield {'a': np.zeros(1)}

  it = data_lib.prefetch_iterator(source(), depth=2)
  next(it)
  it.close()
  n_after_close = len(produced)
  assert n_after_close < 50  # producer stopped, didn't drain 10k
  assert threading.active_count() < 20
