import itertools

import numpy as np

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib


def test_streaming_dataset(testdata_dir):
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  ds = data_lib.StreamingDataset(
      patterns=str(testdata_dir / 'human_1m/tf_examples/train/*'),
      params=params,
      batch_size=16,
      buffer_size=64,
  )
  batches = list(itertools.islice(iter(ds), 5))
  assert len(batches) == 5
  for batch in batches:
    assert batch['rows'].shape == (16, 85, 100, 1)
    assert batch['label'].shape == (16, 100)
  # Stream repeats past one epoch without exhausting (1239 examples).
  more = list(itertools.islice(iter(ds), 100))
  assert len(more) == 100


def test_streaming_dataset_workers_yield_real_examples(testdata_dir):
  """workers>0 moves shard reading + decode into processes; every
  streamed (rows, label) pair must still be a genuine dataset example
  (checked against the eagerly-loaded iterator's example set)."""
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  pattern = str(testdata_dir / 'human_1m/tf_examples/train/*')
  eager = data_lib.DatasetIterator(
      patterns=pattern, params=params, batch_size=4, shuffle=False,
  )
  known = {
      (r.tobytes(), l.tobytes())
      for r, l in zip(eager.rows, eager.labels)
  }
  ds = data_lib.StreamingDataset(
      patterns=pattern, params=params, batch_size=16, buffer_size=64,
      workers=2,
  )
  it = iter(ds)
  try:
    for batch in itertools.islice(it, 4):
      assert batch['rows'].shape == (16, 85, 100, 1)
      for row, label in zip(batch['rows'], batch['label']):
        assert (row.tobytes(), label.tobytes()) in known
  finally:
    it.close()


def test_left_shift_batched_matches_per_row():
  from deepconsensus_tpu.utils import phred

  rng = np.random.default_rng(3)
  batch = rng.integers(0, 5, size=(64, 100)).astype(np.float32)
  want = np.stack([phred.left_shift_seq(row) for row in batch])
  got = phred.left_shift(batch)
  np.testing.assert_array_equal(got, want)


def test_prefetch_iterator_matches_plain():
  from deepconsensus_tpu.models import data as data_lib

  items = [{'a': np.full((2, 2), i)} for i in range(7)]
  got = list(data_lib.prefetch_iterator(iter(items), depth=2))
  assert len(got) == 7
  for want, g in zip(items, got):
    np.testing.assert_array_equal(g['a'], want['a'])


def test_prefetch_iterator_propagates_errors():
  from deepconsensus_tpu.models import data as data_lib

  def bad():
    yield {'a': np.zeros(1)}
    raise RuntimeError('boom in producer')

  it = data_lib.prefetch_iterator(bad())
  next(it)
  import pytest as _pytest
  with _pytest.raises(RuntimeError, match='boom in producer'):
    next(it)


def test_prefetch_iterator_early_close_stops_producer():
  import threading

  from deepconsensus_tpu.models import data as data_lib

  produced = []

  def source():
    for i in range(10_000):
      produced.append(i)
      yield {'a': np.zeros(1)}

  it = data_lib.prefetch_iterator(source(), depth=2)
  next(it)
  it.close()
  n_after_close = len(produced)
  assert n_after_close < 50  # producer stopped, didn't drain 10k
  assert threading.active_count() < 20


def _split_shards(testdata_dir, tmp_path, n_shards, corrupt_index=None):
  """Re-shards the bundled train records into n_shards small shards;
  optionally corrupts one shard mid-file."""
  from deepconsensus_tpu.io.tfrecord import (TFRecordReader,
                                             TFRecordWriter)

  src = str(testdata_dir / 'human_1m/tf_examples/train/train.tfrecord.gz')
  records = list(TFRecordReader(src))
  paths = []
  for s in range(n_shards):
    path = str(tmp_path / f'shard-{s:02d}.tfrecord.gz')
    with TFRecordWriter(path, compression='BGZF') as w:
      for r in records[s::n_shards]:
        w.write(r)
    paths.append(path)
  if corrupt_index is not None:
    # Truncate rather than bit-flip: the shard payload is float tensors
    # (incompressible -> deflate stored blocks), where a single flipped
    # byte can decode "successfully" into corrupt data; truncation
    # breaks framing deterministically on every decode path.
    data = open(paths[corrupt_index], 'rb').read()
    with open(paths[corrupt_index], 'wb') as f:
      f.write(data[: int(len(data) * 0.7)])
  return paths, records


def test_streaming_workers_multishard_handoff_coverage(
    testdata_dir, tmp_path):
  """Workers split 6 shards 3 ways; the stream must cover EVERY shard
  (round-robin assignment leaves no shard unread) and yield only
  genuine examples (VERDICT r4 #8: worker-scaling correctness)."""
  paths, records = _split_shards(testdata_dir, tmp_path, n_shards=6)
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  eager = data_lib.DatasetIterator(
      patterns=str(tmp_path / 'shard-*.tfrecord.gz'), params=params,
      batch_size=4, shuffle=False,
  )
  known = {
      (r.tobytes(), l.tobytes())
      for r, l in zip(eager.rows, eager.labels)
  }
  per_shard = {
      s: {
          (r.tobytes(), l.tobytes())
          for r, l in zip(eager.rows[s::6], eager.labels[s::6])
      }
      for s in range(6)
  }
  ds = data_lib.StreamingDataset(
      patterns=str(tmp_path / 'shard-*.tfrecord.gz'), params=params,
      batch_size=64, buffer_size=256, workers=3, seed=3,
  )
  seen = set()
  it = iter(ds)
  try:
    # > one epoch of records so every shard must have contributed.
    for batch in itertools.islice(it, 2 * len(records) // 64 + 2):
      for row, label in zip(batch['rows'], batch['label']):
        key = (row.tobytes(), label.tobytes())
        assert key in known
        seen.add(key)
  finally:
    it.close()
  for s, shard_keys in per_shard.items():
    assert seen & shard_keys, f'shard {s} never contributed'


def test_streaming_workers_corrupt_shard_fails_loudly(
    testdata_dir, tmp_path):
  """A corrupt shard inside a WORKER process must fail iteration (the
  worker dies, the parent's liveness check raises) — never silently
  shrink the dataset (VERDICT r4 #8: corrupt-shard propagation under
  load)."""
  import pytest

  paths, records = _split_shards(testdata_dir, tmp_path, n_shards=4,
                                 corrupt_index=2)
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  ds = data_lib.StreamingDataset(
      patterns=str(tmp_path / 'shard-*.tfrecord.gz'), params=params,
      batch_size=32, buffer_size=64, workers=2, seed=0,
  )
  it = iter(ds)
  try:
    with pytest.raises(Exception) as exc_info:
      # Both workers must hit their corrupt shard within a few epochs
      # of drain; the buffer can hide the crash for a while but not
      # forever.
      for _ in itertools.islice(it, 400):
        pass
    assert exc_info.type is not StopIteration
  finally:
    it.close()


def test_streaming_workers_teardown_is_deterministic(
    testdata_dir, tmp_path):
  """close() must not return while worker processes are still running
  (round-4 review: lingering workers skewed subsequent benchmark legs
  on the 1-core host)."""
  import multiprocessing
  import time

  _split_shards(testdata_dir, tmp_path, n_shards=2)
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  ds = data_lib.StreamingDataset(
      patterns=str(tmp_path / 'shard-*.tfrecord.gz'), params=params,
      batch_size=16, buffer_size=32, workers=2, seed=1,
  )
  it = iter(ds)
  next(it)  # workers are up and feeding
  assert multiprocessing.active_children()
  t0 = time.perf_counter()
  it.close()
  dt = time.perf_counter() - t0
  assert dt < 15, f'close() took {dt:.1f}s'
  deadline = time.time() + 5
  while multiprocessing.active_children() and time.time() < deadline:
    time.sleep(0.1)
  assert not multiprocessing.active_children(), 'workers outlived close()'
