import itertools

import numpy as np

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib


def test_streaming_dataset(testdata_dir):
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  ds = data_lib.StreamingDataset(
      patterns=str(testdata_dir / 'human_1m/tf_examples/train/*'),
      params=params,
      batch_size=16,
      buffer_size=64,
  )
  batches = list(itertools.islice(iter(ds), 5))
  assert len(batches) == 5
  for batch in batches:
    assert batch['rows'].shape == (16, 85, 100, 1)
    assert batch['label'].shape == (16, 100)
  # Stream repeats past one epoch without exhausting (1239 examples).
  more = list(itertools.islice(iter(ds), 100))
  assert len(more) == 100
