"""Single-pack-stream ragged engine: packing, byte identity, residency.

The use_ragged_kernel path replaces the per-bucket _WindowPacker fleet
with ONE _RaggedPacker feeding ONE compiled forward
(ModelRunner.dispatch_ragged). Three contracts under test:

  * packing mechanics — exact-fill cuts, largest-first placement over
    the bucket divisibility chain, end-of-input-only partial packs, no
    starvation flush, dp round-up of the slot batch;
  * byte identity — mixed-width streams produce (ids, quals) identical
    to the bucketed multi-packer path, at dp 1 and dp 8, with
    n_forward_shapes collapsed to 1;
  * residency — the traced pack loop's device_compute gaps are
    attributable to transfers, asserted through `dctpu trace --json`.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from test_fused_hotpath import make_params, nonzero_alphas
from test_ragged_kernel import fake_rows_at

from deepconsensus_tpu.inference import engine as engine_lib
from deepconsensus_tpu.inference import runner as runner_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.obs import trace as trace_lib

BUCKETS = (100, 200)
STUB_QUAL = 40


@pytest.fixture(scope='module')
def params():
  p = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(p, is_training=False)
  return p


def _win(params, length, rng):
  return rng.integers(
      0, 5, size=(params.total_rows, length, 1)).astype(np.float32)


def _ragged_stub_engine(params, batch_size=4, fail_packs=(),
                        buckets=BUCKETS):
  """Engine on the ragged path over a weightless runner whose
  dispatch_ragged/finalize are host stubs echoing each window's
  draft-CCS row (per-slot, per-offset — so placement correctness is
  observable in the delivered bytes)."""
  options = runner_lib.InferenceOptions(batch_size=batch_size)
  options.max_passes = params.max_passes
  options.max_length = params.max_length
  options.use_ccs_bq = params.use_ccs_bq
  options.window_buckets = buckets
  options.use_ragged_kernel = True
  runner = runner_lib.ModelRunner(params, {}, options)
  mp = params.max_passes
  seq = [0]

  def dispatch_ragged(pack, lengths):
    s = seq[0]
    seq[0] += 1
    if s in fail_packs:
      raise RuntimeError(f'stub failure in ragged pack {s}')
    return pack, lengths

  def finalize(handle):
    pack, _lengths = handle
    ids = pack[:, 4 * mp, :, 0].astype(np.int32)
    return ids, np.full(ids.shape, STUB_QUAL, np.int32)

  runner.dispatch_ragged = dispatch_ragged
  runner.finalize = finalize
  delivered = {}
  failures = []
  engine = engine_lib.ConsensusEngine(
      runner, options,
      deliver=lambda t, ids, quals: delivered.__setitem__(t, (ids, quals)),
      on_pack_failure=lambda ts, s, e: failures.append((list(ts), s, e)))
  return engine, delivered, failures


# ----------------------------------------------------------------------
# Packing mechanics (stub runner)


def test_exact_fill_cuts_immediately_no_padding(params):
  """batch_size=4 with buckets (100, 200) compiles 2 slots of 200; any
  400 positions of windows cut as a zero-padding pack mid-stream."""
  rng = np.random.default_rng(1)
  engine, delivered, failures = _ragged_stub_engine(params)
  engine.submit([_win(params, 100, rng) for _ in range(4)],
                list(range(4)))
  assert engine.n_packs == 1  # 4x100 fills 2x200 exactly
  engine.submit([_win(params, 200, rng), _win(params, 100, rng),
                 _win(params, 100, rng)], [4, 5, 6])
  assert engine.n_packs == 2  # 200 + 2x100 fills 2x200 exactly
  engine.flush()
  assert engine.n_packs == 2  # nothing buffered: flush cuts no pack
  assert engine.n_pack_rows == 7
  assert engine.n_pad_rows == 0
  assert engine.n_starvation_flushes == 0
  assert not failures
  assert set(delivered) == set(range(7))


def test_partial_packs_only_at_end_of_input(params):
  """An inexact fill defers: 3x100 waits (no starvation flush ever),
  a 200 completes the plan (largest-first: the 200 takes its own slot),
  and only flush() cuts the leftover as a zero-length-padded pack."""
  rng = np.random.default_rng(2)
  engine, delivered, _ = _ragged_stub_engine(params)
  engine.submit([_win(params, 100, rng) for _ in range(3)], [0, 1, 2])
  assert engine.n_packs == 0  # 300 of 400 positions: cannot fill exactly
  engine.submit([_win(params, 200, rng)], [3])
  assert engine.n_packs == 1  # slot0=[200], slot1=[100,100]; one 100 waits
  assert engine.has_work
  engine.flush()
  assert engine.n_packs == 2
  assert engine.n_pack_rows == 4
  # The final partial pack wasted 300 positions = 3 min-width windows.
  assert engine.n_pad_rows == 3
  assert set(delivered) == {0, 1, 2, 3}


def test_delivery_is_placement_exact_across_widths(params):
  """The stub echoes the CCS row through the slot layout, so each
  delivered window must byte-match its own submission — proving the
  (slot, offset, width) scatter/gather round-trips exactly."""
  rng = np.random.default_rng(3)
  engine, delivered, failures = _ragged_stub_engine(params)
  widths = (100, 200, 100, 100, 200, 100, 100, 100)
  wins = [_win(params, w, rng) for w in widths]
  engine.submit(wins, list(range(len(wins))))
  engine.flush()
  assert not failures
  mp = params.max_passes
  for i, w in enumerate(wins):
    np.testing.assert_array_equal(
        delivered[i][0], w[4 * mp, :, 0].astype(np.uint8))
    assert delivered[i][1].shape == (w.shape[1],)
    assert (delivered[i][1] == STUB_QUAL).all()


def test_no_starvation_flush_on_single_stream(params):
  """The bucketed path's pathological stream — one wide tail behind
  full narrow packs — needs no starvation flush here: the tail rides
  the next exact-fill pack with the narrow traffic."""
  rng = np.random.default_rng(4)
  engine, delivered, _ = _ragged_stub_engine(params)
  engine.submit([_win(params, 200, rng)], ['tail'])
  engine.submit([_win(params, 100, rng) for _ in range(8)],
                [('a', i) for i in range(8)])
  # 200 + 8x100 = 1000 positions -> two exact packs (800), 2x100 wait.
  # The wide tail rode pack 0 (largest-first), not a padded flush.
  assert engine.n_packs == 2
  assert engine.n_pad_rows == 0
  assert engine.n_starvation_flushes == 0
  engine.flush()
  assert delivered['tail'][0].shape == (200,)
  stats = engine.stats()
  assert stats['n_starvation_flushes'] == 0
  assert stats['flush_padding_fraction'] == 0.0
  assert stats['use_ragged_kernel'] == 1


def test_slot_batch_rounds_up_to_dp(params):
  import types

  options = runner_lib.InferenceOptions(batch_size=4)
  fake = types.SimpleNamespace(mesh_dp=8, obs=None)
  packer = engine_lib._RaggedPacker(
      fake, options, BUCKETS, timing_rows=[],
      on_pack_failure=lambda *a: None, deliver=lambda *a: None)
  assert packer.slot_len == 200
  assert packer.windows_per_slot == 2
  assert packer.n_slots == 8  # max(1, 4 // 2) = 2, rounded up to dp


def test_rejects_width_outside_buckets(params):
  engine, _, _ = _ragged_stub_engine(params)
  rng = np.random.default_rng(5)
  with pytest.raises(ValueError, match='not in window buckets'):
    engine.submit([_win(params, 150, rng)], [0])


def test_rejects_buckets_without_divisibility_chain(params):
  engine, _, _ = _ragged_stub_engine(params, buckets=(100, 250))
  rng = np.random.default_rng(6)
  with pytest.raises(ValueError, match='divisibility chain'):
    engine.submit([_win(params, 100, rng)], [0])


def test_poison_fails_whole_ragged_pack_once(params):
  rng = np.random.default_rng(7)
  engine, delivered, failures = _ragged_stub_engine(params)
  tickets = [object() for _ in range(8)]
  engine.poison_ticket(tickets[5])  # second pack (windows 4..7)
  engine.submit([_win(params, 100, rng) for _ in range(8)], tickets)
  engine.flush()
  assert len(failures) == 1
  failed_tickets, seq, err = failures[0]
  assert seq == 1
  assert failed_tickets == tickets[4:8]
  assert 'poison' in str(err)
  assert set(map(id, delivered)) == set(map(id, tickets[:4]))
  # Consume-once: the same ticket goes through on resubmission.
  engine.submit([_win(params, 100, rng)], [tickets[5]])
  engine.flush()
  assert len(failures) == 1
  assert tickets[5] in delivered


def test_dispatch_failure_routes_tickets_not_deliver(params):
  rng = np.random.default_rng(8)
  engine, delivered, failures = _ragged_stub_engine(params,
                                                    fail_packs=(0,))
  engine.submit([_win(params, 100, rng) for _ in range(6)],
                list(range(6)))
  engine.flush()
  assert len(failures) == 1
  failed_tickets, seq, err = failures[0]
  assert seq == 0
  assert failed_tickets == [0, 1, 2, 3]
  assert 'stub failure' in str(err)
  assert set(delivered) == {4, 5}


# ----------------------------------------------------------------------
# Byte identity vs the multi-packer path (real weights)


@pytest.fixture(scope='module')
def real_setup():
  p = make_params(pre=dict(window_buckets=BUCKETS))
  model = model_lib.get_model(p)
  init_rows = jnp.asarray(fake_rows_at(p, BUCKETS[0], 2, 0))
  variables = nonzero_alphas(model.init(jax.random.PRNGKey(0), init_rows))
  return p, jax.tree.map(np.asarray, variables)


def _run_stream(real_setup, stream, use_ragged, mesh=None, batch=4,
                depth=2):
  p, variables = real_setup
  opts = runner_lib.InferenceOptions(
      max_length=p.max_length, max_passes=p.max_passes,
      use_ccs_bq=p.use_ccs_bq, batch_size=batch, dispatch_depth=depth,
      window_buckets=BUCKETS, use_ragged_kernel=use_ragged)
  runner = runner_lib.ModelRunner(
      p, jax.tree.map(np.array, variables), opts, mesh=mesh)
  out = {}
  eng = engine_lib.ConsensusEngine(
      runner, opts,
      deliver=lambda t, ids, quals: out.__setitem__(
          t, (ids.copy(), quals.copy())))
  eng.submit_formatted(list(stream), list(range(len(stream))))
  eng.flush()
  return out, eng


def _mixed_stream(p, seed=5):
  """20 windows, ~70/30 narrow/wide, interleaved pseudo-randomly."""
  rng = np.random.default_rng(seed)
  narrow = fake_rows_at(p, BUCKETS[0], 14, 21)
  wide = fake_rows_at(p, BUCKETS[-1], 6, 22)
  stream, i1, i2 = [], 0, 0
  for flip in rng.random(20):
    if (flip < 0.7 and i1 < 14) or i2 >= 6:
      stream.append(narrow[i1])
      i1 += 1
    else:
      stream.append(wide[i2])
      i2 += 1
  return stream


def _adversarial_stream(p):
  """One window per bucket, strictly interleaved — the stream that
  maximizes multi-packer fragmentation (every bucket always holds a
  sub-batch tail) and exercises every mixed slot composition."""
  narrow = fake_rows_at(p, BUCKETS[0], 8, 31)
  wide = fake_rows_at(p, BUCKETS[-1], 8, 32)
  stream = []
  for i in range(8):
    stream.append(narrow[i])
    stream.append(wide[i])
  return stream


def _assert_identical(base, ragged, n):
  assert set(base) == set(ragged) == set(range(n))
  for t in range(n):
    np.testing.assert_array_equal(base[t][0], ragged[t][0])
    np.testing.assert_array_equal(base[t][1], ragged[t][1])


def test_mixed_stream_byte_identity(real_setup):
  stream = _mixed_stream(real_setup[0])
  base, be = _run_stream(real_setup, stream, use_ragged=False)
  ragged, re_ = _run_stream(real_setup, stream, use_ragged=True)
  _assert_identical(base, ragged, len(stream))
  # The whole point: one compiled forward where the bucketed path
  # needed one per bucket.
  assert be.stats()['n_forward_shapes'] == len(BUCKETS)
  assert re_.stats()['n_forward_shapes'] == 1
  assert re_.stats()['use_ragged_kernel'] == 1
  assert re_.stats()['n_packs_by_bucket'] == {BUCKETS[-1]: re_.n_packs}
  assert re_.stats()['n_starvation_flushes'] == 0


def test_adversarial_interleave_byte_identity(real_setup):
  stream = _adversarial_stream(real_setup[0])
  base, _ = _run_stream(real_setup, stream, use_ragged=False)
  ragged, re_ = _run_stream(real_setup, stream, use_ragged=True)
  _assert_identical(base, ragged, len(stream))
  assert re_.stats()['n_forward_shapes'] == 1


@pytest.mark.multichip
def test_mixed_stream_byte_identity_dp8(real_setup):
  """dp=8 over the forced host devices: the ragged slot batch rounds
  up to the data axis and each pack shards; bytes must not move."""
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  mesh = mesh_lib.make_mesh(dp=8, tp=1, devices=jax.devices()[:8])
  stream = _mixed_stream(real_setup[0], seed=6)
  base, _ = _run_stream(real_setup, stream, use_ragged=False,
                        mesh=mesh, batch=8)
  ragged, re_ = _run_stream(real_setup, stream, use_ragged=True,
                            mesh=mesh, batch=8)
  _assert_identical(base, ragged, len(stream))
  assert re_.stats()['n_forward_shapes'] == 1
  assert re_.stats()['n_packs_dispatched_sharded'] == re_.n_packs > 0


@pytest.mark.multichip
def test_adversarial_interleave_byte_identity_dp8(real_setup):
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  mesh = mesh_lib.make_mesh(dp=8, tp=1, devices=jax.devices()[:8])
  stream = _adversarial_stream(real_setup[0])
  base, _ = _run_stream(real_setup, stream, use_ragged=False,
                        mesh=mesh, batch=8)
  ragged, re_ = _run_stream(real_setup, stream, use_ragged=True,
                            mesh=mesh, batch=8)
  _assert_identical(base, ragged, len(stream))
  assert re_.stats()['n_forward_shapes'] == 1


# ----------------------------------------------------------------------
# Residency: trace spans through `dctpu trace --json`


def test_traced_ragged_run_reports_device_gaps(real_setup, tmp_path,
                                               capsys):
  """A live traced ragged run drives the full span pipeline: every
  pack gets an h2d_transfer and a device_compute span at ONE bucket
  (the slot length), and the summary exposes the device_gaps block."""
  from deepconsensus_tpu import cli

  path = str(tmp_path / 'ragged_trace.jsonl')
  trace_lib.configure(path, tier='run')
  try:
    _out, eng = _run_stream(real_setup, _mixed_stream(real_setup[0]),
                            use_ragged=True)
  finally:
    trace_lib.configure(None)
  assert cli.main(['trace', path, '--json']) == 0
  payload = json.loads(capsys.readouterr().out)
  assert payload['stage_counts']['device_compute'] == eng.n_packs
  assert payload['stage_counts']['h2d_transfer'] == eng.n_packs
  assert payload['overlap']['n_packs'] == eng.n_packs
  gaps = payload['device_gaps']
  # Pipelined packs overlap their compute spans, so a run can show
  # FEWER gaps than packs — never more.
  assert 0 <= gaps['n_gaps'] <= eng.n_packs - 1
  assert 0.0 <= gaps['transfer_only_fraction'] <= 1.0


def test_resident_pack_loop_trace_is_transfer_only(tmp_path, capsys):
  """The residency acceptance fixture: a device-resident pack loop's
  trace — back-to-back device_compute spans whose gaps hold only the
  next pack's h2d_transfer, drains batched at end-of-input (so no
  finalize_drain span per pack). `dctpu trace --json` must attribute
  every inter-compute gap to transfers and count every drain-free
  pack's launch as overlapped."""
  from deepconsensus_tpu import cli

  def span(name, ts_s, dur_s, **args):
    return {'name': name, 'cat': 'stage', 'ph': 'X', 'ts': ts_s * 1e6,
            'dur': dur_s * 1e6, 'pid': 1, 'tid': 1, 'args': args}

  events = [{'name': 'process_name', 'ph': 'M', 'pid': 1, 'tid': 0,
             'args': {'name': 'dctpu-run'}}]
  # Pack k computes on [k, k+0.9]; the 0.1s gap to pack k+1 is exactly
  # the h2d of pack k+2's uint8 planes. No finalize_drain spans at all.
  for k in range(4):
    events.append(span('h2d_transfer', max(0.0, k - 0.1), 0.1,
                       pack=k, bucket=200))
    events.append(span('device_compute', float(k), 0.9, pack=k,
                       bucket=200, dp=1, n_rows=8))
  path = tmp_path / 'resident.jsonl'
  path.write_text('\n'.join(json.dumps(e) for e in events) + '\n')

  assert cli.main(['trace', str(path), '--json']) == 0
  payload = json.loads(capsys.readouterr().out)
  # Drain-free packs: launches can only have been overlapped (a direct
  # launch happens inside finalize, which would have emitted a span).
  assert payload['overlap']['n_packs'] == 4
  assert payload['overlap']['n_overlapped'] == 4
  assert payload['overlap']['span_overlap_fraction'] == 1.0
  gaps = payload['device_gaps']
  assert gaps['n_gaps'] == 3
  assert gaps['gap_s'] == pytest.approx(0.3)
  assert gaps['transfer_s'] == pytest.approx(0.3)
  assert gaps['host_gap_s'] == pytest.approx(0.0, abs=1e-9)
  assert gaps['transfer_only_fraction'] == 1.0
