"""Pod-scale training tests: partition rules, the pjit train step,
prefetch-overlapped transfers, and the training degradation ladder.

All multichip drills run over the 8 forced host-platform CPU devices
from conftest.py; real-chip numbers come from measure_r4.sh
(train_dp2/train_dp4 stages) and bench.py's train_dp_scaling stage.

Cross-dp identity, precisely: at equal global batch and seed the
dp=8 run consumes byte-identical batches in the same order as dp=1
(the data pipeline is host-side and mesh-independent), so the loss
curves agree to all-reduce reduction order — empirically ~1e-6
relative on CPU, NOT bitwise, because sharding the batch changes the
summation order of the cross-device mean. The tests below pin that
contract two ways: np.allclose at rtol=1e-4 on the raw curves, and
equality of the 1e-4-quantized digest that bench_train_scaling.py
reports per dp point.
"""
import json
import os
import sys

import numpy as np
import pytest

import jax

from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu.models import checkpoints as checkpoints_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import flywheel as flywheel_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.models import train as train_lib
from deepconsensus_tpu.parallel import mesh as mesh_lib
from deepconsensus_tpu.parallel import partition_rules
from jax.sharding import PartitionSpec as P

pytestmark = [pytest.mark.multichip, pytest.mark.resilience]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
  sys.path.insert(0, _REPO_ROOT)

MAX_PASSES = 5
MAX_LENGTH = 20
GLOBAL_BATCH = 16
N_EXAMPLES = 96  # 6 steps per epoch at the fixed global batch


@pytest.fixture
def fresh_faults(monkeypatch):
  """Fault hooks are consume-once per process; isolate each test."""
  monkeypatch.setattr(faults_lib, '_fired', set())


@pytest.fixture(scope='module')
def shards(tmp_path_factory):
  from scripts import inject_faults

  d = tmp_path_factory.mktemp('synth_shards')
  return inject_faults.write_synthetic_tfrecords(
      str(d), n_shards=4, n_examples=N_EXAMPLES,
      max_passes=MAX_PASSES, max_length=MAX_LENGTH,
  )


def tiny_params(**overrides):
  params = config_lib.get_config('fc+test')
  with params.unlocked():
    params.max_passes = MAX_PASSES
    params.max_length = MAX_LENGTH
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.batch_size = GLOBAL_BATCH
    params.warmup_steps = 2
    params.log_every_n_steps = 1
    params.seed = 7
    for k, v in overrides.items():
      setattr(params, k, v)
  return params


def run_tiny_training(shards, out_dir, dp, **overrides):
  params = tiny_params(**overrides)
  mesh = mesh_lib.make_mesh(dp=dp, tp=1, devices=jax.devices()[:dp])
  train_lib.run_training(
      params=params, out_dir=out_dir,
      train_patterns=list(shards), eval_patterns=list(shards),
      num_epochs=1, mesh=mesh, eval_every=1_000_000,
  )
  return out_dir


def metrics_entries(out_dir, split=None):
  entries = []
  with open(os.path.join(out_dir, 'metrics.jsonl')) as f:
    for line in f:
      e = json.loads(line)
      if split is None or e.get('split') == split:
        entries.append(e)
  return entries


def train_losses(out_dir):
  return [e['loss'] for e in metrics_entries(out_dir, 'train')]


def curve_digest_1e4(losses):
  import hashlib

  return hashlib.sha256(
      json.dumps([round(l, 4) for l in losses]).encode()
  ).hexdigest()[:16]


def final_checkpoint_params(out_dir):
  latest = checkpoints_lib.latest_valid_checkpoint(
      os.path.join(out_dir, 'checkpoints'))
  assert latest is not None
  return checkpoints_lib.load_params(latest)


@pytest.fixture(scope='module')
def dp8_run(shards, tmp_path_factory):
  """The undisturbed dp=8 baseline shared by the identity, overlap,
  and degradation tests."""
  out = str(tmp_path_factory.mktemp('dp8_baseline'))
  return run_tiny_training(shards, out, dp=8)


# ----------------------------------------------------------------------
# Partition rules: the declarative table every pjit entry point shares


def transformer_test_params():
  params = config_lib.get_config('transformer_learn_values+test')
  with params.unlocked():
    params.max_passes = MAX_PASSES
    params.max_length = MAX_LENGTH
  config_lib.finalize_params(params)
  return params


def test_partition_rules_cover_every_leaf_exactly_once():
  """Round-trip over the REAL transformer tree: explain_matches maps
  every leaf to exactly one rule, attention/ffn leaves to their
  dedicated (non-catch-all) rules, scalars to replication."""
  params = transformer_test_params()
  model = model_lib.get_model(params)
  rows = np.zeros(
      (1, params.total_rows, params.max_length, 1), np.float32)
  variables = model.init(jax.random.PRNGKey(0), rows)

  explained = partition_rules.explain_matches(
      partition_rules.DEFAULT_RULES, variables['params'])
  paths = {'/'.join(str(getattr(k, 'key', k)) for k in p)
           for p, _ in jax.tree_util.tree_flatten_with_path(
               variables['params'])[0]}
  # Exactly once: explain_matches is a dict keyed by leaf path, and it
  # covers the flattened tree — no leaf missing, none matched twice.
  assert set(explained) == paths

  scalar_paths = {
      '/'.join(str(getattr(k, 'key', k)) for k in p)
      for p, leaf in jax.tree_util.tree_flatten_with_path(
          variables['params'])[0]
      if np.ndim(leaf) == 0
  }
  catch_all = len(partition_rules.DEFAULT_RULES) - 1
  for path, idx in explained.items():
    last = path.rsplit('/', 1)[-1]
    if path in scalar_paths:
      # Scalars (the attention-wrapper alpha gates) replicate without
      # consulting the rules; explain_matches marks them -1.
      assert idx == -1, (path, idx)
    elif '/self_attention' in path and last == 'kernel':
      assert idx in (0, 1), (path, idx)
    elif '/ffn_' in path and (path.endswith('filter_layer/kernel')
                              or path.endswith('filter_layer/bias')
                              or path.endswith('output_layer/kernel')):
      assert idx in (2, 3, 4), (path, idx)
    else:
      assert idx == catch_all, (path, idx)

  # Under a tp=2 mesh the rules must actually shard the model axis.
  mesh = mesh_lib.make_mesh(dp=4, tp=2, devices=jax.devices()[:8])
  shardings = partition_rules.tree_shardings(mesh, variables['params'])
  n_model_sharded = sum(
      any(entry == mesh_lib.MODEL_AXIS
          or (isinstance(entry, tuple) and mesh_lib.MODEL_AXIS in entry)
          for entry in s.spec)
      for s in jax.tree_util.tree_leaves(shardings))
  assert n_model_sharded >= 36  # 4 kernels + 1 bias per layer, 6+ layers


def test_unmatched_leaf_raises_typed_error():
  rules_without_catchall = partition_rules.DEFAULT_RULES[:-1]
  tree = {'oddball': {'kernel': np.zeros((4, 4), np.float32)}}
  with pytest.raises(partition_rules.PartitionRuleError) as ei:
    partition_rules.match_partition_rules(rules_without_catchall, tree)
  assert 'oddball/kernel' in str(ei.value)
  # The CLI maps ValueError to exit 2; the typed error must stay one.
  assert isinstance(ei.value, ValueError)


def test_first_matching_rule_wins_and_scalars_replicate():
  rules = (
      (r'ffn_\d+/filter_layer/kernel', P(None, mesh_lib.MODEL_AXIS)),
      (r'ffn_\d+/.*', P()),
      (r'.*', P()),
  )
  tree = {
      'ffn_0': {'filter_layer': {'kernel': np.zeros((2, 4), np.float32),
                                 'bias': np.zeros((4,), np.float32)}},
      'count': np.float32(0),  # scalar: replicated regardless of rules
  }
  specs = partition_rules.match_partition_rules(rules, tree)
  assert specs['ffn_0']['filter_layer']['kernel'] == P(
      None, mesh_lib.MODEL_AXIS)
  assert specs['ffn_0']['filter_layer']['bias'] == P()
  assert specs['count'] == P()
  explained = partition_rules.explain_matches(rules, tree)
  assert explained['ffn_0/filter_layer/kernel'] == 0
  assert explained['ffn_0/filter_layer/bias'] == 1
  assert explained['count'] == -1


def test_optimizer_moments_shard_like_their_params(tmp_path):
  """The LAMB moment leaf paths CONTAIN the param paths, so one rule
  table shards optimizer state exactly like the parameters."""
  params = transformer_test_params()
  with params.unlocked():
    params.dtype = 'float32'
    params.batch_size = 8
  mesh = mesh_lib.make_mesh(dp=4, tp=2, devices=jax.devices()[:8])
  trainer = train_lib.Trainer(
      params=params, out_dir=str(tmp_path), mesh=mesh)
  state = trainer.init_state(steps_total=10)
  shardings = trainer.state_shardings(state)
  param_specs = jax.tree_util.tree_flatten_with_path(
      shardings.params)[0]
  sharded_params = {
      '/'.join(str(getattr(k, 'key', k)) for k in p)
      for p, s in param_specs if s.spec != P()
  }
  assert sharded_params  # tp=2 shards the attention/ffn kernels
  moment_specs = jax.tree_util.tree_flatten_with_path(
      shardings.opt_state)[0]
  moment_hits = set()
  for path, spec in moment_specs:
    joined = '/'.join(str(getattr(k, 'key', k)) for k in path)
    for pp in sharded_params:
      if pp in joined:
        # Moment mirrors its parameter: same spec, not replicated.
        assert spec.spec != P(), (joined, spec)
        moment_hits.add(pp)
  # Every sharded param has at least one sharded optimizer moment.
  assert moment_hits == sharded_params


# ----------------------------------------------------------------------
# Cross-dp loss-curve identity + prefetch overlap counters


def test_dp8_loss_curve_matches_single_device(shards, dp8_run, tmp_path):
  """Equal global batch + equal seed => equal curve across dp, up to
  all-reduce reduction order (see module docstring)."""
  dp1 = run_tiny_training(shards, str(tmp_path / 'dp1'), dp=1)
  losses1 = train_losses(dp1)
  losses8 = train_losses(dp8_run)
  assert len(losses1) == len(losses8) == N_EXAMPLES // GLOBAL_BATCH
  np.testing.assert_allclose(losses1, losses8, rtol=1e-4)
  assert curve_digest_1e4(losses1) == curve_digest_1e4(losses8)
  # The curve must also be a real training signal, not a constant.
  assert losses1[-1] < losses1[0]


def test_prefetch_overlap_counters(dp8_run):
  """A clean N-step run launches N sharded transfers and overlaps all
  but the first under the previous step's compute: the sidecar must
  report exactly (N-1)/N."""
  faults = metrics_entries(dp8_run, 'faults')[-1]
  n_steps = N_EXAMPLES // GLOBAL_BATCH
  assert faults['n_batch_launches'] == n_steps
  assert faults['n_batches_prefetched'] == n_steps - 1
  assert faults['train_transfer_overlap_fraction'] == pytest.approx(
      (n_steps - 1) / n_steps, abs=1e-3)
  assert faults.get('n_batches_replaced', 0) == 0
  assert 'n_train_degraded' not in faults


# ----------------------------------------------------------------------
# Training degradation ladder: mid-training device loss, dp 8 -> 4


def test_device_lost_mid_training_degrades_dp8_to_dp4(
    shards, dp8_run, tmp_path, fresh_faults, monkeypatch):
  """DCTPU_FAULT_DEVICE_LOST_AT_STEP fires a permanent DeviceLostError
  mid-run; --on_device_error=degrade rebuilds the mesh at dp=4,
  carries the live state over IN MEMORY (no checkpoint rollback: the
  state survived the device), re-places the failed batch, and
  completes every step. Final weights must match the undisturbed dp=8
  run to reduction-order tolerance — the ladder changes where the
  math runs, not what it computes."""
  monkeypatch.setenv(faults_lib.ENV_DEVICE_LOST_AT_STEP, '3')
  out = run_tiny_training(
      shards, str(tmp_path / 'degraded'), dp=8,
      on_device_error='degrade')

  n_steps = N_EXAMPLES // GLOBAL_BATCH
  losses = train_losses(out)
  assert len(losses) == n_steps  # the failed step re-ran, none lost
  assert np.isfinite(losses).all()

  faults = metrics_entries(out, 'faults')[-1]
  assert faults['n_train_degraded'] == 1.0
  # The failed batch was re-placed directly on the rebuilt mesh.
  assert faults['n_batches_replaced'] >= 1
  # No NaN-sentinel rollback happened: degradation is not a rollback.
  assert faults.get('n_nan_rollbacks', 0) == 0

  # In-memory carry-over: the degraded curve tracks the undisturbed
  # dp=8 baseline, including the steps AFTER the device loss.
  baseline = train_losses(dp8_run)
  np.testing.assert_allclose(losses, baseline, rtol=1e-4)
  final = final_checkpoint_params(out)
  final_base = final_checkpoint_params(dp8_run)
  jax.tree_util.tree_map_with_path(
      lambda p, a, b: np.testing.assert_allclose(
          np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
          err_msg=str(p)),
      final, final_base)


def test_degrade_refused_at_dp1_reraises(shards, tmp_path, fresh_faults,
                                         monkeypatch):
  """dp=1 has no smaller mesh: the ladder refuses and the typed
  DeviceLostError surfaces instead of an infinite retry loop."""
  monkeypatch.setenv(faults_lib.ENV_DEVICE_LOST_AT_STEP, '2')
  with pytest.raises(faults_lib.DeviceLostError):
    run_tiny_training(shards, str(tmp_path / 'dp1'), dp=1,
                      on_device_error='degrade')


def test_device_lost_without_degrade_fails_fast(shards, tmp_path,
                                                fresh_faults,
                                                monkeypatch):
  monkeypatch.setenv(faults_lib.ENV_DEVICE_LOST_AT_STEP, '2')
  with pytest.raises(faults_lib.DeviceLostError):
    run_tiny_training(shards, str(tmp_path / 'fail'), dp=8)


# ----------------------------------------------------------------------
# Guard rails: bucket-set validation + flywheel gate enforcement


def test_invalid_bucket_sets_raise_typed(tmp_path):
  """Genuinely invalid bucket sets stay a typed config-time fault.
  Valid multi-bucket sets train (tests/test_longwin_training.py); what
  must still be refused is a bucket list that cannot work: widths out
  of order, or a model family whose parameter shapes depend on the
  window width."""
  # Non-ascending widths are operator error at config time.
  params = tiny_params()
  with params.unlocked():
    params.window_buckets = (40, 20)
  with pytest.raises(faults_lib.WindowBucketError):
    train_lib.Trainer(params=params, out_dir=str(tmp_path / 'order'),
                      mesh=None)
  # The FC head sizes its output Dense by max_length: one param tree
  # cannot serve two widths, so fc + multi-bucket is refused with the
  # remedy (use a transformer config).
  params = tiny_params()
  with params.unlocked():
    params.window_buckets = (20, 40)
  with pytest.raises(faults_lib.WindowBucketError) as ei:
    train_lib.Trainer(params=params, out_dir=str(tmp_path / 'fc'),
                      mesh=None)
  msg = str(ei.value)
  assert 'window_buckets' in msg and 'transformer' in msg
  # ValueError subclass: `dctpu train` maps it to exit code 2.
  assert isinstance(ei.value, ValueError)


def test_flywheel_gate_failure_is_typed(shards, tmp_path):
  """An impossible bf16 threshold must fail the gate and _enforce must
  raise the typed FlywheelGateError carrying the measurement."""
  params = tiny_params()
  trainer = train_lib.Trainer(
      params=params, out_dir=str(tmp_path), mesh=None)
  state = trainer.init_state(steps_total=4)
  variables = {'params': jax.device_get(state.params)}
  gate = flywheel_lib.bf16_qv_gate(
      params, variables, list(shards), threshold=-1, max_batches=1)
  assert not gate['passed']
  assert gate['measured'] >= 0
  with pytest.raises(faults_lib.FlywheelGateError) as ei:
    flywheel_lib._enforce([gate])
  err = ei.value
  assert err.gate == 'bf16_max_qv_delta'
  assert err.measured == gate['measured']
  assert err.threshold == -1
  # Sanity: a sane threshold passes the same measurement.
  ok = flywheel_lib.bf16_qv_gate(
      params, variables, list(shards),
      threshold=flywheel_lib.BF16_QV_GATE, max_batches=1)
  assert ok['passed']


def test_flywheel_manifest_written_atomically(tmp_path):
  manifest = {'stages': {}, 'gates': [
      {'name': 'g', 'measured': 1, 'threshold': 0, 'passed': False}],
      'ok': False}
  path = flywheel_lib._write_manifest(str(tmp_path), manifest)
  assert os.path.basename(path) == flywheel_lib.MANIFEST_NAME
  assert not os.path.exists(path + '.tmp')
  assert json.load(open(path)) == manifest
