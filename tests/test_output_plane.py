"""Threshold-table exactness for the device-resident output plane.

ops/output_plane.py bisects, against the real host epilogue as oracle,
the smallest f32 probability at which each integer quality becomes
reachable; the device then computes a quality as a count of cleared
thresholds (pure IEEE comparisons, no transcendentals). These tests
pin the oracle/threshold equivalence over dense f32 probes, the
non-representable fallbacks, and the XLA/Pallas epilogue parity.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.calibration import lib as calibration_lib
from deepconsensus_tpu.ops import output_plane


def _probes(thresholds, n_random=100_000, seed=0):
  """Dense f32 probe set: uniform randoms, a near-1 log cluster where
  the quality curve is steepest, and every threshold's bit
  neighbourhood (the exact boundaries the bisection pinned)."""
  rng = np.random.default_rng(seed)
  parts = [
      rng.random(n_random, dtype=np.float32),
      (1.0 - np.logspace(-12, 0, 4096)).astype(np.float32),
      np.float32([0.0, 1.0]),
  ]
  if thresholds.size:
    bits = output_plane._bits(thresholds)[:, None] + np.arange(-2, 3)
    bits = np.clip(bits, 0, int(output_plane._bits(np.float32([1.0]))[0]))
    parts.append(output_plane._from_bits(bits.ravel()))
  p = np.concatenate(parts)
  return p[(p >= 0.0) & (p <= 1.0)]


@pytest.mark.parametrize('calibration,maxq', [
    ('skip', 93),
    ('0,0.9,2.5', 93),     # threshold 0: transform everywhere
    ('15,1.1,2', 93),      # thresholded, monotone at the seam
    ('10,0.5,30', 90),     # compressive but still monotone
    ('skip', 40),          # low clamp: every step near the top
])
def test_threshold_count_matches_host_oracle(calibration, maxq):
  cv = calibration_lib.parse_calibration_string(calibration)
  thresholds = output_plane.quality_thresholds(cv, maxq)
  assert thresholds is not None
  # thresholds[k-1] is the SMALLEST f32 with oracle >= k: exact at the
  # threshold, one ulp below must fall short.
  ks = np.arange(1, thresholds.size + 1)
  oracle = output_plane.host_quality_reference(thresholds, cv, maxq)
  assert np.all(oracle >= ks)
  # One-ulp-below must fall short (skip thresholds already at p=0.0 —
  # a quality reachable everywhere has no "below", and bits-1 of 0
  # is not a float).
  bits = output_plane._bits(thresholds)
  positive = bits > 0
  below = output_plane._from_bits(bits[positive] - 1)
  below_q = output_plane.host_quality_reference(below, cv, maxq)
  assert np.all(below_q < ks[positive])
  # Count-of-cleared-thresholds == host integer on a dense probe set.
  p = _probes(thresholds)
  counted = (p[:, None] >= thresholds[None, :]).sum(axis=1)
  np.testing.assert_array_equal(
      counted.astype(np.int32),
      output_plane.host_quality_reference(p, cv, maxq))


def test_non_monotone_calibration_not_representable():
  # w < 0: quality decreases in max_prob — no threshold table exists.
  cv = calibration_lib.parse_calibration_string('0,-1,50')
  assert not output_plane.calibration_is_monotone(cv)
  assert output_plane.quality_thresholds(cv, 93) is None
  # Downward jump at the seam: 15*1.1-3 = 13.5 < 15.
  cv = calibration_lib.parse_calibration_string('15,1.1,-3')
  assert not output_plane.calibration_is_monotone(cv)
  assert output_plane.quality_thresholds(cv, 93) is None


def test_top_quality_past_uint8_plane_not_representable():
  # maxq clamp above 255 with an amplifying calibration: the top
  # quality exceeds what the uint8 plane can carry.
  cv = calibration_lib.parse_calibration_string('0,3,0')
  assert output_plane.calibration_is_monotone(cv)
  assert output_plane.quality_thresholds(cv, 400) is None
  # The same calibration under the uint8 ceiling is fine.
  assert output_plane.quality_thresholds(cv, 93) is not None


def test_d2h_bytes_per_position():
  assert output_plane.d2h_bytes_per_position(True) == 2
  assert output_plane.d2h_bytes_per_position(False) == 8


def _soft_preds(b=8, length=16, vocab=5, seed=3):
  rng = np.random.default_rng(seed)
  logits = rng.normal(size=(b, length, vocab)).astype(np.float32)
  e = np.exp(logits - logits.max(-1, keepdims=True))
  return (e / e.sum(-1, keepdims=True)).astype(np.float32)


@pytest.mark.parametrize('calibration,maxq', [
    ('skip', 93), ('15,1.1,2', 93), ('skip', 40),
])
def test_phred_epilogue_matches_host_oracle(calibration, maxq):
  cv = calibration_lib.parse_calibration_string(calibration)
  thresholds = output_plane.quality_thresholds(cv, maxq)
  preds = _soft_preds()
  ids, quals = output_plane.phred_epilogue(jnp.asarray(preds), thresholds)
  assert ids.dtype == jnp.uint8 and quals.dtype == jnp.uint8
  np.testing.assert_array_equal(np.asarray(ids), preds.argmax(-1))
  np.testing.assert_array_equal(
      np.asarray(quals, np.int32),
      output_plane.host_quality_reference(preds.max(-1), cv, maxq))


def test_phred_epilogue_pallas_interpret_parity():
  cv = calibration_lib.parse_calibration_string('skip')
  thresholds = output_plane.quality_thresholds(cv, 93)
  preds = jnp.asarray(_soft_preds(b=8, length=32, seed=5))
  ids_x, quals_x = output_plane.phred_epilogue(preds, thresholds)
  ids_p, quals_p = output_plane.phred_epilogue(
      preds, thresholds, use_pallas=True, interpret=True)
  np.testing.assert_array_equal(np.asarray(ids_p), np.asarray(ids_x))
  np.testing.assert_array_equal(np.asarray(quals_p), np.asarray(quals_x))
