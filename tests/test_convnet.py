import jax
import jax.numpy as jnp
import numpy as np

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib


def test_convnet_forward():
  params = config_lib.get_config('conv_net+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.conv_model = 'resnet50'
  model = model_lib.get_model(params)
  rows = jnp.asarray(
      np.random.default_rng(0)
      .integers(0, 5, size=(2, params.total_rows, 100, 1))
      .astype(np.float32)
  )
  variables = model.init(jax.random.PRNGKey(0), rows)
  assert 'batch_stats' in variables
  preds = model.apply(variables, rows)
  assert preds.shape == (2, 100, 5)
  np.testing.assert_allclose(
      np.asarray(preds.sum(-1)), np.ones((2, 100)), atol=1e-5
  )


def test_resnet_depths_registered():
  from deepconsensus_tpu.models.convnet import RESNET_DEPTHS

  assert set(RESNET_DEPTHS) == {'resnet50', 'resnet101', 'resnet152'}
