"""Cross-batch window packer + array-native output plane.

Covers the packer's edge cases (empty model set, sub-batch tail flush,
molecules spanning pack boundaries, packed-batch failure attribution)
through the full pipeline with a stubbed model forward — the stub
echoes each window's draft-CCS row, so correct scatter/stitch is
observable as the CCS sequence coming back out — plus direct
array-plane vs string-plane stitch parity.
"""
import json

import numpy as np
import pytest

from deepconsensus_tpu import constants
from deepconsensus_tpu.inference import runner as runner_lib
from deepconsensus_tpu.io import bam as bam_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.postprocess import stitch
from deepconsensus_tpu.utils import phred

pytestmark = pytest.mark.resilience

N_ZMWS = 6
SEQ_LEN = 600
STUB_QUAL = 40


@pytest.fixture(scope='module')
def params():
  p = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(p, is_training=False)
  return p


def _stub_model(runner, params, fail=False):
  """Replaces the jitted forward: finalize returns each window's
  draft-CCS row as the prediction with a constant quality, making the
  pack -> scatter -> stitch path verifiable without weights."""
  mp = params.max_passes

  def dispatch(rows):
    if fail:
      raise RuntimeError('stub model pack failure')
    return rows

  def finalize(rows):
    ids = rows[:, 4 * mp, :, 0].astype(np.int32)
    return ids, np.full(ids.shape, STUB_QUAL, np.int32)

  runner.dispatch = dispatch
  runner.finalize = finalize


def _run(tmp_path, synthetic_bams, params, name, fail=False, **kw):
  subreads, ccs = synthetic_bams(
      subdir=f'bams_{name}', n_zmws=N_ZMWS, seq_len=SEQ_LEN)
  kw.setdefault('batch_zmws', 2)
  kw.setdefault('skip_windows_above', 0)  # falsy: no quality skips
  kw.setdefault('min_quality', 0)
  options = runner_lib.InferenceOptions(**kw)
  runner = runner_lib.ModelRunner(params, {}, options)
  _stub_model(runner, params, fail=fail)
  out = str(tmp_path / f'{name}.fastq')
  counters = runner_lib.run_inference(
      subreads_to_ccs=subreads, ccs_bam=ccs, checkpoint=None,
      output=out, options=options, runner=runner)
  return out, counters, ccs


def _reads(path):
  with open(path) as f:
    lines = [line.rstrip('\n') for line in f]
  return {lines[i][1:]: (lines[i + 1], lines[i + 3])
          for i in range(0, len(lines), 4)}


def _ccs_seqs(ccs_bam):
  with bam_lib.BamReader(ccs_bam) as r:
    return {rec.qname: rec.seq for rec in r}


def test_empty_model_set(tmp_path, synthetic_bams, params):
  """All windows quality-skipped: the packer must never dispatch (the
  stub would raise on weightless variables anyway via fail=True)."""
  out, counters, ccs = _run(tmp_path, synthetic_bams, params, 'empty',
                            fail=True, skip_windows_above=1,
                            batch_size=32)
  assert counters['n_model_packs'] == 0
  assert counters['n_model_pack_rows'] == 0
  assert sorted(_reads(out)) == sorted(_ccs_seqs(ccs))


def test_tail_flush_pads_final_pack(tmp_path, synthetic_bams, params):
  """36 windows at batch_size=8: 4 full packs cut across featurize
  batches + one padded tail pack at end-of-input."""
  out, counters, ccs = _run(tmp_path, synthetic_bams, params, 'tail',
                            batch_size=8)
  assert counters['n_model_packs'] == 5
  assert counters['n_model_pack_rows'] == 36
  assert counters['n_model_pad_rows'] == 5 * 8 - 36
  reads, seqs = _reads(out), _ccs_seqs(ccs)
  assert sorted(reads) == sorted(seqs)
  for name, (seq, qual) in reads.items():
    assert seq == seqs[name]  # stub echoes the draft CCS
    assert qual == chr(STUB_QUAL + 33) * SEQ_LEN


def test_sidecar_reports_starvation_counters(tmp_path, synthetic_bams,
                                             params):
  """run_inference copies the engine's starvation accounting into the
  counters sidecar: fixed-width streams never starve, so both keys are
  present at their zero values (the live values are exercised at the
  engine boundary in test_engine.py)."""
  _out, counters, _ccs = _run(tmp_path, synthetic_bams, params,
                              'starve_keys', batch_size=8)
  assert counters['n_starvation_flushes'] == 0
  assert counters['flush_padding_fraction'] == 0.0


def test_molecules_span_pack_boundaries(tmp_path, synthetic_bams, params):
  """batch_size < windows-per-molecule: every molecule's windows land
  in different packs (and different featurize batches' packs) and must
  still scatter back and stitch in order."""
  out, counters, ccs = _run(tmp_path, synthetic_bams, params, 'span',
                            batch_size=4)
  assert counters['n_model_packs'] == 9  # 36 windows / 4
  assert counters['n_model_pad_rows'] == 0
  reads, seqs = _reads(out), _ccs_seqs(ccs)
  for name, (seq, _) in reads.items():
    assert seq == seqs[name]


def test_cross_batch_packing_output_invariance(tmp_path, synthetic_bams,
                                               params):
  """Packing windows across featurize batches must not change a single
  output byte vs per-batch padded dispatch — only the pad accounting."""
  packed, c_packed, _ = _run(tmp_path, synthetic_bams, params, 'packed',
                             batch_size=8, pack_across_batches=True)
  padded, c_padded, _ = _run(tmp_path, synthetic_bams, params, 'padded',
                             batch_size=8, pack_across_batches=False)
  with open(packed, 'rb') as a, open(padded, 'rb') as b:
    assert a.read() == b.read()
  # Without cross-batch packing every 12-window featurize batch cuts
  # its own 8 + 4-pad packs.
  assert c_packed['n_model_pad_rows'] == 4
  assert c_padded['n_model_packs'] == 6
  assert c_padded['n_model_pad_rows'] == 12


def test_pack_failure_attributes_member_molecules(tmp_path,
                                                 synthetic_bams, params):
  """A failed pack quarantines exactly its member molecules, recording
  which pack took them down; under ccs-fallback every member degrades
  to its draft CCS (original base qualities) instead of vanishing."""
  out, counters, ccs = _run(tmp_path, synthetic_bams, params, 'fail',
                            fail=True, batch_size=8,
                            on_zmw_error='ccs-fallback')
  reads, seqs = _reads(out), _ccs_seqs(ccs)
  assert sorted(reads) == sorted(seqs)
  for name, (seq, qual) in reads.items():
    assert seq == seqs[name]
    assert qual == chr(30 + 33) * SEQ_LEN  # synthetic base_qual=30
  with open(out + '.failed.jsonl') as f:
    entries = [json.loads(line) for line in f]
  assert {e['zmw'] for e in entries} == set(seqs)
  for e in entries:
    assert e['stage'] == 'model'
    assert e['action'] == 'ccs-fallback'
    assert 'model_pack' in e and 'n_windows_in_pack' in e


def _string_plane(name, windows, max_length, min_quality, min_length):
  counter = stitch.OutcomeCounter()
  preds = [
      stitch.DCModelOutput(
          molecule_name=name, window_pos=pos,
          sequence=phred.encoded_sequence_to_string(ids),
          quality_string=phred.quality_scores_to_string(quals))
      for pos, ids, quals in windows
  ]
  preds.sort(key=lambda p: (p.molecule_name, p.window_pos))
  fastq = stitch.stitch_to_fastq(
      molecule_name=name, predictions=preds, max_length=max_length,
      min_quality=min_quality, min_length=min_length,
      outcome_counter=counter)
  return fastq, counter


def _array_plane(name, windows, max_length, min_quality, min_length):
  counter = stitch.OutcomeCounter()
  result = stitch.stitch_arrays(
      name,
      np.asarray([w[0] for w in windows], dtype=np.int64),
      np.stack([w[1] for w in windows]).astype(np.uint8),
      np.stack([w[2] for w in windows]).astype(np.uint8),
      max_length=max_length, min_quality=min_quality,
      min_length=min_length, outcome_counter=counter)
  fastq = (None if result is None
           else stitch.format_fastq_bytes(name, *result).decode('ascii'))
  return fastq, counter


def test_array_plane_matches_string_plane():
  """stitch_arrays + format_fastq_bytes must be byte-for-byte the
  legacy stitch_to_fastq, including which outcome counter each filter
  path charges."""
  rng = np.random.default_rng(11)
  L = 25

  def win(pos, gap_frac=0.2, qual_lo=20, qual_hi=60):
    ids = rng.integers(1, len(constants.SEQ_VOCAB), size=L)
    ids[rng.random(L) < gap_frac] = constants.GAP_INT
    quals = rng.integers(qual_lo, qual_hi, size=L)
    return pos, ids, quals

  cases = {
      'success': ([win(0), win(L), win(2 * L)], dict()),
      # Windows arrive shuffled; the stable pos sort must fix it.
      'shuffled': ([win(2 * L), win(0), win(L)], dict()),
      'missing_window': ([win(0), win(2 * L)], dict()),
      'gaps_only': ([win(0, gap_frac=1.0)], dict()),
      'low_quality': ([win(0, qual_lo=1, qual_hi=5)],
                      dict(min_quality=30)),
      'too_short': ([win(0, gap_frac=0.9)], dict(min_length=20)),
  }
  for name, (windows, kw) in cases.items():
    kw = dict(min_quality=kw.get('min_quality', 10),
              min_length=kw.get('min_length', 0))
    old, c_old = _string_plane(name, windows, L, **kw)
    new, c_new = _array_plane(name, windows, L, **kw)
    assert old == new, name
    assert c_old == c_new, name
