"""Resume-from-checkpoint, retry-on-preemption, ccs_fasta input, and
the inference worker pool."""
import os

import numpy as np
import pytest

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import train as train_lib


def tiny_params():
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.batch_size = 8
    params.num_hidden_layers = 1
    params.filter_size = 32
    params.warmup_steps = 2
  return params


def test_retry_wrapper_retries_transient(monkeypatch, tmp_path):
  calls = []

  def fake_run_training(*args, **kwargs):
    calls.append(1)
    if len(calls) < 3:
      raise RuntimeError('UNAVAILABLE: TPU preempted')
    return {'eval/loss': 1.0}

  monkeypatch.setattr(train_lib, 'run_training', fake_run_training)
  out = train_lib.run_training_with_retry()
  assert out == {'eval/loss': 1.0}
  assert len(calls) == 3


def test_retry_wrapper_raises_permanent(monkeypatch):
  def fake_run_training(*args, **kwargs):
    raise RuntimeError('INVALID_ARGUMENT: bad shape')

  monkeypatch.setattr(train_lib, 'run_training', fake_run_training)
  with pytest.raises(RuntimeError, match='INVALID_ARGUMENT'):
    train_lib.run_training_with_retry()


def test_training_resumes_from_checkpoint(tmp_path, testdata_dir):
  params = tiny_params()
  out_dir = str(tmp_path / 'resume')
  patterns = [str(testdata_dir / 'human_1m/tf_examples/eval/*')]  # 65 ex
  m1 = train_lib.run_training(
      params=params, out_dir=out_dir,
      train_patterns=patterns, eval_patterns=patterns,
      num_epochs=1, eval_every=10**9,
  )
  def list_ckpts(d):
    return {
        name for name in os.listdir(os.path.join(d, 'checkpoints'))
        if not name.endswith('-tmp')
    }

  ckpts = list_ckpts(out_dir)
  # Second invocation with a larger epoch budget restores the latest
  # checkpoint, skips the completed steps, and trains the remainder.
  m2 = train_lib.run_training(
      params=params, out_dir=out_dir,
      train_patterns=patterns, eval_patterns=patterns,
      num_epochs=2, eval_every=10**9,
  )
  ckpts2 = list_ckpts(out_dir)
  assert ckpts2 > ckpts  # a later-step checkpoint was added
  assert np.isfinite(m2['eval/loss'])


def test_ccs_fasta_feeder(tmp_path, testdata_dir):
  """Feeding CCS drafts from FASTA instead of BAM."""
  from deepconsensus_tpu.io import bam as bam_lib
  from deepconsensus_tpu.preprocess import FeatureLayout, create_proc_feeder

  td = str(testdata_dir / 'human_1m')
  # Build a FASTA of the ccs drafts.
  fasta = tmp_path / 'ccs.fasta'
  with open(fasta, 'w') as f:
    for rec in bam_lib.BamReader(f'{td}/ccs.bam'):
      f.write(f'>{rec.qname}\n{rec.seq}\n')
  layout = FeatureLayout(20, 100)
  feeder, counter = create_proc_feeder(
      subreads_to_ccs=f'{td}/subreads_to_ccs.bam',
      ccs_fasta=str(fasta),
      layout=layout,
      ins_trim=5,
      limit=2,
  )
  items = list(feeder())
  assert len(items) == 2
  subreads, name, *_ = items[0]
  ccs_read = subreads[-1]
  assert ccs_read.name == name
  # FASTA mode has no quality scores -> zeros.
  assert (ccs_read.base_quality_scores == 0).all()


def test_inference_with_worker_pool(tmp_path, testdata_dir):
  import jax
  import jax.numpy as jnp

  from deepconsensus_tpu.inference import runner as runner_lib
  from deepconsensus_tpu.models import model as model_lib

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params, is_training=False)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = 1
    params.filter_size = 32
  options = runner_lib.InferenceOptions(
      batch_size=32, batch_zmws=4, limit=2, cpus=2
  )
  model = model_lib.get_model(params)
  rows = jnp.zeros((1, params.total_rows, params.max_length, 1))
  variables = model.init(jax.random.PRNGKey(0), rows)
  runner = runner_lib.ModelRunner(params, variables, options)
  out = str(tmp_path / 'pooled.fastq')
  counters = runner_lib.run_inference(
      subreads_to_ccs=str(testdata_dir / 'human_1m/subreads_to_ccs.bam'),
      ccs_bam=str(testdata_dir / 'human_1m/ccs.bam'),
      checkpoint=None,
      output=out,
      options=options,
      runner=runner,
  )
  assert counters['n_zmw_pass'] == 2


def test_warm_start_does_not_override_resume(tmp_path, testdata_dir):
  """A preempted warm-started run must resume its own latest
  checkpoint, not reload the warm-start weights at step 0."""
  params = tiny_params()
  out_dir = str(tmp_path / 'warm_resume')
  patterns = [str(testdata_dir / 'human_1m/tf_examples/eval/*')]  # 65 ex
  train_lib.run_training(
      params=params, out_dir=out_dir,
      train_patterns=patterns, eval_patterns=patterns,
      num_epochs=1, eval_every=10**9,
  )
  ckpt_dir = os.path.join(out_dir, 'checkpoints')
  steps = sorted(
      int(n.split('-')[1]) for n in os.listdir(ckpt_dir)
      if n.startswith('checkpoint-') and not n.endswith('-tmp')
  )
  first_final = steps[-1]
  warm = os.path.join(ckpt_dir, f'checkpoint-{first_final}')
  # Restart with warm_start set (as run_training_with_retry would).
  # eval_every=3 would produce a checkpoint at step 3 if training
  # wrongly restarted from 0.
  train_lib.run_training(
      params=params, out_dir=out_dir,
      train_patterns=patterns, eval_patterns=patterns,
      num_epochs=2, eval_every=3, warm_start=warm,
  )
  steps2 = sorted(
      int(n.split('-')[1]) for n in os.listdir(ckpt_dir)
      if n.startswith('checkpoint-') and not n.endswith('-tmp')
  )
  new_steps = [s for s in steps2 if s not in steps]
  assert new_steps and all(s > first_final for s in new_steps), steps2


def test_cli_train_uses_retry_wrapper(monkeypatch, tmp_path):
  """`dctpu train` survives a transient UNAVAILABLE (VERDICT r1 #6)."""
  from deepconsensus_tpu import cli

  calls = []

  def fake_run_training(*args, **kwargs):
    calls.append(kwargs)
    if len(calls) == 1:
      raise RuntimeError('UNAVAILABLE: TPU worker preempted')
    return {'eval/loss': 0.5}

  monkeypatch.setattr(train_lib, 'run_training', fake_run_training)
  rc = cli.main([
      'train', '--out_dir', str(tmp_path / 'cli_out'),
      '--train_path', 'unused', '--eval_path', 'unused',
      '--num_epochs', '1',
  ])
  assert rc == 0
  assert len(calls) == 2


def test_pool_worker_never_raises_and_leaks_nothing(tmp_path):
  """A failing featurization task must not raise (a raising starmap
  task would discard sibling results, orphaning their shm segments)."""
  import glob

  from deepconsensus_tpu.inference import runner as runner_lib

  before = set(glob.glob('/dev/shm/*'))
  status, payload = runner_lib._pool_worker(
      ('malformed', 'zmw', 'input'), runner_lib.InferenceOptions()
  )
  assert status == 'error'
  assert 'Traceback' in payload
  assert set(glob.glob('/dev/shm/*')) == before


def test_warm_start_into_fresh_dir_from_full_checkpoint(
    tmp_path, testdata_dir):
  """Warm-starting a FRESH run from a full TrainState checkpoint
  (params + opt_state + step, what Trainer.save_checkpoint writes)
  must restore the params subtree rather than raising an orbax
  structure mismatch on the extra collections."""
  params = tiny_params()
  src_dir = str(tmp_path / 'teacher_run')
  patterns = [str(testdata_dir / 'human_1m/tf_examples/eval/*')]
  train_lib.run_training(
      params=params, out_dir=src_dir,
      train_patterns=patterns, eval_patterns=patterns,
      num_epochs=1, eval_every=10**9,
  )
  def _step(name):
    try:
      return int(name.split('-')[1])
    except (IndexError, ValueError):  # orbax tmp dirs etc.
      return None

  ckpt_dir = os.path.join(src_dir, 'checkpoints')
  last = max(
      s for s in (_step(n) for n in os.listdir(ckpt_dir)
                  if n.startswith('checkpoint-'))
      if s is not None
  )
  warm = os.path.join(ckpt_dir, f'checkpoint-{last}')

  fresh_dir = str(tmp_path / 'warm_fresh')
  train_lib.run_training(
      params=params, out_dir=fresh_dir,
      train_patterns=patterns, eval_patterns=patterns,
      num_epochs=1, eval_every=10**9, warm_start=warm,
  )
  fresh_ckpts = os.listdir(os.path.join(fresh_dir, 'checkpoints'))
  assert any(n.startswith('checkpoint-') for n in fresh_ckpts)
