"""Unit tests for the vectorized multi-read spacing model."""
import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.preprocess.alignment import AlignedRead
from deepconsensus_tpu.preprocess.spacing import space_out_reads

C = constants.Cigar
M, I = int(C.MATCH), int(C.INS)


def make_read(seq, cigar_ops, name='m/1/0', truth_range=None, ccs_start=0):
  bases = np.array(
      [constants.SEQ_VOCAB.index(c) for c in seq], dtype=np.uint8
  )
  cigar = np.array(cigar_ops, dtype=np.uint8)
  is_ref = np.array([op != I for op in cigar_ops])
  ccs_idx = np.where(is_ref, ccs_start + np.cumsum(is_ref) - 1, -1).astype(
      np.int64
  )
  return AlignedRead(
      name=name,
      bases=bases,
      cigar=cigar,
      pw=np.arange(1, len(seq) + 1, dtype=np.int32),
      ip=np.arange(1, len(seq) + 1, dtype=np.int32),
      sn=np.ones(4, dtype=np.float32),
      strand=constants.Strand.FORWARD,
      ccs_idx=ccs_idx,
      truth_range=truth_range,
  )


def spaced_strings(reads):
  return [str(r) for r in space_out_reads(reads)]


def test_no_insertions_identity():
  r1 = make_read('ACGT', [M] * 4)
  r2 = make_read('AC T', [M] * 4)
  out = spaced_strings([r1, r2])
  assert out == ['ACGT', 'AC T']


def test_single_insertion_creates_column():
  # r1 has an insertion after its first base; r2 gets a gap there.
  r1 = make_read('ACGT', [M, I, M, M])
  r2 = make_read('AGT', [M, M, M])
  out = spaced_strings([r1, r2])
  assert out == ['ACGT', 'A GT']


def test_insertions_left_aligned_within_block():
  r1 = make_read('ATTG', [M, I, I, M])  # two insertions
  r2 = make_read('ACG', [M, I, M])      # one insertion, same boundary
  r3 = make_read('AG', [M, M])
  out = spaced_strings([r1, r2, r3])
  assert out == ['ATTG', 'AC G', 'A  G']


def test_insertion_at_start():
  r1 = make_read('TAC', [I, M, M])
  r2 = make_read('AC', [M, M])
  out = spaced_strings([r1, r2])
  assert out == ['TAC', ' AC']


def test_trailing_insertions():
  r1 = make_read('ACT', [M, M, I])
  r2 = make_read('AC', [M, M])
  out = spaced_strings([r1, r2])
  assert out == ['ACT', 'AC ']


def test_pw_values_follow_bases():
  r1 = make_read('ACGT', [M, I, M, M])
  r2 = make_read('AGT', [M, M, M])
  spaced = space_out_reads([r1, r2])
  np.testing.assert_array_equal(spaced[0].pw, [1, 2, 3, 4])
  np.testing.assert_array_equal(spaced[1].pw, [1, 0, 2, 3])


def test_ccs_idx_preserved():
  r1 = make_read('ACGT', [M, I, M, M])
  r2 = make_read('AGT', [M, M, M])
  spaced = space_out_reads([r1, r2])
  np.testing.assert_array_equal(spaced[0].ccs_idx, [0, -1, 1, 2])
  np.testing.assert_array_equal(spaced[1].ccs_idx, [0, -1, 1, 2])


def test_label_insertions_do_not_create_columns():
  # Label (truth) insertions are consumed eagerly; subreads don't space.
  sub = make_read('ACG', [M, M, M])
  ccs = make_read('ACG', [M, M, M])
  label = make_read(
      'ATCG', [M, I, M, M], truth_range={'contig': 'c', 'begin': 0, 'end': 4}
  )
  spaced = space_out_reads([sub, ccs, label])
  # Subreads get no new columns, but the pileup width grows to fit the
  # label, whose eager insertion consumption advances it one column
  # past the others (reference state machine: pre_lib.py:200-216).
  assert [str(r) for r in spaced[:2]] == ['ACG ', 'ACG ']
  assert str(spaced[2]) == 'ATCG'
  # Truth positions attach to read-advancing (M/I) columns only.
  np.testing.assert_array_equal(spaced[2].truth_idx, [0, 1, 2, 3])


def test_label_with_subread_insertions():
  sub = make_read('ATCG', [M, I, M, M])
  ccs = make_read('ACG', [M, M, M])
  label = make_read(
      'ACG', [M, M, M], truth_range={'contig': 'c', 'begin': 5, 'end': 8}
  )
  spaced = space_out_reads([sub, ccs, label])
  assert str(spaced[0]) == 'ATCG'
  assert str(spaced[1]) == 'A CG'
  # Label gets a gap through the subread insertion column.
  assert str(spaced[2]) == 'A CG'
  np.testing.assert_array_equal(spaced[2].truth_idx, [5, -1, 6, 7])


def test_all_reads_padded_to_same_width():
  r1 = make_read('ACTTT', [M, M, I, I, I])
  r2 = make_read('AC', [M, M])
  spaced = space_out_reads([r1, r2])
  assert len(spaced[0]) == len(spaced[1]) == 5
