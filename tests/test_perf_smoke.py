"""Perf smoke: the inference forward compiles once per shape.

The whole point of fixed-shape packed batches is that the compiled
forward is reused for every pack; a recompile per featurize batch (or
per ragged tail) would silently erase the pipeline win. Asserted via
JAX's lowering counters, so it runs in seconds on CPU — no timing, no
flakiness.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax._src import test_util as jtu

from deepconsensus_tpu.inference import runner as runner_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib

BATCH = 8


@pytest.fixture(scope='module')
def runner():
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params, is_training=False)
  model = model_lib.get_model(params)
  variables = model.init(
      jax.random.PRNGKey(0),
      jnp.zeros((1, params.total_rows, params.max_length, 1)))
  options = runner_lib.InferenceOptions(batch_size=BATCH)
  return runner_lib.ModelRunner(params, variables, options)


def _rows(runner, n, seed):
  rng = np.random.default_rng(seed)
  params = runner.params
  shape = (n, params.total_rows, params.max_length, 1)
  return rng.integers(0, 5, size=shape).astype(np.float32)


def test_forward_compiles_once_per_shape(runner):
  out = runner.predict(_rows(runner, BATCH, 0))  # pays the one compile
  assert out[0].shape == (BATCH, runner.params.max_length)
  with jtu.count_jit_and_pmap_lowerings() as count:
    # Steady state: full packs AND ragged tails (dispatch pads them to
    # the compiled batch shape) must all hit the same executable.
    for i, n in enumerate((BATCH, BATCH, BATCH // 2, 3, 1)):
      ids, quals = runner.predict(_rows(runner, n, i + 1))
      assert ids.shape == (n, runner.params.max_length)
  assert count[0] == 0, (
      f'{count[0]} re-lowerings in steady state: the forward is being '
      'recompiled per batch instead of reused per shape')
