"""Corruption-fuzz suite for the hardened decode layer.

The invariant (ISSUE 4): over a deterministic mutant corpus per format
(BAM, raw BGZF, TFRecord), every mutant either parses, raises
CorruptInputError (incl. TruncatedBamError), or is skipped under a skip
policy — never any other exception, never an allocation beyond
max_record_bytes (plus interpreter slack), never a hang (per-mutant
alarm). Mutant counts default to 500 per format (acceptance floor) and
are overridable via DCTPU_FUZZ_MUTANTS for quick local runs.

Also holds the end-to-end degradation acceptance test: one surgically
corrupted mid-file record + --on_zmw_error=skip -> exactly that
molecule is dead-lettered, every clean ZMW still polishes.
"""
import json
import os
import signal
import tracemalloc
from contextlib import contextmanager

import numpy as np
import pytest

from deepconsensus_tpu.faults import CorruptInputError
from deepconsensus_tpu.io import bam as bam_lib
from deepconsensus_tpu.io import tfrecord as tfrecord_lib
from deepconsensus_tpu.io import validate as validate_lib
from deepconsensus_tpu.io.bam_writer import BgzfWriter

pytestmark = pytest.mark.resilience

N_MUTANTS = int(os.environ.get('DCTPU_FUZZ_MUTANTS', '500'))
# Tight per-record cap: corpora are tiny, so any decode allocating past
# this is trusting a corrupt length field.
CAP_BYTES = 1 << 20
# Interpreter/numpy slack on top of the cap for the tracemalloc bound.
ALLOC_SLACK = 8 << 20
# Sampling stride for the tracemalloc bound (tracing every mutant would
# triple the suite's runtime for no extra signal).
TRACE_EVERY = 25
PER_MUTANT_TIMEOUT_S = 10.0


@contextmanager
def deadline(seconds: float):
  """Per-mutant hang guard via SIGALRM (CPython honors it between
  bytecodes, which is exactly where a decode loop would spin)."""

  def on_alarm(signum, frame):
    raise TimeoutError('decode exceeded per-mutant deadline')

  previous = signal.signal(signal.SIGALRM, on_alarm)
  signal.setitimer(signal.ITIMER_REAL, seconds)
  try:
    yield
  finally:
    signal.setitimer(signal.ITIMER_REAL, 0)
    signal.signal(signal.SIGALRM, previous)


def _drain_bam(path: str, skip: bool) -> int:
  """Consumes every record; returns the count. CorruptInputError is the
  only exception allowed to escape (and under skip, only the
  non-recoverable kind)."""
  n = 0
  reader = bam_lib.BamReader(path, use_native=False,
                             max_record_bytes=CAP_BYTES,
                             skip_corrupt_records=skip)
  with reader:
    for _ in reader:
      n += 1
  return n


def _fuzz_loop(tmp_path, src: bytes, run_one, protect_prefix: int = 0,
               seed: int = 1234):
  """Shared harness: for every mutant, run_one(path) must either return
  or raise CorruptInputError; allocation and wall-clock are bounded."""
  from scripts import inject_faults

  n_parsed = n_rejected = 0
  mutant_path = str(tmp_path / 'mutant.bin')
  for i, mode, data in inject_faults.fuzz_mutants(
      src, N_MUTANTS, seed=seed, protect_prefix=protect_prefix):
    with open(mutant_path, 'wb') as f:
      f.write(data)
    trace = (i % TRACE_EVERY) == 0
    if trace:
      tracemalloc.start()
    try:
      with deadline(PER_MUTANT_TIMEOUT_S):
        try:
          run_one(mutant_path)
          n_parsed += 1
        except CorruptInputError:
          n_rejected += 1
        # Anything else (struct.error, ValueError, MemoryError,
        # UnicodeDecodeError, TimeoutError...) propagates and fails
        # the test — that IS the invariant.
    finally:
      if trace:
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        assert peak < CAP_BYTES + ALLOC_SLACK, (
            f'mutant {i} ({mode}) allocated {peak} bytes '
            f'(cap {CAP_BYTES} + slack {ALLOC_SLACK})')
  # A corpus where nothing was ever rejected means the mutator is too
  # weak to exercise the defenses; a corpus where nothing parses means
  # the baseline file itself is broken.
  assert n_rejected > 0
  assert n_parsed + n_rejected == N_MUTANTS


# ----------------------------------------------------------------------
# Per-format fuzz invariants


def test_fuzz_bam_fail_fast(tmp_path, synthetic_bams):
  subreads, _ = synthetic_bams('fuzz_bam', n_zmws=3, n_subreads=2,
                               seq_len=60)
  with open(subreads, 'rb') as f:
    src = f.read()
  _fuzz_loop(tmp_path, src, lambda p: _drain_bam(p, skip=False))


def test_fuzz_bam_skip_policy(tmp_path, synthetic_bams):
  """Same corpus under skip_corrupt_records: recoverable damage is
  swallowed; only stream-level CorruptInputError may escape."""
  subreads, _ = synthetic_bams('fuzz_bam_skip', n_zmws=3, n_subreads=2,
                               seq_len=60)
  with open(subreads, 'rb') as f:
    src = f.read()
  _fuzz_loop(tmp_path, src, lambda p: _drain_bam(p, skip=True),
             seed=4321)


def test_fuzz_bam_uncompressed_records(tmp_path, synthetic_bams):
  """Mutates the DECOMPRESSED BAM byte stream (BGZF container stays
  pristine), so every mutant exercises the record decoder rather than
  dying in gzip. The header prefix is shielded to reach the per-record
  paths."""
  subreads, _ = synthetic_bams('fuzz_bam_raw', n_zmws=3, n_subreads=2,
                               seq_len=60)
  raw = bam_lib.bgzf_decompress_file_py(subreads)
  # Shield magic + l_text so mutants pass the header and hit records.
  protect = 8 + int(np.frombuffer(raw[4:8], dtype='<i4')[0])

  from scripts import inject_faults

  n_parsed = n_rejected = 0
  mutant_path = str(tmp_path / 'mutant.bam')
  for i, mode, data in inject_faults.fuzz_mutants(
      raw, N_MUTANTS, seed=77, protect_prefix=protect):
    writer = BgzfWriter(mutant_path)
    writer.write(data)
    writer.close()
    with deadline(PER_MUTANT_TIMEOUT_S):
      try:
        _drain_bam(mutant_path, skip=(i % 2 == 0))
        n_parsed += 1
      except CorruptInputError:
        n_rejected += 1
  assert n_rejected > 0
  assert n_parsed + n_rejected == N_MUTANTS


def test_fuzz_raw_bgzf(tmp_path):
  """Raw BGZF container fuzz via the pure-Python whole-file
  decompressor (the BamReader fallback's gzip layer)."""
  src_path = str(tmp_path / 'seed.bgzf')
  writer = BgzfWriter(src_path)
  rng = np.random.RandomState(5)
  writer.write(rng.bytes(200_000))
  writer.close()
  with open(src_path, 'rb') as f:
    src = f.read()
  _fuzz_loop(
      tmp_path, src,
      lambda p: bam_lib.bgzf_decompress_file_py(p, max_out=CAP_BYTES))


def test_fuzz_tfrecord(tmp_path, scripts_importable):
  from scripts import inject_faults

  shard = inject_faults.write_synthetic_tfrecords(
      str(tmp_path / 'shards'), n_shards=1, n_examples=24)[0]
  with open(shard, 'rb') as f:
    src = f.read()

  def run_one(path):
    with tfrecord_lib.TFRecordReader(path, compression='GZIP',
                                     check_crc=True,
                                     max_record_bytes=CAP_BYTES) as reader:
      for _ in reader:
        pass

  _fuzz_loop(tmp_path, src, run_one)


def test_fuzz_tfrecord_uncompressed(tmp_path):
  """Uncompressed shard: mutants hit the TFRecord framing itself
  (length caps + unconditional length-CRC), not the gzip layer."""
  shard = str(tmp_path / 'seed.tfrecord')
  rng = np.random.RandomState(11)
  with tfrecord_lib.TFRecordWriter(shard) as writer:
    for _ in range(50):
      writer.write(rng.bytes(int(rng.randint(10, 2000))))
  with open(shard, 'rb') as f:
    src = f.read()

  def run_one(path):
    with tfrecord_lib.TFRecordReader(path,
                                     max_record_bytes=CAP_BYTES) as reader:
      for _ in reader:
        pass

  _fuzz_loop(tmp_path, src, run_one)


# ----------------------------------------------------------------------
# Targeted regressions the fuzzer motivates


def test_tfrecord_length_inflation_never_allocates(tmp_path):
  """A corrupt 8-byte length claiming 2**62 bytes must be rejected by
  the length-CRC check before any allocation — even with
  check_crc=False."""
  shard = str(tmp_path / 'bomb.tfrecord')
  with tfrecord_lib.TFRecordWriter(shard) as writer:
    writer.write(b'payload-one')
  with open(shard, 'r+b') as f:
    f.write((1 << 62).to_bytes(8, 'little'))  # inflate length, stale CRC
  tracemalloc.start()
  try:
    with pytest.raises(CorruptInputError, match='length crc'):
      for _ in tfrecord_lib.TFRecordReader(shard):
        pass
    _, peak = tracemalloc.get_traced_memory()
  finally:
    tracemalloc.stop()
  assert peak < ALLOC_SLACK


def test_tfrecord_crc_valid_oversize_hits_cap(tmp_path):
  """A length over the cap with a VALID crc (attacker fixes the crc)
  still refuses to allocate: the cap check is independent of the CRC."""
  shard = str(tmp_path / 'capped.tfrecord')
  with tfrecord_lib.TFRecordWriter(shard) as writer:
    writer.write(b'x' * 64)
  with open(shard, 'r+b') as f:
    import struct

    header = struct.pack('<Q', 1 << 40)
    f.write(header)
    f.write(struct.pack('<I', tfrecord_lib._masked_crc(header)))
  with pytest.raises(CorruptInputError, match='max_record_bytes'):
    for _ in tfrecord_lib.TFRecordReader(shard,
                                         max_record_bytes=CAP_BYTES):
      pass


def test_bam_block_size_inflation_skips_without_alloc(tmp_path,
                                                      synthetic_bams):
  """block_size inflated to 1 GiB: the reader must consume in bounded
  chunks (no 1 GiB allocation) and raise typed."""
  subreads, _ = synthetic_bams('inflate', n_zmws=2, n_subreads=2,
                               seq_len=60)
  from scripts import inject_faults

  out = str(tmp_path / 'inflated.bam')
  inject_faults.corrupt_bam_record(subreads, out, record_index=1,
                                   mode='block_size_inflate')
  tracemalloc.start()
  try:
    with pytest.raises(CorruptInputError):
      _drain_bam(out, skip=False)
    _, peak = tracemalloc.get_traced_memory()
  finally:
    tracemalloc.stop()
  assert peak < CAP_BYTES + ALLOC_SLACK


@pytest.mark.parametrize('mode', ['read_name_zero', 'read_name_overrun',
                                  'cigar_overrun'])
def test_bam_record_body_damage_is_recoverable(tmp_path, synthetic_bams,
                                               mode):
  """Framing-intact record damage: fail-fast raises a recoverable
  CorruptInputError; skip mode yields every OTHER record."""
  subreads, _ = synthetic_bams(f'body_{mode}', n_zmws=3, n_subreads=2,
                               seq_len=60)
  total = _drain_bam(subreads, skip=False)
  out = str(tmp_path / 'damaged.bam')
  from scripts import inject_faults

  inject_faults.corrupt_bam_record(subreads, out, record_index=2,
                                   mode=mode)
  with pytest.raises(CorruptInputError) as err:
    _drain_bam(out, skip=False)
  assert err.value.recoverable
  assert err.value.path == out
  reader = bam_lib.BamReader(out, use_native=False,
                             skip_corrupt_records=True)
  with reader:
    survivors = sum(1 for _ in reader)
  assert survivors == total - 1
  assert reader.n_corrupt_records == 1


# ----------------------------------------------------------------------
# End-to-end degradation + preflight acceptance


def _run_skip_policy_inference(tmp_path, subreads, ccs):
  """Runs the real inference pipeline (tiny model, no jit) with
  --on_zmw_error=skip over the given pair."""
  from deepconsensus_tpu.inference import runner as runner_lib
  from deepconsensus_tpu.models import config as config_lib

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params, is_training=False)
  options = runner_lib.InferenceOptions(
      batch_size=8, batch_zmws=2, min_quality=0, skip_windows_above=1,
      on_zmw_error='skip', max_record_bytes=CAP_BYTES,
  )
  output = str(tmp_path / 'out.fastq')
  model_runner = runner_lib.ModelRunner(params, {}, options)
  counters = runner_lib.run_inference(subreads, ccs, None, output,
                                      options=options, runner=model_runner)
  return output, counters


def test_corrupt_midfile_record_quarantines_and_run_completes(
    tmp_path, synthetic_bams):
  """ISSUE 4 acceptance: with --on_zmw_error=skip, one corrupt mid-file
  subread record dead-letters its molecule at the decode stage and the
  run completes with output for every clean ZMW."""
  subreads, ccs = synthetic_bams('e2e', n_zmws=5, n_subreads=3,
                                 seq_len=60)
  from scripts import inject_faults

  corrupt = str(tmp_path / 'corrupt_subreads.bam')
  # Record 7 = mid-molecule of ZMW 102 (3 subreads per ZMW).
  inject_faults.corrupt_bam_record(subreads, corrupt, record_index=7,
                                   mode='read_name_overrun')
  output, counters = _run_skip_policy_inference(tmp_path, corrupt, ccs)
  assert counters['n_corrupt_records'] == 1
  # Clean molecules all made it to the output.
  from deepconsensus_tpu.io import fastx

  names = [name for name, _, _ in fastx.read_fastq(output)]
  assert len(names) == 4
  assert not any('/102/' in name for name in names)
  # The poisoned molecule is attributed in the dead-letter sidecar.
  letters = [json.loads(line)
             for line in open(output + '.failed.jsonl')]
  assert len(letters) == 1
  assert letters[0]['stage'] == 'decode'
  assert '102' in (letters[0]['zmw'] or '')


def test_validate_clean_pair_ok(tmp_path, synthetic_bams):
  subreads, ccs = synthetic_bams('validate_clean')
  report = validate_lib.validate_inputs(subreads_to_ccs=subreads,
                                        ccs_bam=ccs)
  assert report['ok'], report
  assert report['n_errors'] == 0
  assert report['pair']['ok']
  for entry in report['files']:
    assert entry['bgzf_eof']
    assert entry['n_records'] > 0


def test_validate_cli_exit_codes_and_json(tmp_path, synthetic_bams,
                                          capsys):
  """dctpu validate: 0 on a clean corpus; nonzero + JSON naming file and
  offset on each mutant class (truncation, record damage, bad CRC)."""
  from scripts import inject_faults

  from deepconsensus_tpu import cli

  subreads, ccs = synthetic_bams('validate_cli')
  assert cli.main(['validate', '--subreads_to_ccs', subreads,
                   '--ccs_bam', ccs]) == 0
  capsys.readouterr()

  # Mutant class 1: truncated tail (missing BGZF EOF).
  truncated = str(tmp_path / 'trunc.bam')
  with open(subreads, 'rb') as f:
    data = f.read()
  with open(truncated, 'wb') as f:
    f.write(data[:len(data) // 2])
  rc = cli.main(['validate', '--subreads_to_ccs', truncated])
  report = json.loads(capsys.readouterr().out)
  assert rc == 1
  assert any(e['file'] == truncated for e in report['files'][0]['errors'])

  # Mutant class 2: framing-intact record damage (file + offset named).
  damaged = str(tmp_path / 'damaged.bam')
  offset = inject_faults.corrupt_bam_record(subreads, damaged,
                                            record_index=3,
                                            mode='cigar_overrun')
  report_path = str(tmp_path / 'report.json')
  rc = cli.main(['validate', '--subreads_to_ccs', damaged,
                 '--report', report_path])
  capsys.readouterr()
  assert rc == 1
  report = json.load(open(report_path))
  entry = report['files'][0]
  assert entry['n_corrupt_records'] == 1
  assert entry['errors'][0]['file'] == damaged
  assert entry['errors'][0]['offset'] == offset

  # Mutant class 3: TFRecord CRC corruption.
  shard = inject_faults.write_synthetic_tfrecords(
      str(tmp_path / 'shards'), n_shards=1, n_examples=8)[0]
  with open(shard, 'rb') as f:
    sdata = bytearray(f.read())
  sdata[len(sdata) // 2] ^= 0xFF
  bad_shard = str(tmp_path / 'bad.tfrecord.gz')
  with open(bad_shard, 'wb') as f:
    f.write(sdata)
  rc = cli.main(['validate', '--tfrecord', bad_shard])
  report = json.loads(capsys.readouterr().out)
  assert rc == 1
  assert report['files'][0]['errors'][0]['file'] == bad_shard


def test_validate_detects_pair_mismatch(tmp_path, synthetic_bams):
  """actc referencing a ccs read that is absent from the ccs BAM."""
  subreads, _ = synthetic_bams('pair_a', n_zmws=4)
  _, other_ccs = synthetic_bams('pair_b', n_zmws=2)
  report = validate_lib.validate_inputs(subreads_to_ccs=subreads,
                                        ccs_bam=other_ccs)
  assert not report['ok']
  assert not report['pair']['ok']
  assert any('absent from the ccs BAM' in e['error']
             for e in report['pair']['errors'])


def test_training_skip_policy_counts_corrupt_records(tmp_path,
                                                     scripts_importable):
  """A corrupt shard under on_shard_error=skip surfaces as both
  n_shard_errors and n_corrupt_records (the faults metrics split,
  train.py merges stream_ds.counters into it)."""
  from scripts import inject_faults

  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models.data import StreamingDataset

  paths = inject_faults.write_synthetic_tfrecords(
      str(tmp_path / 'shards'), n_shards=2, n_examples=32,
      max_passes=5, max_length=20)
  with open(paths[0], 'rb') as f:
    data = bytearray(f.read())
  data[len(data) // 2] ^= 0xFF  # mid-stream BGZF bit flip
  with open(paths[0], 'wb') as f:
    f.write(data)
  params = config_lib.get_config('fc+test')
  with params.unlocked():
    params.max_passes = 5
    params.max_length = 20
  config_lib.finalize_params(params)
  ds = StreamingDataset(patterns=paths, params=params, batch_size=8,
                        buffer_size=16, seed=0, on_shard_error='skip')
  it = iter(ds)
  try:
    batches = [next(it) for _ in range(4)]  # > one pass over the pair
  finally:
    it.close()
  assert all(b['rows'].shape[0] == 8 for b in batches)
  # The flip surfaces as record-local payload corruption, a framing
  # CorruptInputError ending the shard, or both — always attributed.
  assert ds.counters['n_corrupt_records'] >= 1
