"""Fault-injection tests for the self-healing training layer.

Everything runs against synthetic TFRecord shards written by
scripts/inject_faults.write_synthetic_tfrecords (no reference testdata):
checkpoint integrity manifests + quarantine, preemption-safe saves, the
NaN sentinel's rollback, corrupt-shard tolerance, and the crash-loop
breaker in run_training_with_retry.
"""
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu.models import checkpoints as checkpoints_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.models import train as train_lib

pytestmark = pytest.mark.resilience

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
  sys.path.insert(0, _REPO_ROOT)

MAX_PASSES = 5
MAX_LENGTH = 20


@pytest.fixture
def fresh_faults(monkeypatch):
  """Fault hooks are consume-once per process; isolate each test."""
  monkeypatch.setattr(faults_lib, '_fired', set())


@pytest.fixture(scope='module')
def shards(tmp_path_factory):
  from scripts import inject_faults

  d = tmp_path_factory.mktemp('synth_shards')
  return inject_faults.write_synthetic_tfrecords(
      str(d), n_shards=4, n_examples=64,
      max_passes=MAX_PASSES, max_length=MAX_LENGTH,
  )


def tiny_params(**overrides):
  params = config_lib.get_config('fc+test')
  with params.unlocked():
    params.max_passes = MAX_PASSES
    params.max_length = MAX_LENGTH
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.batch_size = 8
    params.warmup_steps = 2
    params.buffer_size = 16
    params.log_every_n_steps = 4
    params.streaming = True
    params.n_examples_train = 64  # 8 steps per "epoch"
    for k, v in overrides.items():
      setattr(params, k, v)
  return params


def ckpt_dir_of(out_dir):
  return os.path.join(out_dir, 'checkpoints')


def list_ckpts(out_dir):
  d = ckpt_dir_of(out_dir)
  return sorted(
      n for n in os.listdir(d)
      if checkpoints_lib.checkpoint_step(n) is not None
  )


def metrics_entries(out_dir, split=None):
  entries = []
  with open(os.path.join(out_dir, 'metrics.jsonl')) as f:
    for line in f:
      e = json.loads(line)
      if split is None or e.get('split') == split:
        entries.append(e)
  return entries


# ----------------------------------------------------------------------
# Checkpoint integrity: manifests, validation, quarantine (unit level)


def _fake_checkpoint(ckpt_root, step, payload=b'x' * 64):
  path = os.path.join(ckpt_root, f'checkpoint-{step}')
  os.makedirs(os.path.join(path, 'sub'))
  with open(os.path.join(path, 'arrays.bin'), 'wb') as f:
    f.write(payload)
  with open(os.path.join(path, 'sub', 'meta.json'), 'w') as f:
    f.write('{}')
  return path


def test_manifest_roundtrip_and_truncation_detected(tmp_path):
  root = str(tmp_path)
  path = _fake_checkpoint(root, 5)
  checkpoints_lib.write_manifest(path, 5, digest='d' * 8)
  ok, reason = checkpoints_lib.validate_checkpoint(path)
  assert ok, reason
  manifest = checkpoints_lib.read_manifest(path)
  assert manifest['step'] == 5
  assert manifest['files']['arrays.bin'] == 64

  with open(os.path.join(path, 'arrays.bin'), 'r+b') as f:
    f.truncate(10)
  ok, reason = checkpoints_lib.validate_checkpoint(path)
  assert not ok and 'size mismatch' in reason

  os.unlink(checkpoints_lib.manifest_path(path))
  ok, reason = checkpoints_lib.validate_checkpoint(path)
  assert not ok and 'manifest' in reason


def test_latest_valid_quarantines_corrupt_newest(tmp_path):
  root = str(tmp_path)
  good = _fake_checkpoint(root, 2)
  checkpoints_lib.write_manifest(good, 2)
  bad = _fake_checkpoint(root, 4)
  checkpoints_lib.write_manifest(bad, 4)
  with open(os.path.join(bad, 'arrays.bin'), 'r+b') as f:
    f.truncate(3)

  assert checkpoints_lib.latest_valid_checkpoint(root) == good
  qdir = os.path.join(root, checkpoints_lib.QUARANTINE_DIRNAME)
  assert os.path.isdir(os.path.join(qdir, 'checkpoint-4'))
  assert os.path.exists(os.path.join(qdir, 'checkpoint-4.reason.txt'))
  assert not os.path.exists(bad)
  # Second scan is stable: the quarantined dir never reappears.
  assert checkpoints_lib.latest_valid_checkpoint(root) == good


def test_uncommitted_newest_is_quarantined(tmp_path):
  """A directory without a committed manifest (crash between orbax
  finishing and the manifest write, or mid-save) must not be resumed
  when a committed sibling exists."""
  root = str(tmp_path)
  good = _fake_checkpoint(root, 8)
  checkpoints_lib.write_manifest(good, 8)
  _fake_checkpoint(root, 12)  # no manifest: save never committed

  assert checkpoints_lib.latest_valid_checkpoint(root) == good
  qdir = os.path.join(root, checkpoints_lib.QUARANTINE_DIRNAME)
  assert os.path.isdir(os.path.join(qdir, 'checkpoint-12'))


def test_legacy_dir_without_manifests_uses_newest(tmp_path):
  """Pre-manifest checkpoint dirs resume with the old newest-step rule
  instead of quarantining a whole run's history."""
  root = str(tmp_path)
  _fake_checkpoint(root, 2)
  newest = _fake_checkpoint(root, 4)
  assert checkpoints_lib.latest_valid_checkpoint(root) == newest
  assert not os.path.exists(
      os.path.join(root, checkpoints_lib.QUARANTINE_DIRNAME))
  assert checkpoints_lib.latest_valid_step(root) == 4


def test_latest_valid_step_is_read_only(tmp_path):
  root = str(tmp_path)
  good = _fake_checkpoint(root, 2)
  checkpoints_lib.write_manifest(good, 2)
  bad = _fake_checkpoint(root, 4)
  checkpoints_lib.write_manifest(bad, 4)
  with open(os.path.join(bad, 'arrays.bin'), 'r+b') as f:
    f.truncate(1)
  assert checkpoints_lib.latest_valid_step(root) == 2
  assert os.path.exists(bad)  # not quarantined by the read-only probe


def test_load_missing_checkpoint_names_path(tmp_path):
  missing = str(tmp_path / 'no' / 'such' / 'checkpoint-3')
  with pytest.raises(FileNotFoundError, match='checkpoint-3'):
    checkpoints_lib.load_params(missing)
  with pytest.raises(FileNotFoundError, match='checkpoint-3'):
    checkpoints_lib.load_full_state(missing)


def test_tree_digest_sensitive_to_values():
  tree = {'a': np.arange(8, dtype=np.float32), 'b': np.zeros(3)}
  d1 = checkpoints_lib.tree_digest(tree)
  tree['a'] = tree['a'] + 1
  assert checkpoints_lib.tree_digest(tree) != d1


def test_save_checkpoint_commits_manifest_and_digest(tmp_path):
  params = tiny_params()
  out_dir = str(tmp_path / 'save')
  trainer = train_lib.Trainer(params=params, out_dir=out_dir)
  state = trainer.init_state(steps_total=8)
  path = trainer.save_checkpoint(state, 0, {})
  ok, reason = checkpoints_lib.validate_checkpoint(path)
  assert ok, reason
  assert checkpoints_lib.verify_digest(path)
  assert trainer.latest_valid_checkpoint() == path


# ----------------------------------------------------------------------
# End-to-end recovery paths (in-process training on synthetic shards)


@pytest.mark.slow


def test_resume_skips_truncated_checkpoint(tmp_path, shards):
  from scripts import inject_faults

  params = tiny_params()
  out_dir = str(tmp_path / 'resume')
  train_lib.run_training(
      params=params, out_dir=out_dir, train_patterns=shards,
      eval_patterns=shards, num_epochs=2, eval_every=4,
  )
  assert list_ckpts(out_dir) == [
      'checkpoint-12', 'checkpoint-16', 'checkpoint-4', 'checkpoint-8'
  ]
  newest = os.path.join(ckpt_dir_of(out_dir), 'checkpoint-16')
  inject_faults.corrupt_checkpoint(newest, mode='truncate')

  m = train_lib.run_training(
      params=params, out_dir=out_dir, train_patterns=shards,
      eval_patterns=shards, num_epochs=3, eval_every=4,
  )
  assert np.isfinite(m['eval/loss'])
  qdir = os.path.join(ckpt_dir_of(out_dir),
                      checkpoints_lib.QUARANTINE_DIRNAME)
  assert os.path.isdir(os.path.join(qdir, 'checkpoint-16'))
  # Resumed from checkpoint-12 and trained through the 3-epoch budget.
  assert 'checkpoint-24' in list_ckpts(out_dir)
  steps = [e['step'] for e in metrics_entries(out_dir, 'train')]
  # A restart from step 0 would log step 4 a second time.
  assert steps.count(4) == 1
  assert 24 in steps


@pytest.mark.slow


def test_nan_sentinel_rolls_back_and_dead_letters(
    tmp_path, shards, monkeypatch, fresh_faults):
  params = tiny_params(nan_sentinel_steps=1, track_window_ids=True)
  out_dir = str(tmp_path / 'nan')
  monkeypatch.setenv(faults_lib.ENV_NAN_AT_STEP, '6')
  m = train_lib.run_training(
      params=params, out_dir=out_dir, train_patterns=shards,
      eval_patterns=shards, num_epochs=2, eval_every=4,
  )
  assert np.isfinite(m['eval/loss'])
  # 16 batches; steps 1..6 (6 poisoned), detected during iteration 7,
  # rolled back to checkpoint-4, remaining 9 batches run steps 5..13.
  assert 'checkpoint-13' in list_ckpts(out_dir)
  letters = faults_lib.read_dead_letters(
      os.path.join(out_dir, 'training.failed.jsonl'))
  assert letters and letters[0]['action'] == 'rollback'
  assert letters[0]['step'] == 6
  ids = letters[0]['window_ids']
  assert len(ids) == params.batch_size
  assert all(i.startswith('syn/') for i in ids)
  faults = metrics_entries(out_dir, 'faults')[-1]
  assert faults['n_nonfinite_steps'] >= 1
  assert faults['n_nan_rollbacks'] == 1


@pytest.mark.slow


def test_nan_sentinel_never_checkpoints_contaminated_state(
    tmp_path, shards, monkeypatch, fresh_faults):
  # NaN at step 6 with the default 3-step sentinel: the step-8 eval
  # boundary arrives while the state is contaminated but the verdict
  # is still pending (verdicts read one step late). The boundary must
  # force-resolve the verdict and skip the save — a poisoned
  # checkpoint-8 would otherwise become the "last valid checkpoint"
  # the rollback restores, and the run would exhaust its rollback
  # budget ping-ponging on NaN weights (caught by the CLI drive).
  params = tiny_params(nan_sentinel_steps=3, nan_max_rollbacks=2)
  out_dir = str(tmp_path / 'nan_boundary')
  monkeypatch.setenv(faults_lib.ENV_NAN_AT_STEP, '6')
  m = train_lib.run_training(
      params=params, out_dir=out_dir, train_patterns=shards,
      eval_patterns=shards, num_epochs=2, eval_every=4,
  )
  assert np.isfinite(m['eval/loss'])
  faults = metrics_entries(out_dir, 'faults')[-1]
  assert faults['n_nan_rollbacks'] == 1
  assert faults['n_nonfinite_steps'] == 3
  # Rolled back from step 8 to checkpoint-4 (16-batch budget, 8 spent,
  # remaining 8 land on steps 5..12); the surviving checkpoints all
  # hold finite weights.
  assert 'checkpoint-12' in list_ckpts(out_dir)
  letters = faults_lib.read_dead_letters(
      os.path.join(out_dir, 'training.failed.jsonl'))
  assert [l['action'] for l in letters] == [
      'recorded', 'recorded', 'rollback']


def test_nan_sentinel_without_checkpoint_raises_permanent(
    tmp_path, shards, monkeypatch, fresh_faults):
  """Divergence before the first checkpoint has nothing to roll back
  to: the error must be permanent (no retry loop on a diverged run)."""
  params = tiny_params(nan_sentinel_steps=1)
  monkeypatch.setenv(faults_lib.ENV_NAN_AT_STEP, '2')
  with pytest.raises(faults_lib.NonFiniteTrainingError):
    train_lib.run_training(
        params=params, out_dir=str(tmp_path / 'nan2'),
        train_patterns=shards, eval_patterns=shards,
        num_epochs=1, eval_every=10**9,
    )
  err = 'NonFiniteTrainingError: training diverged'
  assert faults_lib.classify_error(err) == faults_lib.FaultKind.PERMANENT


@pytest.mark.slow


def test_sigterm_checkpoints_and_exits_cleanly(
    tmp_path, shards, monkeypatch, fresh_faults):
  params = tiny_params()
  out_dir = str(tmp_path / 'preempt')
  monkeypatch.setenv(faults_lib.ENV_SIGTERM_AT_STEP, '5')
  before = signal.getsignal(signal.SIGTERM)
  m = train_lib.run_training(
      params=params, out_dir=out_dir, train_patterns=shards,
      eval_patterns=shards, num_epochs=2, eval_every=10**9,
  )
  assert m == {'preempted': 1.0, 'stop_step': 5.0}
  # The emergency save is a committed, resumable checkpoint.
  path = os.path.join(ckpt_dir_of(out_dir), 'checkpoint-5')
  ok, reason = checkpoints_lib.validate_checkpoint(path)
  assert ok, reason
  # Handlers restored after the run.
  assert signal.getsignal(signal.SIGTERM) == before
  # A restart resumes from the emergency checkpoint and completes.
  m2 = train_lib.run_training(
      params=params, out_dir=out_dir, train_patterns=shards,
      eval_patterns=shards, num_epochs=2, eval_every=10**9,
  )
  assert np.isfinite(m2['eval/loss'])
  assert 'checkpoint-16' in list_ckpts(out_dir)


# ----------------------------------------------------------------------
# Corrupt-shard tolerance (StreamingDataset --on_shard_error)


def _truncate(path, keep=40):
  with open(path, 'r+b') as f:
    f.truncate(keep)


@pytest.fixture
def shards_one_corrupt(tmp_path):
  # 4 shards so the workers=2 round-robin assignment gives the corrupt
  # shard's owner a good shard too (a worker whose ENTIRE subset is
  # undecodable exits by design, even under skip).
  from scripts import inject_faults

  paths = inject_faults.write_synthetic_tfrecords(
      str(tmp_path / 'mixed'), n_shards=4, n_examples=64,
      max_passes=MAX_PASSES, max_length=MAX_LENGTH,
  )
  _truncate(paths[1])
  return paths


def _drain(ds, n):
  it = iter(ds)
  try:
    return [next(it) for _ in range(n)]
  finally:
    it.close()


def test_corrupt_shard_fails_by_default(shards_one_corrupt):
  params = tiny_params()
  ds = data_lib.StreamingDataset(
      patterns=shards_one_corrupt, params=params, batch_size=8,
      buffer_size=16, seed=0,
  )
  with pytest.raises(Exception, match='end-of-stream|truncated'):
    _drain(ds, 20)


def test_corrupt_shard_skipped_serial(shards_one_corrupt):
  params = tiny_params()
  ds = data_lib.StreamingDataset(
      patterns=shards_one_corrupt, params=params, batch_size=8,
      buffer_size=16, seed=0, on_shard_error='skip',
  )
  batches = _drain(ds, 12)  # > one epoch of the three good shards
  assert all(b['rows'].shape[0] == 8 for b in batches)
  assert ds.counters['n_shard_errors'] >= 1


def test_corrupt_shard_skipped_with_workers(shards_one_corrupt):
  params = tiny_params()
  ds = data_lib.StreamingDataset(
      patterns=shards_one_corrupt, params=params, batch_size=8,
      buffer_size=16, seed=0, workers=2, on_shard_error='skip',
  )
  batches = _drain(ds, 12)
  assert all(b['rows'].shape[0] == 8 for b in batches)
  assert ds.counters['n_shard_errors'] >= 1


def test_per_worker_decode_counters_cover_all_workers(shards):
  """Every worker's parses land in its own n_parsed_worker_N counter —
  the evidence bench_loader.py uses to prove the decode split."""
  params = tiny_params()
  ds = data_lib.StreamingDataset(
      patterns=shards, params=params, batch_size=8,
      buffer_size=16, seed=0, workers=2,
  )
  _drain(ds, 12)
  per_worker = {k: v for k, v in ds.counters.items()
                if k.startswith('n_parsed_worker_')}
  assert set(per_worker) == {'n_parsed_worker_0', 'n_parsed_worker_1'}
  assert all(v > 0 for v in per_worker.values())


def test_all_shards_corrupt_raises_even_under_skip(tmp_path):
  from scripts import inject_faults

  paths = inject_faults.write_synthetic_tfrecords(
      str(tmp_path / 'allbad'), n_shards=2, n_examples=16,
      max_passes=MAX_PASSES, max_length=MAX_LENGTH,
  )
  for p in paths:
    _truncate(p)
  params = tiny_params()
  ds = data_lib.StreamingDataset(
      patterns=paths, params=params, batch_size=8, buffer_size=16,
      seed=0, on_shard_error='skip',
  )
  with pytest.raises(RuntimeError, match='every shard failed'):
    _drain(ds, 1)


def test_worker_crash_names_owned_shards(shards, monkeypatch, tmp_path):
  """A SIGKILLed shard reader must be reported with the exact shard
  paths it owned, so the operator can bisect to the corrupt file."""
  params = tiny_params()
  monkeypatch.setenv(faults_lib.ENV_KILL_SHARD_READER, 'shard-00001')
  monkeypatch.setenv(faults_lib.ENV_KILL_TOKEN,
                     str(tmp_path / 'kill.token'))
  ds = data_lib.StreamingDataset(
      patterns=shards, params=params, batch_size=8, buffer_size=16,
      seed=0, workers=2,
  )
  with pytest.raises(RuntimeError) as err:
    _drain(ds, 50)
  msg = str(err.value)
  assert 'owned shards' in msg
  assert 'shard-00001' in msg


def test_abandoned_iterator_stops_workers(shards):
  """Regression: closing/abandoning the iterator must stop the reader
  machinery (workers + producer thread), not leak it into the next
  retry attempt."""
  import multiprocessing

  params = tiny_params()
  ds = data_lib.StreamingDataset(
      patterns=shards, params=params, batch_size=8, buffer_size=16,
      seed=0, workers=2,
  )
  it = iter(ds)
  assert next(it)['rows'].shape[0] == 8
  it.close()
  leftover = [p for p in multiprocessing.active_children()
              if p.is_alive()]
  assert not leftover


def test_training_survives_corrupt_shard_with_skip(
    tmp_path, shards_one_corrupt):
  """Acceptance demo (c): a corrupt shard under --on_shard_error=skip
  ends at the expected step with the skip counted in the summary."""
  params = tiny_params(on_shard_error='skip', n_examples_train=32)
  out_dir = str(tmp_path / 'skiprun')
  m = train_lib.run_training(
      params=params, out_dir=out_dir, train_patterns=shards_one_corrupt,
      eval_patterns=[shards_one_corrupt[0], shards_one_corrupt[2]],
      num_epochs=2, eval_every=10**9,
  )
  assert np.isfinite(m['eval/loss'])
  assert 'checkpoint-8' in list_ckpts(out_dir)  # 2 * 32/8 steps
  faults = metrics_entries(out_dir, 'faults')[-1]
  assert faults['n_shard_errors'] >= 1


# ----------------------------------------------------------------------
# Crash-loop breaker + retry taxonomy


def test_crash_loop_breaker_aborts_stalled_restarts(monkeypatch, tmp_path):
  calls = []

  def fake_run_training(*args, **kwargs):
    calls.append(1)
    raise RuntimeError('UNAVAILABLE: TPU worker restarted')

  monkeypatch.setattr(train_lib, 'run_training', fake_run_training)
  monkeypatch.setattr(train_lib.time, 'sleep', lambda s: None)
  with pytest.raises(faults_lib.CrashLoopError, match='resume step'):
    train_lib.run_training_with_retry(out_dir=str(tmp_path / 'loop'))
  # 1 initial + max_stalled_restarts retries without progress.
  assert len(calls) == 4


def test_retry_continues_while_resume_step_advances(monkeypatch, tmp_path):
  calls = []
  steps = iter([4, 8, 12, 16, 20, 24])

  def fake_run_training(*args, **kwargs):
    calls.append(1)
    if len(calls) <= 6:
      raise RuntimeError('UNAVAILABLE: preempted')
    return {'eval/loss': 0.1}

  monkeypatch.setattr(train_lib, 'run_training', fake_run_training)
  monkeypatch.setattr(train_lib.time, 'sleep', lambda s: None)
  monkeypatch.setattr(
      train_lib.checkpoints_lib, 'latest_valid_step',
      lambda d: next(steps, 24),
  )
  out = train_lib.run_training_with_retry(out_dir=str(tmp_path / 'adv'))
  assert out == {'eval/loss': 0.1}
  assert len(calls) == 7  # breaker never tripped


def test_retry_backoff_is_exponential(monkeypatch, tmp_path):
  delays = []

  def fake_run_training(*args, **kwargs):
    if len(delays) < 3:
      raise RuntimeError('UNAVAILABLE: flapping')
    return {}

  monkeypatch.setattr(train_lib, 'run_training', fake_run_training)
  monkeypatch.setattr(train_lib.time, 'sleep', delays.append)
  train_lib.run_training_with_retry(backoff_base=0.5, backoff_max=64.0)
  assert delays == [0.5, 1.0, 2.0]


def test_nonfinite_error_not_retried(monkeypatch):
  calls = []

  def fake_run_training(*args, **kwargs):
    calls.append(1)
    raise faults_lib.NonFiniteTrainingError('training diverged')

  monkeypatch.setattr(train_lib, 'run_training', fake_run_training)
  with pytest.raises(faults_lib.NonFiniteTrainingError):
    train_lib.run_training_with_retry()
  assert len(calls) == 1


# ----------------------------------------------------------------------
# Acceptance demo (a): SIGKILL mid-run, truncate the newest checkpoint,
# restart resumes from the previous valid one and finishes.


@pytest.mark.slow
def test_subprocess_kill_truncate_resume(tmp_path):
  from scripts import inject_faults

  repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  shard_dir = str(tmp_path / 'shards')
  inject_faults.write_synthetic_tfrecords(
      shard_dir, n_shards=2, n_examples=64,
      max_passes=MAX_PASSES, max_length=MAX_LENGTH,
  )
  out_dir = str(tmp_path / 'run')
  cmd = [
      sys.executable, '-m', 'deepconsensus_tpu.cli', 'train',
      '--config', 'fc+test', '--out_dir', out_dir,
      '--train_path', os.path.join(shard_dir, 'shard-*.tfrecord.gz'),
      '--eval_path', os.path.join(shard_dir, 'shard-*.tfrecord.gz'),
      '--num_epochs', '4', '--batch_size', '8',
      '--set', 'max_passes=5', '--set', 'max_length=20',
      '--set', 'dtype=float32', '--set', 'warmup_steps=2',
      '--set', 'eval_every_n_steps=4', '--set', 'log_every_n_steps=4',
  ]
  env = dict(
      os.environ,
      JAX_PLATFORMS='cpu',
      PYTHONPATH=repo_root,
      **{
          faults_lib.ENV_KILL_TRAIN_AT_STEP: '10',
          faults_lib.ENV_KILL_TOKEN: str(tmp_path / 'kill.token'),
      },
  )
  first = subprocess.run(cmd, env=env, cwd=repo_root,
                         capture_output=True, text=True, timeout=300)
  assert first.returncode == -signal.SIGKILL, first.stderr[-2000:]
  # 64 examples / batch 8 = 8 steps/epoch; killed at step 10 after the
  # saves at 4 and 8.
  assert {'checkpoint-4', 'checkpoint-8'} <= set(list_ckpts(out_dir))

  inject_faults.corrupt_checkpoint(
      os.path.join(ckpt_dir_of(out_dir), 'checkpoint-8'),
      mode='truncate',
  )
  second = subprocess.run(cmd, env=env, cwd=repo_root,
                          capture_output=True, text=True, timeout=300)
  assert second.returncode == 0, second.stderr[-2000:]
  qdir = os.path.join(ckpt_dir_of(out_dir),
                      checkpoints_lib.QUARANTINE_DIRNAME)
  assert os.path.isdir(os.path.join(qdir, 'checkpoint-8'))
  # Resumed from checkpoint-4 and ran out the 4-epoch (32-step) budget.
  ckpts = list_ckpts(out_dir)
  assert 'checkpoint-32' in ckpts
  # The restart re-saves a FRESH checkpoint-8 (resuming from 4 passes
  # the step-8 eval boundary again); it must validate, unlike the
  # truncated original now in quarantine.
  ok, reason = checkpoints_lib.validate_checkpoint(
      os.path.join(ckpt_dir_of(out_dir), 'checkpoint-8'))
  assert ok, reason
  train_steps = [e['step'] for e in metrics_entries(out_dir, 'train')]
  # A restart from step 0 would log step 4 a second time.
  assert train_steps.count(4) == 1
  assert 32 in train_steps
