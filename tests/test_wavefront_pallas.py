"""Pallas wavefront scorer vs the lax.scan formulation (interpret)."""
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.ops import wavefront, wavefront_pallas


def random_costs(rng, b=8, m=20, n=20):
  subs = jnp.asarray(rng.uniform(0, 5, size=(b, m, n)).astype(np.float32))
  ins = jnp.asarray(rng.uniform(0, 5, size=(b, n)).astype(np.float32))
  lens = jnp.asarray(rng.integers(1, m + 1, size=b).astype(np.int32))
  return subs, ins, lens


@pytest.mark.parametrize('loss_reg', [None, 0.5])
@pytest.mark.parametrize('seed', range(3))
def test_pallas_scorer_matches_scan(seed, loss_reg):
  rng = np.random.default_rng(seed)
  subs, ins, lens = random_costs(rng)
  import jax

  if loss_reg is None:
    minop = lambda t: jnp.min(t, axis=0)
  else:
    # Stable soft-min, matching losses.AlignmentLoss's minop.
    minop = lambda t: -loss_reg * jax.nn.logsumexp(-t / loss_reg, axis=0)
  want = wavefront.alignment_scan(subs, ins, jnp.float32(3.0), lens, minop)
  got = wavefront_pallas.alignment_scores(
      subs, ins, 3.0, lens, loss_reg=loss_reg, interpret=True
  )
  np.testing.assert_allclose(
      np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-4
  )


def test_pallas_scorer_non_divisible_batch():
  rng = np.random.default_rng(9)
  subs, ins, lens = random_costs(rng, b=6)
  want = wavefront.alignment_scan(
      subs, ins, jnp.float32(2.0), lens, lambda t: jnp.min(t, axis=0)
  )
  got = wavefront_pallas.alignment_scores(
      subs, ins, 2.0, lens, interpret=True
  )
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


@pytest.mark.parametrize('loss_reg', [0.1, 1.0])
@pytest.mark.slow
def test_pallas_vjp_grads_match_scan(loss_reg):
  """Custom-VJP backward kernel vs jax.grad of the scan DP."""
  import jax

  rng = np.random.default_rng(3)
  subs, ins, lens = random_costs(rng, b=8, m=14, n=14)
  minop = lambda t: -loss_reg * jax.nn.logsumexp(-t / loss_reg, axis=0)

  def scan_loss(subs, ins):
    return jnp.sum(
        wavefront.alignment_scan(subs, ins, jnp.float32(3.0), lens, minop)
    )

  def pallas_loss(subs, ins):
    return jnp.sum(
        wavefront_pallas.alignment_scores_vjp(
            subs, ins, lens, 3.0, loss_reg, interpret=True
        )
    )

  want_val, (want_ds, want_di) = jax.value_and_grad(
      scan_loss, argnums=(0, 1)
  )(subs, ins)
  got_val, (got_ds, got_di) = jax.value_and_grad(
      pallas_loss, argnums=(0, 1)
  )(subs, ins)
  np.testing.assert_allclose(
      np.asarray(got_val), np.asarray(want_val), rtol=1e-5
  )
  np.testing.assert_allclose(
      np.asarray(got_ds), np.asarray(want_ds), rtol=1e-4, atol=1e-5
  )
  np.testing.assert_allclose(
      np.asarray(got_di), np.asarray(want_di), rtol=1e-4, atol=1e-5
  )


@pytest.mark.slow
def test_pallas_vjp_hard_min_grads():
  """Hard-min (loss_reg=None) grads match the scan DP's subgradient."""
  import jax

  rng = np.random.default_rng(11)
  subs, ins, lens = random_costs(rng, b=4, m=10, n=10)
  minop = lambda t: jnp.min(t, axis=0)

  def scan_loss(subs, ins):
    return jnp.sum(
        wavefront.alignment_scan(subs, ins, jnp.float32(2.0), lens, minop)
    )

  def pallas_loss(subs, ins):
    return jnp.sum(
        wavefront_pallas.alignment_scores_vjp(
            subs, ins, lens, 2.0, None, interpret=True
        )
    )

  want_ds, want_di = jax.grad(scan_loss, argnums=(0, 1))(subs, ins)
  got_ds, got_di = jax.grad(pallas_loss, argnums=(0, 1))(subs, ins)
  np.testing.assert_allclose(
      np.asarray(got_ds), np.asarray(want_ds), rtol=1e-4, atol=1e-6
  )
  np.testing.assert_allclose(
      np.asarray(got_di), np.asarray(want_di), rtol=1e-4, atol=1e-6
  )


@pytest.mark.slow
def test_alignment_loss_pallas_path_trains():
  """AlignmentLoss(use_pallas=True) values + grads match the scan path."""
  import jax

  from deepconsensus_tpu.models import losses as losses_lib

  rng = np.random.default_rng(7)
  b, m, vocab = 8, 12, 5
  y_true = jnp.asarray(rng.integers(0, vocab, size=(b, m)), jnp.int32)
  logits = jnp.asarray(
      rng.normal(size=(b, m, vocab)).astype(np.float32)
  )
  y_pred = jax.nn.softmax(logits)

  loss_scan = losses_lib.AlignmentLoss(del_cost=10.0, loss_reg=0.1)
  loss_pallas = losses_lib.AlignmentLoss(
      del_cost=10.0, loss_reg=0.1, use_pallas=True
  )

  def f_scan(y_pred):
    return loss_scan(y_true, y_pred)

  def f_pallas(y_pred):
    return loss_pallas(y_true, y_pred)

  want, want_g = jax.value_and_grad(f_scan)(y_pred)
  got, got_g = jax.value_and_grad(f_pallas)(y_pred)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)
  np.testing.assert_allclose(
      np.asarray(got_g), np.asarray(want_g), rtol=1e-4, atol=1e-5
  )


def test_auto_unroll_respects_vmem_budget():
  """Unroll scales down with batch/width so streamed blocks stay inside
  the VMEM budget (a fixed unroll=8 would overflow at train batch 1024)."""
  from deepconsensus_tpu.ops import wavefront_pallas as wp

  # Small problems keep the requested unroll.
  assert wp._auto_unroll(8, 64, 2 * 24 + 1) == 8
  # Production-ish train shapes must shrink: at B=1024, m=121 the
  # double-buffered subs+ins stream is ~2 MB per diagonal (+1 MB of
  # emitted rows in the recompute pass, ~3 MB more in the 6-stream
  # reverse sweep), so 8 diagonals would blow the ~8 MB budget.
  m, b = 121, 1024
  fwd = wp._auto_unroll(8, b, 2 * m + 1)
  rec = wp._auto_unroll(8, b, 2 * m + 1 + (m + 1))
  bwd = wp._auto_unroll(8, b, 6 * m + 4)
  assert 1 <= bwd <= rec <= fwd < 8
  per_diag_fwd = 2 * 4 * b * (2 * m + 1)
  assert fwd * per_diag_fwd <= wp._VMEM_STREAM_BUDGET
  # Never below 1, even for absurd shapes.
  assert wp._auto_unroll(8, 1 << 20, 6 * 512 + 4) == 1


@pytest.mark.slow
def test_unroll_invariance():
  """Scores and gradients are bit-identical in expectation across
  unroll factors (the block padding/masking algebra must not leak into
  values for any unroll choice)."""
  import jax

  from deepconsensus_tpu.ops import wavefront_pallas as wp

  rng = np.random.default_rng(11)
  b, m, n = 4, 9, 7
  subs = jnp.asarray(rng.normal(size=(b, m, n)).astype(np.float32))
  ins = jnp.asarray(rng.normal(size=(b, n)).astype(np.float32))
  lens = jnp.asarray(rng.integers(3, m + 1, size=(b,)), jnp.int32)

  base = wp.alignment_scores(subs, ins, 2.0, lens, loss_reg=0.5,
                             interpret=True, unroll=1)
  for unroll in (2, 3, 8):
    got = wp.alignment_scores(subs, ins, 2.0, lens, loss_reg=0.5,
                              interpret=True, unroll=unroll)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base),
                               rtol=1e-6, atol=1e-6)

  def loss(u):
    # Per-call unroll override (advisor r3: the knob must work through
    # the VJP path, not only via the module-level env default).
    def f(s, i):
      return jnp.sum(wp.alignment_scores_vjp(s, i, lens, 2.0, 0.5,
                                             interpret=True, unroll=u))
    return jax.grad(f, argnums=(0, 1))(subs, ins)

  g1 = loss(1)
  for u in (3, 8):
    gu = loss(u)
    for want, got in zip(g1, gu):
      np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                 rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# Banded kernels (band-space twins of wavefront.banded_alignment_scan).
# ---------------------------------------------------------------------------


def random_banded_costs(rng, b=6, m=12):
  subs = jnp.asarray(rng.uniform(0, 5, size=(b, m, m)).astype(np.float32))
  ins = jnp.asarray(rng.uniform(0, 5, size=(b, m)).astype(np.float32))
  lens = jnp.asarray(rng.integers(1, m + 1, size=b).astype(np.int32))
  return subs, ins, lens


@pytest.mark.parametrize('loss_reg', [None, 0.5])
@pytest.mark.parametrize('width', [1, 2, 5])
@pytest.mark.parametrize('seed', range(2))
def test_banded_pallas_scorer_matches_scan(seed, width, loss_reg):
  import jax

  rng = np.random.default_rng(seed)
  subs, ins, lens = random_banded_costs(rng)
  if loss_reg is None:
    minop = lambda t: jnp.min(t, axis=0)
  else:
    minop = lambda t: -loss_reg * jax.nn.logsumexp(-t / loss_reg, axis=0)
  want = wavefront.banded_alignment_scan(
      subs, ins, jnp.float32(3.0), lens, width, minop
  )
  got = wavefront_pallas.banded_alignment_scores(
      subs, ins, 3.0, lens, width, loss_reg=loss_reg, interpret=True
  )
  np.testing.assert_allclose(
      np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-4
  )


def test_banded_pallas_width_wider_than_matrix():
  """width >= m degenerates to the full DP; the band formulas must not
  read out of range."""
  import jax

  rng = np.random.default_rng(4)
  subs, ins, lens = random_banded_costs(rng, b=3, m=7)
  minop = lambda t: jnp.min(t, axis=0)
  want = wavefront.banded_alignment_scan(
      subs, ins, jnp.float32(2.0), lens, 9, minop
  )
  got = wavefront_pallas.banded_alignment_scores(
      subs, ins, 2.0, lens, 9, interpret=True
  )
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


@pytest.mark.parametrize('loss_reg', [0.1, 1.0, None])
@pytest.mark.slow
def test_banded_pallas_vjp_grads_match_scan(loss_reg):
  """Banded custom-VJP backward vs jax.grad of the banded scan DP
  (hard-min included: tie-averaged subgradients match the scan's)."""
  import jax

  rng = np.random.default_rng(3)
  subs, ins, lens = random_banded_costs(rng, b=5, m=11)
  width = 3
  if loss_reg is None:
    minop = lambda t: jnp.min(t, axis=0)
  else:
    minop = lambda t: -loss_reg * jax.nn.logsumexp(-t / loss_reg, axis=0)

  def scan_loss(subs, ins):
    return jnp.sum(wavefront.banded_alignment_scan(
        subs, ins, jnp.float32(3.0), lens, width, minop))

  def pallas_loss(subs, ins):
    return jnp.sum(wavefront_pallas.banded_alignment_scores_vjp(
        subs, ins, lens, 3.0, loss_reg, width, interpret=True))

  want_val, (want_ds, want_di) = jax.value_and_grad(
      scan_loss, argnums=(0, 1))(subs, ins)
  got_val, (got_ds, got_di) = jax.value_and_grad(
      pallas_loss, argnums=(0, 1))(subs, ins)
  np.testing.assert_allclose(
      np.asarray(got_val), np.asarray(want_val), rtol=1e-5)
  np.testing.assert_allclose(
      np.asarray(got_ds), np.asarray(want_ds), rtol=1e-4, atol=1e-5)
  np.testing.assert_allclose(
      np.asarray(got_di), np.asarray(want_di), rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_banded_pallas_unroll_invariance():
  """Banded scores and grads are invariant to the unroll choice (block
  padding/masking algebra must not leak into values)."""
  import jax

  from deepconsensus_tpu.ops import wavefront_pallas as wp

  rng = np.random.default_rng(11)
  subs, ins, lens = random_banded_costs(rng, b=4, m=9)
  width = 2

  base = wp.banded_alignment_scores(subs, ins, 2.0, lens, width,
                                    loss_reg=0.5, interpret=True, unroll=1)
  for unroll in (2, 3, 8):
    got = wp.banded_alignment_scores(subs, ins, 2.0, lens, width,
                                     loss_reg=0.5, interpret=True,
                                     unroll=unroll)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base),
                               rtol=1e-6, atol=1e-6)

  def grads(u):
    def f(s, i):
      return jnp.sum(wp.banded_alignment_scores_vjp(
          s, i, lens, 2.0, 0.5, width, interpret=True, unroll=u))
    return jax.grad(f, argnums=(0, 1))(subs, ins)

  g1 = grads(1)
  for u in (3, 8):
    for want, got in zip(g1, grads(u)):
      np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                 rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_alignment_loss_banded_pallas_path_trains():
  """AlignmentLoss(width=4, use_pallas=True) values + grads match the
  banded scan path end-to-end through the loss wrapper."""
  import jax

  from deepconsensus_tpu.models import losses as losses_lib

  rng = np.random.default_rng(7)
  b, m, vocab = 6, 10, 5
  y_true = jnp.asarray(rng.integers(0, vocab, size=(b, m)), jnp.int32)
  logits = jnp.asarray(rng.normal(size=(b, m, vocab)).astype(np.float32))
  y_pred = jax.nn.softmax(logits)

  loss_scan = losses_lib.AlignmentLoss(del_cost=10.0, loss_reg=0.1,
                                       width=4)
  loss_pallas = losses_lib.AlignmentLoss(del_cost=10.0, loss_reg=0.1,
                                         width=4, use_pallas=True)

  want, want_g = jax.value_and_grad(
      lambda p: loss_scan(y_true, p))(y_pred)
  got, got_g = jax.value_and_grad(
      lambda p: loss_pallas(y_true, p))(y_pred)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)
  np.testing.assert_allclose(
      np.asarray(got_g), np.asarray(want_g), rtol=1e-4, atol=1e-5)
