"""Pallas wavefront scorer vs the lax.scan formulation (interpret)."""
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.ops import wavefront, wavefront_pallas


def random_costs(rng, b=8, m=20, n=20):
  subs = jnp.asarray(rng.uniform(0, 5, size=(b, m, n)).astype(np.float32))
  ins = jnp.asarray(rng.uniform(0, 5, size=(b, n)).astype(np.float32))
  lens = jnp.asarray(rng.integers(1, m + 1, size=b).astype(np.int32))
  return subs, ins, lens


@pytest.mark.parametrize('loss_reg', [None, 0.5])
@pytest.mark.parametrize('seed', range(3))
def test_pallas_scorer_matches_scan(seed, loss_reg):
  rng = np.random.default_rng(seed)
  subs, ins, lens = random_costs(rng)
  import jax

  if loss_reg is None:
    minop = lambda t: jnp.min(t, axis=0)
  else:
    # Stable soft-min, matching losses.AlignmentLoss's minop.
    minop = lambda t: -loss_reg * jax.nn.logsumexp(-t / loss_reg, axis=0)
  want = wavefront.alignment_scan(subs, ins, jnp.float32(3.0), lens, minop)
  got = wavefront_pallas.alignment_scores(
      subs, ins, 3.0, lens, loss_reg=loss_reg, interpret=True
  )
  np.testing.assert_allclose(
      np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-4
  )


def test_pallas_scorer_non_divisible_batch():
  rng = np.random.default_rng(9)
  subs, ins, lens = random_costs(rng, b=6)
  want = wavefront.alignment_scan(
      subs, ins, jnp.float32(2.0), lens, lambda t: jnp.min(t, axis=0)
  )
  got = wavefront_pallas.alignment_scores(
      subs, ins, 2.0, lens, interpret=True
  )
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)
