"""Durability tests for the flywheel orchestration layer.

Two tiers in one file:

* Fast in-process tests of the journal (atomic commits, schema
  versioning), the `Stage` orchestrator (`--resume` skip/re-entry
  semantics, stale-journal rejection, preemption between and inside
  stages), and the stage-level transient retry loop with its
  crash-loop breaker. These run everywhere the resilience marker runs.

* The slow end-to-end drills — real `dctpu flywheel` subprocess
  cycles on synthetic shards: SIGKILL at every stage boundary with
  `--resume` completing each killed cycle (and the final artifact
  serving byte-identically to an undisturbed cycle), gate-failure
  resume still exiting 3 with the gates measured exactly once,
  idempotent export re-entry, SIGTERM mid-train checkpointing +
  resuming, and the two-host elastic cycle surviving a mid-train host
  loss. A full drill pass costs ~20 minutes of CPU training, so these
  are gated behind DCTPU_FLYWHEEL_DRILL=1 — `scripts/run_resilience.sh
  --flywheel` (or `./run_all_tests.sh flywheel`) sets it.
"""
import glob as glob_lib
import json
import os
import shutil
import signal
import subprocess
import sys
import types

import numpy as np
import pytest

from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu import obs as obs_lib
from deepconsensus_tpu.models import flywheel as flywheel_lib

pytestmark = pytest.mark.resilience

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
  sys.path.insert(0, _REPO_ROOT)

_DRILL = pytest.mark.skipif(
    os.environ.get('DCTPU_FLYWHEEL_DRILL') != '1',
    reason='full flywheel drill (~20 min of CPU cycles); run '
           'scripts/run_resilience.sh --flywheel')

MAX_PASSES = 5
MAX_LENGTH = 20


# ----------------------------------------------------------------------
# In-process helpers.


def _registry() -> obs_lib.MetricsRegistry:
  return obs_lib.MetricsRegistry(tier='test')


def _guard(hits=()):
  """Stand-in for PreemptionGuard: local() pops scripted answers."""
  answers = list(hits)

  def local():
    return answers.pop(0) if answers else False

  return types.SimpleNamespace(local=local)


def _toy_stage(name, calls, outputs=None, run=None, **kwargs):
  def default_run():
    calls.append(name)
    return dict(outputs or {'ok': True})

  return flywheel_lib.Stage(name, {'cfg': name}, run or default_run,
                            **kwargs)


# ----------------------------------------------------------------------
# Journal + atomic writer.


def test_atomic_write_json_round_trip(tmp_path):
  path = str(tmp_path / 'j.json')
  flywheel_lib.atomic_write_json(path, {'a': 1})
  flywheel_lib.atomic_write_json(path, {'a': 2})
  with open(path) as f:
    assert json.load(f) == {'a': 2}
  # No leftover tmp files (the name is pid-unique so concurrent
  # elastic hosts can't rename each other's half-written commits).
  assert [p.name for p in tmp_path.iterdir()] == ['j.json']


def test_journal_round_trip(tmp_path):
  out = str(tmp_path)
  journal = flywheel_lib.FlywheelJournal(out)
  assert journal.load() is False  # fresh out_dir: no journal yet
  journal.begin('train', {'x': 1})
  journal.finish('train', {'checkpoint': '/c/1'})
  journal.note_retry('train')
  journal.commit()

  fresh = flywheel_lib.FlywheelJournal(out)
  assert fresh.load() is True
  entry = fresh.entry('train')
  assert entry['status'] == 'done'
  assert entry['inputs'] == {'x': 1}
  assert entry['inputs_digest'] == flywheel_lib._inputs_digest({'x': 1})
  assert entry['outputs'] == {'checkpoint': '/c/1'}
  assert fresh.counters() == {'n_stage_retries': 1, 'n_stage_resumes': 0}


def test_journal_schema_mismatch_raises_typed(tmp_path):
  out = str(tmp_path)
  flywheel_lib.atomic_write_json(
      os.path.join(out, flywheel_lib.JOURNAL_NAME),
      {'schema_version': 99, 'stages': {}})
  journal = flywheel_lib.FlywheelJournal(out)
  with pytest.raises(faults_lib.FlywheelResumeError) as exc_info:
    journal.load()
  assert exc_info.value.field == 'schema_version'
  assert exc_info.value.journal_value == 99
  assert '--resume' in str(exc_info.value)


def test_begin_preserves_retry_count_across_reentry(tmp_path):
  journal = flywheel_lib.FlywheelJournal(str(tmp_path))
  journal.begin('train', {'x': 1})
  journal.note_retry('train')
  journal.note_retry('train')
  journal.begin('train', {'x': 1}, n_resumes=1)
  entry = journal.entry('train')
  assert entry['n_retries'] == 2
  assert entry['n_resumes'] == 1


# ----------------------------------------------------------------------
# The orchestrator: skip / re-enter / stale / preempt.


def test_resume_skips_done_stages(tmp_path):
  out = str(tmp_path)
  calls = []
  factories = [lambda r: _toy_stage('a', calls, {'art': 'a1'}),
               lambda r: _toy_stage('b', calls, {'art': 'b1'})]

  journal = flywheel_lib.FlywheelJournal(out)
  results, interrupted = flywheel_lib._run_stages(
      factories, journal, _guard(), _registry())
  assert interrupted is None
  assert calls == ['a', 'b']

  resumed = flywheel_lib.FlywheelJournal(out)
  assert resumed.load()
  obs = _registry()
  results, interrupted = flywheel_lib._run_stages(
      factories, resumed, _guard(), obs, resume=True)
  assert interrupted is None
  assert calls == ['a', 'b']  # nothing re-ran
  assert results == {'a': {'art': 'a1'}, 'b': {'art': 'b1'}}
  assert obs.counter_values().get('n_stage_skips') == 2


def test_resume_reenters_inflight_stage_and_counts(tmp_path):
  out = str(tmp_path)
  calls = []
  factories = [lambda r: _toy_stage('a', calls)]

  # Simulate the SIGKILL-after-commit crash: a durable `running` entry.
  journal = flywheel_lib.FlywheelJournal(out)
  journal.begin('a', {'cfg': 'a'})
  journal.commit()

  resumed = flywheel_lib.FlywheelJournal(out)
  assert resumed.load()
  obs = _registry()
  _, interrupted = flywheel_lib._run_stages(
      factories, resumed, _guard(), obs, resume=True)
  assert interrupted is None
  assert calls == ['a']
  entry = resumed.entry('a')
  assert entry['status'] == 'done'
  assert entry['n_resumes'] == 1
  assert obs.counter_values().get('n_stage_resumes') == 1


def test_stale_journal_names_mismatched_field(tmp_path):
  out = str(tmp_path)
  journal = flywheel_lib.FlywheelJournal(out)
  journal.begin('a', {'cfg': 'old', 'batch': 8})
  journal.finish('a', {'ok': True})
  journal.commit()

  resumed = flywheel_lib.FlywheelJournal(out)
  resumed.load()
  calls = []
  stage = flywheel_lib.Stage('a', {'cfg': 'new', 'batch': 8},
                             lambda: calls.append('a') or {})
  with pytest.raises(faults_lib.FlywheelResumeError) as exc_info:
    flywheel_lib._run_stages(
        [lambda r: stage], resumed, _guard(), _registry(), resume=True)
  err = exc_info.value
  assert err.field == 'cfg'
  assert err.journal_value == 'old'
  assert err.current_value == 'new'
  assert err.stage == 'a'
  assert not calls  # rejected before any work ran


def test_invalid_outputs_force_rerun(tmp_path):
  out = str(tmp_path)
  calls = []
  journal = flywheel_lib.FlywheelJournal(out)
  journal.begin('a', {'cfg': 'a'})
  journal.finish('a', {'checkpoint': '/gone'})
  journal.commit()

  resumed = flywheel_lib.FlywheelJournal(out)
  resumed.load()
  factories = [lambda r: _toy_stage('a', calls, {'checkpoint': '/new'},
                                    outputs_valid=lambda o: False)]
  results, _ = flywheel_lib._run_stages(
      factories, resumed, _guard(), _registry(), resume=True)
  assert calls == ['a']  # quarantined outputs: the stage re-ran
  assert results['a'] == {'checkpoint': '/new'}


def test_preemption_between_stages_interrupts(tmp_path):
  out = str(tmp_path)
  calls = []
  factories = [lambda r: _toy_stage('a', calls),
               lambda r: _toy_stage('b', calls)]
  journal = flywheel_lib.FlywheelJournal(out)
  # guard goes hot after stage a completes.
  results, interrupted = flywheel_lib._run_stages(
      factories, journal, _guard([False, True]), _registry())
  assert interrupted == 'b'
  assert calls == ['a']
  assert journal.entry('a')['status'] == 'done'
  assert journal.entry('b')['status'] == 'interrupted'
  assert 'b' not in results


def test_preempted_stage_outputs_interrupt(tmp_path):
  out = str(tmp_path)

  def run():
    return {'preempted': True, 'stop_step': 3.0, 'checkpoint': '/c/3'}

  journal = flywheel_lib.FlywheelJournal(out)
  results, interrupted = flywheel_lib._run_stages(
      [lambda r: flywheel_lib.Stage('train', {'cfg': 't'}, run)],
      journal, _guard(), _registry())
  assert interrupted == 'train'
  entry = journal.entry('train')
  assert entry['status'] == 'interrupted'
  assert entry['outputs']['checkpoint'] == '/c/3'
  assert results['train']['preempted']


# ----------------------------------------------------------------------
# Stage retries + the crash-loop breaker.


def test_transient_stage_failure_retries_and_journals(tmp_path):
  journal = flywheel_lib.FlywheelJournal(str(tmp_path))
  sleeps = []
  degraded = []
  attempts = {'n': 0}

  def run():
    attempts['n'] += 1
    if attempts['n'] == 1:
      raise RuntimeError('UNAVAILABLE: device preempted')
    return {'ok': True}

  stage = flywheel_lib.Stage(
      'train', {'cfg': 't'}, run,
      progress=lambda: attempts['n'],
      on_transient=degraded.append)
  obs = _registry()
  results, interrupted = flywheel_lib._run_stages(
      [lambda r: stage], journal, _guard(), obs,
      retry_opts={'sleep': sleeps.append})
  assert interrupted is None
  assert results['train'] == {'ok': True}
  assert attempts['n'] == 2
  assert sleeps == [0.5]  # backoff_base * 2**0
  assert len(degraded) == 1
  assert journal.entry('train')['n_retries'] == 1
  assert obs.counter_values().get('n_stage_retries') == 1


def test_crash_loop_breaker_on_stalled_stage(tmp_path):
  journal = flywheel_lib.FlywheelJournal(str(tmp_path))

  def run():
    raise RuntimeError('DEADLINE_EXCEEDED: collective timed out')

  stage = flywheel_lib.Stage('distill', {'cfg': 'd'}, run,
                             progress=lambda: 7)  # never advances
  with pytest.raises(faults_lib.CrashLoopError) as exc_info:
    flywheel_lib._run_stages(
        [lambda r: stage], journal, _guard(), _registry(),
        retry_opts={'sleep': lambda s: None, 'max_stalled_restarts': 2})
  assert 'distill' in str(exc_info.value)
  assert journal.entry('distill')['status'] == 'failed'


def test_permanent_error_is_not_retried_and_is_typed(tmp_path):
  journal = flywheel_lib.FlywheelJournal(str(tmp_path))
  attempts = {'n': 0}

  def run():
    attempts['n'] += 1
    raise RuntimeError('matmul dimension mismatch')

  with pytest.raises(faults_lib.FlywheelStageError) as exc_info:
    flywheel_lib._run_stages(
        [lambda r: flywheel_lib.Stage('gates', {'cfg': 'g'}, run)],
        journal, _guard(), _registry())
  assert attempts['n'] == 1  # permanent: no retry
  assert exc_info.value.stage == 'gates'
  assert journal.entry('gates')['status'] == 'failed'


def test_value_error_passes_through_unwrapped(tmp_path):
  journal = flywheel_lib.FlywheelJournal(str(tmp_path))

  def run():
    raise ValueError('unknown config override')

  with pytest.raises(ValueError, match='unknown config override'):
    flywheel_lib._run_stages(
        [lambda r: flywheel_lib.Stage('train', {'cfg': 't'}, run)],
        journal, _guard(), _registry())
  assert journal.entry('train')['status'] == 'failed'


# ----------------------------------------------------------------------
# Manifest + fault-hook plumbing.


def test_manifest_carries_schema_version_and_counters(tmp_path):
  out = str(tmp_path)
  journal = flywheel_lib.FlywheelJournal(out)
  journal.begin('train', {'x': 1}, n_resumes=2)
  journal.finish('train', {'checkpoint': '/c'})
  manifest = flywheel_lib._build_manifest({'train': {'checkpoint': '/c'}},
                                          journal)
  flywheel_lib._write_manifest(out, manifest)
  with open(os.path.join(out, flywheel_lib.MANIFEST_NAME)) as f:
    loaded = json.load(f)
  assert loaded['schema_version'] == flywheel_lib.MANIFEST_SCHEMA_VERSION
  assert loaded['counters'] == {'n_stage_retries': 0, 'n_stage_resumes': 2}
  assert loaded['ok'] is False  # no gates, no export


def test_gate_thresholds_come_from_config():
  from deepconsensus_tpu.models import config as config_lib

  assert flywheel_lib.INT8_IDENTITY_GATE is config_lib.INT8_IDENTITY_GATE
  assert flywheel_lib.BF16_QV_GATE is config_lib.BF16_QV_GATE


def test_inject_faults_flywheel_prints_env(capsys):
  from scripts import inject_faults

  assert inject_faults.main(['flywheel', '--kill_at_stage', 'distill',
                             '--kill_token', '/tmp/tok']) == 0
  out = capsys.readouterr().out
  assert 'export DCTPU_FAULT_FLYWHEEL_KILL_AT_STAGE=distill' in out
  assert 'export DCTPU_FAULT_KILL_TOKEN=/tmp/tok' in out


def test_kill_hook_only_fires_on_named_stage(monkeypatch):
  monkeypatch.setattr(faults_lib, '_fired', set())
  monkeypatch.delenv(faults_lib.ENV_FLYWHEEL_KILL_AT_STAGE, raising=False)
  # Unarmed: any stage name is a no-op (we are still alive to assert).
  faults_lib.maybe_kill_flywheel_at_stage('train')
  monkeypatch.setenv(faults_lib.ENV_FLYWHEEL_KILL_AT_STAGE, 'export')
  faults_lib.maybe_kill_flywheel_at_stage('train')
  faults_lib.maybe_kill_flywheel_at_stage('gates')
  assert faults_lib.ENV_FLYWHEEL_KILL_AT_STAGE not in faults_lib._fired


# ----------------------------------------------------------------------
# The end-to-end drills (real subprocess cycles; DCTPU_FLYWHEEL_DRILL).


@pytest.fixture(scope='module')
def shards(tmp_path_factory):
  from scripts import inject_faults

  d = tmp_path_factory.mktemp('fw_shards')
  inject_faults.write_synthetic_tfrecords(
      str(d), n_shards=2, n_examples=64,
      max_passes=MAX_PASSES, max_length=MAX_LENGTH)
  return os.path.join(str(d), 'shard-*')


def _flywheel_args(out_dir, shard_glob, *extra):
  return ['--out_dir', out_dir,
          '--train_path', shard_glob, '--eval_path', shard_glob,
          '--batch_size', '8', '--num_epochs', '1',
          '--export_batch_size', '8',
          '--set', f'max_passes={MAX_PASSES}',
          '--set', f'max_length={MAX_LENGTH}',
          '--student_set', f'max_passes={MAX_PASSES}',
          '--student_set', f'max_length={MAX_LENGTH}',
          *extra]


# The drills run real `dctpu flywheel` cycles as subprocesses. Pin
# them to one host-platform device: conftest.py forces 8 faked CPU
# devices into os.environ for the multichip unit tests, but the
# flywheel recipe (docs/training.md) is a plain single-host run, and
# the drills must reproduce the documented recipe — durability
# semantics, not device sharding, are under test here.
_DRILL_ENV = dict(JAX_PLATFORMS='cpu', PYTHONPATH=_REPO_ROOT,
                  XLA_FLAGS='--xla_force_host_platform_device_count=1')


def _run_cli(args, env_extra=None, timeout=570):
  cmd = [sys.executable, '-m', 'deepconsensus_tpu.cli', 'flywheel'] + args
  env = dict(os.environ, **_DRILL_ENV)
  env.update(env_extra or {})
  return subprocess.run(cmd, env=env, cwd=_REPO_ROOT,
                        capture_output=True, text=True, timeout=timeout)


def _journal_statuses(out_dir):
  with open(os.path.join(out_dir, flywheel_lib.JOURNAL_NAME)) as f:
    journal = json.load(f)
  return {name: entry['status']
          for name, entry in journal['stages'].items()}, journal


def _served_planes(export_dir):
  from deepconsensus_tpu.inference import runner as runner_lib

  rng = np.random.RandomState(0)
  rows = rng.uniform(0.0, 10.0, size=(
      8, 4 * MAX_PASSES + 5, MAX_LENGTH, 1)).astype(np.float32)
  runner = runner_lib.ModelRunner.from_exported(
      export_dir, runner_lib.InferenceOptions(batch_size=8))
  ids, quals = runner.predict(rows)
  return np.asarray(ids), np.asarray(quals)


@pytest.fixture(scope='module')
def undisturbed_run(shards, tmp_path_factory):
  """One full cycle with no faults — the baseline every drill compares
  against (byte-identical serving, teacher checkpoint reuse)."""
  out = str(tmp_path_factory.mktemp('fw_baseline') / 'fw')
  result = _run_cli(_flywheel_args(out, shards))
  assert result.returncode == 0, result.stderr[-4000:]
  return out


@_DRILL
@pytest.mark.slow
def test_sigkill_at_every_stage_boundary_then_resume(
    shards, undisturbed_run, tmp_path_factory):
  """ROADMAP item 3 acceptance drill: SIGKILL right after each stage's
  `running` journal commit (the worst-timed crash), resume each time,
  and the final artifact must serve byte-identically to the
  undisturbed baseline with every gate recorded exactly once."""
  out = str(tmp_path_factory.mktemp('fw_drill') / 'fw')
  for i, stage in enumerate(flywheel_lib.STAGE_ORDER):
    extra = () if stage == 'train' else ('--resume',)
    result = _run_cli(
        _flywheel_args(out, shards, *extra),
        env_extra={faults_lib.ENV_FLYWHEEL_KILL_AT_STAGE: stage})
    assert result.returncode == -signal.SIGKILL, (
        stage, result.returncode, result.stderr[-2000:])
    statuses, _ = _journal_statuses(out)
    assert statuses[stage] == 'running'
    for earlier in flywheel_lib.STAGE_ORDER[:i]:
      assert statuses[earlier] == 'done'

  final = _run_cli(_flywheel_args(out, shards, '--resume'))
  assert final.returncode == 0, final.stderr[-4000:]
  payload = json.loads(final.stdout)
  assert [g['name'] for g in payload['gates']] == [
      'int8_alignment_identity_delta', 'bf16_max_qv_delta']
  assert all(g['passed'] for g in payload['gates'])

  statuses, journal = _journal_statuses(out)
  assert statuses == {s: 'done' for s in flywheel_lib.STAGE_ORDER}
  for stage in flywheel_lib.STAGE_ORDER:
    assert journal['stages'][stage]['n_resumes'] == 1

  with open(os.path.join(out, flywheel_lib.MANIFEST_NAME)) as f:
    manifest = json.load(f)
  assert manifest['ok'] is True
  assert manifest['schema_version'] == flywheel_lib.MANIFEST_SCHEMA_VERSION
  assert manifest['counters'] == {'n_stage_resumes': 4,
                                  'n_stage_retries': 0}
  assert len(manifest['gates']) == 2  # measured exactly once

  ids_d, quals_d = _served_planes(os.path.join(out, 'export'))
  ids_b, quals_b = _served_planes(os.path.join(undisturbed_run, 'export'))
  np.testing.assert_array_equal(ids_d, ids_b)
  np.testing.assert_array_equal(quals_d, quals_b)


@_DRILL
@pytest.mark.slow
def test_gate_failure_resume_still_exits_3(
    shards, undisturbed_run, tmp_path_factory):
  """A failed gate is durable: rerunning with --resume re-verifies the
  journaled measurement (no re-eval) and still refuses to export."""
  ckpts = glob_lib.glob(
      os.path.join(undisturbed_run, 'teacher', 'checkpoints',
                   'checkpoint-*'))
  teacher_ckpt = max(ckpts, key=lambda p: int(p.rsplit('-', 1)[1]))
  out = str(tmp_path_factory.mktemp('fw_gatefail') / 'fw')
  args = _flywheel_args(out, shards,
                        '--teacher_checkpoint', teacher_ckpt,
                        '--bf16_gate', '-1')

  first = _run_cli(args)
  assert first.returncode == 3, first.stderr[-4000:]
  statuses, journal = _journal_statuses(out)
  assert statuses['gates'] == 'done'  # measured, then enforcement failed
  assert 'export' not in statuses
  assert not os.path.isdir(os.path.join(out, 'export'))

  second = _run_cli(args + ['--resume'])
  assert second.returncode == 3, second.stderr[-4000:]
  _, journal = _journal_statuses(out)
  assert journal['stages']['gates']['n_resumes'] == 0  # not re-measured
  with open(os.path.join(out, flywheel_lib.MANIFEST_NAME)) as f:
    manifest = json.load(f)
  assert manifest['ok'] is False
  failed = [g for g in manifest['gates'] if not g['passed']]
  assert [g['name'] for g in failed] == ['bf16_max_qv_delta']


@_DRILL
@pytest.mark.slow
def test_export_reentry_is_idempotent_and_stale_journal_rejected(
    shards, undisturbed_run, tmp_path_factory):
  out = str(tmp_path_factory.mktemp('fw_reentry') / 'fw')
  shutil.copytree(undisturbed_run, out)

  # Simulate a crash mid-export: journal says `running`, staging holds
  # junk, and the published dir is wreckage from an interrupted publish.
  journal_path = os.path.join(out, flywheel_lib.JOURNAL_NAME)
  with open(journal_path) as f:
    journal = json.load(f)
  journal['stages']['export']['status'] = 'running'
  flywheel_lib.atomic_write_json(journal_path, journal)
  staging = os.path.join(out, flywheel_lib.EXPORT_STAGING)
  os.makedirs(staging, exist_ok=True)
  with open(os.path.join(staging, 'junk'), 'w') as f:
    f.write('half-written')
  with open(os.path.join(out, 'export', 'wreckage'), 'w') as f:
    f.write('stale')

  result = _run_cli(_flywheel_args(out, shards, '--resume'))
  assert result.returncode == 0, result.stderr[-4000:]
  assert not os.path.exists(staging)  # published atomically
  export_dir = os.path.join(out, 'export')
  assert os.path.exists(os.path.join(export_dir, 'serving.stablehlo'))
  assert not os.path.exists(os.path.join(export_dir, 'wreckage'))
  _, journal = _journal_statuses(out)
  assert journal['stages']['export']['status'] == 'done'
  assert journal['stages']['export']['n_resumes'] == 1

  # Stale journal: same out_dir, changed gate threshold -> typed
  # rejection (exit 2) naming the drifted field, nothing re-run.
  stale = _run_cli(_flywheel_args(out, shards, '--resume',
                                  '--bf16_gate', '99'))
  assert stale.returncode == 2, (stale.returncode, stale.stderr[-2000:])
  assert 'bf16_gate_threshold' in stale.stderr


@_DRILL
@pytest.mark.slow
def test_sigterm_mid_train_interrupts_then_resume_completes(
    shards, tmp_path_factory):
  """Preemption notice mid-train: checkpoint, journal `interrupted`,
  exit 0 with a resume hint; --resume finishes the cycle."""
  out = str(tmp_path_factory.mktemp('fw_sigterm') / 'fw')
  args = _flywheel_args(out, shards)
  first = _run_cli(args,
                   env_extra={faults_lib.ENV_SIGTERM_AT_STEP: '3'})
  assert first.returncode == 0, first.stderr[-4000:]
  payload = json.loads(first.stdout)
  assert payload['interrupted'] == 'train'
  assert '--resume' in payload['resume']
  statuses, _ = _journal_statuses(out)
  assert statuses['train'] == 'interrupted'

  second = _run_cli(args + ['--resume'])
  assert second.returncode == 0, second.stderr[-4000:]
  payload = json.loads(second.stdout)
  assert all(g['passed'] for g in payload['gates'])
  statuses, journal = _journal_statuses(out)
  assert statuses == {s: 'done' for s in flywheel_lib.STAGE_ORDER}
  assert journal['stages']['train']['n_resumes'] == 1


@_DRILL
@pytest.mark.slow
def test_mid_train_host_loss_degrades_and_completes(
    shards, tmp_path_factory):
  """Two elastic hosts share one cycle; host 1 is lost mid-train. The
  survivor rebuilds the pod, finishes training solo, and carries the
  cycle through gates and export."""
  out = str(tmp_path_factory.mktemp('fw_hostloss') / 'fw')
  args = _flywheel_args(out, shards, '--elastic', '--num_processes', '2',
                        '--elastic_barrier_timeout', '5')
  env = dict(os.environ, **_DRILL_ENV)
  env[faults_lib.ENV_HOST_LOST_AT_STEP] = '3'
  env[faults_lib.ENV_HOST_LOST_HOST] = '1'
  cmd = [sys.executable, '-m', 'deepconsensus_tpu.cli', 'flywheel']
  procs = []
  for host in (1, 0):
    procs.append(subprocess.Popen(
        cmd + args + ['--process_id', str(host)],
        env=env, cwd=_REPO_ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
  out1, err1 = procs[0].communicate(timeout=570)
  out0, err0 = procs[1].communicate(timeout=570)
  assert procs[0].returncode == -signal.SIGKILL, (out1, err1[-2000:])
  assert procs[1].returncode == 0, err0[-4000:]
  payload = json.loads(out0)
  assert all(g['passed'] for g in payload['gates'])
  statuses, _ = _journal_statuses(out)
  assert statuses == {s: 'done' for s in flywheel_lib.STAGE_ORDER}
