"""Byte-identity and plumbing tests for the device-resident output
plane (--device_epilogue): the forward emits final uint8 (ids, quals)
planes on device and finalize becomes a pure 2-bytes/position drain.

The contract under test: FASTQ output is byte-identical with the
epilogue on or off, across the quantization levers, dp sharding, the
serve/engine boundary, and exported artifacts — and with it on, the
host never touches per-position float math again.

The fast tier's gate (`run_all_tests.sh fast` / `epilogue`) runs the
single-device subset via `-k identity -m 'not multichip'`; name any
new identity invariant accordingly.
"""
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu.calibration import lib as calibration_lib
from deepconsensus_tpu.inference import engine as engine_lib
from deepconsensus_tpu.inference import runner as runner_lib
from deepconsensus_tpu.io import fastx
from deepconsensus_tpu.models import (
    config as config_lib,
    export as export_lib,
    model as model_lib,
)


def _params(layers=2, **kw):
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params, is_training=False)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = layers
    params.filter_size = 64
    params.batch_size = 4
    for k, v in kw.items():
      params[k] = v
  return params


def _init_variables(params, seed=0):
  model = model_lib.get_model(params)
  rows = jnp.zeros((1, params.total_rows, params.max_length, 1))
  return model.init(jax.random.PRNGKey(seed), rows)


def _rows(params, n, seed=7):
  rng = np.random.default_rng(seed)
  return rng.integers(
      0, 4, size=(n, params.total_rows, params.max_length, 1)
  ).astype(np.float32)


def _runner(variables, device_epilogue, mesh=None, batch_size=8, **opt_kw):
  options = runner_lib.InferenceOptions(
      batch_size=batch_size, device_epilogue=device_epilogue, **opt_kw)
  p = _params()
  runner_lib._apply_quant_levers(p, options)
  return runner_lib.ModelRunner(p, variables, options, mesh=mesh)


def _ids_quals(runner, rows):
  ids, quals = runner.predict(rows)
  return np.asarray(ids, np.int64), np.asarray(quals, np.int64)


# ---------------------------------------------------------------------------
# End-to-end FASTQ byte identity (the fast-tier gate).
# ---------------------------------------------------------------------------


def test_fastq_byte_identity_host_vs_device(tmp_path, synthetic_bams):
  """The headline invariant: the device epilogue changes the transfer
  format (uint8 planes, 4x fewer D2H bytes), never a single FASTQ
  byte."""
  subreads, ccs = synthetic_bams()
  params = _params()
  variables = _init_variables(params, seed=4)

  def run(tag, device_epilogue):
    options = runner_lib.InferenceOptions(
        batch_size=32, batch_zmws=4, min_quality=0,
        device_epilogue=device_epilogue)
    p = _params()
    runner_lib._apply_quant_levers(p, options)
    runner = runner_lib.ModelRunner(p, variables, options)
    out = str(tmp_path / f'{tag}.fastq')
    counters = runner_lib.run_inference(
        subreads_to_ccs=subreads, ccs_bam=ccs, checkpoint=None,
        output=out, options=options, runner=runner)
    return counters, out

  counters_dev, out_dev = run('device', True)
  counters_host, out_host = run('host', False)
  assert counters_dev['n_zmw_pass'] == counters_host['n_zmw_pass'] > 0
  with open(out_dev, 'rb') as f_dev, open(out_host, 'rb') as f_host:
    assert f_dev.read() == f_host.read()
  # Same reads parse out (guards against an identical-but-empty pair).
  assert len(list(fastx.read_fastq(out_dev))) > 0


@pytest.mark.parametrize('levers', [
    dict(inference_dtype='bfloat16'),
    dict(quantize_matmuls='int8'),
    dict(inference_dtype='bfloat16', quantize_matmuls='int8'),
])
def test_predict_identity_across_quant_levers(levers):
  """Each quantization lever changes the logits, but for a FIXED lever
  the epilogue on/off outputs must stay byte-identical (the model's
  output head is f32 regardless of lever, so one threshold table
  serves them all)."""
  params = _params()
  variables = _init_variables(params, seed=6)
  rows = _rows(params, 8)
  on = _runner(variables, True, **levers)
  off = _runner(variables, False, **levers)
  ids_on, quals_on = _ids_quals(on, rows)
  ids_off, quals_off = _ids_quals(off, rows)
  np.testing.assert_array_equal(ids_on, ids_off)
  np.testing.assert_array_equal(quals_on, quals_off)
  assert on.dispatch_stats()['device_epilogue'] == 1
  assert off.dispatch_stats()['device_epilogue'] == 0


@pytest.mark.parametrize('calibration,maxq', [
    ('0,0.9,2.5', 93),
    ('15,1.1,2', 93),
    ('skip', 40),
])
def test_predict_identity_with_calibration(calibration, maxq):
  """Calibration and clamp knobs ride inside the threshold table; the
  identity holds for every representable combination."""
  params = _params()
  variables = _init_variables(params, seed=8)
  rows = _rows(params, 8, seed=9)
  cv = calibration_lib.parse_calibration_string(calibration)
  on = _runner(variables, True,
               dc_calibration_values=cv, max_base_quality=maxq)
  off = _runner(variables, False,
                dc_calibration_values=cv, max_base_quality=maxq)
  assert on.dispatch_stats()['device_epilogue'] == 1
  ids_on, quals_on = _ids_quals(on, rows)
  ids_off, quals_off = _ids_quals(off, rows)
  np.testing.assert_array_equal(ids_on, ids_off)
  np.testing.assert_array_equal(quals_on, quals_off)


def test_fused_hotpath_identity_uses_pallas_epilogue():
  """On the fused hot path the Pallas epilogue kernel (appended after
  the last fused encoder block) carries the output plane; same
  identity bar."""
  params = _params()
  variables = _init_variables(params, seed=10)
  rows = _rows(params, 8, seed=11)
  options = runner_lib.InferenceOptions(batch_size=8, device_epilogue=True)
  p = _params(use_fused_hotpath=True)
  runner_lib._apply_quant_levers(p, options)
  on = runner_lib.ModelRunner(p, variables, options)
  off_options = runner_lib.InferenceOptions(
      batch_size=8, device_epilogue=False)
  p_off = _params(use_fused_hotpath=True)
  runner_lib._apply_quant_levers(p_off, off_options)
  off = runner_lib.ModelRunner(p_off, variables, off_options)
  ids_on, quals_on = _ids_quals(on, rows)
  ids_off, quals_off = _ids_quals(off, rows)
  np.testing.assert_array_equal(ids_on, ids_off)
  np.testing.assert_array_equal(quals_on, quals_off)


@pytest.mark.multichip
def test_dp8_predict_identity():
  """dp-sharded dispatch with the device epilogue (the uint8 planes
  shard with the same out_shardings) matches the single-device host
  path — full and padded-partial packs."""
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  if len(jax.devices()) < 8:
    pytest.skip('needs the 8-device virtual mesh')
  params = _params()
  variables = _init_variables(params, seed=12)
  mesh = mesh_lib.make_mesh(dp=8, tp=1, devices=jax.devices()[:8])
  sharded = _runner(variables, True, mesh=mesh, batch_size=64)
  host = _runner(variables, False, batch_size=64)
  for n in (64, 37):
    rows = _rows(params, n, seed=n)
    ids_s, quals_s = _ids_quals(sharded, rows)
    ids_h, quals_h = _ids_quals(host, rows)
    np.testing.assert_array_equal(ids_s, ids_h)
    np.testing.assert_array_equal(quals_s, quals_h)
  assert sharded.dispatch_stats()['n_epilogue_packs'] == 2


# ---------------------------------------------------------------------------
# Serve/engine boundary.
# ---------------------------------------------------------------------------


def _engine_options(params, device_epilogue):
  options = runner_lib.InferenceOptions(
      batch_size=8, device_epilogue=device_epilogue)
  options.max_passes = params.max_passes
  options.max_length = params.max_length
  options.use_ccs_bq = params.use_ccs_bq
  return options


def test_engine_predict_windows_identity():
  """The serve path's engine boundary delivers identical uint8 results
  with the epilogue on or off (engine._deliver_pack already casts the
  host path's int32 to uint8)."""
  params = _params()
  variables = _init_variables(params, seed=14)
  raw = _rows(params, 11, seed=15)
  results = {}
  for device_epilogue in (True, False):
    options = _engine_options(params, device_epilogue)
    p = _params()
    runner_lib._apply_quant_levers(p, options)
    runner = runner_lib.ModelRunner(p, variables, options)
    engine = engine_lib.ConsensusEngine(
        runner, options, deliver=lambda t, ids, quals: None)
    results[device_epilogue] = engine.predict_windows(raw)
  ids_on, quals_on = results[True]
  ids_off, quals_off = results[False]
  assert ids_on.dtype == np.uint8 and quals_on.dtype == np.uint8
  assert ids_off.dtype == np.uint8 and quals_off.dtype == np.uint8
  np.testing.assert_array_equal(ids_on, ids_off)
  np.testing.assert_array_equal(quals_on, quals_off)


def test_serve_stats_surface_epilogue_counters():
  from deepconsensus_tpu.serve.service import ConsensusService, ServeOptions

  params = _params()
  variables = _init_variables(params, seed=16)
  options = _engine_options(params, True)
  p = _params()
  runner_lib._apply_quant_levers(p, options)
  runner = runner_lib.ModelRunner(p, variables, options)
  service = ConsensusService(runner, options, ServeOptions())
  faults = service.stats()['counters']
  assert faults['device_epilogue'] == 1
  assert faults['n_epilogue_packs'] == 0
  assert faults['d2h_bytes_per_pack'] == 0


# ---------------------------------------------------------------------------
# Finalize is a pure drain; counters measure the saved bytes.
# ---------------------------------------------------------------------------


def test_finalize_pure_drain_when_epilogue_on(monkeypatch):
  """With the epilogue on, _finalize_sync must not touch per-position
  float math: no np.log10, no np.round. (Runners are built and warmed
  BEFORE patching — the threshold build itself legitimately calls
  log10, and the first finalize pays jit tracing.)"""
  params = _params()
  variables = _init_variables(params, seed=18)
  rows = _rows(params, 8, seed=19)
  on = _runner(variables, True)
  off = _runner(variables, False)
  on.predict(rows)
  off.predict(rows)

  calls = []

  def spy(name, fn):
    def wrapped(*args, **kwargs):
      calls.append(name)
      return fn(*args, **kwargs)
    return wrapped

  monkeypatch.setattr(np, 'log10', spy('log10', np.log10))
  monkeypatch.setattr(np, 'round', spy('round', np.round))

  ids, quals = on.finalize(on.dispatch(rows))
  assert 'log10' not in calls and 'round' not in calls
  assert ids.dtype == np.uint8 and quals.dtype == np.uint8

  calls.clear()
  off.finalize(off.dispatch(rows))
  assert 'log10' in calls and 'round' in calls


def test_d2h_counters_show_4x_reduction():
  params = _params()
  variables = _init_variables(params, seed=20)
  rows = _rows(params, 8, seed=21)
  on = _runner(variables, True)
  off = _runner(variables, False)
  on.predict(rows)
  off.predict(rows)
  stats_on = on.dispatch_stats()
  stats_off = off.dispatch_stats()
  assert stats_on['device_epilogue'] == 1
  assert stats_on['n_epilogue_packs'] == 1
  assert stats_off['device_epilogue'] == 0
  assert stats_off['n_epilogue_packs'] == 0
  # Measured from the actual drained device arrays: 2 uint8 planes vs
  # int32 ids + f32 max_prob.
  assert stats_on['d2h_bytes_per_pack'] > 0
  assert stats_off['d2h_bytes_per_pack'] == (
      4 * stats_on['d2h_bytes_per_pack'])


def test_non_representable_calibration_falls_back(caplog):
  """A non-monotone calibration cannot ride the threshold table; the
  runner warns and serves the host path (still correct, just 8
  bytes/position)."""
  cv = calibration_lib.parse_calibration_string('0,-1,50')
  params = _params()
  variables = _init_variables(params, seed=22)
  with caplog.at_level(logging.WARNING):
    runner = _runner(variables, True, dc_calibration_values=cv)
  assert runner.dispatch_stats()['device_epilogue'] == 0
  assert any('falling back to host quality math' in r.message
             for r in caplog.records)
  rows = _rows(params, 8, seed=23)
  host = _runner(variables, False, dc_calibration_values=cv)
  ids_a, quals_a = _ids_quals(runner, rows)
  ids_b, quals_b = _ids_quals(host, rows)
  np.testing.assert_array_equal(ids_a, ids_b)
  np.testing.assert_array_equal(quals_a, quals_b)


# ---------------------------------------------------------------------------
# Exported artifacts: epilogue baked into the program + metadata.
# ---------------------------------------------------------------------------


def _export(tmp_path, tag, **kw):
  params = _params(layers=1)
  variables = _init_variables(params)
  export_dir = str(tmp_path / tag)
  export_lib.export_model(
      checkpoint_path=export_dir, out_dir=export_dir, batch_size=8,
      variables=variables, params=params, **kw)
  return export_dir, params, variables


def test_exported_epilogue_identity(tmp_path):
  """An epilogue artifact's baked program reproduces the checkpoint
  host path byte-for-byte; a pre-epilogue artifact does too (via the
  host fallback)."""
  export_dir, params, variables = _export(tmp_path, 'epi')
  import json
  with open(f'{export_dir}/export_meta.json') as f:
    meta = json.load(f)
  assert meta['device_epilogue'] is True
  assert meta['max_base_quality'] == 93
  assert meta['dc_calibration'] == 'skip'

  rows = _rows(params, 8, seed=24)
  host = runner_lib.ModelRunner(
      _params(layers=1), variables,
      runner_lib.InferenceOptions(batch_size=8, device_epilogue=False))
  exported = runner_lib.ModelRunner.from_exported(
      export_dir, runner_lib.InferenceOptions(batch_size=8))
  assert exported.dispatch_stats()['device_epilogue'] == 1
  ids_h, quals_h = _ids_quals(host, rows)
  ids_e, quals_e = _ids_quals(exported, rows)
  np.testing.assert_array_equal(ids_e, ids_h)
  np.testing.assert_array_equal(quals_e, quals_h)

  plain_dir, _, _ = _export(tmp_path, 'plain', device_epilogue=False)
  plain = runner_lib.ModelRunner.from_exported(
      plain_dir, runner_lib.InferenceOptions(batch_size=8))
  assert plain.dispatch_stats()['device_epilogue'] == 0
  ids_p, quals_p = _ids_quals(plain, rows)
  np.testing.assert_array_equal(ids_p, ids_h)
  np.testing.assert_array_equal(quals_p, quals_h)


def test_exported_epilogue_mismatch_both_directions(tmp_path):
  epi_dir, _, _ = _export(tmp_path, 'epi')
  plain_dir, _, _ = _export(tmp_path, 'plain', device_epilogue=False)

  # Baked epilogue, caller explicitly demands the host path.
  with pytest.raises(faults_lib.ExportedArtifactMismatchError) as excinfo:
    runner_lib.ModelRunner.from_exported(
        epi_dir,
        runner_lib.InferenceOptions(batch_size=8, device_epilogue=False))
  err = excinfo.value
  assert err.reexport_command and 'dctpu export' in err.reexport_command
  assert '--no_device_epilogue' in err.reexport_command
  assert err.reexport_command in str(err)

  # Baked pre-epilogue, caller explicitly demands the device plane.
  with pytest.raises(faults_lib.ExportedArtifactMismatchError) as excinfo:
    runner_lib.ModelRunner.from_exported(
        plain_dir,
        runner_lib.InferenceOptions(batch_size=8, device_epilogue=True))
  assert '--device_epilogue' in excinfo.value.reexport_command


def test_exported_epilogue_quality_knob_mismatch(tmp_path):
  """An epilogue artifact bakes its calibration and clamp into the
  compiled program; a disagreeing serving knob is a refusal naming the
  exact re-export command, never a silent override."""
  epi_dir, _, _ = _export(tmp_path, 'epi')

  with pytest.raises(faults_lib.ExportedArtifactMismatchError) as excinfo:
    runner_lib.ModelRunner.from_exported(
        epi_dir,
        runner_lib.InferenceOptions(batch_size=8, max_base_quality=40))
  assert '--max_base_quality 40' in excinfo.value.reexport_command

  cv = calibration_lib.parse_calibration_string('0,0.9,2.5')
  with pytest.raises(faults_lib.ExportedArtifactMismatchError) as excinfo:
    runner_lib.ModelRunner.from_exported(
        epi_dir,
        runner_lib.InferenceOptions(batch_size=8,
                                    dc_calibration_values=cv))
  assert '--dc_calibration 0,0.9,2.5' in excinfo.value.reexport_command

  # A pre-epilogue artifact leaves the quality knobs host-side: no
  # baking, no refusal.
  plain_dir, _, _ = _export(tmp_path, 'plain', device_epilogue=False)
  runner_lib.ModelRunner.from_exported(
      plain_dir,
      runner_lib.InferenceOptions(batch_size=8, max_base_quality=40,
                                  dc_calibration_values=cv))
