"""Parallel calibrate equals serial; CLI error surfaces cleanly."""
import csv


def test_calibrate_parallel_equals_serial(testdata_dir, tmp_path):
  from deepconsensus_tpu.calibration import measure

  bam = str(
      testdata_dir
      / 'prediction_assessment/CHM13_chr20_0_200000_dc.to_truth.bam'
  )
  ref = str(testdata_dir / 'prediction_assessment/CHM13_chr20_0_200000.fa')
  serial = measure.calculate_quality_calibration(
      bam=bam, ref=ref, output=str(tmp_path / 's.csv'), min_mapq=0, cpus=0
  )
  parallel = measure.calculate_quality_calibration(
      bam=bam, ref=ref, output=str(tmp_path / 'p.csv'), min_mapq=0, cpus=2
  )
  # Single contig in this testdata -> pool path may fall back; force a
  # check on equality either way.
  assert serial == parallel


def test_cli_clean_errors(capsys):
  from deepconsensus_tpu import cli

  rc = cli.main([
      'filter_reads', '--input', '/nope.fastq', '--output', '/tmp/x.fq',
      '--quality', '10',
  ])
  assert rc == 2
  err = capsys.readouterr().err
  assert 'dctpu: file not found' in err


def test_cli_clean_value_error(capsys, testdata_dir):
  from deepconsensus_tpu import cli

  td = str(testdata_dir / 'human_1m')
  rc = cli.main([
      'preprocess',
      '--subreads_to_ccs', f'{td}/subreads_to_ccs.bam',
      '--ccs_bam', f'{td}/ccs.bam',
      '--truth_to_ccs', f'{td}/truth_to_ccs.bam',
      '--truth_bed', f'{td}/truth.bed',
      '--truth_split', f'{td}/truth_split.tsv',
      '--output', '/tmp/no_split_here.tfrecord.gz',
  ])
  assert rc == 2
  assert '@split' in capsys.readouterr().err
