"""Accuracy gates and plumbing tests for the quantized-inference
levers (params.inference_dtype=bfloat16, params.quantize_matmuls=int8).

The gates (satellite of the full-encoder fusion PR):

* int8: held-out alignment_identity within 0.002 of the f32 baseline,
  measured with models/evaluate.run_evaluation over synthetic labeled
  TFRecords (and over the reference eval set where testdata exists).
* bf16: end-to-end FASTQ parity vs f32 on synthetic ZMW BAMs with a
  documented max-QV-delta gate.
* export: both levers are baked into export_meta.json; a mismatched
  from_exported load raises ExportedArtifactMismatchError naming the
  exact re-export command (tested in both directions).
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.inference import runner as runner_lib
from deepconsensus_tpu.io import Example, TFRecordWriter, fastx
from deepconsensus_tpu.models import (
    config as config_lib,
    evaluate as evaluate_lib,
    export as export_lib,
    model as model_lib,
    quantize as quantize_lib,
)
from deepconsensus_tpu import faults as faults_lib

pytestmark = pytest.mark.quant


def _params(layers=2, **kw):
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params, is_training=False)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = layers
    params.filter_size = 64
    params.batch_size = 4
    for k, v in kw.items():
      params[k] = v
  return params


def _init_variables(params, seed=0):
  model = model_lib.get_model(params)
  rows = jnp.zeros((1, params.total_rows, params.max_length, 1))
  return model.init(jax.random.PRNGKey(seed), rows)


def write_labeled_tfrecord(path, params, n_examples=8, seed=5):
  """Synthetic labeled tf.Examples in the reference layout
  (subreads/encoded [total_rows, L, 1] + label/encoded [L]) so the
  identity gate runs without the bundled reference testdata."""
  rng = np.random.default_rng(seed)
  h, length = params.total_rows, params.max_length
  mp = params.max_passes
  with TFRecordWriter(str(path)) as w:
    for i in range(n_examples):
      sub = np.zeros((h, length, 1), np.float32)
      sub[:mp] = rng.integers(0, 5, size=sub[:mp].shape)
      sub[mp:2 * mp] = rng.integers(0, 256, size=sub[:mp].shape)
      sub[2 * mp:3 * mp] = rng.integers(0, 256, size=sub[:mp].shape)
      sub[3 * mp:4 * mp] = rng.integers(0, 3, size=sub[:mp].shape)
      sub[4 * mp] = rng.integers(0, 5, size=sub[4 * mp].shape)
      sub[4 * mp + 1:] = rng.integers(0, 501, size=sub[4 * mp + 1:].shape)
      label = rng.integers(0, 5, size=(length,)).astype(np.float32)
      ex = Example()
      ex.add_bytes('subreads/encoded', [sub.tobytes()])
      ex.add_int64('subreads/shape', list(sub.shape))
      ex.add_bytes('label/encoded', [label.tobytes()])
      ex.add_int64('label/shape', [length])
      ex.add_bytes('name', [f'm0/{i}/ccs'.encode()])
      w.write(ex.serialize())
  return str(path)


# ---------------------------------------------------------------------------
# Lever mechanics.
# ---------------------------------------------------------------------------


def test_prepare_variables_quantizes_and_dequantizes():
  params = _params(quantize_matmuls='int8')
  variables = _init_variables(params)
  out, n_quantized = quantize_lib.prepare_inference_variables(
      variables, params)
  # 4 attention projections + 2 FFN matmuls per encoder layer.
  assert n_quantized == 6 * params.num_hidden_layers
  q = out['quant']['encoder']['ffn_0']['filter_layer']
  assert q['values'].dtype == jnp.int8
  assert q['scale'].dtype == jnp.float32
  # The params leaf is REPLACED by the dequantized weight, so the XLA
  # path and the accuracy gates see the quantized-effective model.
  dequant = np.asarray(q['values'], np.float32) * np.asarray(q['scale'])
  np.testing.assert_allclose(
      np.asarray(out['params']['encoder']['ffn_0']['filter_layer']['kernel']),
      dequant, rtol=1e-6)
  # Round-trip error is bounded by half a quantization step per entry.
  orig = np.asarray(
      variables['params']['encoder']['ffn_0']['filter_layer']['kernel'])
  step = np.asarray(q['scale'])[None, :]
  assert np.all(np.abs(dequant - orig) <= 0.5 * step + 1e-7)


def test_bf16_cast_applies_to_params_only():
  params = _params(inference_dtype='bfloat16', quantize_matmuls='int8')
  variables = _init_variables(params)
  out, _ = quantize_lib.prepare_inference_variables(variables, params)
  leaves = jax.tree_util.tree_leaves(out['params'])
  assert all(l.dtype != jnp.float32 for l in leaves
             if jnp.issubdtype(l.dtype, jnp.floating))
  # int8 values and f32 scales are untouched by the bf16 cast.
  q = out['quant']['encoder']['self_attention_0']['query']
  assert q['values'].dtype == jnp.int8
  assert q['scale'].dtype == jnp.float32


# ---------------------------------------------------------------------------
# Accuracy gates.
# ---------------------------------------------------------------------------


def test_int8_identity_within_gate_of_f32(tmp_path):
  """The int8 acceptance gate: held-out alignment identity within
  0.002 of the f32 baseline, via models/evaluate.run_evaluation."""
  params = _params()
  shard = write_labeled_tfrecord(
      tmp_path / 'eval.tfrecord.gz', params)
  variables = _init_variables(params)

  base = evaluate_lib.run_evaluation(
      params=params, checkpoint_path=None, eval_patterns=[shard],
      out_dir=str(tmp_path / 'f32'), variables=variables)

  params_q = _params(quantize_matmuls='int8')
  variables_q, n_quantized = quantize_lib.prepare_inference_variables(
      variables, params_q)
  assert n_quantized > 0
  quant = evaluate_lib.run_evaluation(
      params=params_q, checkpoint_path=None, eval_patterns=[shard],
      out_dir=str(tmp_path / 'int8'), variables=variables_q)

  delta = abs(quant['alignment_identity'] - base['alignment_identity'])
  assert delta <= config_lib.INT8_IDENTITY_GATE, (
      f'int8 identity gate failed: |delta|={delta:.5f} > '
      f'{config_lib.INT8_IDENTITY_GATE} '
      f'(f32={base["alignment_identity"]:.5f}, '
      f'int8={quant["alignment_identity"]:.5f})')


def test_int8_identity_gate_on_reference_eval_set(tmp_path, testdata_dir):
  """Same 0.002 gate over the bundled reference eval examples (skips
  where the reference testdata is not installed)."""
  params = _params()
  patterns = [str(testdata_dir / 'human_1m/tf_examples/eval/*')]
  variables = _init_variables(params)
  base = evaluate_lib.run_evaluation(
      params=params, checkpoint_path=None, eval_patterns=patterns,
      out_dir=str(tmp_path / 'f32'), variables=variables)
  params_q = _params(quantize_matmuls='int8')
  variables_q, _ = quantize_lib.prepare_inference_variables(
      variables, params_q)
  quant = evaluate_lib.run_evaluation(
      params=params_q, checkpoint_path=None, eval_patterns=patterns,
      out_dir=str(tmp_path / 'int8'), variables=variables_q)
  assert abs(quant['alignment_identity']
             - base['alignment_identity']) <= config_lib.INT8_IDENTITY_GATE


def test_bf16_fused_model_matches_f32():
  """bf16 end-to-end: loose tolerance + near-total argmax agreement
  (the same bar as the attn_softmax_dtype lever — bf16 legitimately
  perturbs logits at ~1e-2)."""
  params = _params()
  variables = _init_variables(params, seed=2)
  rng = np.random.default_rng(3)
  rows = jnp.asarray(rng.integers(
      0, 4, size=(4, params.total_rows, params.max_length, 1)
  ).astype(np.float32))
  ref = model_lib.get_model(params).apply(variables, rows)

  params_bf16 = _params(inference_dtype='bfloat16', dtype='bfloat16',
                        use_fused_hotpath=True)
  variables_bf16, _ = quantize_lib.prepare_inference_variables(
      variables, params_bf16)
  got = model_lib.get_model(params_bf16).apply(variables_bf16, rows)
  got = np.asarray(got, np.float32)
  assert np.all(np.isfinite(got))
  np.testing.assert_allclose(got, np.asarray(ref), atol=5e-2)
  agree = np.mean(got.argmax(-1) == np.asarray(ref).argmax(-1))
  assert agree >= 0.98, f'argmax agreement {agree:.3f}'


# ---------------------------------------------------------------------------
# End-to-end FASTQ: f32 vs bf16 on synthetic ZMW BAMs.
# ---------------------------------------------------------------------------

# Documented QV gate for the bf16 lever: per-base Phred QVs of reads
# whose polished sequence matches the f32 run may move by at most this
# many units (bf16 logit rounding is ~1e-2 relative; on the synthetic
# BAMs the measured max delta is <=1, the gate leaves margin for other
# inputs). Reads whose argmax flips at a near-tie are excluded from
# the per-base comparison but bounded in count below. The value lives
# in models/config.py, the one shared home for gate thresholds.
MAX_QV_DELTA = config_lib.BF16_QV_GATE


def test_gate_thresholds_have_one_shared_home():
  """The runtime flywheel gates and these acceptance tests must use
  the SAME thresholds: both sides import them from models/config.py,
  and this test pins the flywheel re-exports to that home so neither
  can drift silently."""
  from deepconsensus_tpu.models import flywheel as flywheel_lib

  assert flywheel_lib.INT8_IDENTITY_GATE is config_lib.INT8_IDENTITY_GATE
  assert flywheel_lib.BF16_QV_GATE is config_lib.BF16_QV_GATE
  assert MAX_QV_DELTA == config_lib.BF16_QV_GATE


def test_fastq_f32_vs_bf16_qv_delta(tmp_path, synthetic_bams):
  subreads, ccs = synthetic_bams()
  params = _params()
  variables = _init_variables(params, seed=4)

  def run(tag, inference_dtype):
    options = runner_lib.InferenceOptions(
        batch_size=32, batch_zmws=4, min_quality=0,
        inference_dtype=inference_dtype)
    p = _params()
    runner_lib._apply_quant_levers(p, options)
    runner = runner_lib.ModelRunner(p, variables, options)
    out = str(tmp_path / f'{tag}.fastq')
    counters = runner_lib.run_inference(
        subreads_to_ccs=subreads, ccs_bam=ccs, checkpoint=None,
        output=out, options=options, runner=runner)
    return counters, {name: (seq, qual)
                      for name, seq, qual in fastx.read_fastq(out)}

  counters32, reads32 = run('f32', None)
  counters16, reads16 = run('bf16', 'bfloat16')

  # The non-numeric inference_dtype label must survive the counter
  # merge (plain Counter.update would TypeError on strings).
  assert counters32['inference_dtype'] == 'float32'
  assert counters16['inference_dtype'] == 'bfloat16'
  assert counters16['n_zmw_pass'] == counters32['n_zmw_pass'] > 0

  assert set(reads16) == set(reads32)
  same_seq = [n for n in reads32 if reads16[n][0] == reads32[n][0]]
  # bf16 near-tie argmax flips may change a few bases; most reads must
  # polish to the identical sequence.
  assert len(same_seq) * 2 >= len(reads32), (
      f'only {len(same_seq)}/{len(reads32)} reads match between f32 '
      'and bf16')
  max_delta = 0
  for name in same_seq:
    q32 = np.frombuffer(reads32[name][1].encode(), np.uint8)
    q16 = np.frombuffer(reads16[name][1].encode(), np.uint8)
    max_delta = max(max_delta, int(np.abs(
        q32.astype(int) - q16.astype(int)).max()))
  assert max_delta <= MAX_QV_DELTA, (
      f'bf16 QV gate failed: max per-base delta {max_delta} > '
      f'{MAX_QV_DELTA}')


def test_runner_dispatch_stats_reports_levers():
  params = _params()
  variables = _init_variables(params)
  options = runner_lib.InferenceOptions(
      batch_size=32, inference_dtype='bfloat16', quantize_matmuls='int8')
  p = _params()
  runner_lib._apply_quant_levers(p, options)
  runner = runner_lib.ModelRunner(p, variables, options)
  stats = runner.dispatch_stats()
  assert stats['inference_dtype'] == 'bfloat16'
  assert stats['n_quantized_matmuls'] == 6 * params.num_hidden_layers

  # Levers off: explicit f32 label, zero quantized matmuls.
  plain = runner_lib.ModelRunner(
      _params(), variables, runner_lib.InferenceOptions(batch_size=32))
  stats = plain.dispatch_stats()
  assert stats['inference_dtype'] == 'float32'
  assert stats['n_quantized_matmuls'] == 0


def test_bf16_int8_runner_predict_agrees_with_f32():
  params = _params()
  variables = _init_variables(params, seed=6)
  rng = np.random.default_rng(7)
  rows = rng.integers(
      0, 4, size=(8, params.total_rows, params.max_length, 1)
  ).astype(np.float32)

  base = runner_lib.ModelRunner(
      _params(), variables, runner_lib.InferenceOptions(batch_size=8))
  ids_b, q_b = base.predict(rows)

  options = runner_lib.InferenceOptions(
      batch_size=8, inference_dtype='bfloat16', quantize_matmuls='int8')
  p = _params(use_fused_hotpath=True)
  runner_lib._apply_quant_levers(p, options)
  quant = runner_lib.ModelRunner(p, variables, options)
  ids_q, q_q = quant.predict(rows)

  assert np.all(np.isfinite(np.asarray(q_q, np.float32)))
  agree = np.mean(np.asarray(ids_q) == np.asarray(ids_b))
  assert agree >= 0.95, f'base agreement {agree:.3f}'


# ---------------------------------------------------------------------------
# Exported artifacts: levers baked into metadata, mismatch refused.
# ---------------------------------------------------------------------------


def _export(tmp_path, tag, **levers):
  params = _params(layers=1)
  variables = _init_variables(params)
  export_dir = str(tmp_path / tag)
  export_lib.export_model(
      checkpoint_path=export_dir, out_dir=export_dir, batch_size=8,
      variables=variables, params=params, **levers)
  return export_dir


def test_export_bakes_levers_into_metadata(tmp_path):
  export_dir = _export(tmp_path, 'quant', inference_dtype='bfloat16',
                       quantize_matmuls='int8')
  with open(f'{export_dir}/export_meta.json') as f:
    meta = json.load(f)
  assert meta['inference_dtype'] == 'bfloat16'
  assert meta['quantize_matmuls'] == 'int8'
  # No levers requested -> explicit defaults recorded.
  plain_dir = _export(tmp_path, 'plain')
  with open(f'{plain_dir}/export_meta.json') as f:
    meta = json.load(f)
  assert meta['inference_dtype'] == 'float32'
  assert meta['quantize_matmuls'] == 'none'


def test_exported_lever_mismatch_raises_both_directions(tmp_path):
  quant_dir = _export(tmp_path, 'quant', inference_dtype='bfloat16',
                      quantize_matmuls='int8')
  plain_dir = _export(tmp_path, 'plain')

  # Baked bf16/int8, caller explicitly demands f32: refused, and the
  # fault names the exact re-export command.
  with pytest.raises(faults_lib.ExportedArtifactMismatchError) as excinfo:
    runner_lib.ModelRunner.from_exported(
        quant_dir,
        runner_lib.InferenceOptions(batch_size=8,
                                    inference_dtype='float32'))
  err = excinfo.value
  assert err.reexport_command and 'dctpu export' in err.reexport_command
  assert '--inference_dtype float32' in err.reexport_command
  assert err.reexport_command in str(err)

  # Baked plain, caller explicitly demands int8: also refused.
  with pytest.raises(faults_lib.ExportedArtifactMismatchError) as excinfo:
    runner_lib.ModelRunner.from_exported(
        plain_dir,
        runner_lib.InferenceOptions(batch_size=8, quantize_matmuls='int8'))
  assert '--quantize_matmuls int8' in excinfo.value.reexport_command


def test_exported_lever_match_and_none_accepted(tmp_path):
  export_dir = _export(tmp_path, 'quant', inference_dtype='bfloat16',
                       quantize_matmuls='int8')
  # Explicitly matching levers load fine.
  runner_lib.ModelRunner.from_exported(
      export_dir,
      runner_lib.InferenceOptions(batch_size=8, inference_dtype='bfloat16',
                                  quantize_matmuls='int8'))
  # No preference (None) accepts the artifact as-is — flag-less loads
  # of quantized artifacts keep working.
  runner = runner_lib.ModelRunner.from_exported(
      export_dir, runner_lib.InferenceOptions(batch_size=8))
  assert runner.dispatch_stats()['inference_dtype'] == 'bfloat16'
