"""Two-process jax.distributed training over localhost (CPU backend).

Validates the real multi-host wiring — distributed.initialize, per-host
local_batch_slice feeding, host_local_to_global assembly, and
process-0-only checkpoint/metric writes — the JAX counterpart of the
reference's TPUStrategy pod path (model_train_custom_loop.py:333-343).
"""
import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent('''
    import json, os, sys
    import jax

    jax.config.update('jax_platforms', 'cpu')
    jax.config.update('jax_num_cpu_devices', 2)

    port, pid, out_dir, data_pattern = sys.argv[1:5]
    from deepconsensus_tpu.models import config as config_lib
    from deepconsensus_tpu.models import train as train_lib

    params = config_lib.get_config('transformer_learn_values+test')
    config_lib.finalize_params(params)
    with params.unlocked():
      params.dtype = 'float32'
      params.batch_size = 8
      params.num_hidden_layers = 1
      params.filter_size = 32
      params.warmup_steps = 2

    metrics = train_lib.run_training(
        params=params,
        out_dir=out_dir,
        train_patterns=[data_pattern],
        eval_patterns=[data_pattern],
        num_epochs=1,
        eval_every=10**9,
        distributed_config={
            'coordinator_address': f'localhost:{port}',
            'num_processes': 2,
            'process_id': int(pid),
        },
    )
    print('RESULT ' + json.dumps({
        'process': jax.process_index(),
        'n_processes': jax.process_count(),
        'n_devices': jax.device_count(),
        'loss': metrics['eval/loss'],
    }))
''')


def _free_port() -> int:
  with socket.socket() as s:
    s.bind(('localhost', 0))
    return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_training(tmp_path, testdata_dir):
  port = _free_port()
  out_dir = str(tmp_path / 'multihost')
  pattern = str(testdata_dir / 'human_1m/tf_examples/eval/*')
  env = {
      **os.environ,
      'PYTHONPATH': REPO_ROOT,
      'JAX_PLATFORMS': 'cpu',
      'XLA_FLAGS': '',
  }
  procs = [
      subprocess.Popen(
          [sys.executable, '-c', _WORKER, str(port), str(pid), out_dir,
           pattern],
          stdout=subprocess.PIPE,
          stderr=subprocess.PIPE,
          text=True,
          env=env,
      )
      for pid in (0, 1)
  ]
  results = {}
  for pid, proc in enumerate(procs):
    try:
      stdout, stderr = proc.communicate(timeout=600)
    except subprocess.TimeoutExpired:
      for p in procs:
        p.kill()
      pytest.fail(f'process {pid} timed out')
    assert proc.returncode == 0, (
        f'process {pid} failed:\n{stderr[-3000:]}'
    )
    for line in stdout.splitlines():
      if line.startswith('RESULT '):
        results[pid] = json.loads(line[len('RESULT '):])
  assert set(results) == {0, 1}
  for pid, r in results.items():
    assert r['n_processes'] == 2, r
    assert r['n_devices'] == 4, r
  # Replicated state: both hosts converge to the identical eval loss.
  assert results[0]['loss'] == pytest.approx(results[1]['loss'], rel=1e-6)
  # Only process 0 writes checkpoints and metric sidecars.
  ckpts = os.listdir(os.path.join(out_dir, 'checkpoints'))
  assert any(c.startswith('checkpoint-') for c in ckpts)
  assert os.path.exists(os.path.join(out_dir, 'metrics.jsonl'))
