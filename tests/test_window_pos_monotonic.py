"""Per-ZMW window positions are strictly increasing (the reference's
preprocess e2e assertion: preprocess_test.py:63-180)."""
import collections

from deepconsensus_tpu.io import tfrecord
from deepconsensus_tpu.io.example_proto import Example
from deepconsensus_tpu.preprocess.driver import run_preprocess


def test_window_pos_monotonic_per_zmw(testdata_dir, tmp_path):
  td = str(testdata_dir / 'human_1m')
  out = str(tmp_path / '@split.tfrecord.gz')
  run_preprocess(
      subreads_to_ccs=f'{td}/subreads_to_ccs.bam',
      ccs_bam=f'{td}/ccs.bam',
      output=out,
      ins_trim=5,
      limit=5,
  )
  positions = collections.defaultdict(list)
  for raw in tfrecord.read_tfrecords(out.replace('@split', 'inference')):
    ex = Example.parse(raw)
    positions[ex['name'][0]].append(ex['window_pos'][0])
  assert positions
  for name, pos in positions.items():
    assert pos == sorted(pos), name
    assert len(set(pos)) == len(pos), name
