"""The conv (BatchNorm) family trains through the shared loop."""
import numpy as np

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import train as train_lib


def test_conv_net_trains(tmp_path, testdata_dir):
  params = config_lib.get_config('conv_net+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.batch_size = 8
    params.warmup_steps = 2
    # Shrink the trunk for CPU test speed.
    params.conv_model = 'resnet50'
  import deepconsensus_tpu.models.convnet as convnet

  orig = convnet.RESNET_DEPTHS['resnet50']
  convnet.RESNET_DEPTHS['resnet50'] = (1, 1, 1, 1)
  try:
    metrics = train_lib.run_training(
        params=params,
        out_dir=str(tmp_path / 'conv'),
        train_patterns=[str(testdata_dir / 'human_1m/tf_examples/eval/*')],
        eval_patterns=[str(testdata_dir / 'human_1m/tf_examples/eval/*')],
        num_epochs=1,
        eval_every=10**9,
    )
  finally:
    convnet.RESNET_DEPTHS['resnet50'] = orig
  assert np.isfinite(metrics['eval/loss'])
