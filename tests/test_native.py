"""Native C++ accelerator parity tests (skipped when g++ unavailable)."""
import pytest

from deepconsensus_tpu import native
from deepconsensus_tpu.io import bam, tfrecord


@pytest.fixture(scope='module')
def lib():
  lib = native.get_lib()
  if lib is None:
    pytest.skip('native library unavailable')
  return lib


def test_crc32c_parity(lib):
  for data in (b'', b'123456789', b'\x00' * 100, bytes(range(256)) * 7):
    assert native.crc32c(data) == tfrecord._crc32c_py(data)


def test_bgzf_native_matches_gzip(lib, testdata_dir):
  path = str(testdata_dir / 'human_1m/subreads_to_ccs.bam')
  native_names = [r.qname for r in bam.BamReader(path, use_native=True)]
  python_names = [r.qname for r in bam.BamReader(path, use_native=False)]
  assert native_names == python_names
  assert len(native_names) > 50


def test_bgzf_decompress_roundtrip_with_our_writer(lib, tmp_path):
  from deepconsensus_tpu.io.bam_writer import BgzfWriter

  path = str(tmp_path / 'data.bgzf')
  payload = bytes(range(256)) * 1000
  with BgzfWriter(path) as w:
    w.write(payload)
  out = native.bgzf_decompress_file(path)
  assert out == payload


def test_bgzf_decompress_file_respects_max_out(lib, tmp_path):
  from deepconsensus_tpu.io.bam_writer import BgzfWriter

  path = str(tmp_path / 'data.bgzf')
  payload = bytes(range(256)) * 1000
  with BgzfWriter(path) as w:
    w.write(payload)
  assert native.bgzf_decompress_file(path, max_out=1024) is None
  assert native.bgzf_decompress_file(path, max_out=len(payload)) == payload


@pytest.mark.resilience
def test_bgzf_corrupt_input_parity(lib, tmp_path):
  """ISSUE 4 satellite: the same mutated BGZF file must produce the
  same accept/reject outcome through native bgzf_decompress_file and
  the pure-Python path — in particular the native path must NEVER
  accept bytes (or different bytes) where Python rejects or differs."""
  import os

  from deepconsensus_tpu.faults import CorruptInputError
  from deepconsensus_tpu.io.bam_writer import BgzfWriter
  from scripts import inject_faults

  src_path = str(tmp_path / 'seed.bgzf')
  import numpy as np

  rng = np.random.RandomState(3)
  with BgzfWriter(src_path) as w:
    w.write(rng.bytes(150_000))
  with open(src_path, 'rb') as f:
    src = f.read()
  mutant = str(tmp_path / 'mutant.bgzf')
  n_mutants = int(os.environ.get('DCTPU_FUZZ_MUTANTS', '500'))
  n_py_rejects = n_native_rejects = 0
  for i, mode, data in inject_faults.fuzz_mutants(src, n_mutants,
                                                  seed=99):
    with open(mutant, 'wb') as f:
      f.write(data)
    try:
      py_out = bam.bgzf_decompress_file_py(mutant)
    except CorruptInputError:
      py_out = None
      n_py_rejects += 1
    native_out = native.bgzf_decompress_file(mutant)
    if native_out is None:
      n_native_rejects += 1
    if py_out is None:
      assert native_out is None, (
          f'mutant {i} ({mode}): native accepted input Python rejects')
    elif native_out is not None:
      assert native_out == py_out, (
          f'mutant {i} ({mode}): native decoded different bytes')
  assert n_py_rejects > 0  # the corpus exercised the reject paths


@pytest.mark.resilience
def test_tfrecord_corrupt_native_falls_back_to_typed_error(lib, tmp_path):
  """A TFRecord shard with a corrupt length header: the native
  whole-shard decode returns None (framing reject) and the streaming
  path raises CorruptInputError — no bare error through either path."""
  from deepconsensus_tpu.faults import CorruptInputError

  path = str(tmp_path / 'shard.tfrecord')
  with tfrecord.TFRecordWriter(path) as w:
    w.write(b'payload-a')
    w.write(b'payload-b')
  with open(path, 'r+b') as f:
    f.write((1 << 50).to_bytes(8, 'little'))  # inflate first length
  assert native.read_tfrecord_records(path, compressed=False) is None
  with pytest.raises(CorruptInputError):
    for _ in tfrecord.TFRecordReader(path):
      pass


def test_native_tfrecord_validates_length_crc(lib, tmp_path):
  """The native indexer must reject a length whose CRC does not match
  even when the inflated length still fits the buffer (framing
  desync), matching the hardened Python reader."""
  path = str(tmp_path / 'shard.tfrecord')
  with tfrecord.TFRecordWriter(path) as w:
    w.write(b'x' * 100)
    w.write(b'y' * 100)
  with open(path, 'r+b') as f:
    f.write((5).to_bytes(8, 'little'))  # plausible but CRC-stale length
  assert native.read_tfrecord_records(path, compressed=False) is None
