"""Native C++ accelerator parity tests (skipped when g++ unavailable)."""
import pytest

from deepconsensus_tpu import native
from deepconsensus_tpu.io import bam, tfrecord


@pytest.fixture(scope='module')
def lib():
  lib = native.get_lib()
  if lib is None:
    pytest.skip('native library unavailable')
  return lib


def test_crc32c_parity(lib):
  for data in (b'', b'123456789', b'\x00' * 100, bytes(range(256)) * 7):
    assert native.crc32c(data) == tfrecord._crc32c_py(data)


def test_bgzf_native_matches_gzip(lib, testdata_dir):
  path = str(testdata_dir / 'human_1m/subreads_to_ccs.bam')
  native_names = [r.qname for r in bam.BamReader(path, use_native=True)]
  python_names = [r.qname for r in bam.BamReader(path, use_native=False)]
  assert native_names == python_names
  assert len(native_names) > 50


def test_bgzf_decompress_roundtrip_with_our_writer(lib, tmp_path):
  from deepconsensus_tpu.io.bam_writer import BgzfWriter

  path = str(tmp_path / 'data.bgzf')
  payload = bytes(range(256)) * 1000
  with BgzfWriter(path) as w:
    w.write(payload)
  out = native.bgzf_decompress_file(path)
  assert out == payload
