"""Ragged window attention: geometry, kernel parity, model routing.

Three layers of the single-pack-stream contract (ISSUE 17):

  * `slot_geometry` / `ragged_attention_mask` — the lengths-derived
    geometry both the kernel and the XLA model path share;
  * the Pallas kernel against `reference_ragged_forward` in interpret
    mode, at every DEFAULT_WINDOW_BUCKETS width and at an overflow
    width above FUSED_MAX_WINDOW_LEN;
  * the model's XLA ragged apply (window_lengths=...) BITWISE against
    the per-width bucketed applies — this is the path that carries the
    engine's byte-identity guarantee.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from test_fused_hotpath import make_params, nonzero_alphas

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.ops import fused_window_attention as fwa
from deepconsensus_tpu.ops import ragged_window_attention as rwa

BUCKETS = config_lib.DEFAULT_WINDOW_BUCKETS


def fake_rows_at(params, width, batch, seed):
  """fake_rows at an arbitrary window width, with the SN rows constant
  per window across positions (as the real featurizer emits them —
  the ragged dispatch path extracts one SN scalar per window)."""
  rng = np.random.default_rng(seed)
  rows = np.zeros((batch, params.total_rows, width, 1), dtype=np.float32)
  mp = params.max_passes
  rows[:, :mp] = rng.integers(0, 5, size=rows[:, :mp].shape)
  rows[:, mp:2 * mp] = rng.integers(0, 256, size=rows[:, :mp].shape)
  rows[:, 2 * mp:3 * mp] = rng.integers(0, 256, size=rows[:, :mp].shape)
  rows[:, 3 * mp:4 * mp] = rng.integers(0, 3, size=rows[:, :mp].shape)
  rows[:, 4 * mp] = rng.integers(0, 5, size=rows[:, 4 * mp].shape)
  if params.use_ccs_bq:
    rows[:, 4 * mp + 1] = rng.integers(
        -1, params.CCS_BQ_MAX - 1, size=rows[:, 4 * mp + 1].shape)
    sn_lo = 4 * mp + 2
  else:
    sn_lo = 4 * mp + 1
  sn = rng.integers(0, 501, size=(batch, rows.shape[1] - sn_lo, 1, 1))
  rows[:, sn_lo:] = np.broadcast_to(sn, rows[:, sn_lo:].shape)
  return rows


# ----------------------------------------------------------------------
# Geometry helpers


def test_validate_buckets_accepts_divisibility_chain():
  assert rwa.validate_ragged_buckets((100, 200)) == (100, 200)
  assert rwa.validate_ragged_buckets((50, 100, 200)) == (50, 100, 200)
  assert rwa.windows_per_slot((100, 200)) == 2
  assert rwa.windows_per_slot((50, 100, 200)) == 4


@pytest.mark.parametrize('bad,match', [
    ((), 'positive'),
    ((100, 0), 'positive'),
    ((200, 100), 'ascending'),
    ((100, 100, 200), 'ascending'),
    ((100, 150), 'divisibility chain'),
])
def test_validate_buckets_rejects(bad, match):
  with pytest.raises(ValueError, match=match):
    rwa.validate_ragged_buckets(bad)


def test_slot_geometry_mixed_slots():
  lengths = jnp.asarray([[200, 0], [100, 100], [100, 0]], jnp.int32)
  seg, start, width, valid = rwa.slot_geometry(lengths, 200)
  seg, start, width, valid = map(np.asarray, (seg, start, width, valid))
  # Slot 0: one window spanning all 200 positions.
  assert (seg[0] == 0).all() and (start[0] == 0).all()
  assert (width[0] == 200).all() and valid[0].all()
  # Slot 1: window 0 at [0,100), window 1 at [100,200).
  assert (seg[1, :100] == 0).all() and (seg[1, 100:] == 1).all()
  assert (start[1, :100] == 0).all() and (start[1, 100:] == 100).all()
  assert (width[1] == 100).all() and valid[1].all()
  # Slot 2: half-filled — tail positions invalid, seg stays 0 there.
  assert valid[2, :100].all() and not valid[2, 100:].any()
  assert (seg[2] == 0).all()


def test_ragged_attention_mask_is_blockwise_band():
  lengths = jnp.asarray([[100, 100]], jnp.int32)
  win = 12
  mask = np.asarray(rwa.ragged_attention_mask(lengths, 200, win))[0]
  # No attention across the window seam, in either direction.
  assert not mask[:100, 100:].any() and not mask[100:, :100].any()
  # Within a window the mask equals the per-width band: |i-j| <= win.
  ii, jj = np.meshgrid(np.arange(100), np.arange(100), indexing='ij')
  band = np.abs(ii - jj) <= win
  np.testing.assert_array_equal(mask[:100, :100], band)
  np.testing.assert_array_equal(mask[100:, 100:], band)
  # Unused capacity attends to nothing and is attended by nothing.
  half = np.asarray(rwa.ragged_attention_mask(
      jnp.asarray([[100, 0]], jnp.int32), 200, win))[0]
  assert not half[100:, :].any() and not half[:, 100:].any()


# ----------------------------------------------------------------------
# Pallas kernel vs jnp reference (interpret mode)


@pytest.fixture(scope='module')
def ragged_setup():
  params = make_params(pre=dict(window_buckets=BUCKETS))
  model = model_lib.get_model(params)
  init_rows = jnp.asarray(fake_rows_at(params, BUCKETS[0], 2, 0))
  variables = nonzero_alphas(model.init(jax.random.PRNGKey(0), init_rows))
  specs, keys, _ = fwa.build_family_specs(params)
  p = variables['params']
  tables = {k: p[f'{k}_embedding']['embedding'] for k in keys}
  h = params.hidden_size
  a0 = p['encoder']['self_attention_0']
  weights = dict(
      w_cond=p['condenser']['kernel'],
      wq=a0['query']['kernel'].reshape(h, h),
      wk=a0['key']['kernel'].reshape(h, h),
      wv=a0['value']['kernel'].reshape(h, h),
      wo=a0['output_transform']['kernel'].reshape(h, h))
  kwargs = dict(specs=specs, table_keys=keys, num_heads=params.num_heads,
                attn_win_size=params.attn_win_size or None)
  return params, model, variables, tables, weights, kwargs


def _slots_and_lengths(params, widths_per_slot, slot_len, seed=7):
  """Build a [n_slots, R, slot_len] pack + lengths from a width plan."""
  rng_seed = seed
  n_slots = len(widths_per_slot)
  wps = max(len(ws) for ws in widths_per_slot)
  slots = np.zeros((n_slots, params.total_rows, slot_len, 1), np.float32)
  lengths = np.zeros((n_slots, wps), np.int32)
  for s, ws in enumerate(widths_per_slot):
    off = 0
    for j, w in enumerate(ws):
      slots[s, :, off:off + w] = fake_rows_at(params, w, 1, rng_seed)[0]
      lengths[s, j] = w
      off += w
      rng_seed += 1
  return jnp.asarray(np.squeeze(slots, -1)), jnp.asarray(lengths)


def _run_pair(setup, widths_per_slot, slot_len):
  params, _model, _variables, tables, weights, kwargs = setup
  ids, lengths = _slots_and_lengths(params, widths_per_slot, slot_len)
  pos = jnp.asarray(model_lib.sinusoidal_position_encoding(
      slot_len, params.hidden_size))
  args = (ids, lengths, tables, weights['w_cond'], weights['wq'],
          weights['wk'], weights['wv'], weights['wo'], pos)
  ref = rwa.reference_ragged_forward(*args, **kwargs)
  got = rwa.ragged_embed_condense_attention(*args, **kwargs, interpret=True)
  return ref, got


@pytest.mark.parametrize('width', BUCKETS)
def test_kernel_interpret_parity_uniform_width(ragged_setup, width):
  """Slots uniformly packed at one bucket width — the degenerate mix
  every pure stream produces — must match the reference exactly."""
  slot_len = BUCKETS[-1]
  per_slot = slot_len // width
  (xb_r, at_r), (xb_k, at_k) = _run_pair(
      ragged_setup, [[width] * per_slot, [width] * per_slot], slot_len)
  np.testing.assert_allclose(xb_k, xb_r, rtol=0, atol=1e-6)
  np.testing.assert_allclose(at_k, at_r, rtol=0, atol=1e-6)


def test_kernel_interpret_parity_mixed_and_partial(ragged_setup):
  """The real mixed-stream shapes: a full wide slot, a full pair of
  narrow windows, and a partial slot with trailing unused capacity."""
  slot_len = BUCKETS[-1]
  (xb_r, at_r), (xb_k, at_k) = _run_pair(
      ragged_setup,
      [[slot_len], [BUCKETS[0], BUCKETS[0]], [BUCKETS[0]]], slot_len)
  np.testing.assert_allclose(xb_k, xb_r, rtol=0, atol=1e-6)
  np.testing.assert_allclose(at_k, at_r, rtol=0, atol=1e-6)


def test_kernel_interpret_parity_overflow_width(ragged_setup):
  """One width above the largest bucket (and FUSED_MAX_WINDOW_LEN):
  the slot layout doesn't care what widths the engine buckets to, only
  that slot_len stays under RAGGED_MAX_SLOT_LEN."""
  assert 256 > BUCKETS[-1]
  (xb_r, at_r), (xb_k, at_k) = _run_pair(ragged_setup, [[256]], 256)
  np.testing.assert_allclose(xb_k, xb_r, rtol=0, atol=1e-6)
  np.testing.assert_allclose(at_k, at_r, rtol=0, atol=1e-6)


def test_kernel_rejects_oversized_slot(ragged_setup):
  params = ragged_setup[0]
  with pytest.raises(ValueError, match='RAGGED_MAX_SLOT_LEN'):
    _run_pair(ragged_setup, [[rwa.RAGGED_MAX_SLOT_LEN + 128]],
              rwa.RAGGED_MAX_SLOT_LEN + 128)


def test_ragged_reference_matches_narrow_fused_reference(ragged_setup):
  """A narrow window computed inside a ragged slot agrees with the
  bucketed fused reference computing it at its natural width."""
  params, _model, _variables, tables, weights, kwargs = ragged_setup
  w = BUCKETS[0]
  narrow = fake_rows_at(params, w, 2, 31)
  slot_len = BUCKETS[-1]
  slots = np.zeros((1, params.total_rows, slot_len), np.float32)
  slots[0, :, :w] = narrow[0, :, :, 0]
  slots[0, :, w:2 * w] = narrow[1, :, :, 0]
  lengths = jnp.asarray([[w, w]], jnp.int32)
  pos_s = jnp.asarray(model_lib.sinusoidal_position_encoding(
      slot_len, params.hidden_size))
  pos_n = jnp.asarray(model_lib.sinusoidal_position_encoding(
      w, params.hidden_size))
  _xb_r, at_r = rwa.reference_ragged_forward(
      jnp.asarray(slots), lengths, tables, weights['w_cond'],
      weights['wq'], weights['wk'], weights['wv'], weights['wo'],
      pos_s, **kwargs)
  _xb_n, at_n = fwa.reference_fused_forward(
      jnp.asarray(np.squeeze(narrow, -1)), tables, weights['w_cond'],
      weights['wq'], weights['wk'], weights['wv'], weights['wo'],
      pos_n, **kwargs)
  np.testing.assert_allclose(at_r[0, :w], at_n[0], rtol=0, atol=1e-5)
  np.testing.assert_allclose(at_r[0, w:2 * w], at_n[1], rtol=0, atol=1e-5)


# ----------------------------------------------------------------------
# XLA model routing: ragged apply is BITWISE vs per-width applies


def test_model_ragged_apply_bitwise_vs_per_width(ragged_setup):
  """The byte-identity mechanism: the ragged apply computes each bucket
  width over the reshaped slots — THE SAME SHAPE as a per-width apply
  of that reshape — so a plain apply on the reshaped content must agree
  bit-for-bit at every position the lengths vector owns. (Cross-shape
  agreement — e.g. vs a standalone batch-of-1 apply — is ~1-ulp, since
  XLA's CPU tiling varies with batch; the engine's FASTQ byte identity
  is carried by the shape-matched compute plus uint8 quantization, and
  asserted end-to-end in test_ragged_engine.py.)"""
  params, model, variables, *_ = ragged_setup
  wide, narrow = BUCKETS[-1], BUCKETS[0]
  per_slot = wide // narrow
  w_wide = fake_rows_at(params, wide, 1, 7)
  w_narrow = fake_rows_at(params, narrow, per_slot + 1, 11)

  r = params.total_rows
  slots = np.zeros((3, r, wide, 1), np.float32)
  lengths = np.zeros((3, per_slot), np.int32)
  slots[0] = w_wide[0]
  lengths[0, 0] = wide
  for j in range(per_slot):
    slots[1, :, j * narrow:(j + 1) * narrow] = w_narrow[j]
    lengths[1, j] = narrow
  slots[2, :, :narrow] = w_narrow[per_slot]
  lengths[2, 0] = narrow

  got = np.asarray(model.apply(
      variables, jnp.asarray(slots), False,
      window_lengths=jnp.asarray(lengths),
      method='apply_with_intermediates')['preds'])

  # Per-width references at the ragged path's own reshape batch: the
  # slots read as 3 wide windows, or (splitting the position axis) as
  # 6 narrow windows in slot-major order.
  ref_wide = np.asarray(model.apply(
      variables, jnp.asarray(slots), False,
      method='apply_with_intermediates')['preds'])
  as_narrow = slots.reshape(3, r, per_slot, narrow, 1).transpose(
      0, 2, 1, 3, 4).reshape(3 * per_slot, r, narrow, 1)
  ref_narrow = np.asarray(model.apply(
      variables, jnp.asarray(as_narrow), False,
      method='apply_with_intermediates')['preds'])

  np.testing.assert_array_equal(got[0, :wide], ref_wide[0])
  for j in range(per_slot):
    np.testing.assert_array_equal(
        got[1, j * narrow:(j + 1) * narrow], ref_narrow[per_slot + j])
  np.testing.assert_array_equal(
      got[2, :narrow], ref_narrow[2 * per_slot])

  # Cross-shape (standalone per-window applies): numerically tight but
  # not bitwise — XLA reassociates tiling across batch shapes.
  alone = np.asarray(model.apply(
      variables, jnp.asarray(w_narrow), False,
      method='apply_with_intermediates')['preds'])
  np.testing.assert_allclose(got[2, :narrow], alone[per_slot],
                             rtol=0, atol=1e-5)
