import numpy as np
import pytest

from deepconsensus_tpu import constants
from deepconsensus_tpu.utils import phred


def test_vocab_layout():
  assert constants.SEQ_VOCAB == ' ATCG'
  assert constants.GAP_INT == 0
  assert constants.SEQ_VOCAB_SIZE == 5


def test_encoded_sequence_to_string():
  assert phred.encoded_sequence_to_string(np.array([1, 2, 0, 3, 4])) == 'AT CG'


def test_quality_string_roundtrip():
  scores = [0, 10, 20, 40, 93]
  s = phred.quality_scores_to_string(scores)
  assert s == '!+5I~'
  assert phred.quality_string_to_array(s) == scores
  assert phred.quality_score_to_string(0) == '!'


def test_quality_string_uint8_fast_path():
  # The device-epilogue drain hands uint8 planes straight to the
  # emitters; the fast path must byte-match the generic int path.
  scores = [0, 10, 20, 40, 93]
  want = phred.quality_scores_to_string(scores)
  got = phred.quality_scores_to_string(np.asarray(scores, np.uint8))
  assert got == want == '!+5I~'
  assert phred.quality_scores_to_bytes(
      np.asarray(scores, np.uint8)) == want.encode('ascii')
  # Full device range stays lossless (93+33=126 is the top of ASCII
  # printables, the FASTQ ceiling the epilogue's clamp guarantees).
  full = np.arange(94, dtype=np.uint8)
  assert phred.quality_scores_to_string(full) == (
      phred.quality_scores_to_string(full.astype(np.int64)))


def test_avg_phred_prob_domain():
  # Mean in probability domain, not phred domain.
  got = phred.avg_phred([10, 30])
  probs = np.array([1e-1, 1e-3])
  want = -10 * np.log10(probs.mean())
  assert got == pytest.approx(want)


def test_avg_phred_ignores_negative():
  assert phred.avg_phred([-1, -1, 20]) == pytest.approx(20.0)
  assert phred.avg_phred([-1, -1]) == 0.0
  assert phred.avg_phred([0, 0]) == 0.0


def test_left_shift_seq():
  seq = np.array([0, 1, 0, 2, 3, 0])
  np.testing.assert_array_equal(
      phred.left_shift_seq(seq), np.array([1, 2, 3, 0, 0, 0])
  )


def test_left_shift_batch():
  batch = np.array([[0, 1, 0, 2], [4, 0, 0, 3]])
  np.testing.assert_array_equal(
      phred.left_shift(batch), np.array([[1, 2, 0, 0], [4, 3, 0, 0]])
  )
