from deepconsensus_tpu.calibration import yield_metrics


def test_yield_metrics_on_assessment_data(testdata_dir, tmp_path):
  bam = str(
      testdata_dir
      / 'prediction_assessment/CHM13_chr20_0_200000_dc.to_truth.bam'
  )
  ref = str(testdata_dir / 'prediction_assessment/CHM13_chr20_0_200000.fa')
  out = str(tmp_path / 'yield.csv')
  rows = yield_metrics.calculate_yield_metrics(bam, ref, output=out)
  assert [r['quality_threshold'] for r in rows] == [20, 30, 40]
  q20 = rows[0]
  assert q20['num_reads'] > 0
  # Polished reads against truth: high mean identity, with a subset
  # clearing the 0.999 yield bar.
  assert q20['mean_identity'] > 0.9
  assert q20['num_reads_identity_ok'] > 0
  # Monotonic: tighter threshold keeps fewer (or equal) reads.
  assert rows[0]['num_reads'] >= rows[1]['num_reads'] >= rows[2]['num_reads']
  with open(out) as f:
    header = f.readline()
  assert 'yield_bases' in header


def test_assess_read_counts():
  import numpy as np

  from deepconsensus_tpu.io.bam import BamRecord

  rec = BamRecord(
      qname='r1', flag=0, ref_id=0, pos=2, mapq=60,
      cigar_ops=np.array([0, 1, 0, 2, 0], np.uint8),   # 2M 1I 2M 1D 1M
      cigar_lens=np.array([2, 1, 2, 1, 1], np.int32),
      seq='ACGTTA', quals=np.full(6, 30, np.int32),
      reference_name='chr',
  )
  ref = {'chr': 'NNACGTAAT'}
  out = yield_metrics.assess_read(rec, ref)
  # ref[2:4]=AC vs AC -> 2 matches; ins G; ref[4:6]=GT vs TT -> 1 match
  # 1 mismatch; del 1; ref[7]=A vs A -> match.
  assert out.matches == 4
  assert out.mismatches == 1
  assert out.insertions == 1
  assert out.deletions == 1
  assert abs(out.identity - 4 / 7) < 1e-9
