"""Device fault domain: classification, watchdog, bisection, mesh fallback.

Every injected fault here fires inside the REAL ModelRunner dispatch
path (faults.injected_device_fault / injected_device_hang live in
_launch), so these tests use random-init weights rather than the stub
runners other suites lean on. The mesh tests run on the 8 forced
host-platform devices (tests/conftest.py), proving the acceptance
criterion on CPU: a dp=8 run losing a device mid-stream degrades to
dp=4, resubmits the failed pack, and stays byte-identical to a clean
single-device run — for both the engine and the resident service.

The device hooks are consume-once per PROCESS (faults._fired), so every
clean baseline runs BEFORE its env hook is armed, and the `arm` fixture
re-arms the latch on teardown for later tests in the same process.
"""
import contextlib
import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu import faults as shared_faults
from deepconsensus_tpu.inference import engine as engine_lib
from deepconsensus_tpu.inference import faults as inf_faults
from deepconsensus_tpu.inference import runner as runner_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib

pytestmark = pytest.mark.resilience

BATCH = 8


@pytest.fixture(scope='module')
def params():
  p = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(p, is_training=False)
  return p


@pytest.fixture(scope='module')
def variables(params):
  return model_lib.get_model(params).init(
      jax.random.PRNGKey(0),
      jnp.zeros((1, params.total_rows, params.max_length, 1)))


@pytest.fixture
def arm(monkeypatch):
  """Arms a device-fault env hook; teardown re-arms the consume-once
  latch so the same hook can fire again in a later test."""

  def _arm(name, value):
    monkeypatch.setenv(name, str(value))

  yield _arm
  for name in (shared_faults.ENV_DEVICE_OOM_AT_PACK,
               shared_faults.ENV_DEVICE_LOST_AT_PACK,
               shared_faults.ENV_DEVICE_HANG_AT_PACK):
    shared_faults._fired.discard(name)


@pytest.fixture
def inject(scripts_importable):
  from scripts import inject_faults
  return inject_faults


def _dev_runner(params, variables, mesh=None, **kw):
  kw.setdefault('batch_size', BATCH)
  options = runner_lib.InferenceOptions(**kw)
  options.max_passes = params.max_passes
  options.max_length = params.max_length
  options.use_ccs_bq = params.use_ccs_bq
  return runner_lib.ModelRunner(params, variables, options,
                                mesh=mesh), options


def _collecting_engine(runner, options):
  delivered = {}
  failures = []
  engine = engine_lib.ConsensusEngine(
      runner, options,
      deliver=lambda t, ids, quals: delivered.__setitem__(t, (ids, quals)),
      on_pack_failure=lambda ts, seq, e: failures.append((list(ts), seq, e)))
  return engine, delivered, failures


def _raw_windows(params, n, seed=0):
  rng = np.random.default_rng(seed)
  shape = (n, params.total_rows, params.max_length, 1)
  return rng.integers(0, 5, size=shape).astype(np.float32)


def _fastq_names(path):
  with open(path) as f:
    return [line.rstrip('\n')[1:] for line in f if line.startswith('@')]


# ----------------------------------------------------------------------
# Classification: XlaRuntimeError text -> typed DeviceFault family


class TestClassification:

  def test_resource_exhausted_wraps_transient_oom(self):
    err = RuntimeError('RESOURCE_EXHAUSTED: out of memory allocating '
                       '8589934592 bytes')
    wrapped = shared_faults.classify_device_error(err)
    assert isinstance(wrapped, shared_faults.DeviceOomError)
    assert wrapped.kind == shared_faults.FaultKind.TRANSIENT
    assert wrapped.__cause__ is err

  @pytest.mark.parametrize('text', [
      'DATA_LOSS: device out of sync',
      'INTERNAL: compiled program failed',
      'slice 3 core halted unexpectedly',
  ])
  def test_lost_markers_wrap_permanent(self, text):
    wrapped = shared_faults.classify_device_error(RuntimeError(text))
    assert isinstance(wrapped, shared_faults.DeviceLostError)
    assert wrapped.kind == shared_faults.FaultKind.PERMANENT

  def test_unrelated_error_passes_through(self):
    err = ValueError('bad window shape')
    assert shared_faults.classify_device_error(err) is err

  def test_already_typed_fault_is_idempotent(self):
    err = shared_faults.DeviceOomError('pack too big')
    assert shared_faults.classify_device_error(err) is err

  def test_dispatch_timeout_is_transient_watchdog(self):
    err = shared_faults.DispatchTimeoutError(
        'pack finalize produced no result within dispatch_timeout=5.0s')
    assert 'watchdog' in str(err)
    assert err.kind == shared_faults.FaultKind.TRANSIENT

  def test_fault_family_registered_with_dclint(self, scripts_importable):
    """typed-faults zero-baseline: the DeviceFault family must be in
    the linter's FAULT_TYPES so raises of these types stay clean."""
    from tools.dclint import config as dclint_config
    assert {'DeviceFault', 'DeviceOomError', 'DeviceLostError',
            'DispatchTimeoutError'} <= set(dclint_config.FAULT_TYPES)

  def test_inject_faults_device_subcommand_prints_env(self, inject,
                                                      capsys):
    assert inject.main(['device', '--fault', 'hang', '--pack', '3',
                        '--hang_s', '7.5']) == 0
    out = capsys.readouterr().out
    assert f'export {shared_faults.ENV_DEVICE_HANG_AT_PACK}=3' in out
    assert f'export {shared_faults.ENV_DEVICE_HANG_S}=7.5' in out
    assert inject.main(['device', '--fault', 'oom', '--pack', '2']) == 0
    out = capsys.readouterr().out
    assert f'export {shared_faults.ENV_DEVICE_OOM_AT_PACK}=2' in out


# ----------------------------------------------------------------------
# Engine policy: fail mode surfaces, degrade mode recovers


def test_fail_mode_surfaces_typed_fault_without_retry(params, variables,
                                                      arm):
  """--on_device_error=fail (the default): the classified fault routes
  to on_pack_failure untouched — no bisection, no degradation."""
  runner, options = _dev_runner(params, variables)
  engine, delivered, failures = _collecting_engine(runner, options)
  arm(shared_faults.ENV_DEVICE_OOM_AT_PACK, 1)
  engine.submit(_raw_windows(params, BATCH, seed=20), list(range(BATCH)))
  engine.flush()
  assert len(failures) == 1
  tickets, seq, err = failures[0]
  assert tickets == list(range(BATCH)) and seq == 0
  assert isinstance(err, shared_faults.DeviceOomError)
  assert engine.n_device_faults == 1
  assert engine.n_oom_bisections == 0
  assert not delivered


def test_oom_bisection_byte_identical(params, variables, arm):
  """degrade mode: an OOM pack retries as halves at half batch shape
  and every window still gets exactly its clean result."""
  raw = _raw_windows(params, 2 * BATCH + 3, seed=21)
  runner_a, options_a = _dev_runner(params, variables)
  baseline = engine_lib.ConsensusEngine(
      runner_a, options_a,
      deliver=lambda t, ids, quals: None).predict_windows(raw)
  arm(shared_faults.ENV_DEVICE_OOM_AT_PACK, 1)
  runner_b, options_b = _dev_runner(params, variables,
                                    on_device_error='degrade')
  engine = engine_lib.ConsensusEngine(
      runner_b, options_b, deliver=lambda t, ids, quals: None)
  ids, quals = engine.predict_windows(raw)
  np.testing.assert_array_equal(ids, baseline[0])
  np.testing.assert_array_equal(quals, baseline[1])
  assert engine.n_oom_bisections == 1
  assert engine.n_device_faults == 1
  assert engine.stats()['n_oom_bisections'] == 1


def test_hang_bounded_by_dispatch_watchdog(params, variables, arm):
  """A wedged finalize (injected 6s hang) becomes DispatchTimeoutError
  within --dispatch_timeout + slack, attributed to the hung pack;
  sibling packs deliver. Timeouts are never retried, even under
  degrade (a hung device would hang again)."""
  runner, options = _dev_runner(params, variables, dispatch_timeout=1.0,
                                on_device_error='degrade')
  engine, delivered, failures = _collecting_engine(runner, options)
  arm(shared_faults.ENV_DEVICE_HANG_AT_PACK, 1)
  arm(shared_faults.ENV_DEVICE_HANG_S, 6.0)
  engine.submit(_raw_windows(params, 2 * BATCH, seed=22),
                list(range(2 * BATCH)))
  t0 = time.monotonic()
  engine.flush()
  elapsed = time.monotonic() - t0
  # Bound: the 1.0s watchdog plus generous slack, well under the 6s
  # injected hang — without the watchdog this flush takes 6+ seconds.
  assert elapsed < 4.5, f'watchdog did not bound the hang: {elapsed:.1f}s'
  assert len(failures) == 1
  tickets, seq, err = failures[0]
  assert tickets == list(range(BATCH)) and seq == 0
  assert isinstance(err, shared_faults.DispatchTimeoutError)
  assert engine.n_dispatch_timeouts == 1
  assert engine.n_device_faults == 1
  assert engine.n_oom_bisections == 0
  assert set(delivered) == set(range(BATCH, 2 * BATCH))


# ----------------------------------------------------------------------
# Mesh degradation ladder (8 forced host-platform devices)


@pytest.mark.multichip
def test_lost_device_degrades_mesh_byte_identical(params, variables, arm):
  """The acceptance core at the engine boundary: dp=8 loses a "device"
  mid-stream, degrades to dp=4, resubmits the failed pack, and the
  output is byte-identical to a clean single-device run."""
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  raw = _raw_windows(params, 2 * BATCH + 5, seed=31)
  runner_s, options_s = _dev_runner(params, variables)
  baseline = engine_lib.ConsensusEngine(
      runner_s, options_s,
      deliver=lambda t, ids, quals: None).predict_windows(raw)

  mesh = mesh_lib.make_mesh(dp=8, tp=1, devices=jax.devices()[:8])
  runner_m, options_m = _dev_runner(params, variables, mesh=mesh,
                                    on_device_error='degrade')
  engine = engine_lib.ConsensusEngine(
      runner_m, options_m, deliver=lambda t, ids, quals: None)
  assert runner_m.mesh_dp == 8 and not runner_m.is_degraded
  arm(shared_faults.ENV_DEVICE_LOST_AT_PACK, 2)
  ids, quals = engine.predict_windows(raw)
  np.testing.assert_array_equal(ids, baseline[0])
  np.testing.assert_array_equal(quals, baseline[1])
  assert runner_m.mesh_dp == 4
  assert runner_m.is_degraded
  assert engine.n_device_faults == 1
  stats = engine.stats()
  assert stats['n_mesh_degradations'] == 1
  assert stats['mesh_dp'] == 4


@pytest.mark.multichip
def test_oom_bisection_floors_at_dp_divisibility(params, variables, arm):
  """batch 8 over dp=8 cannot bisect (half of 8 does not split over 8
  devices): the OOM routes to on_pack_failure instead of looping."""
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  mesh = mesh_lib.make_mesh(dp=8, tp=1, devices=jax.devices()[:8])
  runner, options = _dev_runner(params, variables, mesh=mesh,
                                on_device_error='degrade')
  engine, delivered, failures = _collecting_engine(runner, options)
  arm(shared_faults.ENV_DEVICE_OOM_AT_PACK, 1)
  engine.submit(_raw_windows(params, BATCH, seed=32), list(range(BATCH)))
  engine.flush()
  assert len(failures) == 1
  assert isinstance(failures[0][2], shared_faults.DeviceOomError)
  assert engine.n_oom_bisections == 0
  assert not delivered
  assert runner.mesh_dp == 8  # OOM never touches the mesh ladder


@pytest.mark.multichip
def test_run_inference_mid_stream_degradation_byte_identical(
    params, variables, arm, synthetic_bams, tmp_path):
  """End-to-end acceptance (engine variant): the batch pipeline on a
  dp=8 mesh loses a device mid-stream, degrades, completes, and the
  FASTQ is byte-identical to a clean single-device run — with the
  recovery counters in the run's own stats."""
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  subreads, ccs = synthetic_bams(subdir='bams_device', n_zmws=6,
                                 seq_len=600)
  run_kw = dict(batch_zmws=100, skip_windows_above=0, min_quality=0)

  ref_out = str(tmp_path / 'ref.fastq')
  runner_s, options_s = _dev_runner(params, variables, **run_kw)
  runner_lib.run_inference(subreads, ccs, None, ref_out,
                           options=options_s, runner=runner_s)

  arm(shared_faults.ENV_DEVICE_LOST_AT_PACK, 2)
  out = str(tmp_path / 'degraded.fastq')
  mesh = mesh_lib.make_mesh(dp=8, tp=1, devices=jax.devices()[:8])
  runner_m, options_m = _dev_runner(params, variables, mesh=mesh,
                                    on_device_error='degrade', **run_kw)
  counters = runner_lib.run_inference(subreads, ccs, None, out,
                                      options=options_m, runner=runner_m)
  assert counters['success'] == 6
  assert counters['n_device_faults'] == 1
  assert counters['n_mesh_degradations'] == 1
  assert counters['mesh_dp'] == 4
  assert counters.get('n_zmw_quarantined', 0) == 0
  with open(ref_out, 'rb') as a, open(out, 'rb') as b:
    assert a.read() == b.read()


# ----------------------------------------------------------------------
# Abort + resume, and dead-letter attribution, after device faults


def test_resume_after_device_fault_abort(params, variables, arm,
                                         monkeypatch, synthetic_bams,
                                         tmp_path):
  """fail-mode abort mid-run on a device fault: the manifest stays
  consistent, --resume completes the run, and no ZMW is emitted twice."""
  subreads, ccs = synthetic_bams(subdir='bams_resume', n_zmws=6,
                                 seq_len=600)
  # depth 1 drains packs eagerly (the default depth of 8 would hold
  # every pack in flight until the final flush, so the abort would land
  # before any group committed — a valid but progress-free manifest);
  # emit depth 1 makes the first group's commit happen-before the
  # second emit_put returns, so groups_done >= 1 is deterministic.
  run_kw = dict(batch_zmws=2, skip_windows_above=0, min_quality=0,
                dispatch_depth=1, emit_queue_depth=1)

  ref_out = str(tmp_path / 'ref.fastq')
  runner1, options1 = _dev_runner(params, variables, **run_kw)
  runner_lib.run_inference(subreads, ccs, None, ref_out,
                           options=options1, runner=runner1)

  out = str(tmp_path / 'out.fastq')
  arm(shared_faults.ENV_DEVICE_LOST_AT_PACK, 4)
  runner2, options2 = _dev_runner(params, variables, **run_kw)
  with pytest.raises(inf_faults.DeviceLostError, match='halted'):
    runner_lib.run_inference(subreads, ccs, None, out,
                             options=options2, runner=runner2)
  monkeypatch.delenv(shared_faults.ENV_DEVICE_LOST_AT_PACK)
  assert not os.path.exists(out)
  assert os.path.exists(out + '.tmp')
  manifest = json.load(open(out + '.progress.json'))
  assert manifest['groups_done'] >= 1
  assert json.load(open(out + '.inference.json')).get('partial') is True

  runner3, options3 = _dev_runner(params, variables, resume=True,
                                  **run_kw)
  counters = runner_lib.run_inference(subreads, ccs, None, out,
                                      options=options3, runner=runner3)
  assert counters['n_zmw_resume_skipped'] >= 1
  assert 'partial' not in counters
  assert not os.path.exists(out + '.progress.json')
  assert not os.path.exists(out + '.tmp')
  got = sorted(_fastq_names(out))
  assert got == sorted(_fastq_names(ref_out))
  assert len(got) == len(set(got)), 'duplicate ZMWs after resume'


def test_device_fault_dead_letter_carries_kind(params, variables, arm,
                                               synthetic_bams, tmp_path):
  """Quarantined pack failures keep the device-fault classification:
  the dead-letter line names the typed fault and its permanent kind."""
  subreads, ccs = synthetic_bams(subdir='bams_dl', n_zmws=6, seq_len=600)
  out = str(tmp_path / 'out.fastq')
  arm(shared_faults.ENV_DEVICE_LOST_AT_PACK, 2)
  runner, options = _dev_runner(params, variables, batch_zmws=2,
                                skip_windows_above=0, min_quality=0,
                                on_zmw_error='ccs-fallback')
  counters = runner_lib.run_inference(subreads, ccs, None, out,
                                      options=options, runner=runner)
  assert counters['n_device_faults'] == 1
  assert counters['n_zmw_quarantined'] >= 1
  assert len(_fastq_names(out)) == 6  # fallbacks emitted, none lost
  letters = [e for e in inf_faults.read_dead_letters(out + '.failed.jsonl')
             if e['stage'] == 'model']
  assert letters
  for entry in letters:
    assert 'DeviceLostError' in entry['error']
    assert entry['kind'] == shared_faults.FaultKind.PERMANENT
    assert entry['action'] == 'ccs-fallback'


# ----------------------------------------------------------------------
# Resident service: degraded capacity, bisection counters, drain


def _mol(params, name, n=4, seed=0):
  rng = np.random.default_rng(seed)
  return dict(
      name=name,
      subreads=rng.integers(
          0, 5, size=(n, params.total_rows, params.max_length, 1)
      ).astype(np.float32),
      window_pos=np.arange(n, dtype=np.int64) * params.max_length,
      ccs_bq=np.full((n, params.max_length), 30, dtype=np.int32),
      overflow=np.zeros(n, dtype=np.uint8),
  )


@contextlib.contextmanager
def _serving(params, variables, mesh=None, serve_kw=None, **opt_kw):
  from deepconsensus_tpu.serve import server as server_lib
  from deepconsensus_tpu.serve.client import ServeClient
  from deepconsensus_tpu.serve.service import (ConsensusService,
                                               ServeOptions)

  opt_kw.setdefault('min_quality', 0)
  opt_kw.setdefault('min_length', 0)
  runner, options = _dev_runner(params, variables, mesh=mesh, **opt_kw)
  so_kw = dict(io_timeout_s=2.0)
  so_kw.update(serve_kw or {})
  service = ConsensusService(runner, options, ServeOptions(**so_kw))
  service.warmup()  # consumes dispatch ordinal 1
  service.start()
  httpd = server_lib.build_server(service, '127.0.0.1', 0)
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  try:
    yield service, ServeClient(port=httpd.server_address[1], timeout=30)
  finally:
    service.begin_drain()
    httpd.shutdown()
    httpd.server_close()
    service.drain(timeout=15)


@pytest.mark.multichip
def test_serve_degrades_mid_stream_byte_identical(params, variables, arm):
  """Acceptance (serve variant): the resident service loses a mesh
  device under live traffic, degrades to dp=4, and every response
  stays byte-identical to the single-device service — while /readyz
  stays 200 and reports the reduced capacity."""
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  mols = [_mol(params, f'm/{i}/ccs', n=3 + i % 4, seed=i)
          for i in range(6)]

  def serve_all(mesh, **opt_kw):
    with _serving(params, variables, mesh=mesh, **opt_kw) as (
        service, client):
      assert client.wait_ready(10)
      responses = [client.polish(**m) for m in mols]
      return responses, client.metricz(), client.readyz()

  single, _, _ = serve_all(None)
  # Warmup is dispatch ordinal 1; the first polished pack is 2.
  arm(shared_faults.ENV_DEVICE_LOST_AT_PACK, 2)
  mesh = mesh_lib.make_mesh(dp=8, tp=1, devices=jax.devices()[:8])
  sharded, metrics, ready = serve_all(mesh, on_device_error='degrade')

  for i, (s, m) in enumerate(zip(single, sharded)):
    assert m['status'] == s['status'] == 'ok', i
    assert m['seq'] == s['seq'], i
    np.testing.assert_array_equal(m['quals'], s['quals'])
  assert ready['_status'] == 200  # degraded capacity stays ready
  assert ready['degraded'] is True
  assert ready['mesh_dp'] == 4
  assert ready['initial_dp'] == 8
  faults = metrics['counters']
  assert faults['n_device_faults'] == 1
  assert faults['n_mesh_degradations'] == 1
  assert metrics['capacity']['degraded'] is True


def test_serve_oom_bisection_in_metricz(params, variables, arm):
  """An OOM pack under the service bisects transparently: the request
  succeeds with its clean bytes and /metricz shows the bisection."""
  mol = _mol(params, 'm/1/ccs', n=4, seed=3)
  with _serving(params, variables,
                on_device_error='degrade') as (service, client):
    assert client.wait_ready(10)
    clean = client.polish(**mol)  # dispatch ordinal 2
    arm(shared_faults.ENV_DEVICE_OOM_AT_PACK, 3)
    chaos = client.polish(**mol)  # ordinal 3: the OOM pack
    assert chaos['status'] == 'ok'
    assert chaos['seq'] == clean['seq']
    np.testing.assert_array_equal(chaos['quals'], clean['quals'])
    m = client.metricz()
    assert m['counters']['n_oom_bisections'] == 1
    assert m['counters']['n_device_faults'] == 1
    ready = client.readyz()
    assert ready['degraded'] is False  # bisection is not degradation


def test_serve_drain_resolves_device_fault_on_final_pack(params,
                                                         variables, arm):
  """Drain audit regression: a deferred-launch device fault on the
  LAST in-flight pack during drain must neither hang the drain nor
  lose the admitted request (it resolves via the isolation retry)."""
  from deepconsensus_tpu.serve import protocol
  from deepconsensus_tpu.serve.service import (ConsensusService,
                                               ServeOptions)

  runner, options = _dev_runner(params, variables, min_quality=0,
                                min_length=0)
  service = ConsensusService(
      runner, options,
      ServeOptions(io_timeout_s=2.0, on_request_error='ccs-fallback'))
  service.warmup()  # dispatch ordinal 1
  mol = _mol(params, 'm/9/ccs', n=3, seed=5)
  req = protocol.decode_request(
      protocol.encode_request(**mol),
      total_rows=params.total_rows, max_length=params.max_length,
      max_windows=64)
  # Admit BEFORE the loop starts, then drain: the request's own pack
  # (ordinal 2) is the final in-flight handle of the drain.
  state = service.submit(req, None)
  arm(shared_faults.ENV_DEVICE_LOST_AT_PACK, 2)
  service.begin_drain()
  service.start()
  assert service.drain(timeout=30), 'drain hung on the faulted pack'
  result = service.wait(state)
  # Accepted-then-recovered, not accepted-then-lost: the consume-once
  # fault fails the shared pack, the isolation retry succeeds.
  assert result['status'] == 'ok'
  assert service._loop_error is None
  stats = service.stats()
  assert stats['counters']['n_device_faults'] == 1
  assert stats['counters']['n_isolation_retries'] >= 1
