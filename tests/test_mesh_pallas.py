"""Pallas training kernels under a sharded (dp x tp) mesh.

The custom-VJP wavefront loss and fused banded attention must compose
with pjit sharding — a regression here would silently break the
multi-chip training path for the Pallas flags.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import train as train_lib
from deepconsensus_tpu.parallel import mesh as mesh_lib


@pytest.mark.slow
def test_pallas_kernels_under_mesh_train_step(tmp_path):
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.batch_size = 16
    params.num_hidden_layers = 1
    params.filter_size = 32
    params.use_pallas_wavefront = True
    params.use_pallas_attention = True

  mesh = mesh_lib.make_mesh(dp=4, tp=2)
  trainer = train_lib.Trainer(
      params=params, out_dir=str(tmp_path / 'mesh_pallas'), mesh=mesh
  )
  state = trainer.init_state(steps_total=10)
  step = trainer.train_step_fn()
  rng = np.random.default_rng(0)
  rows = jnp.asarray(
      rng.uniform(0, 4, size=(16, params.total_rows, params.max_length,
                              1)).astype(np.float32))
  label = jnp.asarray(
      rng.integers(0, 5, size=(16, params.max_length)), jnp.int32)
  with mesh:
    state, m = step(state, {'rows': rows, 'label': label})
    loss1 = float(m['loss'])
    state, m = step(state, {'rows': rows, 'label': label})
  assert np.isfinite(loss1) and np.isfinite(float(m['loss']))
  assert float(m['loss']) != loss1  # params updated through both kernels
