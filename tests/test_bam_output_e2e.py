"""BAM-output inference mode and end_after_stage truncation."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.inference import runner as runner_lib
from deepconsensus_tpu.io import bam as bam_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib


@pytest.fixture(scope='module')
def runner_and_options():
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params, is_training=False)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = 1
    params.filter_size = 32
  options = runner_lib.InferenceOptions(
      batch_size=32, batch_zmws=4, limit=2, skip_windows_above=1,
      min_quality=0,
  )
  model = model_lib.get_model(params)
  rows = jnp.zeros((1, params.total_rows, params.max_length, 1))
  variables = model.init(jax.random.PRNGKey(0), rows)
  return runner_lib.ModelRunner(params, variables, options), options


def test_bam_output_mode(tmp_path, testdata_dir, runner_and_options):
  runner, options = runner_and_options
  out = str(tmp_path / 'polished.bam')
  counters = runner_lib.run_inference(
      subreads_to_ccs=str(testdata_dir / 'human_1m/subreads_to_ccs.bam'),
      ccs_bam=str(testdata_dir / 'human_1m/ccs.bam'),
      checkpoint=None,
      output=out,
      options=options,
      runner=runner,
  )
  out_reader = bam_lib.BamReader(out)
  ccs_reader = bam_lib.BamReader(
      str(testdata_dir / 'human_1m/ccs.bam'))
  # The CCS header (incl. its @RG lines) must carry into the output so
  # per-read RG:Z tags reference declared read groups
  # (reference quick_inference.py:894-897 uses template=ccs).
  assert ccs_reader.header_text.strip()
  assert ccs_reader.header_text in out_reader.header_text
  declared_rgs = {
      line.split('ID:')[1].split('\t')[0]
      for line in ccs_reader.header_text.splitlines()
      if line.startswith('@RG') and 'ID:' in line
  }
  records = list(out_reader)
  assert len(records) == counters['success'] > 0
  for rec in records:
    if rec.has_tag('RG'):
      assert rec.get_tag('RG') in declared_rgs
    assert rec.is_unmapped
    assert rec.qname.endswith('/ccs')
    assert rec.get_tag('zm') == int(rec.qname.split('/')[1])
    # Aux tags propagate when present on the draft CCS record.
    assert rec.has_tag('rq') and rec.has_tag('np')
    assert rec.quals is not None and len(rec.quals) == len(rec.seq)


@pytest.mark.parametrize('stage,expect_output', [
    ('dc_input', False),
    ('tf_examples', False),
    ('run_model', False),
    ('full', True),
])
def test_end_after_stage(tmp_path, testdata_dir, runner_and_options, stage,
                         expect_output):
  runner, base = runner_and_options
  options = runner_lib.InferenceOptions(
      batch_size=32, batch_zmws=4, limit=2, skip_windows_above=1,
      min_quality=0, end_after_stage=stage,
  )
  out = str(tmp_path / f'{stage}.fastq')
  counters = runner_lib.run_inference(
      subreads_to_ccs=str(testdata_dir / 'human_1m/subreads_to_ccs.bam'),
      ccs_bam=str(testdata_dir / 'human_1m/ccs.bam'),
      checkpoint=None,
      output=out,
      options=options,
      runner=runner,
  )
  assert (counters.get('success', 0) > 0) == expect_output
