"""Pallas fused banded attention vs the unfused reference (interpret
mode on CPU)."""
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.ops import banded_attention as ba


def make_qkv(b=2, l=100, h=2, d=140, seed=0):
  rng = np.random.default_rng(seed)
  mk = lambda: jnp.asarray(rng.normal(size=(b, l, h, d)).astype(np.float32))
  return mk(), mk(), mk()


@pytest.mark.parametrize('win', [12, 6, None])
def test_kernel_matches_reference(win):
  q, k, v = make_qkv()
  want = ba.reference_banded_attention(q, k, v, win)
  got = ba.banded_attention(q, k, v, win, interpret=True)
  np.testing.assert_allclose(
      np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-5
  )


def test_kernel_in_model_forward():
  import jax
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib
  from deepconsensus_tpu.ops import banded_attention as ba_mod

  # Route the kernel through interpret mode for the CPU test.
  orig = ba_mod.banded_attention
  ba_mod.banded_attention = lambda q, k, v, w: orig(q, k, v, w,
                                                    interpret=True)
  try:
    params = config_lib.get_config('transformer_learn_values+test')
    config_lib.finalize_params(params)
    with params.unlocked():
      params.dtype = 'float32'
      params.num_hidden_layers = 1
      params.filter_size = 32
    rows = jnp.zeros((2, params.total_rows, params.max_length, 1))
    model = model_lib.get_model(params)
    variables = model.init(jax.random.PRNGKey(0), rows)
    base = model.apply(variables, rows)
    with params.unlocked():
      params.use_pallas_attention = True
    model_p = model_lib.get_model(params)
    fused = model_p.apply(variables, rows)
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(base), atol=1e-5
    )
  finally:
    ba_mod.banded_attention = orig
