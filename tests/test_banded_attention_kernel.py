"""Pallas fused banded attention vs the unfused reference (interpret
mode on CPU)."""
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.ops import banded_attention as ba


def make_qkv(b=2, l=100, h=2, d=140, seed=0):
  rng = np.random.default_rng(seed)
  mk = lambda: jnp.asarray(rng.normal(size=(b, l, h, d)).astype(np.float32))
  return mk(), mk(), mk()


@pytest.mark.parametrize('win', [12, 6, None])
def test_kernel_matches_reference(win):
  q, k, v = make_qkv()
  want = ba.reference_banded_attention(q, k, v, win)
  got = ba.banded_attention(q, k, v, win, interpret=True)
  np.testing.assert_allclose(
      np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-5
  )


def test_kernel_in_model_forward():
  import jax
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib

  # Off-TPU the kernel auto-resolves to interpret mode.
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = 1
    params.filter_size = 32
  rows = jnp.zeros((2, params.total_rows, params.max_length, 1))
  model = model_lib.get_model(params)
  variables = model.init(jax.random.PRNGKey(0), rows)
  base = model.apply(variables, rows)
  with params.unlocked():
    params.use_pallas_attention = True
  model_p = model_lib.get_model(params)
  fused = model_p.apply(variables, rows)
  np.testing.assert_allclose(
      np.asarray(fused), np.asarray(base), atol=1e-5
  )


@pytest.mark.parametrize('win', [12, None])
def test_vjp_grads_match_reference(win):
  import jax

  q, k, v = make_qkv(b=2, l=24, h=2, d=16, seed=3)

  def ref_loss(q, k, v):
    out = ba.reference_banded_attention(q, k, v, win)
    return jnp.sum(out * jnp.cos(out))

  def pallas_loss(q, k, v):
    out = ba.banded_attention_vjp(q, k, v, win, True)
    return jnp.sum(out * jnp.cos(out))

  want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
  got = jax.grad(pallas_loss, argnums=(0, 1, 2))(q, k, v)
  for g, w in zip(got, want):
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(w), atol=2e-4, rtol=1e-4
    )


def test_dropout_vjp_matches_masked_reference():
  """With the SAME keep-mask, the fused dropout kernel must agree with
  the unfused weights*mask/keep_prob semantics in values and grads."""
  import jax

  win = 8
  keep_prob = 0.9
  q, k, v = make_qkv(b=2, l=20, h=2, d=16, seed=5)
  b, l, h, _ = q.shape
  mask = jax.random.bernoulli(
      jax.random.PRNGKey(7), keep_prob, (b, h, l, l)
  ).astype(jnp.uint8)

  def ref_loss(q, k, v):
    logits = jnp.einsum('BTNH,BFNH->BNFT', k, q)
    i = jnp.arange(l)
    band = jnp.abs(i[:, None] - i[None, :]) <= win
    logits = jnp.where(band[None, None], logits, -1e9)
    weights = jax.nn.softmax(logits, axis=-1)
    weights = weights * mask.astype(weights.dtype) / keep_prob
    out = jnp.einsum('BNFT,BTNH->BFNH', weights, v)
    return jnp.sum(out * jnp.cos(out))

  def pallas_loss(q, k, v):
    out = ba.banded_attention_dropout_vjp(
        q, k, v, mask, win, keep_prob, True
    )
    return jnp.sum(out * jnp.cos(out))

  want_val = ref_loss(q, k, v)
  got_val = pallas_loss(q, k, v)
  np.testing.assert_allclose(
      np.asarray(got_val), np.asarray(want_val), rtol=1e-5
  )
  want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
  got = jax.grad(pallas_loss, argnums=(0, 1, 2))(q, k, v)
  for g, w in zip(got, want):
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(w), atol=2e-4, rtol=1e-4
    )


@pytest.mark.slow


def test_model_trains_with_pallas_attention():
  """Full train step (dropout on) through the fused attention VJP."""
  import jax
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import train as train_lib

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.batch_size = 8
    params.num_hidden_layers = 1
    params.filter_size = 32
    params.use_pallas_attention = True

  trainer = train_lib.Trainer(
      params=params, out_dir='/tmp/dc_pallas_attn_smoke', mesh=None
  )
  state = trainer.init_state(steps_total=10)
  step = trainer.train_step_fn()
  rng = np.random.default_rng(0)
  rows = jnp.asarray(
      rng.uniform(0, 4, size=(8, params.total_rows, params.max_length,
                              1)).astype(np.float32))
  label = jnp.asarray(
      rng.integers(0, 5, size=(8, params.max_length)), jnp.int32)
  state, m = step(state, {'rows': rows, 'label': label})
  l1 = float(m['loss'])
  state, m = step(state, {'rows': rows, 'label': label})
  assert np.isfinite(l1) and np.isfinite(float(m['loss']))
  assert float(m['loss']) != l1  # params actually updated


@pytest.mark.parametrize('l,win', [
    (100, 12),    # flagship window size
    (256, 12),    # multi-block queries, single-block band reach
    (257, 30),    # non-multiple length + padded tail rows
    (384, 130),   # band wider than one key block (w_blocks > 1)
    (192, None),  # full attention via the flash path
])
def test_flash_band_matches_reference(l, win):
  from deepconsensus_tpu.ops import flash_band_attention as fba

  q, k, v = make_qkv(b=1, l=l, h=2, d=64, seed=3)
  want = ba.reference_banded_attention(q, k, v, win)
  got = fba.flash_band_attention(q, k, v, win, interpret=True)
  np.testing.assert_allclose(
      np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-5
  )


def test_flash_band_bf16():
  """bf16 inputs against the f32 truth: the kernel accumulates in f32,
  so it tracks the f32 reference *closer* than the unfused bf16 path
  does (which rounds the softmax weights to bf16 before PV)."""
  from deepconsensus_tpu.ops import flash_band_attention as fba

  qf, kf, vf = make_qkv(b=2, l=160, d=64)
  q, k, v = (x.astype(jnp.bfloat16) for x in (qf, kf, vf))
  want_f32 = np.asarray(ba.reference_banded_attention(qf, kf, vf, 12))
  got = np.asarray(
      fba.flash_band_attention(q, k, v, 12, interpret=True), np.float32
  )
  unfused_bf16 = np.asarray(
      ba.reference_banded_attention(q, k, v, 12), np.float32
  )
  kernel_err = np.abs(got - want_f32).max()
  unfused_err = np.abs(unfused_bf16 - want_f32).max()
  # Both paths share the bf16 input rounding (~1e-1 on these scales);
  # the kernel must not add error beyond it, and its f32 accumulation
  # should track the truth at least as well as the unfused bf16 path.
  assert kernel_err < 1e-1
  assert kernel_err <= unfused_err


def test_flash_kernel_in_long_window_model():
  """use_pallas_attention at L>128 routes inference through the flash
  kernel and matches the unfused model output."""
  import jax
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = 1
    params.filter_size = 32
    params.max_length = 192
  rows = jnp.zeros((2, params.total_rows, params.max_length, 1))
  rng = np.random.default_rng(0)
  rows = jnp.asarray(
      rng.integers(0, 4, size=rows.shape).astype(np.float32)
  )
  model = model_lib.get_model(params)
  variables = model.init(jax.random.PRNGKey(0), rows)
  base = model.apply(variables, rows)
  with params.unlocked():
    params.use_pallas_attention = True
  model_p = model_lib.get_model(params)
  flash = model_p.apply(variables, rows)
  np.testing.assert_allclose(
      np.asarray(flash), np.asarray(base), atol=1e-5
  )


@pytest.mark.parametrize('l,win', [
    (100, 12),
    (256, 12),
    (257, 30),
    (192, None),
])
def test_flash_band_vjp_grads_match_reference(l, win):
  """The flash-band custom VJP (lse-saving forward + two backward
  kernels) must match jax.grad through the unfused reference."""
  import jax
  from deepconsensus_tpu.ops import flash_band_attention as fba

  q, k, v = make_qkv(b=1, l=l, h=2, d=32, seed=11)

  def ref_loss(q, k, v):
    out = ba.reference_banded_attention(q, k, v, win)
    return jnp.sum(out * jnp.cos(out))

  def flash_loss(q, k, v):
    out = fba.flash_band_attention_vjp(q, k, v, win, True)
    return jnp.sum(out * jnp.cos(out))

  np.testing.assert_allclose(
      np.asarray(flash_loss(q, k, v)), np.asarray(ref_loss(q, k, v)),
      rtol=1e-5,
  )
  want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
  got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
  for g, w in zip(got, want):
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(w), atol=3e-4, rtol=1e-4
    )


def test_long_window_dropout_routes_to_xla(monkeypatch):
  """L > WHOLE_L_LIMIT with attention_dropout > 0 in training must use
  the XLA banded path: the whole-L dropout kernel cannot compile past
  its VMEM limit (ADVICE r2 / VERDICT r2 #5)."""
  import jax
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib
  from deepconsensus_tpu.ops import banded_attention as ba_mod

  def boom(*a, **k):
    raise AssertionError('whole-L dropout kernel must not be used at '
                         'long window lengths')

  monkeypatch.setattr(ba_mod, 'banded_attention_dropout_vjp', boom)

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = 1
    params.filter_size = 32
    params.max_length = 512
    params.use_pallas_attention = True
    params.attention_dropout = 0.1
  model = model_lib.get_model(params)
  rng = np.random.default_rng(0)
  rows = jnp.asarray(
      rng.integers(0, 4, size=(2, params.total_rows, params.max_length,
                               1)).astype(np.float32))
  import jax as _jax
  variables = model.init(_jax.random.PRNGKey(0), rows)
  out = model.apply(
      variables, rows, train=True,
      rngs={'dropout': _jax.random.PRNGKey(1)},
  )
  assert np.isfinite(np.asarray(out)).all()

  # Short windows with dropout still take the fused dropout kernel.
  with params.unlocked():
    params.max_length = 100
  model_short = model_lib.get_model(params)
  rows_s = jnp.asarray(
      rng.integers(0, 4, size=(2, params.total_rows, 100, 1)).astype(
          np.float32))
  vars_s = model_short.init(_jax.random.PRNGKey(0), rows_s)
  with pytest.raises(AssertionError, match='must not be used'):
    model_short.apply(
        vars_s, rows_s, train=True,
        rngs={'dropout': _jax.random.PRNGKey(1)},
    )


def test_model_trains_long_window_through_flash_vjp():
  """Full train step at L>WHOLE_L_LIMIT with use_pallas_attention and
  dropout off: the encoder routes through the flash-band custom VJP
  and the optimizer step must update params with a finite loss."""
  import jax
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import train as train_lib

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.batch_size = 4
    params.num_hidden_layers = 1
    params.filter_size = 32
    params.max_length = 160
    params.use_pallas_attention = True
    params.attention_dropout = 0.0
    params.use_pallas_wavefront = False  # scan DP: the kernel under
    # test here is the attention VJP, and interpret-mode DP is slow.

  trainer = train_lib.Trainer(
      params=params, out_dir='/tmp/dc_flash_vjp_smoke', mesh=None
  )
  state = trainer.init_state(steps_total=10)
  step = trainer.train_step_fn()
  rng = np.random.default_rng(0)
  rows = jnp.asarray(
      rng.integers(0, 4, size=(4, params.total_rows, params.max_length,
                               1)).astype(np.float32))
  label = jnp.asarray(
      rng.integers(0, 5, size=(4, params.max_length)), jnp.int32)
  state, m = step(state, {'rows': rows, 'label': label})
  l1 = float(m['loss'])
  state, m = step(state, {'rows': rows, 'label': label})
  assert np.isfinite(l1) and np.isfinite(float(m['loss']))
  assert float(m['loss']) != l1
