"""Self-tests for the dclint static-analysis suite (tools/dclint).

Each rule gets fixture snippets: seeded violations the checker must
catch and clean snippets it must pass. Checkers take a virtual
repo-relative path, so fixtures never touch the real tree; the
baseline / CLI tests use a tmp mirror tree instead. The repo-wide
tests are the actual gate: `dctpu lint` must exit 0 against the
committed baseline, and the typed-faults / guarded-by baselines must
stay empty (violations get fixed, not suppressed).
"""
import json
import pathlib
import sys
import textwrap

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
  sys.path.insert(0, str(REPO_ROOT))

from tools.dclint import __main__ as dclint_main
from tools.dclint import config as dclint_config
from tools.dclint import core
from tools.dclint import guarded_by
from tools.dclint import jit_hazards
from tools.dclint import registry_writes
from tools.dclint import shape_literals
from tools.dclint import typed_faults


def findings_for(checker, path, source):
  src = core.SourceFile(path, textwrap.dedent(source))
  return checker.check(src)


def lines_of(findings):
  return sorted(f.line for f in findings)


# ---------------------------------------------------------------------------
# typed-faults
# ---------------------------------------------------------------------------


class TestTypedFaults:

  IO_PATH = 'deepconsensus_tpu/io/fixture.py'

  def test_catches_bare_valueerror(self):
    found = findings_for(typed_faults, self.IO_PATH, """\
        def parse(buf):
          if not buf:
            raise ValueError('empty buffer')
        """)
    assert len(found) == 1 and found[0].rule == 'typed-faults'

  def test_catches_bare_runtimeerror_in_serve(self):
    found = findings_for(
        typed_faults, 'deepconsensus_tpu/serve/fixture.py', """\
        def admit(req):
          raise RuntimeError('queue full')
        """)
    assert len(found) == 1

  def test_catches_swallowing_broad_except(self):
    found = findings_for(typed_faults, self.IO_PATH, """\
        def read(path):
          try:
            return open(path).read()
          except Exception:
            return None
        """)
    assert len(found) == 1
    assert 'broad' in found[0].message

  def test_catches_bare_except(self):
    found = findings_for(typed_faults, self.IO_PATH, """\
        def read(path):
          try:
            return decode(path)
          except:
            pass
        """)
    assert len(found) == 1

  def test_passes_typed_fault_raise(self):
    found = findings_for(typed_faults, self.IO_PATH, """\
        from deepconsensus_tpu.faults import CorruptInputError

        def parse(buf, path):
          if not buf:
            raise CorruptInputError('empty buffer', path=path)
        """)
    assert found == []

  def test_passes_reraise_and_routing_handler(self):
    found = findings_for(typed_faults, self.IO_PATH, """\
        def run(quarantine):
          try:
            step()
          except Exception as e:
            quarantine.record_failure('zmw', e)
          try:
            step()
          except Exception:
            raise
        """)
    assert found == []

  def test_passes_local_subclass_of_fault(self):
    found = findings_for(typed_faults, self.IO_PATH, """\
        from deepconsensus_tpu.faults import CorruptInputError

        class TruncatedError(CorruptInputError):
          pass

        def parse(buf):
          raise TruncatedError('short read')
        """)
    assert found == []

  def test_allow_comment_suppresses(self):
    found = findings_for(typed_faults, self.IO_PATH, """\
        def parse(kind):
          # dclint: allow=typed-faults (programmer error, not a fault)
          raise ValueError(f'unknown kind {kind}')
        """)
    assert found == []

  def test_out_of_scope_file_ignored(self):
    found = findings_for(
        typed_faults, 'deepconsensus_tpu/models/model.py', """\
        def f():
          raise ValueError('not data plane')
        """)
    assert found == []


# ---------------------------------------------------------------------------
# jit-hazards
# ---------------------------------------------------------------------------


class TestJitHazards:

  ENGINE = 'deepconsensus_tpu/inference/engine.py'
  RUNNER = 'deepconsensus_tpu/inference/runner.py'
  SERVICE = 'deepconsensus_tpu/serve/service.py'

  def test_catches_jit_in_loop(self):
    found = findings_for(jit_hazards, self.ENGINE, """\
        import jax

        def run(batches, f):
          for b in batches:
            fwd = jax.jit(f)
            fwd(b)
        """)
    assert any('inside a loop' in f.message for f in found)

  def test_catches_jit_in_hot_function(self):
    found = findings_for(jit_hazards, self.RUNNER, """\
        import jax

        class R:
          def dispatch(self, rows):
            fwd = jax.jit(self._forward)
            return fwd(rows)
        """)
    assert any('hot function' in f.message for f in found)

  def test_catches_scalar_arg_at_jitted_call_site(self):
    found = findings_for(jit_hazards, self.RUNNER, """\
        import jax

        fwd = jax.jit(lambda x, n: x)

        def predict(rows):
          return fwd(rows, len(rows))
        """)
    assert any('Python-scalar' in f.message for f in found)

  def test_catches_item_in_hot_function(self):
    found = findings_for(jit_hazards, self.SERVICE, """\
        class S:
          def _model_loop(self):
            out = self._runner.dispatch(self._batch)
            return out.sum().item()
        """)
    assert any('.item()' in f.message for f in found)

  def test_catches_asarray_of_device_value(self):
    found = findings_for(jit_hazards, self.RUNNER, """\
        import numpy as np

        class R:
          def predict(self, rows):
            out = self.dispatch(rows)
            return np.asarray(out)
        """)
    assert any('materialises a device value' in f.message
               for f in found)

  def test_passes_init_scope_jit_and_array_args(self):
    found = findings_for(jit_hazards, self.RUNNER, """\
        import jax

        class R:
          def __init__(self, f):
            self._fwd = jax.jit(f)

          def predict(self, rows):
            return self._fwd(rows)
        """)
    assert found == []

  def test_passes_allowed_deliberate_sync(self):
    found = findings_for(jit_hazards, self.RUNNER, """\
        import numpy as np

        class R:
          def finalize(self, dispatched):
            # dclint: allow=jit-hazards (this IS the sync point)
            return np.asarray(dispatched)
        """)
    assert found == []

  def test_passes_asarray_of_host_value(self):
    found = findings_for(jit_hazards, self.RUNNER, """\
        import numpy as np

        class R:
          def predict(self, rows):
            host = list(range(4))
            return np.asarray(host)
        """)
    assert found == []

  def test_catches_double_buffer_sync_before_forward(self):
    """A device_put transfer host-materialised before the forward
    consumes it defeats the transfer/compute overlap."""
    found = findings_for(jit_hazards, self.RUNNER, """\
        import jax
        import numpy as np

        class R:
          def dispatch(self, rows):
            main_dev = jax.device_put(rows, self._sharding)
            peek = np.asarray(main_dev)
            out = self._forward(self.variables, main_dev)
            return out
        """)
    assert any('double-buffer hazard' in f.message for f in found)

  def test_catches_double_buffer_sync_with_no_forward(self):
    found = findings_for(jit_hazards, self.RUNNER, """\
        import jax

        class R:
          def dispatch(self, rows):
            main_dev = jax.device_put(rows, self._sharding)
            return float(main_dev[0, 0])
        """)
    assert any('double-buffer hazard' in f.message for f in found)

  def test_passes_double_buffer_transfer_into_forward(self):
    found = findings_for(jit_hazards, self.RUNNER, """\
        import jax

        class R:
          def dispatch(self, rows):
            main_dev = jax.device_put(rows, self._sharding)
            out = self._forward(self.variables, main_dev)
            return out
        """)
    assert found == []

  def test_passes_sync_after_forward_consumed_transfer(self):
    """Materialising the transfer AFTER the forward consumed it is not
    a double-buffer hazard (the generic host-sync rule still governs
    it; here the allow comment covers that deliberate sync)."""
    found = findings_for(jit_hazards, self.RUNNER, """\
        import jax
        import numpy as np

        class R:
          def dispatch(self, rows):
            main_dev = jax.device_put(rows, self._sharding)
            out = self._forward(self.variables, main_dev)
            # dclint: allow=jit-hazards (post-forward debug readback)
            dbg = np.asarray(main_dev)
            return out
        """)
    assert found == []

  def test_catches_asarray_of_epilogue_outputs(self):
    """The device epilogue's uint8 planes are device values: a host
    materialisation sneaking in before finalize is flagged."""
    found = findings_for(jit_hazards, self.RUNNER, '''\
        import numpy as np

        from deepconsensus_tpu.ops import output_plane

        class R:
          def dispatch(self, rows):
            ids, quals = output_plane.phred_epilogue(rows, self._thr)
            return np.asarray(quals)
        ''')
    assert any('materialises a device value' in f.message
               for f in found)

  def test_passes_double_buffer_transfer_into_epilogue(self):
    """The epilogue call counts as a forward for the double-buffer
    rule: a transfer consumed by it is not a hazard."""
    found = findings_for(jit_hazards, self.RUNNER, '''\
        import jax

        from deepconsensus_tpu.ops import output_plane

        class R:
          def dispatch(self, rows):
            main_dev = jax.device_put(rows, self._sharding)
            out = output_plane.phred_epilogue(main_dev, self._thr)
            return out
        ''')
    assert found == []


# ---------------------------------------------------------------------------
# jit-hazards: dtype-downcast sub-rule
# ---------------------------------------------------------------------------


class TestDtypeDowncast:

  MODELS = 'deepconsensus_tpu/models/fixture.py'
  OPS = 'deepconsensus_tpu/ops/fixture.py'

  def test_catches_astype_bfloat16(self):
    found = findings_for(jit_hazards, self.MODELS, """\
        import jax.numpy as jnp

        def f(x):
          return x.astype(jnp.bfloat16)
        """)
    assert len(found) == 1 and 'downcast' in found[0].message

  def test_catches_asarray_string_dtype(self):
    found = findings_for(jit_hazards, self.OPS, """\
        import jax.numpy as jnp

        def f(x):
          return jnp.asarray(x, 'bfloat16')
        """)
    assert len(found) == 1

  def test_catches_cast_to_compute_dtype(self):
    found = findings_for(jit_hazards, self.MODELS, """\
        class M:
          def encode(self, x):
            return x.astype(self.compute_dtype)
        """)
    assert len(found) == 1 and 'compute_dtype' in found[0].message

  def test_catches_dtype_keyword_form(self):
    found = findings_for(jit_hazards, self.OPS, """\
        import jax.numpy as jnp

        def f(x):
          return jnp.array(x, dtype=jnp.float16)
        """)
    assert len(found) == 1

  def test_passes_f32_upcast(self):
    found = findings_for(jit_hazards, self.OPS, """\
        import jax.numpy as jnp

        def f(x):
          return x.astype(jnp.float32)
        """)
    assert found == []

  def test_passes_dtype_rematch(self):
    """Casting to an existing array's dtype re-matches a decision made
    elsewhere; the downcast site is wherever that dtype was chosen."""
    found = findings_for(jit_hazards, self.OPS, """\
        import jax.numpy as jnp

        def kernel(x_ref, out_ref):
          out_ref[...] = jnp.asarray(x_ref[...], out_ref.dtype)
        """)
    assert found == []

  def test_allow_comment_suppresses(self):
    found = findings_for(jit_hazards, self.MODELS, """\
        import jax.numpy as jnp

        def f(x):
          # dclint: allow=dtype-downcast (model entry cast)
          return x.astype(jnp.bfloat16)
        """)
    assert found == []

  def test_out_of_scope_file_ignored(self):
    found = findings_for(
        jit_hazards, 'deepconsensus_tpu/io/fixture.py', """\
        import jax.numpy as jnp

        def f(x):
          return x.astype(jnp.bfloat16)
        """)
    assert found == []


# ---------------------------------------------------------------------------
# guarded-by
# ---------------------------------------------------------------------------


class TestGuardedBy:

  SERVICE = 'deepconsensus_tpu/serve/service.py'

  def test_catches_unannotated_shared_attribute(self):
    found = findings_for(guarded_by, self.SERVICE, """\
        import threading

        class S:
          def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._t = threading.Thread(target=self._loop)

          def _loop(self):
            self.count += 1

          def stats(self):
            return self.count
        """)
    assert any('self.count' in f.message for f in found)

  def test_catches_guarded_access_outside_lock(self):
    found = findings_for(guarded_by, self.SERVICE, """\
        import threading

        class S:
          def __init__(self):
            self._lock = threading.Lock()
            self.count = 0  # guarded by: self._lock
            self._t = threading.Thread(target=self._loop)

          def _loop(self):
            with self._lock:
              self.count += 1

          def stats(self):
            return self.count
        """)
    assert any('outside `with self._lock:`' in f.message
               for f in found)

  def test_catches_unannotated_shared_closure_var(self):
    found = findings_for(guarded_by, self.SERVICE, """\
        import threading

        def run(batches):
          done = []

          def worker():
            done.append(1)

          t = threading.Thread(target=worker)
          t.start()
          done.append(0)
          t.join()
          return done
        """)
    assert any('closure variable `done`' in f.message for f in found)

  def test_passes_locked_attribute(self):
    found = findings_for(guarded_by, self.SERVICE, """\
        import threading

        class S:
          def __init__(self):
            self._lock = threading.Lock()
            self.count = 0  # guarded by: self._lock
            self._t = threading.Thread(target=self._loop)

          def _loop(self):
            with self._lock:
              self.count += 1

          def stats(self):
            with self._lock:
              return self.count
        """)
    assert found == []

  def test_passes_lock_free_annotation(self):
    found = findings_for(guarded_by, self.SERVICE, """\
        import threading

        class S:
          def __init__(self):
            # dclint: lock-free (monotonic flag, single writer)
            self._draining = False
            self._t = threading.Thread(target=self._loop)

          def _loop(self):
            while not self._draining:
              pass

          def drain(self):
            self._draining = True
        """)
    assert found == []

  def test_passes_queue_attribute_and_safe_publish(self):
    found = findings_for(guarded_by, self.SERVICE, """\
        import queue
        import threading

        def run(batches):
          work = queue.Queue()
          sink = open('/dev/null', 'w')

          def worker():
            while True:
              sink.write(work.get())

          t = threading.Thread(target=worker)
          t.start()
          for b in batches:
            work.put(b)
          t.join()
        """)
    assert found == []

  def test_single_threaded_class_ignored(self):
    found = findings_for(guarded_by, self.SERVICE, """\
        class S:
          def __init__(self):
            self.count = 0

          def bump(self):
            self.count += 1
        """)
    assert found == []


# ---------------------------------------------------------------------------
# shape-literals
# ---------------------------------------------------------------------------


class TestShapeLiterals:

  PATH = 'deepconsensus_tpu/inference/fixture.py'

  def test_catches_shape_assignment(self):
    found = findings_for(shape_literals, self.PATH, """\
        max_length = 100
        """)
    assert len(found) == 1 and '100' in found[0].message

  def test_catches_shape_keyword(self):
    found = findings_for(shape_literals, self.PATH, """\
        def f(make):
          return make(example_width=100)
        """)
    assert len(found) == 1

  def test_catches_shape_comparison(self):
    found = findings_for(shape_literals, self.PATH, """\
        def fits(rows):
          return rows.shape[-1] <= 128
        """)
    assert len(found) == 1

  def test_catches_shape_param_default(self):
    found = findings_for(shape_literals, self.PATH, """\
        def windows(reads, window_len=100):
          return reads[:window_len]
        """)
    assert len(found) == 1

  def test_passes_non_shape_literal(self):
    found = findings_for(shape_literals, self.PATH, """\
        RETRIES = 100

        def f(xs):
          return xs[:100] + list(range(128))
        """)
    assert found == []

  def test_passes_config_py(self):
    found = findings_for(
        shape_literals, 'deepconsensus_tpu/models/config.py', """\
        max_length = 100
        """)
    assert found == []


# ---------------------------------------------------------------------------
# registry-writes
# ---------------------------------------------------------------------------


class TestRegistryWrites:

  PATH = 'deepconsensus_tpu/fleet/router.py'

  def test_catches_subscript_write(self):
    found = findings_for(registry_writes, self.PATH, """\
        class Core:
          def bump(self, key):
            self._counters[key] += 1
        """)
    assert len(found) == 1 and found[0].rule == 'registry-writes'

  def test_catches_subscript_assign(self):
    found = findings_for(registry_writes, self.PATH, """\
        class Core:
          def reset(self, key):
            self.fault_counters[key] = 0
        """)
    assert len(found) == 1

  def test_catches_update_call(self):
    found = findings_for(registry_writes, self.PATH, """\
        class Core:
          def merge(self, other):
            self._counters.update(other)
        """)
    assert len(found) == 1

  def test_allow_comment_suppresses(self):
    found = findings_for(registry_writes, self.PATH, """\
        class Core:
          def bump(self, key):
            # dclint: allow=registry-writes (migration shim)
            self._counters[key] += 1
        """)
    assert found == []

  def test_reads_and_local_dicts_pass(self):
    found = findings_for(registry_writes, self.PATH, """\
        class Core:
          def stats(self):
            counters = dict(self._counters)
            counters['n_requests'] = 1
            counters.setdefault('n_retries', 0)
            return counters
        """)
    assert found == []

  def test_registry_implementation_exempt(self):
    found = findings_for(
        registry_writes, 'deepconsensus_tpu/obs/metrics.py', """\
        class MetricsRegistry:
          def counter(self, name):
            self._counters[name] = object()
        """)
    assert found == []

  def test_out_of_scope_file_ignored(self):
    found = findings_for(
        registry_writes, 'deepconsensus_tpu/inference/runner.py', """\
        class R:
          def f(self):
            self._counters['x'] += 1
        """)
    assert found == []


# ---------------------------------------------------------------------------
# Baseline workflow (tmp mirror tree)
# ---------------------------------------------------------------------------


def make_tree(tmp_path, rel_path, source):
  p = tmp_path / rel_path
  p.parent.mkdir(parents=True, exist_ok=True)
  p.write_text(textwrap.dedent(source))
  return p


class TestBaselineWorkflow:

  SHAPE_VIOLATION = """\
      max_length = 100
      """

  def test_new_violation_fails(self, tmp_path, capsys):
    make_tree(tmp_path, 'deepconsensus_tpu/inference/x.py',
              self.SHAPE_VIOLATION)
    assert dclint_main.run(['--root', str(tmp_path)]) == 1

  def test_update_then_clean_then_new_violation(self, tmp_path):
    f = make_tree(tmp_path, 'deepconsensus_tpu/inference/x.py',
                  self.SHAPE_VIOLATION)
    root = ['--root', str(tmp_path)]
    assert dclint_main.run(root + ['--update-baseline']) == 0
    baseline = tmp_path / 'tools' / 'dclint' / 'baseline.json'
    assert baseline.exists()
    # Baselined finding no longer fails.
    assert dclint_main.run(root) == 0
    # A NEW violation (different line text) still fails.
    f.write_text(f.read_text() + 'example_width = 100\n')
    assert dclint_main.run(root) == 1
    # --no-baseline reports everything.
    assert dclint_main.run(root + ['--no-baseline']) == 1

  def test_update_baseline_refuses_zero_baseline_rules(
      self, tmp_path, capsys):
    make_tree(tmp_path, 'deepconsensus_tpu/io/x.py', """\
        def f():
          raise ValueError('nope')
        """)
    assert dclint_main.run(['--root', str(tmp_path),
                            '--update-baseline']) == 1
    out = capsys.readouterr().out
    assert 'refusing to baseline' in out
    assert not (tmp_path / 'tools' / 'dclint' / 'baseline.json').exists()

  def test_fingerprints_survive_line_moves(self, tmp_path):
    f = make_tree(tmp_path, 'deepconsensus_tpu/inference/x.py',
                  self.SHAPE_VIOLATION)
    root = ['--root', str(tmp_path)]
    assert dclint_main.run(root + ['--update-baseline']) == 0
    # Pushing the finding down the file must not invalidate its entry.
    f.write_text('import os\n\n\n' + f.read_text())
    assert dclint_main.run(root) == 0

  def test_json_format(self, tmp_path, capsys):
    make_tree(tmp_path, 'deepconsensus_tpu/inference/x.py',
              self.SHAPE_VIOLATION)
    assert dclint_main.run(['--root', str(tmp_path),
                            '--format', 'json']) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload['new'] and payload['new'][0]['rule'] == (
        'shape-literals')


# ---------------------------------------------------------------------------
# Repo-wide gates
# ---------------------------------------------------------------------------


class TestRepoGates:

  def test_repo_lints_clean_against_committed_baseline(self, capsys):
    assert dclint_main.run([]) == 0, capsys.readouterr().out

  def test_no_zero_baseline_rule_findings_in_repo(self):
    findings = core.run_lint(str(REPO_ROOT))
    burned_down = [f for f in findings
                   if f.rule in dclint_main.ZERO_BASELINE_RULES
                   or f.rule == 'jit-hazards']
    assert burned_down == [], '\n'.join(f.format() for f in burned_down)

  def test_committed_baseline_has_no_zero_baseline_rules(self):
    baseline = json.loads(
        (REPO_ROOT / 'tools' / 'dclint' / 'baseline.json').read_text())
    for rule in dclint_main.ZERO_BASELINE_RULES:
      assert not baseline['rules'].get(rule), (
          f'{rule} findings must be fixed, never baselined')

  def test_cli_lint_subcommand(self, capsys):
    from deepconsensus_tpu import cli

    assert cli.main(['lint']) == 0, capsys.readouterr().out


# ---------------------------------------------------------------------------
# Config stays in sync with the real fault modules
# ---------------------------------------------------------------------------


def public_names(module):
  return {
      name for name in vars(module)
      if not name.startswith('_')
      and getattr(getattr(module, name), '__module__', module.__name__)
      == module.__name__
  }


class TestConfigSync:

  def test_fault_types_exist_and_are_exceptions(self):
    import deepconsensus_tpu.faults as shared
    import deepconsensus_tpu.inference.faults as inf

    for name in dclint_config.FAULT_TYPES:
      obj = getattr(shared, name, None) or getattr(inf, name, None)
      assert obj is not None, f'FAULT_TYPES entry {name} no longer exists'
      assert issubclass(obj, BaseException), name

  def test_shared_fault_taxonomy_covered(self):
    """Every exception class in the shared faults module is in
    FAULT_TYPES (adding a fault type must extend the checker too)."""
    import deepconsensus_tpu.faults as shared

    taxonomy = {
        name for name in public_names(shared)
        if isinstance(getattr(shared, name), type)
        and issubclass(getattr(shared, name), BaseException)
    }
    assert taxonomy <= set(dclint_config.FAULT_TYPES), (
        taxonomy - set(dclint_config.FAULT_TYPES))

  def test_inference_faults_reexports_shared_surface(self):
    """The inference-side shim must re-export every public name of the
    shared faults module as the identical object (no drift)."""
    import deepconsensus_tpu.faults as shared
    import deepconsensus_tpu.inference.faults as inf

    missing = {
        name for name in public_names(shared)
        if getattr(inf, name, None) is not getattr(shared, name)
    }
    assert missing == set(), (
        f'inference.faults re-export shim drifted: {sorted(missing)}')
