"""`dctpu serve` resilience suite.

In-process server on a stubbed (weightless) model for the fast tier:
admission control, deadlines, client fault modes, pack-failure
isolation, quarantine attribution, drain semantics, and serve-vs-batch
byte identity. The real-subprocess SIGTERM-under-load acceptance demo
(jit compile + signal delivery) is marked slow and runs with the
resilience suite (`scripts/run_resilience.sh --serve`).
"""
import collections
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from deepconsensus_tpu import faults as shared_faults
from deepconsensus_tpu.inference import runner as runner_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.serve import client as client_lib
from deepconsensus_tpu.serve import server as server_lib
from deepconsensus_tpu.serve.client import ServeClient, ServeClientError
from deepconsensus_tpu.serve.service import ConsensusService, ServeOptions

pytestmark = pytest.mark.resilience

BATCH = 8
STUB_QUAL = 40


@pytest.fixture(scope='module')
def params():
  p = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(p, is_training=False)
  return p


class _StubControl:
  """Mutable knobs for the stubbed forward (per-test behavior)."""

  def __init__(self):
    self.dispatch_delay = 0.0


def _stub_runner(params, control=None):
  options = runner_lib.InferenceOptions(batch_size=BATCH)
  options.max_passes = params.max_passes
  options.max_length = params.max_length
  options.use_ccs_bq = params.use_ccs_bq
  runner = runner_lib.ModelRunner(params, {}, options)
  mp = params.max_passes
  control = control or _StubControl()

  def dispatch(rows):
    if control.dispatch_delay:
      time.sleep(control.dispatch_delay)
    return rows

  def finalize(rows):
    ids = rows[:, 4 * mp, :, 0].astype(np.int32)
    return ids, np.full(ids.shape, STUB_QUAL, np.int32)

  runner.dispatch = dispatch
  runner.finalize = finalize
  return runner, options, control


class _Ctx:
  def __init__(self, service, httpd, port, control):
    self.service = service
    self.httpd = httpd
    self.port = port
    self.control = control
    self.client = ServeClient(port=port, timeout=30)


@pytest.fixture()
def serve_ctx(params, tmp_path):
  """One in-process server per test: fresh counters, fresh dead-letter
  sidecar, stub model (no weights, no jit)."""
  made = []

  def make(**overrides):
    runner, options, control = _stub_runner(params)
    buckets = overrides.pop('window_buckets', None)
    if buckets:
      options.window_buckets = buckets
    so_kw = dict(
        io_timeout_s=2.0,
        default_deadline_s=20.0,
        dead_letter_path=str(tmp_path / 'serve.failed.jsonl'),
    )
    so_kw.update(overrides)
    service = ConsensusService(runner, options, ServeOptions(**so_kw))
    service.warmup()
    service.start()
    httpd = server_lib.build_server(service, '127.0.0.1', 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    ctx = _Ctx(service, httpd, httpd.server_address[1], control)
    made.append(ctx)
    return ctx

  yield make
  for ctx in made:
    ctx.service.begin_drain()
    ctx.httpd.shutdown()
    ctx.httpd.server_close()
    ctx.service.drain(timeout=10)


def _mol(params, name, n=4, seed=0, width=None):
  width = width or params.max_length
  rng = np.random.default_rng(seed)
  return dict(
      name=name,
      subreads=rng.integers(
          0, 5, size=(n, params.total_rows, width, 1)
      ).astype(np.float32),
      window_pos=np.arange(n, dtype=np.int64) * width,
      ccs_bq=np.full((n, width), 30, dtype=np.int32),
      overflow=np.zeros(n, dtype=np.uint8),
  )


def test_polish_roundtrip_and_metrics(serve_ctx, params):
  ctx = serve_ctx()
  assert ctx.client.wait_ready(10)
  resp = ctx.client.polish(**_mol(params, 'm/1/ccs'))
  assert resp['status'] == 'ok'
  assert len(resp['seq']) > 0
  assert len(resp['quals']) == len(resp['seq'])
  assert resp['counters']['n_windows_to_model'] == 4
  m = ctx.client.metricz()
  assert m['counters']['n_requests'] == 1
  assert m['latency']['count'] == 1
  assert m['latency']['p50'] is not None
  assert m['counters']['n_rejected_backpressure'] == 0
  assert m['counters']['n_deadline_cancelled'] == 0
  assert m['counters']['n_quarantined_by_request'] == 0


def test_concurrent_clients_byte_identical_to_solo(serve_ctx, params):
  """Continuous batching packs many clients' windows into shared
  fixed-shape packs; every client still gets exactly its solo result
  (zero cross-request state leaks)."""
  ctx = serve_ctx()
  mols = [_mol(params, f'm/{i}/ccs', n=3 + i % 4, seed=i)
          for i in range(10)]
  solo = [ctx.client.polish(**m) for m in mols]
  results = [None] * len(mols)
  errors = []

  def worker(i):
    try:
      results[i] = ServeClient(port=ctx.port, timeout=30).polish(**mols[i])
    except Exception as e:
      errors.append(e)

  threads = [threading.Thread(target=worker, args=(i,))
             for i in range(len(mols))]
  for t in threads:
    t.start()
  for t in threads:
    t.join(30)
  assert not errors
  for i, (s, r) in enumerate(zip(solo, results)):
    assert r['status'] == 'ok', i
    assert r['seq'] == s['seq'], i
    np.testing.assert_array_equal(r['quals'], s['quals'])
  stats = ctx.service.stats()
  # Shared packs actually happened: fewer packs than requests' windows
  # would need unbatched.
  assert stats['n_model_packs'] < sum(3 + i % 4 for i in range(10))


def test_mixed_width_clients_share_per_bucket_packs(serve_ctx, params):
  """Clients sending L=100 and L=200 requests concurrently each get
  their solo bytes back; the engine packs each width into its own
  bucket's shared packs and reports per-bucket counters in /metricz."""
  ctx = serve_ctx(window_buckets=(100, 200))
  assert ctx.client.wait_ready(10)
  mols = [_mol(params, f'm/{i}/ccs', n=3 + i % 3, seed=i,
               width=200 if i % 2 else 100)
          for i in range(10)]
  solo = [ctx.client.polish(**m) for m in mols]
  results = [None] * len(mols)
  errors = []

  def worker(i):
    try:
      results[i] = ServeClient(port=ctx.port, timeout=30).polish(**mols[i])
    except Exception as e:
      errors.append(e)

  threads = [threading.Thread(target=worker, args=(i,))
             for i in range(len(mols))]
  for t in threads:
    t.start()
  for t in threads:
    t.join(30)
  assert not errors
  for i, (s, r) in enumerate(zip(solo, results)):
    assert r['status'] == 'ok', i
    assert r['seq'] == s['seq'], i
    np.testing.assert_array_equal(r['quals'], s['quals'])
  m = ctx.client.metricz()
  counters = m['counters']
  assert set(map(int, counters['n_packs_by_bucket'])) == {100, 200}
  assert counters['padding_fraction'] > 0
  # Starvation accounting reaches /metricz (values depend on request
  # interleaving; the math is pinned at the engine boundary).
  assert counters['n_starvation_flushes'] >= 0
  assert 0.0 <= counters['flush_padding_fraction'] <= 1.0
  assert counters['use_ragged_kernel'] == 0
  assert m['window_buckets'] == [100, 200]
  # A width outside the buckets is a 400, not an engine fault.
  with pytest.raises(ServeClientError, match='400'):
    ctx.client.polish(**_mol(params, 'm/bad/ccs', width=150))


def test_metricz_hammer_during_soak_exact_counters(serve_ctx, params):
  """Regression for the metrics/model-loop race: /metricz used to
  sort the latency deque while _finish appended to it ("deque mutated
  during iteration"). N reader threads hammer /metricz through a full
  soak batch; every read must succeed and the final counters must be
  exact — no torn reads, no lost increments."""
  ctx = serve_ctx()
  assert ctx.client.wait_ready(10)
  ctx.control.dispatch_delay = 0.002  # keep latencies flowing
  n_requests = 24
  stop = threading.Event()
  reader_errors = []
  n_reads = [0]

  def hammer():
    client = ServeClient(port=ctx.port, timeout=30)
    while not stop.is_set():
      try:
        m = client.metricz()
        # Counters must always be internally coherent mid-soak.
        assert 0 <= m['counters']['n_requests'] <= n_requests
        assert 0 <= m['latency']['count'] <= n_requests
        n_reads[0] += 1
      except Exception as e:  # noqa: BLE001 - reported via the assert
        reader_errors.append(e)
        return

  readers = [threading.Thread(target=hammer) for _ in range(4)]
  for t in readers:
    t.start()

  submit_errors = []

  def submit(base):
    client = ServeClient(port=ctx.port, timeout=30)
    for i in range(n_requests // 4):
      try:
        resp = client.polish(**_mol(params, f'm/{base}_{i}/ccs'))
        assert resp['status'] == 'ok'
      except Exception as e:  # noqa: BLE001
        submit_errors.append(e)

  submitters = [threading.Thread(target=submit, args=(w,))
                for w in range(4)]
  for t in submitters:
    t.start()
  for t in submitters:
    t.join(60)
  stop.set()
  for t in readers:
    t.join(30)

  assert not submit_errors, submit_errors[:3]
  assert not reader_errors, reader_errors[:3]
  assert n_reads[0] > 0
  m = ctx.client.metricz()
  assert m['counters']['n_requests'] == n_requests
  assert m['latency']['count'] == n_requests
  assert m['counters']['n_quarantined_by_request'] == 0
  assert m['counters']['n_deadline_cancelled'] == 0


def test_garbage_body_rejected_400(serve_ctx, params):
  ctx = serve_ctx()
  status = client_lib.send_garbage('127.0.0.1', ctx.port)
  assert status == 400
  # Service unharmed: a well-formed request still completes.
  assert ctx.client.polish(**_mol(params, 'm/2/ccs'))['status'] == 'ok'


def test_oversized_rejected_on_header_413(serve_ctx, params):
  ctx = serve_ctx()
  status = client_lib.send_oversized('127.0.0.1', ctx.port,
                                     claimed_bytes=1 << 40)
  assert status == 413
  assert ctx.client.polish(**_mol(params, 'm/3/ccs'))['status'] == 'ok'


def test_window_cap_rejected_413(serve_ctx, params):
  ctx = serve_ctx(max_windows_per_request=2)
  with pytest.raises(ServeClientError) as exc:
    ctx.client.polish(**_mol(params, 'm/4/ccs', n=5))
  assert exc.value.status == 413


def test_mid_request_disconnect_harmless(serve_ctx, params):
  ctx = serve_ctx()
  from deepconsensus_tpu.serve import protocol
  body = protocol.encode_request(**_mol(params, 'm/5/ccs'))
  for _ in range(3):
    client_lib.send_disconnect('127.0.0.1', ctx.port, body)
  assert ctx.client.healthz()['_status'] == 200
  assert ctx.client.polish(**_mol(params, 'm/6/ccs'))['status'] == 'ok'
  # Disconnected uploads never reached admission.
  assert ctx.client.metricz()['counters']['n_requests'] == 1


def test_slowloris_cut_by_io_timeout(serve_ctx, params):
  """A drip-feed connection is cut at io_timeout_s (2s here), long
  before the requested 20s, and the model loop never notices."""
  ctx = serve_ctx()
  survived = client_lib.send_slowloris('127.0.0.1', ctx.port,
                                       duration_s=20.0, interval_s=0.5)
  assert survived < 10.0
  assert ctx.client.polish(**_mol(params, 'm/7/ccs'))['status'] == 'ok'


def test_backpressure_429(serve_ctx, params):
  """max_pending=1 with a slow model: while one request occupies the
  loop, the next is shed with a typed 429 classifying transient."""
  ctx = serve_ctx(max_pending=1)
  ctx.control.dispatch_delay = 3.0
  first = {}

  def slow_one():
    first['resp'] = ctx.client.polish(**_mol(params, 'm/8/ccs'))

  t = threading.Thread(target=slow_one)
  t.start()
  time.sleep(0.5)  # the slow request is admitted and in flight
  rejected = None
  deadline = time.monotonic() + 2.0  # well inside the 3s dispatch
  while time.monotonic() < deadline and rejected is None:
    try:
      ServeClient(port=ctx.port, timeout=10).polish(
          **_mol(params, 'm/9/ccs'))
    except ServeClientError as e:
      rejected = e
    time.sleep(0.05)
  t.join(20)
  assert rejected is not None, 'never saw backpressure'
  assert rejected.status == 429
  assert rejected.kind == shared_faults.FaultKind.TRANSIENT
  assert first['resp']['status'] == 'ok'  # admitted work unaffected
  assert ctx.client.metricz()['counters']['n_rejected_backpressure'] >= 1


def test_deadline_cancelled_504(serve_ctx, params):
  ctx = serve_ctx()
  ctx.control.dispatch_delay = 2.0
  with pytest.raises(ServeClientError) as exc:
    ctx.client.polish(**_mol(params, 'm/10/ccs'), deadline_s=0.3)
  assert exc.value.status == 504
  assert exc.value.kind == shared_faults.FaultKind.TRANSIENT
  ctx.control.dispatch_delay = 0.0
  # The loop sheds the cancelled work and keeps serving.
  assert ctx.client.polish(**_mol(params, 'm/11/ccs'))['status'] == 'ok'
  assert ctx.client.metricz()['counters']['n_deadline_cancelled'] == 1


def test_poison_quarantined_with_attribution_others_clean(
    serve_ctx, params, monkeypatch, tmp_path):
  """The acceptance core: a poison request sharing packs with clean
  requests fails its shared pack, fails its isolation retry, and is
  quarantined + dead-lettered with request attribution — while the
  clean requests complete byte-identical to their solo runs."""
  ctx = serve_ctx(on_request_error='ccs-fallback')
  clean = [_mol(params, f'm/{20 + i}/ccs', n=3, seed=i) for i in range(4)]
  solo = [ctx.client.polish(**m) for m in clean]
  poison_mol = _mol(params, 'm/666/ccs', n=3, seed=99)

  monkeypatch.setenv(shared_faults.ENV_POISON_WINDOW, 'm/666/')
  results = [None] * len(clean)
  poison_result = {}

  def clean_worker(i):
    results[i] = ServeClient(port=ctx.port, timeout=30).polish(**clean[i])

  def poison_worker():
    poison_result['resp'] = ServeClient(
        port=ctx.port, timeout=30).polish(**poison_mol)

  threads = [threading.Thread(target=clean_worker, args=(i,))
             for i in range(len(clean))] + [
      threading.Thread(target=poison_worker)]
  for t in threads:
    t.start()
  for t in threads:
    t.join(30)
  monkeypatch.delenv(shared_faults.ENV_POISON_WINDOW)

  # Clean clients: byte-identical to solo despite sharing packs with
  # the poison payload.
  for i, (s, r) in enumerate(zip(solo, results)):
    assert r is not None and r['status'] == 'ok', i
    assert r['seq'] == s['seq'], i
  # Poison client: degraded per policy (draft-CCS fallback), not a
  # service crash.
  resp = poison_result['resp']
  assert resp['status'] == 'fallback'
  assert 'poison' in resp['error']
  assert ctx.service.healthy
  m = ctx.client.metricz()
  assert m['counters']['n_quarantined_by_request'] == 1
  assert m['counters']['n_isolation_retries'] >= 1
  # Dead-letter carries request attribution.
  entries = [json.loads(line)
             for line in open(tmp_path / 'serve.failed.jsonl')]
  mine = [e for e in entries if e['zmw'] == 'm/666/ccs']
  assert len(mine) == 1
  assert mine[0]['stage'] == 'model'
  assert mine[0]['action'] == 'ccs-fallback'
  assert mine[0]['request_id'] > 0
  assert 'client' in mine[0] and 'model_pack' in mine[0]


def test_quarantine_skip_policy(serve_ctx, params, monkeypatch):
  ctx = serve_ctx(on_request_error='skip')
  monkeypatch.setenv(shared_faults.ENV_POISON_WINDOW, 'm/667/')
  resp = ctx.client.polish(**_mol(params, 'm/667/ccs', seed=1))
  assert resp['status'] == 'quarantined'
  assert resp['seq'] == b''


def test_draining_rejects_new_admissions(serve_ctx, params):
  ctx = serve_ctx()
  assert ctx.client.polish(**_mol(params, 'm/30/ccs'))['status'] == 'ok'
  ctx.service.begin_drain()
  assert ctx.client.readyz()['_status'] == 503
  assert ctx.client.healthz()['_status'] == 200  # alive, just draining
  with pytest.raises(ServeClientError) as exc:
    ctx.client.polish(**_mol(params, 'm/31/ccs'))
  assert exc.value.status == 503
  assert exc.value.kind == shared_faults.FaultKind.TRANSIENT
  assert ctx.service.drain(timeout=10)


def test_client_sabotage_env_hooks(serve_ctx, params, monkeypatch):
  """DCTPU_FAULT_SERVE_CLIENT turns a well-behaved ServeClient into
  the adversarial one, scoped by ZMW substring."""
  ctx = serve_ctx()
  monkeypatch.setenv(shared_faults.ENV_SERVE_CLIENT_FAULT, 'garbage')
  monkeypatch.setenv(shared_faults.ENV_SERVE_CLIENT_FAULT_ZMW, '/40/')
  sabotaged = ctx.client.polish(**_mol(params, 'm/40/ccs'))
  assert sabotaged['status'] == 'client-fault'
  assert sabotaged['mode'] == 'garbage'
  # Out-of-scope names are untouched.
  assert ctx.client.polish(**_mol(params, 'm/41/ccs'))['status'] == 'ok'


# ----------------------------------------------------------------------
# Observability plane: unified /metricz schema, Prometheus exposition,
# on-demand profiler capture, request trace spans (ISSUE 15)


def _http_get(port, path):
  import urllib.request
  req = urllib.request.urlopen(
      f'http://127.0.0.1:{port}{path}', timeout=15)
  with req as r:
    return r.status, r.headers.get('Content-Type', ''), r.read()


def test_metricz_unified_schema(serve_ctx, params):
  """Every tier's /metricz leads with the same top-level keys; the
  one-release legacy aliases (serve `faults` block, `p50_s`/`p99_s`/`n`
  percentile keys) are gone."""
  ctx = serve_ctx()
  assert ctx.client.wait_ready(10)
  ctx.client.polish(**_mol(params, 'm/70/ccs'))
  m = ctx.client.metricz()
  for key in ('tier', 'ready', 'draining', 'outstanding', 'counters',
              'latency', 'histograms'):
    assert key in m, key
  assert m['tier'] == 'serve'
  assert m['counters']['n_requests'] == 1
  assert 'serve_request_latency_s' in m['histograms']
  # Nearest-rank percentiles under the canonical keys ONLY: the
  # p50_s/p99_s/n aliases kept for one release are removed.
  lat = m['latency']
  assert lat['p50'] is not None and lat['p99'] is not None
  assert lat['count'] == 1
  assert not {'p50_s', 'p99_s', 'n'} & set(lat)
  # The legacy serve-only faults split is removed with them.
  assert 'faults' not in m


def test_metricz_prom_format(serve_ctx, params):
  ctx = serve_ctx()
  assert ctx.client.wait_ready(10)
  ctx.client.polish(**_mol(params, 'm/71/ccs'))
  status, ctype, body = _http_get(ctx.port, '/metricz?format=prom')
  assert status == 200
  assert ctype.startswith('text/plain')
  text = body.decode()
  assert 'dctpu_n_requests{tier="serve"} 1' in text
  assert 'dctpu_serve_request_latency_s_bucket{tier="serve",' in text
  assert 'dctpu_serve_request_latency_s_count{tier="serve"} 1' in text


def test_debugz_profile_capture(serve_ctx, params, tmp_path):
  """/debugz/profile?seconds=N runs a bounded jax.profiler capture in
  the handler thread and reports a status dict either way."""
  ctx = serve_ctx()
  assert ctx.client.wait_ready(10)
  out_dir = str(tmp_path / 'prof')
  status, _, body = _http_get(
      ctx.port, f'/debugz/profile?seconds=0.2&out={out_dir}')
  result = json.loads(body)
  assert status in (200, 503)
  assert 'ok' in result
  if result['ok']:
    assert result['out_dir'] == out_dir
    assert os.path.isdir(out_dir)
  # Bad seconds param is a 400, not a crash.
  import urllib.error
  with pytest.raises(urllib.error.HTTPError) as exc:
    _http_get(ctx.port, '/debugz/profile?seconds=banana')
  assert exc.value.code == 400


def test_request_trace_spans_and_header_propagation(
    serve_ctx, params, tmp_path):
  """A traced replica emits the request's span tree stamped with the
  trace id minted upstream (carried in the polish protocol header)."""
  from deepconsensus_tpu import obs as obs_lib

  trace_path = str(tmp_path / 'serve_trace.jsonl')
  obs_lib.trace.configure(trace_path, tier='serve')
  try:
    ctx = serve_ctx()
    assert ctx.client.wait_ready(10)
    resp = ctx.client.polish(**_mol(params, 'm/72/ccs'),
                             trace_id='0123456789abcdef')
    assert resp['status'] == 'ok'
  finally:
    obs_lib.trace.configure(None)
  from deepconsensus_tpu.obs import summarize as summarize_lib
  events = summarize_lib.load_trace(trace_path)
  spans = [e for e in events if e.get('ph') == 'X']
  req = [e for e in spans if e['name'] == 'serve_request']
  assert len(req) == 1
  assert req[0]['args']['trace_id'] == '0123456789abcdef'
  assert req[0]['args']['zmw'] == 'm/72/ccs'
  # The stitch leg of the same request carries the same id.
  stitch = [e for e in spans if e['name'] == 'stitch'
            and e['args'].get('trace_id') == '0123456789abcdef']
  assert stitch


def test_quarantine_record_carries_trace_id(serve_ctx, params,
                                            monkeypatch, tmp_path):
  """Dead-lettered / quarantined requests are joinable to their trace:
  the failure record carries the request's trace id."""
  ctx = serve_ctx(on_request_error='ccs-fallback')
  monkeypatch.setenv(shared_faults.ENV_POISON_WINDOW, 'm/73/')
  resp = ctx.client.polish(**_mol(params, 'm/73/ccs'),
                           trace_id='feedfeedfeedfeed')
  assert resp['status'] == 'fallback'
  entries = [json.loads(line)
             for line in open(tmp_path / 'serve.failed.jsonl')]
  mine = [e for e in entries if e['zmw'] == 'm/73/ccs']
  assert len(mine) == 1
  assert mine[0]['trace_id'] == 'feedfeedfeedfeed'


# ----------------------------------------------------------------------
# Data-parallel serving: mesh-backed service vs single-device service


@pytest.mark.multichip
def test_serve_with_mesh_byte_identical_to_single_device(params):
  """A dp=8 mesh behind the service must be invisible to clients:
  every response byte-matches the single-device service, while
  /metricz's faults split reports the sharded-dispatch counters."""
  import jax
  import jax.numpy as jnp

  from deepconsensus_tpu.models import model as model_lib
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  variables = model_lib.get_model(params).init(
      jax.random.PRNGKey(0),
      jnp.zeros((1, params.total_rows, params.max_length, 1)))
  mols = [_mol(params, f'm/{i}/ccs', n=3 + i % 4, seed=i)
          for i in range(6)]

  def serve_all(mesh):
    options = runner_lib.InferenceOptions(
        batch_size=BATCH, min_quality=0, min_length=0)
    options.max_passes = params.max_passes
    options.max_length = params.max_length
    options.use_ccs_bq = params.use_ccs_bq
    runner = runner_lib.ModelRunner(params, variables, options,
                                    mesh=mesh)
    service = ConsensusService(runner, options,
                               ServeOptions(io_timeout_s=2.0))
    service.warmup()
    service.start()
    httpd = server_lib.build_server(service, '127.0.0.1', 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
      client = ServeClient(port=httpd.server_address[1], timeout=30)
      assert client.wait_ready(10)
      responses = [client.polish(**m) for m in mols]
      metrics = client.metricz()
    finally:
      service.begin_drain()
      httpd.shutdown()
      httpd.server_close()
      service.drain(timeout=10)
    return responses, metrics

  single, metrics_single = serve_all(None)
  mesh = mesh_lib.make_mesh(dp=8, tp=1, devices=jax.devices()[:8])
  sharded, metrics_sharded = serve_all(mesh)

  for i, (s, m) in enumerate(zip(single, sharded)):
    assert m['status'] == s['status'], i
    assert m['seq'] == s['seq'], i
    np.testing.assert_array_equal(m['quals'], s['quals'])
  assert metrics_single['counters']['n_packs_dispatched_sharded'] == 0
  counters = metrics_sharded['counters']
  assert counters['n_packs_dispatched_sharded'] > 0
  assert (counters['n_transfer_overlapped']
          + counters['n_transfer_direct']) >= counters[
              'n_packs_dispatched_sharded']


# ----------------------------------------------------------------------
# Subprocess acceptance demo: SIGTERM drain under load, clean exit


@pytest.mark.slow
def test_sigterm_drains_under_load_subprocess(params, tmp_path):
  """Real `dctpu serve` process (random-init weights, real jit):
  SIGTERM mid-load must stop admissions, finish every admitted
  request (zero accepted-then-lost), and exit 0."""
  env = dict(os.environ, JAX_PLATFORMS='cpu')
  proc = subprocess.Popen(
      [sys.executable, '-m', 'deepconsensus_tpu.cli', 'serve',
       '--random_init', '--port', '0', '--min_quality', '0',
       '--dead_letter', str(tmp_path / 'dl.jsonl')],
      stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True)
  try:
    ready = json.loads(proc.stdout.readline())
    assert ready['event'] == 'ready'
    port = ready['port']
    client = ServeClient(port=port, timeout=60)
    assert client.wait_ready(60)

    outcomes = collections.Counter()
    lock = threading.Lock()
    stop_clients = threading.Event()

    def worker(wid):
      i = 0
      while not stop_clients.is_set():
        i += 1
        try:
          resp = ServeClient(port=port, timeout=60).polish(
              **_mol(params, f'm/{wid}_{i}/ccs', n=2, seed=wid * 100 + i))
          with lock:
            outcomes[resp['status']] += 1
        except ServeClientError as e:
          with lock:
            # 503 draining is the only acceptable rejection here.
            outcomes[f'http_{e.status}'] += 1
        except (ConnectionError, OSError):
          with lock:
            outcomes['conn_refused'] += 1
          return

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(4)]
    for t in threads:
      t.start()
    time.sleep(2.0)  # load flowing
    proc.send_signal(signal.SIGTERM)
    proc.wait(timeout=120)
    stop_clients.set()
    for t in threads:
      t.join(30)

    assert proc.returncode == 0, proc.stderr.read()[-2000:]
    tail = [json.loads(line) for line in proc.stdout.read().splitlines()
            if line.startswith('{')]
    drained = [d for d in tail if d.get('event') == 'drained']
    assert drained and drained[0]['drained'] is True
    # Zero accepted-then-lost: every request either completed ('ok',
    # or 'filtered' when random weights polish below the length floor)
    # or was rejected with a typed drain/backpressure code before
    # admission. No deadline cancels, no quarantines, no hangs.
    assert outcomes['ok'] + outcomes['filtered'] >= 1
    unexpected = {k: v for k, v in outcomes.items()
                  if k not in ('ok', 'filtered', 'http_503', 'http_429',
                               'conn_refused')}
    assert not unexpected, outcomes
    assert drained[0]['counters']['n_deadline_cancelled'] == 0
  finally:
    if proc.poll() is None:
      proc.kill()
      proc.wait()
