"""Model construction, shapes, and forward-pass invariants
(modeled on reference networks_test.py coverage)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.models import model as model_lib


def make_params(name='transformer_learn_values+test', **overrides):
  params = config_lib.get_config(name)
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'  # deterministic numerics on CPU tests
    for k, v in overrides.items():
      params[k] = v
  return params


def fake_rows(params, batch=2, seed=0):
  rng = np.random.default_rng(seed)
  rows = np.zeros(
      (batch, params.total_rows, params.max_length, 1), dtype=np.float32
  )
  mp = params.max_passes
  rows[:, :mp] = rng.integers(0, 5, size=rows[:, :mp].shape)
  rows[:, mp : 2 * mp] = rng.integers(0, 256, size=rows[:, :mp].shape)
  rows[:, 2 * mp : 3 * mp] = rng.integers(0, 256, size=rows[:, :mp].shape)
  rows[:, 3 * mp : 4 * mp] = rng.integers(0, 3, size=rows[:, :mp].shape)
  rows[:, 4 * mp] = rng.integers(0, 5, size=rows[:, 4 * mp].shape)
  rows[:, 4 * mp + 1 :] = rng.integers(0, 501, size=rows[:, 4 * mp + 1 :].shape)
  return jnp.asarray(rows)


def test_hidden_size_derivation():
  params = make_params()
  # 20 passes * (8+8+8+2) + ccs 8 + sn 4*8 = 560, condensed to 280.
  assert params.total_rows == 85
  assert params.hidden_size == 280
  assert params.transformer_input_size == 280


def test_forward_shapes_and_softmax():
  params = make_params()
  model = model_lib.get_model(params)
  rows = fake_rows(params)
  variables = model.init(jax.random.PRNGKey(0), rows)
  preds = model.apply(variables, rows)
  assert preds.shape == (2, params.max_length, 5)
  np.testing.assert_allclose(
      np.asarray(preds.sum(-1)), np.ones((2, params.max_length)), atol=1e-5
  )


def test_embed_onehot_matches_gather():
  """The one-hot-matmul embedding lever (embed_onehot) must be a pure
  execution-strategy change: identical predictions with the SAME
  variables as the default gather path (each output row is a single
  table row either way)."""
  params = make_params()
  rows = fake_rows(params, batch=3, seed=7)
  model = model_lib.get_model(params)
  variables = model.init(jax.random.PRNGKey(0), rows)
  base = model.apply(variables, rows)
  params_oh = make_params(embed_onehot=True)
  model_oh = model_lib.get_model(params_oh)
  got = model_oh.apply(variables, rows)
  np.testing.assert_allclose(np.asarray(got), np.asarray(base),
                             rtol=1e-6, atol=1e-6)
  # Large-vocab families (pw/ip 256, sn 501) must stay on the gather
  # path regardless of the flag (one-hot materialization cost).
  assert model_lib._ONEHOT_MAX_VOCAB < 256


def test_attn_softmax_dtype_lever():
  """bf16 softmax accumulation runs and stays close to the f32 path
  (banded logits are bounded); argmax calls must agree everywhere on
  this scale of input."""
  params = make_params()
  rows = fake_rows(params, batch=2, seed=3)
  model = model_lib.get_model(params)
  variables = model.init(jax.random.PRNGKey(0), rows)
  base = np.asarray(model.apply(variables, rows))
  params_bf = make_params(attn_softmax_dtype='bfloat16')
  got = np.asarray(model_lib.get_model(params_bf).apply(variables, rows))
  np.testing.assert_allclose(got, base, atol=0.02)
  assert (got.argmax(-1) == base.argmax(-1)).mean() > 0.999


def test_intermediates_exposed():
  params = make_params()
  model = model_lib.get_model(params)
  rows = fake_rows(params)
  variables = model.init(jax.random.PRNGKey(0), rows)
  out = model.apply(
      variables, rows, method=model.apply_with_intermediates
  )
  assert out['logits'].shape == (2, params.max_length, 5)
  assert out['final_output'].shape == (2, params.max_length, 280)


@pytest.mark.parametrize('win', [0, 6, 12, None])
def test_attention_window_sweep(win):
  params = make_params()
  with params.unlocked():
    params.attn_win_size = win
  model = model_lib.get_model(params)
  rows = fake_rows(params, batch=1)
  variables = model.init(jax.random.PRNGKey(0), rows)
  preds = model.apply(variables, rows)
  assert np.isfinite(np.asarray(preds)).all()


def test_rezero_starts_as_identity_plus_embedding():
  """With ReZero alphas at 0, the encoder stack is the identity, so two
  different inits differ only through embeddings/condenser/logits."""
  params = make_params()
  model = model_lib.get_model(params)
  rows = fake_rows(params, batch=1)
  variables = model.init(jax.random.PRNGKey(0), rows)
  alphas = [
      np.asarray(v)
      for k, v in jax.tree_util.tree_flatten_with_path(variables)[0]
      if 'alpha' in str(k)
  ]
  assert len(alphas) == 2 * params.num_hidden_layers
  assert all(a == 0.0 for a in alphas)


def test_masked_embedding_zero_id():
  emb = model_lib.MaskedEmbed(vocab_size=5, features=8)
  variables = emb.init(jax.random.PRNGKey(0), jnp.array([[0, 1]]))
  out = emb.apply(variables, jnp.array([[0, 1]]))
  np.testing.assert_array_equal(np.asarray(out[0, 0]), np.zeros(8))
  assert np.abs(np.asarray(out[0, 1])).sum() > 0


def test_bq_variant_builds():
  params = make_params('transformer_learn_values+test_bq')
  assert params.total_rows == 86
  model = model_lib.get_model(params)
  rows = jnp.zeros((1, params.total_rows, params.max_length, 1))
  variables = model.init(jax.random.PRNGKey(0), rows)
  preds = model.apply(variables, rows)
  assert preds.shape == (1, 100, 5)


def test_fc_model():
  params = make_params('fc+test')
  model = model_lib.get_model(params)
  rows = fake_rows(params, batch=2)
  variables = model.init(jax.random.PRNGKey(0), rows)
  preds = model.apply(variables, rows)
  assert preds.shape == (2, 100, 5)


def test_dataset_iterator_from_reference_shards(testdata_dir):
  params = make_params()
  ds = data_lib.DatasetIterator(
      patterns=str(testdata_dir / 'human_1m/tf_examples/train/*'),
      params=params,
      batch_size=8,
  )
  assert len(ds) == 1239
  batch = next(iter(ds))
  assert batch['rows'].shape == (8, 85, 100, 1)
  assert batch['label'].shape == (8, 100)
  # PW/IP clipped into vocab range.
  assert batch['rows'][:, 20:60].max() <= 255
  assert batch['rows'][:, 61:].max() <= 500


def test_model_runs_on_real_examples(testdata_dir):
  params = make_params()
  ds = data_lib.DatasetIterator(
      patterns=str(testdata_dir / 'human_1m/tf_examples/train/*'),
      params=params,
      batch_size=4,
      limit=4,
  )
  model = model_lib.get_model(params)
  batch = next(iter(ds))
  variables = model.init(jax.random.PRNGKey(0), jnp.asarray(batch['rows']))
  preds = model.apply(variables, jnp.asarray(batch['rows']))
  assert np.isfinite(np.asarray(preds)).all()


def test_params_json_roundtrip(tmp_path):
  params = make_params()
  config_lib.save_params_as_json(str(tmp_path), params)
  back = config_lib.read_params_from_json(str(tmp_path))
  assert back.hidden_size == params.hidden_size
  assert back.max_passes == params.max_passes
  assert back.model_name == params.model_name


def test_remat_encoder_matches_baseline():
  """params.remat must not change values or gradients — only the
  memory/recompute schedule."""
  import jax

  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import model as model_lib

  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = 2
    params.filter_size = 32
  rng = np.random.default_rng(0)
  rows = jnp.asarray(
      rng.uniform(0, 4, size=(4, params.total_rows, params.max_length,
                              1)).astype(np.float32))
  model = model_lib.get_model(params)
  variables = model.init(jax.random.PRNGKey(0), rows)
  with params.unlocked():
    params.remat = True
  model_r = model_lib.get_model(params)

  def loss(m):
    return lambda v: jnp.sum(m.apply(v, rows) ** 2)

  base_val, base_grad = jax.value_and_grad(loss(model))(variables)
  remat_val, remat_grad = jax.value_and_grad(loss(model_r))(variables)
  np.testing.assert_allclose(
      float(remat_val), float(base_val), rtol=1e-6
  )
  flat_b = jax.tree_util.tree_leaves(base_grad)
  flat_r = jax.tree_util.tree_leaves(remat_grad)
  for gb, gr in zip(flat_b, flat_r):
    np.testing.assert_allclose(
        np.asarray(gr), np.asarray(gb), atol=1e-5, rtol=1e-4
    )


def test_unknown_model_name_raises():
  """(reference model_utils_test: test_invalid_model_name_throws_error)"""
  import ml_collections
  import pytest as _pytest

  from deepconsensus_tpu.models import model as model_lib

  params = ml_collections.ConfigDict({'model_name': 'nonexistent_net'})
  with _pytest.raises(ValueError, match='Unknown model name'):
    model_lib.get_model(params)
