"""bench.py supervision: metric-line detection and failure reporting."""
import json
import sys


sys.path.insert(0, '/root/repo')
import bench  # noqa: E402


def test_is_metric_line_accepts_metric_json():
  line = json.dumps({'metric': 'model_forward_windows_per_sec',
                     'value': 123.0, 'unit': 'w/s', 'vs_baseline': 1.1})
  assert bench._is_metric_line(line)


def test_is_metric_line_rejects_garbage():
  assert not bench._is_metric_line('no json here')
  assert not bench._is_metric_line('{"not_metric": 1}')
  assert not bench._is_metric_line('')
  assert not bench._is_metric_line('WARNING: some backend log')


def test_report_failure_schema(capsys):
  rc = bench._report_failure('unit test', 3)
  assert rc == 3
  out = json.loads(capsys.readouterr().out)
  assert out['metric'] == 'model_forward_windows_per_sec'
  assert out['value'] == 0.0
  assert 'unit test' in out['unit']
  assert out['vs_baseline'] == 0.0


def test_forward_line_units_are_honest():
  line = bench._forward_line(228.0, 256, cpu_fallback=False)
  assert line['vs_baseline'] == 2.0
  assert 'NOT forward-to-forward' in line['unit']
  cpu = bench._forward_line(40.0, 256, cpu_fallback=True)
  assert 'CPU FALLBACK' in cpu['unit']


def test_tpu_child_refuses_cpu_backend():
  """A TPU-labeled child on a CPU backend must die without emitting a
  metric line: its unmarked numbers would override an honest CPU
  FALLBACK line (the driver keeps the LAST parseable line)."""
  import os
  import subprocess

  env = dict(os.environ)
  env.pop('DC_BENCH_CPU', None)
  env['JAX_PLATFORMS'] = 'cpu'
  # The axon plugin ignores JAX_PLATFORMS and hangs on a dead tunnel;
  # keep it off the child's path so the backend resolves to cpu.
  repo_dir = os.path.dirname(os.path.abspath(bench.__file__))
  env['PYTHONPATH'] = ':'.join(
      [repo_dir] + [p for p in env.get('PYTHONPATH', '').split(':')
                    if p and p != repo_dir and 'axon' not in p])
  proc = subprocess.run(
      [sys.executable, bench.__file__, '--child'],
      capture_output=True, text=True, env=env, timeout=120)
  assert proc.returncode == 3
  assert not any(bench._is_metric_line(l) for l in proc.stdout.splitlines())
  assert 'refusing to emit mislabeled metrics' in proc.stderr


def test_late_tpu_upgrade_runs_tpu_child_when_probe_recovers(monkeypatch):
  """Once the chip answers a late probe, the TPU child runs WITHOUT the
  CPU-fallback flag so its metric lines upgrade the CPU number."""
  probes = []
  runs = []
  monkeypatch.setattr(
      bench, '_tpu_alive',
      lambda timeout_secs: probes.append(timeout_secs) or len(probes) >= 2)
  monkeypatch.setattr(
      bench, '_run_child', lambda env, wd: runs.append((env, wd)) or (0, True))
  monkeypatch.setattr(bench.time, 'sleep', lambda s: None)
  bench._late_tpu_upgrade({'DC_BENCH_CPU': '1'}, left=lambda: 600)
  assert len(probes) == 2  # first probe fails, second succeeds
  (env, watchdog), = runs
  assert 'DC_BENCH_CPU' not in env
  assert watchdog >= 120
  assert int(env['DC_BENCH_CHILD_BUDGET']) >= 60


def test_late_tpu_upgrade_gives_up_without_budget(monkeypatch):
  """No probe (let alone a child) once the remaining budget cannot fit
  probe + a useful child run."""
  monkeypatch.setattr(
      bench, '_tpu_alive',
      lambda timeout_secs: (_ for _ in ()).throw(AssertionError('probed')))
  bench._late_tpu_upgrade({}, left=lambda: bench.LATE_RETRY_MIN_SECS - 1)


def test_late_tpu_upgrade_stops_probing_when_chip_stays_dead(monkeypatch):
  """Failed probes consume wall-clock; the loop must terminate."""
  clock = [0.0]
  monkeypatch.setattr(
      bench, '_tpu_alive',
      lambda timeout_secs: clock.__setitem__(0, clock[0] + 90) or False)
  monkeypatch.setattr(
      bench.time, 'sleep', lambda s: clock.__setitem__(0, clock[0] + s))
  monkeypatch.setattr(
      bench, '_run_child',
      lambda env, wd: (_ for _ in ()).throw(AssertionError('ran child')))
  bench._late_tpu_upgrade({}, left=lambda: 600 - clock[0])
