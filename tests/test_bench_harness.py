"""bench.py supervision: result-line extraction and failure reporting."""
import json
import sys


sys.path.insert(0, '/root/repo')
import bench  # noqa: E402


def test_find_result_line_picks_metric_json():
  stdout = '\n'.join([
      'WARNING: some backend log',
      json.dumps({'metric': 'model_forward_windows_per_sec',
                  'value': 123.0, 'unit': 'w/s', 'vs_baseline': 1.1}),
      'I0000 shutdown notice',
  ])
  line = bench._find_result_line(stdout)
  assert line is not None
  assert json.loads(line)['value'] == 123.0


def test_find_result_line_none_for_garbage():
  assert bench._find_result_line('no json here\n{"not_metric": 1}') is None
  assert bench._find_result_line('') is None


def test_report_failure_schema(capsys):
  rc = bench._report_failure('unit test', 3)
  assert rc == 3
  out = json.loads(capsys.readouterr().out)
  assert out['metric'] == 'model_forward_windows_per_sec'
  assert out['value'] == 0.0
  assert 'unit test' in out['unit']
  assert out['vs_baseline'] == 0.0
