"""bench.py supervision: metric-line detection and failure reporting."""
import json
import sys


sys.path.insert(0, '/root/repo')
import bench  # noqa: E402


def test_is_metric_line_accepts_metric_json():
  line = json.dumps({'metric': 'model_forward_windows_per_sec',
                     'value': 123.0, 'unit': 'w/s', 'vs_baseline': 1.1})
  assert bench._is_metric_line(line)


def test_is_metric_line_rejects_garbage():
  assert not bench._is_metric_line('no json here')
  assert not bench._is_metric_line('{"not_metric": 1}')
  assert not bench._is_metric_line('')
  assert not bench._is_metric_line('WARNING: some backend log')


def test_report_failure_schema(capsys):
  rc = bench._report_failure('unit test', 3)
  assert rc == 3
  out = json.loads(capsys.readouterr().out)
  assert out['metric'] == 'model_forward_windows_per_sec'
  assert out['value'] == 0.0
  assert 'unit test' in out['unit']
  assert out['vs_baseline'] == 0.0


def test_forward_line_units_are_honest():
  line = bench._forward_line(228.0, 256, cpu_fallback=False)
  assert line['vs_baseline'] == 2.0
  assert 'NOT forward-to-forward' in line['unit']
  cpu = bench._forward_line(40.0, 256, cpu_fallback=True)
  assert 'CPU FALLBACK' in cpu['unit']
