"""Test configuration: force an 8-device virtual CPU mesh before jax loads.

Multi-chip sharding is validated on virtual CPU devices since tests run
off-TPU; real-TPU execution is exercised by bench.py and the driver's
compile checks.
"""
import os

os.environ['JAX_PLATFORMS'] = 'cpu'
# The reference Keras model (test_tf_forward_parity) needs Keras 2
# (tf.keras.layers.experimental.EinsumDense, legacy add_weight); must
# be set before the first tensorflow import anywhere in the process.
os.environ.setdefault('TF_USE_LEGACY_KERAS', '1')
_flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in _flags:
  os.environ['XLA_FLAGS'] = (
      _flags + ' --xla_force_host_platform_device_count=8'
  ).strip()

# The environment may pin JAX_PLATFORMS to a TPU plugin; the config
# knob takes precedence over whatever the plugin registers.
import jax

jax.config.update('jax_platforms', 'cpu')

import pathlib

import pytest

REFERENCE_TESTDATA = pathlib.Path('/root/reference/deepconsensus/testdata')


def pytest_configure(config):
  config.addinivalue_line(
      'markers',
      'resilience: fault-injection tests for the inference and '
      'training fault-tolerance layers (scripts/run_resilience.sh)',
  )
  config.addinivalue_line(
      'markers',
      'multichip: data-parallel sharded-dispatch tests driven over '
      'the 8 forced host-platform devices (run_all_tests.sh multichip)',
  )
  config.addinivalue_line(
      'markers',
      'quant: quantized-inference lever tests (bf16 end-to-end, int8 '
      'matmuls) — accuracy gates and export plumbing '
      '(run_all_tests.sh quant)',
  )
  config.addinivalue_line(
      'markers',
      'fleet: multi-replica fleet tier tests — dctpu route balancing/'
      'retry semantics, featurize workers, protocol version '
      'negotiation (run_all_tests.sh fleet)',
  )


@pytest.fixture(scope='session')
def testdata_dir() -> pathlib.Path:
  if not REFERENCE_TESTDATA.exists():
    pytest.skip('reference testdata not available')
  return REFERENCE_TESTDATA


@pytest.fixture(scope='session')
def scripts_importable():
  """Puts the repo root on sys.path so tests can import the scripts/
  package regardless of the checkout location."""
  import sys

  repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
  if repo_root not in sys.path:
    sys.path.insert(0, repo_root)
  return repo_root


@pytest.fixture
def synthetic_bams(tmp_path, scripts_importable):
  """Factory for synthetic (subreads_to_ccs.bam, ccs.bam) pairs built
  by the fault-injection harness — no reference testdata needed."""
  from scripts import inject_faults

  def make(subdir: str = 'bams', **kwargs):
    return inject_faults.write_synthetic_zmw_bams(
        str(tmp_path / subdir), **kwargs)

  return make
