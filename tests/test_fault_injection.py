"""Fault-injection tests for the inference fault-tolerance layer.

Every test runs against synthetic BAMs written by scripts/inject_faults
(no reference testdata), with skip_windows_above=1 so all windows adopt
the draft CCS and no jitted forward pass compiles — the faults under
test live in the feeder/pool/writer layers, not the model.
"""
import glob
import json
import os

import numpy as np
import pytest

from deepconsensus_tpu.inference import faults
from deepconsensus_tpu.inference import runner as runner_lib
from deepconsensus_tpu.io import bam as bam_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.preprocess.feeder import create_proc_feeder
from deepconsensus_tpu.preprocess.pileup import FeatureLayout

pytestmark = pytest.mark.resilience

MOVIE = 'm00001_000000_000000'
CORRUPT_ZMW = 102
CORRUPT_NAME = f'{MOVIE}/{CORRUPT_ZMW}/ccs'


@pytest.fixture(scope='module')
def params():
  p = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(p, is_training=False)
  return p


def _make_runner(params, **kwargs):
  kwargs.setdefault('batch_size', 32)
  kwargs.setdefault('batch_zmws', 2)
  kwargs.setdefault('skip_windows_above', 1)  # all windows adopt CCS
  kwargs.setdefault('min_quality', 0)
  options = runner_lib.InferenceOptions(**kwargs)
  # Empty variables: the forward pass is never invoked on the
  # skip-everything path, so no weights (and no jit compile) needed.
  return runner_lib.ModelRunner(params, {}, options), options


def _fastq_names(path):
  with open(path) as f:
    return [line.rstrip('\n')[1:] for line in f if line.startswith('@')]


def _corrupt(inject_faults_mod, subreads, tmp_path, zmw=CORRUPT_ZMW):
  bad = str(tmp_path / 'corrupt.bam')
  n = inject_faults_mod.corrupt_zmw(subreads, bad, zmw)
  assert n > 0
  return bad


@pytest.fixture
def inject(scripts_importable):
  from scripts import inject_faults
  return inject_faults


class TestFeederFaults:
  """Satellite: truncated/corrupt subreads BAM through create_proc_feeder."""

  def _layout(self):
    return FeatureLayout(max_passes=20, max_length=100, use_ccs_bq=False)

  def test_corrupt_zmw_fail_fast_raises(self, synthetic_bams, inject,
                                        tmp_path):
    subreads, ccs = synthetic_bams()
    bad = _corrupt(inject, subreads, tmp_path)
    feeder, _ = create_proc_feeder(bad, ccs_bam=ccs, layout=self._layout())
    with pytest.raises(KeyError):
      list(feeder())

  def test_corrupt_zmw_skip_policy(self, synthetic_bams, inject, tmp_path):
    subreads, ccs = synthetic_bams()
    bad = _corrupt(inject, subreads, tmp_path)
    quarantine = faults.Quarantine('skip', None)
    feeder, _ = create_proc_feeder(
        bad, ccs_bam=ccs, layout=self._layout(), quarantine=quarantine)
    names = [item[1] for item in feeder()]
    assert CORRUPT_NAME not in names
    assert len(names) == 5
    assert quarantine.counters['n_zmw_skipped_on_error'] == 1
    assert quarantine.counters['n_fault_featurize'] == 1

  def test_corrupt_zmw_ccs_fallback_yields_draft(self, synthetic_bams,
                                                 inject, tmp_path):
    subreads, ccs = synthetic_bams()
    bad = _corrupt(inject, subreads, tmp_path)
    quarantine = faults.Quarantine('ccs-fallback', None)
    feeder, _ = create_proc_feeder(
        bad, ccs_bam=ccs, layout=self._layout(), quarantine=quarantine)
    items = list(feeder())
    fallbacks = [i for i in items if isinstance(i, faults.CcsFallback)]
    assert len(fallbacks) == 1
    fb = fallbacks[0]
    assert fb.molecule_name == CORRUPT_NAME
    # Draft CCS carries the original bases and qualities.
    ccs_rec = next(r for r in bam_lib.BamReader(ccs)
                   if r.qname == CORRUPT_NAME)
    assert fb.sequence == ccs_rec.seq
    np.testing.assert_array_equal(fb.quality_scores, ccs_rec.quals)
    assert quarantine.counters['n_zmw_ccs_fallback'] == 1

  def test_truncated_bam_mid_file_decode_fault(self, synthetic_bams,
                                               inject, tmp_path):
    import shutil

    subreads, ccs = synthetic_bams()
    trunc = str(tmp_path / 'trunc.bam')
    shutil.copy(subreads, trunc)
    inject.truncate_file(trunc, fraction=0.5)
    # Fail-fast: the decode error propagates.
    feeder, _ = create_proc_feeder(trunc, ccs_bam=ccs,
                                   layout=self._layout())
    with pytest.raises(bam_lib.TruncatedBamError):
      list(feeder())
    # Quarantined: the groups before the truncation point still come
    # through, then one decode dead-letter ends the stream.
    quarantine = faults.Quarantine('skip', None)
    feeder2, counter = create_proc_feeder(
        trunc, ccs_bam=ccs, layout=self._layout(), quarantine=quarantine)
    items = list(feeder2())
    assert 0 < len(items) < 6
    assert counter['n_zmw_decode_failed'] == 1
    assert quarantine.counters['n_fault_decode'] == 1


class TestEndToEndQuarantine:
  """Acceptance (a): corrupted ZMW + ccs-fallback completes the run,
  emits the draft CCS, and records one dead-letter entry."""

  @pytest.mark.parametrize('cpus', [1, 2])
  def test_corrupt_zmw_ccs_fallback_run(self, synthetic_bams, inject,
                                        tmp_path, params, cpus):
    subreads, ccs = synthetic_bams()
    bad = _corrupt(inject, subreads, tmp_path)
    out = str(tmp_path / 'out.fastq')
    runner, options = _make_runner(
        params, on_zmw_error='ccs-fallback', cpus=cpus,
        batch_timeout=30.0 if cpus > 1 else 0.0)
    counters = runner_lib.run_inference(bad, ccs, None, out,
                                        options=options, runner=runner)
    assert counters['success'] == 5
    assert counters['n_zmw_ccs_fallback'] == 1
    assert counters['n_fallback_emitted'] == 1
    assert 'partial' not in counters
    names = _fastq_names(out)
    assert CORRUPT_NAME in names and len(names) == 6
    letters = faults.read_dead_letters(out + '.failed.jsonl')
    assert len(letters) == 1
    assert letters[0]['zmw'] == CORRUPT_NAME
    assert letters[0]['stage'] == 'featurize'
    assert letters[0]['action'] == 'ccs-fallback'
    # Atomic output: no tmp/manifest remnants after success.
    assert not os.path.exists(out + '.tmp')
    assert not os.path.exists(out + '.progress.json')

  @pytest.mark.parametrize('cpus', [1, 2])
  def test_corrupt_zmw_skip_run_counters(self, synthetic_bams, inject,
                                         tmp_path, params, cpus):
    subreads, ccs = synthetic_bams()
    bad = _corrupt(inject, subreads, tmp_path)
    out = str(tmp_path / 'out.fastq')
    runner, options = _make_runner(
        params, on_zmw_error='skip', cpus=cpus,
        batch_timeout=30.0 if cpus > 1 else 0.0)
    counters = runner_lib.run_inference(bad, ccs, None, out,
                                        options=options, runner=runner)
    assert counters['success'] == 5
    assert counters['n_zmw_skipped_on_error'] == 1
    assert counters.get('n_fallback_emitted', 0) == 0
    assert CORRUPT_NAME not in _fastq_names(out)

  def test_corrupt_zmw_fail_policy_aborts(self, synthetic_bams, inject,
                                          tmp_path, params):
    subreads, ccs = synthetic_bams()
    bad = _corrupt(inject, subreads, tmp_path)
    out = str(tmp_path / 'out.fastq')
    runner, options = _make_runner(params)  # on_zmw_error='fail'
    with pytest.raises(KeyError):
      runner_lib.run_inference(bad, ccs, None, out,
                               options=options, runner=runner)
    # Crashed run leaves no plausible final output, but does leave a
    # partial-stamped sidecar (satellite: no unconditional sidecars).
    assert not os.path.exists(out)
    sidecar = json.load(open(out + '.inference.json'))
    assert sidecar.get('partial') is True


class TestWatchdog:
  """Acceptance (b): SIGKILLing a pool worker mid-batch triggers the
  watchdog retry and output is byte-identical to an uninterrupted run."""

  @pytest.mark.slow

  def test_sigkilled_worker_retries_byte_identical(
      self, synthetic_bams, inject, tmp_path, params, monkeypatch):
    subreads, ccs = synthetic_bams()
    shm_before = set(glob.glob('/dev/shm/*'))

    ref_out = str(tmp_path / 'ref.fastq')
    runner, options = _make_runner(
        params, cpus=2, batch_timeout=5.0, batch_retries=2,
        on_zmw_error='ccs-fallback')
    runner_lib.run_inference(subreads, ccs, None, ref_out,
                             options=options, runner=runner)

    kill_out = str(tmp_path / 'kill.fastq')
    token = str(tmp_path / 'kill.token')
    monkeypatch.setenv(faults.ENV_KILL_ZMW, CORRUPT_NAME)
    monkeypatch.setenv(faults.ENV_KILL_TOKEN, token)
    runner2, options2 = _make_runner(
        params, cpus=2, batch_timeout=5.0, batch_retries=2,
        on_zmw_error='ccs-fallback')
    counters = runner_lib.run_inference(subreads, ccs, None, kill_out,
                                        options=options2, runner=runner2)
    assert os.path.exists(token), 'kill was never injected'
    assert counters['n_watchdog_timeouts'] >= 1
    assert counters['n_pool_respawns'] >= 1
    assert counters['success'] == 6
    # The retry recovered every ZMW: nothing quarantined, output
    # byte-identical to the uninterrupted run.
    assert counters.get('n_zmw_quarantined', 0) == 0
    with open(ref_out, 'rb') as a, open(kill_out, 'rb') as b:
      assert a.read() == b.read()
    leaked = {
        p for p in set(glob.glob('/dev/shm/*')) - shm_before
        if 'dctpu' in p or 'psm' in p
    }
    assert not leaked, f'leaked shm segments: {leaked}'

  @pytest.mark.slow

  def test_watchdog_exhaustion_quarantines_batch(
      self, synthetic_bams, inject, tmp_path, params, monkeypatch):
    subreads, ccs = synthetic_bams(n_zmws=2)
    out = str(tmp_path / 'out.fastq')
    # No kill token: every attempt re-kills the worker, exhausting the
    # watchdog; ccs-fallback then recovers the whole batch.
    monkeypatch.setenv(faults.ENV_KILL_ZMW, f'{MOVIE}/100/ccs')
    runner, options = _make_runner(
        params, cpus=2, batch_timeout=2.0, batch_retries=1,
        on_zmw_error='ccs-fallback')
    counters = runner_lib.run_inference(subreads, ccs, None, out,
                                        options=options, runner=runner)
    assert counters['n_watchdog_timeouts'] >= 2
    assert counters['n_zmw_quarantined'] == 2
    assert counters['n_fallback_emitted'] == 2
    assert sorted(_fastq_names(out)) == [
        f'{MOVIE}/100/ccs', f'{MOVIE}/101/ccs']


class TestResume:
  """Acceptance (c): interrupt + --resume yields the same ZMW set as an
  uninterrupted run, no duplicates, no leaked shm segments."""

  @pytest.mark.parametrize('suffix', ['fastq', 'bam'])
  def test_crash_and_resume_same_zmw_set(self, synthetic_bams, inject,
                                         tmp_path, params, monkeypatch,
                                         suffix):
    subreads, ccs = synthetic_bams(n_zmws=6)
    shm_before = set(glob.glob('/dev/shm/*'))

    ref_out = str(tmp_path / f'ref.{suffix}')
    runner, options = _make_runner(params)
    runner_lib.run_inference(subreads, ccs, None, ref_out,
                             options=options, runner=runner)

    out = str(tmp_path / f'out.{suffix}')
    monkeypatch.setenv(faults.ENV_CRASH_AFTER_BATCHES, '1')
    runner2, options2 = _make_runner(params)
    with pytest.raises(RuntimeError, match='injected crash'):
      runner_lib.run_inference(subreads, ccs, None, out,
                               options=options2, runner=runner2)
    monkeypatch.delenv(faults.ENV_CRASH_AFTER_BATCHES)
    assert not os.path.exists(out)
    assert os.path.exists(out + '.tmp')
    manifest = json.load(open(out + '.progress.json'))
    assert manifest['groups_done'] == 2
    assert json.load(open(out + '.inference.json')).get('partial') is True

    runner3, options3 = _make_runner(params, resume=True)
    counters = runner_lib.run_inference(subreads, ccs, None, out,
                                        options=options3, runner=runner3)
    assert counters['n_zmw_resume_skipped'] == 2
    assert 'partial' not in counters
    assert not os.path.exists(out + '.progress.json')
    assert not os.path.exists(out + '.tmp')

    if suffix == 'bam':
      ref_names = sorted(r.qname for r in bam_lib.BamReader(ref_out))
      got_names = sorted(r.qname for r in bam_lib.BamReader(out))
    else:
      ref_names = sorted(_fastq_names(ref_out))
      got_names = sorted(_fastq_names(out))
    assert got_names == ref_names
    assert len(got_names) == len(set(got_names)), 'duplicate ZMWs'
    leaked = {
        p for p in set(glob.glob('/dev/shm/*')) - shm_before
        if 'dctpu' in p or 'psm' in p
    }
    assert not leaked, f'leaked shm segments: {leaked}'

  def test_resume_rejects_different_source(self, synthetic_bams, inject,
                                           tmp_path, params, monkeypatch):
    subreads, ccs = synthetic_bams('a')
    other_subreads, other_ccs = synthetic_bams('b', seed=9)
    out = str(tmp_path / 'out.fastq')
    monkeypatch.setenv(faults.ENV_CRASH_AFTER_BATCHES, '1')
    runner, options = _make_runner(params)
    with pytest.raises(RuntimeError):
      runner_lib.run_inference(subreads, ccs, None, out,
                               options=options, runner=runner)
    monkeypatch.delenv(faults.ENV_CRASH_AFTER_BATCHES)
    runner2, options2 = _make_runner(params, resume=True)
    with pytest.raises(ValueError, match='manifest mismatch'):
      runner_lib.run_inference(other_subreads, other_ccs, None, out,
                               options=options2, runner=runner2)


class TestSatellites:

  def test_plain_names_bam_output_omits_zm_tag(self, synthetic_bams,
                                               tmp_path, params):
    """BAM emit must not crash on non-PacBio read names (satellite:
    defensive zm parse)."""
    subreads, ccs = synthetic_bams(plain_names=True)
    out = str(tmp_path / 'out.bam')
    runner, options = _make_runner(params)
    counters = runner_lib.run_inference(subreads, ccs, None, out,
                                        options=options, runner=runner)
    assert counters['success'] == 6
    records = list(bam_lib.BamReader(out))
    assert len(records) == 6
    for rec in records:
      assert not rec.has_tag('zm')
      assert rec.has_tag('rq')

  def test_pacbio_names_bam_output_keeps_zm_tag(self, synthetic_bams,
                                                tmp_path, params):
    subreads, ccs = synthetic_bams()
    out = str(tmp_path / 'out.bam')
    runner, options = _make_runner(params)
    runner_lib.run_inference(subreads, ccs, None, out,
                             options=options, runner=runner)
    zms = sorted(int(r.get_tag('zm')) for r in bam_lib.BamReader(out))
    assert zms == [100, 101, 102, 103, 104, 105]

  def test_cli_flags_plumb_to_options(self, scripts_importable):
    from deepconsensus_tpu import cli

    args = cli.build_parser().parse_args([
        'run', '--subreads_to_ccs', 'a', '--ccs_bam', 'b',
        '--checkpoint', 'c', '--output', 'd',
        '--on_zmw_error', 'ccs-fallback', '--batch_timeout', '12.5',
        '--batch_retries', '4', '--resume',
    ])
    assert args.on_zmw_error == 'ccs-fallback'
    assert args.batch_timeout == 12.5
    assert args.batch_retries == 4
    assert args.resume is True

  def test_classify_error_taxonomy(self):
    assert faults.classify_error('DEADLINE_EXCEEDED: slice') == 'transient'
    assert faults.classify_error('watchdog fired') == 'transient'
    assert faults.classify_error("KeyError: 'pw'") == 'permanent'

  def test_dead_letter_roundtrip(self, tmp_path):
    path = str(tmp_path / 'x.failed.jsonl')
    writer = faults.DeadLetterWriter(path)
    writer.record('z/1/ccs', 'featurize', 'permanent', 'boom', 'skip')
    writer.record(None, 'decode', 'permanent', 'eof', 'skip')
    writer.close()
    entries = faults.read_dead_letters(path)
    assert [e['zmw'] for e in entries] == ['z/1/ccs', None]
    assert entries[0]['action'] == 'skip'
