"""Golden tests for AlignmentLoss/AlignmentMetric against the expected
values enumerated in the reference's losses_and_metrics_test.py."""
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu import constants
from deepconsensus_tpu.models import losses, metrics


def seq_to_array(seq):
  return np.array([constants.SEQ_VOCAB.index(c) for c in seq], np.float32)


def seq_to_one_hot(seq):
  eye = np.eye(len(constants.SEQ_VOCAB), dtype=np.float32)
  return np.stack([eye[constants.SEQ_VOCAB.index(c)] for c in seq])


def convert_seqs(sequences):
  y_true = np.stack([seq_to_array(s) for s in sequences[0]])
  y_pred = np.stack([seq_to_one_hot(s) for s in sequences[1]])
  return jnp.asarray(y_true), jnp.asarray(y_pred)


def test_left_shift_sequence():
  y = jnp.asarray([[0, 1, 0, 2, 3, 0], [4, 0, 0, 3, 0, 0]])
  out = np.asarray(losses.left_shift_sequence(y))
  np.testing.assert_array_equal(out, [[1, 2, 3, 0, 0, 0], [4, 3, 0, 0, 0, 0]])


ALIGNMENT_LOSS_CASES = [
    # (true, pred, del_cost, loss_reg, width, expected)
    ((['TTAGGC', 'AGCTGG'], ['TTAGGC', 'AGCTGG']), 1.0, None, None, 0.0),
    ((['TTAGGC    ', 'AGCTGG    '],
      ['TTAGGC    ', 'AGCTGG    ']), 1.0, None, None, 0.0),
    ((['TTAGGCAT', 'AGCTGG  '],
      ['TTAGGCAT  ', 'AGCTGG    ']), 1.0, None, None, 0.0),
    ((['TTAGGC', 'AGCTGG'], ['T TA G G C', 'AGC    TGG']),
     1.0, None, None, 0.0),
    ((['TTAGGC    ', 'AGCTGG    '],
      ['TTA G GC  ', 'AGC    TGG']), 1.0, None, None, 0.0),
    ((['TTAGGC', 'AGCTGG'], ['TTAGG ', 'GCTGG ']), 1.0, None, None, 1.0),
    ((['TTAGGC', 'AGCTGG'], ['TAGGC ', 'AGCGG ']), 2.0, None, None, 2.0),
    ((['TTAGGC', 'AGCTGG'], ['TTAG  ', 'GCGG  ']), 1.0, None, None, 2.0),
    ((['TTAGGC', 'AGCTGG'], ['ATAGGC', 'TGCTGG']), 1.0, None, None, 16.118),
    ((['TTAGGC', 'AGCTGG'], ['AAAGGC', 'TGCTGC']), 1.0, None, None, 32.236),
    ((['TTAGGC', 'ATCGAC', 'AGCTGG'],
      ['TTAGGCA', 'ATCCGAC', 'CAGCTGG']), 1.0, None, None, 16.118),
    ((['ATCG ', 'ATCG '], ['TCG  ', 'TCG  ']), 1.0, None, None, 1.0),
    ((['ATCG ', 'ATCG '], ['TCG  ', 'TCG  ']), 1e9, None, None, 64.472),
    # Banded cases.
    ((['TTAGGC', 'AGCTGG'], ['TTAGGC', 'AGCTGG']), 1.0, None, 2, 0.0),
    ((['TTAGGC', 'AGCTGG'], ['TTAGG ', 'GCTGG ']), 1.0, None, 2, 1.0),
    ((['TTAGGC    ', 'AGCTGG    '],
      ['TTAGGC    ', 'AGCTGG    ']), 1.0, None, 1, 0.0),
    ((['TTAGGC   ', 'AGCTG   G'], ['T TAG G C', 'AGC   TGG']),
     1.0, None, 8, 0.0),
    ((['TTAGGC    ', 'AGCTGG    '],
      ['TTA G GC  ', 'AGC    TGG']), 1.0, None, 8, 0.0),
    ((['TTAGGC', 'AGCTGG'], ['AAAGGC', 'TGCTGC']), 1.0, None, 4, 32.236),
    ((['TTA', 'GGC'], ['A  ', 'C  ']), 1.0, None, 2, 2.0),
    ((['TTA', 'GGC'], ['A  ', 'C  ']), 1.0, None, 1, 18.118),
]


@pytest.mark.parametrize('use_pallas', [False, True])
@pytest.mark.parametrize(
    'sequences,del_cost,loss_reg,width,expected', ALIGNMENT_LOSS_CASES
)
def test_alignment_loss(sequences, del_cost, loss_reg, width, expected,
                        use_pallas):
  y_true, y_pred = convert_seqs(sequences)
  loss = losses.AlignmentLoss(
      del_cost=del_cost, loss_reg=loss_reg, width=width,
      use_pallas=use_pallas,
  )
  got = float(loss(y_true, y_pred))
  assert got == pytest.approx(expected, abs=2e-2)


def test_soft_alignment_close_to_hard_for_small_reg():
  sequences = (['TTAGGC', 'AGCTGG'], ['TTAGG ', 'GCTGG '])
  y_true, y_pred = convert_seqs(sequences)
  hard = float(losses.AlignmentLoss(del_cost=1.0, loss_reg=None)(
      y_true, y_pred))
  soft = float(losses.AlignmentLoss(del_cost=1.0, loss_reg=0.01)(
      y_true, y_pred))
  assert soft == pytest.approx(hard, abs=0.1)


def test_alignment_loss_differentiable():
  import jax
  sequences = (['TTAGGC'], ['TTAGG '])
  y_true, y_pred = convert_seqs(sequences)
  # Soften the one-hot so probabilities sit inside the clip range
  # (exact one-hots have zero gradient at the clip boundaries).
  y_pred = y_pred * 0.9 + 0.02
  loss = losses.AlignmentLoss(del_cost=10.0, loss_reg=0.1)
  grad = jax.grad(lambda p: loss(y_true, p))(y_pred)
  assert np.isfinite(np.asarray(grad)).all()
  assert np.abs(np.asarray(grad)).sum() > 0


ALIGNMENT_METRIC_CASES = [
    ((['TTAGGC', 'AGCTGG'], ['TTAGGC', 'AGCTGG']), (1.0, 1.0)),
    ((['TTAGGC', 'AGCTGG'], ['AAAGGC', 'TGCTGC']), (0.667, 0.667)),
    ((['TTAGGC', 'AGCTGG'], ['T TA G G C', 'AGC    TGG']), (1.0, 1.0)),
    ((['TTAGGC', 'AGCTGG'], ['TTAGG ', 'GCTGG ']), (0.833, 0.833)),
    ((['TTAGGC', 'ATCGAC', 'AGCTGG'],
      ['TTAGGCA', 'ATCCGAC', 'CAGCTGG']), (0.857, 0.857, 0.857)),
    ((['ATCG ', 'ATCG '], ['TCG  ', 'TCG  ']), (0.75, 0.75)),
    ((['ATCG ', 'ATCG '], ['     ', '     ']), (0.0, 0.0)),
    ((['     ', '     '], ['ATCG ', 'ATCG ']), (0.0, 0.0)),
    ((['A    ', 'T    '], ['     ', '     ']), (0.0, 0.0)),
    ((['     ', '     '], ['A    ', 'T    ']), (0.0, 0.0)),
    ((['     ', '     '], ['     ', '     ']), (1.0, 1.0)),
]


@pytest.mark.parametrize('sequences,expected_pid', ALIGNMENT_METRIC_CASES)
def test_alignment_metric_pid(sequences, expected_pid):
  y_true, y_pred = convert_seqs(sequences)
  metric = metrics.AlignmentMetric()
  _, _, mv = metric.alignment(y_true, y_pred)
  pid = np.asarray(mv['pid'])
  for i, want in enumerate(expected_pid):
    assert pid[i] == pytest.approx(want, abs=2e-3), (i, pid)


def test_per_example_accuracy():
  y_true = jnp.asarray(np.stack([
      seq_to_array('A T C G'),
      seq_to_array('T T T T'),
      seq_to_array('A A A A'),
  ]))
  y_pred = jnp.asarray(np.stack([
      seq_to_one_hot('   ATCG'),
      seq_to_one_hot('   GGGG'),
      seq_to_one_hot('   AAAA'),
  ]))
  correct, total = metrics.per_example_accuracy_counts(y_true, y_pred)
  assert int(correct) == 2 and int(total) == 3


def test_per_class_accuracy():
  # y_true = A A T gap; prediction decodes to A A T G.
  y_true = jnp.asarray([[1, 1, 2, 0]], dtype=jnp.float32)
  y_pred = jnp.asarray([seq_to_one_hot('AATG')])
  correct, total = metrics.per_class_accuracy_counts(y_true, y_pred, 1)
  assert int(correct) == 2 and int(total) == 2
  correct, total = metrics.per_class_accuracy_counts(y_true, y_pred, 2)
  assert int(correct) == 1 and int(total) == 1
  correct, total = metrics.per_class_accuracy_counts(y_true, y_pred, 0)
  assert int(correct) == 0 and int(total) == 1


def test_batch_identity_and_yield():
  sequences = (['TTAGGC', 'AGCTGG'], ['TTAGGC', 'AGCTGG'])
  y_true, y_pred = convert_seqs(sequences)
  ccs = y_true
  metric = metrics.AlignmentMetric()
  id_ccs, id_pred = metrics.batch_identity_ccs_pred(
      ccs, y_pred, y_true, metric
  )
  assert float(id_ccs) == pytest.approx(1.0)
  assert float(id_pred) == pytest.approx(1.0)
  y = metrics.YieldOverCCS()
  y.update(float(id_ccs), float(id_pred))
  assert y.result() == 1.0


def test_distillation_loss_zero_for_identical():
  logits = jnp.asarray(np.random.default_rng(0).normal(size=(2, 10, 5)),
                       dtype=jnp.float32)
  assert float(losses.distillation_loss(logits, logits)) == 0.0
  other = logits + 1.0  # softmax-invariant shift -> still zero
  assert float(losses.distillation_loss(logits, other)) == pytest.approx(
      0.0, abs=1e-6)


def test_xentropy_subs_cost_pointwise():
  """Pairwise substitution costs equal naive per-(i,j) cross-entropy
  (reference: losses_and_metrics_test XentropySubsCostFn, incl. the
  unequal-length case)."""
  rng = np.random.default_rng(0)
  b, m, n, vocab = 2, 4, 6, 5
  y_true = jnp.asarray(rng.integers(1, vocab, size=(b, m)), jnp.int32)
  y_pred = rng.uniform(size=(b, n, vocab)).astype(np.float32)
  y_pred /= y_pred.sum(-1, keepdims=True)
  got = np.asarray(losses.xentropy_subs_cost(y_true, jnp.asarray(y_pred)))
  assert got.shape == (b, m, n)
  for bi in range(b):
    for i in range(m):
      for j in range(n):
        want = -np.log(y_pred[bi, j, int(y_true[bi, i])])
        np.testing.assert_allclose(got[bi, i, j], want, rtol=1e-5)


def test_xentropy_ins_cost_pointwise():
  rng = np.random.default_rng(1)
  b, n, vocab = 3, 5, 5
  y_pred = rng.uniform(size=(b, n, vocab)).astype(np.float32)
  y_pred /= y_pred.sum(-1, keepdims=True)
  got = np.asarray(losses.xentropy_ins_cost(jnp.asarray(y_pred)))
  want = -np.log(y_pred[..., constants.GAP_INT])
  np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.parametrize(
    'threshold,ids_dc,ids_ccs,exp_over_ccs',
    [
        # (reference losses_and_metrics_test YieldOverCCSMetricTest)
        (0.99, [1.0, 1.0], [1.0, 1.0], [1.0, 1.0]),
        (0.99, [0.9, 1.0], [1.0, 1.0], [0.0, 0.5]),
        (0.99, [1.0, 1.0], [0.9, 1.0], [0.0, 2.0]),
        (0.99, [1.0, 1.0], [1.0, 0.9], [1.0, 2.0]),
        (0.9, [0.9, 1.0], [1.0, 1.0], [1.0, 1.0]),
    ],
)
def test_yield_over_ccs_multiple_updates(threshold, ids_dc, ids_ccs,
                                         exp_over_ccs):
  y = metrics.YieldOverCCS(quality_threshold=threshold)
  for dc, ccs, want in zip(ids_dc, ids_ccs, exp_over_ccs):
    y.update(ccs, dc)
    assert y.result() == pytest.approx(want)
