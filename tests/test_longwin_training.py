"""Bucketed multi-width training and the L=500 long-insert path.

Covers the training side of the window-bucket system (the inference
side lives in test_ragged_engine.py / test_inference_buckets.py):

* triage + per-bucket batches in both loaders (DatasetIterator epochs
  and the StreamingDataset reservoir), including the padding counters
  and the starvation-promotion flush,
* compile-once-per-bucket: over a mixed-width stream the jitted train
  step traces exactly len(window_buckets) times (no mid-run
  recompiles),
* dp8-vs-dp1 loss-curve identity for a two-bucket config at equal
  global batch (the test_train_parallel.py contract, bucketed),
* the blockwise ring-attention forward for windows past the fused
  kernel's VMEM limit: numerical parity with full_attention_reference
  at L=500 (forward AND gradients), and proof that a long-window
  training forward routes through it,
* the overflow-width quarantine (--on_shard_error=skip +
  n_width_rejected) vs the typed WindowBucketError under 'fail'.

The @slow drills (an L=500 run_training cycle, the L=100/200 flywheel
producing a servable artifact, the student-vs-baseline identity
record) run under `./run_all_tests.sh longwin`.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import data as data_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.models import train as train_lib
from deepconsensus_tpu.parallel import mesh as mesh_lib
from deepconsensus_tpu.parallel import ring_attention as ring_lib

pytestmark = [pytest.mark.multichip]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
  sys.path.insert(0, _REPO_ROOT)

MAX_PASSES = 5
GLOBAL_BATCH = 16
N_PER_WIDTH = 48  # 3 batches per bucket at the fixed global batch


@pytest.fixture(scope='module')
def mixed_shards(tmp_path_factory):
  """Two widths, 20 and 40: separate shard sets so tests can stream
  either width alone or both together."""
  from scripts import inject_faults

  d = tmp_path_factory.mktemp('mixed_shards')
  w20 = inject_faults.write_synthetic_tfrecords(
      str(d / 'w20'), n_shards=2, n_examples=N_PER_WIDTH,
      max_passes=MAX_PASSES, max_length=20)
  w40 = inject_faults.write_synthetic_tfrecords(
      str(d / 'w40'), n_shards=2, n_examples=N_PER_WIDTH,
      max_passes=MAX_PASSES, max_length=40, seed=5)
  return w20, w40


def bucketed_params(max_length=20, **overrides):
  """Tiny transformer (the length-agnostic family buckets require)."""
  params = config_lib.get_config('transformer_learn_values+test')
  with params.unlocked():
    params.max_passes = MAX_PASSES
    params.max_length = max_length
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.batch_size = GLOBAL_BATCH
    params.num_hidden_layers = 1
    params.filter_size = 32
    params.warmup_steps = 2
    params.log_every_n_steps = 1
    params.seed = 7
    params.window_buckets = (max_length, 2 * max_length)
    for k, v in overrides.items():
      setattr(params, k, v)
  return params


def run_bucketed_training(mixed_shards, out_dir, dp, **overrides):
  w20, w40 = mixed_shards
  params = bucketed_params(**overrides)
  mesh = mesh_lib.make_mesh(dp=dp, tp=1, devices=jax.devices()[:dp])
  train_lib.run_training(
      params=params, out_dir=out_dir,
      train_patterns=list(w20) + list(w40), eval_patterns=list(w20),
      num_epochs=1, mesh=mesh, eval_every=1_000_000,
  )
  return out_dir


def metrics_entries(out_dir, split=None):
  entries = []
  with open(os.path.join(out_dir, 'metrics.jsonl')) as f:
    for line in f:
      e = json.loads(line)
      if split is None or e.get('split') == split:
        entries.append(e)
  return entries


def train_losses(out_dir):
  return [e['loss'] for e in metrics_entries(out_dir, 'train')]


def curve_digest(losses, decimals):
  """The quantized curve digest bench_train_scaling.py reports per dp
  point (same construction as test_train_parallel.py's
  curve_digest_1e4, with the quantization step explicit)."""
  import hashlib

  return hashlib.sha256(
      json.dumps([round(l, decimals) for l in losses]).encode()
  ).hexdigest()[:16]


@pytest.fixture(scope='module')
def dp1_run(mixed_shards, tmp_path_factory):
  out = str(tmp_path_factory.mktemp('buck_dp1') / 'run')
  return run_bucketed_training(mixed_shards, out, dp=1)


# ----------------------------------------------------------------------
# Loaders


def test_dataset_iterator_groups_by_bucket(mixed_shards):
  w20, w40 = mixed_shards
  params = bucketed_params()
  ds = data_lib.DatasetIterator(
      patterns=list(w20) + list(w40), params=params,
      batch_size=GLOBAL_BATCH, seed=3)
  assert ds.window_buckets_present == (20, 40)
  assert len(ds) == 2 * N_PER_WIDTH
  widths_seen = set()
  for batch in ds.epoch():
    width = batch['rows'].shape[2]
    widths_seen.add(width)
    # Width-pure batches: label length matches the bucket geometry.
    assert batch['label'].shape == (GLOBAL_BATCH, width)
  assert widths_seen == {20, 40}
  assert ds.counters['n_train_batches_by_bucket_20'] == 3
  assert ds.counters['n_train_batches_by_bucket_40'] == 3
  # On-bucket corpus: no padding burned.
  assert ds.counters['n_train_padded_positions'] == 0
  assert ds.counters['n_train_window_positions'] == (
      3 * GLOBAL_BATCH * 20 + 3 * GLOBAL_BATCH * 40)


def test_narrow_windows_pad_into_their_bucket(mixed_shards):
  """A width-20 window under buckets (40,) pads to 40 (zero label/rows
  in the tail, which AlignmentLoss ignores as gap) and the padding
  counters record the burn."""
  w20, _ = mixed_shards
  params = bucketed_params(max_length=40, window_buckets=(40,))
  ds = data_lib.DatasetIterator(
      patterns=list(w20), params=params, batch_size=8, seed=3)
  batch = next(iter(ds.epoch()))
  assert batch['rows'].shape[2] == 40
  assert batch['label'].shape == (8, 40)
  np.testing.assert_array_equal(batch['rows'][:, :, 20:, :], 0)
  np.testing.assert_array_equal(batch['label'][:, 20:], 0)
  assert ds.counters['n_train_padded_positions'] == 8 * 20
  assert ds.counters['n_train_window_positions'] == 8 * 40


def test_streaming_overflow_fail_names_window(mixed_shards):
  """Under the default policy an overflow width is a typed fault."""
  _, w40 = mixed_shards
  params = bucketed_params(window_buckets=(20,))
  ds = data_lib.StreamingDataset(
      patterns=list(w40), params=params, batch_size=4, buffer_size=8,
      on_shard_error='fail')
  with pytest.raises(faults_lib.WindowBucketError) as ei:
    next(iter(ds))
  msg = str(ei.value)
  assert 'width 40' in msg and 'on_shard_error=skip' in msg


def test_streaming_overflow_skip_quarantines(mixed_shards):
  """--on_shard_error=skip quarantines overflow widths (counted as
  n_width_rejected) and keeps emitting on-bucket batches."""
  w20, w40 = mixed_shards
  params = bucketed_params(window_buckets=(20,))
  ds = data_lib.StreamingDataset(
      patterns=list(w20) + list(w40), params=params, batch_size=4,
      buffer_size=8, on_shard_error='skip')
  it = iter(ds)
  # Enough batches to consume more than one full shard cycle
  # (96 on-bucket + 48 overflow windows), so the overflow shards are
  # guaranteed to have streamed past the triage.
  for _ in range(30):
    batch = next(it)
    assert batch['rows'].shape[2] == 20
  it.close()
  assert ds.counters['n_width_rejected'] > 0
  assert ds.counters['n_train_batches_by_bucket_20'] == 30


def test_streaming_starvation_flush_promotes_narrow_windows(tmp_path):
  """A rare wide width never fills a batch on its own: after
  bucket_starvation_batches clock ticks the starved bucket flushes by
  promoting narrow windows (padded up), so wide windows don't go
  stale and every batch still carries batch_size real windows."""
  from scripts import inject_faults

  many = inject_faults.write_synthetic_tfrecords(
      str(tmp_path / 'w20'), n_shards=1, n_examples=64,
      max_passes=MAX_PASSES, max_length=20)
  rare = inject_faults.write_synthetic_tfrecords(
      str(tmp_path / 'w40'), n_shards=1, n_examples=2,
      max_passes=MAX_PASSES, max_length=40, seed=5)
  params = bucketed_params()
  with params.unlocked():
    params.bucket_starvation_batches = 2
  ds = data_lib.StreamingDataset(
      patterns=list(many) + list(rare), params=params, batch_size=8,
      buffer_size=16, on_shard_error='fail')
  it = iter(ds)
  widths = [next(it)['rows'].shape[2] for _ in range(12)]
  it.close()
  assert 40 in widths, widths
  assert ds.counters['n_train_starvation_flushes'] > 0
  assert ds.counters['n_train_promoted_windows'] > 0
  # Promoted (width-20) windows padded into the 40 bucket.
  assert ds.counters['n_train_padded_positions'] > 0


# ----------------------------------------------------------------------
# Compile-once + cross-dp identity


def test_bucketed_training_compiles_once_per_bucket(dp1_run):
  faults = metrics_entries(dp1_run, 'faults')[-1]
  assert faults['n_train_forward_shapes'] == 2.0
  assert faults['n_train_batches_by_bucket_20'] == 3
  assert faults['n_train_batches_by_bucket_40'] == 3
  # On-bucket synthetic corpus: the padding fraction is exactly zero.
  assert faults['train_padding_fraction'] == 0.0
  # Six optimizer steps landed (3 per bucket).
  assert len(train_losses(dp1_run)) == 6


def test_bucketed_dp8_matches_dp1(mixed_shards, dp1_run, tmp_path):
  """Equal global batch + seed: the bucketed batch schedule is host-
  side and mesh-independent, so dp=8 consumes the identical per-bucket
  batch sequence and the loss curves agree to all-reduce reduction
  order (same contract as the fixed-shape test, see
  test_train_parallel.py module docstring)."""
  dp8 = run_bucketed_training(
      mixed_shards, str(tmp_path / 'dp8'), dp=8)
  losses1 = train_losses(dp1_run)
  losses8 = train_losses(dp8)
  assert len(losses1) == len(losses8) == 6
  np.testing.assert_allclose(losses1, losses8, rtol=1e-4)
  # The two-bucket curve's losses are O(100), so the 1e-4 ABSOLUTE
  # quantization of curve_digest_1e4 is finer than the ~1e-7-relative
  # all-reduce reduction-order noise (measured: <= 1.4e-7 rel);
  # digest at 1e-3 where the quantization cell is safely wider.
  assert curve_digest(losses1, 3) == curve_digest(losses8, 3)
  faults8 = metrics_entries(dp8, 'faults')[-1]
  assert faults8['n_train_forward_shapes'] == 2.0


# ----------------------------------------------------------------------
# The L=500 long-insert forward: blockwise ring attention


def make_qkv(b, l, h, d, seed=0):
  rng = np.random.default_rng(seed)
  mk = lambda: jnp.asarray(rng.normal(size=(b, l, h, d)).astype(np.float32))
  return mk(), mk(), mk()


def test_blockwise_ring_matches_reference_l500():
  """Forward parity at the long-insert width. Measured max abs error
  on CPU f32 is ~5e-7 (one extra renormalization per 128-block);
  atol=1e-5 matches the sharded ring-attention tests' tolerance."""
  q, k, v = make_qkv(2, 500, 2, 8, seed=0)
  want = ring_lib.full_attention_reference(q, k, v, attn_win_size=12)
  got = ring_lib.ring_attention_blockwise(q, k, v, attn_win_size=12)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                             atol=1e-5)


def test_blockwise_ring_grads_match_reference_l500():
  """Gradient parity: the blockwise scan is plain differentiable ops
  (no custom VJP), so training can backprop through it. Measured max
  abs grad error ~8e-7 on CPU f32; atol=1e-5."""
  q, k, v = make_qkv(2, 500, 2, 8, seed=1)

  def loss(attn):
    def f(q, k, v):
      o = attn(q, k, v, 12)
      return jnp.sum(o * jnp.cos(o))
    return f

  g_ref = jax.grad(loss(ring_lib.full_attention_reference),
                   argnums=(0, 1, 2))(q, k, v)
  g_blk = jax.grad(loss(ring_lib.ring_attention_blockwise),
                   argnums=(0, 1, 2))(q, k, v)
  for a, b in zip(g_ref, g_blk):
    np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-5)


def test_l500_training_forward_routes_through_ring(monkeypatch):
  """A train-mode forward at the long-insert width goes through the
  blockwise ring scan (trace counter moves), produces the same values
  as the XLA einsum path, and backprops to finite grads. The fused
  Pallas hot path is structurally unreachable here: it requires
  eval-mode (not train) AND L <= its VMEM window limit (128)."""
  params = config_lib.get_config('transformer_learn_values+test')
  with params.unlocked():
    params.max_passes = MAX_PASSES
    params.max_length = config_lib.LONG_INSERT_WINDOW_LEN
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = 1
    params.filter_size = 32
    params.attention_dropout = 0.0  # ring precondition (no weights)
  model = model_lib.get_model(params)
  rows = jnp.zeros(
      (2, params.total_rows, config_lib.LONG_INSERT_WINDOW_LEN, 1))
  variables = model.init(jax.random.PRNGKey(0), rows)
  rngs = {'dropout': jax.random.PRNGKey(1)}

  before = ring_lib.n_blockwise_traces
  out_ring = model.apply(variables, rows, train=True, rngs=rngs)
  assert ring_lib.n_blockwise_traces == before + 1
  assert out_ring.shape == (2, config_lib.LONG_INSERT_WINDOW_LEN, 5)

  # Same params, ring crossover pushed out of reach -> XLA einsum path;
  # values must agree (exact attention either way).
  monkeypatch.setattr(config_lib, 'RING_ATTENTION_MIN_LEN', 10**9)
  out_xla = model.apply(variables, rows, train=True, rngs=rngs)
  monkeypatch.undo()
  np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_xla),
                             atol=1e-4)

  def train_loss(p):
    o = model.apply({'params': p['params']}, rows, train=True, rngs=rngs)
    return jnp.sum(o * o)

  grads = jax.grad(train_loss)(variables)
  flat = jax.tree_util.tree_leaves(grads)
  assert all(bool(jnp.all(jnp.isfinite(g))) for g in flat)


# ----------------------------------------------------------------------
# @slow end-to-end drills (./run_all_tests.sh longwin)


@pytest.mark.slow
def test_l500_run_training_uses_ring_and_reports_identity(
    tmp_path_factory):
  """An L=500 config trains end to end: the sidecar proves the forward
  traced through the blockwise ring scan (n_ring_attention_traces) and
  the final eval reports alignment-identity metrics for the long
  windows."""
  from scripts import inject_faults

  d = tmp_path_factory.mktemp('l500')
  shards = inject_faults.write_synthetic_tfrecords(
      str(d / 'shards'), n_shards=1, n_examples=8,
      max_passes=MAX_PASSES, max_length=500)
  params = bucketed_params(
      max_length=500, window_buckets=(500,), batch_size=4,
      attention_dropout=0.0)  # ring precondition: no attn dropout
  mesh = mesh_lib.make_mesh(dp=1, tp=1, devices=jax.devices()[:1])
  out = str(d / 'out')
  train_lib.run_training(
      params=params, out_dir=out, train_patterns=list(shards),
      eval_patterns=list(shards), num_epochs=1, mesh=mesh)
  faults = metrics_entries(out, 'faults')[-1]
  assert faults.get('n_ring_attention_traces', 0) >= 1
  assert faults['n_train_forward_shapes'] == 1.0
  evals = metrics_entries(out, 'eval')
  assert evals and 'eval/identity_pred' in evals[-1]
  assert np.isfinite(evals[-1]['eval/identity_pred'])


@pytest.mark.slow
def test_long_insert_identity_record_vs_baseline(mixed_shards, dp1_run,
                                                 tmp_path):
  """The flywheel's informational gate record: student identity vs a
  reference checkpoint on the same shards, and the typed-error branch
  when the baseline cannot consume the long windows."""
  from deepconsensus_tpu.models import checkpoints as checkpoints_lib
  from deepconsensus_tpu.models import flywheel as flywheel_lib

  w20, w40 = mixed_shards
  ckpt = checkpoints_lib.latest_valid_checkpoint(
      os.path.join(dp1_run, 'checkpoints'))
  assert ckpt is not None
  student_params = config_lib.read_params_from_json(ckpt)
  config_lib.finalize_params(student_params, is_training=False)
  variables = {'params': checkpoints_lib.load_params(ckpt)}

  # Baseline == the same checkpoint: both sides evaluate, delta == 0.
  rec = flywheel_lib.long_insert_identity_record(
      student_params, variables, ckpt, list(w20), str(tmp_path / 'a'))
  assert rec['name'] == 'long_insert_identity_vs_baseline'
  assert rec['passed'] is True
  assert rec['measured'] == pytest.approx(0.0, abs=1e-9)
  assert rec['detail']['student_identity'] == (
      rec['detail']['baseline_identity'])

  # A baseline that cannot be evaluated (missing, or its buckets don't
  # cover the long windows) records the error instead of aborting the
  # flywheel cycle: the record is informational, never a veto.
  rec2 = flywheel_lib.long_insert_identity_record(
      student_params, variables, str(tmp_path / 'missing_ckpt'),
      list(w40), str(tmp_path / 'b'))
  assert rec2['passed'] is True
  assert rec2['measured'] is None
  assert 'baseline_error' in rec2['detail']
  assert 'student_identity' in rec2['detail']


@pytest.mark.slow
def test_flywheel_bucketed_long_windows_exports_artifact(
    tmp_path_factory):
  """`dctpu flywheel --window_buckets 100,200` on mixed L=100/L=200
  shards: train -> distill -> gates -> export completes and the
  artifact serves. The distill stage IS the 'real L>100 config'
  acceptance run, at CI scale."""
  from scripts import inject_faults

  d = tmp_path_factory.mktemp('fw_longwin')
  inject_faults.write_synthetic_tfrecords(
      str(d / 'shards'), n_shards=1, n_examples=16,
      max_passes=MAX_PASSES, max_length=100)
  inject_faults.write_synthetic_tfrecords(
      str(d / 'shards2'), n_shards=1, n_examples=16,
      max_passes=MAX_PASSES, max_length=200, seed=5)
  glob_all = [os.path.join(str(d / 'shards'), 'shard-*'),
              os.path.join(str(d / 'shards2'), 'shard-*')]
  out = str(d / 'fw')
  sets = []
  for flag in ('--set', '--student_set'):
    sets += [flag, f'max_passes={MAX_PASSES}', flag, 'max_length=100',
             flag, 'num_hidden_layers=1', flag, 'filter_size=32']
  env = dict(os.environ, JAX_PLATFORMS='cpu', PYTHONPATH=_REPO_ROOT,
             XLA_FLAGS='--xla_force_host_platform_device_count=1')
  result = subprocess.run(
      [sys.executable, '-m', 'deepconsensus_tpu.cli', 'flywheel',
       '--out_dir', out, '--train_path', *glob_all,
       '--eval_path', glob_all[0],
       '--batch_size', '8', '--num_epochs', '1',
       '--export_batch_size', '8', '--window_buckets', '100,200',
       *sets],
      env=env, cwd=_REPO_ROOT, capture_output=True, text=True,
      timeout=1200)
  assert result.returncode == 0, result.stderr[-4000:]
  manifest = json.load(
      open(os.path.join(out, 'flywheel_manifest.json')))
  assert manifest['stages']['export']['artifact']
  # Both training stages consumed both widths with one trace each.
  for stage_dir in ('teacher', 'student'):
    faults = metrics_entries(os.path.join(out, stage_dir), 'faults')[-1]
    assert faults['n_train_forward_shapes'] == 2.0
    assert faults['n_train_batches_by_bucket_100'] >= 1
    assert faults['n_train_batches_by_bucket_200'] >= 1
  # The artifact serves the export geometry.
  from deepconsensus_tpu.inference import runner as runner_lib

  rows = np.random.RandomState(0).uniform(
      0.0, 10.0, size=(8, 4 * MAX_PASSES + 5, 100, 1)).astype(np.float32)
  # The manifest records the artifact FILE; from_exported loads the
  # containing export directory.
  runner = runner_lib.ModelRunner.from_exported(
      os.path.dirname(manifest['stages']['export']['artifact']),
      runner_lib.InferenceOptions(batch_size=8))
  ids, quals = runner.predict(rows)
  assert np.asarray(ids).shape[0] == 8
