"""Golden parity: our preprocess vs the reference's bundled TFRecords.

The reference testdata summary records the exact flags used to produce
the bundled shards (ins_trim=5, max_passes=20, max_length=100), so a
byte-exact comparison validates the whole preprocessing stack: BAM
parsing, insertion trimming, alignment expansion, multi-read spacing,
label handling, windowing, and feature assembly.
"""
import collections

import numpy as np
import pytest

from deepconsensus_tpu.io import tfrecord
from deepconsensus_tpu.io.example_proto import Example
from deepconsensus_tpu.preprocess import (
    FeatureLayout,
    create_proc_feeder,
    reads_to_pileup,
)


def _load_reference(testdata_dir, subdir):
  ref = {}
  split_of = {}
  for split in ('train', 'eval', 'test'):
    pattern = str(
        testdata_dir / f'human_1m/{subdir}/{split}/{split}.tfrecord.gz'
    )
    for raw in tfrecord.read_tfrecords(pattern):
      ex = Example.parse(raw)
      key = (ex['name'][0].decode(), ex['window_pos'][0])
      ref[key] = ex
      split_of[key] = split
  return ref, split_of


def _run_ours(testdata_dir, use_ccs_bq):
  td = str(testdata_dir / 'human_1m')
  layout = FeatureLayout(max_passes=20, max_length=100, use_ccs_bq=use_ccs_bq)
  feeder, counter = create_proc_feeder(
      subreads_to_ccs=f'{td}/subreads_to_ccs.bam',
      ccs_bam=f'{td}/ccs.bam',
      layout=layout,
      ins_trim=5,
      truth_bed=f'{td}/truth.bed',
      truth_to_ccs=f'{td}/truth_to_ccs.bam',
      truth_split=f'{td}/truth_split.tsv',
  )
  ours = {}
  split_of = {}
  agg = collections.Counter()
  for subreads, name, lay, split, ww in feeder():
    pileup = reads_to_pileup(subreads, name, lay, ww)
    for window in pileup.iter_windows():
      key = (window.name, window.ccs.ccs_bounds.start)
      ours[key] = window.to_example()
      split_of[key] = split
    agg.update(pileup.counter)
  return ours, split_of, counter, agg


@pytest.mark.parametrize('use_ccs_bq,subdir', [
    (False, 'tf_examples'),
    (True, 'tf_examples_bq'),
])
def test_byte_exact_examples(testdata_dir, use_ccs_bq, subdir):
  ref, ref_split = _load_reference(testdata_dir, subdir)
  ours, our_split, counter, agg = _run_ours(testdata_dir, use_ccs_bq)

  assert set(ref) == set(ours)
  assert len(ref) == 1507
  for key in ref:
    r, o = ref[key], ours[key]
    assert ref_split[key] == our_split[key]
    assert r['subreads/encoded'][0] == o['subreads/encoded'][0], key
    assert r['subreads/shape'] == o['subreads/shape'], key
    assert r['subreads/num_passes'] == o['subreads/num_passes'], key
    assert r['ccs_base_quality_scores'] == o['ccs_base_quality_scores'], key
    assert r.get('label/encoded') == o.get('label/encoded'), key
    assert r.get('label/shape') == o.get('label/shape'), key


def test_counters_match_reference_summary(testdata_dir):
  # Values from testdata/human_1m/tf_examples/summary/summary.training.json.
  _, _, counter, agg = _run_ours(testdata_dir, use_ccs_bq=False)
  assert counter['n_zmw_processed'] == 10
  assert counter['zmw_total_bp'] == 1116014
  assert counter['zmw_trimmed_insertions'] == 790
  assert counter['zmw_trimmed_insertions_bp'] == 9421
  assert counter['n_zmw_train'] == 7
  assert counter['n_zmw_eval'] == 1
  assert counter['n_zmw_test'] == 1
  assert counter['n_zmw_missing_truth_range'] == 1
  assert counter['n_zmw_pass'] == 9
  assert agg['example_width_bucket_100'] == 1551
  assert agg['n_examples_skip_large_windows_keep'] == 1507
  assert agg['n_examples_adjusted_label'] == 305
  assert agg['n_examples_label_overflow'] == 44
