import struct

import numpy as np
import pytest

from deepconsensus_tpu import constants
from deepconsensus_tpu.faults import CorruptInputError
from deepconsensus_tpu.io import bam
from deepconsensus_tpu.io.bam_writer import BamWriter, BgzfWriter


def test_read_subreads_bam(testdata_dir):
  path = str(testdata_dir / 'human_1m/subreads_to_ccs.bam')
  reader = bam.BamReader(path)
  assert reader.references  # one ccs reference per ZMW
  records = []
  for i, rec in enumerate(reader):
    records.append(rec)
    if i >= 9:
      break
  first = records[0]
  assert first.qname
  assert set(first.seq) <= set('ACGTN')
  assert first.has_tag('zm')
  assert first.has_tag('pw') and first.has_tag('ip') and first.has_tag('sn')
  pw = first.get_tag('pw')
  assert isinstance(pw, np.ndarray)
  assert len(pw) == len(first.seq)
  sn = first.get_tag('sn')
  assert len(sn) == 4 and sn.dtype == np.float32


def test_ccs_bam_has_quals_and_aux(testdata_dir):
  path = str(testdata_dir / 'human_1m/ccs.bam')
  rec = next(iter(bam.BamReader(path)))
  assert rec.qname.endswith('/ccs')
  assert rec.quals is not None
  assert rec.quals.min() >= 0
  assert 'np' in rec.tags and 'rq' in rec.tags


def test_subread_grouper_groups_by_zmw(testdata_dir):
  path = str(testdata_dir / 'human_1m/subreads_to_ccs.bam')
  groups = list(bam.SubreadGrouper(path))
  assert len(groups) == 10  # n_zmw_processed in the bundled summary
  for group in groups:
    zmws = {int(r.get_tag('zm')) for r in group}
    assert len(zmws) == 1
    assert all(not r.is_unmapped for r in group)


def test_aligned_index_arrays_consistency(testdata_dir):
  path = str(testdata_dir / 'human_1m/subreads_to_ccs.bam')
  for i, rec in enumerate(bam.BamReader(path)):
    read_idx, ref_idx = rec.aligned_index_arrays()
    # Every base of seq appears exactly once in query-consuming columns.
    n_query = (read_idx >= 0).sum()
    assert n_query == len(rec.seq)
    covered = read_idx[read_idx >= 0]
    np.testing.assert_array_equal(covered, np.arange(len(rec.seq)))
    # Reference columns are increasing, starting at pos.
    refs = ref_idx[ref_idx >= 0]
    if len(refs):
      assert refs[0] == rec.pos
      assert np.all(np.diff(refs) == 1)
    # Expanded cigar length matches the number of columns.
    assert len(rec.expanded_cigar()) == len(read_idx)
    if i >= 20:
      break


def test_read_truth_bam_by_name(testdata_dir):
  path = str(testdata_dir / 'human_1m/truth_to_ccs.bam')
  by_ref = bam.read_bam_by_name(path)
  assert by_ref
  for name, records in by_ref.items():
    assert name.endswith('/ccs')
    assert all(r.reference_name == name for r in records)


# --- Hardened-decoder regressions (corrupt/truncated inputs) ---------------


def _write_tiny_bam(path, tags=None):
  """One-record BAM whose decompressed bytes are easy to patch."""
  with BamWriter(path, header_text='@HD\tVN:1.6\n') as w:
    w.write('m0/1/0_8', 'ACGTACGT', None,
            tags=tags if tags is not None else {'zm': 1})


def _rewrap(raw: bytes, path: str) -> str:
  with BgzfWriter(path) as w:
    w.write(bytes(raw))
  return path


def test_truncated_header_names_path_and_offset(tmp_path):
  src = str(tmp_path / 'tiny.bam')
  _write_tiny_bam(src)
  raw = bam.bgzf_decompress_file_py(src)
  # Cut mid way through the header text: reading it hits EOF.
  out = _rewrap(raw[:6], str(tmp_path / 'truncated.bam'))
  with pytest.raises(bam.TruncatedBamError) as exc_info:
    bam.BamReader(out, use_native=False)
  err = exc_info.value
  assert err.path == out
  assert err.offset is not None
  assert out in str(err)
  assert not err.recoverable


def test_non_bam_magic_rejected(tmp_path):
  out = _rewrap(b'XAM\x01' + b'\x00' * 64, str(tmp_path / 'notbam.bam'))
  with pytest.raises(CorruptInputError, match='magic'):
    bam.BamReader(out, use_native=False)


def test_negative_l_text_rejected(tmp_path):
  raw = bytearray(b'BAM\x01')
  raw += struct.pack('<i', -1)  # l_text
  out = _rewrap(raw, str(tmp_path / 'neg_ltext.bam'))
  with pytest.raises(CorruptInputError, match='header text length'):
    bam.BamReader(out, use_native=False)


def test_negative_block_size_rejected(tmp_path):
  src = str(tmp_path / 'tiny.bam')
  _write_tiny_bam(src)
  raw = bytearray(bam.bgzf_decompress_file_py(src))
  # Header is magic + l_text + text + n_ref (no references here).
  (l_text,) = struct.unpack_from('<i', raw, 4)
  header_end = 4 + 4 + l_text + 4
  raw[header_end:header_end + 4] = struct.pack('<i', -5)
  out = _rewrap(raw, str(tmp_path / 'neg_block.bam'))
  reader = bam.BamReader(out, use_native=False,
                         skip_corrupt_records=True)  # not skippable
  with pytest.raises(CorruptInputError, match='block_size') as exc_info:
    next(iter(reader))
  assert not exc_info.value.recoverable


def _patch_tag_bytes(raw: bytearray, marker: bytes, at: int,
                     replacement: bytes) -> None:
  idx = bytes(raw).find(marker)
  assert idx >= 0, f'tag marker {marker!r} not found'
  raw[idx + at:idx + at + len(replacement)] = replacement


def test_tag_count_overrun_names_read(tmp_path):
  """Regression: a B-array whose count field overruns the record must
  raise a recoverable CorruptInputError naming the read, never allocate
  the claimed array."""
  src = str(tmp_path / 'tiny.bam')
  _write_tiny_bam(src, tags={'pw': np.arange(8)})
  raw = bytearray(bam.bgzf_decompress_file_py(src))
  # 'pw' encodes as b'pwBi' + u32 count; inflate the count.
  _patch_tag_bytes(raw, b'pwBi', 4, struct.pack('<I', 0xFFFFFFFF))
  out = _rewrap(raw, str(tmp_path / 'tag_overrun.bam'))
  with pytest.raises(CorruptInputError, match='overruns') as exc_info:
    list(bam.BamReader(out, use_native=False))
  err = exc_info.value
  assert err.recoverable
  assert 'm0/1/0_8' in str(err)
  assert out in str(err)


def test_unknown_tag_type_names_read_and_file(tmp_path):
  src = str(tmp_path / 'tiny.bam')
  _write_tiny_bam(src, tags={'RG': 'grp1'})
  raw = bytearray(bam.bgzf_decompress_file_py(src))
  _patch_tag_bytes(raw, b'RGZ', 2, b'Q')  # 'Q' is not a BAM tag type
  out = _rewrap(raw, str(tmp_path / 'bad_tag_type.bam'))
  with pytest.raises(CorruptInputError, match='unknown BAM tag type'):
    list(bam.BamReader(out, use_native=False))
  # Under the skip policy a tag-corrupt record is recoverable: the
  # reader steps over it and counts it instead of dying.
  reader = bam.BamReader(out, use_native=False, skip_corrupt_records=True)
  assert list(reader) == []
  assert reader.n_corrupt_records == 1
