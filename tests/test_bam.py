import numpy as np

from deepconsensus_tpu import constants
from deepconsensus_tpu.io import bam


def test_read_subreads_bam(testdata_dir):
  path = str(testdata_dir / 'human_1m/subreads_to_ccs.bam')
  reader = bam.BamReader(path)
  assert reader.references  # one ccs reference per ZMW
  records = []
  for i, rec in enumerate(reader):
    records.append(rec)
    if i >= 9:
      break
  first = records[0]
  assert first.qname
  assert set(first.seq) <= set('ACGTN')
  assert first.has_tag('zm')
  assert first.has_tag('pw') and first.has_tag('ip') and first.has_tag('sn')
  pw = first.get_tag('pw')
  assert isinstance(pw, np.ndarray)
  assert len(pw) == len(first.seq)
  sn = first.get_tag('sn')
  assert len(sn) == 4 and sn.dtype == np.float32


def test_ccs_bam_has_quals_and_aux(testdata_dir):
  path = str(testdata_dir / 'human_1m/ccs.bam')
  rec = next(iter(bam.BamReader(path)))
  assert rec.qname.endswith('/ccs')
  assert rec.quals is not None
  assert rec.quals.min() >= 0
  assert 'np' in rec.tags and 'rq' in rec.tags


def test_subread_grouper_groups_by_zmw(testdata_dir):
  path = str(testdata_dir / 'human_1m/subreads_to_ccs.bam')
  groups = list(bam.SubreadGrouper(path))
  assert len(groups) == 10  # n_zmw_processed in the bundled summary
  for group in groups:
    zmws = {int(r.get_tag('zm')) for r in group}
    assert len(zmws) == 1
    assert all(not r.is_unmapped for r in group)


def test_aligned_index_arrays_consistency(testdata_dir):
  path = str(testdata_dir / 'human_1m/subreads_to_ccs.bam')
  for i, rec in enumerate(bam.BamReader(path)):
    read_idx, ref_idx = rec.aligned_index_arrays()
    # Every base of seq appears exactly once in query-consuming columns.
    n_query = (read_idx >= 0).sum()
    assert n_query == len(rec.seq)
    covered = read_idx[read_idx >= 0]
    np.testing.assert_array_equal(covered, np.arange(len(rec.seq)))
    # Reference columns are increasing, starting at pos.
    refs = ref_idx[ref_idx >= 0]
    if len(refs):
      assert refs[0] == rec.pos
      assert np.all(np.diff(refs) == 1)
    # Expanded cigar length matches the number of columns.
    assert len(rec.expanded_cigar()) == len(read_idx)
    if i >= 20:
      break


def test_read_truth_bam_by_name(testdata_dir):
  path = str(testdata_dir / 'human_1m/truth_to_ccs.bam')
  by_ref = bam.read_bam_by_name(path)
  assert by_ref
  for name, records in by_ref.items():
    assert name.endswith('/ccs')
    assert all(r.reference_name == name for r in records)
