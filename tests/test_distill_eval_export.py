"""Distillation, offline evaluation, and export round-trips."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepconsensus_tpu.models import (
    config as config_lib,
    distill as distill_lib,
    evaluate as evaluate_lib,
    export as export_lib,
    model as model_lib,
)


def _params(name='transformer_learn_values+test', layers=2, **kw):
  params = config_lib.get_config(name)
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = layers
    params.filter_size = 64
    params.batch_size = 4
    for k, v in kw.items():
      params[k] = v
  return params


def test_init_student_from_teacher():
  teacher_cfg = _params(layers=2)
  student_cfg = _params('transformer_learn_values_distill+test', layers=1)
  with student_cfg.unlocked():
    student_cfg.teacher_encoder_layers = [1]
    student_cfg.student_encoder_layers = [0]
    student_cfg.filter_size = 64
  rows = jnp.zeros((1, teacher_cfg.total_rows, 100, 1))
  teacher = model_lib.get_model(teacher_cfg)
  student = model_lib.get_model(student_cfg)
  t_vars = teacher.init(jax.random.PRNGKey(0), rows)
  s_vars = student.init(jax.random.PRNGKey(1), rows)
  merged = distill_lib.init_student_from_teacher(
      s_vars['params'], t_vars['params'], student_cfg
  )
  # Student layer 0 == teacher layer 1 weights.
  np.testing.assert_array_equal(
      np.asarray(
          merged['encoder']['self_attention_0']['query']['kernel']
      ),
      np.asarray(
          t_vars['params']['encoder']['self_attention_1']['query']['kernel']
      ),
  )
  # Non-encoder layers copied too.
  np.testing.assert_array_equal(
      np.asarray(merged['bases_embedding']['embedding']),
      np.asarray(t_vars['params']['bases_embedding']['embedding']),
  )


def test_distillation_smoke(tmp_path, testdata_dir):
  teacher_cfg = _params(layers=2)
  teacher = model_lib.get_model(teacher_cfg)
  rows = jnp.zeros((1, teacher_cfg.total_rows, 100, 1))
  t_vars = teacher.init(jax.random.PRNGKey(0), rows)

  student_cfg = _params('transformer_learn_values_distill+test', layers=1)
  with student_cfg.unlocked():
    student_cfg.teacher_encoder_layers = [1]
    student_cfg.student_encoder_layers = [0]
    student_cfg.filter_size = 64
    student_cfg.num_epochs = 1
  metrics = distill_lib.run_distillation(
      params=student_cfg,
      teacher_params_cfg=teacher_cfg,
      teacher_variables=t_vars,
      out_dir=str(tmp_path / 'distill'),
      train_patterns=[str(testdata_dir / 'human_1m/tf_examples/train/*')],
      eval_patterns=[str(testdata_dir / 'human_1m/tf_examples/eval/*')],
      num_epochs=1,
  )
  assert np.isfinite(metrics['eval/loss'])


def test_evaluation_writes_csv(tmp_path, testdata_dir):
  params = _params(layers=1)
  model = model_lib.get_model(params)
  rows = jnp.zeros((1, params.total_rows, 100, 1))
  variables = model.init(jax.random.PRNGKey(0), rows)
  metrics = evaluate_lib.run_evaluation(
      params=params,
      checkpoint_path=None,
      eval_patterns=[str(testdata_dir / 'human_1m/tf_examples/eval/*')],
      out_dir=str(tmp_path / 'eval'),
      variables=variables,
  )
  assert os.path.exists(tmp_path / 'eval' / 'inference.csv')
  assert 0.0 <= metrics['per_example_accuracy'] <= 1.0
  assert np.isfinite(metrics['loss'])
  # An untrained model should not beat CCS identity.
  assert metrics['ccs_identity'] > metrics['alignment_identity']


def test_export_roundtrip(tmp_path):
  params = _params(layers=1)
  model = model_lib.get_model(params)
  rows_np = np.zeros((4, params.total_rows, 100, 1), np.float32)
  variables = model.init(jax.random.PRNGKey(0), jnp.asarray(rows_np))
  out_dir = str(tmp_path / 'export')
  export_lib.export_model(
      checkpoint_path=out_dir,  # unused when variables given
      out_dir=out_dir,
      batch_size=4,
      variables=variables,
      params=params,
      # Pre-epilogue artifact: raw preds are the round-trip observable
      # here (epilogue-baked exports are covered by
      # test_device_epilogue.py).
      device_epilogue=False,
  )
  serving, meta = export_lib.load_exported(out_dir)
  assert meta['batch_size'] == 4
  assert meta['device_epilogue'] is False
  preds = serving(jnp.asarray(rows_np))
  direct = model.apply(variables, jnp.asarray(rows_np))
  np.testing.assert_allclose(
      np.asarray(preds), np.asarray(direct), atol=1e-5
  )


def test_cli_export_subcommand(tmp_path, testdata_dir):
  """`dctpu export` produces a servable artifact from a checkpoint
  (parity with reference convert_to_saved_model.py)."""
  from deepconsensus_tpu import cli
  from deepconsensus_tpu.models import config as config_lib
  from deepconsensus_tpu.models import train as train_lib

  params = _params(layers=1)
  out_dir = str(tmp_path / 'train')
  patterns = [str(testdata_dir / 'human_1m/tf_examples/eval/*')]
  with params.unlocked():
    params.batch_size = 8
  train_lib.run_training(
      params=params, out_dir=out_dir,
      train_patterns=patterns, eval_patterns=patterns,
      num_epochs=1, eval_every=10**9,
  )
  ckpts = [
      n for n in os.listdir(os.path.join(out_dir, 'checkpoints'))
      if n.startswith('checkpoint-') and not n.endswith('-tmp')
  ]
  ckpt = os.path.join(out_dir, 'checkpoints', sorted(ckpts)[-1])
  export_dir = str(tmp_path / 'exported')
  rc = cli.main([
      'export', '--checkpoint', ckpt, '--output', export_dir,
      '--batch_size', '8',
  ])
  assert rc == 0
  serving, meta = export_lib.load_exported(export_dir)
  assert meta['batch_size'] == 8
  rows = jnp.zeros((8, params.total_rows, params.max_length, 1))
  preds = serving(rows)
  assert np.asarray(preds).shape == (8, params.max_length, 5)


def test_cli_evaluate_subcommand(tmp_path, testdata_dir):
  from deepconsensus_tpu import cli
  from deepconsensus_tpu.models import train as train_lib

  params = _params(layers=1)
  out_dir = str(tmp_path / 'train')
  patterns = [str(testdata_dir / 'human_1m/tf_examples/eval/*')]
  with params.unlocked():
    params.batch_size = 8
  train_lib.run_training(
      params=params, out_dir=out_dir,
      train_patterns=patterns, eval_patterns=patterns,
      num_epochs=1, eval_every=10**9,
  )
  ckpts = sorted(
      n for n in os.listdir(os.path.join(out_dir, 'checkpoints'))
      if n.startswith('checkpoint-') and not n.endswith('-tmp')
  )
  eval_dir = str(tmp_path / 'eval_out')
  rc = cli.main([
      'evaluate',
      '--checkpoint', os.path.join(out_dir, 'checkpoints', ckpts[-1]),
      '--eval_path', patterns[0],
      '--out_dir', eval_dir, '--limit', '16',
  ])
  assert rc == 0
  csv_path = os.path.join(eval_dir, 'inference.csv')
  assert os.path.exists(csv_path)
  with open(csv_path) as f:
    header, row = f.read().strip().splitlines()
  assert 'loss' in header and row
