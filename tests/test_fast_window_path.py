"""The fast whole-ZMW window path must equal the per-window slow path."""
import numpy as np

from deepconsensus_tpu.preprocess import (
    FeatureLayout,
    create_proc_feeder,
    reads_to_pileup,
)

TDKEYS = ('subreads/num_passes', 'name', 'window_pos', 'overflow',
          'ec', 'np_num_passes', 'rq', 'rg')


def test_fast_path_equals_slow_path(testdata_dir):
  td = str(testdata_dir / 'human_1m')
  layout = FeatureLayout(20, 100)
  feeder, _ = create_proc_feeder(
      subreads_to_ccs=f'{td}/subreads_to_ccs.bam',
      ccs_bam=f'{td}/ccs.bam',
      layout=layout,
      ins_trim=5,
  )
  n_windows = 0
  for subreads, name, lay, split, ww in feeder():
    slow_pileup = reads_to_pileup(subreads, name, lay, ww)
    slow = [w.to_features_dict() for w in slow_pileup.iter_windows()]
    slow_counter = dict(slow_pileup.counter)
    fast_pileup = reads_to_pileup(subreads, name, lay, ww)
    fast = list(fast_pileup.iter_window_features())
    assert dict(fast_pileup.counter) == slow_counter
    assert len(fast) == len(slow)
    for f, s in zip(fast, slow):
      for key in TDKEYS:
        assert f[key] == s[key], key
      np.testing.assert_array_equal(
          f['subreads'], s['subreads'], err_msg=str((name, s['window_pos']))
      )
      np.testing.assert_array_equal(
          f['ccs_base_quality_scores'], s['ccs_base_quality_scores']
      )
      n_windows += 1
  assert n_windows > 1500
