"""Plain (non-learned-values) transformer: raw row features, even-padded
hidden size, no embedding tables (reference EncoderOnlyTransformer)."""
import jax
import jax.numpy as jnp
import numpy as np

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib


def test_plain_transformer_forward_and_params():
  params = config_lib.get_config('transformer+test')
  config_lib.finalize_params(params)
  assert params.hidden_size == 86  # total_rows 85 padded even
  with params.unlocked():
    params.dtype = 'float32'
    params.num_hidden_layers = 1
    params.filter_size = 32
  model = model_lib.get_model(params)
  rows = jnp.asarray(
      np.random.default_rng(0)
      .integers(0, 5, (2, params.total_rows, 100, 1))
      .astype(np.float32)
  )
  variables = model.init(jax.random.PRNGKey(0), rows)
  assert not any('embedding' in k for k in variables['params'])
  preds = model.apply(variables, rows)
  assert preds.shape == (2, 100, 5)
  np.testing.assert_allclose(
      np.asarray(preds.sum(-1)), np.ones((2, 100)), atol=1e-5
  )
