"""Elastic multi-host training: bounded barriers, coordinated pod
rebuild, and host re-admission (PR 18).

Each in-process "host" is a thread running the real `run_training`
loop over its own forced CPU device with an `elastic_config`; the
shared-filesystem pod under <out_dir>/.pod is the only channel
between them, exactly as on a real fleet with a shared out_dir.

The identity contract mirrors test_train_parallel's cross-dp one:
every member consumes the SAME global batch (same seed) and slices it
by member rank, and step_sync's weighted mean (weights = local slice
rows) reconstructs the exact global-batch-mean gradient — so a run
disturbed by a host death (pod shrinks to the survivors) or a
re-admission (pod grows back) must trace the SAME loss curve as an
undisturbed run, to all-reduce reduction order (~1e-6 relative on
CPU; pinned at rtol=1e-4 plus the 1e-4-quantized digest).
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu import obs as obs_lib
from deepconsensus_tpu.models import checkpoints as checkpoints_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import train as train_lib
from deepconsensus_tpu.parallel import distributed
from deepconsensus_tpu.parallel import elastic as elastic_lib
from deepconsensus_tpu.parallel import mesh as mesh_lib

pytestmark = [pytest.mark.multichip, pytest.mark.resilience]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
  sys.path.insert(0, _REPO_ROOT)

MAX_PASSES = 5
MAX_LENGTH = 20
GLOBAL_BATCH = 16
N_EXAMPLES = 96  # 6 steps per epoch at the fixed global batch
STEPS_PER_EPOCH = 6


@pytest.fixture(scope='module')
def shards(tmp_path_factory):
  from scripts import inject_faults

  d = tmp_path_factory.mktemp('elastic_shards')
  return inject_faults.write_synthetic_tfrecords(
      str(d), n_shards=4, n_examples=N_EXAMPLES,
      max_passes=MAX_PASSES, max_length=MAX_LENGTH,
  )


def tiny_params(**overrides):
  params = config_lib.get_config('fc+test')
  with params.unlocked():
    params.max_passes = MAX_PASSES
    params.max_length = MAX_LENGTH
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.batch_size = GLOBAL_BATCH
    params.warmup_steps = 2
    params.log_every_n_steps = 1
    params.seed = 7
    for k, v in overrides.items():
      setattr(params, k, v)
  return params


def elastic_host(shards, out_dir, host_id, n_hosts, num_epochs,
                 results, key=None, **ecfg):
  """One pod member: the full training loop on its own device, talking
  to peers only through <out_dir>/.pod."""
  key = host_id if key is None else key
  try:
    params = tiny_params()
    mesh = mesh_lib.make_mesh(dp=1, tp=1,
                              devices=[jax.devices()[host_id]])
    m = train_lib.run_training(
        params=params, out_dir=out_dir,
        train_patterns=list(shards), eval_patterns=list(shards),
        num_epochs=num_epochs, mesh=mesh, eval_every=1_000_000,
        elastic_config={'host_id': host_id, 'n_hosts': n_hosts,
                        'barrier_timeout': 5.0,
                        'heartbeat_interval': 0.1, **ecfg},
    )
    results[key] = ('ok', m)
  except BaseException as e:  # noqa: B036 - drills inject BaseException
    results[key] = ('err', e)


def metrics_entries(out_dir, split=None):
  entries = []
  with open(os.path.join(out_dir, 'metrics.jsonl')) as f:
    for line in f:
      e = json.loads(line)
      if split is None or e.get('split') == split:
        entries.append(e)
  return entries


def train_losses(out_dir):
  return [e['loss'] for e in metrics_entries(out_dir, 'train')]


def curve_digest_1e4(losses):
  import hashlib

  return hashlib.sha256(
      json.dumps([round(l, 4) for l in losses]).encode()
  ).hexdigest()[:16]


def final_checkpoint_params(out_dir):
  latest = checkpoints_lib.latest_valid_checkpoint(
      os.path.join(out_dir, 'checkpoints'))
  assert latest is not None
  return checkpoints_lib.load_params(latest)


def trace_event_names(trace_path):
  names = []
  with open(trace_path) as f:
    for line in f:
      line = line.strip().rstrip(',')
      if not line or line == '[':
        continue
      names.append(json.loads(line).get('name'))
  return names


class _shared_trace:
  """Context manager: one stable trace writer for all drill threads.

  run_training calls trace.configure_from_env per invocation; with two
  in-process hosts that would close the sibling's writer mid-run (real
  fleets are separate processes, where per-process configure is
  correct). Configure once here and no-op the per-run reconfigure."""

  def __init__(self, path):
    self.path = path

  def __enter__(self):
    self._orig = obs_lib.trace.configure_from_env
    obs_lib.trace.configure(self.path, tier='train')
    obs_lib.trace.configure_from_env = lambda tier='': None
    return self

  def __exit__(self, *exc):
    obs_lib.trace.configure_from_env = self._orig
    obs_lib.trace.configure(None)
    return False


def assert_params_close(out_a, out_b):
  la = jax.tree_util.tree_leaves(final_checkpoint_params(out_a))
  lb = jax.tree_util.tree_leaves(final_checkpoint_params(out_b))
  assert len(la) == len(lb)
  for va, vb in zip(la, lb):
    np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                               rtol=1e-4, atol=1e-6)


# ----------------------------------------------------------------------
# bounded_call: the watchdog for uncancellable legacy collectives


def test_bounded_call_passes_value_and_error_through():
  assert elastic_lib.bounded_call(lambda: 42, 5.0, 'ok') == 42
  with pytest.raises(ZeroDivisionError):
    elastic_lib.bounded_call(lambda: 1 / 0, 5.0, 'boom')


def test_bounded_call_deadline_is_bounded_and_typed():
  t0 = time.monotonic()
  with pytest.raises(faults_lib.HostLostError) as ei:
    elastic_lib.bounded_call(lambda: time.sleep(60), 0.3, 'stuck-vote')
  elapsed = time.monotonic() - t0
  assert elapsed < 5.0, f'watchdog waited {elapsed:.1f}s for a 0.3s deadline'
  assert 'stuck-vote' in str(ei.value)
  assert faults_lib.classify_error(
      f'{type(ei.value).__name__}: {ei.value}'
  ) == faults_lib.FaultKind.TRANSIENT


# ----------------------------------------------------------------------
# Pod protocol units (no training loop)


def test_pod_geometry_and_timeout_validation(tmp_path):
  with pytest.raises(ValueError):
    elastic_lib.ElasticPod(str(tmp_path / 'p'), host_id=0, n_hosts=0)
  with pytest.raises(ValueError):
    elastic_lib.ElasticPod(str(tmp_path / 'p'), host_id=-1, n_hosts=2)
  with pytest.raises(ValueError):
    elastic_lib.ElasticPod(str(tmp_path / 'p'), host_id=0, n_hosts=1,
                           barrier_timeout=0.0)


def test_member_batch_slice_partitions_exactly():
  for n, k in [(16, 2), (16, 3), (7, 3), (5, 8)]:
    slices = [distributed.member_batch_slice(n, k, r) for r in range(k)]
    rows = np.concatenate([np.arange(n)[s] for s in slices])
    np.testing.assert_array_equal(rows, np.arange(n))
    sizes = [len(np.arange(n)[s]) for s in slices]
    assert sizes == [len(part) for part in np.array_split(np.arange(n), k)]


@pytest.fixture
def booted_pair(tmp_path):
  """Two started pod endpoints that rendezvoused as founding members."""
  pods = [
      elastic_lib.ElasticPod(str(tmp_path / 'pod'), host_id=i, n_hosts=2,
                             barrier_timeout=5.0, heartbeat_interval=0.1,
                             boot_timeout=30.0)
      for i in range(2)
  ]
  starts = [None, None]

  def boot(i):
    starts[i] = pods[i].start()

  threads = [threading.Thread(target=boot, args=(i,)) for i in range(2)]
  for t in threads:
    t.start()
  for t in threads:
    t.join(timeout=60)
  assert all(s is not None and not s.joined for s in starts)
  assert all(p.members == (0, 1) and p.epoch == 1 for p in pods)
  yield pods
  for p in pods:
    p.close()


def test_barrier_timeout_sweep_no_unbounded_wait(booted_pair):
  """A silent peer surfaces as a typed error naming the missing host
  after ~the configured deadline — for every deadline, never an
  unbounded wait."""
  pod0, _ = booted_pair
  for timeout_s in (0.4, 0.8, 1.6):
    t0 = time.monotonic()
    with pytest.raises(faults_lib.HostLostError) as ei:
      pod0.barrier(f'sweep-{timeout_s}', timeout_s=timeout_s)
    elapsed = time.monotonic() - t0
    # Generous slack for fs polling; the point is elapsed tracks the
    # configured deadline instead of growing without bound.
    assert elapsed < timeout_s + 3.0, (
        f'{timeout_s}s barrier took {elapsed:.1f}s')
    assert ei.value.missing == (1,)
    assert ei.value.epoch == 1
  assert pod0.counters()['n_barrier_timeouts'] == 3.0


def test_step_sync_weighted_mean_and_control_plane(booted_pair):
  pods = booted_pair
  grads = {0: np.full(4, 1.0, np.float32), 1: np.full(4, 4.0, np.float32)}
  weights = {0: 6.0, 1: 2.0}
  out = [None, None]

  def sync(i):
    out[i] = pods[i].step_sync(
        1, [grads[i]], weight=weights[i],
        meta={'loss': float(i)}, stop_vote=(i == 1))

  threads = [threading.Thread(target=sync, args=(i,)) for i in range(2)]
  for t in threads:
    t.start()
  for t in threads:
    t.join(timeout=30)
  for i in range(2):
    assert out[i] is not None
    # Exact global mean: (6*1 + 2*4) / 8 = 1.75.
    np.testing.assert_allclose(out[i].arrays[0],
                               np.full(4, 1.75, np.float32), rtol=1e-6)
    assert out[i].stop  # one vote is enough: stop is ORed
    assert out[i].weight_total == 8.0
    assert out[i].metas[0]['loss'] == 0.0
    assert out[i].metas[1]['loss'] == 1.0


def test_advance_round_isolates_replayed_steps(booted_pair):
  """After a rollback (advance_round) a replayed step number must NOT
  collect the stale payloads of its first pass."""
  pods = booted_pair
  out = [None, None]

  def sync(i, value):
    out[i] = pods[i].step_sync(1, [np.full(2, value, np.float32)],
                               weight=1.0)

  for value in (1.0, 9.0):
    threads = [threading.Thread(target=sync, args=(i, value))
               for i in range(2)]
    for t in threads:
      t.start()
    for t in threads:
      t.join(timeout=30)
    np.testing.assert_allclose(out[0].arrays[0],
                               np.full(2, value, np.float32))
    for p in pods:
      p.advance_round()


# ----------------------------------------------------------------------
# Bounded legacy collectives: stop vote + orbax save


def test_preemption_guard_stop_vote_bounded(monkeypatch):
  from jax.experimental import multihost_utils

  monkeypatch.setattr(jax, 'process_count', lambda: 2)
  monkeypatch.setattr(multihost_utils, 'process_allgather',
                      lambda *a, **k: time.sleep(60))
  guard = train_lib.PreemptionGuard(barrier_timeout=0.3)
  t0 = time.monotonic()
  with pytest.raises(faults_lib.HostLostError) as ei:
    guard.requested()
  assert time.monotonic() - t0 < 5.0
  assert 'preemption-stop-vote' in str(ei.value)


def test_orbax_save_bounded_names_missing_peer(tmp_path, monkeypatch):
  params = tiny_params()
  trainer = train_lib.Trainer(params=params, out_dir=str(tmp_path / 's'))
  state = trainer.init_state(steps_total=8)
  monkeypatch.setattr(jax, 'process_count', lambda: 2)
  monkeypatch.setattr(trainer, '_save_timeout', lambda: 0.3)
  monkeypatch.setattr(trainer._checkpointer, 'save',
                      lambda *a, **k: time.sleep(60))
  t0 = time.monotonic()
  with pytest.raises(faults_lib.HostLostError) as ei:
    trainer.save_checkpoint(state, 0, {})
  assert time.monotonic() - t0 < 5.0
  assert 'orbax-save-0' in str(ei.value)


# ----------------------------------------------------------------------
# Drill 1: kill one host mid-run -> coordinated rebuild, survivors
# finish, and the result is indistinguishable from an undisturbed run.


@pytest.fixture(scope='module')
def solo6_run(shards, tmp_path_factory):
  """Undisturbed pod-of-1 elastic baseline, 1 epoch (6 steps)."""
  out = str(tmp_path_factory.mktemp('elastic_solo6'))
  results = {}
  elastic_host(shards, out, 0, 1, 1, results)
  assert results[0][0] == 'ok', results[0]
  return out


@pytest.fixture(scope='module')
def kill_drill(shards, tmp_path_factory):
  """2-host pod; host 1 dies (drop mode: barriers abandoned, thread
  keeps heartbeating until the exception unwinds) at step 3."""
  out = str(tmp_path_factory.mktemp('elastic_kill'))
  fired_before = faults_lib._fired
  faults_lib._fired = set()
  os.environ['DCTPU_FAULT_HOST_LOST_AT_STEP'] = '3'
  os.environ['DCTPU_FAULT_HOST_LOST_HOST'] = '1'
  os.environ['DCTPU_FAULT_HOST_LOST_MODE'] = 'drop'
  results = {}
  try:
    with _shared_trace(os.path.join(out, 'trace.jsonl')):
      threads = [
          threading.Thread(target=elastic_host,
                           args=(shards, out, i, 2, 1, results))
          for i in range(2)
      ]
      for t in threads:
        t.start()
      for t in threads:
        t.join(timeout=420)
  finally:
    for key in list(os.environ):
      if key.startswith('DCTPU_FAULT_HOST_LOST'):
        del os.environ[key]
    faults_lib._fired = fired_before
  return out, results


def test_kill_drill_survivor_finishes_and_victim_died(kill_drill):
  _, results = kill_drill
  assert results[0][0] == 'ok', results[0]
  assert results[1][0] == 'err'
  assert isinstance(results[1][1], faults_lib.InjectedHostDeath)


def test_kill_drill_counts_one_rebuild_and_bumps_epoch(kill_drill):
  out, _ = kill_drill
  row = metrics_entries(out, 'faults')[-1]
  assert row['n_host_rebuilds'] == 1.0
  assert row['n_barrier_timeouts'] >= 1.0
  assert row['pod_epoch'] == 2.0  # boot(1) -> rebuild(2)
  assert row['n_host_readmissions'] == 0.0


def test_kill_drill_curve_matches_undisturbed_run(kill_drill, solo6_run):
  out, _ = kill_drill
  disturbed, solo = train_losses(out), train_losses(solo6_run)
  assert len(disturbed) == len(solo) == STEPS_PER_EPOCH
  np.testing.assert_allclose(solo, disturbed, rtol=1e-4, atol=1e-6)
  assert curve_digest_1e4(disturbed) == curve_digest_1e4(solo)


def test_kill_drill_final_weights_match_undisturbed_run(
    kill_drill, solo6_run):
  out, _ = kill_drill
  assert_params_close(out, solo6_run)


def test_kill_drill_manifest_records_shrunken_pod(kill_drill):
  out, _ = kill_drill
  latest = checkpoints_lib.latest_valid_checkpoint(
      os.path.join(out, 'checkpoints'))
  manifest = checkpoints_lib.read_manifest(latest)
  assert manifest['pod_epoch'] == 2
  assert manifest['pod_members'] == [0]


def test_kill_drill_emits_rebuild_trace_span(kill_drill):
  out, _ = kill_drill
  names = trace_event_names(os.path.join(out, 'trace.jsonl'))
  assert 'host_rebuild' in names
  assert 'host_readmit' not in names


# ----------------------------------------------------------------------
# Drill 2: the dead host comes back -> admitted at a step boundary,
# epoch bumped twice (rebuild + readmit), identity preserved.


@pytest.fixture(scope='module')
def solo12_run(shards, tmp_path_factory):
  """Undisturbed pod-of-1 elastic baseline, 2 epochs (12 steps)."""
  out = str(tmp_path_factory.mktemp('elastic_solo12'))
  results = {}
  elastic_host(shards, out, 0, 1, 2, results)
  assert results[0][0] == 'ok', results[0]
  return out


@pytest.fixture(scope='module')
def rejoin_drill(shards, tmp_path_factory):
  """2-host pod over 2 epochs: host 1 dies at step 2, restarts, and
  defers its join announcement to step 6 — survivors admit it at the
  next boundary. Steps are paced (~0.2s) so the announcement lands
  while the run is still going; on a real fleet the step time itself
  provides the window."""
  out = str(tmp_path_factory.mktemp('elastic_rejoin'))
  fired_before = faults_lib._fired
  faults_lib._fired = set()
  orig_sync = elastic_lib.ElasticPod.step_sync

  def paced_sync(self, *args, **kwargs):
    time.sleep(0.2)
    return orig_sync(self, *args, **kwargs)

  elastic_lib.ElasticPod.step_sync = paced_sync
  os.environ['DCTPU_FAULT_HOST_LOST_AT_STEP'] = '2'
  os.environ['DCTPU_FAULT_HOST_LOST_HOST'] = '1'
  os.environ['DCTPU_FAULT_HOST_LOST_MODE'] = 'drop'
  results = {}
  try:
    with _shared_trace(os.path.join(out, 'trace.jsonl')):
      threads = [
          threading.Thread(target=elastic_host,
                           args=(shards, out, i, 2, 2, results))
          for i in range(2)
      ]
      for t in threads:
        t.start()
      deadline = time.monotonic() + 300
      while 1 not in results and time.monotonic() < deadline:
        time.sleep(0.05)
      assert results.get(1, ('missing',))[0] == 'err', (
          'injected death never fired')
      assert isinstance(results[1][1], faults_lib.InjectedHostDeath)
      for key in list(os.environ):
        if key.startswith('DCTPU_FAULT_HOST_LOST'):
          del os.environ[key]
      faults_lib._fired = set()
      os.environ['DCTPU_FAULT_HOST_REJOIN_AT_STEP'] = '6'
      rejoin = threading.Thread(
          target=elastic_host,
          args=(shards, out, 1, 2, 2, results), kwargs={'key': 'rejoin'})
      rejoin.start()
      threads[0].join(timeout=420)
      rejoin.join(timeout=420)
  finally:
    elastic_lib.ElasticPod.step_sync = orig_sync
    for key in list(os.environ):
      if key.startswith('DCTPU_FAULT_HOST'):
        del os.environ[key]
    faults_lib._fired = fired_before
  return out, results


def test_rejoin_drill_both_sides_finish(rejoin_drill):
  _, results = rejoin_drill
  assert results[0][0] == 'ok', results[0]
  assert results['rejoin'][0] == 'ok', results['rejoin']


def test_rejoin_drill_bumps_epoch_twice_and_counts_readmission(
    rejoin_drill):
  out, _ = rejoin_drill
  row = metrics_entries(out, 'faults')[-1]
  assert row['pod_epoch'] == 3.0  # boot(1) -> rebuild(2) -> readmit(3)
  assert row['n_host_rebuilds'] == 1.0
  assert row['n_host_readmissions'] == 1.0


def test_rejoin_drill_curve_matches_undisturbed_run(
    rejoin_drill, solo12_run):
  out, _ = rejoin_drill
  disturbed, solo = train_losses(out), train_losses(solo12_run)
  assert len(disturbed) == len(solo) == 2 * STEPS_PER_EPOCH
  np.testing.assert_allclose(solo, disturbed, rtol=1e-4, atol=1e-6)
  assert curve_digest_1e4(disturbed) == curve_digest_1e4(solo)


def test_rejoin_drill_final_weights_match_undisturbed_run(
    rejoin_drill, solo12_run):
  out, _ = rejoin_drill
  assert_params_close(out, solo12_run)


def test_rejoin_drill_manifest_records_full_strength_pod(rejoin_drill):
  out, _ = rejoin_drill
  latest = checkpoints_lib.latest_valid_checkpoint(
      os.path.join(out, 'checkpoints'))
  manifest = checkpoints_lib.read_manifest(latest)
  assert manifest['pod_epoch'] == 3
  assert manifest['pod_members'] == [0, 1]


def test_rejoin_drill_emits_rebuild_and_readmit_spans(rejoin_drill):
  out, _ = rejoin_drill
  names = trace_event_names(os.path.join(out, 'trace.jsonl'))
  assert 'host_rebuild' in names
  assert 'host_readmit' in names


def test_solo_baselines_share_their_prefix(solo6_run, solo12_run):
  """The data stream is deterministic in (seed, epoch): the 2-epoch
  baseline's first epoch IS the 1-epoch baseline."""
  np.testing.assert_allclose(
      train_losses(solo12_run)[:STEPS_PER_EPOCH],
      train_losses(solo6_run), rtol=1e-6)


# ----------------------------------------------------------------------
# The hard drill: a REAL process SIGKILLed mid-step, driven through the
# CLI exactly as an operator would run it.


@pytest.mark.slow
def test_subprocess_sigkill_drill_survivor_finishes(shards, tmp_path):
  out = str(tmp_path / 'pod_run')
  base = [
      sys.executable, '-m', 'deepconsensus_tpu.cli', 'train',
      '--config', 'fc+test', '--out_dir', out,
      '--train_path', *shards, '--eval_path', *shards,
      '--num_epochs', '1', '--batch_size', str(GLOBAL_BATCH),
      '--set', f'max_passes={MAX_PASSES}',
      '--set', f'max_length={MAX_LENGTH}',
      '--set', 'log_every_n_steps=1',
      '--elastic', '--num_processes', '2',
      '--elastic_barrier_timeout', '10',
  ]
  env = dict(os.environ, JAX_PLATFORMS='cpu')
  env.pop('DCTPU_FAULT_KILL_TOKEN', None)
  env_victim = dict(env)
  env_victim[faults_lib.ENV_HOST_LOST_AT_STEP] = '3'
  env_victim[faults_lib.ENV_HOST_LOST_HOST] = '1'
  env_victim[faults_lib.ENV_KILL_TOKEN] = str(tmp_path / 'kill.token')
  survivor = subprocess.Popen(base + ['--process_id', '0'], env=env)
  victim = subprocess.Popen(base + ['--process_id', '1'], env=env_victim)
  try:
    assert victim.wait(timeout=600) == -9  # SIGKILL, not a clean exit
    assert survivor.wait(timeout=600) == 0
  finally:
    for proc in (survivor, victim):
      if proc.poll() is None:
        proc.kill()
  row = metrics_entries(out, 'faults')[-1]
  assert row['n_host_rebuilds'] == 1.0
  assert row['pod_epoch'] == 2.0
  assert len(train_losses(out)) == STEPS_PER_EPOCH
