"""Training-loop smoke tests on the bundled reference shards
(modeled on reference model_train_custom_loop_test.py coverage)."""
import os

import numpy as np
import pytest

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import train as train_lib


@pytest.fixture(scope='module')
def tiny_params():
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.batch_size = 4
    params.num_hidden_layers = 1
    params.filter_size = 64
    params.warmup_steps = 2
    params.eval_every_n_steps = 5
    params.log_every_n_steps = 1
  return params


def test_learning_rate_schedule(tiny_params):
  fn = train_lib.create_learning_rate_fn(tiny_params, decay_steps=100)
  warm = float(fn(0))
  peak = float(fn(tiny_params.warmup_steps))
  end = float(fn(100))
  assert 0 < warm < peak
  assert peak == pytest.approx(
      tiny_params.initial_learning_rate, rel=0.1
  )
  assert end == pytest.approx(tiny_params.end_learning_rate, rel=0.05)


def test_weight_decay_mask(tiny_params):
  import jax
  from deepconsensus_tpu.models import model as model_lib
  import jax.numpy as jnp

  model = model_lib.get_model(tiny_params)
  rows = jnp.zeros((1, tiny_params.total_rows, 100, 1))
  variables = model.init(jax.random.PRNGKey(0), rows)
  mask = train_lib._weight_decay_mask(variables['params'])
  flat = jax.tree_util.tree_flatten_with_path(mask)[0]
  by_path = {
      '/'.join(getattr(k, 'key', str(k)) for k in path): v
      for path, v in flat
  }
  assert any(v for v in by_path.values())
  for path, v in by_path.items():
    if path.endswith('bias') or 'alpha' in path or 'norm' in path.lower():
      assert not v, path


def test_short_training_run(tiny_params, tmp_path, testdata_dir):
  out_dir = str(tmp_path / 'train_out')
  metrics = train_lib.run_training(
      params=tiny_params,
      out_dir=out_dir,
      train_patterns=[str(testdata_dir / 'human_1m/tf_examples/train/*')],
      eval_patterns=[str(testdata_dir / 'human_1m/tf_examples/eval/*')],
      num_epochs=1,
      eval_every=10**9,  # only the final eval
  )
  assert np.isfinite(metrics['eval/loss'])
  assert 0.0 <= metrics['eval/per_example_accuracy'] <= 1.0
  # Checkpoint artifacts exist (reference asserts the same set:
  # model_train_custom_loop_test.py:41-84).
  assert os.path.exists(os.path.join(out_dir, 'params.json'))
  assert os.path.exists(os.path.join(out_dir, 'checkpoint_metrics.tsv'))
  assert os.path.exists(os.path.join(out_dir, 'best_checkpoint.txt'))
  assert os.path.exists(os.path.join(out_dir, 'metrics.jsonl'))
  ckpts = os.listdir(os.path.join(out_dir, 'checkpoints'))
  assert any(c.startswith('checkpoint-') for c in ckpts)
