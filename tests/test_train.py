"""Training-loop smoke tests on the bundled reference shards
(modeled on reference model_train_custom_loop_test.py coverage)."""
import os

import numpy as np
import pytest

from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import train as train_lib


@pytest.fixture(scope='module')
def tiny_params():
  params = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(params)
  with params.unlocked():
    params.dtype = 'float32'
    params.batch_size = 4
    params.num_hidden_layers = 1
    params.filter_size = 64
    params.warmup_steps = 2
    params.eval_every_n_steps = 5
    params.log_every_n_steps = 1
  return params


def test_learning_rate_schedule(tiny_params):
  fn = train_lib.create_learning_rate_fn(tiny_params, decay_steps=100)
  warm = float(fn(0))
  peak = float(fn(tiny_params.warmup_steps))
  end = float(fn(100))
  assert 0 < warm < peak
  assert peak == pytest.approx(
      tiny_params.initial_learning_rate, rel=0.1
  )
  assert end == pytest.approx(tiny_params.end_learning_rate, rel=0.05)


def test_weight_decay_mask(tiny_params):
  import jax
  from deepconsensus_tpu.models import model as model_lib
  import jax.numpy as jnp

  model = model_lib.get_model(tiny_params)
  rows = jnp.zeros((1, tiny_params.total_rows, 100, 1))
  variables = model.init(jax.random.PRNGKey(0), rows)
  mask = train_lib._weight_decay_mask(variables['params'])
  flat = jax.tree_util.tree_flatten_with_path(mask)[0]
  by_path = {
      '/'.join(getattr(k, 'key', str(k)) for k in path): v
      for path, v in flat
  }
  assert any(v for v in by_path.values())
  for path, v in by_path.items():
    if path.endswith('bias') or 'alpha' in path or 'norm' in path.lower():
      assert not v, path


def test_best_checkpoint_metric_selection(tiny_params, tmp_path, caplog):
  """best_checkpoint.txt follows params.best_checkpoint_metric: the
  default (per_example_accuracy) ties at 0.0 on held-out sets and
  keeps the first checkpoint, while identity_pred tracks the real
  peak; a typo'd metric warns loudly instead of silently never
  updating (round-4 held-out artifact fallout)."""
  import logging

  def run(metric_name, evals):
    params = config_lib.get_config('transformer_learn_values+test')
    config_lib.finalize_params(params)
    out = str(tmp_path / f'best_{metric_name.replace("/", "_")}')
    with params.unlocked():
      params.dtype = 'float32'
      params.num_hidden_layers = 1
      params.filter_size = 32
      params.best_checkpoint_metric = metric_name
    trainer = train_lib.Trainer(params=params, out_dir=out, mesh=None)
    state = trainer.init_state(steps_total=10)
    for step, metrics in evals:
      trainer.save_checkpoint(state, step, metrics)
    best = os.path.join(out, 'best_checkpoint.txt')
    return open(best).read().strip() if os.path.exists(best) else None

  trajectory = [
      (1, {'eval/per_example_accuracy': 0.0, 'eval/identity_pred': 0.5}),
      (2, {'eval/per_example_accuracy': 0.0, 'eval/identity_pred': 0.9}),
      (3, {'eval/per_example_accuracy': 0.0, 'eval/identity_pred': 0.7}),
  ]
  # Reference default: all-zero per_example_accuracy -> first ckpt.
  assert run('eval/per_example_accuracy', trajectory) == 'checkpoint-1'
  # Identity selector finds the held-out peak.
  assert run('eval/identity_pred', trajectory) == 'checkpoint-2'
  # Typo'd name: loud warning, no best file.
  with caplog.at_level(logging.WARNING):
    got = run('eval/identity_typo', trajectory[:1])
  assert got is None
  assert any('best_checkpoint_metric' in r.message for r in caplog.records)


def test_short_training_run(tiny_params, tmp_path, testdata_dir):
  out_dir = str(tmp_path / 'train_out')
  metrics = train_lib.run_training(
      params=tiny_params,
      out_dir=out_dir,
      train_patterns=[str(testdata_dir / 'human_1m/tf_examples/train/*')],
      eval_patterns=[str(testdata_dir / 'human_1m/tf_examples/eval/*')],
      num_epochs=1,
      eval_every=10**9,  # only the final eval
  )
  assert np.isfinite(metrics['eval/loss'])
  assert 0.0 <= metrics['eval/per_example_accuracy'] <= 1.0
  # Checkpoint artifacts exist (reference asserts the same set:
  # model_train_custom_loop_test.py:41-84).
  assert os.path.exists(os.path.join(out_dir, 'params.json'))
  assert os.path.exists(os.path.join(out_dir, 'checkpoint_metrics.tsv'))
  assert os.path.exists(os.path.join(out_dir, 'best_checkpoint.txt'))
  assert os.path.exists(os.path.join(out_dir, 'metrics.jsonl'))
  ckpts = os.listdir(os.path.join(out_dir, 'checkpoints'))
  assert any(c.startswith('checkpoint-') for c in ckpts)
