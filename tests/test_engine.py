"""ConsensusEngine boundary tests.

Ports the compile-once smoke (test_perf_smoke.py) and the packer edge
cases (test_window_packer.py) to the engine's submit/deliver interface,
and proves the runner refactor behavior-preserving: driving the engine
directly over a featurized synthetic input reproduces the batch CLI's
FASTQ byte-for-byte.
"""
import collections

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax._src import test_util as jtu

from deepconsensus_tpu.inference import engine as engine_lib
from deepconsensus_tpu.inference import runner as runner_lib
from deepconsensus_tpu.models import config as config_lib
from deepconsensus_tpu.models import model as model_lib
from deepconsensus_tpu.postprocess import stitch

pytestmark = pytest.mark.resilience

BATCH = 8
STUB_QUAL = 40


@pytest.fixture(scope='module')
def params():
  p = config_lib.get_config('transformer_learn_values+test')
  config_lib.finalize_params(p, is_training=False)
  return p


def _stub_runner(params, batch_size=BATCH, fail_packs=()):
  """Weightless ModelRunner whose finalize echoes each window's
  draft-CCS row; packs listed in fail_packs raise at dispatch."""
  options = runner_lib.InferenceOptions(batch_size=batch_size)
  options.max_passes = params.max_passes
  options.max_length = params.max_length
  options.use_ccs_bq = params.use_ccs_bq
  runner = runner_lib.ModelRunner(params, {}, options)
  mp = params.max_passes
  seq = [0]

  def dispatch(rows):
    pack = seq[0]
    seq[0] += 1
    if pack in fail_packs:
      raise RuntimeError(f'stub failure in pack {pack}')
    return rows

  def finalize(rows):
    ids = rows[:, 4 * mp, :, 0].astype(np.int32)
    return ids, np.full(ids.shape, STUB_QUAL, np.int32)

  runner.dispatch = dispatch
  runner.finalize = finalize
  return runner, options


def _raw_windows(params, n, seed=0):
  rng = np.random.default_rng(seed)
  shape = (n, params.total_rows, params.max_length, 1)
  return rng.integers(0, 5, size=shape).astype(np.float32)


def _collecting_engine(params, batch_size=BATCH, fail_packs=()):
  runner, options = _stub_runner(params, batch_size, fail_packs)
  delivered = {}
  failures = []
  engine = engine_lib.ConsensusEngine(
      runner, options,
      deliver=lambda t, ids, quals: delivered.__setitem__(t, (ids, quals)),
      on_pack_failure=lambda ts, seq, e: failures.append((list(ts), seq, e)))
  return engine, delivered, failures


# ----------------------------------------------------------------------
# Compile-once smoke at the engine boundary (port of test_perf_smoke)


@pytest.fixture(scope='module')
def real_engine(params):
  variables = model_lib.get_model(params).init(
      jax.random.PRNGKey(0),
      jnp.zeros((1, params.total_rows, params.max_length, 1)))
  options = runner_lib.InferenceOptions(batch_size=BATCH)
  options.max_passes = params.max_passes
  options.max_length = params.max_length
  options.use_ccs_bq = params.use_ccs_bq
  runner = runner_lib.ModelRunner(params, variables, options)
  return engine_lib.ConsensusEngine(
      runner, options, deliver=lambda t, ids, quals: None)


def test_engine_compiles_once_per_shape(real_engine, params):
  ids, quals = real_engine.predict_windows(_raw_windows(params, BATCH))
  assert ids.shape == (BATCH, params.max_length)
  with jtu.count_jit_and_pmap_lowerings() as count:
    # Full packs AND ragged tails (flush pads them) must all reuse the
    # executable paid for above.
    for i, n in enumerate((BATCH, BATCH, BATCH // 2, 3, 1)):
      ids, quals = real_engine.predict_windows(
          _raw_windows(params, n, seed=i + 1))
      assert ids.shape == (n, params.max_length)
      assert quals.dtype == np.uint8
  assert count[0] == 0, (
      f'{count[0]} re-lowerings behind the engine boundary: the '
      'forward is recompiled per submission instead of per shape')


def test_engine_uint8_contract(real_engine, params):
  ids, quals = real_engine.predict_windows(_raw_windows(params, 3, 7))
  assert ids.dtype == np.uint8 and quals.dtype == np.uint8
  assert quals.max() <= real_engine.options.max_base_quality


# ----------------------------------------------------------------------
# Packer edge cases at the engine boundary (port of test_window_packer)


def test_full_packs_cut_across_submissions(params):
  """3 submissions of 5 windows at batch_size=8: packs cut at 8-window
  boundaries regardless of submission seams; tail pads on flush."""
  engine, delivered, failures = _collecting_engine(params)
  for s in range(3):
    engine.submit(_raw_windows(params, 5, seed=s),
                  [(s, i) for i in range(5)])
  assert engine.n_packs == 1  # 15 buffered -> one full pack cut
  engine.flush()
  assert engine.n_packs == 2
  assert engine.n_pack_rows == 15
  assert engine.n_pad_rows == 2 * BATCH - 15
  assert not failures
  assert set(delivered) == {(s, i) for s in range(3) for i in range(5)}


def test_delivery_matches_submission(params):
  """Each ticket gets exactly its own window's result (stub echoes the
  CCS row, so scatter correctness is observable)."""
  engine, delivered, _ = _collecting_engine(params)
  raw = _raw_windows(params, 11, seed=3)
  engine.submit(raw, list(range(11)))
  engine.flush()
  mp = params.max_passes
  for t in range(11):
    np.testing.assert_array_equal(
        delivered[t][0], raw[t, 4 * mp, :, 0].astype(np.uint8))
    assert (delivered[t][1] == STUB_QUAL).all()


def test_pack_failure_routes_tickets_not_deliver(params):
  """A failed pack surfaces ALL of its tickets through on_pack_failure
  and none through deliver; sibling packs are untouched."""
  engine, delivered, failures = _collecting_engine(
      params, fail_packs=(1,))
  engine.submit(_raw_windows(params, 20, seed=4), list(range(20)))
  engine.flush()
  assert len(failures) == 1
  failed_tickets, seq, err = failures[0]
  assert seq == 1
  assert failed_tickets == list(range(8, 16))
  assert 'stub failure' in str(err)
  assert set(delivered) == set(range(8)) | set(range(16, 20))


def test_poison_ticket_fails_only_its_pack(params):
  """poison_ticket makes exactly the pack carrying that ticket fail at
  dispatch (the DCTPU_FAULT_POISON_WINDOW mechanism) and is
  consume-once."""
  engine, delivered, failures = _collecting_engine(params)
  tickets = [object() for _ in range(20)]
  engine.poison_ticket(tickets[10])  # lands in pack 1 (windows 8..15)
  engine.submit(_raw_windows(params, 20, seed=5), tickets)
  engine.flush()
  assert len(failures) == 1
  failed_tickets, seq, err = failures[0]
  assert seq == 1
  assert failed_tickets == tickets[8:16]
  assert 'poison' in str(err)
  assert set(map(id, delivered)) == set(
      map(id, tickets[:8] + tickets[16:]))
  # Consume-once: resubmitting the same ticket succeeds.
  engine.submit(_raw_windows(params, 1, seed=6), [tickets[10]])
  engine.flush()
  assert len(failures) == 1
  assert tickets[10] in delivered


def test_submit_validates_ticket_alignment(params):
  engine, _, _ = _collecting_engine(params)
  with pytest.raises(ValueError, match='tickets'):
    engine.submit(_raw_windows(params, 3), [1, 2])
  with pytest.raises(ValueError, match='tickets'):
    engine.submit_formatted(np.zeros((2, 4, 4, 1), np.float32), [1])


def test_flush_without_drain_leaves_packs_in_flight(params):
  engine, delivered, _ = _collecting_engine(params)
  engine.submit(_raw_windows(params, 3, seed=8), [0, 1, 2])
  engine.flush(drain=False)
  assert engine.n_packs == 1
  assert engine.has_work  # dispatched but not finalized
  engine.flush(drain=True)
  assert not engine.has_work
  assert set(delivered) == {0, 1, 2}


# ----------------------------------------------------------------------
# Bucketed variable-length windows: per-bucket packing + ragged dispatch


def _win(params, length, rng):
  return rng.integers(
      0, 5, size=(params.total_rows, length, 1)).astype(np.float32)


def _bucketed_engine(params, batch_size=BATCH, fail_packs=(),
                     buckets=(100, 200), flush_packs=8):
  runner, options = _stub_runner(params, batch_size, fail_packs)
  options.window_buckets = buckets
  options.bucket_flush_packs = flush_packs
  delivered = {}
  failures = []
  engine = engine_lib.ConsensusEngine(
      runner, options,
      deliver=lambda t, ids, quals: delivered.__setitem__(t, (ids, quals)),
      on_pack_failure=lambda ts, seq, e: failures.append((list(ts), seq, e)))
  return engine, delivered, failures


def test_mixed_length_submission_routes_per_bucket(params):
  """One submit carrying L=100 and L=200 windows routes each to its
  bucket's packer; every ticket delivers at its window's natural width
  (no pad-to-max) and the per-bucket counters account for all of it."""
  rng = np.random.default_rng(21)
  engine, delivered, failures = _bucketed_engine(params, batch_size=4)
  widths = (100, 200, 100, 200, 100, 100)
  wins = [_win(params, w, rng) for w in widths]
  engine.submit(wins, list(range(len(wins))))
  engine.flush()
  assert not failures
  mp = params.max_passes
  for i, w in enumerate(wins):
    np.testing.assert_array_equal(
        delivered[i][0], w[4 * mp, :, 0].astype(np.uint8))
    assert delivered[i][1].shape == (w.shape[1],)
  stats = engine.stats()
  assert stats['window_buckets'] == [100, 200]
  assert stats['n_windows_by_bucket'] == {100: 4, 200: 2}
  assert stats['n_packs_by_bucket'] == {100: 1, 200: 1}
  # Bucketed dispatch moved 4*100 + 2*200 = 800 positions where
  # pad-to-max would have moved 6*200 = 1200.
  assert stats['padding_fraction'] == pytest.approx(1 - 800 / 1200, abs=1e-4)


def test_single_bucket_reports_zero_padding_fraction(params):
  engine, _, _ = _bucketed_engine(params, buckets=(100,))
  engine.submit(_raw_windows(params, 3, seed=2), [0, 1, 2])
  engine.flush()
  assert engine.stats()['padding_fraction'] == 0.0


def test_ragged_tails_flush_in_both_buckets(params):
  """Both buckets hold sub-batch tails at end of input: flush() cuts
  each as its own padded pack and no window crosses buckets."""
  rng = np.random.default_rng(22)
  engine, delivered, _ = _bucketed_engine(params)
  wins = ([_win(params, 100, rng) for _ in range(3)]
          + [_win(params, 200, rng) for _ in range(5)])
  engine.submit(wins, list(range(len(wins))))
  assert engine.n_packs == 0  # neither bucket reached batch_size
  engine.flush()
  assert engine.n_packs_by_bucket == {100: 1, 200: 1}
  assert engine.n_pack_rows == 8
  assert engine.n_pad_rows == 2 * BATCH - 8
  assert set(delivered) == set(range(len(wins)))
  for i, w in enumerate(wins):
    assert delivered[i][0].shape == (w.shape[1],)


def test_bucket_starvation_flush(params):
  """A tail stranded in a rarely-fed bucket is force-cut (padded) once
  the engine as a whole has dispatched bucket_flush_packs packs since
  the tail started waiting — it can't sit buffered until end of input
  behind a stream of full packs in the other bucket."""
  rng = np.random.default_rng(23)
  engine, delivered, _ = _bucketed_engine(params, flush_packs=2)
  engine.submit([_win(params, 200, rng)], ['tail'])
  engine.submit([_win(params, 100, rng) for _ in range(BATCH)],
                [('a', i) for i in range(BATCH)])
  # One pack cut since the tail buffered: below the limit, still held.
  assert engine.n_packs_by_bucket.get(200, 0) == 0
  engine.submit([_win(params, 100, rng) for _ in range(BATCH)],
                [('b', i) for i in range(BATCH)])
  # Second pack hit the limit: the tail was cut as a padded pack.
  assert engine.n_packs_by_bucket[200] == 1
  assert engine.n_pad_rows == BATCH - 1
  engine.flush()
  assert delivered['tail'][0].shape == (200,)
  # The cut reset the mark: nothing further to flush, no empty packs.
  assert engine.n_packs == 3


def test_starvation_flush_counters_and_fraction(params):
  """Satellite of the ragged-kernel PR: starvation flushes get their
  own counters — how often a stranded tail was force-cut and what
  position fraction of all dispatched capacity those flushes padded —
  so operators can see the cost the single-pack-stream path removes."""
  rng = np.random.default_rng(27)
  engine, delivered, _ = _bucketed_engine(params, flush_packs=2)
  engine.submit([_win(params, 200, rng)], ['tail'])
  for group in ('a', 'b'):
    engine.submit([_win(params, 100, rng) for _ in range(BATCH)],
                  [(group, i) for i in range(BATCH)])
  # The 200-tail was starvation-flushed after the second 100-pack.
  assert engine.n_starvation_flushes == 1
  stats = engine.stats()
  assert stats['n_starvation_flushes'] == 1
  # Flush-padded positions / dispatched position capacity:
  # (BATCH-1)*200 over (2 packs * BATCH * 100 + 1 pack * BATCH * 200).
  expect = ((BATCH - 1) * 200) / (2 * BATCH * 100 + BATCH * 200)
  assert stats['flush_padding_fraction'] == pytest.approx(expect,
                                                          abs=1e-4)
  engine.flush()
  assert delivered['tail'][0].shape == (200,)


def test_starvation_flush_pads_counted_once(params):
  """Regression: a bucket whose FINAL pack was a starvation flush must
  not double-count its pad rows — the flush attributes them once, and
  the end-of-input flush() (buffered == 0 after the cut) cannot re-pad
  the same tail. n_pad_rows stays exactly batch - k."""
  rng = np.random.default_rng(28)
  engine, delivered, _ = _bucketed_engine(params, flush_packs=2)
  engine.submit([_win(params, 200, rng)], ['tail'])
  for group in ('a', 'b'):
    engine.submit([_win(params, 100, rng) for _ in range(BATCH)],
                  [(group, i) for i in range(BATCH)])
  assert engine.n_pad_rows == BATCH - 1
  before = engine.stats()['flush_padding_fraction']
  engine.flush()
  # No window entered the 200 bucket after its starvation flush: the
  # end-of-input flush adds no pack, no pad rows, no fraction drift —
  # the flush-cut tail (buffered == 0 after the cut) is not re-padded.
  assert engine.n_packs_by_bucket[200] == 1
  assert engine.n_pad_rows == BATCH - 1
  assert engine.n_starvation_flushes == 1
  assert engine.stats()['flush_padding_fraction'] == before
  assert set(delivered) > {'tail'}


def test_end_of_input_flush_is_not_starvation(params):
  """Ordinary end-of-input tails (both buckets sub-batch at flush())
  pad the general pool but never the starvation counters."""
  rng = np.random.default_rng(29)
  engine, _, _ = _bucketed_engine(params)
  engine.submit([_win(params, 100, rng) for _ in range(3)]
                + [_win(params, 200, rng) for _ in range(2)],
                list(range(5)))
  engine.flush()
  assert engine.n_pad_rows == 2 * BATCH - 5
  assert engine.n_starvation_flushes == 0
  stats = engine.stats()
  assert stats['n_starvation_flushes'] == 0
  assert stats['flush_padding_fraction'] == 0.0
  assert stats['padding_fraction'] > 0


def test_poison_in_one_bucket_leaves_other_bucket_identical(params):
  """Poisoning a ticket whose window lands in the 200-bucket fails only
  that bucket's pack; the 100-bucket's deliveries are byte-identical to
  the same run without the poison."""
  rng = np.random.default_rng(24)
  widths = (100, 200, 100, 200, 100, 100, 200, 100)
  wins = [_win(params, w, rng) for w in widths]

  def run(poison_idx=None):
    engine, delivered, failures = _bucketed_engine(params, batch_size=4)
    tickets = list(range(len(wins)))
    if poison_idx is not None:
      engine.poison_ticket(tickets[poison_idx])
    engine.submit(wins, tickets)
    engine.flush()
    return delivered, failures

  clean, clean_failures = run()
  poisoned, failures = run(poison_idx=3)  # a 200-bucket window
  assert not clean_failures
  assert len(failures) == 1
  failed_tickets, _seq, err = failures[0]
  assert 'poison' in str(err)
  # Exactly the 200-bucket tickets failed; every 100-bucket ticket
  # delivered bytes identical to the clean run.
  assert failed_tickets == [i for i, w in enumerate(widths) if w == 200]
  for i, w in enumerate(widths):
    if w == 100:
      np.testing.assert_array_equal(poisoned[i][0], clean[i][0])
      np.testing.assert_array_equal(poisoned[i][1], clean[i][1])
    else:
      assert i not in poisoned


def test_submit_rejects_width_outside_buckets(params):
  engine, _, _ = _bucketed_engine(params)
  rng = np.random.default_rng(25)
  with pytest.raises(ValueError, match='not in window buckets'):
    engine.submit([_win(params, 150, rng)], [0])


def test_engine_compiles_once_per_bucket(params):
  """Two buckets cost exactly two forward traces; every later pack —
  full or padded, either width — reuses its bucket's executable. The
  runner's n_forward_shapes counter exposes the same fact."""
  variables = model_lib.get_model(params).init(
      jax.random.PRNGKey(0),
      jnp.zeros((1, params.total_rows, params.max_length, 1)))
  options = runner_lib.InferenceOptions(batch_size=4)
  options.max_passes = params.max_passes
  options.max_length = params.max_length
  options.use_ccs_bq = params.use_ccs_bq
  options.window_buckets = (100, 200)
  runner = runner_lib.ModelRunner(params, variables, options)
  engine = engine_lib.ConsensusEngine(
      runner, options, deliver=lambda t, ids, quals: None)
  rng = np.random.default_rng(26)
  # Warm both buckets (one trace each).
  engine.predict_windows([_win(params, 100, rng), _win(params, 200, rng)])
  with jtu.count_jit_and_pmap_lowerings() as count:
    out_ids, _ = engine.predict_windows(
        [_win(params, w, rng) for w in (100, 200, 200, 100, 100, 200)])
    assert [i.shape[0] for i in out_ids] == [100, 200, 200, 100, 100, 200]
  assert count[0] == 0, (
      f'{count[0]} re-lowerings across bucketed packs: each bucket '
      'must compile once and reuse its executable')
  assert runner.dispatch_stats()['n_forward_shapes'] == 2


# ----------------------------------------------------------------------
# Behavior preservation: engine-direct output == batch pipeline output


def test_engine_reproduces_batch_pipeline_bytes(tmp_path, synthetic_bams,
                                                params):
  """Featurize a synthetic input once; polish it (a) through the full
  run_inference pipeline and (b) by driving ConsensusEngine + stitch
  directly. The FASTQ bytes must match exactly — the refactored
  pipeline is a thin client of the same engine."""
  subreads, ccs = synthetic_bams(subdir='bams_engine', n_zmws=6,
                                 seq_len=600)

  def make_options():
    opts = runner_lib.InferenceOptions(
        batch_size=BATCH, batch_zmws=100, skip_windows_above=0,
        min_quality=0)
    opts.max_passes = params.max_passes
    opts.max_length = params.max_length
    opts.use_ccs_bq = params.use_ccs_bq
    return opts

  # (a) the batch pipeline
  options = make_options()
  runner, _ = _stub_runner(params, BATCH)
  out = str(tmp_path / 'pipeline.fastq')
  runner_lib.run_inference(
      subreads_to_ccs=subreads, ccs_bam=ccs, checkpoint=None,
      output=out, options=options, runner=runner)
  with open(out, 'rb') as f:
    pipeline_bytes = f.read()

  # (b) engine-direct: featurize, triage, submit, stitch, format
  from deepconsensus_tpu.preprocess import (FeatureLayout,
                                            create_proc_feeder)

  options = make_options()
  runner, _ = _stub_runner(params, BATCH)
  layout = FeatureLayout(
      max_passes=options.max_passes, max_length=options.max_length,
      use_ccs_bq=options.use_ccs_bq)
  feeder, _ = create_proc_feeder(
      subreads_to_ccs=subreads, ccs_bam=ccs, layout=layout,
      ins_trim=options.ins_trim)
  mols = {}  # name -> [(pos, ids, quals)]

  def deliver(ticket, ids, quals):
    name, pos = ticket
    mols[name].append((pos, ids, quals))

  engine = engine_lib.ConsensusEngine(runner, options, deliver=deliver)
  counter = collections.Counter()
  for zmw_input in feeder():
    features, _ = runner_lib.preprocess_zmw(zmw_input, options)
    to_model, to_skip = engine_lib.triage_windows(
        features, options, counter)
    for fd in to_skip:
      name = fd['name'] if isinstance(fd['name'], str) else fd['name'].decode()
      mols.setdefault(name, []).append(
          (fd['window_pos'],
           *engine_lib.skipped_window_arrays(fd, options)))
    tickets = []
    for fd in to_model:
      name = fd['name'] if isinstance(fd['name'], str) else fd['name'].decode()
      mols.setdefault(name, [])
      tickets.append((name, fd['window_pos']))
    if to_model:
      engine.submit(
          np.stack([fd['subreads'] for fd in to_model]), tickets)
  engine.flush()

  outcome = stitch.OutcomeCounter()
  direct = b''
  for name in sorted(mols):
    windows = mols[name]
    result = stitch.stitch_arrays(
        name,
        np.asarray([w[0] for w in windows], dtype=np.int64),
        np.stack([w[1] for w in windows]),
        np.stack([w[2] for w in windows]),
        max_length=options.max_length,
        min_quality=options.min_quality,
        min_length=options.min_length,
        outcome_counter=outcome)
    if result is not None:
      direct += stitch.format_fastq_bytes(name, *result)
  assert direct == pipeline_bytes


# ----------------------------------------------------------------------
# Data-parallel sharded dispatch (8 forced host-platform devices)


def _real_runner(params, mesh=None, batch=BATCH):
  variables = model_lib.get_model(params).init(
      jax.random.PRNGKey(0),
      jnp.zeros((1, params.total_rows, params.max_length, 1)))
  options = runner_lib.InferenceOptions(batch_size=batch)
  options.max_passes = params.max_passes
  options.max_length = params.max_length
  options.use_ccs_bq = params.use_ccs_bq
  return runner_lib.ModelRunner(params, variables, options,
                                mesh=mesh), options


@pytest.mark.multichip
def test_engine_byte_identity_single_vs_dp8(params):
  """The engine boundary must produce identical uint8 (ids, quals)
  whether the runner dispatches to one device or dp-shards each pack
  over all 8 — full packs and the padded flush tail alike."""
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  mesh = mesh_lib.make_mesh(dp=8, tp=1, devices=jax.devices()[:8])
  raw = _raw_windows(params, 21, seed=11)  # 2 full packs + ragged tail
  runner_s, options_s = _real_runner(params)
  runner_m, options_m = _real_runner(params, mesh=mesh)
  engine_s = engine_lib.ConsensusEngine(
      runner_s, options_s, deliver=lambda t, ids, quals: None)
  engine_m = engine_lib.ConsensusEngine(
      runner_m, options_m, deliver=lambda t, ids, quals: None)
  ids_s, quals_s = engine_s.predict_windows(raw)
  ids_m, quals_m = engine_m.predict_windows(raw)
  np.testing.assert_array_equal(ids_s, ids_m)
  np.testing.assert_array_equal(quals_s, quals_m)
  stats = engine_m.stats()
  assert stats['n_packs_dispatched_sharded'] == 3
  assert engine_s.stats()['n_packs_dispatched_sharded'] == 0


@pytest.mark.multichip
def test_dispatch_handles_are_dp_sharded(params):
  """The dispatch contract: the transfer slot holds dp-sharded input
  buffers, the forward launches when the next pack dispatches
  (overlapped) or at finalize (direct), and the logits come back
  sharded on the data axis."""
  from deepconsensus_tpu.models import data as data_lib
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  mesh = mesh_lib.make_mesh(dp=8, tp=1, devices=jax.devices()[:8])
  batch_sh = mesh_lib.batch_sharding(mesh)
  runner, _ = _real_runner(params, mesh=mesh)
  rows1 = data_lib.format_rows_batch(_raw_windows(params, BATCH, 1), params)
  rows2 = data_lib.format_rows_batch(_raw_windows(params, BATCH, 2), params)
  h1 = runner.dispatch(rows1)
  # Pack 1 sits in the transfer slot: inputs placed, forward not run.
  assert not h1.launched
  assert h1.inputs[0].sharding == batch_sh
  assert h1.inputs[1].sharding == batch_sh
  h2 = runner.dispatch(rows2)
  # Pack 2's dispatch launched pack 1's forward (overlapped); its own
  # transfer slot is sharded and still pending.
  assert h1.launched and h1.outputs is not None
  assert h1.outputs[0].sharding.is_equivalent_to(
      batch_sh, h1.outputs[0].ndim)
  assert not h2.launched
  assert h2.inputs[0].sharding == batch_sh
  ids1, quals1 = runner.finalize(h1)
  ids2, quals2 = runner.finalize(h2)  # direct launch: nothing followed
  assert ids1.shape == ids2.shape == (BATCH, params.max_length)
  stats = runner.dispatch_stats()
  assert stats['n_packs_dispatched_sharded'] == 2
  assert stats['n_transfer_overlapped'] == 1
  assert stats['n_transfer_direct'] == 1
  assert stats['transfer_overlap_fraction'] == 0.5


@pytest.mark.multichip
def test_deferred_launch_failure_attributed_to_failing_pack(params):
  """Double-buffering defers pack N's forward launch into pack N+1's
  dispatch; a launch error must still surface at pack N's finalize so
  the engine quarantines pack N's tickets — and the packs around it
  deliver, in featurize order."""
  from deepconsensus_tpu.parallel import mesh as mesh_lib

  mesh = mesh_lib.make_mesh(dp=8, tp=1, devices=jax.devices()[:8])
  runner, options = _real_runner(params, mesh=mesh)
  real_forward = runner._forward
  calls = [0]

  def flaky_forward(variables, main_u8, sn):
    calls[0] += 1
    if calls[0] == 2:
      raise RuntimeError('injected mid-stream forward failure')
    return real_forward(variables, main_u8, sn)

  runner._forward = flaky_forward
  delivered = {}
  failures = []
  engine = engine_lib.ConsensusEngine(
      runner, options,
      deliver=lambda t, ids, quals: delivered.__setitem__(t, ids),
      on_pack_failure=lambda ts, seq, e: failures.append(
          (list(ts), seq, str(e))))
  engine.submit(_raw_windows(params, 3 * BATCH, seed=13),
                list(range(3 * BATCH)))
  engine.flush()
  # The error was raised while pack 2 dispatched, but it belongs to
  # pack 1: exactly pack 1's tickets fail, with its pack seq.
  assert len(failures) == 1
  failed_tickets, seq, err = failures[0]
  assert seq == 1
  assert failed_tickets == list(range(BATCH, 2 * BATCH))
  assert 'injected mid-stream forward failure' in err
  # Packs 0 and 2 delivered, in featurize order.
  assert list(delivered) == (
      list(range(BATCH)) + list(range(2 * BATCH, 3 * BATCH)))
