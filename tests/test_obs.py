"""Observability plane suite (deepconsensus_tpu/obs/).

Covers the four obs subsystems in isolation plus their contracts:

  * metrics registry — typed counters/gauges, fixed-bucket histograms
    with nearest-rank percentiles (the deque-index under-report at
    small n is the regression test), unified snapshot, Prometheus text
    exposition;
  * trace spans — Chrome-trace JSONL framing (one `[` header however
    many writers share the file, atomic one-line appends), the
    tracing-off fast path, thread-local trace-id stamping, and the
    record_stage contract that feeds the SAME measured interval to
    both the histogram and the span (the reconciliation guarantee
    bench.py asserts end to end);
  * summarize — per-stage totals/coverage, critical-path ordering,
    straggler extraction, span-derived overlap (launch-before-finalize
    ordering), trace-group connectivity, corrupt-file typing;
  * profiler — guarded on-demand capture status dicts;

plus the `dctpu trace` CLI and dead-letter trace-id stamping.
"""
import json
import os
import threading

import pytest

from deepconsensus_tpu import faults as faults_lib
from deepconsensus_tpu import obs as obs_lib
from deepconsensus_tpu.obs import metrics as metrics_lib
from deepconsensus_tpu.obs import profiler as profiler_lib
from deepconsensus_tpu.obs import summarize as summarize_lib
from deepconsensus_tpu.obs import trace as trace_lib


@pytest.fixture(autouse=True)
def _reset_trace():
  """Each test starts and ends with tracing off and no trace id."""
  trace_lib.configure(None)
  trace_lib.set_trace_id(None)
  yield
  trace_lib.configure(None)
  trace_lib.set_trace_id(None)


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------


class TestMetricsRegistry:

  def test_counter_and_gauge(self):
    reg = metrics_lib.MetricsRegistry(tier='test')
    reg.inc('n_requests')
    reg.inc('n_requests', 4)
    reg.set_gauge('outstanding', 3.5)
    assert reg.counter_values()['n_requests'] == 5
    snap = reg.snapshot()
    assert snap['counters']['n_requests'] == 5
    assert snap['gauges']['outstanding'] == 3.5

  def test_histogram_observe_and_snapshot(self):
    reg = metrics_lib.MetricsRegistry()
    h = reg.histogram('latency_s', bounds=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
      h.observe(v)
    snap = h.snapshot()
    assert snap['count'] == 5
    assert snap['sum'] == pytest.approx(56.05)
    assert snap['buckets'] == [[0.1, 1], [1.0, 2], [10.0, 1], ['inf', 1]]

  def test_nearest_rank_percentiles_small_n(self):
    # The old deque implementation indexed int(0.99 * n), which at
    # n=10 reads the 9th of 10 sorted samples — under-reporting p99.
    # Nearest-rank picks ceil(0.99 * 10) = the 10th sample's bucket.
    h = metrics_lib.Histogram('x', threading.Lock(),
                              bounds=(0.01, 0.1, 1.0, 10.0))
    for _ in range(9):
      h.observe(0.005)
    h.observe(5.0)  # the single slow outlier
    assert h.percentile(0.99) == 10.0
    assert h.percentile(0.50) == 0.01

  def test_percentiles_canonical_keys_only(self):
    h = metrics_lib.Histogram('x', threading.Lock(), bounds=(1.0,))
    assert h.percentiles()['p50'] is None
    h.observe(0.5)
    p = h.percentiles()
    assert p['p50'] == 1.0
    assert p['count'] == 1
    # The one-release p50_s/p99_s/n aliases are removed.
    assert set(p) == {'p50', 'p99', 'count'}

  def test_empty_histogram_rejected(self):
    with pytest.raises(ValueError):
      metrics_lib.Histogram('x', threading.Lock(), bounds=())

  def test_prom_text(self):
    reg = metrics_lib.MetricsRegistry(tier='serve')
    reg.inc('n_requests', 7)
    reg.set_gauge('outstanding', 2)
    reg.histogram('latency_s', bounds=(0.1, 1.0)).observe(0.5)
    text = reg.to_prom()
    assert 'dctpu_n_requests{tier="serve"} 7' in text
    assert 'dctpu_outstanding{tier="serve"} 2' in text
    # Cumulative le buckets plus +Inf, _sum and _count.
    assert 'dctpu_latency_s_bucket{tier="serve",le="0.1"} 0' in text
    assert 'dctpu_latency_s_bucket{tier="serve",le="1.0"} 1' in text
    assert 'dctpu_latency_s_bucket{tier="serve",le="+Inf"} 1' in text
    assert 'dctpu_latency_s_count{tier="serve"} 1' in text

  def test_prom_counters_text_skips_non_numeric(self):
    text = metrics_lib.prom_counters_text(
        {'n_ok': 3, 'inference_dtype': 'float32', 'flag': True},
        tier='serve')
    assert 'dctpu_n_ok{tier="serve"} 3' in text
    assert 'inference_dtype' not in text
    assert 'flag' not in text

  def test_concurrent_inc(self):
    reg = metrics_lib.MetricsRegistry()
    threads = [threading.Thread(
        target=lambda: [reg.inc('n') for _ in range(1000)])
        for _ in range(8)]
    for t in threads:
      t.start()
    for t in threads:
      t.join()
    assert reg.counter_values()['n'] == 8000


# ---------------------------------------------------------------------------
# Trace spans
# ---------------------------------------------------------------------------


class TestTraceSpans:

  def test_off_by_default(self):
    assert not trace_lib.enabled()
    # No-ops, no file writes.
    trace_lib.complete_event('x', 'stage', 0.0, 1.0)
    with trace_lib.span('x'):
      pass

  def test_writes_loadable_chrome_trace(self, tmp_path):
    path = str(tmp_path / 'trace.jsonl')
    trace_lib.configure(path, tier='run')
    trace_lib.complete_event('featurize', 'stage', 10.0, 10.5,
                             {'n_zmws': 3})
    with trace_lib.span('stitch', n_zmws=3):
      pass
    trace_lib.configure(None)
    raw = open(path).read()
    assert raw.startswith('[\n')
    events = summarize_lib.load_trace(path)
    names = [e['name'] for e in events]
    assert 'process_name' in names          # tier metadata
    assert 'featurize' in names and 'stitch' in names
    feat = next(e for e in events if e['name'] == 'featurize')
    assert feat['ph'] == 'X'
    assert feat['ts'] == pytest.approx(10.0 * 1e6)
    assert feat['dur'] == pytest.approx(0.5 * 1e6)
    assert feat['args']['n_zmws'] == 3

  def test_single_header_with_multiple_writers(self, tmp_path):
    # N fleet processes share one file: only the O_CREAT|O_EXCL winner
    # writes `[`; everyone appends whole-line events.
    path = str(tmp_path / 'shared.jsonl')
    w1 = trace_lib.TraceWriter(path, tier='router')
    w2 = trace_lib.TraceWriter(path, tier='serve')
    w1.complete_event('route', 'request', 1.0, 0.1)
    w2.complete_event('serve_request', 'request', 1.05, 0.2)
    w1.close()
    w2.close()
    lines = open(path).read().splitlines()
    assert lines.count('[') == 1 and lines[0] == '['
    events = summarize_lib.load_trace(path)
    names = [e['name'] for e in events]
    assert 'route' in names and 'serve_request' in names
    # Both writers announced their tier (in a real fleet each is its
    # own pid; in-process they collide on pid, so count the events).
    labels = sorted(e['args']['name'] for e in events
                    if e['name'] == 'process_name')
    assert labels == ['dctpu-router', 'dctpu-serve']

  def test_thread_local_trace_id_stamping(self, tmp_path):
    path = str(tmp_path / 'trace.jsonl')
    trace_lib.configure(path, tier='run')
    trace_lib.set_trace_id('aabbccdd00112233')
    trace_lib.complete_event('stitch', 'stage', 0.0, 1.0)
    # Explicit arg wins over the thread-local binding.
    trace_lib.complete_event('stitch', 'stage', 0.0, 1.0,
                             {'trace_id': 'other'})
    seen = {}

    def other_thread():
      trace_lib.complete_event('featurize', 'stage', 0.0, 1.0)
      seen['done'] = True

    t = threading.Thread(target=other_thread)
    t.start()
    t.join()
    trace_lib.configure(None)
    events = [e for e in summarize_lib.load_trace(path)
              if e['ph'] == 'X']
    ids = [e['args'].get('trace_id') for e in events]
    assert ids == ['aabbccdd00112233', 'other', None]
    assert seen['done']

  def test_mint_trace_id(self):
    a, b = trace_lib.mint_trace_id(), trace_lib.mint_trace_id()
    assert len(a) == 16 and a != b
    int(a, 16)  # hex

  def test_configure_from_env(self, tmp_path, monkeypatch):
    path = str(tmp_path / 'env.jsonl')
    monkeypatch.setenv(trace_lib.ENV_TRACE, path)
    assert trace_lib.configure_from_env(tier='serve') is not None
    assert trace_lib.enabled()
    monkeypatch.delenv(trace_lib.ENV_TRACE)
    assert trace_lib.configure_from_env() is None
    assert not trace_lib.enabled()


class TestRecordStage:

  def test_feeds_histogram_and_span_same_interval(self, tmp_path):
    # The reconciliation guarantee: span totals == histogram sums
    # because both read the same (t0, t1).
    path = str(tmp_path / 'trace.jsonl')
    trace_lib.configure(path, tier='run')
    reg = metrics_lib.MetricsRegistry()
    intervals = [(1.0, 1.5), (2.0, 2.25), (3.0, 3.75)]
    for t0, t1 in intervals:
      obs_lib.record_stage(reg, trace_lib.STAGE_STITCH, t0, t1, pack=1)
    trace_lib.configure(None)
    hist_sum = reg.histogram(
        obs_lib.stage_histogram_name(trace_lib.STAGE_STITCH)
    ).snapshot()['sum']
    events = summarize_lib.load_trace(path)
    span_sum = sum(e['dur'] for e in events if e.get('ph') == 'X') / 1e6
    assert hist_sum == pytest.approx(1.5)
    assert span_sum == pytest.approx(hist_sum, rel=1e-6)

  def test_none_registry_still_emits_span(self, tmp_path):
    path = str(tmp_path / 'trace.jsonl')
    trace_lib.configure(path, tier='run')
    obs_lib.record_stage(None, trace_lib.STAGE_FEATURIZE, 0.0, 0.5)
    trace_lib.configure(None)
    events = summarize_lib.load_trace(path)
    assert any(e.get('name') == 'featurize' for e in events)

  def test_tracing_off_records_histogram_only(self):
    reg = metrics_lib.MetricsRegistry()
    obs_lib.record_stage(reg, trace_lib.STAGE_H2D, 0.0, 0.5)
    snap = reg.histogram(
        obs_lib.stage_histogram_name(trace_lib.STAGE_H2D)).snapshot()
    assert snap['count'] == 1


# ---------------------------------------------------------------------------
# Summarize
# ---------------------------------------------------------------------------


def _span(name, ts_s, dur_s, pid=1, cat='stage', **args):
  return {'name': name, 'cat': cat, 'ph': 'X', 'ts': ts_s * 1e6,
          'dur': dur_s * 1e6, 'pid': pid, 'tid': 1, 'args': args}


class TestSummarize:

  def _pipeline_events(self):
    ev = [{'name': 'process_name', 'ph': 'M', 'pid': 1, 'tid': 0,
           'args': {'name': 'dctpu-run'}}]
    # Two packs: pack 0 launched directly (inside finalize), pack 1
    # overlapped (launched before its finalize started).
    ev += [
        _span('featurize', 0.0, 1.0, n_zmws=10, trace_id='t1'),
        _span('pack_wait', 1.0, 0.2, bucket=100),
        _span('h2d_transfer', 1.2, 0.1, pack=0, bucket=100),
        # pack 0: compute starts AT its finalize start (direct).
        _span('finalize_drain', 1.3, 0.5, pack=0),
        _span('device_compute', 1.3, 0.5, pack=0, bucket=100, dp=1,
              n_rows=64),
        # pack 1: compute started 1.5, finalize started 1.9 (overlap).
        _span('h2d_transfer', 1.4, 0.1, pack=1, bucket=100),
        _span('device_compute', 1.5, 2.0, pack=1, bucket=100, dp=1,
              n_rows=64),
        _span('finalize_drain', 1.9, 1.6, pack=1),
        _span('stitch', 3.5, 0.5, n_zmws=10, trace_id='t1'),
    ]
    return ev

  def test_stage_totals_and_counts(self):
    s = summarize_lib.summarize(self._pipeline_events())
    assert s['stage_totals_s']['device_compute'] == pytest.approx(2.5)
    assert s['stage_counts']['device_compute'] == 2
    assert s['stage_totals_s']['featurize'] == pytest.approx(1.0)
    assert s['wall_s'] == pytest.approx(4.0)
    assert s['tiers'] == {1: 'dctpu-run'}

  def test_critical_path_ordering(self):
    s = summarize_lib.summarize(self._pipeline_events())
    # device_compute spans [1.3, 1.8] U [1.5, 3.5] -> 2.2s coverage,
    # the largest single-stage coverage -> top of the critical path.
    top = s['critical_path'][0]
    assert top['stage'] == 'device_compute'
    assert top['coverage_s'] == pytest.approx(2.2)
    assert top['fraction_of_wall'] == pytest.approx(2.2 / 4.0, abs=1e-3)

  def test_span_overlap_rule(self):
    overlap = summarize_lib.span_overlap(self._pipeline_events())
    # pack 0: compute ts == finalize ts -> direct; pack 1: compute ts
    # strictly before finalize ts -> overlapped.
    assert overlap['n_packs'] == 2
    assert overlap['n_overlapped'] == 1
    assert overlap['n_direct'] == 1
    assert overlap['span_overlap_fraction'] == 0.5

  def test_overlap_counts_drain_free_pack_as_overlapped(self):
    """Regression: a device_compute span with no finalize_drain span
    (a drain-free pack — device-resident runs batch their drain at
    end-of-input) used to be dropped from the sample, skewing the
    span-derived fraction LOW on exactly the best-overlapped runs. A
    direct launch only ever happens inside finalize, which would have
    emitted the span — so drain-free means overlapped."""
    events = [_span('device_compute', 0.0, 1.0, pack=9)]
    overlap = summarize_lib.span_overlap(events)
    assert overlap['n_packs'] == 1
    assert overlap['n_overlapped'] == 1
    assert overlap['n_direct'] == 0
    assert overlap['span_overlap_fraction'] == 1.0

  def test_overlap_mixed_drained_and_drain_free(self):
    events = self._pipeline_events() + [
        _span('device_compute', 4.0, 0.5, pack=2, bucket=100, dp=1,
              n_rows=64),
    ]
    overlap = summarize_lib.span_overlap(events)
    assert overlap['n_packs'] == 3
    assert overlap['n_overlapped'] == 2  # pack 1 (early launch) + pack 2
    assert overlap['n_direct'] == 1

  def test_device_gaps_fully_transfer_covered(self):
    """Resident pack loop: each inter-compute gap exactly holds the
    next pack's H2D -> zero host gap, transfer_only_fraction 1.0."""
    events = []
    for k in range(3):
      events.append(_span('h2d_transfer', 1.1 * k + 1.0, 0.1, pack=k))
      events.append(_span('device_compute', 1.1 * k, 1.0, pack=k))
    gaps = summarize_lib.device_gaps(events)
    assert gaps['n_gaps'] == 2
    assert gaps['gap_s'] == pytest.approx(0.2)
    assert gaps['transfer_s'] == pytest.approx(0.2)
    assert gaps['host_gap_s'] == pytest.approx(0.0, abs=1e-9)
    assert gaps['transfer_only_fraction'] == 1.0

  def test_device_gaps_partial_coverage_is_host_time(self):
    """Half of a 1s gap covered by H2D: the other half is host work on
    the critical path (pack assembly, weight re-transfer, python)."""
    events = [
        _span('device_compute', 0.0, 1.0, pack=0),
        _span('h2d_transfer', 1.2, 0.5, pack=1),
        _span('device_compute', 2.0, 1.0, pack=1),
    ]
    gaps = summarize_lib.device_gaps(events)
    assert gaps['n_gaps'] == 1
    assert gaps['gap_s'] == pytest.approx(1.0)
    assert gaps['transfer_s'] == pytest.approx(0.5)
    assert gaps['host_gap_s'] == pytest.approx(0.5)
    assert gaps['max_host_gap_s'] == pytest.approx(0.5)
    assert gaps['transfer_only_fraction'] == pytest.approx(0.5)

  def test_device_gaps_clips_transfers_and_isolates_pids(self):
    """H2D spans clip to the gap they cover (overlap-running transfers
    don't inflate coverage), and compute on another pid never pairs."""
    events = [
        _span('device_compute', 0.0, 1.0, pack=0),
        # Transfer starts inside compute and runs past the gap start:
        # only its in-gap portion counts.
        _span('h2d_transfer', 0.5, 0.7, pack=1),
        _span('device_compute', 1.5, 1.0, pack=1),
        _span('device_compute', 5.0, 1.0, pid=2, pack=0),
    ]
    gaps = summarize_lib.device_gaps(events)
    assert gaps['n_gaps'] == 1
    assert gaps['gap_s'] == pytest.approx(0.5)
    assert gaps['transfer_s'] == pytest.approx(0.2)
    assert gaps['host_gap_s'] == pytest.approx(0.3)

  def test_device_gaps_no_computes(self):
    gaps = summarize_lib.device_gaps([_span('featurize', 0.0, 1.0)])
    assert gaps['n_gaps'] == 0
    assert gaps['gap_s'] == 0.0
    # No gap time at all = nothing attributable to the host.
    assert gaps['transfer_only_fraction'] == 1.0

  def test_summary_and_text_include_device_gaps(self):
    s = summarize_lib.summarize(self._pipeline_events())
    assert 'device_gaps' in s
    text = summarize_lib.format_summary(s)
    assert 'device gaps' in text

  def test_stragglers_slowest_decile(self):
    events = [
        _span('device_compute', float(i), 0.1 + (0.9 if i == 7 else 0),
              pack=i, bucket=200, dp=2, n_rows=32)
        for i in range(10)
    ]
    s = summarize_lib.summarize(events)
    assert len(s['stragglers']) == 1
    row = s['stragglers'][0]
    assert row['pack'] == 7 and row['bucket'] == 200 and row['dp'] == 2

  def test_trace_groups_connectivity(self):
    events = [
        _span('route', 0.0, 1.0, pid=1, cat='request', trace_id='abc'),
        _span('featurize', 0.1, 0.5, pid=2, trace_id='abc'),
        _span('serve_request', 0.6, 0.4, pid=3, cat='request',
              trace_id='abc'),
        _span('serve_request', 0.0, 0.1, pid=3, cat='request',
              trace_id='other'),
    ]
    groups = summarize_lib.trace_groups(events)
    assert groups['abc']['pids'] == [1, 2, 3]
    assert groups['abc']['n_spans'] == 3
    assert groups['other']['pids'] == [3]

  def test_empty_trace_is_corrupt(self):
    with pytest.raises(faults_lib.CorruptInputError):
      summarize_lib.summarize([])

  def test_corrupt_file_typed(self, tmp_path):
    p = tmp_path / 'bad.jsonl'
    p.write_text('[\n{"name": "x", not json}\n')
    with pytest.raises(faults_lib.CorruptInputError):
      summarize_lib.load_trace(str(p))
    with pytest.raises(faults_lib.CorruptInputError):
      summarize_lib.load_trace(str(tmp_path / 'missing.jsonl'))

  def test_format_summary_renders(self):
    s = summarize_lib.summarize(self._pipeline_events())
    text = summarize_lib.format_summary(s)
    assert 'device_compute' in text
    assert 'transfer overlap (span-derived)' in text
    assert 'straggler' in text


# ---------------------------------------------------------------------------
# Profiler
# ---------------------------------------------------------------------------


class TestProfiler:

  def test_capture_returns_status_dict(self, tmp_path):
    result = profiler_lib.capture_profile(str(tmp_path / 'prof'), 0.1)
    # On a jax-enabled box the capture succeeds; either way the call
    # must return a status dict, never raise.
    assert isinstance(result, dict) and 'ok' in result
    if result['ok']:
      assert result['out_dir'] == str(tmp_path / 'prof')

  def test_concurrent_capture_refused(self, tmp_path):
    assert profiler_lib._capture_lock.acquire(blocking=False)
    try:
      result = profiler_lib.capture_profile(str(tmp_path / 'p'), 0.1)
    finally:
      profiler_lib._capture_lock.release()
    assert result['ok'] is False
    assert 'already running' in result['error']

  def test_install_sigusr2_off_main_thread(self, tmp_path):
    out = {}

    def worker():
      out['installed'] = profiler_lib.install_sigusr2(str(tmp_path))

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert out['installed'] is False


# ---------------------------------------------------------------------------
# Dead-letter trace stamping + CLI
# ---------------------------------------------------------------------------


class TestDeadLetterTraceId:

  def test_record_stamps_thread_local_trace_id(self, tmp_path):
    path = str(tmp_path / 'failed.jsonl')
    writer = faults_lib.DeadLetterWriter(path)
    trace_lib.set_trace_id('feedfacefeedface')
    writer.record('zmw/1', 'featurize', 'ValueError', 'boom', 'dropped')
    trace_lib.set_trace_id(None)
    writer.record('zmw/2', 'featurize', 'ValueError', 'boom', 'dropped')
    writer.close()
    entries = [json.loads(l) for l in open(path)]
    assert entries[0]['trace_id'] == 'feedfacefeedface'
    assert 'trace_id' not in entries[1]


class TestTraceCli:

  def _write_trace(self, tmp_path):
    path = str(tmp_path / 'trace.jsonl')
    trace_lib.configure(path, tier='run')
    obs_lib.record_stage(None, trace_lib.STAGE_FEATURIZE, 0.0, 1.0)
    obs_lib.record_stage(None, trace_lib.STAGE_DEVICE_COMPUTE,
                         1.0, 2.0, pack=0)
    obs_lib.record_stage(None, trace_lib.STAGE_FINALIZE, 1.0, 2.1,
                         pack=0)
    trace_lib.configure(None)
    return path

  def test_cli_text_and_json(self, tmp_path, capsys):
    from deepconsensus_tpu import cli

    path = self._write_trace(tmp_path)
    assert cli.main(['trace', path]) == 0
    out = capsys.readouterr().out
    assert 'featurize' in out and 'device_compute' in out
    assert cli.main(['trace', path, '--json']) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload['stage_counts']['featurize'] == 1
    assert payload['overlap']['n_packs'] == 1

  def test_cli_corrupt_exits_2(self, tmp_path, capsys):
    from deepconsensus_tpu import cli

    bad = tmp_path / 'bad.jsonl'
    bad.write_text('{nope\n')
    assert cli.main(['trace', str(bad)]) == 2
    assert 'dctpu:' in capsys.readouterr().err
